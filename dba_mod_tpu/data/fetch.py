"""Dataset preflight: exact upstream URLs, sha256 checksums, and the
honest synthetic-fallback story (`python -m dba_mod_tpu.main fetch`).

The reference downloads implicitly through torchvision at first use
(image_helper.py:186-219) — in an air-gapped or quota'd deployment that
turns the first training run into a surprise network job, and a truncated
download into silent garbage. This module makes ingestion explicit:

- every dataset's upstream artifacts are pinned here — URL + sha256 where
  upstream bytes are stable (MNIST idx archives, the CIFAR-10 python
  tarball); artifacts upstream does not publish a digest for
  (Tiny-ImageNet's zip) are verified by size and their computed sha256 is
  printed so a deployment can pin it;
- ``fetch`` downloads what is missing, verifies, and extracts into the
  exact on-disk layout `data/datasets.py` loads (MNIST gz files are read
  in place; CIFAR extracts to ``cifar-10-batches-py/``; Tiny-ImageNet
  extracts then still needs the documented ``tiny-etl`` + ``cache-tiny``
  passes); LOAN has no anonymous upstream (Kaggle auth) and is documented
  as a manual step through the existing ``loan-etl``;
- ``--check-only`` does the same audit with zero network, exits nonzero
  when anything is absent, and prints exactly what a training run will do
  instead: fall back to the deterministic synthetic stand-in
  (datasets.py) — never an error, but never silent either.
"""
from __future__ import annotations

import dataclasses
import hashlib
import sys
import tarfile
import zipfile
from pathlib import Path
from typing import Callable, List, Optional

# statuses a dataset can preflight to
READY = "ready"          # loader-ready files on disk (verified when pinned)
ARCHIVE = "archive"      # verified archive present, extraction/ETL needed
MISSING = "missing"      # nothing usable on disk → synthetic fallback
CORRUPT = "corrupt"      # artifact present but fails its pinned checksum
MANUAL = "manual"        # no anonymous upstream; operator action required


@dataclasses.dataclass(frozen=True)
class RemoteFile:
    """One upstream artifact: where it lives, what its bytes hash to."""
    relpath: str                  # destination under data_dir
    url: Optional[str]            # None = manual acquisition
    sha256: Optional[str] = None  # None = upstream publishes no digest;
                                  # fetch prints the computed one to pin


# MNIST digests are the canonical published values for the four idx
# archives (mirrored by CVDF for programmatic access — yann.lecun.com now
# 403s unauthenticated clients); the CIFAR-10 digest is the published
# value for cifar-10-python.tar.gz from the Toronto origin.
_MNIST_BASE = "https://storage.googleapis.com/cvdf-datasets/mnist/"
_MNIST_FILES = [
    RemoteFile("MNIST/raw/train-images-idx3-ubyte.gz",
               _MNIST_BASE + "train-images-idx3-ubyte.gz",
               "440fcabf73cc546fa21475e81ea370265605f56be210a402"
               "4d2ca8f203523609"),
    RemoteFile("MNIST/raw/train-labels-idx1-ubyte.gz",
               _MNIST_BASE + "train-labels-idx1-ubyte.gz",
               "3552534a0a558bbed6aed32b30c495cca23d567ec52cac8b"
               "e1a0730e8010255c"),
    RemoteFile("MNIST/raw/t10k-images-idx3-ubyte.gz",
               _MNIST_BASE + "t10k-images-idx3-ubyte.gz",
               "8d422c7b0a1c1c79245a5bcf07fe86e33eeafee792b84584"
               "aec276f5a2dbc4e6"),
    RemoteFile("MNIST/raw/t10k-labels-idx1-ubyte.gz",
               _MNIST_BASE + "t10k-labels-idx1-ubyte.gz",
               "f7ae60f92e00ec6debd23a6088c31dbd2371eca3ffa0defa"
               "efb259924204aec6"),
]
_CIFAR_FILES = [
    RemoteFile("cifar-10-python.tar.gz",
               "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
               "6d958be074577803d12ecdefd02955f39262c83c16fe9348"
               "329d7fe0b5c001ce"),
]
_TINY_FILES = [
    # Stanford publishes no digest for the zip; fetch verifies a sane size
    # and prints the computed sha256 so deployments can pin it themselves.
    RemoteFile("tiny-imagenet-200.zip",
               "http://cs231n.stanford.edu/tiny-imagenet-200.zip", None),
]
_LOAN_FILES = [
    # Kaggle's lending-club dataset requires an authenticated session (the
    # reference shipped a Google-Drive copy, README.md:33-34) — manual:
    # download `accepted_2007_to_2018Q4.csv` (or the reference's
    # loan_data.csv), then run `python -m dba_mod_tpu.main loan-etl
    # --input <csv>` to produce the per-state shards datasets.py loads.
    RemoteFile("loan/", None, None),
]


@dataclasses.dataclass(frozen=True)
class DatasetSpec:
    name: str
    files: List[RemoteFile]
    ready_probe: Callable[[Path], bool]   # loader-ready layout present?
    post_steps: str = ""                  # remaining ETL after download


def _mnist_ready(root: Path) -> bool:
    # same search paths as datasets.load_mnist (idx files, .gz accepted)
    names = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    dirs = [root, root / "MNIST" / "raw", root / "mnist"]
    return all(any((d / n).exists() or (d / (n + ".gz")).exists()
                   for d in dirs) for n in names)


def _cifar_ready(root: Path) -> bool:
    return (root / "cifar-10-batches-py" / "data_batch_1").exists()


def _tiny_ready(root: Path) -> bool:
    return ((root / "tiny-imagenet-200.npz").exists()
            or (root / "tiny-imagenet-200" / "train").exists())


def _loan_ready(root: Path) -> bool:
    return bool(list((root / "loan").glob("loan_*.csv")))


DATASETS = {
    "mnist": DatasetSpec("mnist", _MNIST_FILES, _mnist_ready),
    "cifar": DatasetSpec(
        "cifar", _CIFAR_FILES, _cifar_ready,
        post_steps="auto-extracted to cifar-10-batches-py/"),
    "tiny-imagenet-200": DatasetSpec(
        "tiny-imagenet-200", _TINY_FILES, _tiny_ready,
        post_steps="then: python -m dba_mod_tpu.main tiny-etl && "
                   "python -m dba_mod_tpu.main cache-tiny"),
    "loan": DatasetSpec(
        "loan", _LOAN_FILES, _loan_ready,
        post_steps="manual Kaggle download, then: python -m "
                   "dba_mod_tpu.main loan-etl --input <raw csv>"),
}


def sha256_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def check_dataset(name: str, data_dir: str | Path) -> "tuple[str, List[str]]":
    """Audit one dataset with zero network. Returns (status, detail lines):
    READY when the loader will find real data; ARCHIVE when a (verified)
    archive awaits extraction/ETL; CORRUPT when a pinned checksum fails;
    MANUAL for LOAN with nothing on disk; MISSING otherwise."""
    spec = DATASETS[name]
    root = Path(data_dir)
    details: List[str] = []
    if spec.ready_probe(root):
        return READY, [f"loader-ready files present under {root}"]
    status = MISSING
    for rf in spec.files:
        dst = root / rf.relpath
        if rf.url is None:
            details.append(f"{rf.relpath}: no anonymous upstream — "
                           f"{spec.post_steps}")
            status = MANUAL
            continue
        if not dst.exists():
            details.append(f"{rf.relpath}: absent (upstream: {rf.url})")
            continue
        if rf.sha256 is not None:
            got = sha256_file(dst)
            if got != rf.sha256:
                details.append(
                    f"{rf.relpath}: sha256 MISMATCH — expected "
                    f"{rf.sha256}, got {got} (truncated/tampered "
                    f"download; delete and re-fetch)")
                return CORRUPT, details
            details.append(f"{rf.relpath}: archive verified "
                           f"(sha256 {got[:12]}…)")
        else:
            details.append(
                f"{rf.relpath}: present, {dst.stat().st_size} bytes — "
                f"upstream publishes no digest; computed sha256 "
                f"{sha256_file(dst)} (pin it in your deploy config)")
        status = ARCHIVE
    return status, details


def _download(rf: RemoteFile, dst: Path) -> bool:
    """Stream one artifact; sha256-verify when pinned. Failure is reported
    and survivable — preflight continues to the fallback report."""
    import urllib.request
    dst.parent.mkdir(parents=True, exist_ok=True)
    tmp = dst.with_suffix(dst.suffix + ".fetch_tmp")
    try:
        print(f"  fetching {rf.url}")
        with urllib.request.urlopen(rf.url, timeout=60) as r, \
                open(tmp, "wb") as f:
            while True:
                chunk = r.read(1 << 20)
                if not chunk:
                    break
                f.write(chunk)
        if rf.sha256 is not None:
            got = sha256_file(tmp)
            if got != rf.sha256:
                print(f"  sha256 mismatch for {dst.name}: expected "
                      f"{rf.sha256}, got {got} — discarding", file=sys.stderr)
                tmp.unlink(missing_ok=True)
                return False
            print(f"  verified sha256 {got[:12]}…")
        else:
            print(f"  downloaded; computed sha256 {sha256_file(tmp)} "
                  f"(upstream publishes none — pin this)")
        tmp.replace(dst)
        return True
    except Exception as exc:  # noqa: BLE001 — network failures must not
        print(f"  fetch failed: {exc!r}", file=sys.stderr)  # kill preflight
        tmp.unlink(missing_ok=True)
        return False


def _extract(name: str, data_dir: Path) -> None:
    """Unpack downloaded archives into the loader layout."""
    if name == "cifar":
        tar = data_dir / "cifar-10-python.tar.gz"
        if tar.exists() and not _cifar_ready(data_dir):
            print(f"  extracting {tar.name}")
            with tarfile.open(tar, "r:gz") as t:
                t.extractall(data_dir)  # noqa: S202 — pinned-sha archive
    elif name == "tiny-imagenet-200":
        z = data_dir / "tiny-imagenet-200.zip"
        if z.exists() and not (data_dir / "tiny-imagenet-200").exists():
            print(f"  extracting {z.name}")
            with zipfile.ZipFile(z) as f:
                f.extractall(data_dir)


_FALLBACK_NOTE = (
    "runs will use the DETERMINISTIC SYNTHETIC stand-in "
    "(data/datasets.py): same shapes/class counts, seeded by "
    "random_seed — every pipeline stage still runs, but accuracy "
    "curves are not the real dataset's")


def run_preflight(types: Optional[List[str]], data_dir: str,
                  check_only: bool = False) -> int:
    """The `fetch` subcommand body. Returns the process exit code: 0 when
    every requested dataset is loader-ready, 1 otherwise (preflight
    contract — CI gates on it)."""
    names = list(types) if types else list(DATASETS)
    root = Path(data_dir)
    all_ready = True
    for name in names:
        status, details = check_dataset(name, root)
        if status not in (READY,) and not check_only:
            spec = DATASETS[name]
            for rf in spec.files:
                if rf.url is None:
                    continue
                dst = root / rf.relpath
                if not dst.exists() or status == CORRUPT:
                    if status == CORRUPT:
                        dst.unlink(missing_ok=True)
                    _download(rf, dst)
            _extract(name, root)
            status, details = check_dataset(name, root)
        print(f"{name}: {status.upper()}")
        for d in details:
            print(f"  {d}")
        spec = DATASETS[name]
        if status == ARCHIVE and spec.post_steps:
            print(f"  next: {spec.post_steps}")
        if status != READY:
            all_ready = False
            print(f"  -> {_FALLBACK_NOTE}")
    return 0 if all_ready else 1
