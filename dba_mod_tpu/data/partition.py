"""Client data partitioning with reference-RNG parity.

`sample_dirichlet_indices` reproduces image_helper.py:82-110 *numerically*:
same `random.shuffle` on each class's index pool, same
`np.random.dirichlet([alpha]*P)` draw per class, same int(round(·)) prefix
consumption of the pool — so with the same seeds the resulting partition is
identical to the reference's, which keeps accuracy curves comparable
(SURVEY §7.2.7).
"""
from __future__ import annotations

import random
from collections import defaultdict
from typing import Dict, List, Sequence

import numpy as np


def build_class_indices(labels: np.ndarray) -> Dict[int, List[int]]:
    """Label → list of sample indices, in dataset order
    (image_helper.py:72-80)."""
    out: Dict[int, List[int]] = defaultdict(list)
    for ind, label in enumerate(labels):
        out[int(label)].append(ind)
    return dict(out)


def sample_dirichlet_indices(labels: np.ndarray, no_participants: int,
                             alpha: float,
                             py_rng: random.Random | None = None,
                             np_rng: np.random.RandomState | None = None
                             ) -> Dict[int, List[int]]:
    """Non-IID Dirichlet partition (image_helper.py:82-110). Consumes RNG in
    the reference's order: per class, shuffle the pool then draw one Dirichlet
    vector over participants. `class_size` is len(class 0)'s pool, used as the
    scale for every class (reference quirk, :92)."""
    py_rng = py_rng or random
    np_rng = np_rng or np.random
    classes = build_class_indices(labels)
    class_size = len(classes[0])
    no_classes = len(classes)
    per_participant: Dict[int, List[int]] = defaultdict(list)
    for n in range(no_classes):
        pool = classes[n]
        py_rng.shuffle(pool)
        probs = class_size * np_rng.dirichlet(
            np.array(no_participants * [alpha]))
        for user in range(no_participants):
            no_imgs = int(round(probs[user]))
            take = min(len(pool), no_imgs)
            per_participant[user].extend(pool[:take])
            pool = pool[take:]
    return dict(per_participant)


def equal_split_indices(num_samples: int, no_participants: int,
                        py_rng: random.Random | None = None
                        ) -> Dict[int, List[int]]:
    """Equal random split (image_helper.py:231-236, :265-280): one global
    shuffle, then contiguous chunks of len(dataset)/P."""
    py_rng = py_rng or random
    all_range = list(range(num_samples))
    py_rng.shuffle(all_range)
    data_len = num_samples // no_participants
    return {pos: all_range[pos * data_len:(pos + 1) * data_len]
            for pos in range(no_participants)}


def poison_test_indices(test_labels: np.ndarray,
                        poison_label_swap: int) -> np.ndarray:
    """Indices of test samples whose true label != the swap target — the
    poisoned-eval set drops images already of the target class
    (image_helper.py:148-172)."""
    return np.nonzero(test_labels != poison_label_swap)[0].astype(np.int32)
