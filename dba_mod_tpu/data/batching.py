"""Batch plans: precomputed index tensors driving device-resident gathers.

The reference's DataLoader+SubsetRandomSampler reshuffles each client's subset
every internal epoch and yields a partial final batch (image_helper.py:252-263,
drop_last=False). The TPU-native equivalent precomputes, per round, an index
tensor [clients, epochs, steps, batch] plus a validity mask; the jitted client
step gathers rows straight from the device-resident dataset — the host ships
only these small int32 plans each round.

Shuffling uses per-client numpy RNG rather than the reference's global torch
RNG: the sequential loop's RNG stream is inherently irreproducible under
parallel clients, so parity here is statistical (SURVEY §7.2.4).
"""
from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np


@dataclasses.dataclass
class BatchPlan:
    """One round's data access plan for the stacked client step."""
    idx: np.ndarray        # [C, E, S, B] int32 indices into the dataset
    mask: np.ndarray       # [C, E, S, B] bool — valid (non-padding) samples
    num_samples: np.ndarray  # [C] int32 — true per-client dataset sizes
    num_epochs: np.ndarray   # [C] int32 — per-client internal-epoch counts


@dataclasses.dataclass
class EvalPlan:
    idx: np.ndarray        # [S, B] int32
    mask: np.ndarray       # [S, B] bool


def build_batch_plan(client_indices: Sequence[Sequence[int]],
                     client_epochs: Sequence[int], batch_size: int,
                     rng: np.random.RandomState,
                     min_steps: int = 1, min_epochs: int = 1) -> BatchPlan:
    """Build the [C, E, S, B] plan. E = max(client_epochs, min_epochs);
    clients with fewer epochs get fully-masked rows beyond their count. Every
    epoch reshuffles each client's subset (SubsetRandomSampler semantics).
    Empty clients are fully masked. `min_steps`/`min_epochs` pin the plan
    shape across rounds so the jitted round never recompiles."""
    C = len(client_indices)
    E = max(min_epochs, max(client_epochs, default=1), 1)
    sizes = np.array([len(ix) for ix in client_indices], np.int32)
    S = max(min_steps, int(np.ceil(sizes.max() / batch_size)) if sizes.max() else min_steps)
    idx = np.zeros((C, E, S, batch_size), np.int64)
    mask = np.zeros((C, E, S, batch_size), bool)
    for c, indices in enumerate(client_indices):
        n = len(indices)
        if n == 0:
            continue
        arr = np.asarray(indices, np.int64)
        for e in range(min(int(client_epochs[c]), E) if client_epochs[c] else 0):
            shuffled = arr[rng.permutation(n)]
            # Pad by wrapping the shuffled subset rather than with zeros:
            # padding rows are masked out of the loss but still flow through
            # BatchNorm's batch statistics, so they must be real samples of
            # the same client, not black images.
            reps = int(np.ceil(S * batch_size / n))
            padded = np.tile(shuffled, reps)[:S * batch_size]
            idx[c, e] = padded.reshape(S, batch_size)
            m = np.zeros((S * batch_size,), bool)
            m[:n] = True
            mask[c, e] = m.reshape(S, batch_size)
    return BatchPlan(idx=idx.astype(np.int32), mask=mask, num_samples=sizes,
                     num_epochs=np.asarray(client_epochs, np.int32))


def build_eval_plan(indices: np.ndarray, batch_size: int) -> EvalPlan:
    """Sequential padded batches over `indices` (test loaders iterate the full
    set once; order is irrelevant to the accuracy sums — test.py:29-37)."""
    n = len(indices)
    S = max(1, int(np.ceil(n / batch_size)))
    idx = np.zeros((S * batch_size,), np.int64)
    idx[:n] = np.asarray(indices, np.int64)
    mask = np.zeros((S * batch_size,), bool)
    mask[:n] = True
    return EvalPlan(idx=idx.reshape(S, batch_size).astype(np.int32),
                    mask=mask.reshape(S, batch_size))


def stack_ragged(arrays: List[np.ndarray], pad_value=0) -> np.ndarray:
    """Stack per-client ragged arrays into [C, max_n, ...] with padding —
    used for LOAN per-state shards."""
    C = len(arrays)
    max_n = max(a.shape[0] for a in arrays)
    out = np.full((C, max_n) + arrays[0].shape[1:], pad_value,
                  arrays[0].dtype)
    for i, a in enumerate(arrays):
        out[i, :a.shape[0]] = a
    return out
