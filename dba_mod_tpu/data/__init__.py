"""Data layer: host-side ingestion + partitioning, device-resident batching.

Replaces the reference's torchvision/DataLoader stack (image_helper.py:173-296,
loan_helper.py:29-210) with:

- raw-file dataset loaders (MNIST idx, CIFAR-10 pickle, Tiny-ImageNet folders,
  LOAN per-state CSVs) plus deterministic synthetic fallbacks for machines
  without the datasets (zero-egress environments, CI);
- numerically-parity-preserving client partitioning (Dirichlet / equal /
  per-US-state natural shards);
- *batch plans*: precomputed [clients, epochs, steps, batch] index tensors into
  a device-resident dataset, so a whole FL round's data access is one gather —
  no host↔device transfer in the hot loop.
"""
from dba_mod_tpu.data.datasets import (ImageData, LoanData, load_image_dataset,
                                       load_loan_dataset)
from dba_mod_tpu.data.partition import (equal_split_indices,
                                        sample_dirichlet_indices)
from dba_mod_tpu.data.batching import (BatchPlan, EvalPlan, build_batch_plan,
                                       build_eval_plan)
