"""Offline data-prep tooling (reference L9: utils/loan_preprocess.py,
utils/tinyimagenet_reformat.py, run via the process_*.sh scripts).

`preprocess_loan` reproduces the reference pipeline semantics
(loan_preprocess.py:8-56): drop the two fixed column lists, fillna(0),
first-appearance ordinal-encode object columns (except addr_state),
magnitude-bucket scale numeric columns by their mean (>10→/10, >100→/100,
>1000→/10000), then split into one CSV per `addr_state` — the natural 51-way
client sharding.

`reformat_tiny_imagenet_val` reproduces tinyimagenet_reformat.py: move val
images into per-wnid folders using val_annotations.txt.
"""
from __future__ import annotations

from pathlib import Path

_DROP_COLS_A = ["id", "member_id", "emp_title", "issue_d", "zip_code",
                "emp_length", "title", "earliest_cr_line", "last_pymnt_d",
                "hardship_start_date", "desc", "hardship_end_date",
                "payment_plan_start_date", "next_pymnt_d", "settlement_date",
                "last_credit_pull_d", "debt_settlement_flag_date",
                "sec_app_earliest_cr_line"]
_DROP_COLS_B = ["url", "mths_since_last_delinq", "mths_since_last_major_derog",
                "mths_since_last_record", "annual_inc_joint", "dti_joint",
                "verification_status_joint", "mths_since_recent_bc_dlq",
                "mths_since_recent_revol_delinq", "revol_bal_joint",
                "sec_app_inq_last_6mths", "sec_app_mort_acc",
                "sec_app_open_acc", "sec_app_revol_util",
                "sec_app_open_act_il", "sec_app_num_rev_accts",
                "sec_app_chargeoff_within_12_mths",
                "sec_app_collections_12_mths_ex_med",
                "sec_app_mths_since_last_major_derog", "hardship_type",
                "hardship_reason", "hardship_status", "deferral_term",
                "hardship_amount", "hardship_length", "hardship_dpd",
                "hardship_loan_status",
                "orig_projected_additional_accrued_interest",
                "hardship_payoff_balance_amount",
                "hardship_last_payment_amount", "settlement_status",
                "settlement_amount", "settlement_percentage",
                "settlement_term"]


def preprocess_loan(input_csv: str | Path, out_dir: str | Path) -> int:
    """Raw Kaggle lending-club CSV → per-state CSVs. Returns shard count."""
    import pandas as pd

    df = pd.read_csv(input_csv)
    df = df.drop(columns=[c for c in _DROP_COLS_A if c in df.columns])
    df = df.drop(columns=[c for c in _DROP_COLS_B if c in df.columns])
    df = df.fillna(0)

    for col in df.columns:
        # reference checks dtype == 'object' (loan_preprocess.py:22); newer
        # pandas may infer a dedicated string dtype for the same columns
        is_texty = (df[col].dtype == object
                    or pd.api.types.is_string_dtype(df[col]))
        if is_texty and col != "addr_state":
            # first-appearance ordinal encoding (loan_preprocess.py:22-27)
            values = list(df.drop_duplicates(col)[col])
            mapping = {v: j for j, v in enumerate(values)}
            df[col] = df[col].map(mapping)
        elif pd.api.types.is_numeric_dtype(df[col]):
            mean = df[col].mean()
            if 10.0 < mean <= 100.0:
                df[col] = df[col] / 10
            elif 100.0 < mean <= 1000.0:
                df[col] = df[col] / 100
            elif mean > 1000.0:
                df[col] = df[col] / 10000

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    states = sorted(set(df["addr_state"]))
    for state in states:
        shard = df.loc[df["addr_state"] == state].drop(columns=["addr_state"])
        shard.to_csv(out_dir / f"loan_{state}.csv", index=False)
    return len(states)


def reformat_tiny_imagenet_val(root: str | Path) -> int:
    """Move <root>/val/images/* into <root>/val/<wnid>/ per
    val_annotations.txt. Returns moved-image count."""
    import shutil

    root = Path(root)
    val = root / "val"
    ann = val / "val_annotations.txt"
    if not ann.exists():
        return 0
    val_dict = {}
    with open(ann) as f:
        for line in f:
            parts = line.split("\t")
            if len(parts) >= 2:
                val_dict[parts[0]] = parts[1]
    moved = 0
    img_dir = val / "images"
    for path in sorted(img_dir.glob("*")):
        wnid = val_dict.get(path.name)
        if wnid is None:
            continue
        dest = val / wnid
        dest.mkdir(exist_ok=True)
        shutil.move(str(path), str(dest / path.name))
        moved += 1
    if moved:
        ann.unlink()
        try:
            img_dir.rmdir()
        except OSError:
            pass
    return moved
