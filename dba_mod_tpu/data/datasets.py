"""Dataset ingestion: raw files when present, deterministic synthetic fallback.

The reference downloads via torchvision (image_helper.py:186-219) and reads
LOAN per-state CSVs produced by its ETL (loan_helper.py:111-132,
utils/loan_preprocess.py). This module reads the same on-disk artifacts
directly (idx/pickle/folder/CSV — no torch dependency in the data path) and,
when the files are absent, generates a *deterministic synthetic* stand-in with
the same shapes/class counts so every pipeline stage runs anywhere. Pixel
values match the reference's ToTensor() range [0,1] (no normalization —
image_helper.py:178-201); images are stored uint8 host-side and scaled on
device.
"""
from __future__ import annotations

import dataclasses
import gzip
import os
import pickle
import struct
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from dba_mod_tpu import config as cfg


@dataclasses.dataclass
class ImageData:
    """Host-side image classification data. Images uint8 NHWC in [0,255]."""
    train_images: np.ndarray
    train_labels: np.ndarray
    test_images: np.ndarray
    test_labels: np.ndarray
    num_classes: int
    synthetic: bool = False


@dataclasses.dataclass
class LoanData:
    """Host-side LOAN data: one shard per US state (natural non-IID clients,
    loan_helper.py:119-132). 80/20 train/test split per shard with
    sklearn(random_state=42) parity (loan_helper.py:172)."""
    state_names: List[str]
    train_x: List[np.ndarray]   # per state, [N_s, F] float32
    train_y: List[np.ndarray]
    test_x: List[np.ndarray]
    test_y: List[np.ndarray]
    feature_names: List[str]
    num_classes: int = 9
    synthetic: bool = False

    @property
    def feature_dict(self) -> Dict[str, int]:
        return {n: i for i, n in enumerate(self.feature_names)}


# ---------------------------------------------------------------------- MNIST
def _read_idx(path: Path) -> np.ndarray:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        magic, = struct.unpack(">I", f.read(4))
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), np.uint8).reshape(dims)


def _find(dirs: List[Path], names: List[str]) -> Optional[Path]:
    for d in dirs:
        for n in names:
            for cand in (d / n, d / (n + ".gz")):
                if cand.exists():
                    return cand
    return None


def load_mnist(data_dir: str) -> Optional[ImageData]:
    root = Path(data_dir)
    dirs = [root, root / "MNIST" / "raw", root / "mnist"]
    files = {
        "train_x": ["train-images-idx3-ubyte"],
        "train_y": ["train-labels-idx1-ubyte"],
        "test_x": ["t10k-images-idx3-ubyte"],
        "test_y": ["t10k-labels-idx1-ubyte"],
    }
    paths = {k: _find(dirs, v) for k, v in files.items()}
    if any(p is None for p in paths.values()):
        return None
    return ImageData(
        train_images=_read_idx(paths["train_x"])[..., None],
        train_labels=_read_idx(paths["train_y"]).astype(np.int32),
        test_images=_read_idx(paths["test_x"])[..., None],
        test_labels=_read_idx(paths["test_y"]).astype(np.int32),
        num_classes=10)


# --------------------------------------------------------------------- CIFAR10
def load_cifar10(data_dir: str) -> Optional[ImageData]:
    root = Path(data_dir) / "cifar-10-batches-py"
    if not root.exists():
        return None

    def read_batch(name):
        with open(root / name, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return imgs, np.array(d[b"labels"], np.int32)

    xs, ys = zip(*[read_batch(f"data_batch_{i}") for i in range(1, 6)])
    test_x, test_y = read_batch("test_batch")
    return ImageData(np.concatenate(xs), np.concatenate(ys), test_x, test_y,
                     num_classes=10)


# -------------------------------------------------------------- Tiny-ImageNet
def load_tiny_imagenet(data_dir: str) -> Optional[ImageData]:
    """Reads the post-ETL layout (train/<wnid>/images/*.JPEG + reformatted
    val/<wnid>/*), or a prebuilt `tiny-imagenet-200.npz` cache. JPEG decoding
    needs PIL; building the npz cache once via
    `python -m dba_mod_tpu.main cache-tiny` is the fast path."""
    root = Path(data_dir) / "tiny-imagenet-200"
    npz = root.with_suffix(".npz")
    if npz.exists():
        z = np.load(npz)
        return ImageData(z["train_x"], z["train_y"].astype(np.int32),
                         z["test_x"], z["test_y"].astype(np.int32), 200)
    if not (root / "train").exists():
        return None
    try:
        from PIL import Image
    except ImportError:
        return None
    wnids = sorted(p.name for p in (root / "train").iterdir() if p.is_dir())
    cls = {w: i for i, w in enumerate(wnids)}

    def read_split(split_dir: Path):
        xs, ys = [], []
        for wnid_dir in sorted(split_dir.iterdir()):
            if not wnid_dir.is_dir() or wnid_dir.name not in cls:
                continue
            img_dir = wnid_dir / "images" if (wnid_dir / "images").exists() else wnid_dir
            for img_path in sorted(img_dir.glob("*.JPEG")):
                img = np.asarray(Image.open(img_path).convert("RGB"), np.uint8)
                xs.append(img)
                ys.append(cls[wnid_dir.name])
        return np.stack(xs), np.array(ys, np.int32)

    train_x, train_y = read_split(root / "train")
    test_x, test_y = read_split(root / "val")
    return ImageData(train_x, train_y, test_x, test_y, 200)


# ------------------------------------------------------------------ synthetic
_IMAGE_SHAPES = {cfg.TYPE_MNIST: (28, 28, 1, 10),
                 cfg.TYPE_CIFAR: (32, 32, 3, 10),
                 cfg.TYPE_TINYIMAGENET: (64, 64, 3, 200)}


def synthetic_image_dataset(dtype: str, train_size: int = 0,
                            test_size: int = 0, seed: int = 0,
                            noise_std: float = 25.0) -> ImageData:
    """Deterministic learnable stand-in: per-class low-frequency template +
    noise, labels balanced. Sized like the real dataset unless overridden.

    `noise_std` (config key `synthetic_noise_std`) sets the task's
    difficulty: 25 → models saturate at ~100% (handy for fast smoke runs);
    ~90 → a ResNet plateaus below saturation with nonzero loss, emulating
    the real-data converged regime (nonzero gradients at the plateau — the
    regime the reference resumes its attacks from; fully-saturated models
    make FoolsGold's gradient similarities rounding noise and turn
    post-attack recovery into a cliff)."""
    h, w, c, ncls = _IMAGE_SHAPES[dtype]
    defaults = {cfg.TYPE_MNIST: (60000, 10000), cfg.TYPE_CIFAR: (50000, 10000),
                cfg.TYPE_TINYIMAGENET: (100000, 10000)}
    n_train = train_size or defaults[dtype][0]
    n_test = test_size or defaults[dtype][1]
    rng = np.random.RandomState(seed)
    templates = rng.randint(40, 216, size=(ncls, h, w, c)).astype(np.float32)

    def make(n, rng):
        labels = rng.randint(0, ncls, size=n).astype(np.int32)
        noise = rng.randn(n, h, w, c).astype(np.float32) * float(noise_std)
        imgs = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
        return imgs, labels

    train_x, train_y = make(n_train, rng)
    test_x, test_y = make(n_test, np.random.RandomState(seed + 1))
    return ImageData(train_x, train_y, test_x, test_y, ncls, synthetic=True)


_US_STATES = ["AK", "AL", "AR", "AZ", "CA", "CO", "CT", "DC", "DE", "FL", "GA",
              "HI", "IA", "ID", "IL", "IN", "KS", "KY", "LA", "MA", "MD", "ME",
              "MI", "MN", "MO", "MS", "MT", "NC", "ND", "NE", "NH", "NJ", "NM",
              "NV", "NY", "OH", "OK", "OR", "PA", "RI", "SC", "SD", "TN", "TX",
              "UT", "VA", "VT", "WA", "WI", "WV", "WY"]

# Feature names used by the reference LOAN trigger configs
# (utils/loan_params.yaml:31-36) must exist in the synthetic schema.
_LOAN_TRIGGER_FEATURES = ["num_tl_120dpd_2m", "num_tl_90g_dpd_24m",
                          "pub_rec_bankruptcies", "pub_rec", "acc_now_delinq",
                          "tax_liens", "out_prncp", "total_pymnt_inv",
                          "out_prncp_inv", "total_rec_prncp",
                          "last_pymnt_amnt", "all_util"]


def synthetic_loan_dataset(num_states: int = 51, num_features: int = 91,
                           rows_per_state: int = 800,
                           seed: int = 0) -> LoanData:
    """Synthetic LOAN: 9-class labels correlated with features through a fixed
    random linear map, per-state row counts varied deterministically."""
    feature_names = list(_LOAN_TRIGGER_FEATURES)
    feature_names += [f"feat_{i}" for i in range(num_features - len(feature_names))]
    rng = np.random.RandomState(seed)
    proj = rng.randn(num_features, 9).astype(np.float32)
    names, tx, ty, sx, sy = [], [], [], [], []
    for s in range(num_states):
        n = rows_per_state + (s * 37) % 400
        x = rng.randn(n, num_features).astype(np.float32)
        logits = x @ proj + rng.randn(n, 9).astype(np.float32)
        y = np.argmax(logits, axis=1).astype(np.int32)
        k = max(1, int(0.8 * n))
        names.append(_US_STATES[s % len(_US_STATES)])
        tx.append(x[:k]); ty.append(y[:k]); sx.append(x[k:]); sy.append(y[k:])
    return LoanData(names, tx, ty, sx, sy, feature_names, synthetic=True)


def load_loan_csvs(data_dir: str) -> Optional[LoanData]:
    """Per-state CSVs from the LOAN ETL (utils/loan_preprocess.py:49-56; files
    named loan_<STATE>.csv with a `loan_status` label column). Split 80/20 with
    sklearn random_state=42 for parity with LoanDataset (loan_helper.py:172)."""
    root = Path(data_dir) / "loan"
    if not root.exists():
        return None
    try:
        import pandas as pd
        from sklearn.model_selection import train_test_split
    except ImportError:
        return None
    files = sorted(root.glob("loan_*.csv"))
    if not files:
        return None
    names, tx, ty, sx, sy, feature_names = [], [], [], [], [], None
    for f in files:
        df = pd.read_csv(f)
        x_cols = [c for c in df.columns if c != "loan_status"]
        if feature_names is None:
            feature_names = x_cols
        x = df[x_cols].values.astype(np.float32)
        y = df["loan_status"].values.astype(np.int32)
        x_tr, x_te, y_tr, y_te = train_test_split(x, y, test_size=0.2,
                                                  random_state=42)
        names.append(f.stem[5:7])
        tx.append(x_tr); ty.append(y_tr); sx.append(x_te); sy.append(y_te)
    return LoanData(names, tx, ty, sx, sy, feature_names)


# ------------------------------------------------------------------ dispatch
def load_image_dataset(params: cfg.Params) -> ImageData:
    t = params.type
    data = None
    if not params.get("synthetic_data", False):
        loader = {cfg.TYPE_MNIST: load_mnist, cfg.TYPE_CIFAR: load_cifar10,
                  cfg.TYPE_TINYIMAGENET: load_tiny_imagenet}[t]
        data = loader(params.get("data_dir", "./data"))
    if data is None:
        data = synthetic_image_dataset(
            t, train_size=int(params.get("synthetic_train_size", 0) or 0),
            test_size=int(params.get("synthetic_test_size", 0) or 0),
            seed=int(params.get("random_seed", 1)),
            noise_std=float(params.get("synthetic_noise_std", 25.0)))
    return data


def load_loan_dataset(params: cfg.Params) -> LoanData:
    data = None
    if not params.get("synthetic_data", False):
        data = load_loan_csvs(params.get("data_dir", "./data"))
    if data is None:
        data = synthetic_loan_dataset(
            num_states=max(51, int(params["number_of_total_participants"])),
            seed=int(params.get("random_seed", 1)))
    return data
