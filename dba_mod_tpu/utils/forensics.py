"""Defense forensics: per-client aggregation-introspection artifacts.

The paper's central question — can an aggregation defense *see* a
distributed backdoor — needs per-round, per-client evidence: what each
client submitted (norms), how aligned it was with what the server applied
(cosine), what the screening pass decided (verdict + reason), and how the
defense weighted it (FoolsGold wv/alpha, RFA Weiszfeld weights/distances).
`fl/rounds.py` computes these inside the jitted round program
(ForensicStats rides the payload's single device_get); this module is the
host side: `ForensicsWriter` streams the rows to two run-folder files and
mirrors them to TensorBoard, and `render_report` turns them into a
standalone HTML round-audit for the `report` CLI subcommand.

Files (written atomically, recorder-style full rewrites — crash-safe):

  forensics.jsonl       one line per round: the full per-client vectors
                        plus round-level defense outcomes (quarantine
                        count, retries, degradation, RFA oracle calls)
  client_forensics.csv  one row per (round, client) with the stable
                        FORENSICS_HEADER schema (tests/test_forensics.py
                        pins names and dtypes)

Everything here is inert unless `forensics: true` — the Experiment never
constructs a writer otherwise.
"""
from __future__ import annotations

import csv
import json
import math
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from dba_mod_tpu.utils.html import html_doc, svg_timeline, table_html

# Column schema of client_forensics.csv — STABLE: downstream notebooks and
# the schema golden test parse by name. Ints: epoch/client/participant_id/
# adversary/verdict; floats (or blank when not applicable): delta_norm/
# recv_norm/cosine_to_agg/agg_weight/fg_max_sim/rfa_distance/poison_acc;
# strings: name, reason.
FORENSICS_HEADER = [
    "epoch", "client", "name", "participant_id", "adversary",
    "delta_norm", "recv_norm", "cosine_to_agg", "verdict", "reason",
    "agg_weight", "fg_max_sim", "rfa_distance", "poison_acc"]


def _fmt(v: Optional[float]) -> str:
    """Float cell: blank for not-applicable, 'nan'/'inf' kept verbatim
    (a corrupted payload's norm IS the forensic signal)."""
    if v is None:
        return ""
    return format(float(v), ".6g")


def _jsonable(vals) -> Optional[List[Optional[float]]]:
    """JSON-safe float list: non-finite → None (json.dumps would otherwise
    emit bare NaN tokens, which are not valid JSON)."""
    if vals is None:
        return None
    return [float(v) if math.isfinite(float(v)) else None for v in vals]


class ForensicsWriter:
    """Accumulates per-round forensic rows; saves after every round.

    `folder=None` keeps everything in memory (bench runs with
    save_results=False still exercise the full row-building path).
    `tb_sink(tag, value, step)` mirrors per-client scalars under
    `forensics/...` — wired to the recorder's TensorBoard writer when
    `tensorboard: true`."""

    def __init__(self, folder: Optional[Path] = None, tb_sink=None):
        self.folder = Path(folder) if folder else None
        self.tb_sink = tb_sink
        self.rows: List[list] = []          # client_forensics.csv data rows
        self.round_rows: List[dict] = []    # forensics.jsonl lines

    def add_round(self, *, epoch: int, aggregation: str,
                  names: Sequence[Any], participant_ids: Sequence[int],
                  adversary_flags: Sequence[int], delta_norms, recv_norms,
                  cosine, verdict, reason_codes,
                  reason_names: Dict[int, str], weights=None, alpha=None,
                  poison_acc=None, oracle_calls: int = 1,
                  n_retries: int = 0, degraded: bool = False) -> None:
        """One round's forensic record. Vector args are length-C host
        arrays (C = real clients; padded mesh lanes already sliced off by
        the caller). `weights`/`alpha` are None for FedAvg, whose rule
        defines no per-client weight; `poison_acc` is None on benign runs
        or when the local battery is off."""
        is_fg = aggregation == "foolsgold"
        reasons = [reason_names.get(int(r), str(int(r)))
                   for r in reason_codes]
        for c, name in enumerate(names):
            w = None if weights is None else float(weights[c])
            a = None if alpha is None else float(alpha[c])
            self.rows.append([
                int(epoch), c, str(name), int(participant_ids[c]),
                int(adversary_flags[c]),
                _fmt(float(delta_norms[c])), _fmt(float(recv_norms[c])),
                _fmt(float(cosine[c])), int(bool(verdict[c])), reasons[c],
                _fmt(w),
                _fmt(a if is_fg else None),        # FoolsGold max pairwise
                _fmt(None if is_fg else a),        # cos-sim vs RFA distance
                _fmt(None if poison_acc is None else float(poison_acc[c])),
            ])
        self.round_rows.append({
            "epoch": int(epoch), "aggregation": str(aggregation),
            "oracle_calls": int(oracle_calls),
            "n_quarantined": int(sum(1 for v in verdict if not bool(v))),
            "n_retries": int(n_retries), "degraded": bool(degraded),
            "clients": [str(n) for n in names],
            "adversaries": [str(n) for n, f in zip(names, adversary_flags)
                            if int(f)],
            "delta_norm": _jsonable(delta_norms),
            "recv_norm": _jsonable(recv_norms),
            "cosine_to_agg": _jsonable(cosine),
            "verdict": [int(bool(v)) for v in verdict],
            "reason": reasons,
            "agg_weight": _jsonable(weights),
            "alpha": _jsonable(alpha),
            "poison_acc": _jsonable(poison_acc)})
        if self.tb_sink is not None:
            for c, name in enumerate(names):
                tag = str(name).replace("/", "_")
                for sub, vals in (("delta_norm", delta_norms),
                                  ("cosine", cosine),
                                  ("weight", weights)):
                    if vals is not None and math.isfinite(float(vals[c])):
                        self.tb_sink(f"forensics/{sub}/{tag}",
                                     float(vals[c]), int(epoch))
            self.tb_sink("forensics/quarantined",
                         float(self.round_rows[-1]["n_quarantined"]),
                         int(epoch))

    # ------------------------------------------------------------------ save
    def _atomic_write(self, name: str, emit) -> None:
        """Crash-safe full rewrite — same contract as Recorder's."""
        path = self.folder / name
        tmp = self.folder / (name + ".tmp")
        try:
            with open(tmp, "w", newline="") as f:
                emit(f)
            os.replace(tmp, path)
        finally:
            if tmp.exists():
                tmp.unlink()

    def save(self) -> None:
        if self.folder is None:
            return
        self.folder.mkdir(parents=True, exist_ok=True)

        def emit_csv(f):
            w = csv.writer(f)
            w.writerow(FORENSICS_HEADER)
            w.writerows(self.rows)

        def emit_jsonl(f):
            for row in self.round_rows:
                f.write(json.dumps(row) + "\n")

        self._atomic_write("client_forensics.csv", emit_csv)
        self._atomic_write("forensics.jsonl", emit_jsonl)

    def load_from_folder(self, keep_until_epoch: int) -> int:
        """Auto-resume: continue the killed run's forensic streams, keeping
        rows through `keep_until_epoch` and dropping later ones — the same
        truncate-and-continue contract as Recorder.load_from_folder (a
        replayed round must not appear twice). Returns kept round count."""
        self.rows, self.round_rows = [], []
        if self.folder is None:
            return 0
        fcsv = self.folder / "client_forensics.csv"
        if fcsv.exists():
            with open(fcsv, newline="") as f:
                data = list(csv.reader(f))
            for row in data[1:]:
                if row and int(row[0]) <= keep_until_epoch:
                    self.rows.append(row)
        fjs = self.folder / "forensics.jsonl"
        if fjs.exists():
            for line in fjs.read_text().splitlines():
                if not line.strip():
                    continue
                rec = json.loads(line)
                if int(rec["epoch"]) <= keep_until_epoch:
                    self.round_rows.append(rec)
        return len(self.round_rows)


# ------------------------------------------------------------------- report
_ATT_COLOR, _BEN_COLOR, _Q_COLOR = "#d62728", "#1f77b4", "#ff7f0e"


def _mean(vals: List[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None and math.isfinite(v)]
    return sum(vals) / len(vals) if vals else None


def _split_series(rounds: List[dict], key: str):
    """(attacker_points, benign_points) — per-epoch means of `key`, split
    by the round's recorded adversary set."""
    att, ben = [], []
    for r in rounds:
        vals = r.get(key)
        if vals is None:
            continue
        adv = set(r.get("adversaries", []))
        a = _mean([v for n, v in zip(r["clients"], vals) if n in adv])
        b = _mean([v for n, v in zip(r["clients"], vals) if n not in adv])
        if a is not None:
            att.append((r["epoch"], a))
        if b is not None:
            ben.append((r["epoch"], b))
    return att, ben


def _timeline(rounds: List[dict], key: str, title: str) -> str:
    att, ben = _split_series(rounds, key)
    series = []
    if att:
        series.append({"label": "attacker mean", "color": _ATT_COLOR,
                       "points": att})
    if ben:
        series.append({"label": "benign mean", "color": _BEN_COLOR,
                       "points": ben, "dash": not att})
    svg = svg_timeline(series, title=title)
    return f"<figure>{svg}</figure>" if svg else ""


def _suspicion(r: dict, c: int) -> float:
    """Per-client suspicion score for the ranking table: quarantined
    clients outrank everything; otherwise low defense weight (FoolsGold/
    RFA) or — for weightless FedAvg — a large received norm is suspicious.
    A display-ranking heuristic, not a detector."""
    if not r["verdict"][c]:
        return 2.0
    w = r.get("agg_weight")
    if w is not None and w[c] is not None:
        finite = [v for v in w if v is not None]
        top = max(finite) if finite else 0.0
        return 1.0 - (w[c] / top if top > 0 else 0.0)
    norms = [v for v in (r.get("recv_norm") or []) if v is not None]
    top = max(norms) if norms else 0.0
    rn = (r.get("recv_norm") or [None])[c]
    if rn is None:
        return 1.0  # non-finite norm: maximally suspicious short of a drop
    return rn / top if top > 0 else 0.0


def render_report(run_folder: Path) -> str:
    """Self-contained HTML round-audit from a run folder's forensics.jsonl:
    attacker-vs-benign timelines (norms / defense weights / cosine), the
    per-round suspicion ranking, and every defense decision (quarantines,
    retries, degraded rounds) as an annotated table."""
    run_folder = Path(run_folder)
    src = run_folder / "forensics.jsonl"
    if not src.exists():
        raise FileNotFoundError(
            f"{src} not found — run with `forensics: true` first")
    rounds = [json.loads(l) for l in src.read_text().splitlines()
              if l.strip()]
    if not rounds:
        raise ValueError(f"{src} is empty")
    rounds.sort(key=lambda r: r["epoch"])
    agg = rounds[-1]["aggregation"]
    all_adv = sorted({n for r in rounds for n in r.get("adversaries", [])})
    n_quar = sum(r["n_quarantined"] for r in rounds)
    n_deg = sum(1 for r in rounds if r.get("degraded"))

    body = [
        "<p class='note'>",
        f"run <b>{run_folder.name}</b> · aggregation <b>{agg}</b> · "
        f"{len(rounds)} rounds (epochs {rounds[0]['epoch']}–"
        f"{rounds[-1]['epoch']}) · adversaries: "
        f"{', '.join(all_adv) if all_adv else 'none recorded'} · "
        f"{n_quar} quarantines · {n_deg} degraded rounds</p>"]

    body.append("<h2>Attacker vs benign timelines</h2>")
    body.append(_timeline(rounds, "delta_norm",
                          "per-client update norm (mean)"))
    if any(r.get("agg_weight") for r in rounds):
        body.append(_timeline(rounds, "agg_weight",
                              "defense aggregation weight (mean)"))
    body.append(_timeline(rounds, "cosine_to_agg",
                          "cosine to the applied update (mean)"))
    if any(r.get("poison_acc") for r in rounds):
        body.append(_timeline(rounds, "poison_acc",
                              "local poison-battery accuracy (mean)"))

    body.append("<h2>Suspicion ranking (top 3 per round)</h2>")
    body.append("<p class='note'>suspicion score: quarantined &gt; low defense "
                "weight (or, for FedAvg, large received norm). Adversaries "
                "are marked *.</p>")
    sus_rows = []
    for r in rounds:
        adv = set(r.get("adversaries", []))
        ranked = sorted(range(len(r["clients"])),
                        key=lambda c: -_suspicion(r, c))[:3]
        cells = [f"{r['clients'][c]}{'*' if r['clients'][c] in adv else ''}"
                 f" ({_suspicion(r, c):.2f})" for c in ranked]
        sus_rows.append([r["epoch"]] + cells + [""] * (3 - len(cells)))
    body.append(table_html(["epoch", "1st", "2nd", "3rd"], sus_rows))

    body.append("<h2>Defense decisions</h2>")
    dec_rows, dec_flags = [], []
    for r in rounds:
        for c, name in enumerate(r["clients"]):
            if not r["verdict"][c]:
                rn = (r.get("recv_norm") or [None])[c]
                dec_rows.append([r["epoch"], name, r["reason"][c],
                                 "quarantined",
                                 "" if rn is None else format(rn, ".4g")])
                dec_flags.append(True)
        if r.get("n_retries"):
            dec_rows.append([r["epoch"], "—", "non-finite aggregate",
                             f"{r['n_retries']} retr"
                             f"{'y' if r['n_retries'] == 1 else 'ies'}", ""])
            dec_flags.append(False)
        if r.get("degraded"):
            dec_rows.append([r["epoch"], "—", "too few survivors",
                             "degraded (model carried)", ""])
            dec_flags.append(True)
    if dec_rows:
        body.append(table_html(
            ["epoch", "client", "reason", "decision", "recv ‖Δ‖"],
            dec_rows, dec_flags))
    else:
        body.append("<p class='note'>no quarantines, retries, or degraded "
                    "rounds — every client entered every aggregate.</p>")

    return html_doc(f"Defense forensics — {run_folder.name}",
                    "".join(body))


def write_report(run_folder: Path, out: Optional[Path] = None) -> Path:
    out = Path(out) if out else Path(run_folder) / "forensics_report.html"
    out.write_text(render_report(run_folder))
    return out
