"""Shared persistent XLA compile-cache setup.

ResNet-sized round programs take minutes to compile (longer through the TPU
remote-compile path); every entry point that compiles them — bench, tests,
the multichip dryrun, probes — enables the same persistent cache so a shape
compiles once per machine. One helper so the knobs can't silently diverge
across call sites."""
from __future__ import annotations


def enable_compile_cache(path: str = "/tmp/jax_cache_dba_tests") -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
