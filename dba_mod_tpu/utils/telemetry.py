"""Process-wide telemetry: span tracing, a metrics registry, and XLA
compile/memory instrumentation for the round path.

Until now the only per-round observability was the recorder's CSV/JSONL
parity set plus a single wall-clock `round_time` — "where did this round's
time go, did XLA recompile, and what did the device hold" needed an external
profiler. This module makes those first-class:

- **Spans** — nestable ``with telemetry.span("round/dispatch"):`` blocks
  timed with ``time.perf_counter()``. Because JAX dispatch is asynchronous, a
  span that measures device work must end at an explicit sync point:
  ``telemetry.sync(payload)`` (``jax.block_until_ready``) inside the block,
  or :func:`instrument`, which wraps a compiled callable so every call runs
  under a synced span. Spans export as Chrome-trace-format ``trace.json``
  (open in Perfetto / ``chrome://tracing``) and feed per-round duration
  histograms.
- **Metrics registry** — counters (cumulative), gauges (last value) and
  histograms (windowed between flushes). :meth:`Telemetry.flush_round`
  writes one JSON line per round to ``telemetry.jsonl`` and mirrors scalars
  to the recorder's TensorBoard writer under ``telemetry/...`` tags.
- **XLA instrumentation** — a ``jax.monitoring`` listener counts every
  backend compile (jit cache miss that reaches XLA); after
  :meth:`Telemetry.mark_warm` any further compile increments
  ``xla/recompiles_after_warmup`` and logs loudly, so silent retrace
  regressions fail in tests instead of burning device-minutes in
  production. Per-round device memory gauges come from
  ``jax.local_devices()[0].memory_stats()`` where the backend provides it
  (TPU does; CPU returns None and the gauges are simply absent).

The module keeps ONE process-wide current instance (:func:`current`),
defaulting to a no-op null object: call sites in the round path pay a single
attribute check when telemetry is off, and the knobs (``telemetry``,
``telemetry_dir`` in config.py) add no files and no per-round work. These
files are additive observability, not part of the reference-parity CSV set
(PARITY.md).
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger("dba_mod_tpu")

# Round-pipelining metric family (fl/experiment.py, fl/async_rounds.py —
# README "Round pipelining"). Emitted only when overlap_eval is ON and
# telemetry is ON, which forces the round loop SEQUENTIAL: per-phase span
# attribution (dispatch vs eval vs finalize) is only honest when phases do
# not overlap, so the engines trade the pipelining away rather than record
# misattributed spans. The counters below therefore measure the split
# program running serially — the hidden-time clocks come from the
# experiment's host-side accumulators (bench.py reports them per lane).
#   overlap/rounds              counter — rounds run through the split path
#   overlap/hidden_eval_s       gauge   — cumulative eval+sync seconds that
#                                         ran behind the next dispatch
#   overlap/dispatch_ahead_depth gauge  — in-flight rounds ahead (depth 1)
#   overlap/eval_wait_s         histogram — per-round blocking fetch tail
OVERLAP_METRIC_PREFIX = "overlap/"

# jax.monitoring event fired on every backend compile — i.e. every jit cache
# miss that actually reaches XLA (tracing-only cache hits don't fire it).
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# persistent-compile-cache misses (only fired when the disk cache is enabled)
PERSISTENT_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_LOCK = threading.Lock()


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted list (q in [0, 1])."""
    if not sorted_vals:
        return 0.0
    i = min(round(q * (len(sorted_vals) - 1)), len(sorted_vals) - 1)
    return sorted_vals[i]


class Counter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with _LOCK:
            self.value += int(n)


class Gauge:
    __slots__ = ("value",)

    def __init__(self):
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Windowed histogram: observations accumulate until the next per-round
    flush snapshots-and-resets the window; exact all-run count/sum ride
    along (the end-of-run p50/p95 span summary draws on the per-span
    durations Telemetry keeps, not on histogram windows)."""

    __slots__ = ("window", "total_count", "total_sum")

    def __init__(self):
        self.window: List[float] = []
        self.total_count = 0
        self.total_sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with _LOCK:
            self.window.append(v)
            self.total_count += 1
            self.total_sum += v

    def snapshot_and_reset(self) -> Dict[str, float]:
        with _LOCK:
            vals, self.window = self.window, []
        vals.sort()
        return {"count": len(vals), "sum": sum(vals),
                "min": vals[0] if vals else 0.0,
                "max": vals[-1] if vals else 0.0,
                "p50": _percentile(vals, 0.50),
                "p95": _percentile(vals, 0.95)}


class _NullMetric:
    """Shared no-op counter/gauge/histogram for the disabled path."""
    value = 0

    def inc(self, n: int = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_METRIC = _NullMetric()
_NULL_CM = contextlib.nullcontext()  # reusable; nullcontext holds no state


class _NullTelemetry:
    """The disabled telemetry object: every operation is a no-op, `enabled`
    is the one attribute hot paths check. Shared singleton."""
    enabled = False
    current_epoch: Optional[int] = None

    def span(self, name: str):
        return _NULL_CM

    def sync(self, x: Any) -> Any:
        return x

    def counter(self, name: str) -> _NullMetric:
        return _NULL_METRIC

    def gauge(self, name: str) -> _NullMetric:
        return _NULL_METRIC

    def histogram(self, name: str) -> _NullMetric:
        return _NULL_METRIC

    def phase(self) -> str:
        return "-"

    def span_stack(self) -> List[str]:
        return []

    def set_epoch(self, epoch: Optional[int]) -> None:
        pass

    def mark_warm(self) -> None:
        pass

    def record_memory(self) -> None:
        pass

    def flush_round(self, epoch: int) -> None:
        pass

    def write_trace(self) -> None:
        pass

    def summary_table(self) -> str:
        return "telemetry disabled"

    def close(self) -> None:
        pass


NULL = _NullTelemetry()


class Telemetry:
    """One run's telemetry state. Construct via :func:`configure` so call
    sites throughout the round path resolve it through :func:`current`."""

    enabled = True
    TRACE_WRITE_EVERY = 20  # flushes between periodic trace.json rewrites

    def __init__(self, folder: Optional[Path] = None,
                 tb_sink: Optional[Callable[[str, float, int], None]] = None,
                 max_trace_events: int = 200_000):
        self.folder = Path(folder) if folder is not None else None
        self.tb_sink = tb_sink
        self.max_trace_events = int(max_trace_events)
        self._origin = time.perf_counter()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._trace_events: List[dict] = []
        self._span_all: Dict[str, List[float]] = {}
        self._local = threading.local()
        self._flush_count = 0
        self._warm = False
        self.current_epoch: Optional[int] = None
        self.peak_memory_bytes = 0
        if self.folder is not None:
            self.folder.mkdir(parents=True, exist_ok=True)
            # truncate a stale jsonl from a previous run in the same folder
            (self.folder / "telemetry.jsonl").write_text("")

    # ------------------------------------------------------------- registry
    def counter(self, name: str) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with _LOCK:
                c = self._counters.setdefault(name, Counter())
        return c

    def gauge(self, name: str) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with _LOCK:
                g = self._gauges.setdefault(name, Gauge())
        return g

    def histogram(self, name: str) -> Histogram:
        h = self._histograms.get(name)
        if h is None:
            with _LOCK:
                h = self._histograms.setdefault(name, Histogram())
        return h

    # ---------------------------------------------------------------- spans
    def _stack(self) -> List[str]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    @contextlib.contextmanager
    def span(self, name: str):
        """Nestable timed block. End device-measuring spans at a sync point:
        call :meth:`sync` on the measured payload inside the block."""
        stack = self._stack()
        stack.append(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            self._record_span(name, t0, dur)

    def sync(self, x: Any) -> Any:
        """``jax.block_until_ready`` on `x` — the explicit device-sync point
        that makes a span honest under JAX's async dispatch."""
        import jax
        return jax.block_until_ready(x)

    def _record_span(self, name: str, t0: float, dur: float) -> None:
        event = {"name": name, "ph": "X", "cat": "span",
                 "ts": (t0 - self._origin) * 1e6, "dur": dur * 1e6,
                 "pid": os.getpid(), "tid": threading.get_ident()}
        with _LOCK:
            if len(self._trace_events) < self.max_trace_events:
                self._trace_events.append(event)
                dropped = False
            else:
                dropped = True
            self._span_all.setdefault(name, []).append(dur)
        if dropped:
            self.counter("trace/dropped_events").inc()
        self.histogram(f"span/{name}").observe(dur)

    def phase(self) -> str:
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else "-"

    def span_stack(self) -> List[str]:
        """Copy of the calling thread's open-span stack (thread-local —
        callers that need another thread's stack must capture it *in* that
        thread, e.g. the watchdog captures at zone entry)."""
        stack = getattr(self._local, "stack", None)
        return list(stack) if stack else []

    def set_epoch(self, epoch: Optional[int]) -> None:
        self.current_epoch = epoch

    # ------------------------------------------------------ instrumentation
    def mark_warm(self) -> None:
        """Declare warmup over: every program a steady-state round needs has
        compiled. Any backend compile after this is a retrace regression —
        counted in ``xla/recompiles_after_warmup`` and logged loudly.
        Idempotent — only the first call flips the flag."""
        if self._warm:
            return
        self._warm = True
        # materialize the counter so post-warmup flushes report an explicit
        # 0 rather than an absent key
        self.counter("xla/recompiles_after_warmup")
        logger.info("telemetry: warmup complete after %d XLA compiles; "
                    "further compiles are counted as recompiles",
                    self.counter("xla/compiles").value)

    def record_memory(self) -> None:
        """Device memory gauges from the backend, when it reports them
        (TPU/GPU do; the CPU backend returns None and this is a no-op)."""
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — absent backend support must
            stats = None   # never break a round
        if not stats:
            return
        for key in ("bytes_in_use", "peak_bytes_in_use",
                    "largest_alloc_size", "bytes_limit"):
            if key in stats:
                self.gauge(f"memory/{key}").set(stats[key])
        peak = stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
        self.peak_memory_bytes = max(self.peak_memory_bytes, int(peak))

    # ----------------------------------------------------------- round flush
    def flush_round(self, epoch: int) -> None:
        """One JSON line per round: cumulative counters, last-value gauges,
        and the histogram window since the previous flush (span durations,
        delta norms). Mirrored to TensorBoard when a sink is wired."""
        self.record_memory()
        with _LOCK:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {k: g.value for k, g in self._gauges.items()
                      if g.value is not None}
            hist_items = list(self._histograms.items())
        hists = {}
        for k, h in hist_items:
            snap = h.snapshot_and_reset()
            if snap["count"]:
                hists[k] = {m: round(v, 6) for m, v in snap.items()}
        row = {"epoch": int(epoch), "time": time.time(),
               "counters": counters, "gauges": gauges, "histograms": hists}
        if self.folder is not None:
            with open(self.folder / "telemetry.jsonl", "a") as f:
                f.write(json.dumps(row) + "\n")
            # trace.json is a full rewrite (the Chrome trace format is one
            # JSON document), so a per-round rewrite would make trace I/O
            # quadratic over a long run — persist on the first flush and
            # every Kth after; close() always writes the complete trace
            self._flush_count += 1
            if self._flush_count % self.TRACE_WRITE_EVERY == 1:
                self.write_trace()
        if self.tb_sink is not None:
            step = int(epoch)
            for k, v in counters.items():
                self.tb_sink(f"telemetry/{k}", float(v), step)
            for k, v in gauges.items():
                self.tb_sink(f"telemetry/{k}", float(v), step)
            for k, snap in hists.items():
                self.tb_sink(f"telemetry/{k}/p50", snap["p50"], step)
                self.tb_sink(f"telemetry/{k}/p95", snap["p95"], step)

    # ----------------------------------------------------------- trace file
    def write_trace(self) -> None:
        """Atomic rewrite of ``trace.json`` (Chrome trace format). Called
        periodically from :meth:`flush_round` and always from :meth:`close`,
        so a crashed run still leaves a loadable (if slightly stale)
        trace."""
        if self.folder is None:
            return
        with _LOCK:
            events = list(self._trace_events)
        meta = [{"name": "process_name", "ph": "M", "pid": os.getpid(),
                 "args": {"name": "dba_mod_tpu"}}]
        doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
        path = self.folder / "trace.json"
        tmp = path.with_name(path.name + ".tmp")
        tmp.write_text(json.dumps(doc))
        os.replace(tmp, path)

    # -------------------------------------------------------------- summary
    def summary_table(self) -> str:
        """End-of-run phase summary: p50/p95 per span, recompile count, peak
        device memory."""
        with _LOCK:
            spans = {k: sorted(v) for k, v in self._span_all.items()}
        lines = [f"{'span':<32} {'count':>6} {'total_s':>9} "
                 f"{'p50_ms':>9} {'p95_ms':>9}"]
        for name in sorted(spans):
            vals = spans[name]
            lines.append(
                f"{name:<32} {len(vals):>6} {sum(vals):>9.3f} "
                f"{_percentile(vals, 0.50) * 1e3:>9.2f} "
                f"{_percentile(vals, 0.95) * 1e3:>9.2f}")
        compiles = self.counter("xla/compiles").value
        recompiles = self.counter("xla/recompiles_after_warmup").value
        mem = (f"{self.peak_memory_bytes / 2**20:.1f} MiB"
               if self.peak_memory_bytes else "n/a")
        lines.append(f"xla compiles: {compiles} "
                     f"(after warmup: {recompiles}) | "
                     f"peak device memory: {mem}")
        return "\n".join(lines)

    def close(self) -> None:
        """Final trace/summary flush; safe to call more than once."""
        if self.folder is not None:
            self.write_trace()


# --------------------------------------------------------- process-wide state
_current: Any = NULL
_listeners_installed = False


def current() -> Any:
    """The process-wide telemetry instance (the null object when off)."""
    return _current


def configure(enabled: bool, folder: Optional[Path] = None,
              tb_sink: Optional[Callable[[str, float, int], None]] = None,
              ) -> Any:
    """Install (or clear) the process-wide telemetry instance. With
    `enabled` False the null object is installed and no files are touched.
    One instance per process: a second Experiment in the same process takes
    over the module-level current, so spans from SHARED code paths
    (checkpoint.py, rounds.py eval wrappers) follow the most recent
    experiment — an Experiment's own round spans go through its
    `self.telemetry` handle and are unaffected by the takeover."""
    global _current
    if not enabled:
        _current = NULL
        return NULL
    _current = Telemetry(folder=folder, tb_sink=tb_sink)
    install_xla_listeners()
    return _current


def span(name: str):
    return _current.span(name)


def sync(x: Any) -> Any:
    if _current.enabled:
        _current.sync(x)
    return x


def count(name: str, n: int = 1) -> None:
    if _current.enabled:
        _current.counter(name).inc(n)


def observe(name: str, v: float) -> None:
    if _current.enabled:
        _current.histogram(name).observe(v)


def set_gauge(name: str, v: float) -> None:
    if _current.enabled:
        _current.gauge(name).set(v)


def set_epoch(epoch: Optional[int]) -> None:
    _current.set_epoch(epoch)


def instrument(fn: Callable, name: str, batches: int = 0) -> Callable:
    """Wrap a compiled callable so every call runs under a synced span
    (`jax.block_until_ready` on the result — honest device time under async
    dispatch). Zero-overhead passthrough while telemetry is off; `batches`
    increments the ``eval/batches`` counter per call when set."""
    def wrapped(*args, **kwargs):
        t = _current
        if not t.enabled:
            return fn(*args, **kwargs)
        with t.span(name):
            out = fn(*args, **kwargs)
            t.sync(out)
        if batches:
            t.counter("eval/batches").inc(batches)
        return out
    wrapped.__wrapped__ = fn
    wrapped.__name__ = getattr(fn, "__name__", name)
    return wrapped


# ------------------------------------------------------------- XLA listeners
def _on_event_duration(event: str, duration: float, **kwargs) -> None:
    t = _current
    if not t.enabled or event != BACKEND_COMPILE_EVENT:
        return
    t.counter("xla/compiles").inc()
    t.histogram("xla/compile_secs").observe(duration)
    if t._warm:
        t.counter("xla/recompiles_after_warmup").inc()
        logger.warning(
            "telemetry: XLA backend compile AFTER warmup (%.2fs) — a shape "
            "or constant is retracing in the steady state", duration)


def _on_event(event: str, **kwargs) -> None:
    if _current.enabled and event == PERSISTENT_CACHE_MISS_EVENT:
        _current.counter("xla/persistent_cache_misses").inc()


def install_xla_listeners() -> None:
    """Register the jax.monitoring listeners once per process. The listeners
    forward to whatever instance is current, so they are safe to leave
    installed when telemetry is later disabled."""
    global _listeners_installed
    if _listeners_installed:
        return
    import jax
    jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
    jax.monitoring.register_event_listener(_on_event)
    _listeners_installed = True


# -------------------------------------------------------------- logging setup
class _PhaseFilter(logging.Filter):
    """Injects epoch/phase context (the current telemetry span) into every
    record so the formatter can show where in the round a line came from."""

    def filter(self, record: logging.LogRecord) -> bool:
        t = _current
        ep = t.current_epoch
        record.phase = (f"e{ep}/{t.phase()}" if ep is not None
                        else t.phase())
        return True


_LOG_FORMAT = "%(asctime)s %(levelname).1s [%(phase)s] %(message)s"


def setup_logging(folder: Optional[Path] = None,
                  level: int = logging.INFO) -> logging.Logger:
    """Idempotent configuration of the ``dba_mod_tpu`` logger.

    Replaces the previous per-Experiment ``logging.basicConfig`` + stacked
    ``FileHandler`` (two experiments in one process — e.g. a parity A/B —
    each added a handler and every line went to both files, duplicated).
    The stream handler and formatter are configured exactly once; the
    run-folder file handler is REPLACED when a new folder is configured, so
    log lines follow the active experiment. With no `folder` the logger is
    returned untouched — folder-less runs (bench.py, ``--no-save``) stay as
    quiet as they were before this helper existed."""
    lg = logging.getLogger("dba_mod_tpu")
    if folder is None:
        return lg
    fmt = logging.Formatter(_LOG_FORMAT)
    if not getattr(lg, "_dba_configured", False):
        lg.setLevel(level)
        sh = logging.StreamHandler()
        sh.setFormatter(fmt)
        sh.addFilter(_PhaseFilter())
        lg.addHandler(sh)
        lg.propagate = False
        lg._dba_configured = True  # type: ignore[attr-defined]
    path = os.path.abspath(str(Path(folder) / "log.txt"))
    existing = [h for h in lg.handlers
                if getattr(h, "_dba_run_file", False)]
    if any(getattr(h, "baseFilename", None) == path for h in existing):
        return lg
    for h in existing:
        lg.removeHandler(h)
        h.close()
    fh = logging.FileHandler(path)
    fh.setFormatter(fmt)
    fh.addFilter(_PhaseFilter())
    fh._dba_run_file = True  # type: ignore[attr-defined]
    lg.addHandler(fh)
    return lg
