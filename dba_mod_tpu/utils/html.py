"""Params → HTML table (reference utils/utils.py:8-19 `dict_html`).

The reference posts this into the visdom dashboard header (main.py:122);
here it is written into the run folder as `params.html` so a run's exact
configuration is one click away without a plot server.
"""
from __future__ import annotations

import html
from typing import Any, Dict


def dict_html(d: Dict[str, Any], current_time: str = "") -> str:
    rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in sorted(d.items(), key=lambda kv: str(kv[0])))
    return (f"<h4>Run {html.escape(str(current_time))}</h4>"
            f"<table border=1 cellpadding=2>"
            f"<tr><th>param</th><th>value</th></tr>{rows}</table>")
