"""Standalone-HTML building blocks for run artifacts.

`dict_html` is the reference's params table (utils/utils.py:8-19) — the
reference posts it into the visdom dashboard header (main.py:122); here it
is written into the run folder as `params.html` so a run's exact
configuration is one click away without a plot server. The rest
(`html_doc`, `table_html`, `svg_timeline`) are the shared pieces of the
forensics round-audit report (utils/forensics.py): pure string builders,
no external assets, so every emitted document is self-contained.
"""
from __future__ import annotations

import html
import math
from typing import Any, Dict, List, Sequence


def dict_html(d: Dict[str, Any], current_time: str = "") -> str:
    rows = "".join(
        f"<tr><td>{html.escape(str(k))}</td>"
        f"<td>{html.escape(str(v))}</td></tr>"
        for k, v in sorted(d.items(), key=lambda kv: str(kv[0])))
    return (f"<h4>Run {html.escape(str(current_time))}</h4>"
            f"<table border=1 cellpadding=2>"
            f"<tr><th>param</th><th>value</th></tr>{rows}</table>")


_DOC_CSS = """
body { font-family: system-ui, sans-serif; margin: 24px; color: #1a1a1a; }
h1 { font-size: 1.4em; } h2 { font-size: 1.1em; margin-top: 1.6em; }
table { border-collapse: collapse; margin: 8px 0; font-size: 0.85em; }
th, td { border: 1px solid #bbb; padding: 3px 8px; text-align: left; }
th { background: #f0f0f0; }
tr.flagged td { background: #fde8e8; }
figure { margin: 8px 0; }
figcaption { font-size: 0.8em; color: #555; }
.note { font-size: 0.85em; color: #555; }
"""


def html_doc(title: str, body: str) -> str:
    """Wrap a body fragment into a complete self-contained document."""
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title>"
            f"<style>{_DOC_CSS}</style></head>"
            f"<body><h1>{html.escape(title)}</h1>{body}</body></html>")


def table_html(header: Sequence[str], rows: Sequence[Sequence[Any]],
               flagged: Sequence[bool] = ()) -> str:
    """Rows are escaped; `flagged[i]` highlights row i (quarantine rows)."""
    head = "".join(f"<th>{html.escape(str(h))}</th>" for h in header)
    body = []
    for i, row in enumerate(rows):
        cls = " class='flagged'" if (i < len(flagged) and flagged[i]) else ""
        cells = "".join(f"<td>{html.escape(str(c))}</td>" for c in row)
        body.append(f"<tr{cls}>{cells}</tr>")
    return f"<table><tr>{head}</tr>{''.join(body)}</table>"


def svg_timeline(series: List[Dict[str, Any]], title: str = "",
                 width: int = 720, height: int = 200) -> str:
    """Inline-SVG line chart. `series` is a list of
    {"label": str, "color": str, "points": [(x, y), ...], "dash": bool?};
    non-finite points are dropped per-series (a NaN-corrupted round must
    not blank the whole timeline). Returns an empty string when no series
    has any finite point."""
    clean = []
    for s in series:
        pts = [(float(x), float(y)) for x, y in s.get("points", ())
               if math.isfinite(float(x)) and math.isfinite(float(y))]
        if pts:
            clean.append({**s, "points": sorted(pts)})
    if not clean:
        return ""
    xs = [p[0] for s in clean for p in s["points"]]
    ys = [p[1] for s in clean for p in s["points"]]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    if x1 - x0 < 1e-12:
        x1 = x0 + 1.0
    if y1 - y0 < 1e-12:
        y1 = y0 + (abs(y0) or 1.0) * 0.1
    ml, mr, mt, mb = 58, 12, 26, 30   # margins: left/right/top/bottom
    pw, ph = width - ml - mr, height - mt - mb

    def sx(x):
        return ml + (x - x0) / (x1 - x0) * pw

    def sy(y):
        return mt + ph - (y - y0) / (y1 - y0) * ph

    parts = [f"<svg width='{width}' height='{height}' "
             f"viewBox='0 0 {width} {height}' "
             "xmlns='http://www.w3.org/2000/svg'>",
             f"<rect x='{ml}' y='{mt}' width='{pw}' height='{ph}' "
             "fill='#fafafa' stroke='#ccc'/>"]
    if title:
        parts.append(f"<text x='{ml}' y='16' font-size='12' "
                     f"font-weight='bold'>{html.escape(title)}</text>")
    for frac in (0.0, 0.5, 1.0):  # y gridline + label at min/mid/max
        yv = y0 + frac * (y1 - y0)
        py = sy(yv)
        parts.append(f"<line x1='{ml}' y1='{py:.1f}' x2='{ml + pw}' "
                     f"y2='{py:.1f}' stroke='#ddd'/>")
        parts.append(f"<text x='{ml - 4}' y='{py + 4:.1f}' font-size='10' "
                     f"text-anchor='end'>{yv:.4g}</text>")
    for xv in (x0, x1):           # x labels at the range ends (epochs)
        parts.append(f"<text x='{sx(xv):.1f}' y='{mt + ph + 14}' "
                     f"font-size='10' text-anchor='middle'>"
                     f"{xv:.4g}</text>")
    lx = ml + 6
    for i, s in enumerate(clean):
        color = s.get("color", "#1f77b4")
        dash = " stroke-dasharray='5,3'" if s.get("dash") else ""
        path = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in s["points"])
        parts.append(f"<polyline points='{path}' fill='none' "
                     f"stroke='{color}' stroke-width='1.5'{dash}/>")
        for x, y in s["points"]:
            parts.append(f"<circle cx='{sx(x):.1f}' cy='{sy(y):.1f}' "
                         f"r='2' fill='{color}'/>")
        ly = mt + 12 + 13 * i
        parts.append(f"<line x1='{lx}' y1='{ly - 3}' x2='{lx + 16}' "
                     f"y2='{ly - 3}' stroke='{color}' "
                     f"stroke-width='2'{dash}/>")
        parts.append(f"<text x='{lx + 20}' y='{ly}' font-size='10'>"
                     f"{html.escape(str(s.get('label', '')))}</text>")
    parts.append("</svg>")
    return "".join(parts)
