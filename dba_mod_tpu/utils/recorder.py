"""Result recording with column-schema parity to the reference's CSVs
(utils/csv_record.py) so curves can be diffed directly, plus a JSONL metrics
stream and (opt-in) TensorBoard scalar series covering every live visdom chart
family the reference ships (models/simple.py:18-200; call sites main.py:39-83,
image_train.py:108-297, test.py:47,112) — SURVEY §5 replaces visdom with
TensorBoard, so each chart family maps to a named TB tag (see PARITY.md):

  visdom window              TB tag family
  train_acc / train_loss   → train/acc/{client}, train/loss/{client}
  train_batch_loss         → train_batch/loss/{client}
  global_dist              → distance_to_global/{client}
  Aggregation_Weight       → aggregation/weight/{client}
  FG_Alpha                 → aggregation/alpha/{client}
  test_acc / test_loss     → test/acc/{model}, test/loss/{model}
  poison_test_acc/loss     → poison_test/acc/{model}, poison_test/loss/{model}
  poison_triggerweight_vis_acc / poison_state_trigger_acc
                           → trigger_test/acc/{model}.{trigger}, .../loss/...

Like the reference, `save()` rewrites every CSV each round
(csv_record.py:21-59); unlike it, every rewrite is atomic (tempfile in the
run folder + os.replace, so a crash mid-save can no longer truncate
metrics.jsonl / round_result.csv) and state lives on an instance, not module
globals.
The per-batch channels (train_batch/distance) additionally land in CSVs of
their own — the reference only plotted them.
"""
from __future__ import annotations

import csv
import json
import os
import time
from pathlib import Path
from typing import Any, List, Optional

TRAIN_HEADER = ["local_model", "round", "epoch", "internal_epoch",
                "average_loss", "accuracy", "correct_data", "total_data"]
TEST_HEADER = ["model", "epoch", "average_loss", "accuracy", "correct_data",
               "total_data"]
TRIGGER_HEADER = ["model", "trigger_name", "trigger_value", "epoch",
                  "average_loss", "accuracy", "correct_data", "total_data"]
BATCH_HEADER = ["local_model", "round", "epoch", "internal_epoch", "batch",
                "value"]
# per-round robustness columns (fl/faults.py + the quarantine pass in
# fl/rounds.py) so PARITY/trajectory harnesses can plot attack success
# under faults; all-zero when the fault layer is off. dispatch_time /
# finalize_time split round_time into host-planning+enqueue vs the round's
# blocking fetch (perf_counter durations; under pipeline_rounds round_time
# spans the overlap with the next round's dispatch — the split columns are
# the honest per-phase numbers)
ROUND_HEADER = ["epoch", "global_acc", "global_loss", "backdoor_acc",
                "n_quarantined", "n_dropped", "n_retries", "degraded",
                "round_time", "dispatch_time", "finalize_time"]

# wall-clock columns/keys: the ONLY recorded values allowed to differ
# between a serial run and the same run under overlap_eval /
# pipeline_rounds. Everything else is covered by the bit-identity
# contract (README "Round pipelining"; tests/test_overlap.py)
VOLATILE_KEYS = frozenset(
    {"time", "round_time", "dispatch_time", "finalize_time"})


def canonical_run_outputs(folder) -> dict:
    """Wall-clock-free view of a run folder's recorded outputs, for
    byte-level A/B comparison of two runs (the overlap_eval bit-identity
    contract). metrics.jsonl rows and round_result.csv drop the
    VOLATILE_KEYS columns; every other CSV is compared as raw bytes."""
    folder = Path(folder)
    out: dict = {}
    mj = folder / "metrics.jsonl"
    if mj.exists():
        out["metrics.jsonl"] = [
            {k: v for k, v in json.loads(line).items()
             if k not in VOLATILE_KEYS}
            for line in mj.read_text().splitlines() if line.strip()]
    rr = folder / "round_result.csv"
    if rr.exists():
        with open(rr, newline="") as f:
            rows = list(csv.reader(f))
        keep = [i for i, c in enumerate(rows[0])
                if c not in VOLATILE_KEYS] if rows else []
        out["round_result.csv"] = [[r[i] for i in keep] for r in rows]
    for name in ("train_result.csv", "test_result.csv",
                 "posiontest_result.csv", "poisontriggertest_result.csv",
                 "weight_result.csv", "scale_result.csv",
                 "train_batch_result.csv", "distance_result.csv"):
        p = folder / name
        if p.exists():
            out[name] = p.read_bytes()
    return out


def _tag(name: Any) -> str:
    return str(name).replace("/", "_")


class Recorder:
    def __init__(self, folder: Optional[Path] = None,
                 tensorboard: bool = False):
        """`tensorboard` is opt-in (config key of the same name): the writer
        drags the TensorFlow import into the process."""
        self.folder = Path(folder) if folder else None
        self._tb = None
        if self.folder is not None and tensorboard:
            from flax.metrics.tensorboard import SummaryWriter
            self._tb = SummaryWriter(str(self.folder / "tb"))
        self.train_result: List[list] = []
        self.test_result: List[list] = []
        self.posiontest_result: List[list] = []   # (sic) reference file name
        self.poisontriggertest_result: List[list] = []
        self.weight_result: List[list] = []
        self.scale_result: List[list] = []
        self.scale_temp_one_row: List[Any] = []
        self.batch_loss_result: List[list] = []
        self.batch_distance_result: List[list] = []
        self.round_result: List[list] = []
        self._jsonl_rows: List[dict] = []

    def _scalar(self, tag: str, value: float, step: int):
        if self._tb is not None:
            self._tb.scalar(tag, float(value), int(step))

    # ------------------------------------------------------------------ adds
    def add_train(self, name, temp_local_epoch, epoch, internal_epoch, loss,
                  acc, correct, total):
        self.train_result.append([name, temp_local_epoch, epoch,
                                  internal_epoch, loss, acc, correct, total])
        # train_vis (models/simple.py:18-31): x = temp_local_epoch
        self._scalar(f"train/acc/{_tag(name)}", acc, temp_local_epoch)
        self._scalar(f"train/loss/{_tag(name)}", loss, temp_local_epoch)

    def add_test(self, name, epoch, loss, acc, correct, total):
        self.test_result.append([name, epoch, loss, acc, correct, total])
        # test_vis (models/simple.py:178-200, test.py:47)
        self._scalar(f"test/acc/{_tag(name)}", acc, epoch)
        self._scalar(f"test/loss/{_tag(name)}", loss, epoch)

    def add_poisontest(self, name, epoch, loss, acc, correct, total):
        self.posiontest_result.append([name, epoch, loss, acc, correct,
                                       total])
        # poison_test_vis (models/simple.py:131-153, test.py:112)
        self._scalar(f"poison_test/acc/{_tag(name)}", acc, epoch)
        self._scalar(f"poison_test/loss/{_tag(name)}", loss, epoch)

    def add_triggertest(self, model, trigger_name, trigger_value, epoch, loss,
                        acc, correct, total):
        self.poisontriggertest_result.append(
            [model, trigger_name, trigger_value, epoch, loss, acc, correct,
             total])
        # trigger_test_vis / trigger_agent_test_vis (models/simple.py:88-129,
        # main.py:39-58, image_train.py:287-297)
        tag = f"{_tag(model)}.{_tag(trigger_name)}"
        self._scalar(f"trigger_test/acc/{tag}", acc, epoch)
        self._scalar(f"trigger_test/loss/{tag}", loss, epoch)

    def add_weight_result(self, names, weights, alphas, epoch=None):
        # reference appends three rows per round (csv_record.py:61-64)
        self.weight_result.append(list(names))
        self.weight_result.append(list(weights))
        self.weight_result.append(list(alphas))
        # weight_vis / alpha_vis (models/simple.py:62-87, main.py:60-83)
        if epoch is not None:
            for n, w, a in zip(names, weights, alphas):
                self._scalar(f"aggregation/weight/{_tag(n)}", w, epoch)
                self._scalar(f"aggregation/alpha/{_tag(n)}", a, epoch)

    def add_batch_loss(self, name, temp_local_epoch, epoch, internal_epoch,
                       batch, steps_per_epoch, loss):
        """Per-batch train loss (vis_train_batch_loss,
        image_train.py:225-235; train_batch_vis models/simple.py:32-42)."""
        self.batch_loss_result.append(
            [name, temp_local_epoch, epoch, internal_epoch, batch, loss])
        step = (temp_local_epoch - 1) * steps_per_epoch + batch
        self._scalar(f"train_batch/loss/{_tag(name)}", loss, step)

    def add_batch_distance(self, name, temp_local_epoch, epoch,
                           internal_epoch, batch, steps_per_epoch, dist):
        """Per-batch post-step distance to the round anchor
        (batch_track_distance, image_train.py:236-245;
        track_distance_batch_vis models/simple.py:43-61)."""
        self.batch_distance_result.append(
            [name, temp_local_epoch, epoch, internal_epoch, batch, dist])
        step = (temp_local_epoch - 1) * steps_per_epoch + batch
        self._scalar(f"distance_to_global/{_tag(name)}", dist, step)

    def add_round_json(self, **kwargs):
        kwargs.setdefault("time", time.time())
        self._jsonl_rows.append(kwargs)
        if "epoch" in kwargs:
            self.round_result.append(
                [kwargs["epoch"], kwargs.get("global_acc"),
                 kwargs.get("global_loss"), kwargs.get("backdoor_acc"),
                 int(kwargs.get("n_quarantined", 0) or 0),
                 int(kwargs.get("n_dropped", 0) or 0),
                 int(kwargs.get("n_retries", 0) or 0),
                 int(bool(kwargs.get("degraded", False))),
                 kwargs.get("round_time"),
                 kwargs.get("dispatch_time"),
                 kwargs.get("finalize_time")])
        if self._tb is not None and "epoch" in kwargs:
            step = int(kwargs["epoch"])
            for k, v in kwargs.items():
                if isinstance(v, (int, float)) and k not in ("epoch", "time"):
                    self._tb.scalar(k, float(v), step)
            self._tb.flush()

    # ---------------------------------------------------------- resume/load
    def load_from_folder(self, keep_until_epoch: int) -> int:
        """Auto-resume continuation: reload this run folder's previously
        saved CSV/JSONL streams, truncated to rows at or before
        `keep_until_epoch` (a kill can land after round N recorded but
        before round N's checkpoint verified — the resumed run replays N,
        and duplicate rows would corrupt every downstream curve). Because
        `save()` rewrites every file from these in-memory lists each
        round, reloading + truncating here is exactly "continue the stream
        past the resume epoch". CSV cells reload as the strings the writer
        emitted, so the kept prefix round-trips byte-identically. Returns
        the number of metrics.jsonl rows kept."""
        if self.folder is None:
            return 0
        cut = int(keep_until_epoch)

        def rows_of(name):
            path = self.folder / name
            if not path.exists():
                return None
            with open(path, newline="") as f:
                return list(csv.reader(f))

        def load_csv(name, target, epoch_col, has_header=True):
            rows = rows_of(name)
            if rows is None:
                return
            body = rows[1:] if has_header and rows else rows
            for row in body:
                try:
                    if int(float(row[epoch_col])) > cut:
                        continue
                except (IndexError, ValueError):
                    continue  # malformed row: drop rather than crash resume
                target.append(row)

        load_csv("train_result.csv", self.train_result, 2)
        load_csv("test_result.csv", self.test_result, 1)
        load_csv("posiontest_result.csv", self.posiontest_result, 1)
        load_csv("poisontriggertest_result.csv",
                 self.poisontriggertest_result, 3)
        load_csv("train_batch_result.csv", self.batch_loss_result, 2)
        load_csv("distance_result.csv", self.batch_distance_result, 2)
        load_csv("round_result.csv", self.round_result, 0)
        # scale rows start with (epoch, norm) pairs — filter on the first
        # cell; weight rows are epochless [names, wv, alpha] triplets, one
        # per recorded round, so keep one triplet per kept round row
        load_csv("scale_result.csv", self.scale_result, 0, has_header=False)
        wrows = rows_of("weight_result.csv")
        if wrows is not None:
            n_triplets = min(len(wrows) // 3, len(self.round_result))
            self.weight_result.extend(wrows[:3 * n_triplets])

        jsonl = self.folder / "metrics.jsonl"
        if jsonl.exists():
            with open(jsonl) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                        keep = int(row.get("epoch", 0)) <= cut
                    except (ValueError, TypeError, AttributeError):
                        continue  # malformed line (truncated write, bit
                                  # rot): drop rather than crash resume,
                                  # like the CSV loader above
                    if keep:
                        self._jsonl_rows.append(row)
        return len(self._jsonl_rows)

    # ------------------------------------------------------------------ save
    def _atomic_write(self, name: str, emit) -> None:
        """Crash-safe full rewrite: `emit(file)` writes into a tempfile in
        the run folder, which is `os.replace`d over the target only on
        success — a crash (or an exception) mid-save leaves the previously
        saved file intact, where the old rewrite-in-place truncated it."""
        path = self.folder / name
        tmp = self.folder / (name + ".tmp")
        try:
            with open(tmp, "w", newline="") as f:
                emit(f)
            os.replace(tmp, path)
        finally:
            if tmp.exists():
                tmp.unlink()

    def save(self, is_poison: bool):
        # the scale row closes at save time whether or not files are written
        # (csv_record.py:44-50 semantics)
        if self.scale_temp_one_row:
            self.scale_result.append(list(self.scale_temp_one_row))
            self.scale_temp_one_row.clear()
        if self.folder is None:
            return
        self.folder.mkdir(parents=True, exist_ok=True)

        def write(name, header, rows):
            def emit(f):
                w = csv.writer(f)
                if header:
                    w.writerow(header)
                w.writerows(rows)
            self._atomic_write(name, emit)

        write("train_result.csv", TRAIN_HEADER, self.train_result)
        write("test_result.csv", TEST_HEADER, self.test_result)
        if self.weight_result:
            write("weight_result.csv", None, self.weight_result)
        if self.scale_result:
            write("scale_result.csv", None, self.scale_result)
        if self.batch_loss_result:
            write("train_batch_result.csv", BATCH_HEADER,
                  self.batch_loss_result)
        if self.batch_distance_result:
            write("distance_result.csv", BATCH_HEADER,
                  self.batch_distance_result)
        if self.round_result:
            write("round_result.csv", ROUND_HEADER, self.round_result)
        if is_poison:
            write("posiontest_result.csv", TEST_HEADER,
                  self.posiontest_result)
            write("poisontriggertest_result.csv", TRIGGER_HEADER,
                  self.poisontriggertest_result)

        def emit_jsonl(f):
            for row in self._jsonl_rows:
                f.write(json.dumps(row) + "\n")
        self._atomic_write("metrics.jsonl", emit_jsonl)
