"""Result recording with column-schema parity to the reference's CSVs
(utils/csv_record.py) so curves can be diffed directly, plus a JSONL metrics
stream for modern tooling.

Like the reference, `save()` rewrites every CSV each round (csv_record.py:21-59
— crash-safe tail); unlike it, state lives on an instance, not module globals.
"""
from __future__ import annotations

import csv
import json
import time
from pathlib import Path
from typing import Any, List, Optional

TRAIN_HEADER = ["local_model", "round", "epoch", "internal_epoch",
                "average_loss", "accuracy", "correct_data", "total_data"]
TEST_HEADER = ["model", "epoch", "average_loss", "accuracy", "correct_data",
               "total_data"]
TRIGGER_HEADER = ["model", "trigger_name", "trigger_value", "epoch",
                  "average_loss", "accuracy", "correct_data", "total_data"]


class Recorder:
    def __init__(self, folder: Optional[Path] = None,
                 tensorboard: bool = False):
        """`tensorboard` is opt-in (config key of the same name): the writer
        drags the TensorFlow import into the process."""
        self.folder = Path(folder) if folder else None
        self._tb = None
        if self.folder is not None and tensorboard:
            from flax.metrics.tensorboard import SummaryWriter
            self._tb = SummaryWriter(str(self.folder / "tb"))
        self.train_result: List[list] = []
        self.test_result: List[list] = []
        self.posiontest_result: List[list] = []   # (sic) reference file name
        self.poisontriggertest_result: List[list] = []
        self.weight_result: List[list] = []
        self.scale_result: List[list] = []
        self.scale_temp_one_row: List[Any] = []
        self._jsonl_rows: List[dict] = []

    # ------------------------------------------------------------------ adds
    def add_train(self, name, temp_local_epoch, epoch, internal_epoch, loss,
                  acc, correct, total):
        self.train_result.append([name, temp_local_epoch, epoch,
                                  internal_epoch, loss, acc, correct, total])

    def add_test(self, name, epoch, loss, acc, correct, total):
        self.test_result.append([name, epoch, loss, acc, correct, total])

    def add_poisontest(self, name, epoch, loss, acc, correct, total):
        self.posiontest_result.append([name, epoch, loss, acc, correct,
                                       total])

    def add_triggertest(self, model, trigger_name, trigger_value, epoch, loss,
                        acc, correct, total):
        self.poisontriggertest_result.append(
            [model, trigger_name, trigger_value, epoch, loss, acc, correct,
             total])

    def add_weight_result(self, names, weights, alphas):
        # reference appends three rows per round (csv_record.py:61-64)
        self.weight_result.append(list(names))
        self.weight_result.append(list(weights))
        self.weight_result.append(list(alphas))

    def add_round_json(self, **kwargs):
        kwargs.setdefault("time", time.time())
        self._jsonl_rows.append(kwargs)
        if self._tb is not None and "epoch" in kwargs:
            step = int(kwargs["epoch"])
            for k, v in kwargs.items():
                if isinstance(v, (int, float)) and k not in ("epoch", "time"):
                    self._tb.scalar(k, float(v), step)
            self._tb.flush()

    # ------------------------------------------------------------------ save
    def save(self, is_poison: bool):
        # the scale row closes at save time whether or not files are written
        # (csv_record.py:44-50 semantics)
        if self.scale_temp_one_row:
            self.scale_result.append(list(self.scale_temp_one_row))
            self.scale_temp_one_row.clear()
        if self.folder is None:
            return
        self.folder.mkdir(parents=True, exist_ok=True)

        def write(name, header, rows):
            with open(self.folder / name, "w", newline="") as f:
                w = csv.writer(f)
                if header:
                    w.writerow(header)
                w.writerows(rows)

        write("train_result.csv", TRAIN_HEADER, self.train_result)
        write("test_result.csv", TEST_HEADER, self.test_result)
        if self.weight_result:
            write("weight_result.csv", None, self.weight_result)
        if self.scale_result:
            write("scale_result.csv", None, self.scale_result)
        if is_poison:
            write("posiontest_result.csv", TEST_HEADER,
                  self.posiontest_result)
            write("poisontriggertest_result.csv", TRIGGER_HEADER,
                  self.poisontriggertest_result)
        with open(self.folder / "metrics.jsonl", "w") as f:
            for row in self._jsonl_rows:
                f.write(json.dumps(row) + "\n")
