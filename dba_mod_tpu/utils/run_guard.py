"""Process-level crash/preemption tolerance: graceful shutdown + watchdog.

PR 1 made individual rounds survive bad *clients*; this module makes the
*process* killable. Preemptible TPUs deliver SIGTERM with a short grace
window, operators deliver SIGINT, and a wedged runtime delivers nothing at
all — three failure shapes, two tools:

- :class:`GracefulShutdown` — SIGTERM/SIGINT set a stop flag that the
  experiment loop checks at round boundaries; the run writes a final
  verified checkpoint, flushes the recorder and telemetry, and the CLI
  exits with :data:`EXIT_INTERRUPTED` so wrappers can distinguish
  "preempted, resume me" from success and from crashes. A second signal
  forces immediate exit (``128 + signum``) for operators who mean it.
- :class:`Watchdog` — a monotonic-deadline timer around the round path's
  host-blocking sync points (``jax.device_get`` at finalize, the robust
  screen sync, the async-checkpoint wait). A stall past ``watchdog_soft_s``
  logs a loud diagnostic (zone label, epoch, elapsed, the telemetry span
  stack captured at zone entry); past ``watchdog_hard_s`` the process is
  aborted with :data:`EXIT_WATCHDOG` — a wedged run dies *checkpointed*
  (the previous round's verified checkpoint is on disk) instead of burning
  quota silently.

Both are strict no-ops when disabled (the config defaults): no signal
handlers installed, no threads started, zero per-round work beyond one
attribute check. :class:`RunGuard` bundles them behind the config knobs
(``graceful_shutdown``, ``watchdog_soft_s``, ``watchdog_hard_s``).
"""
from __future__ import annotations

import contextlib
import logging
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from dba_mod_tpu.utils import telemetry

logger = logging.getLogger("dba_mod_tpu")

# Distinct exit codes so run wrappers (k8s, slurm, the crash/elastic smoke
# harnesses) can tell the exit shapes apart without parsing logs. 75/76/77
# follow the sysexits.h convention of "temporary failure — retrying is the
# fix"; 77 additionally tells the wrapper the retry must SHRINK the world.
EXIT_INTERRUPTED = 75   # graceful stop after SIGTERM/SIGINT; resume-able
EXIT_WATCHDOG = 76      # watchdog hard abort: a sync point stalled past
                        # watchdog_hard_s; the last committed checkpoint
                        # is the resume point
EXIT_PEER_LOST = 77     # a peer host is gone (stall coincides with missed
                        # heartbeats, or the round-boundary check found a
                        # stale peer): relaunch the SURVIVORS with
                        # JAX_NUM_PROCESSES shrunk and --resume auto
                        # (README "Elastic multi-host")

_NULL_CM = contextlib.nullcontext()


def _flush_checkpoints_bounded(timeout_s: float = 10.0) -> None:
    """Best-effort landing of in-flight async checkpoint commits before an
    abort exit. Bounded: the abort path must never trade a wedged round
    for a wedged flush (an async commit whose collective peer died would
    block forever), so the wait runs on a side thread and is abandoned at
    the deadline — the previous round's manifest-verified snapshot is
    already on disk either way (checkpoint.py flushes async manifests
    every round)."""
    done = threading.Event()

    def _wait():
        try:
            from dba_mod_tpu import checkpoint as ckpt  # lazy: no cycle
            ckpt.wait_for_async_saves()
        except Exception:  # noqa: BLE001 — aborting anyway
            pass
        finally:
            done.set()

    threading.Thread(target=_wait, daemon=True,
                     name="dba-abort-flush").start()
    if not done.wait(timeout_s):
        logger.warning("abort: async checkpoint flush did not finish in "
                       "%.0fs — exiting on the previous verified snapshot",
                       timeout_s)


class GracefulShutdown:
    """SIGTERM/SIGINT → stop flag; second signal → immediate exit.

    Handlers are installed only via :meth:`install` (RunGuard's
    ``__enter__``), only when enabled, and only from the main thread
    (Python restricts ``signal.signal`` to it); :meth:`uninstall` restores
    whatever was there before, so nested/sequential experiments in one
    process (parity A/Bs) don't fight over handlers."""

    SIGNALS = (signal.SIGTERM, signal.SIGINT)

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._stop = threading.Event()
        self._prev: Dict[int, Any] = {}
        self._signal_count = 0
        # injectable for tests — the real thing must be os._exit: a second
        # signal means "now", and raising inside a signal handler would
        # unwind into whatever JAX host callback happens to be on the stack
        self._force_exit: Callable[[int], None] = os._exit

    @property
    def stop_requested(self) -> bool:
        return self._stop.is_set()

    def request_stop(self) -> None:
        """Programmatic stop (tests; also lets hooks trigger the same
        round-boundary drain a signal would)."""
        self._stop.set()

    def install(self) -> None:
        # fresh run, fresh state: without this, a second run() on the same
        # Experiment would exit immediately on the stale stop flag, and —
        # worse — its FIRST signal would take the force-exit branch and
        # skip the final checkpoint/flush the graceful path promises
        self._stop.clear()
        self._signal_count = 0
        if not self.enabled or self._prev:
            return
        if threading.current_thread() is not threading.main_thread():
            logger.warning("graceful_shutdown: not on the main thread — "
                           "signal handlers not installed")
            return
        for sig in self.SIGNALS:
            self._prev[sig] = signal.signal(sig, self._handler)

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def _handler(self, signum, frame) -> None:
        self._signal_count += 1
        if self._signal_count >= 2:
            # the operator insists: no checkpoint, no flush, out now
            self._force_exit(128 + int(signum))
            return
        self._stop.set()
        # NO telemetry.count here: counters take telemetry's non-reentrant
        # module lock, and a handler runs on the main thread — a signal
        # landing while that thread holds the lock (any counter/histogram
        # update) would self-deadlock the process. The honored stop is
        # counted at the round boundary (run/interrupted).
        try:
            name = signal.Signals(signum).name
        except ValueError:  # pragma: no cover — unknown signum
            name = str(signum)
        logger.warning(
            "received %s — finishing the current round, then writing a "
            "final checkpoint and exiting with code %d; signal again to "
            "force immediate exit", name, EXIT_INTERRUPTED)


class _Zone:
    __slots__ = ("label", "t0", "soft_at", "hard_at", "soft_fired",
                 "epoch", "spans")

    def __init__(self, label: str, t0: float, soft_at: float, hard_at: float,
                 epoch: Optional[int], spans: List[str]):
        self.label = label
        self.t0 = t0
        self.soft_at = soft_at
        self.hard_at = hard_at
        self.soft_fired = False
        self.epoch = epoch
        self.spans = spans


class Watchdog:
    """Monotonic-deadline stall detector for host-blocking sync points.

    ``with watchdog.zone("round/finalize"):`` arms a deadline; leaving the
    block disarms it. One daemon thread (started lazily on the first armed
    zone, never when disabled) watches the active zone: at
    ``soft_s`` it logs a stall diagnostic once — the zone label, current
    epoch, elapsed seconds, and the telemetry span stack captured at zone
    entry (captured *in the arming thread*; the span stack is
    thread-local, and the arming thread is the one that is about to be
    wedged inside the zone) — at ``hard_s`` it aborts the process via
    `on_hard` (default: flush logging, ``os._exit(EXIT_WATCHDOG)``).
    Deadlines use ``time.monotonic()`` so wall-clock adjustments can
    neither fire nor suppress the timer."""

    def __init__(self, soft_s: float = 0.0, hard_s: float = 0.0,
                 on_hard: Optional[Callable[[], None]] = None):
        self.soft_s = float(soft_s)
        self.hard_s = float(hard_s)
        self.enabled = self.soft_s > 0 or self.hard_s > 0
        self._on_hard = on_hard or self._default_abort
        self._cv = threading.Condition()
        self._zone: Optional[_Zone] = None
        self._thread: Optional[threading.Thread] = None
        self.soft_stalls = 0
        self.hard_aborts = 0
        # elastic verdict hook (parallel/distributed.py::PeerHealth
        # .lost_peers): when set, a hard stall that coincides with missed
        # peer heartbeats is classified as "peer gone" and the abort exits
        # EXIT_PEER_LOST instead of EXIT_WATCHDOG — the supervisor then
        # relaunches shrunk rather than same-size
        self.peer_probe: Optional[Callable[[], List[int]]] = None
        # the verdict the hard-abort path logged — _default_abort reuses
        # it so the logged code, the run/peer_lost counter, and the real
        # exit code can never disagree (a peer crossing the staleness
        # threshold between two probes would otherwise split them)
        self._verdict: Optional["tuple[int, List[int]]"] = None

    @contextlib.contextmanager
    def zone(self, label: str):
        if not self.enabled:
            yield
            return
        self._ensure_thread()
        t = telemetry.current()
        t0 = time.monotonic()
        z = _Zone(label, t0,
                  t0 + self.soft_s if self.soft_s > 0 else float("inf"),
                  t0 + self.hard_s if self.hard_s > 0 else float("inf"),
                  t.current_epoch, t.span_stack())
        with self._cv:
            self._zone = z
            self._cv.notify()
        try:
            yield
        finally:
            with self._cv:
                self._zone = None
                self._cv.notify()

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="dba-watchdog")
        self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                z = self._zone
                if z is None:
                    self._cv.wait()
                    continue
                now = time.monotonic()
                nxt = min(z.hard_at,
                          z.soft_at if not z.soft_fired else float("inf"))
                if now < nxt:
                    # cap the wait so a re-armed zone is noticed promptly
                    self._cv.wait(min(nxt - now, 1.0))
                    continue
            # a deadline passed. Re-verify the zone is still armed right
            # before acting — the sync point may have completed in the gap
            # since the deadline was read, and a recovered process must
            # not be aborted (nor a misleading stall logged).
            elapsed = now - z.t0
            if not z.soft_fired and now >= z.soft_at:
                with self._cv:
                    armed = self._zone is z
                if not armed:
                    continue
                z.soft_fired = True
                self.soft_stalls += 1
                telemetry.count("watchdog/soft_stalls")
                logger.error(
                    "watchdog: %s has stalled for %.1fs (soft limit %.1fs) "
                    "— epoch=%s span stack at entry=%s; hard abort %s",
                    z.label, elapsed, self.soft_s, z.epoch,
                    z.spans or ["-"],
                    (f"at {self.hard_s:.1f}s" if self.hard_s > 0
                     else "disabled"))
            if now >= z.hard_at:
                # hold the lock across the abort: a zone exit racing this
                # blocks on the cv until the process dies, so a sync point
                # that completed just before the deadline check can never
                # be killed after the fact
                with self._cv:
                    if self._zone is not z:
                        continue
                    self.hard_aborts += 1
                    telemetry.count("watchdog/hard_aborts")
                    code, lost = self._verdict = self.abort_verdict()
                    if lost:
                        telemetry.count("run/peer_lost")
                    logger.critical(
                        "watchdog: %s stalled past the hard limit (%.1fs > "
                        "%.1fs) — epoch=%s span stack at entry=%s; %s"
                        "aborting with exit code %d (the last committed "
                        "checkpoint is the resume point)", z.label, elapsed,
                        self.hard_s, z.epoch, z.spans or ["-"],
                        (f"stall coincides with missed heartbeats from "
                         f"peer(s) {lost} — peer lost, relaunch the "
                         f"survivors shrunk; " if lost else ""),
                        code)
                    self._on_hard()
                    # an injected on_hard (tests) returns — drop the zone
                    # so the abort doesn't re-fire every poll
                    self._zone = None

    def abort_verdict(self) -> "tuple[int, List[int]]":
        """Classify the hard stall: (exit code, lost peer ids). A stall
        with missed peer heartbeats is a peer loss (EXIT_PEER_LOST) — the
        survivor is wedged in a collective whose peer vanished, and only a
        shrunk relaunch can make progress; anything else is the generic
        wedged-runtime abort (EXIT_WATCHDOG). A probe failure never masks
        the abort itself."""
        lost: List[int] = []
        if self.peer_probe is not None:
            try:
                lost = list(self.peer_probe())
            except Exception:  # noqa: BLE001 — the verdict is best-effort
                lost = []
        if lost:
            return EXIT_PEER_LOST, lost
        return EXIT_WATCHDOG, lost

    def _default_abort(self) -> None:  # pragma: no cover — kills the process
        # reuse the verdict _loop just logged/counted; probe fresh only if
        # an injected caller reached here without one
        code, _ = self._verdict or self.abort_verdict()
        _flush_checkpoints_bounded()
        logging.shutdown()
        os._exit(code)


class RunGuard:
    """The experiment-facing bundle: one stop flag + one watchdog, built
    from config. ``with guard:`` installs/uninstalls the signal handlers
    around the run loop; both members are inert when their knobs are off
    (the acceptance contract: no threads, no handlers, no per-round cost
    beyond an attribute check)."""

    def __init__(self, graceful_shutdown: bool = False,
                 watchdog_soft_s: float = 0.0, watchdog_hard_s: float = 0.0):
        self.shutdown = GracefulShutdown(enabled=graceful_shutdown)
        self.watchdog = Watchdog(soft_s=watchdog_soft_s,
                                 hard_s=watchdog_hard_s)

    @classmethod
    def from_params(cls, params) -> "RunGuard":
        return cls(
            graceful_shutdown=bool(params.get("graceful_shutdown", False)),
            watchdog_soft_s=float(params.get("watchdog_soft_s", 0.0)),
            watchdog_hard_s=float(params.get("watchdog_hard_s", 0.0)))

    @property
    def stop_requested(self) -> bool:
        return self.shutdown.stop_requested

    def attach_peer_health(self, peers) -> None:
        """Wire the elastic peer-health layer into the watchdog verdict:
        a hard stall that coincides with missed heartbeats exits
        EXIT_PEER_LOST (77) instead of EXIT_WATCHDOG (76). `peers` is a
        PeerHealth (parallel/distributed.py) or None to detach."""
        self.watchdog.peer_probe = (peers.lost_peers
                                    if peers is not None else None)

    def watch(self, label: str):
        """Watchdog zone around a host-blocking sync point; the shared
        null context when the watchdog is off."""
        if not self.watchdog.enabled:
            return _NULL_CM
        return self.watchdog.zone(label)

    def __enter__(self) -> "RunGuard":
        self.shutdown.install()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown.uninstall()
