"""Observability: CSV recorder with reference-schema parity, JSONL metrics,
run-folder logging, and the telemetry layer (span tracing, metrics registry,
XLA compile/memory instrumentation — utils/telemetry.py). Plotting is
deliberately decoupled from models (the reference's visdom mixin,
models/simple.py:18-200, is not carried over — SURVEY §7.3)."""
from dba_mod_tpu.utils.recorder import Recorder
