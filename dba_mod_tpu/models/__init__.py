"""Model registry.

Maps the reference's four workloads (reference main.py:94-109) to Flax modules and
records the per-model metadata the framework needs:

- `similarity_path`: which parameter stands in for the reference FoolsGold's
  "second-to-last named parameter" (helper.py:537) — for every reference model
  that is the final linear layer's weight;
- `has_batch_stats` / `has_dropout`: which extra variable collections / RNG
  streams the train step must thread.

Models are pure architectures; the reference's visdom-plotting mixin
(models/simple.py:18-200) is deliberately not carried over (observability lives in
`dba_mod_tpu.utils`).
"""
from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from dba_mod_tpu import config as cfg
from dba_mod_tpu.models.loan import LoanNet
from dba_mod_tpu.models.mnist import MnistNet
from dba_mod_tpu.models.resnet import cifar_resnet18, tiny_resnet18


class ModelVars(NamedTuple):
    """A model's full mutable state: trainable params + BN running stats.

    This is the functional equivalent of a torch ``state_dict`` — the unit that
    clients perturb and the server aggregates (the reference averages BN buffers
    together with weights, helper.py:233-257; we preserve that).
    """
    params: Any
    batch_stats: Any  # empty dict for models without BN


@dataclasses.dataclass(frozen=True)
class ModelDef:
    name: str
    module: nn.Module
    input_shape: Tuple[int, ...]   # one sample, NHWC / features
    num_classes: int
    similarity_path: Tuple[str, ...]
    has_batch_stats: bool
    has_dropout: bool

    def init_vars(self, rng: jax.Array) -> ModelVars:
        dummy = jnp.zeros((1,) + self.input_shape, jnp.float32)
        variables = self.module.init(rng, dummy, train=False)
        return ModelVars(params=variables["params"],
                         batch_stats=variables.get("batch_stats", {}))

    def apply(self, model_vars: ModelVars, x, train: bool,
              dropout_rng: jax.Array | None = None):
        """Forward pass. In train mode returns (logits, new_batch_stats)."""
        variables = {"params": model_vars.params}
        if self.has_batch_stats:
            variables["batch_stats"] = model_vars.batch_stats
        if self.has_dropout and train and dropout_rng is None:
            raise ValueError(
                f"{self.name}: dropout_rng is required in train mode")
        rngs = {"dropout": dropout_rng} if (self.has_dropout and train) else None
        if train and self.has_batch_stats:
            logits, updates = self.module.apply(
                variables, x, train=True, rngs=rngs, mutable=["batch_stats"])
            return logits, updates["batch_stats"]
        logits = self.module.apply(variables, x, train=train, rngs=rngs)
        return logits, model_vars.batch_stats

    def similarity_param(self, params) -> jax.Array:
        p = params
        for k in self.similarity_path:
            p = p[k]
        return p


def compute_dtype_of(params: cfg.Params):
    name = str(params.get("compute_dtype", "float32"))
    if name in ("float32", "f32"):
        return jnp.float32
    if name in ("bfloat16", "bf16"):
        return jnp.bfloat16
    raise ValueError(f"unknown compute_dtype {name!r}")


def build_model(params: cfg.Params) -> ModelDef:
    t = params.type
    dtype = compute_dtype_of(params)
    if t == cfg.TYPE_MNIST:
        return ModelDef(name="MnistNet", module=MnistNet(dtype=dtype),
                        input_shape=(28, 28, 1), num_classes=10,
                        similarity_path=("Dense_1", "kernel"),
                        has_batch_stats=False, has_dropout=False)
    if t == cfg.TYPE_CIFAR:
        return ModelDef(name="CifarResNet18",
                        module=cifar_resnet18(dtype=dtype),
                        input_shape=(32, 32, 3), num_classes=10,
                        similarity_path=("Dense_0", "kernel"),
                        has_batch_stats=True, has_dropout=False)
    if t == cfg.TYPE_TINYIMAGENET:
        return ModelDef(name="TinyResNet18",
                        module=tiny_resnet18(dtype=dtype),
                        input_shape=(64, 64, 3), num_classes=200,
                        similarity_path=("Dense_0", "kernel"),
                        has_batch_stats=True, has_dropout=False)
    if t == cfg.TYPE_LOAN:
        return ModelDef(name="LoanNet", module=LoanNet(dtype=dtype),
                        input_shape=(91,), num_classes=9,
                        similarity_path=("Dense_2", "kernel"),
                        has_batch_stats=False, has_dropout=True)
    raise ValueError(f"unknown workload type {t!r}")
