"""LeNet-style MNIST classifier.

Capability parity with reference `models/MnistNet.py:7-33`: conv(1→20, 5×5, valid)
→ maxpool2 → conv(20→50, 5×5, valid) → maxpool2 → fc(800→500) → fc(500→10),
log_softmax output. Layout is NHWC (TPU-native); the flatten order therefore
differs from torch's NCHW `.view`, which is a pure re-parameterisation with no
effect on the function class.

The reference feeds the log_softmax output into F.cross_entropy (MnistNet.py:31 →
image_train.py:85); since log_softmax is idempotent under another log_softmax this
equals training on logits — we keep the log_softmax head for output parity.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from dba_mod_tpu.ops.initializers import torch_bias_init, torch_kaiming_uniform


class MnistNet(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        # x: [N, 28, 28, 1]
        x = x.astype(self.dtype)
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype,
                    kernel_init=torch_kaiming_uniform,
                    bias_init=torch_bias_init(1 * 5 * 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(50, (5, 5), padding="VALID", dtype=self.dtype,
                    kernel_init=torch_kaiming_uniform,
                    bias_init=torch_bias_init(20 * 5 * 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))  # [N, 4*4*50]
        x = nn.Dense(500, dtype=self.dtype,
                     kernel_init=torch_kaiming_uniform,
                     bias_init=torch_bias_init(800))(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=torch_kaiming_uniform,
                     bias_init=torch_bias_init(500))(x)
        # head in float32 — log_softmax over bf16 logits costs accuracy
        return nn.log_softmax(x.astype(jnp.float32), axis=-1)
