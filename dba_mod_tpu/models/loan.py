"""LOAN tabular MLP.

Capability parity with reference `models/loan_model.py:10-27`: 91 → 46 → 23 → 9
with Dropout(0.5) *before* ReLU on each hidden layer (the reference's Sequential
order is Linear → Dropout → ReLU), raw logits out. The reference's host-side NaN
guard (loan_model.py:25-26) is replaced by `dba_mod_tpu.fl` debug-mode checks —
a data-dependent Python raise can't live inside a jitted forward.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from dba_mod_tpu.ops.initializers import torch_bias_init, torch_kaiming_uniform


class LoanNet(nn.Module):
    in_dim: int = 91
    hidden1: int = 46
    hidden2: int = 23
    num_classes: int = 9
    dropout_rate: float = 0.5
    dtype: Any = jnp.float32  # compute dtype; params stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Dense(self.hidden1, dtype=self.dtype,
                     kernel_init=torch_kaiming_uniform,
                     bias_init=torch_bias_init(self.in_dim))(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden2, dtype=self.dtype,
                     kernel_init=torch_kaiming_uniform,
                     bias_init=torch_bias_init(self.hidden1))(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=torch_kaiming_uniform,
                     bias_init=torch_bias_init(self.hidden2))(x)
        return x.astype(jnp.float32)
