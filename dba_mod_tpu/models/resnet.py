"""ResNet family: the narrow CIFAR variant and the torchvision-style Tiny-ImageNet
variant.

Capability parity:
- `cifar_resnet18()` matches reference `models/resnet_cifar.py:70-116`: 3×3 stem,
  **narrow widths (32/64/128/256)** — not the standard 64-base ResNet — BasicBlock
  [2,2,2,2], 4×4 average pool, linear head, raw logits. torch-default inits.
- `tiny_resnet18()` matches reference `models/resnet_tinyimagenet.py:40-238`:
  standard 64-base torchvision ResNet-18 with a 7×7/stride-2 stem, 3×3 max pool,
  global average pool, 200-class head, kaiming_normal(fan_out) conv init and
  BN γ=1/β=0 (reference :158-163).

Layout is NHWC, BatchNorm carries running stats in the `batch_stats` collection
(torch momentum 0.1 ≙ flax momentum 0.9, eps 1e-5) with exact torch running-stat
semantics — `models/norm.py::TorchBatchNorm` updates running_var with the
UNBIASED batch variance like torch, where flax's BatchNorm uses the biased one
(proven equivalent in tests/test_parity_ab.py). Deeper variants
(ResNet-34/50/101/152, reference resnet_cifar.py:106-116) are exposed through the
same constructors via `num_blocks`/`bottleneck`.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from dba_mod_tpu.models.norm import TorchBatchNorm
from dba_mod_tpu.ops.initializers import (kaiming_normal_fan_out,
                                          torch_bias_init,
                                          torch_kaiming_uniform)

ModuleDef = Any


class BasicBlock(nn.Module):
    planes: int
    stride: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding=((1, 1), (1, 1)), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.planes, (3, 3), strides=(1, 1),
                      padding=((1, 1), (1, 1)), use_bias=False)(y)
        y = self.norm()(y)
        if self.stride != 1 or x.shape[-1] != self.planes:
            residual = self.conv(self.planes, (1, 1),
                                 strides=(self.stride, self.stride),
                                 use_bias=False)(x)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    planes: int
    stride: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        out_planes = self.planes * self.expansion
        residual = x
        y = self.conv(self.planes, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.planes, (3, 3), strides=(self.stride, self.stride),
                      padding=((1, 1), (1, 1)), use_bias=False)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(out_planes, (1, 1), use_bias=False)(y)
        y = self.norm()(y)
        if self.stride != 1 or x.shape[-1] != out_planes:
            residual = self.conv(out_planes, (1, 1),
                                 strides=(self.stride, self.stride),
                                 use_bias=False)(x)
            residual = self.norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Configurable ResNet covering both reference variants."""

    num_classes: int
    num_blocks: Sequence[int] = (2, 2, 2, 2)
    widths: Sequence[int] = (32, 64, 128, 256)   # narrow CIFAR widths
    bottleneck: bool = False
    stem: str = "cifar"                          # "cifar": 3x3/s1; "imagenet": 7x7/s2+maxpool
    pool: str = "avg4"                           # "avg4": 4x4 window; "global"
    kernel_init: Callable = torch_kaiming_uniform
    head_init: Tuple[Callable, Callable] | None = None  # (kernel_init, bias_init)
    dtype: Any = jnp.float32   # compute dtype; params/batch_stats stay float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv = partial(nn.Conv, kernel_init=self.kernel_init,
                       dtype=self.dtype)
        norm = partial(TorchBatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        block_cls = Bottleneck if self.bottleneck else BasicBlock

        if self.stem == "cifar":
            x = conv(self.widths[0], (3, 3), padding=((1, 1), (1, 1)),
                     use_bias=False)(x)
            x = norm()(x)
            x = nn.relu(x)
        else:
            x = conv(self.widths[0], (7, 7), strides=(2, 2),
                     padding=((3, 3), (3, 3)), use_bias=False)(x)
            x = norm()(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))

        for stage, (planes, blocks) in enumerate(zip(self.widths, self.num_blocks)):
            for i in range(blocks):
                stride = (2 if stage > 0 else 1) if i == 0 else 1
                x = block_cls(planes=planes, stride=stride,
                              conv=conv, norm=norm)(x)

        if self.pool == "avg4":
            x = nn.avg_pool(x, (4, 4), strides=(4, 4))
        else:
            x = jnp.mean(x, axis=(1, 2), keepdims=True)
        x = x.reshape((x.shape[0], -1))

        feat = x.shape[-1]
        k_init, b_init = (self.head_init if self.head_init is not None
                          else (torch_kaiming_uniform, torch_bias_init(feat)))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     kernel_init=k_init, bias_init=b_init)(x)
        return x.astype(jnp.float32)


def cifar_resnet18(num_classes: int = 10, *, dtype=jnp.float32) -> ResNet:
    return ResNet(num_classes=num_classes, num_blocks=(2, 2, 2, 2),
                  widths=(32, 64, 128, 256), stem="cifar", pool="avg4",
                  dtype=dtype)


def cifar_resnet34(num_classes: int = 10, *, dtype=jnp.float32) -> ResNet:
    return ResNet(num_classes=num_classes, num_blocks=(3, 4, 6, 3),
                  widths=(32, 64, 128, 256), stem="cifar", pool="avg4",
                  dtype=dtype)


def cifar_resnet50(num_classes: int = 10, *, dtype=jnp.float32) -> ResNet:
    return ResNet(num_classes=num_classes, num_blocks=(3, 4, 6, 3),
                  widths=(32, 64, 128, 256), bottleneck=True,
                  stem="cifar", pool="avg4", dtype=dtype)


def tiny_resnet18(num_classes: int = 200, *, dtype=jnp.float32) -> ResNet:
    return ResNet(num_classes=num_classes, num_blocks=(2, 2, 2, 2),
                  widths=(64, 128, 256, 512), stem="imagenet", pool="global",
                  kernel_init=kaiming_normal_fan_out, dtype=dtype)


def cifar_resnet101(num_classes: int = 10, *, dtype=jnp.float32) -> ResNet:
    return ResNet(num_classes=num_classes, num_blocks=(3, 4, 23, 3),
                  widths=(32, 64, 128, 256), bottleneck=True,
                  stem="cifar", pool="avg4", dtype=dtype)


def cifar_resnet152(num_classes: int = 10, *, dtype=jnp.float32) -> ResNet:
    return ResNet(num_classes=num_classes, num_blocks=(3, 8, 36, 3),
                  widths=(32, 64, 128, 256), bottleneck=True,
                  stem="cifar", pool="avg4", dtype=dtype)
