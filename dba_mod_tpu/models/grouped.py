"""Grouped-layout forward for stacked per-client ResNets.

Why this exists (VERDICT r4 weak #1 / ask #2): the round engine trains C
clients by vmapping the per-client step (fl/rounds.py). vmap's batching rule
for `conv_general_dilated` already lowers the stacked convs to *grouped*
convolutions (feature_group_count=C) — the MXU work is identical — but it
re-derives the grouped layout around EVERY conv: transpose the activations
[C,B,H,W,f] → [B,H,W,C·f], merge, convolve, unmerge, transpose back. On the
bench workload those per-conv layout moves are ~19% of train device time
(TRAIN_FLOOR.md kernel table: 13% transposes + 6% copy).

This module runs the SAME math with the grouped layout held across the whole
network instead:

- activations live as [B, H, W, C·f] (client-major channels) from the stem to
  the head — no per-conv transposes;
- conv kernels are carried as [kh, kw, ci, C, co] (client axis third), so the
  merge to the grouped-conv kernel [kh, kw, ci, C·co] is a FREE reshape
  (adjacent dims, no data movement) — the client step keeps params/momentum
  in this layout across the whole scan and converts once per segment
  (fl/grouped_client.py);
- BatchNorm reduces over (B, H, W) per channel — channels never mix, so the
  per-channel statistics equal the per-client ones exactly (models/norm.py
  torch semantics preserved, incl. the unbiased running-var update);
- the head is a per-client batched matmul ([B, C, f] × [C, f, K]).

Per-client math is mathematically identical to the vmapped path (same grouped
convolutions, equally-valid summation orders) but NOT bitwise: last-ulp conv
differences exist per step (forward ≤5e-5, tests/test_grouped_clients.py) and
chaos-amplify over a training round — f32 round deltas agree to ~5e-4, bf16
trajectories decorrelate (TRAIN_FLOOR.md round-5 section). Reference
counterpart: none — this is TPU-native machinery under the reference's
sequential client loop (image_train.py:21-32).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from dba_mod_tpu.models.resnet import ResNet

BN_MOMENTUM = 0.9   # models/resnet.py pins momentum=0.9, epsilon=1e-5
BN_EPS = 1e-5


def supports_grouped(model_def) -> bool:
    """Grouped execution covers the BasicBlock ResNet family (both reference
    CNN workloads: narrow CIFAR and Tiny-ImageNet). Bottleneck variants and
    the small MnistNet/LoanNet fall back to the vmapped path."""
    m = model_def.module
    return (isinstance(m, ResNet) and not m.bottleneck
            and not model_def.has_dropout)


def conv_layout_in(stacked_params):
    """[C, kh, kw, ci, co] conv kernels → [kh, kw, ci, C, co] (client axis
    adjacent to the output-feature axis, making the grouped-kernel merge a
    free reshape). All other leaves keep the client axis leading."""
    return jax.tree_util.tree_map(
        lambda l: jnp.moveaxis(l, 0, 3) if l.ndim == 5 else l, stacked_params)


def conv_layout_out(conv_params):
    return jax.tree_util.tree_map(
        lambda l: jnp.moveaxis(l, 3, 0) if l.ndim == 5 else l, conv_params)


def client_axis_of(leaf) -> int:
    """Which axis of a conv-layout leaf is the clients axis."""
    return 3 if leaf.ndim == 5 else 0


def _conv(x, w, stride: int, pad: int, C: int, dtype):
    """Grouped conv: x [B,H,W,C·ci], w [kh,kw,ci,C,co]."""
    kh, kw, ci, Cw, co = w.shape
    w = w.astype(dtype).reshape(kh, kw, ci, Cw * co)
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=C)


def _bn(x, bp: Dict[str, Any], bs: Dict[str, Any], dtype):
    """TorchBatchNorm train-mode on merged channels (models/norm.py): biased
    variance normalizes, unbiased updates the running stats. bp/bs leaves are
    [C, f]; channels of x are the matching c-major merge."""
    f_tot = x.shape[-1]
    scale = bp["scale"].reshape(f_tot)
    bias = bp["bias"].reshape(f_tot)
    xf = x.astype(jnp.float32).reshape(-1, f_tot)
    n = xf.shape[0]
    mean = jnp.mean(xf, axis=0)
    var = jnp.maximum(
        0.0, jnp.mean(jnp.square(xf), axis=0) - jnp.square(mean))
    bessel = n / max(n - 1, 1)
    m = BN_MOMENTUM
    new_stats = {
        "mean": (m * bs["mean"].reshape(f_tot) + (1.0 - m) * mean).reshape(
            bs["mean"].shape),
        "var": (m * bs["var"].reshape(f_tot)
                + (1.0 - m) * (var * bessel)).reshape(bs["var"].shape)}
    y = (x.astype(jnp.float32) - mean) * lax.rsqrt(var + BN_EPS) * scale + bias
    return y.astype(dtype), new_stats


def _basic_block(x, bp, bs, stride: int, C: int, dtype):
    new_bs: Dict[str, Any] = {}
    y = _conv(x, bp["Conv_0"]["kernel"], stride, 1, C, dtype)
    y, new_bs["BatchNorm_0"] = _bn(y, bp["BatchNorm_0"], bs["BatchNorm_0"],
                                   dtype)
    y = nn.relu(y)
    y = _conv(y, bp["Conv_1"]["kernel"], 1, 1, C, dtype)
    y, new_bs["BatchNorm_1"] = _bn(y, bp["BatchNorm_1"], bs["BatchNorm_1"],
                                   dtype)
    if "Conv_2" in bp:  # downsample branch (resnet.py:53-57)
        r = _conv(x, bp["Conv_2"]["kernel"], stride, 0, C, dtype)
        r, new_bs["BatchNorm_2"] = _bn(r, bp["BatchNorm_2"],
                                       bs["BatchNorm_2"], dtype)
    else:
        r = x
    return nn.relu(y + r), new_bs


def grouped_train_apply(model_def, params_cl, batch_stats, x_cb
                        ) -> Tuple[jax.Array, Any]:
    """Train-mode forward of C stacked clients in grouped layout.

    params_cl: conv-layout stacked params (see `conv_layout_in`);
    batch_stats: stacked [C, f] BN stats; x_cb: [C, B, H, W, ci].
    Returns (logits [C, B, K], new_batch_stats).
    """
    mod: ResNet = model_def.module
    dtype = mod.dtype
    C, B = x_cb.shape[0], x_cb.shape[1]
    # the one activation transpose per step: the tiny input tensor
    # (3 channels), not every layer's activations
    x = jnp.moveaxis(x_cb, 0, 3)
    x = x.reshape(x.shape[:3] + (C * x.shape[4],)).astype(dtype)

    p, bs = params_cl, batch_stats
    new_bs: Dict[str, Any] = {}
    if mod.stem == "cifar":
        x = _conv(x, p["Conv_0"]["kernel"], 1, 1, C, dtype)
        x, new_bs["BatchNorm_0"] = _bn(x, p["BatchNorm_0"],
                                       bs["BatchNorm_0"], dtype)
        x = nn.relu(x)
    else:  # imagenet stem (resnet.py:116-121)
        x = _conv(x, p["Conv_0"]["kernel"], 2, 3, C, dtype)
        x, new_bs["BatchNorm_0"] = _bn(x, p["BatchNorm_0"],
                                       bs["BatchNorm_0"], dtype)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2),
                        padding=((1, 1), (1, 1)))

    b = 0
    for stage, blocks in enumerate(mod.num_blocks):
        for i in range(blocks):
            stride = (2 if stage > 0 else 1) if i == 0 else 1
            name = f"BasicBlock_{b}"
            x, nbs = _basic_block(x, p[name], bs[name], stride, C, dtype)
            new_bs[name] = nbs
            b += 1

    if mod.pool == "avg4":
        x = nn.avg_pool(x, (4, 4), strides=(4, 4))
    else:
        x = jnp.mean(x, axis=(1, 2), keepdims=True)
    x = x.reshape(B, C, -1)  # c-major channel merge → per-client features

    w = p["Dense_0"]["kernel"].astype(dtype)        # [C, f, K]
    bsum = p["Dense_0"]["bias"].astype(dtype)       # [C, K]
    logits = jnp.einsum("bcf,cfk->cbk", x.astype(dtype), w) + bsum[:, None, :]
    return logits.astype(jnp.float32), new_bs
