"""Batch normalization with exact torch semantics.

flax.linen.BatchNorm updates the running variance with the BIASED batch
variance; torch's nn.BatchNorm2d uses the UNBIASED (n/(n-1)) variance for the
running update while normalizing with the biased one (the train-mode output is
identical, the running stats differ by the Bessel factor). The reference
aggregates and evaluates through those running buffers (helper.py:240-257
averages them with the weights; test.py runs model.eval()), so the buffers are
part of the model state we must reproduce — this module implements the torch
rule exactly.

Interface mirrors flax.linen.BatchNorm (same param/collection names: `scale`,
`bias` in params; `mean`, `var` in batch_stats; flax momentum convention
ra = momentum·ra + (1-momentum)·batch, so flax momentum 0.9 ≙ torch 0.1).
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class BatchNorm(nn.Module):
    """Named `BatchNorm` so flax auto-naming keeps the `BatchNorm_N` param
    paths (checkpoint/key compatibility with the stock-flax variant);
    import as `TorchBatchNorm` to make call sites self-documenting."""

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None  # output dtype; statistics always compute in float32

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((features,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((features,), jnp.float32))

        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32).reshape(-1, features)
            n = xf.shape[0]
            mean = jnp.mean(xf, axis=0)
            # biased variance normalizes the batch (torch train-mode
            # output); clamp at 0 — E[x²]−E[x]² can go slightly negative
            # under f32 cancellation for large-mean channels, and a negative
            # value would NaN the rsqrt and poison running_var (torch's
            # centered computation is never negative; flax clamps the same
            # way)
            var = jnp.maximum(
                0.0, jnp.mean(jnp.square(xf), axis=0) - jnp.square(mean))
            if not self.is_initializing():
                # torch running update uses the UNBIASED variance
                bessel = n / max(n - 1, 1)
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
                ra_var.value = m * ra_var.value + (1.0 - m) * (var * bessel)

        y = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(
            var + self.epsilon) * scale + bias
        return y.astype(self.dtype or x.dtype)


TorchBatchNorm = BatchNorm
