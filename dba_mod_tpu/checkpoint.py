"""Checkpoint/resume via orbax.

Reference parity (helper.py:51-57, :420-435; image_helper.py:56-67): the saved
unit is {model state, epoch, lr}; resume restores the global model, sets
start_epoch = saved_epoch + 1 and overwrites the config lr. The canonical use
is "pretrain clean to epoch N, then attack from the checkpoint"
(utils/cifar_params.yaml:68-69); `python -m dba_mod_tpu.main pretrain`
regenerates those clean models since the reference's Google-Drive artifacts
are external (SURVEY §5 checkpoint row).
"""
from __future__ import annotations

from pathlib import Path
from typing import Optional, Tuple

import jax
import numpy as np

from dba_mod_tpu.models import ModelVars


def save_checkpoint(path: str | Path, model_vars: ModelVars, epoch: int,
                    lr: float) -> None:
    import orbax.checkpoint as ocp
    path = Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"params": model_vars.params,
                          "batch_stats": model_vars.batch_stats,
                          "epoch": np.asarray(epoch, np.int64),
                          "lr": np.asarray(lr, np.float64)},
                   force=True)


def load_checkpoint(path: str | Path,
                    like: ModelVars) -> Tuple[ModelVars, int, float]:
    import orbax.checkpoint as ocp
    path = Path(path).absolute()
    abstract = {"params": jax.tree_util.tree_map(np.asarray, like.params),
                "batch_stats": jax.tree_util.tree_map(np.asarray,
                                                      like.batch_stats),
                "epoch": np.asarray(0, np.int64),
                "lr": np.asarray(0, np.float64)}
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, abstract)
    mv = ModelVars(
        params=jax.tree_util.tree_map(jax.numpy.asarray, restored["params"]),
        batch_stats=jax.tree_util.tree_map(jax.numpy.asarray,
                                           restored["batch_stats"]))
    return mv, int(restored["epoch"]), float(restored["lr"])
