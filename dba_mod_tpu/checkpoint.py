"""Checkpoint/resume via orbax.

Reference parity (helper.py:51-57, :420-435; image_helper.py:56-67): the saved
unit is {model state, epoch, lr}; resume restores the global model, sets
start_epoch = saved_epoch + 1 and overwrites the config lr. The canonical use
is "pretrain clean to epoch N, then attack from the checkpoint"
(utils/cifar_params.yaml:68-69); `python -m dba_mod_tpu.main pretrain`
regenerates those clean models since the reference's Google-Drive artifacts
are external (SURVEY §5 checkpoint row).

Two deliberate improvements over the reference:

- **Async saves** (`async_save=True`): orbax's AsyncCheckpointer copies the
  state to host and commits in the background, so per-round checkpointing
  composes with round pipelining. Program order is preserved — a new save
  blocks until the previous commit finished — and `wait_for_async_saves()`
  must run before process exit / before reading a just-written file.
- **Full-state sidecar** (`save_aux_state`): the reference checkpoints only
  the model (helper.py:420-435) while FoolsGold's cross-round memory lives in
  a RAM-only dict (helper.py:545-549) — a mid-attack restart silently resets
  the defense. The sidecar carries FoolsGold memory, best-val loss, the
  host RNG streams and the JAX key, so a resumed run replays the
  uninterrupted trajectory exactly (tests/test_full_state_resume.py).
- **Integrity manifests + auto-resume** (this PR, README "Crash &
  preemption tolerance"): every committed snapshot gets a
  ``<name>.manifest.json`` — sha256/size over every file in the orbax step
  dir plus the aux sidecar, written atomically *after* the commit — so
  resume verifies before restoring. A corrupt/partial snapshot (a kill -9
  mid-overwrite, a flipped byte) is detected, quarantined to
  ``<name>.corrupt/`` and resume falls back to the newest *verified*
  snapshot instead of crashing or silently restoring garbage.
  :func:`find_auto_resume` implements ``resumed_model: auto``: discover
  the newest verified checkpoint across the run folders of a ``run_dir``.
  :class:`CheckpointManager` adds retention GC (``keep_last_n``; the
  ``.best`` and ``model_last`` snapshots are always retained) and the
  startup sweep of orphaned ``*.tmp`` files / uncommitted orbax tmp dirs.
  For async saves the manifest is deferred until the commit is known to
  have landed (orbax serializes commits: enqueueing save K proves saves
  < K are on disk) and always flushed by :func:`wait_for_async_saves`,
  which is also registered via ``atexit`` so no exit path can lose an
  in-flight commit.
"""
from __future__ import annotations

import atexit
import hashlib
import json
import logging
import os
import pickle
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from dba_mod_tpu.models import ModelVars
from dba_mod_tpu.utils import telemetry

logger = logging.getLogger("dba_mod_tpu")

AUX_SUFFIX = ".aux.pkl"
MANIFEST_SUFFIX = ".manifest.json"
CORRUPT_SUFFIX = ".corrupt"
PREV_SUFFIX = ".prev"
# orbax's uncommitted-checkpoint tmp dirs (atomicity discipline: write to
# tmp, rename on commit) — a crash mid-commit leaves one behind
ORBAX_TMP_GLOB = "*.orbax-checkpoint-tmp-*"

_async_ckptr = None

# manifests owed to async saves whose commits have not provably landed yet:
# abs path -> epoch. Module-level (not per-CheckpointManager) so the atexit
# flush below covers every manager in the process.
_pending_manifests: Dict[str, int] = {}


def _get_async_checkpointer():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp
        _async_ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # every exit path must land the in-flight commit AND its manifest —
        # an exception after an async enqueue (or a plain sys.exit) would
        # otherwise lose the newest checkpoint entirely, since force=True
        # already deleted the previous model_last
        atexit.register(wait_for_async_saves)
    return _async_ckptr


def wait_for_async_saves() -> None:
    """Block until every in-flight async checkpoint commit has landed, then
    write the manifests those commits were owed. Registered with atexit on
    first async use, so it runs on every exit path."""
    if _async_ckptr is not None:
        with telemetry.span("checkpoint/wait_async"):
            _async_ckptr.wait_until_finished()
            # errors first: a failed commit must NOT get a manifest (the
            # manifest would bless whatever partial files are on disk)
            _async_ckptr.check_for_errors()
    flush_queued_manifests()


def save_checkpoint(path: str | Path, model_vars: ModelVars, epoch: int,
                    lr: float, *, async_save: bool = False) -> None:
    import orbax.checkpoint as ocp
    path = Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {"params": model_vars.params,
               "batch_stats": model_vars.batch_stats,
               "epoch": np.asarray(epoch, np.int64),
               "lr": np.asarray(lr, np.float64)}
    # the async span covers only the enqueue (the commit runs in orbax's
    # background thread — checkpoint/wait_async is where it lands); the
    # sync span covers the whole write
    telemetry.count("checkpoint/saves")
    if async_save:
        with telemetry.span("checkpoint/save_async_enqueue"):
            _get_async_checkpointer().save(
                path, args=ocp.args.StandardSave(payload), force=True)
    else:
        with telemetry.span("checkpoint/save"):
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(path, payload, force=True)


def load_checkpoint(path: str | Path,
                    like: ModelVars) -> Tuple[ModelVars, int, float]:
    import orbax.checkpoint as ocp
    path = Path(path).absolute()
    abstract = {"params": jax.tree_util.tree_map(np.asarray, like.params),
                "batch_stats": jax.tree_util.tree_map(np.asarray,
                                                      like.batch_stats),
                "epoch": np.asarray(0, np.int64),
                "lr": np.asarray(0, np.float64)}
    telemetry.count("checkpoint/loads")
    with telemetry.span("checkpoint/load"):
        with ocp.StandardCheckpointer() as ckptr:
            restored = ckptr.restore(path, abstract)
    mv = ModelVars(
        params=jax.tree_util.tree_map(jax.numpy.asarray, restored["params"]),
        batch_stats=jax.tree_util.tree_map(jax.numpy.asarray,
                                           restored["batch_stats"]))
    return mv, int(restored["epoch"]), float(restored["lr"])


# ----------------------------------------------------------- full-state aux
def save_aux_state(path: str | Path, aux: Dict[str, Any]) -> None:
    """Write the experiment sidecar next to an orbax checkpoint directory.

    `aux` holds host-side state only (numpy arrays / python scalars / RNG
    state tuples) — callers device_get anything device-resident first. The
    write is atomic (tmp + rename) so a crash mid-save leaves the previous
    sidecar intact, matching orbax's own commit discipline.
    """
    path = Path(str(path) + AUX_SUFFIX).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(aux, f)
    tmp.replace(path)


def load_aux_state(path: str | Path) -> Optional[Dict[str, Any]]:
    """Read the sidecar written by `save_aux_state`; None when absent
    (e.g. resuming a pretrain-only checkpoint — model-only resume is the
    reference behavior and stays fully supported). A truncated/corrupt
    sidecar also degrades to None with a loud warning — model-only resume
    is the documented fallback (the same one the epoch-mismatch check in
    Experiment uses), never a crash at restore time."""
    path = Path(str(path) + AUX_SUFFIX).absolute()
    if not path.exists():
        return None
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception as exc:  # noqa: BLE001 — any unpickling failure
        # (truncation, flipped bytes, EOF) means the sidecar is gone; the
        # model checkpoint may still be fine
        telemetry.count("checkpoint/corrupt_detected")
        logger.warning(
            "resume sidecar %s is corrupt (%r) — degrading to model-only "
            "resume (FoolsGold memory and RNG streams restart)", path, exc)
        return None


# ------------------------------------------------------- integrity manifests
def manifest_path(path: str | Path) -> Path:
    return Path(str(path) + MANIFEST_SUFFIX).absolute()


def _sha256(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _checkpoint_files(path: Path) -> Dict[str, Path]:
    """Every file a manifest covers: the orbax step dir's files (keyed by
    relative posix path under ``ckpt/``) plus the aux sidecar when
    present."""
    out: Dict[str, Path] = {}
    base = Path(path).absolute()
    if base.is_dir():
        for p in sorted(base.rglob("*")):
            if p.is_file():
                out["ckpt/" + p.relative_to(base).as_posix()] = p
    aux = Path(str(base) + AUX_SUFFIX)
    if aux.exists():
        out["aux"] = aux
    return out


def write_manifest(path: str | Path, epoch: int) -> Path:
    """Content-checksum manifest over a *committed* snapshot (orbax step
    dir + sidecar), written atomically (tmp + os.replace) so a crash
    mid-write leaves either the previous manifest or none — never a
    half-manifest that would mark a good checkpoint corrupt."""
    path = Path(path).absolute()
    with telemetry.span("checkpoint/manifest"):
        files = {key: {"sha256": _sha256(p), "size": p.stat().st_size}
                 for key, p in _checkpoint_files(path).items()}
        doc = {"version": 1, "epoch": int(epoch), "files": files}
        mpath = manifest_path(path)
        tmp = mpath.with_name(mpath.name + ".tmp")
        tmp.write_text(json.dumps(doc, indent=0, sort_keys=True))
        os.replace(tmp, mpath)
    return mpath


def manifest_epoch(path: str | Path) -> Optional[int]:
    """The epoch a snapshot's manifest records; None when there is no
    (readable) manifest. Cheap — used to order discovery candidates before
    paying for full verification."""
    try:
        return int(json.loads(manifest_path(path).read_text())["epoch"])
    except (OSError, ValueError, KeyError, TypeError,
            json.JSONDecodeError):
        # TypeError: wrong-shape-but-valid JSON (null, a list, epoch:
        # null) — corruption must demote the candidate, not crash
        # discovery
        return None


VERIFY_OK = "verified"
VERIFY_NO_MANIFEST = "no-manifest"


def verify_checkpoint(path: str | Path) -> Tuple[bool, str]:
    """Recompute checksums against the manifest. Returns ``(True,
    'verified')``, ``(False, 'no-manifest')`` for legacy snapshots (e.g.
    pretrain outputs saved before manifests existed — callers decide
    whether to trust them), or ``(False, <reason>)`` for a detected
    corruption (missing/resized/flipped file, unreadable manifest,
    missing step dir). Extra files beyond the manifest are ignored."""
    path = Path(path).absolute()
    mpath = manifest_path(path)
    if not mpath.exists():
        return False, VERIFY_NO_MANIFEST
    with telemetry.span("checkpoint/verify"):
        # broad catches: this is the never-crash contract — an unreadable
        # manifest (EIO on the same failing disk that corrupted the
        # checkpoint), valid JSON of the wrong shape, or a file vanishing
        # mid-hash all mean "not verified", never an exception into the
        # resume path
        try:
            doc = json.loads(mpath.read_text())
            manifest_files = dict(doc["files"])
        except Exception as exc:  # noqa: BLE001
            return False, f"unreadable manifest: {exc!r}"
        if not path.is_dir():
            return False, "checkpoint dir missing"
        on_disk = _checkpoint_files(path)
        try:
            for key, want in manifest_files.items():
                p = on_disk.get(key)
                if p is None:
                    return False, f"missing file: {key}"
                if p.stat().st_size != int(want["size"]):
                    return False, (f"size mismatch: {key} "
                                   f"({p.stat().st_size} != {want['size']})")
                if _sha256(p) != want["sha256"]:
                    return False, f"checksum mismatch: {key}"
        except Exception as exc:  # noqa: BLE001
            return False, f"verification error: {exc!r}"
    return True, VERIFY_OK


def quarantine_checkpoint(path: str | Path) -> Path:
    """Move a corrupt snapshot (step dir + sidecar + manifest) aside to
    ``<name>.corrupt/`` so it can't be picked again and a human can
    inspect it. Returns the quarantine dir."""
    path = Path(path).absolute()
    dest = Path(str(path) + CORRUPT_SUFFIX)
    n = 0
    while dest.exists():
        n += 1
        dest = Path(str(path) + f"{CORRUPT_SUFFIX}-{n}")
    dest.mkdir(parents=True)
    for piece in (path, Path(str(path) + AUX_SUFFIX), manifest_path(path)):
        if piece.exists():
            shutil.move(str(piece), str(dest / piece.name))
    telemetry.count("checkpoint/quarantined")
    logger.warning("quarantined corrupt checkpoint %s -> %s", path, dest)
    return dest


# ----------------------------------------------------- discovery / fallback
def _discovery_candidates(folder: Path) -> List[Tuple[int, float, Path]]:
    """Manifested snapshot dirs under `folder`, newest first by (manifest
    epoch, mtime). Only manifested snapshots are candidates: auto-resume
    restores exclusively from checkpoints it can verify."""
    out = []
    if not folder.is_dir():
        return out
    for p in folder.iterdir():
        if not p.is_dir() or CORRUPT_SUFFIX in p.name:
            continue
        if "orbax-checkpoint-tmp" in p.name:
            continue
        ep = manifest_epoch(p)
        if ep is None:
            continue
        out.append((ep, p.stat().st_mtime, p))
    # newest epoch first; at equal epoch prefer the canonical snapshot
    # (model_last / .epoch_N) over .best — identical state, but the
    # canonical one is what operators expect resume logs to name
    out.sort(key=lambda t: (t[0], not t[2].name.endswith(".best"), t[1]),
             reverse=True)
    return out


def latest_verified_checkpoint(folder: str | Path,
                               quarantine: bool = True) -> Optional[Path]:
    """Newest snapshot in `folder` that passes manifest verification.
    Corrupt candidates encountered on the way are counted, logged, and
    (by default) quarantined — resume *falls back* past them instead of
    crashing."""
    folder = Path(folder).absolute()
    for ep, _, p in _discovery_candidates(folder):
        ok, reason = verify_checkpoint(p)
        if ok:
            return p
        telemetry.count("checkpoint/corrupt_detected")
        logger.warning(
            "checkpoint %s (epoch %d) failed verification: %s — "
            "falling back to the previous verified snapshot", p, ep, reason)
        if quarantine:
            quarantine_checkpoint(p)
    return None


def resolve_verified(path: str | Path) -> Path:
    """Verification gate for an *explicitly named* resume checkpoint.
    Verified → the path itself. Manifest-less (legacy/pretrain) → the path,
    with a debug note — those snapshots predate manifests and stay fully
    supported. Corrupt → fall back to the newest verified snapshot of the
    SAME name family (``<name>.prev``/``.epoch_N``/``.best``); with none,
    raise. The named path may live in a shared checkpoint library that
    other processes are actively writing, so unlike the auto-resume scan
    of an exclusively-owned run folder this NEVER mutates the directory —
    no quarantine, no sweep — and never silently substitutes an
    unrelated-name checkpoint (which could be a different workload's)."""
    path = Path(path).absolute()
    ok, reason = verify_checkpoint(path)
    if ok:
        return path
    if reason == VERIFY_NO_MANIFEST:
        if not path.is_dir():
            raise FileNotFoundError(f"resume checkpoint not found: {path}")
        logger.debug("resume checkpoint %s has no integrity manifest "
                     "(pre-manifest snapshot) — restoring unverified", path)
        return path
    telemetry.count("checkpoint/corrupt_detected")
    logger.warning("resume checkpoint %s failed verification: %s",
                   path, reason)
    for ep, _, p in _discovery_candidates(path.parent):
        # "." after the base name: family suffixes only (.prev/.epoch_N/
        # .best) — a bare prefix match would accept an unrelated
        # "mnist_pretrain_v2" as fallback for "mnist_pretrain"
        if p == path or not p.name.startswith(path.name + "."):
            continue
        if verify_checkpoint(p)[0]:
            logger.warning("resuming from fallback checkpoint %s "
                           "(epoch %d)", p, ep)
            return p
    raise RuntimeError(
        f"resume checkpoint {path} is corrupt ({reason}) and no verified "
        f"same-name fallback ({path.name}.prev/.epoch_N/.best) exists in "
        f"{path.parent}")


def find_auto_resume(run_dir: str | Path, run_type: str,
                     run_name: str = "") -> Optional[Tuple[Path, Path]]:
    """``resumed_model: auto``: scan `run_dir` for this workload's run
    folders (``{type}_*``), newest first, and return ``(run_folder,
    checkpoint_path)`` for the newest verified checkpoint — or None when
    no run folder holds one (fresh start). With a fixed ``run_name``
    (multi-process / elastic runs share one non-timestamped folder) only
    that folder is considered — an elastic relaunch must re-enter the
    killed world's folder, never a stale timestamped sibling."""
    run_dir = Path(run_dir)
    if not run_dir.is_dir():
        return None
    if run_name:
        folders = [p for p in (run_dir / run_name,) if p.is_dir()]
    else:
        folders = sorted((p for p in run_dir.glob(f"{run_type}_*")
                          if p.is_dir()),
                         key=lambda p: p.stat().st_mtime, reverse=True)
    for folder in folders:
        hit = latest_verified_checkpoint(folder)
        if hit is not None:
            return folder, hit
    return None


# ------------------------------------------------------ fallback protection
def _clone_file(src: Path, dst: Path) -> None:
    try:
        os.link(src, dst)  # same dir => same fs; shares data blocks
    except OSError:  # pragma: no cover — fs without hardlink support
        shutil.copy2(src, dst)


def protect_last(path: str | Path) -> Optional[Path]:
    """Clone a committed+manifested snapshot to ``<name>.prev`` (hardlinks
    — ~zero cost, no data copied) BEFORE force=True replaces it, so one
    verified snapshot exists at every instant of a save. Without this, a
    kill between the overwrite and the new manifest leaves the newest
    snapshot unverifiable (quarantined on discovery) and — when no
    ``.epoch_N``/``.best`` sibling survives — auto-resume restarts from
    scratch. The clone's manifest is written last (atomically), so a kill
    mid-clone never creates an unverifiable discovery candidate. Returns
    the clone path, or None when there is nothing verified to protect."""
    path = Path(path).absolute()
    mpath = manifest_path(path)
    if not path.is_dir() or not mpath.exists():
        return None
    dest = Path(str(path) + PREV_SUFFIX)
    unprotect_prev(path)  # clear a stale clone from an earlier crash
    for p in sorted(path.rglob("*")):
        rel = p.relative_to(path)
        if p.is_dir():
            (dest / rel).mkdir(parents=True, exist_ok=True)
        else:
            (dest / rel).parent.mkdir(parents=True, exist_ok=True)
            _clone_file(p, dest / rel)
    aux = Path(str(path) + AUX_SUFFIX)
    if aux.exists():
        _clone_file(aux, Path(str(dest) + AUX_SUFFIX))
    # the manifest's file keys are relative (ckpt/..., aux), so the
    # original's document is valid for the clone verbatim
    mdest = manifest_path(dest)
    tmp = mdest.with_name(mdest.name + ".tmp")
    tmp.write_text(mpath.read_text())
    os.replace(tmp, mdest)
    return dest


def unprotect_prev(path: str | Path) -> None:
    """Delete ``<name>.prev`` — manifest first, so a kill mid-delete
    demotes the clone to a non-candidate instead of leaving an
    unverifiable one. Only call once the replacement snapshot's own
    manifest is on disk."""
    dest = Path(str(Path(path).absolute()) + PREV_SUFFIX)
    m = manifest_path(dest)
    if m.exists():
        m.unlink()
    aux = Path(str(dest) + AUX_SUFFIX)
    if aux.exists():
        aux.unlink()
    if dest.is_dir():
        shutil.rmtree(dest, ignore_errors=True)


# -------------------------------------------------- pending async manifests
def queue_manifest(path: str | Path, epoch: int) -> None:
    """Record that `path`'s async commit, once landed, is owed a manifest
    for `epoch`."""
    _pending_manifests[str(Path(path).absolute())] = int(epoch)


def drop_queued_manifest(path: str | Path) -> None:
    """Forget a queued manifest — the snapshot is about to be overwritten
    (force=True re-save of model_last/.best), so the queued manifest would
    describe files that no longer exist."""
    _pending_manifests.pop(str(Path(path).absolute()), None)


def flush_queued_manifests() -> None:
    """Write every queued manifest. Only call when the corresponding
    commits are known to have landed: after ``wait_until_finished`` +
    ``check_for_errors``, or for entries strictly older than a save that
    has since been enqueued (orbax serializes commits)."""
    for p, ep in list(_pending_manifests.items()):
        _pending_manifests.pop(p, None)
        if Path(p).is_dir():
            write_manifest(p, ep)
            unprotect_prev(p)  # the new manifest is down — the fallback
                               # clone has done its job


# --------------------------------------------------------- retention + sweep
def sweep_stale(folder: str | Path) -> List[str]:
    """Startup sweep of a checkpoint/run folder: delete orphaned write
    debris a crash can leave behind — ``*.tmp`` files (aux-sidecar /
    manifest / recorder tempfiles whose ``os.replace`` never ran) and
    uncommitted orbax tmp dirs. Returns (and logs) what was removed."""
    folder = Path(folder).absolute()
    removed: List[str] = []
    if not folder.is_dir():
        return removed
    for p in sorted(folder.glob("*.tmp")):
        if p.is_file():
            p.unlink()
            removed.append(p.name)
    for p in sorted(folder.glob(ORBAX_TMP_GLOB)):
        if p.is_dir():
            shutil.rmtree(p, ignore_errors=True)
            removed.append(p.name + "/")
    if removed:
        logger.warning("startup sweep of %s removed %d stale artifact(s): "
                       "%s", folder, len(removed), ", ".join(removed))
    return removed


class CheckpointManager:
    """Per-run-folder policy around the plain save/load functions above:
    integrity manifests (immediate for sync saves, deferred-until-committed
    for async ones), retention GC, and the startup sweep. Pure host-side
    bookkeeping — it never touches the device."""

    def __init__(self, folder: Optional[Path], *, keep_last_n: int = 0,
                 manifests: bool = True):
        self.folder = Path(folder) if folder is not None else None
        self.keep_last_n = int(keep_last_n)
        self.manifests = bool(manifests)

    # ------------------------------------------------------------- manifests
    def prepare_overwrite(self, paths: List[Path], async_save: bool,
                          writer: bool = True) -> None:
        """Call BEFORE re-saving existing snapshot paths with force=True.
        For async saves, first land the in-flight commit and write the
        manifests it was owed — orbax serializes saves, so the upcoming
        enqueue would block on that commit anyway; waiting here moves the
        wait, it doesn't add one, and it means ``model_last`` carries an
        on-disk manifest between rounds (without this, a ``kill -9`` of a
        pipelined run with ``save_on_epochs: []`` would leave ZERO
        verified snapshots and auto-resume would restart from scratch).
        Then drop any still-queued manifests for the paths about to be
        replaced — they would describe dirs the new save deletes — and
        clone each verified snapshot to ``<name>.prev`` so a kill at ANY
        point of the upcoming save still leaves a verified resume point
        (:func:`protect_last`; the clone is dropped once the replacement's
        manifest lands, in :meth:`note_saved` / the flush). `writer` gates
        the filesystem mutations to one process, like the sidecar."""
        if not self.manifests or not writer:
            return
        if async_save and _pending_manifests:
            wait_for_async_saves()
        for p in paths:
            drop_queued_manifest(p)
            protect_last(p)

    def note_saved(self, paths: List[Path], epoch: int,
                   async_save: bool) -> None:
        """Call AFTER a round's snapshots (and their sidecars) are written.
        Sync saves get their manifests immediately. Async saves: manifests
        queued from *previous* rounds are now provably committed (this
        round's enqueue blocked until they landed — orbax serializes), so
        flush them, then queue this round's."""
        if not self.manifests:
            return
        if not async_save:
            for p in paths:
                write_manifest(p, epoch)
                unprotect_prev(p)  # replacement verified — drop the clone
            return
        flush_queued_manifests()
        for p in paths:
            queue_manifest(p, epoch)

    def flush_manifests(self) -> None:
        """End-of-run manifest flush; only valid after
        :func:`wait_for_async_saves` (which already calls this)."""
        if self.manifests:
            flush_queued_manifests()

    # ------------------------------------------------------------------ sweep
    def sweep(self) -> List[str]:
        return sweep_stale(self.folder) if self.folder is not None else []

    # --------------------------------------------------------------------- gc
    def gc(self) -> List[Path]:
        """Retention: with ``keep_last_n > 0``, delete per-epoch snapshots
        (``*.epoch_N`` + sidecar + manifest) beyond the newest N.
        ``model_last`` and the best-val snapshot are always retained, and
        snapshots with an in-flight async commit are skipped. Default
        (``keep_last_n: 0``) keeps everything — ``save_on_epochs`` lists
        are explicit user asks."""
        if self.keep_last_n <= 0 or self.folder is None:
            return []
        snaps = []
        for p in self.folder.iterdir():
            if not p.is_dir() or CORRUPT_SUFFIX in p.name:
                continue
            _, sep, tail = p.name.rpartition(".epoch_")
            if not sep or not tail.isdigit():
                continue
            snaps.append((int(tail), p))
        snaps.sort()
        doomed = [p for _, p in snaps[:-self.keep_last_n]
                  if str(p.absolute()) not in _pending_manifests]
        for p in doomed:
            shutil.rmtree(p, ignore_errors=True)
            for extra in (Path(str(p) + AUX_SUFFIX), manifest_path(p)):
                if extra.exists():
                    extra.unlink()
            telemetry.count("checkpoint/gc_removed")
        if doomed:
            logger.info("checkpoint GC (keep_last_n=%d) removed %s",
                        self.keep_last_n, [p.name for p in doomed])
        return doomed
