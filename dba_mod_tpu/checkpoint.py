"""Checkpoint/resume via orbax.

Reference parity (helper.py:51-57, :420-435; image_helper.py:56-67): the saved
unit is {model state, epoch, lr}; resume restores the global model, sets
start_epoch = saved_epoch + 1 and overwrites the config lr. The canonical use
is "pretrain clean to epoch N, then attack from the checkpoint"
(utils/cifar_params.yaml:68-69); `python -m dba_mod_tpu.main pretrain`
regenerates those clean models since the reference's Google-Drive artifacts
are external (SURVEY §5 checkpoint row).

Two deliberate improvements over the reference:

- **Async saves** (`async_save=True`): orbax's AsyncCheckpointer copies the
  state to host and commits in the background, so per-round checkpointing
  composes with round pipelining. Program order is preserved — a new save
  blocks until the previous commit finished — and `wait_for_async_saves()`
  must run before process exit / before reading a just-written file.
- **Full-state sidecar** (`save_aux_state`): the reference checkpoints only
  the model (helper.py:420-435) while FoolsGold's cross-round memory lives in
  a RAM-only dict (helper.py:545-549) — a mid-attack restart silently resets
  the defense. The sidecar carries FoolsGold memory, best-val loss, the
  host RNG streams and the JAX key, so a resumed run replays the
  uninterrupted trajectory exactly (tests/test_full_state_resume.py).
"""
from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from dba_mod_tpu.models import ModelVars
from dba_mod_tpu.utils import telemetry

AUX_SUFFIX = ".aux.pkl"

_async_ckptr = None


def _get_async_checkpointer():
    global _async_ckptr
    if _async_ckptr is None:
        import orbax.checkpoint as ocp
        _async_ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
    return _async_ckptr


def wait_for_async_saves() -> None:
    """Block until every in-flight async checkpoint commit has landed."""
    if _async_ckptr is not None:
        with telemetry.span("checkpoint/wait_async"):
            _async_ckptr.wait_until_finished()
            _async_ckptr.check_for_errors()


def save_checkpoint(path: str | Path, model_vars: ModelVars, epoch: int,
                    lr: float, *, async_save: bool = False) -> None:
    import orbax.checkpoint as ocp
    path = Path(path).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = {"params": model_vars.params,
               "batch_stats": model_vars.batch_stats,
               "epoch": np.asarray(epoch, np.int64),
               "lr": np.asarray(lr, np.float64)}
    # the async span covers only the enqueue (the commit runs in orbax's
    # background thread — checkpoint/wait_async is where it lands); the
    # sync span covers the whole write
    telemetry.count("checkpoint/saves")
    if async_save:
        with telemetry.span("checkpoint/save_async_enqueue"):
            _get_async_checkpointer().save(
                path, args=ocp.args.StandardSave(payload), force=True)
    else:
        with telemetry.span("checkpoint/save"):
            with ocp.StandardCheckpointer() as ckptr:
                ckptr.save(path, payload, force=True)


def load_checkpoint(path: str | Path,
                    like: ModelVars) -> Tuple[ModelVars, int, float]:
    import orbax.checkpoint as ocp
    path = Path(path).absolute()
    abstract = {"params": jax.tree_util.tree_map(np.asarray, like.params),
                "batch_stats": jax.tree_util.tree_map(np.asarray,
                                                      like.batch_stats),
                "epoch": np.asarray(0, np.int64),
                "lr": np.asarray(0, np.float64)}
    telemetry.count("checkpoint/loads")
    with telemetry.span("checkpoint/load"):
        with ocp.StandardCheckpointer() as ckptr:
            restored = ckptr.restore(path, abstract)
    mv = ModelVars(
        params=jax.tree_util.tree_map(jax.numpy.asarray, restored["params"]),
        batch_stats=jax.tree_util.tree_map(jax.numpy.asarray,
                                           restored["batch_stats"]))
    return mv, int(restored["epoch"]), float(restored["lr"])


# ----------------------------------------------------------- full-state aux
def save_aux_state(path: str | Path, aux: Dict[str, Any]) -> None:
    """Write the experiment sidecar next to an orbax checkpoint directory.

    `aux` holds host-side state only (numpy arrays / python scalars / RNG
    state tuples) — callers device_get anything device-resident first. The
    write is atomic (tmp + rename) so a crash mid-save leaves the previous
    sidecar intact, matching orbax's own commit discipline.
    """
    path = Path(str(path) + AUX_SUFFIX).absolute()
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(aux, f)
    tmp.replace(path)


def load_aux_state(path: str | Path) -> Optional[Dict[str, Any]]:
    """Read the sidecar written by `save_aux_state`; None when absent
    (e.g. resuming a pretrain-only checkpoint — model-only resume is the
    reference behavior and stays fully supported)."""
    path = Path(str(path) + AUX_SUFFIX).absolute()
    if not path.exists():
        return None
    with open(path, "rb") as f:
        return pickle.load(f)
