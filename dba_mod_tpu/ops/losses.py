"""Loss and norm functions used by the client step and evaluation.

Reference semantics preserved:
- per-batch cross entropy is the MEAN over the batch (torch F.cross_entropy
  default, image_train.py:85); with padded batches we mean over valid entries;
- the anomaly-evading blended loss is α·CE + (1-α)·‖w - w_global‖₂
  (image_train.py:87-90; note: the L2 *norm*, not its square);
- distance/global norms run over trainable parameters only — torch
  named_parameters excludes BN running stats but includes BN affine γ/β
  (helper.py:59-71, :110-123).
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def cross_entropy(logits: jax.Array, labels: jax.Array,
                  mask: jax.Array | None = None):
    """Mean cross entropy over valid entries. `logits` may already be
    log-probabilities (log_softmax is idempotent, matching the reference's
    MnistNet head — models/MnistNet.py:31)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(nll.dtype)
    denom = jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.sum(nll * mask) / denom


def cross_entropy_sum(logits: jax.Array, labels: jax.Array,
                      mask: jax.Array | None = None):
    """Summed cross entropy (reduction='sum'), used by the evaluation battery
    (test.py:21-22)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32),
                               axis=-1)[:, 0]
    if mask is not None:
        nll = nll * mask.astype(nll.dtype)
    return jnp.sum(nll)


def tree_dist_norm(params: Any, target_params: Any):
    """‖w - w_target‖₂ over a params pytree (helper.py:110-123).

    Gradient-safe at zero distance: on a client's first step w == w_global, and
    d√x/dx|₀ = ∞ would turn the blended loss's (1-α)·dist term into NaN via
    0·∞ even at α=1. The double-where pattern keeps the gradient exactly 0
    there."""
    sq = jax.tree_util.tree_reduce(
        lambda acc, leaves: acc + jnp.sum(jnp.square(leaves)),
        jax.tree_util.tree_map(lambda a, b: a - b, params, target_params),
        jnp.float32(0.0))
    safe = jnp.where(sq > 0.0, sq, 1.0)
    return jnp.where(sq > 0.0, jnp.sqrt(safe), 0.0)


def tree_global_norm(params: Any):
    """‖w‖₂ over a params pytree (helper.py:59-64)."""
    sq = jax.tree_util.tree_reduce(
        lambda acc, leaf: acc + jnp.sum(jnp.square(leaf)), params,
        jnp.float32(0.0))
    return jnp.sqrt(sq)


def blended_poison_loss(class_loss, dist_norm, alpha: float):
    """α·CE + (1-α)·distance (image_train.py:89-90). With the configs' α=1 the
    distance term vanishes but stays differentiable for α<1 runs."""
    return alpha * class_loss + (1.0 - alpha) * dist_norm
