"""Fused per-step state update: torch-SGD + validity select + FoolsGold
accumulation + BN select as ONE logical op over the whole client state.

Why: the client step updates ~60 parameter tensors per scan step; XLA emits
one elementwise kernel per leaf, and on TPU each small kernel pays a fixed
launch/ramp cost that dominates the narrow-model train phase (measured ~4 ms
of a ~13 ms step on the bench workload — see bench.py's phase report). The
math is embarrassingly fusable; XLA just has no horizontal-fusion pass for
it. A Pallas TPU kernel can read ALL the small leaves in one launch.

Shape problem: the client step is written per-client and vmapped over the
stacked clients axis (fl/rounds.py), and Pallas' automatic vmap rule blocks
per-lane (width-1 leading blocks), which the TPU lowering rejects for
non-aligned shapes. `jax.custom_batching.custom_vmap` solves it exactly: the
unbatched definition is the plain per-leaf jnp math (bit-identical to the
historical path, used for grad-free semantics and non-TPU backends), and the
batch rule receives the full stacked [C, ...] leaves and dispatches a few
multi-tensor Pallas kernels over them.

Semantics (must stay bit-exact with ops/sgd.py::sgd_step + the
jnp.where-based validity selects in fl/client.py):

    g'  = g + weight_decay * w
    m'  = momentum * m + g'
    w'  = w - lr * m'                      (lr per client, traced)
    out = where(valid, updated, old)       for w, m, fg (+= g), bn (new)

Used only when the clients axis is NOT mesh-sharded (GSPMD cannot partition
through a custom call); the mesh path keeps the per-leaf jnp form. No
reference counterpart — this is TPU-native machinery under the reference's
per-client `optimizer.step()` (image_train.py:220)."""
from __future__ import annotations

from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Total VMEM-resident bytes allowed per fused kernel (all inputs + outputs;
# grid=1, full-array blocks). v5e has ~16 MB of VMEM per core; sizes must be
# accounted in the TILED layout — a [10, 32] f32 occupies a full (8, 128)
# tile grid, 6.4× its logical bytes.
_VMEM_BUDGET = 6 * 1024 * 1024

# kind → (#inputs, #outputs) per leaf
_ARITY = {"sgd": (3, 2), "acc": (2, 1), "sel": (2, 1)}


def _ceil(a: int, b: int) -> int:
    return -(-a // b) * b


def _padded_size(shape) -> int:
    """Element count in TPU tiled layout: trailing two dims pad to (8, 128)."""
    if len(shape) < 2:
        return _ceil(int(np.prod(shape)) if shape else 1, 128)
    lead = 1
    for d in shape[:-2]:
        lead *= d
    return lead * _ceil(shape[-2], 8) * _ceil(shape[-1], 128)


def _leaf_bytes(kind: str, shape) -> int:
    n_in, n_out = _ARITY[kind]
    return (n_in + n_out) * _padded_size(shape) * 4  # f32


def _build_kernel(kinds: List[str], momentum: float, weight_decay: float):
    """Kernel over leaves in their NATURAL shapes — reshaping [C, ...] leaves
    to 2-D before the call would be a physical re-tiling copy on TPU (layout
    is tiled over the trailing dims), which costs more than the fusion wins.
    lr/valid arrive as [C, 1] and are re-broadcast per leaf rank in-kernel."""
    n_in = sum(_ARITY[k][0] for k in kinds)

    def kernel(*refs):
        lr0 = refs[0][...]          # [C, 1]
        keep0 = refs[1][...] == 1.0  # [C, 1] bool
        ins = refs[2:2 + n_in]
        outs = refs[2 + n_in:]

        def ranked(v, rank):
            return v.reshape((v.shape[0],) + (1,) * (rank - 1))

        i = o = 0
        for kind in kinds:
            rank = ins[i].shape and len(ins[i].shape)
            lr = ranked(lr0, rank)
            keep = ranked(keep0, rank)
            if kind == "sgd":
                w, g, m = ins[i][...], ins[i + 1][...], ins[i + 2][...]
                i += 3
                g2 = g + weight_decay * w
                m2 = momentum * m + g2
                w2 = w - lr * m2
                outs[o][...] = jnp.where(keep, w2, w)
                outs[o + 1][...] = jnp.where(keep, m2, m)
                o += 2
            elif kind == "acc":
                f, g = ins[i][...], ins[i + 1][...]
                i += 2
                outs[o][...] = jnp.where(keep, f + g, f)
                o += 1
            else:  # sel
                new, old = ins[i][...], ins[i + 1][...]
                i += 2
                outs[o][...] = jnp.where(keep, new, old)
                o += 1

    return kernel


def _run_chunks(entries, lr2, valid2, momentum: float, weight_decay: float,
                interpret: bool):
    """entries: list of (kind, [in arrays [C, d]]). Greedy-packs into
    VMEM-budget chunks, one pallas_call per chunk. Returns flat output list
    aligned with entries."""
    from jax.experimental import pallas as pl

    outputs: List[Any] = [None] * len(entries)
    chunk: List[int] = []
    used = 0

    def flush():
        nonlocal chunk, used
        if not chunk:
            return
        kinds = [entries[j][0] for j in chunk]
        ins = [a for j in chunk for a in entries[j][1]]
        out_shape = []
        for j in chunk:
            kind, arrs = entries[j]
            out_shape += [jax.ShapeDtypeStruct(arrs[0].shape, arrs[0].dtype)
                          ] * _ARITY[kind][1]
        outs = pl.pallas_call(
            _build_kernel(kinds, momentum, weight_decay),
            out_shape=out_shape, interpret=interpret,
        )(lr2, valid2, *ins)
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        o = 0
        for j in chunk:
            n_out = _ARITY[entries[j][0]][1]
            outputs[j] = tuple(outs[o:o + n_out])
            o += n_out
        chunk, used = [], 0

    for j, (kind, arrs) in enumerate(entries):
        nbytes = _leaf_bytes(kind, arrs[0].shape)
        if used + nbytes > _VMEM_BUDGET:
            flush()
        chunk.append(j)
        used += nbytes
    flush()
    return outputs


def make_fused_step_update(momentum: float, weight_decay: float,
                           fg_enabled: bool, use_pallas: bool,
                           interpret: bool = False):
    """Returns fused(lr, valid, params, grads, mom, fg, bn_new, bn_old) ->
    (new_params, new_mom, new_fg, new_bn). `fg` may be an empty tree when
    FoolsGold is off. When use_pallas is False, returns the plain per-leaf
    jnp implementation (today's exact path, traced through vmap as before)."""

    def reference(lr, valid, params, grads, mom, fg, bn_new, bn_old):
        def upd(w, g, m):
            g2 = g + weight_decay * w
            m2 = momentum * m + g2
            return w - lr * m2, m2

        pairs = jax.tree_util.tree_map(upd, params, grads, mom)
        is_pair = lambda t: isinstance(t, tuple)
        w2 = jax.tree_util.tree_map(lambda t: t[0], pairs, is_leaf=is_pair)
        m2 = jax.tree_util.tree_map(lambda t: t[1], pairs, is_leaf=is_pair)
        sel = lambda a, b: jax.tree_util.tree_map(
            lambda x, y: jnp.where(valid, x, y), a, b)
        new_fg = (sel(jax.tree_util.tree_map(jnp.add, fg, grads), fg)
                  if fg_enabled else fg)
        return sel(w2, params), sel(m2, mom), new_fg, sel(bn_new, bn_old)

    if not use_pallas:
        return reference

    from jax import custom_batching

    fused = custom_batching.custom_vmap(reference)

    @fused.def_vmap
    def _batch_rule(axis_size, in_batched, lr, valid, params, grads, mom, fg,
                    bn_new, bn_old):
        # every operand is batched on axis 0 in the client step; broadcast
        # any stragglers so the kernel sees uniform [C, ...] leaves
        def bcast(tree, b_tree):
            return jax.tree_util.tree_map(
                lambda l, b: l if b else jnp.broadcast_to(
                    l[None], (axis_size,) + l.shape), tree, b_tree)

        (lr, valid, params, grads, mom, fg, bn_new, bn_old) = (
            bcast(t, b) for t, b in zip(
                (lr, valid, params, grads, mom, fg, bn_new, bn_old),
                in_batched))
        C = axis_size
        lr2 = lr.reshape(C, 1).astype(jnp.float32)
        valid2 = valid.reshape(C, 1).astype(jnp.float32)

        p_leaves, p_def = jax.tree_util.tree_flatten(params)
        g_leaves = jax.tree_util.tree_leaves(grads)
        m_leaves = jax.tree_util.tree_leaves(mom)
        f_leaves, f_def = jax.tree_util.tree_flatten(fg)
        bnn_leaves, bn_def = jax.tree_util.tree_flatten(bn_new)
        bno_leaves = jax.tree_util.tree_leaves(bn_old)

        # natural shapes throughout — no reshapes (TPU re-tiling copies)
        entries: List[Tuple[str, List[Any]]] = []
        fallback: dict[int, Any] = {}
        order = []  # (kind tag, leaf index within its group)
        for i, (w, g, m) in enumerate(zip(p_leaves, g_leaves, m_leaves)):
            entries.append(("sgd", [w, g, m]))
            order.append(("p", i))
        if fg_enabled:
            for i, (f, g) in enumerate(zip(f_leaves, g_leaves)):
                entries.append(("acc", [f, g]))
                order.append(("f", i))
        for i, (bn, bo) in enumerate(zip(bnn_leaves, bno_leaves)):
            entries.append(("sel", [bn, bo]))
            order.append(("b", i))

        def rk(v, like):
            return v.reshape((C,) + (1,) * (like.ndim - 1))

        # Fallback to jnp for (a) leaves too big for a single-block kernel —
        # bandwidth-bound, nothing to win — and (b) rank>2 leaves: the launch
        # floor lives in the many tiny rank-2 BN/bias tensors, and
        # higher-rank full-array blocks both blow the tiled-VMEM budget and
        # exercise much less-travelled Mosaic lowering paths.
        big = [j for j, (k, a) in enumerate(entries)
               if a[0].ndim != 2
               or _leaf_bytes(k, a[0].shape) > _VMEM_BUDGET]
        for j in big:
            kind, arrs = entries[j]
            keep = rk(valid2, arrs[0]) == 1.0
            if kind == "sgd":
                w, g, m = arrs
                g2 = g + weight_decay * w
                m2 = momentum * m + g2
                w2 = w - rk(lr2, w) * m2
                fallback[j] = (jnp.where(keep, w2, w),
                               jnp.where(keep, m2, m))
            elif kind == "acc":
                f, g = arrs
                fallback[j] = (jnp.where(keep, f + g, f),)
            else:
                bn, bo = arrs
                fallback[j] = (jnp.where(keep, bn, bo),)
        small_entries = [e for j, e in enumerate(entries) if j not in fallback]
        small_out = _run_chunks(small_entries, lr2, valid2, momentum,
                                weight_decay, interpret)
        outs: List[Any] = []
        it = iter(small_out)
        for j in range(len(entries)):
            outs.append(fallback[j] if j in fallback else next(it))

        new_p, new_m = list(p_leaves), list(m_leaves)
        new_f = list(f_leaves)
        new_b = list(bnn_leaves)
        for (tag, i), out in zip(order, outs):
            if tag == "p":
                new_p[i], new_m[i] = out[0], out[1]
            elif tag == "f":
                new_f[i] = out[0]
            else:
                new_b[i] = out[0]
        result = (jax.tree_util.tree_unflatten(p_def, new_p),
                  jax.tree_util.tree_unflatten(p_def, new_m),
                  jax.tree_util.tree_unflatten(f_def, new_f),
                  jax.tree_util.tree_unflatten(bn_def, new_b))
        out_batched = jax.tree_util.tree_map(lambda _: True, result)
        return result, out_batched

    return fused
