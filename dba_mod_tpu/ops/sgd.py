"""SGD with torch semantics, as pure pytree functions.

The reference trains every client with ``torch.optim.SGD(lr, momentum,
weight_decay)`` created fresh each round (reference image_train.py:33-35,
loan_train.py:29-31, poison variants image_train.py:63-65), so momentum buffers
always start at zero within a round. torch's update rule (dampening=0,
nesterov=False) is::

    g   = grad + weight_decay * param        # coupled decay
    buf = momentum * buf + g
    param -= lr * buf

which differs from optax.sgd's decoupled-decay conventions, so we implement it
directly; `lr` may be a traced scalar, enabling per-client learning rates under
vmap.

Also here: the poison MultiStepLR schedule (reference image_train.py:66-68)
including torch's float-milestone quirk, and the LOAN adaptive poison-LR rule
(reference loan_train.py:71-75) as an in-graph function of backdoor accuracy.
"""
from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def sgd_init(params: Any) -> Any:
    """Zero momentum buffers shaped like `params`."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def sgd_step(params: Any, grads: Any, momentum_buf: Any, lr,
             momentum: float, weight_decay: float):
    """One torch-SGD step. Returns (new_params, new_momentum_buf)."""

    def upd(p, g, b):
        g = g + weight_decay * p
        b = momentum * b + g
        return p - lr * b, b

    flat = jax.tree_util.tree_map(upd, params, grads, momentum_buf)
    new_params = jax.tree_util.tree_map(lambda t: t[0], flat,
                                        is_leaf=lambda t: isinstance(t, tuple))
    new_buf = jax.tree_util.tree_map(lambda t: t[1], flat,
                                     is_leaf=lambda t: isinstance(t, tuple))
    return new_params, new_buf


def _milestone_hits(milestones: Sequence[float]) -> list[int]:
    """torch MultiStepLR stores milestones in a Counter keyed by the raw float;
    an integer epoch only matches a float milestone when the float is exactly
    integral (hash equality: 2 == 2.0). E.g. internal_poison_epochs=6 gives
    milestones [1.2000000000000002, 4.800000000000001] which NEVER fire, while
    E=10 gives [2.0, 8.0] which do — reference image_train.py:66-68 inherits
    this quirk and we reproduce it."""
    hits = []
    for m in milestones:
        if float(m) == int(m):
            hits.append(int(m))
    return hits


def multistep_lr_array(num_epochs: int, milestones: Sequence[float],
                       gamma: float = 0.1, step_before: bool = False) -> np.ndarray:
    """Per-internal-epoch LR *multipliers* (relative to base lr), length
    `num_epochs`, for 1-based internal epochs.

    step_before=False (image, reference image_train.py:118-119): scheduler.step()
    runs at the END of each internal epoch, so epoch i uses
    gamma^|{m <= i-1}|.
    step_before=True (LOAN, reference loan_train.py:90-92): scheduler.step()
    runs at the TOP of each internal epoch, so epoch i uses gamma^|{m <= i}|.
    """
    hits = _milestone_hits(milestones)
    out = np.empty((max(num_epochs, 1),), np.float32)
    for i in range(1, max(num_epochs, 1) + 1):
        bound = i if step_before else i - 1
        k = sum(1 for m in hits if m <= bound)
        out[i - 1] = gamma ** k
    return out


def poison_multistep_lr_array(internal_poison_epochs: int, gamma: float = 0.1,
                              step_before: bool = False) -> np.ndarray:
    """The reference's poison schedule: milestones at {0.2, 0.8}·E
    (image_train.py:66-68, loan_train.py:83-85)."""
    e = internal_poison_epochs
    return multistep_lr_array(e, [0.2 * e, 0.8 * e], gamma, step_before)


def loan_adaptive_poison_lr(base_poison_lr, backdoor_acc, baseline: bool):
    """LOAN poison-LR decay by current backdoor accuracy (loan_train.py:71-75):
    acc>20 → lr/5, additionally acc>60 → lr/10 (cumulative /50). `backdoor_acc`
    is a traced percentage scalar; returns a traced lr."""
    if baseline:
        return jnp.asarray(base_poison_lr, jnp.float32)
    lr = jnp.asarray(base_poison_lr, jnp.float32)
    lr = jnp.where(backdoor_acc > 20.0, lr / 5.0, lr)
    lr = jnp.where(backdoor_acc > 60.0, lr / 10.0, lr)
    return lr
