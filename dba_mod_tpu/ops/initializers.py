"""Parameter initializers matching torch defaults.

The reference models rely on two init regimes:
- torch's default ``nn.Conv2d``/``nn.Linear`` init: kaiming_uniform(a=sqrt(5)) for
  the weight, which reduces to U(-1/sqrt(fan_in), 1/sqrt(fan_in)), and the same
  bound for the bias (used by MnistNet.py, resnet_cifar.py, loan_model.py);
- explicit kaiming_normal(fan_out, relu) + BN(weight=1, bias=0)
  (resnet_tinyimagenet.py:158-163).

Matching the init distribution keeps our training curves statistically comparable
to the reference's.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import random
from jax.nn.initializers import variance_scaling

# U(-1/sqrt(fan_in), 1/sqrt(fan_in)): variance_scaling draws
# U(-sqrt(3*scale/fan_in), +sqrt(3*scale/fan_in)); scale=1/3 gives the torch bound.
torch_kaiming_uniform = variance_scaling(1.0 / 3.0, "fan_in", "uniform")

# kaiming_normal(mode=fan_out, nonlinearity=relu): N(0, sqrt(2/fan_out)).
kaiming_normal_fan_out = variance_scaling(2.0, "fan_out", "normal")


def torch_bias_init(fan_in: int):
    """torch Linear/Conv bias default: U(-1/sqrt(fan_in), 1/sqrt(fan_in))."""
    bound = 1.0 / (fan_in ** 0.5) if fan_in > 0 else 0.0

    def init(key, shape, dtype=jnp.float32):
        return random.uniform(key, shape, dtype, minval=-bound, maxval=bound)

    return init
