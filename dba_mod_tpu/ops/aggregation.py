"""Server aggregation rules as pure jnp programs over *stacked* client updates.

Client updates arrive as a pytree whose leaves have a leading `clients` axis
(the TPU-native replacement for the reference's per-client Python dicts,
helper.py:193-231). Three rules, matching reference semantics:

- FedAvg (`average_shrink_models`, helper.py:240-257): global += η/no_models ·
  Σ_c Δ_c, applied to EVERY state entry (weights and BN stats alike), optional
  DP gaussian noise (helper.py:186-191, :253-254). Note the reference divides
  by `no_models`, not by Σ samples — unweighted; kept for parity.
- RFA geometric median (`geometric_median_update`, helper.py:295-373):
  Weiszfeld iterations with sample-count alphas, ftol early stop, oracle-call
  count, optional update-norm rejection. The reference crashes when Weiszfeld
  converges at iteration 0 (`wv=None` → `wv.cpu()`, helper.py:371); we fix it
  by always reporting the most recent weights.
- FoolsGold (`foolsgold_update`, helper.py:259-293 + class FoolsGold
  :527-607): cosine-similarity reweighting over the second-to-last trainable
  tensor's accumulated gradient, per-participant historical memory, pardoning,
  logit re-weighting, applied through one torch-SGD step on trainable params
  only.

Every rule additionally accepts a survivor mask ([C] — clients screened out
by the server's quarantine pass, fl/rounds.py): FedAvg renormalizes over
survivors, Weiszfeld zeroes the excluded clients' weights, FoolsGold masks
the excluded similarity rows and memory writes. Excluded payload rows are
where-zeroed FIRST (`survivor_sanitize`) so NaN/Inf quarantined payloads
cannot leak through `0 * NaN = NaN` arithmetic. With an all-ones mask every
masked rule reduces exactly (bitwise for FedAvg, to f32 identity for the
rest) to the dense rule — tests/test_faults.py pins this.

Beyond the reference's three rules, the wider defense grid (ROADMAP item 3)
adds three classical Byzantine-robust rules under the SAME survivor-mask
contract, so they compose with the quarantine screen and with the async
buffered merge (fl/async_rounds.py) unchanged:

- Krum / multi-Krum (`krum_update`, Blanchard et al., NeurIPS 2017): score
  each client by the sum of squared distances to its n−f−2 nearest peers,
  apply η · mean of the m lowest-scoring updates (m=1 is classic Krum).
- Coordinate-wise trimmed mean (`trimmed_mean_update`, Yin et al., ICML
  2018): per coordinate, drop the ⌊β·n⌋ smallest and largest survivor
  values and average the rest, apply with η.
- Coordinate-wise median (`coordinate_median_update`, Yin et al., ICML
  2018): per-coordinate survivor median, applied with η.

These three have no reference counterpart (no parity constraint); the
masked form IS the rule — mask=None runs the identical program with an
all-ones mask, so dense-reduction equivalence is structural and the tests
pin it against independent numpy oracles (tests/test_aggregation.py).
"""
from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from dba_mod_tpu.ops.sgd import sgd_step


# ------------------------------------------------------------------- utilities
def flatten_stacked(tree: Any) -> jax.Array:
    """Flatten a client-stacked pytree ([C, ...] leaves) to a [C, P] matrix."""
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate(
        [l.reshape(l.shape[0], -1).astype(jnp.float32) for l in leaves], axis=1)


def unflatten_like(vec: jax.Array, tree: Any) -> Any:
    """Inverse of :func:`flatten_stacked` for a single [P] vector, shaped like
    one (un-stacked) element of `tree`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out, off = [], 0
    for l in leaves:
        shape = l.shape[1:]
        size = 1
        for s in shape:
            size *= s
        out.append(vec[off:off + size].reshape(shape).astype(l.dtype))
        off += size
    return jax.tree_util.tree_unflatten(treedef, out)


def _bc_mask(mask: jax.Array, leaf: jax.Array) -> jax.Array:
    """[C] mask → [C, 1, ...] broadcast against a client-stacked leaf."""
    return mask.reshape((mask.shape[0],) + (1,) * (leaf.ndim - 1))


def survivor_sanitize(tree: Any, mask: jax.Array) -> Any:
    """Where-zero the masked-out clients' rows of a stacked payload.

    Quarantined payloads may be NaN/Inf; plain `mask * leaf` would propagate
    them (0 · NaN = NaN), so exclusion must select, not multiply. With an
    all-ones mask this returns the input values bitwise unchanged."""
    return jax.tree_util.tree_map(
        lambda l: jnp.where(_bc_mask(mask > 0, l), l, jnp.zeros((), l.dtype)),
        tree)


def dp_noise_like(rng: jax.Array, tree: Any, sigma: float) -> Any:
    """Gaussian DP noise per state entry (helper.py:186-191)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [jax.random.normal(k, l.shape, jnp.float32) * sigma
              for k, l in zip(keys, leaves)]
    return jax.tree_util.tree_unflatten(treedef, noised)


# --------------------------------------------------------------------- FedAvg
def fedavg_update(global_state: Any, stacked_deltas: Any, eta: float,
                  no_models: int, dp_sigma: float = 0.0,
                  rng: jax.Array | None = None) -> Any:
    """helper.py:240-257. `global_state` is the full model state (params + BN
    stats); `stacked_deltas` has a leading clients axis over the same tree."""
    scale = eta / no_models

    def upd(g, d):
        return (g + scale * jnp.sum(d, axis=0).astype(g.dtype)).astype(g.dtype)

    new_state = jax.tree_util.tree_map(upd, global_state, stacked_deltas)
    if dp_sigma and rng is not None:
        noise = dp_noise_like(rng, new_state, dp_sigma)
        new_state = jax.tree_util.tree_map(lambda s, n: s + n.astype(s.dtype),
                                           new_state, noise)
    return new_state


def fedavg_update_masked(global_state: Any, stacked_deltas: Any, eta: float,
                         no_models: int, mask: jax.Array,
                         counted: jax.Array, dp_sigma: float = 0.0,
                         rng: jax.Array | None = None) -> Any:
    """FedAvg renormalized over the survivor mask.

    Dense FedAvg divides by the static `no_models`; here the divisor drops
    one for every *counted* client the mask excludes (inert mesh-padding
    lanes — `counted` False — contribute zero deltas and never move the
    divisor, preserving the reference's static-divisor semantics). The scale
    is written as `(eta/no_models) · (no_models/divisor)` so an all-ones
    mask yields the dense rule's exact python-float scale — bitwise
    equivalence, not just tolerance."""
    deltas = survivor_sanitize(stacked_deltas, mask)
    excluded = jnp.sum((counted > 0) & ~(mask > 0))
    divisor = jnp.maximum(jnp.float32(no_models) - excluded, 1.0)
    ratio = jnp.float32(no_models) / divisor
    scale = (eta / no_models) * ratio

    def upd(g, d):
        return (g + scale * jnp.sum(d, axis=0).astype(g.dtype)).astype(g.dtype)

    new_state = jax.tree_util.tree_map(upd, global_state, deltas)
    if dp_sigma and rng is not None:
        noise = dp_noise_like(rng, new_state, dp_sigma)
        new_state = jax.tree_util.tree_map(lambda s, n: s + n.astype(s.dtype),
                                           new_state, noise)
    return new_state


# ------------------------------------------------------------- RFA / Weiszfeld
class RfaResult(NamedTuple):
    new_state: Any
    num_oracle_calls: jax.Array   # int32
    is_updated: jax.Array         # bool (norm rejection)
    wv: jax.Array                 # [C] final Weiszfeld weights
    distances: jax.Array          # [C] ‖median - Δ_c‖ (reference's out-alphas)
    nbt_median: jax.Array         # f32 scalar — the (truncated-int-valued)
                                  # `num_batches_tracked` entry of the median


def geometric_median_update(global_state: Any, stacked_deltas: Any,
                            num_samples: jax.Array, eta: float,
                            maxiter: int = 10, eps: float = 1e-5,
                            ftol: float = 1e-6,
                            max_update_norm: float | None = None,
                            dp_sigma: float = 0.0,
                            rng: jax.Array | None = None,
                            nbt_deltas: jax.Array | None = None,
                            n_bn: int = 0,
                            mask: jax.Array | None = None) -> RfaResult:
    """Weiszfeld geometric median of client deltas (helper.py:295-373).

    Runs the full `maxiter` iterations with a `done` mask emulating the
    reference's ftol break — identical numerics, static XLA control flow.

    `nbt_deltas` [C] / `n_bn`: the per-client `num_batches_tracked` deltas
    and the number of BN layers. The reference's client updates are full
    state_dicts, so the int64 batch counters participate in every Weiszfeld
    quantity (l2dist / objective / update-norm, helper.py:376-392) — with
    Dirichlet partitions the per-client counter deltas differ (≈ local step
    counts, ×γ for model-replacement clients), which measurably shifts the
    weights on BN models. The median's counter entry is truncated PER CLIENT
    contribution (weighted_average_oracle's `temp.type_as(data)` int cast,
    helper.py:410-415). The counter's effect on the APPLIED update is nil in
    every runnable reference config: on torch ≥1.5 `data.add_(float)` into
    int64 raises, and on the paper-era torch ≤1.4 the `median * eta` scalar
    multiply truncates eta<1 to 0 — the global counter is frozen either way,
    so this function folds the counter into the geometry only and reports
    `nbt_median` for the record.

    `mask` ([C], optional): survivor mask from the quarantine screen.
    Excluded clients get zero Weiszfeld weight at every iteration (their
    alphas are zeroed before normalization) and their point rows are
    where-zeroed so non-finite quarantined payloads cannot poison the
    distance geometry. mask=None (or all-ones) is the dense rule.
    """
    if mask is not None:
        stacked_deltas = survivor_sanitize(stacked_deltas, mask)
    points = flatten_stacked(stacked_deltas)                    # [C, P]
    alphas = num_samples.astype(jnp.float32)
    if mask is not None:
        alphas = alphas * mask.astype(jnp.float32)
    alphas = alphas / jnp.sum(alphas)
    nbt = (jnp.asarray(nbt_deltas, jnp.float32) if nbt_deltas is not None
           else jnp.zeros((points.shape[0],), jnp.float32))
    if mask is not None:
        nbt = nbt * mask.astype(jnp.float32)
    nbf = float(n_bn) if nbt_deltas is not None else 0.0

    def wavg(w):
        wn = w / jnp.sum(w)
        # per-client truncation of the counter contribution = the
        # reference's per-point `type_as(int64)` cast before accumulation
        return wn @ points, jnp.sum(jnp.trunc(wn * nbt))        # [P], scalar

    def dists(m, mn):
        sq = jnp.sum(jnp.square(points - m[None, :]), axis=1)
        return jnp.sqrt(sq + nbf * jnp.square(nbt - mn))

    def objective(m, mn):
        return jnp.sum(alphas * dists(m, mn))

    median0, nbt0 = wavg(alphas)
    obj0 = objective(median0, nbt0)

    def body(carry, _):
        median, nbt_med, obj, wv, done, calls = carry
        dist = dists(median, nbt_med)
        weights = alphas / jnp.maximum(eps, dist)
        weights = weights / jnp.sum(weights)
        new_median, new_nbt = wavg(weights)
        new_obj = objective(new_median, new_nbt)
        converged = jnp.abs(obj - new_obj) < ftol * new_obj
        step_done = done | converged
        # The reference records wv only on non-breaking iterations
        # (helper.py:352) and crashes when none happened; we instead always
        # keep the latest weights (the documented wv=None fix, SURVEY §7.2.8).
        median = jnp.where(done, median, new_median)
        nbt_med = jnp.where(done, nbt_med, new_nbt)
        obj = jnp.where(done, obj, new_obj)
        wv = jnp.where(done, wv, weights)
        calls = calls + jnp.where(done, 0, 1)
        return (median, nbt_med, obj, wv, step_done, calls), None

    init = (median0, nbt0, obj0, alphas, jnp.asarray(False), jnp.int32(1))
    (median, nbt_med, _obj, wv, _done, calls), _ = jax.lax.scan(
        body, init, None, length=maxiter)

    distances = dists(median, nbt_med)
    update_norm = jnp.sqrt(jnp.sum(jnp.square(median))
                           + nbf * jnp.square(nbt_med))
    is_updated = (jnp.asarray(True) if max_update_norm is None
                  else update_norm < max_update_norm)

    median_tree = unflatten_like(median * eta, stacked_deltas)
    if dp_sigma and rng is not None:
        noise = dp_noise_like(rng, median_tree, dp_sigma)
        median_tree = jax.tree_util.tree_map(
            lambda m, n: m + n.astype(m.dtype), median_tree, noise)

    new_state = jax.tree_util.tree_map(
        lambda g, u: jnp.where(is_updated, g + u.astype(g.dtype), g),
        global_state, median_tree)
    return RfaResult(new_state, calls, is_updated, wv, distances, nbt_med)


# ----------------------------------------------------------------- FoolsGold
class FoolsGoldState(NamedTuple):
    """Cross-round per-participant gradient memory (helper.py:545-549), keyed
    by participant id instead of the reference's name-keyed dict."""
    memory: jax.Array  # [num_participants, grad_len] f32


def foolsgold_init(num_participants: int, grad_len: int) -> FoolsGoldState:
    return FoolsGoldState(memory=jnp.zeros((num_participants, grad_len),
                                           jnp.float32))


def foolsgold_weights(feature_grads: jax.Array,
                      mask: jax.Array | None = None
                      ) -> Tuple[jax.Array, jax.Array]:
    """The FoolsGold re-weighting (helper.py:574-607) on a [C, L] gradient
    matrix. Returns (wv [C], alpha [C]).

    `mask` ([C], optional): survivor mask. Excluded rows are where-zeroed
    before the cosine matrix (a NaN row would poison every similarity) and
    their wv is zeroed ahead of the max-normalization so a quarantined
    client can neither receive nor distort aggregation weight. mask=None
    (or all-ones) is the dense rule."""
    eps = 1e-12
    if mask is not None:
        feature_grads = jnp.where(mask[:, None] > 0, feature_grads,
                                  jnp.zeros((), feature_grads.dtype))
    norms = jnp.linalg.norm(feature_grads, axis=1)
    normed = feature_grads / jnp.maximum(norms, eps)[:, None]
    n = feature_grads.shape[0]
    cs = normed @ normed.T - jnp.eye(n)

    maxcs = jnp.max(cs, axis=1)
    # pardoning (helper.py:584-589): cs[i,j] *= maxcs[i]/maxcs[j] when
    # maxcs[i] < maxcs[j]
    ratio = maxcs[:, None] / maxcs[None, :]
    pardon = jnp.where(maxcs[:, None] < maxcs[None, :], ratio, 1.0)
    pardon = pardon * (1.0 - jnp.eye(n)) + jnp.eye(n)
    cs = cs * pardon

    row_max = jnp.max(cs, axis=1)
    wv = 1.0 - row_max
    wv = jnp.clip(wv, 0.0, 1.0)
    alpha = row_max

    if mask is not None:
        # zero excluded rows BEFORE the max-normalization: a zeroed feature
        # row has no similarity to anyone (wv = 1) and would otherwise both
        # keep full weight and deflate every survivor's normalized weight
        wv = wv * mask.astype(wv.dtype)
    wv = wv / jnp.max(wv)
    wv = jnp.where(wv == 1.0, 0.99, wv)
    logit = jnp.log(wv / (1.0 - wv)) + 0.5
    # reference: wv[(np.isinf(wv) + wv > 1)] = 1; wv[wv < 0] = 0
    # (bool-add precedence quirk: (isinf + wv) > 1 — helper.py:603)
    inf_mask = jnp.isinf(logit).astype(logit.dtype)
    logit = jnp.where(inf_mask + logit > 1.0, 1.0, logit)
    logit = jnp.where(logit < 0.0, 0.0, logit)
    return logit, alpha


class FoolsGoldResult(NamedTuple):
    new_params: Any
    new_fg_state: FoolsGoldState
    wv: jax.Array
    alpha: jax.Array


def foolsgold_update(global_params: Any, stacked_grads: Any,
                     feature_grads: jax.Array, participant_ids: jax.Array,
                     fg_state: FoolsGoldState, eta: float, lr: float,
                     momentum: float, weight_decay: float,
                     use_memory: bool = True,
                     mask: jax.Array | None = None) -> FoolsGoldResult:
    """helper.py:259-293 + FoolsGold.aggregate_gradients (:534-572).

    `stacked_grads`: per-client accumulated gradients over trainable params
    ([C, ...] leaves, from the client step's grad accumulation —
    image_train.py:94-100). `feature_grads`: [C, L] flattened gradient of the
    similarity layer (the reference's `client_grads[i][-2]`). Only trainable
    params are updated; BN stats are untouched (the reference steps an
    optimizer over named_parameters only).

    `mask` ([C], optional): survivor mask. Excluded clients' grads are
    where-zeroed, their similarity rows are masked (see
    :func:`foolsgold_weights`), and — critically — their feature gradients
    are NOT written into the cross-round memory: a quarantined NaN payload
    must not poison the defense's history. mask=None (or all-ones) is the
    dense rule.
    """
    if mask is not None:
        stacked_grads = survivor_sanitize(stacked_grads, mask)
        feature_grads = jnp.where(mask[:, None] > 0, feature_grads,
                                  jnp.zeros((), feature_grads.dtype))
    memory = fg_state.memory.at[participant_ids].add(feature_grads)
    current = memory[participant_ids] if use_memory else feature_grads
    wv, alpha = foolsgold_weights(current, mask=mask)

    num_clients = feature_grads.shape[0]

    def agg(leaf):  # [C, ...] -> [...]
        w = wv.reshape((num_clients,) + (1,) * (leaf.ndim - 1))
        return jnp.sum(w * leaf.astype(jnp.float32), axis=0) / num_clients

    agg_grads = jax.tree_util.tree_map(agg, stacked_grads)
    # Apply via one fresh torch-SGD step with grad = η·agg (helper.py:278-290);
    # fresh momentum buffers are zero, so momentum is a no-op.
    scaled = jax.tree_util.tree_map(lambda g: (eta * g), agg_grads)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, global_params)
    new_params, _ = sgd_step(global_params, scaled, zeros, lr, momentum,
                             weight_decay)
    return FoolsGoldResult(new_params, FoolsGoldState(memory), wv, alpha)


# ------------------------------------------------------- Krum / multi-Krum
# Sentinels for the masked geometry: finite (inf-free) so a degenerate
# survivor set still sorts deterministically — an excluded client's score
# (_EXCLUDED) always exceeds any survivor's, even the 1-survivor case whose
# score is a sum of _FAR pair distances. Both fit comfortably in f32.
_FAR = jnp.float32(1e30)       # pair distance to/from an excluded client
_EXCLUDED = jnp.float32(1e35)  # score of an excluded client


class KrumResult(NamedTuple):
    new_state: Any
    wv: jax.Array      # [C] applied weights: 1/m_eff for selected, else 0
    scores: jax.Array  # [C] Krum scores (_EXCLUDED for masked-out clients)


def _ones_mask(tree: Any) -> jax.Array:
    leaf = jax.tree_util.tree_leaves(tree)[0]
    return jnp.ones((leaf.shape[0],), jnp.float32)


def krum_update(global_state: Any, stacked_deltas: Any, eta: float,
                num_selected: int, byz_f: int,
                mask: jax.Array | None = None, dp_sigma: float = 0.0,
                rng: jax.Array | None = None) -> KrumResult:
    """Krum / multi-Krum (Blanchard et al., NeurIPS 2017) over survivors.

    score_i = Σ of the n−f−2 smallest squared distances from client i to the
    other survivors (n = survivor count, f = `byz_f`); the `num_selected`
    lowest-scoring survivors are averaged and applied as η · mean — m=1 is
    classic Krum, m>1 multi-Krum. The neighbor count is clipped to
    [1, n−1] so undersized survivor sets (n < f+3) degrade to
    nearest-neighbor scoring instead of an invalid slice.

    `mask` ([C], optional): survivor-mask contract — excluded rows are
    where-zeroed, their pair distances pinned to a far sentinel (never a
    nearest neighbor), their scores pinned above every survivor's, and the
    selection size shrinks to min(num_selected, n). mask=None runs the same
    program with an all-ones mask (dense reduction is structural)."""
    if mask is None:
        mask_f = _ones_mask(stacked_deltas)
    else:
        mask_f = (mask > 0).astype(jnp.float32)
        stacked_deltas = survivor_sanitize(stacked_deltas, mask)
    pts = flatten_stacked(stacked_deltas)                        # [C, P]
    C = pts.shape[0]
    sq_norms = jnp.sum(jnp.square(pts), axis=1)                  # [C]
    gram = pts @ pts.T
    d2 = sq_norms[:, None] + sq_norms[None, :] - 2.0 * gram
    d2 = jnp.maximum(d2, 0.0)
    alive = mask_f > 0
    valid_pair = (alive[:, None] & alive[None, :]
                  & ~jnp.eye(C, dtype=bool))
    d2 = jnp.where(valid_pair, d2, _FAR)
    n_alive = jnp.sum(mask_f)
    # n − f − 2 closest peers, clipped to the survivors actually available
    nb = jnp.clip(n_alive - byz_f - 2.0, 1.0,
                  jnp.maximum(n_alive - 1.0, 1.0)).astype(jnp.int32)
    d2_sorted = jnp.sort(d2, axis=1)                             # [C, C]
    near = jnp.arange(C)[None, :] < nb                           # [C, C]
    scores = jnp.sum(jnp.where(near, d2_sorted, 0.0), axis=1)
    scores = jnp.where(alive, scores, _EXCLUDED)
    m_eff = jnp.clip(jnp.int32(num_selected), 1,
                     jnp.maximum(n_alive.astype(jnp.int32), 1))
    rank = jnp.argsort(jnp.argsort(scores))                      # stable
    sel = (rank < m_eff) & alive
    wv = sel.astype(jnp.float32) / m_eff.astype(jnp.float32)

    def upd(g, d):
        chosen = jnp.sum(_bc_mask(wv, d) * d.astype(jnp.float32), axis=0)
        return (g + eta * chosen.astype(g.dtype)).astype(g.dtype)

    new_state = jax.tree_util.tree_map(upd, global_state, stacked_deltas)
    if dp_sigma and rng is not None:
        noise = dp_noise_like(rng, new_state, dp_sigma)
        new_state = jax.tree_util.tree_map(lambda s, n: s + n.astype(s.dtype),
                                           new_state, noise)
    return KrumResult(new_state, wv, scores)


# ------------------------------------- coordinate-wise trimmed mean / median
class CoordwiseResult(NamedTuple):
    new_state: Any
    wv: jax.Array  # [C] uniform survivor weights (the recorded per-client
                   # contribution; coordinate-wise rules have no single
                   # per-client scalar weight)


def _sorted_survivor_columns(stacked_deltas: Any,
                             mask_f: jax.Array) -> Tuple[jax.Array,
                                                         jax.Array]:
    """Columns of the [C, P] survivor matrix sorted ascending with excluded
    rows pushed past the survivors (+inf), plus the survivor count. Rows
    [0, n) of each sorted column are exactly the survivor values."""
    pts = flatten_stacked(stacked_deltas)
    pts = jnp.where(mask_f[:, None] > 0, pts, jnp.inf)
    return jnp.sort(pts, axis=0), jnp.sum(mask_f)


def trimmed_mean_update(global_state: Any, stacked_deltas: Any, eta: float,
                        beta: float, mask: jax.Array | None = None,
                        dp_sigma: float = 0.0,
                        rng: jax.Array | None = None) -> CoordwiseResult:
    """Coordinate-wise β-trimmed mean (Yin et al., ICML 2018): per
    coordinate, drop the k = ⌊β·n⌋ smallest and k largest survivor values
    (k clipped so at least one value remains) and average the rest; apply
    the trimmed mean with η. Survivor-mask contract as in
    :func:`krum_update`."""
    if mask is None:
        mask_f = _ones_mask(stacked_deltas)
    else:
        mask_f = (mask > 0).astype(jnp.float32)
        stacked_deltas = survivor_sanitize(stacked_deltas, mask)
    pts_sorted, n_alive = _sorted_survivor_columns(stacked_deltas, mask_f)
    n_i = n_alive.astype(jnp.int32)
    k = jnp.minimum(jnp.floor(beta * n_alive).astype(jnp.int32),
                    (n_i - 1) // 2)
    row = jnp.arange(pts_sorted.shape[0])[:, None]               # [C, 1]
    keep = (row >= k) & (row < n_i - k)
    kept = jnp.sum(jnp.where(keep, pts_sorted, 0.0), axis=0)
    count = jnp.maximum(n_alive - 2.0 * k.astype(jnp.float32), 1.0)
    mean_vec = kept / count                                      # [P]
    update_tree = unflatten_like(mean_vec * eta, stacked_deltas)
    new_state = jax.tree_util.tree_map(
        lambda g, u: (g + u.astype(g.dtype)).astype(g.dtype),
        global_state, update_tree)
    if dp_sigma and rng is not None:
        noise = dp_noise_like(rng, new_state, dp_sigma)
        new_state = jax.tree_util.tree_map(lambda s, n: s + n.astype(s.dtype),
                                           new_state, noise)
    return CoordwiseResult(new_state, mask_f / jnp.maximum(n_alive, 1.0))


def coordinate_median_update(global_state: Any, stacked_deltas: Any,
                             eta: float, mask: jax.Array | None = None,
                             dp_sigma: float = 0.0,
                             rng: jax.Array | None = None) -> CoordwiseResult:
    """Coordinate-wise survivor median (Yin et al., ICML 2018), even counts
    averaging the two central values (numpy's convention); applied with η.
    Survivor-mask contract as in :func:`krum_update`."""
    if mask is None:
        mask_f = _ones_mask(stacked_deltas)
    else:
        mask_f = (mask > 0).astype(jnp.float32)
        stacked_deltas = survivor_sanitize(stacked_deltas, mask)
    pts_sorted, n_alive = _sorted_survivor_columns(stacked_deltas, mask_f)
    n_i = jnp.maximum(n_alive.astype(jnp.int32), 1)
    lo = (n_i - 1) // 2
    hi = n_i // 2
    P = pts_sorted.shape[1]
    lo_vals = jnp.take_along_axis(
        pts_sorted, jnp.full((1, P), lo, jnp.int32), axis=0)[0]
    hi_vals = jnp.take_along_axis(
        pts_sorted, jnp.full((1, P), hi, jnp.int32), axis=0)[0]
    med = 0.5 * (lo_vals + hi_vals)                              # [P]
    update_tree = unflatten_like(med * eta, stacked_deltas)
    new_state = jax.tree_util.tree_map(
        lambda g, u: (g + u.astype(g.dtype)).astype(g.dtype),
        global_state, update_tree)
    if dp_sigma and rng is not None:
        noise = dp_noise_like(rng, new_state, dp_sigma)
        new_state = jax.tree_util.tree_map(lambda s, n: s + n.astype(s.dtype),
                                           new_state, noise)
    return CoordwiseResult(new_state, mask_f / jnp.maximum(n_alive, 1.0))
