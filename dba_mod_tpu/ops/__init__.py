"""Numeric building blocks: initializers, optimizers, triggers, aggregation math.

Everything in this package is pure jax/jnp (host-free, jit-safe); orchestration
lives in `dba_mod_tpu.fl`.
"""
