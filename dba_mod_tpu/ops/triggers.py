"""Backdoor trigger machinery as pure, vmap-safe jnp ops.

The reference stamps pixel patterns per-sample in a Python loop
(image_helper.py:298-350) and assigns LOAN feature columns per-sample
(loan_train.py:99-107, test.py:75-81). TPU-native equivalents:

- a *pattern bank*: [trigger_num + 1, H, W] {0,1} masks built once on host,
  where row `i` is adversary i's sub-pattern and the LAST row is the combined
  (global) pattern used by `adversarial_index == -1` (image_helper.py:331-335);
  stamping is then `img·(1-mask) + mask` broadcast over channels — pixels are
  set to 1.0 in every channel (image_helper.py:336-348);
- a *feature-trigger bank* for LOAN: [trigger_num + 1, F] value rows plus
  {0,1} masks over feature columns; stamping is a vectorized select;
- batch poisoning as a per-sample boolean: training poisons the first
  `poisoning_per_batch` samples of each batch, evaluation poisons all
  (image_helper.py:306-319).

All functions take the bank + a traced `adv_index` so one jitted computation
serves every adversary; index -1 (mapped to the last bank row) is the global
pattern.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu import config as cfg


# --------------------------------------------------------------------- builders
def build_pixel_pattern_bank(params: cfg.Params, height: int,
                             width: int) -> np.ndarray:
    """[trigger_num + 1, H, W] float32 {0,1} masks; row trigger_num is the
    union of all sub-patterns (the global/combined trigger)."""
    n = int(params["trigger_num"])
    bank = np.zeros((n + 1, height, width), np.float32)
    for i in range(n):
        for (r, c) in params.poison_pattern_for(i):
            bank[i, r, c] = 1.0
            bank[n, r, c] = 1.0
    return bank


def build_feature_trigger_bank(params: cfg.Params,
                               feature_dict: dict,
                               num_features: int
                               ) -> Tuple[np.ndarray, np.ndarray]:
    """LOAN: ([trigger_num + 1, F] values, [trigger_num + 1, F] {0,1} masks);
    row trigger_num is all per-adversary triggers concatenated
    (loan_train.py:49-57). Later values win on overlap, matching the
    reference's sequential assignment."""
    n = int(params["trigger_num"])
    values = np.zeros((n + 1, num_features), np.float32)
    masks = np.zeros((n + 1, num_features), np.float32)
    for i in range(n):
        names, vals = params.poison_trigger_features_for(i)
        for name, val in zip(names, vals):
            col = feature_dict[name]
            values[i, col] = val
            masks[i, col] = 1.0
            values[n, col] = val
            masks[n, col] = 1.0
    return values, masks


def bank_row(adv_index, bank_size: int):
    """Map a (possibly traced) adversarial index to a bank row: -1 → last row
    (the combined/global pattern)."""
    return jnp.where(adv_index < 0, bank_size - 1, adv_index)


# --------------------------------------------------------------------- stamping
def stamp_pixel_pattern(images: jax.Array, pattern_bank: jax.Array,
                        adv_index) -> jax.Array:
    """Stamp trigger pixels to 1.0 in all channels. images: [..., H, W, C]
    (NHWC); pattern_bank: [K, H, W]; adv_index: traced scalar, -1 = global."""
    mask = pattern_bank[bank_row(adv_index, pattern_bank.shape[0])]
    mask = mask[..., None]  # broadcast over channels
    return images * (1.0 - mask) + mask


def stamp_feature_trigger(rows: jax.Array, value_bank: jax.Array,
                          mask_bank: jax.Array, adv_index) -> jax.Array:
    """LOAN: assign trigger feature values. rows: [..., F]."""
    k = bank_row(adv_index, value_bank.shape[0])
    values, mask = value_bank[k], mask_bank[k]
    return rows * (1.0 - mask) + values * mask


def poison_batch(images: jax.Array, labels: jax.Array,
                 pattern_bank: jax.Array, adv_index,
                 poison_label_swap: int, poisoning_per_batch,
                 poison_all=False):
    """Poison a batch the reference way (image_helper.py:298-326): the first
    `poisoning_per_batch` samples (all if `poison_all`, the evaluation mode)
    get the trigger stamped and their label set to `poison_label_swap`.

    Returns (new_images, new_labels, per_sample_poisoned_mask). All selector
    args may be traced, so benign clients ride the same jitted computation with
    `poisoning_per_batch=0`.
    """
    batch = images.shape[0]
    idx = jnp.arange(batch)
    sel = jnp.where(poison_all, jnp.ones((batch,), bool),
                    idx < poisoning_per_batch)
    stamped = stamp_pixel_pattern(images, pattern_bank, adv_index)
    sel_img = sel.reshape((batch,) + (1,) * (images.ndim - 1))
    new_images = jnp.where(sel_img, stamped, images)
    new_labels = jnp.where(sel, poison_label_swap, labels)
    return new_images, new_labels, sel


def poison_batch_features(rows: jax.Array, labels: jax.Array,
                          value_bank: jax.Array, mask_bank: jax.Array,
                          adv_index, poison_label_swap: int,
                          poisoning_per_batch, poison_all=False):
    """LOAN counterpart of :func:`poison_batch` (loan_train.py:99-107)."""
    batch = rows.shape[0]
    idx = jnp.arange(batch)
    sel = jnp.where(poison_all, jnp.ones((batch,), bool),
                    idx < poisoning_per_batch)
    stamped = stamp_feature_trigger(rows, value_bank, mask_bank, adv_index)
    new_rows = jnp.where(sel[:, None], stamped, rows)
    new_labels = jnp.where(sel, poison_label_swap, labels)
    return new_rows, new_labels, sel
