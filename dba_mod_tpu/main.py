"""CLI — reference-compatible entry point.

    python -m dba_mod_tpu.main --params configs/cifar_params.yaml

mirrors `python main.py --params utils/cifar_params.yaml` (reference
main.py:88-92); it also accepts the reference's own YAML files unchanged.
Subcommands beyond the reference:

    pretrain   train a clean model and save the checkpoint that attack
               configs resume from (replaces the reference's Google-Drive
               pretrained artifacts, README.md:33-34)
    fetch      dataset preflight: exact upstream URLs + sha256 checksums
               for CIFAR/MNIST/Tiny-ImageNet/LOAN, download + verify (or
               --check-only), with an explicit printout of the synthetic
               fallback any absent dataset will engage
    cache-tiny decode the Tiny-ImageNet image folders once into an .npz
               cache for fast loading
    loan-etl / tiny-etl   the reference's offline data prep
               (utils/loan_preprocess.py, utils/tinyimagenet_reformat.py)
    report     render a run folder's defense-forensics stream
               (forensics.jsonl, written when `forensics: true`) into a
               standalone HTML round-audit
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
from pathlib import Path

from dba_mod_tpu.config import Params


def _train(args) -> int:
    from dba_mod_tpu.fl.experiment import Experiment
    from dba_mod_tpu.utils import run_guard
    params = Params.from_yaml(args.params)
    if args.epochs is not None:
        params.raw["epochs"] = args.epochs
    if args.synthetic:
        params.raw["synthetic_data"] = True
    if args.resume:
        if args.resume == "auto":
            # discover + continue the newest verified checkpoint under
            # run_dir (README "Crash & preemption tolerance"). Same guard
            # as config.py's validation — the CLI override lands after
            # from_yaml, so re-check the combination it would reject
            if not bool(params.raw.get("checkpoint_manifests", True)):
                raise SystemExit(
                    "--resume auto requires checkpoint_manifests: true "
                    "(auto-resume only restores manifest-verified "
                    "checkpoints; with manifests off every relaunch "
                    "would silently start a fresh run)")
            params.raw["resumed_model"] = "auto"
        else:
            params.raw.update(resumed_model=True,
                              resumed_model_name=args.resume)
    from dba_mod_tpu.parallel.distributed import PeerLostError
    exp = Experiment(params, save_results=not args.no_save)
    try:
        last = exp.run()
    except PeerLostError as e:
        # elastic verdict (README "Elastic multi-host"): a peer host is
        # gone. The run's finally already flushed checkpoints/recorder;
        # exit with the distinct code so the supervisor relaunches the
        # SURVIVORS with JAX_NUM_PROCESSES shrunk + --resume auto.
        # os._exit: the jax.distributed atexit teardown would block on the
        # dead peer — nothing left to flush is worth that hang.
        print(f"peer lost: {e} — relaunch the survivors with "
              f"JAX_NUM_PROCESSES shrunk and --resume auto", flush=True)
        sys.stdout.flush()
        sys.stderr.flush()
        logging.shutdown()
        os._exit(run_guard.EXIT_PEER_LOST)
    if exp.interrupted:
        # graceful SIGTERM/SIGINT stop: distinct exit code so run wrappers
        # know to relaunch with --resume auto rather than report failure
        done = last.get("epoch") if last else exp.start_epoch - 1
        print(f"interrupted: graceful stop after epoch {done} — "
              f"resume with --resume auto")
        return run_guard.EXIT_INTERRUPTED
    if not last:  # resume checkpoint already at/after the final epoch
        print(f"no rounds to run: start_epoch={exp.start_epoch} > "
              f"epochs={params['epochs']}")
        return 0
    print(f"final: epoch={last.get('epoch')} "
          f"acc={last.get('global_acc'):.2f} "
          f"backdoor={last.get('backdoor_acc')}")
    return 0


def _pretrain(args) -> int:
    from dba_mod_tpu import checkpoint as ckpt
    from dba_mod_tpu.fl.experiment import Experiment
    params = Params.from_yaml(args.params)
    params.raw.update(is_poison=False, resumed_model=False,
                      save_model=False)
    if args.epochs is not None:
        params.raw["epochs"] = args.epochs
    if args.synthetic:
        params.raw["synthetic_data"] = True
    exp = Experiment(params, save_results=False)
    last = exp.run()
    out = Path(str(params.get("checkpoint_dir", "saved_models"))) / (
        args.out or f"{params.type}_pretrain/model_last.pt.tar.epoch_"
                    f"{params['epochs']}")
    ckpt.save_checkpoint(out, exp.global_vars, int(params["epochs"]),
                         float(params["lr"]))
    acc = last.get("global_acc")
    print(f"pretrained to epoch {params['epochs']} "
          f"acc={acc if acc is None else round(acc, 2)} -> {out}")
    return 0


def _fetch(args) -> int:
    from dba_mod_tpu.data.fetch import run_preflight
    data_dir = args.data_dir
    types = [args.type] if args.type and args.type != "all" else None
    if args.params:
        params = Params.from_yaml(args.params)
        types = [params.type]
        if args.data_dir == "./data":  # YAML wins unless overridden
            data_dir = str(params.get("data_dir", "./data"))
    return run_preflight(types, data_dir, check_only=args.check_only)


def _cache_tiny(args) -> int:
    import numpy as np
    from dba_mod_tpu.data.datasets import load_tiny_imagenet
    data = load_tiny_imagenet(args.data_dir)
    if data is None:
        print("tiny-imagenet-200 folders not found (or PIL missing)",
              file=sys.stderr)
        return 1
    out = Path(args.data_dir) / "tiny-imagenet-200.npz"
    np.savez_compressed(out, train_x=data.train_images,
                        train_y=data.train_labels, test_x=data.test_images,
                        test_y=data.test_labels)
    print(f"cached {len(data.train_labels)} train / "
          f"{len(data.test_labels)} val images -> {out}")
    return 0


def _loan_etl(args) -> int:
    from dba_mod_tpu.data.etl import preprocess_loan
    n = preprocess_loan(args.input, Path(args.data_dir) / "loan")
    print(f"wrote {n} per-state loan CSVs")
    return 0


def _tiny_etl(args) -> int:
    from dba_mod_tpu.data.etl import reformat_tiny_imagenet_val
    n = reformat_tiny_imagenet_val(Path(args.data_dir) / "tiny-imagenet-200")
    print(f"moved {n} val images into per-class folders")
    return 0


def _report(args) -> int:
    from dba_mod_tpu.utils.forensics import write_report
    out = write_report(Path(args.run),
                       Path(args.out) if args.out else None)
    print(f"wrote {out}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="dba_mod_tpu", description=__doc__)
    sub = parser.add_subparsers(dest="cmd")

    def common(p):
        p.add_argument("--params", required=True,
                       help="YAML config (reference schema)")
        p.add_argument("--epochs", type=int, default=None)
        p.add_argument("--synthetic", action="store_true",
                       help="force the synthetic dataset backend")

    train = sub.add_parser("train", help="run an FL experiment (default)")
    common(train)
    train.add_argument("--no-save", action="store_true")
    train.add_argument(
        "--resume", default=None, metavar="auto|NAME",
        help="'auto': discover the newest verified checkpoint under "
             "run_dir, reuse that run folder and continue its recorder "
             "stream; any other value resumes checkpoint_dir/NAME "
             "(overrides the YAML's resumed_model keys)")
    pre = sub.add_parser("pretrain", help="train+save a clean model")
    common(pre)
    pre.add_argument("--out", default=None,
                     help="checkpoint path under saved_models/")
    fe = sub.add_parser(
        "fetch", help="dataset preflight: check/download + sha256-verify "
                      "the real datasets; absent ones fall back to the "
                      "deterministic synthetic backend at run time")
    fe.add_argument("--params", default=None,
                    help="YAML config: preflight exactly the dataset this "
                         "experiment needs (type + data_dir)")
    fe.add_argument("--type", default="all",
                    choices=["all", "cifar", "mnist", "tiny-imagenet-200",
                             "loan"])
    fe.add_argument("--data-dir", default="./data")
    fe.add_argument("--check-only", action="store_true",
                    help="no network: report presence/integrity and the "
                         "synthetic-fallback consequences, exit nonzero "
                         "if anything is missing")
    ct = sub.add_parser("cache-tiny")
    ct.add_argument("--data-dir", default="./data")
    le = sub.add_parser("loan-etl")
    le.add_argument("--input", required=True, help="raw lending-club CSV")
    le.add_argument("--data-dir", default="./data")
    te = sub.add_parser("tiny-etl")
    te.add_argument("--data-dir", default="./data")
    rp = sub.add_parser(
        "report", help="render forensics.jsonl into a standalone HTML "
                       "round-audit (requires a run with forensics: true)")
    rp.add_argument("--run", required=True,
                    help="run folder containing forensics.jsonl")
    rp.add_argument("--out", default=None,
                    help="output path (default: RUN/forensics_report.html)")
    return parser


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    known = {"train", "pretrain", "fetch", "cache-tiny", "loan-etl",
             "tiny-etl", "report"}
    if argv and argv[0] not in known:
        argv = ["train"] + argv  # reference style: --params only
    args = build_parser().parse_args(argv)
    return {"train": _train, "pretrain": _pretrain, "fetch": _fetch,
            "cache-tiny": _cache_tiny, "loan-etl": _loan_etl,
            "tiny-etl": _tiny_etl, "report": _report}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
