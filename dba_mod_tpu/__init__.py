"""dba_mod_tpu — a TPU-native (JAX/XLA/pjit) federated-learning backdoor-research
framework with the capabilities of the DBA reference (ICLR 2020 code,
`ehsan886/DBA_mod`).

The reference is a single-process PyTorch simulator; this framework re-designs the
same capability surface TPU-first:

- clients are a *mesh axis*, not a Python loop: local training is one jitted,
  vmapped/pjit-sharded XLA computation over stacked client state;
- triggers, aggregation (FedAvg / RFA geometric median / FoolsGold) and the
  evaluation battery are pure on-device jnp programs;
- the round loop on the host only schedules, selects and records.

Public entry points:
    dba_mod_tpu.config.Params.from_yaml      — reference-schema YAML configs
    dba_mod_tpu.fl.experiment.Experiment     — end-to-end FL experiment driver
    dba_mod_tpu.main                         — CLI (python -m dba_mod_tpu.main)
"""

__version__ = "0.1.0"
