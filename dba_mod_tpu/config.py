"""Experiment configuration.

Accepts the reference's flat-YAML schema verbatim (same key names, including the
stringly per-adversary keys ``{i}_poison_epochs`` / ``{i}_poison_pattern`` /
``{i}_poison_trigger_names`` / ``{i}_poison_trigger_values`` — see reference
`utils/cifar_params.yaml`, `image_train.py:43`, `loan_train.py:51-57`), but exposes
them through typed accessors so the rest of the framework never string-concatenates
config keys.

Unlike the reference (which mutates the params dict at runtime, `helper.py:44-48`),
``Params`` is read-mostly: runtime-derived fields live in explicit attributes.
"""
from __future__ import annotations

import copy
import dataclasses
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

import yaml

# Dataset type tags (reference config.py:10-13).
TYPE_CIFAR = "cifar"
TYPE_MNIST = "mnist"
TYPE_TINYIMAGENET = "tiny-imagenet-200"
TYPE_LOAN = "loan"

IMAGE_TYPES = (TYPE_CIFAR, TYPE_MNIST, TYPE_TINYIMAGENET)

# Aggregation method names (reference config.py:4-6).
AGGR_MEAN = "mean"
AGGR_GEO_MED = "geom_median"
AGGR_FOOLSGOLD = "foolsgold"
# Byzantine-robust rules beyond the reference (ROADMAP item 3; no reference
# counterpart — ops/aggregation.py documents the papers and the
# survivor-mask contract they share with the three above).
AGGR_KRUM = "krum"
AGGR_TRIMMED_MEAN = "trimmed_mean"
AGGR_MEDIAN = "median"
AGGR_ALL = (AGGR_MEAN, AGGR_GEO_MED, AGGR_FOOLSGOLD, AGGR_KRUM,
            AGGR_TRIMMED_MEAN, AGGR_MEDIAN)

_REQUIRED_KEYS = ("type", "lr", "batch_size", "epochs", "no_models",
                  "number_of_total_participants", "eta", "aggregation_methods")

_DEFAULTS: Dict[str, Any] = {
    "test_batch_size": 64,
    "momentum": 0.9,
    "decay": 0.0005,
    "internal_epochs": 1,
    "internal_poison_epochs": 1,
    "poisoning_per_batch": 1,
    "aggr_epoch_interval": 1,
    "geom_median_maxiter": 10,
    "fg_use_memory": True,
    "participants_namelist": [],
    "is_random_namelist": True,
    "is_random_adversary": False,
    "is_poison": False,
    "baseline": False,
    "scale_weights_poison": 1.0,
    "sampling_dirichlet": True,
    "dirichlet_alpha": 0.5,
    "poison_label_swap": 0,
    "adversary_list": [],
    "centralized_test_trigger": True,
    "trigger_num": 0,
    "poison_epochs": [],
    "poison_lr": 0.05,
    "poison_step_lr": True,
    "alpha_loss": 1.0,
    "diff_privacy": False,
    "sigma": 0.01,
    "save_model": False,
    "save_on_epochs": [],
    "resumed_model": False,
    "resumed_model_name": "",
    # per-batch tracking channels (reference image_train.py:108-117, :232-246;
    # the reference only plots these to visdom — here they are recorded)
    "vis_train_batch_loss": False,
    "batch_track_distance": False,
    # RFA update-norm rejection threshold (reference helper.py:360-369; its
    # MAX_UPDATE_NORM constant at config.py:7 is dormant — None keeps parity)
    "max_update_norm": None,
    "environment_name": "dba_tpu",
    "log_interval": 2,
    "results_json": True,
    "random_seed": 1,
    # framework-specific knobs (not in the reference schema)
    "compute_dtype": "float32",    # "bfloat16" runs fwd/bwd on the MXU in
                                   # bf16; params/optimizer/aggregation stay
                                   # float32
    "eval_batch_size": 0,          # 0 = use test_batch_size
    "local_eval": True,            # per-client eval battery (reference
                                   # image_train.py:150-164, 268-299)
    "profile_dir": "",             # non-empty: jax.profiler traces per round
    "tensorboard": False,          # scalar summaries (imports TensorFlow)
    "telemetry": False,            # span tracing + metrics registry + XLA
                                   # compile/memory instrumentation
                                   # (utils/telemetry.py): writes
                                   # telemetry.jsonl + Chrome-trace
                                   # trace.json per run, adds honest
                                   # device-sync points to phase spans
                                   # (serializes round pipelining); off =
                                   # no files, no per-round work beyond a
                                   # no-op check
    "telemetry_dir": "",           # where telemetry files land; "" = the
                                   # run folder (in-memory only when the
                                   # run saves no results)
    "forensics": False,            # defense-forensics layer
                                   # (utils/forensics.py): per-client
                                   # aggregation diagnostics — delta/received
                                   # norms, cosine to the applied update,
                                   # screening verdict + quarantine reason,
                                   # FoolsGold/RFA weights and similarities,
                                   # poison-battery accuracy — ride the
                                   # round payload's single fetch and stream
                                   # to forensics.jsonl +
                                   # client_forensics.csv (TensorBoard
                                   # mirror under forensics/ when
                                   # tensorboard is on); `report` renders
                                   # the HTML round-audit. Off = strict
                                   # no-op: nothing traced, no files,
                                   # bit-identical recorded metrics
    "sequential_debug": False,     # run clients one-by-one (A/B vs vmapped)
    "data_dir": "./data",
    "synthetic_data": False,       # force the synthetic dataset backend
    "synthetic_train_size": 0,     # 0 = backend default
    "synthetic_test_size": 0,      # 0 = backend default
    "synthetic_noise_std": 25.0,   # task difficulty: 25 saturates (smoke
                                   # runs); ~90 plateaus below 100% like
                                   # real data (datasets.py docstring)
    "num_devices": 0,              # 0 = use all visible devices on the clients mesh
    "run_dir": "./runs",
    "checkpoint_dir": "saved_models",  # root for resume/pretrain checkpoints
    "dynamic_steps": False,        # size each round's batch plan to the
                                   # round's own max client (bucketed to limit
                                   # recompiles) instead of the global max;
                                   # identical numerics (padding steps are
                                   # fully-masked no-ops)
    "pipeline_rounds": False,      # overlap round N's host fetch with round
                                   # N+1's device compute in Experiment.run
    "overlap_eval": False,         # split the fused round program and overlap
                                   # round N's eval batteries + host
                                   # record/checkpoint with round N+1's
                                   # train/aggregate dispatch (async engine:
                                   # pipeline host bookkeeping with the next
                                   # merge). Eval inputs are snapshots of the
                                   # superseded model, so recorded metrics are
                                   # bit-identical to the serial path; off
                                   # (default) is a strict bit-identical no-op
    "fused_updates": "auto",       # fused pallas per-step state update;
                                   # auto = on for unsharded TPU runs
    "fused_interpret": False,      # run the fused kernels in pallas
                                   # interpret mode (CPU testing)
    "grouped_clients": False,      # grouped-layout client execution
                                   # (models/grouped.py); measured
                                   # perf-neutral vs the vmapped path —
                                   # TRAIN_FLOOR.md round-5 section
    # --- wider defense grid (ops/aggregation.py; ROADMAP item 3) ---
    "krum_m": 1,                   # multi-Krum selection count (1 = classic
                                   # Krum): the m lowest-scoring clients are
                                   # averaged into the applied update
    "krum_byzantine_f": 0,         # assumed Byzantine count f in the Krum
                                   # score (each client scored over its
                                   # n-f-2 nearest peers)
    "trimmed_mean_beta": 0.1,      # per-coordinate trim fraction: drop the
                                   # floor(beta*n) smallest and largest
                                   # survivor values before averaging
    # --- asynchronous buffered federation (fl/async_rounds.py; README
    #     "Asynchronous federation"). mode: "sync" (default) is a strict
    #     no-op for every knob in this block — the lockstep engine does not
    #     read them.
    "mode": "sync",                # "async" = FedBuff-style buffered
                                   # streaming server: clients arrive
                                   # continuously, the server merges every
                                   # buffer_k arrivals with
                                   # staleness-weighted partial
                                   # participation
    "buffer_k": 0,                 # merge every K arrivals; 0 = no_models
                                   # (with zero staleness weighting that
                                   # reduces bit-exactly to the sync round)
    "staleness_weighting": "none",  # per-update weight w(s) of merge-step
                                   # staleness s: "none" (w=1 — the parity
                                   # mode), "polynomial" (1/(1+s)^alpha),
                                   # "exponential" (alpha^s)
    "staleness_alpha": 0.5,        # the alpha of polynomial/exponential
    "arrival_rate": 1.0,           # mean client arrivals per unit virtual
                                   # time (exponential inter-arrival)
    "arrival_jitter": 0.0,         # lognormal sigma multiplying each
                                   # client's service delay (0 = none)
    "straggler_tail": 0.0,         # P(client is a straggler this wave)
    "straggler_factor": 10.0,      # straggler delay multiplier
    "async_steps": 0,              # aggregation steps to run; 0 = derive
                                   # from epochs (epochs*no_models/buffer_k
                                   # — the same total client-update budget
                                   # as the sync run)
    # --- self-healing server loop (fl/async_rounds.py, fl/experiment.py;
    #     README "Self-healing federation"). Every knob here is a strict
    #     bit-identical no-op at its default.
    "merge_timeout_v": 0.0,        # virtual-seconds merge deadline: fire a
                                   # partial merge when the oldest buffered
                                   # arrival has waited this long and >=
                                   # merge_min_k updates are buffered
                                   # (inert-lane padding handles the short
                                   # batch); 0 = K-arrivals-only merges
    "merge_min_k": 1,              # minimum buffered updates for a
                                   # deadline-triggered partial merge
    "starvation_policy": "abort",  # after 200 consecutive empty cohorts:
                                   # "abort" (raise — the pre-existing
                                   # behaviour), "carry" (record a carried
                                   # no-op step and keep going), "wait"
                                   # (keep drawing cohorts indefinitely;
                                   # the watchdog is the backstop)
    "max_outstanding_waves": 0,    # admission control: stop dispatching
                                   # new waves while this many are still
                                   # resident (straggler tails otherwise
                                   # grow _waves unboundedly); 0 = no cap
    "arrival_ttl_v": 0.0,          # expire heap arrivals older (in virtual
                                   # seconds) than this at pop time — the
                                   # update never reaches the buffer and
                                   # its lane is freed; 0 = never expire
    "model_health_check": False,   # jitted post-merge sentinel in BOTH
                                   # engines: all-finite params + update
                                   # norm vs a trailing EMA band; an
                                   # unhealthy merge rolls back to the
                                   # last-good ring and re-merges the same
                                   # buffer with escalated screening
    "health_norm_band": 0.0,       # flag a merge whose update norm exceeds
                                   # band × trailing-EMA(update norm);
                                   # 0 disables the norm band (the finite
                                   # check still runs when the sentinel is
                                   # on)
    "health_ema_alpha": 0.1,       # EMA smoothing for the trailing update
                                   # norm (new = a*obs + (1-a)*old)
    "health_warmup_merges": 3,     # merges before the norm band arms (the
                                   # EMA needs history; finite check is
                                   # active from merge 1)
    "rollback_ring": 0,            # last-good in-memory model versions
                                   # kept for health rollback; 0 = ring off
                                   # (an unhealthy merge then only skips +
                                   # carries, it cannot roll back)
    # --- fault model & robustness (fl/faults.py, README "Fault model") ---
    "fault_injection": False,      # master switch for the deterministic
                                   # fault harness (fl/faults.py); off =
                                   # nothing traced, zero cost
    "fault_seed": 0,               # fault plans are f(fault_seed, epoch) —
                                   # independent of every other RNG stream
    "fault_dropout_prob": 0.0,     # P(client never reports this round)
    "fault_corrupt_prob": 0.0,     # P(payload arrives NaN-corrupted)
    "fault_blowup_prob": 0.0,      # P(payload scaled by blowup factor)
    "fault_blowup_factor": 1e8,    # norm-blowup magnitude
    "fault_stale_prob": 0.0,       # P(client replays last round's delta)
    "fault_host_loss_prob": 0.0,   # P(the round loses one whole HOST):
                                   # multi-process runs SIGKILL the victim
                                   # process at the round boundary (CI for
                                   # the elastic detect→restart path);
                                   # single-process runs drop the victim
                                   # virtual host's client slice through
                                   # the survivor mask
    "fault_num_hosts": 0,          # virtual host count for single-process
                                   # host-loss simulation (>= 2 required
                                   # when the lane is on); multi-process
                                   # runs use the real process count
    "screen_updates": "auto",      # server-side delta validation/quarantine
                                   # (finite + norm screen): "auto" = on iff
                                   # fault_injection; true/false to force
    "screen_norm_mult": 0.0,       # quarantine ‖Δ‖ > mult × survivor-median
                                   # norm; 0 disables the norm screen (the
                                   # finite screen always runs when
                                   # screening is on); retries escalate this
    "max_round_retries": 2,        # re-runs of a round whose aggregated
                                   # model goes non-finite (escalated
                                   # screening each attempt)
    "retry_backoff_s": 0.0,        # host backoff before retry k:
                                   # min(retry_backoff_s · 2^(k-1), 30 s)
    "min_surviving_clients": 1,    # fewer survivors → skip aggregation,
                                   # carry the global model, mark the round
                                   # degraded
    # --- crash/preemption tolerance (utils/run_guard.py, checkpoint.py;
    #     README "Crash & preemption tolerance") ---
    # resumed_model additionally accepts the string "auto": discover the
    # newest VERIFIED checkpoint across run_dir's run folders, reuse that
    # run folder, and continue its recorder stream past the resume epoch
    "graceful_shutdown": False,    # SIGTERM/SIGINT → finish the round,
                                   # write a final verified checkpoint,
                                   # flush recorder/telemetry, exit 75;
                                   # second signal forces immediate exit.
                                   # Off = no signal handlers installed
    "watchdog_soft_s": 0.0,        # stall diagnostic (span stack, epoch,
                                   # elapsed) when a host sync point blocks
                                   # this long; 0 = off (no thread)
    "watchdog_hard_s": 0.0,        # abort the process (exit 76) when a
                                   # sync point blocks this long — a wedged
                                   # run dies checkpointed instead of
                                   # burning quota; 0 = off
    "checkpoint_manifests": True,  # write + verify per-snapshot integrity
                                   # manifests (sha256 over the orbax step
                                   # dir + aux sidecar); required for
                                   # resumed_model: auto, which restores
                                   # only verified snapshots
    "keep_last_n": 0,              # checkpoint retention: keep only the
                                   # newest N *.epoch_N snapshots
                                   # (model_last and .best always kept);
                                   # 0 = keep all
    # --- elastic multi-host (parallel/distributed.py::PeerHealth;
    #     README "Elastic multi-host"). All strict no-ops single-host or
    #     when heartbeat_interval_s is 0: no thread, no files, no
    #     per-round work.
    "heartbeat_interval_s": 0.0,   # per-host heartbeat cadence in a
                                   # multi-process run; 0 = elastic layer
                                   # off
    "heartbeat_timeout_s": 0.0,    # heartbeat staleness past this = the
                                   # peer is GONE (not slow) → exit 77;
                                   # 0 = 6 × heartbeat_interval_s
    "heartbeat_barrier_s": 0.0,    # bounded round-boundary barrier: wait
                                   # up to this long for every peer to
                                   # reach the boundary (timeout = slow
                                   # peer, proceed; stale = PeerLost);
                                   # 0 = non-blocking staleness check only
    "heartbeat_dir": "",           # shared dir for heartbeat files; "" =
                                   # <run_folder>/_peers (per-run — twin
                                   # worlds in one run_dir must not read
                                   # each other's beats), or
                                   # <run_dir>/_peers when the run saves
                                   # no results. Must be on a filesystem
                                   # every host can reach.
    "run_name": "",                # fixed run-folder name (run_dir/
                                   # run_name) instead of the timestamped
                                   # default — REQUIRED for multi-process
                                   # runs that save results/checkpoints,
                                   # so every process and every elastic
                                   # relaunch agrees on one folder
}


@dataclasses.dataclass
class Params:
    """Typed view over a reference-schema config dict."""

    raw: Dict[str, Any]
    current_time: str = dataclasses.field(
        default_factory=lambda: time.strftime("%b.%d_%H.%M.%S"))

    # ------------------------------------------------------------------ loading
    @classmethod
    def from_yaml(cls, path: str | Path) -> "Params":
        with open(path) as f:
            raw = yaml.safe_load(f)
        return cls.from_dict(raw)

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "Params":
        merged = copy.deepcopy(_DEFAULTS)
        merged.update(raw or {})
        missing = [k for k in _REQUIRED_KEYS if k not in merged]
        if missing:
            raise ValueError(f"config missing required keys: {missing}")
        if merged["aggregation_methods"] not in AGGR_ALL:
            raise ValueError(
                f"unknown aggregation_methods: {merged['aggregation_methods']!r}")
        if merged["type"] not in IMAGE_TYPES + (TYPE_LOAN,):
            raise ValueError(f"unknown workload type: {merged['type']!r}")
        if merged["screen_updates"] not in ("auto", True, False):
            raise ValueError(
                f"screen_updates must be 'auto'/true/false, got "
                f"{merged['screen_updates']!r}")
        if int(merged["max_round_retries"]) < 0:
            raise ValueError("max_round_retries must be >= 0")
        if int(merged["min_surviving_clients"]) < 1:
            raise ValueError("min_surviving_clients must be >= 1")
        rm = merged["resumed_model"]
        if not isinstance(rm, bool) and rm != "auto":
            raise ValueError(
                f"resumed_model must be true/false/'auto', got {rm!r}")
        if rm == "auto" and not bool(merged["checkpoint_manifests"]):
            # auto-resume restores only VERIFIED snapshots — without
            # manifests it can never find one and every relaunch would
            # silently discard all progress
            raise ValueError(
                "resumed_model: auto requires checkpoint_manifests: true "
                "(auto-resume only restores manifest-verified checkpoints)")
        soft = float(merged["watchdog_soft_s"])
        hard = float(merged["watchdog_hard_s"])
        if soft < 0 or hard < 0:
            raise ValueError("watchdog_soft_s/watchdog_hard_s must be >= 0")
        if 0 < hard < soft:
            raise ValueError(
                f"watchdog_hard_s ({hard}) must be >= watchdog_soft_s "
                f"({soft}) — the soft diagnostic must fire before the abort")
        if int(merged["keep_last_n"]) < 0:
            raise ValueError("keep_last_n must be >= 0")
        hb = float(merged["heartbeat_interval_s"])
        hb_to = float(merged["heartbeat_timeout_s"])
        hb_bar = float(merged["heartbeat_barrier_s"])
        if hb < 0 or hb_to < 0 or hb_bar < 0:
            raise ValueError("heartbeat_interval_s/heartbeat_timeout_s/"
                             "heartbeat_barrier_s must be >= 0")
        if 0 < hb_to <= hb:
            raise ValueError(
                f"heartbeat_timeout_s ({hb_to}) must exceed "
                f"heartbeat_interval_s ({hb}) — a peer must get at least "
                "one beat window before being declared gone")
        if int(merged["fault_num_hosts"]) < 0:
            raise ValueError("fault_num_hosts must be >= 0")
        if not isinstance(merged["forensics"], bool):
            raise ValueError(
                f"forensics must be true/false, got {merged['forensics']!r}")
        if int(merged["krum_m"]) < 1:
            raise ValueError("krum_m must be >= 1")
        if int(merged["krum_byzantine_f"]) < 0:
            raise ValueError("krum_byzantine_f must be >= 0")
        beta = float(merged["trimmed_mean_beta"])
        if not 0.0 <= beta < 0.5:
            raise ValueError(
                f"trimmed_mean_beta must be in [0, 0.5), got {beta}")
        if merged["mode"] not in ("sync", "async"):
            raise ValueError(
                f"mode must be 'sync' or 'async', got {merged['mode']!r}")
        if int(merged["buffer_k"]) < 0:
            raise ValueError("buffer_k must be >= 0 (0 = no_models)")
        if merged["staleness_weighting"] not in ("none", "polynomial",
                                                 "exponential"):
            raise ValueError(
                "staleness_weighting must be 'none'/'polynomial'/"
                f"'exponential', got {merged['staleness_weighting']!r}")
        if float(merged["arrival_rate"]) <= 0:
            raise ValueError("arrival_rate must be > 0")
        if float(merged["arrival_jitter"]) < 0:
            raise ValueError("arrival_jitter must be >= 0")
        tail = float(merged["straggler_tail"])
        if not 0.0 <= tail <= 1.0:
            raise ValueError(f"straggler_tail must be in [0, 1], got {tail}")
        if float(merged["straggler_factor"]) < 1.0:
            raise ValueError("straggler_factor must be >= 1")
        if int(merged["async_steps"]) < 0:
            raise ValueError("async_steps must be >= 0")
        if float(merged["merge_timeout_v"]) < 0:
            raise ValueError("merge_timeout_v must be >= 0 (0 = off)")
        if int(merged["merge_min_k"]) < 1:
            raise ValueError("merge_min_k must be >= 1")
        if merged["starvation_policy"] not in ("wait", "carry", "abort"):
            raise ValueError(
                "starvation_policy must be 'wait'/'carry'/'abort', got "
                f"{merged['starvation_policy']!r}")
        if int(merged["max_outstanding_waves"]) < 0:
            raise ValueError("max_outstanding_waves must be >= 0 (0 = no cap)")
        if float(merged["arrival_ttl_v"]) < 0:
            raise ValueError("arrival_ttl_v must be >= 0 (0 = never expire)")
        if float(merged["health_norm_band"]) < 0:
            raise ValueError("health_norm_band must be >= 0 (0 = off)")
        alpha_h = float(merged["health_ema_alpha"])
        if not 0.0 < alpha_h <= 1.0:
            raise ValueError(
                f"health_ema_alpha must be in (0, 1], got {alpha_h}")
        if int(merged["health_warmup_merges"]) < 0:
            raise ValueError("health_warmup_merges must be >= 0")
        if int(merged["rollback_ring"]) < 0:
            raise ValueError("rollback_ring must be >= 0 (0 = ring off)")
        if merged["mode"] == "async":
            # the async driver's constraints, rejected at validation so a
            # bad combo fails before data loading: FoolsGold's cross-round
            # memory is keyed to lockstep rounds (a buffered merge has no
            # per-round participant row to update), interval>1 segment
            # chaining has no arrival-process analog, and sequential_debug
            # bypasses the vmapped wave training the driver dispatches.
            if merged["aggregation_methods"] == AGGR_FOOLSGOLD:
                raise ValueError(
                    "mode: async does not support foolsgold aggregation "
                    "(cross-round memory is keyed to lockstep rounds)")
            if int(merged["aggr_epoch_interval"]) != 1:
                raise ValueError(
                    "mode: async requires aggr_epoch_interval: 1")
            if merged["sequential_debug"]:
                raise ValueError(
                    "mode: async is incompatible with sequential_debug")
        return cls(raw=merged)

    # ------------------------------------------------------------- dict access
    def __getitem__(self, key: str) -> Any:
        return self.raw[key]

    def __contains__(self, key: str) -> bool:
        return key in self.raw

    def get(self, key: str, default: Any = None) -> Any:
        return self.raw.get(key, default)

    # ------------------------------------------------------------- shorthands
    @property
    def type(self) -> str:
        return self.raw["type"]

    @property
    def is_image(self) -> bool:
        return self.type in IMAGE_TYPES

    @property
    def aggregation(self) -> str:
        return self.raw["aggregation_methods"]

    @property
    def resume_mode(self) -> str:
        """'off' | 'named' (checkpoint_dir/resumed_model_name) | 'auto'
        (discover the newest verified checkpoint under run_dir)."""
        rm = self.raw["resumed_model"]
        if rm == "auto":
            return "auto"
        return "named" if rm else "off"

    @property
    def adversary_list(self) -> List[Any]:
        return list(self.raw["adversary_list"])

    @property
    def num_adversaries(self) -> int:
        return len(self.raw["adversary_list"])

    @property
    def is_centralized_attack(self) -> bool:
        # A single adversary means "centralized" mode: it stamps the *global*
        # (combined) pattern instead of a per-adversary sub-pattern
        # (reference image_train.py:47-48, main.py:225-231).
        return self.num_adversaries == 1

    # ------------------------------------------------- per-adversary accessors
    def is_adversary(self, agent_name: Any) -> bool:
        return agent_name in self.raw["adversary_list"]

    def adversary_slot_of(self, agent_name: Any) -> int:
        """Position of `agent_name` in adversary_list, or -1 if benign.

        The *slot* keys the poison schedule (``{slot}_poison_epochs``) even in
        centralized mode — the reference resolves the schedule before forcing
        the pattern index to -1 (image_train.py:38-48).
        """
        try:
            return self.adversary_list.index(agent_name)
        except ValueError:
            return -1

    def adversarial_index_of(self, agent_name: Any) -> int:
        """Trigger-pattern index for `agent_name`: its slot, or -1 for benign
        agents AND for the lone attacker in centralized mode, which trains on
        the combined/global pattern (image_train.py:47-48). Use
        :meth:`is_adversary` to distinguish the two -1 cases.
        """
        idx = self.adversary_slot_of(agent_name)
        if idx >= 0 and self.is_centralized_attack:
            return -1
        return idx

    def poison_epochs_for(self, adv_slot: int) -> List[int]:
        """Poison schedule for adversary slot `adv_slot` (``{slot}_poison_epochs``).

        A missing per-slot key for a real adversary slot is a config error and
        raises KeyError, matching the reference's unconditional lookup
        (image_train.py:43, main.py:151); the global ``poison_epochs`` list is
        only the benign-agent default (image_train.py:38).
        """
        if adv_slot >= 0:
            return list(self.raw[f"{adv_slot}_poison_epochs"])
        return list(self.raw["poison_epochs"])

    def poison_pattern_for(self, adv_index: int) -> List[List[int]]:
        """Pixel trigger for adversary slot; -1 = union of all sub-patterns
        (reference image_helper.py:328-335)."""
        if adv_index == -1:
            pattern: List[List[int]] = []
            for i in range(int(self.raw["trigger_num"])):
                pattern.extend(self.raw[f"{i}_poison_pattern"])
            return pattern
        return list(self.raw[f"{adv_index}_poison_pattern"])

    def poison_trigger_features_for(self, adv_index: int):
        """LOAN feature trigger (names, values) for slot; -1 = all concatenated
        (reference loan_train.py:47-57)."""
        names: List[str] = []
        values: List[float] = []
        if adv_index == -1:
            for i in range(int(self.raw["trigger_num"])):
                names.extend(self.raw[f"{i}_poison_trigger_names"])
                values.extend(self.raw[f"{i}_poison_trigger_values"])
        else:
            names = list(self.raw[f"{adv_index}_poison_trigger_names"])
            values = list(self.raw[f"{adv_index}_poison_trigger_values"])
        return names, values

    def scheduled_adversaries(self, epochs: Sequence[int]) -> List[Any]:
        """Adversaries whose poison schedule intersects `epochs`
        (reference main.py:149-154)."""
        out = []
        for idx, name in enumerate(self.adversary_list):
            sched = self.poison_epochs_for(idx)
            if any(e in sched for e in epochs):
                out.append(name)
        return out

    # ---------------------------------------------------------------- run dir
    def write_yaml(self, folder: Path) -> None:
        """Record the effective config in a run folder (overwrites — an
        auto-resumed run re-records the config it resumed with)."""
        with open(Path(folder) / "params.yaml", "w") as f:
            yaml.dump(self.raw, f)

    @property
    def run_name(self) -> str:
        """Fixed run-folder name ('' = timestamped default). Multi-process
        runs that save results must set it: every process — and every
        elastic relaunch of the survivors — has to agree on ONE folder,
        which per-process timestamps cannot guarantee."""
        return str(self.raw.get("run_name", "") or "")

    def make_run_folder(self) -> Path:
        name = self.run_name or f"{self.type}_{self.current_time}"
        folder = Path(self.raw["run_dir"]) / name
        folder.mkdir(parents=True, exist_ok=True)
        self.write_yaml(folder)
        return folder
