"""The clients mesh and sharding specs.

Design (SURVEY §2.2, §5 'distributed communication backend' row):
- axis `clients`: stacked per-client state/batch tensors are sharded on their
  leading axis; the vmapped client step then runs clients-per-device locally
  with zero communication;
- the global model is replicated; FedAvg's Σ_c Δ_c lowers to an ICI psum,
  RFA's per-client distance vector to an all-gather of C scalars, and
  FoolsGold's [C, L] feature-gradient matrix to an all-gather of the (small)
  similarity layer — exactly the collective shapes sketched in SURVEY §5;
- sharding is expressed as jit in_shardings (GSPMD), not hand-written
  shard_map: XLA chooses the collective schedule.

The round's client count must be a multiple of the mesh size; the experiment
driver pads the stacked axis with inert clients (empty plans → zero deltas)
under FedAvg, or picks a compatible no_models.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENTS_AXIS = "clients"


def make_mesh(num_devices: int = 0, devices=None) -> Mesh:
    """1-D mesh over `num_devices` (0 = all visible) with a `clients` axis."""
    devices = list(devices if devices is not None else jax.devices())
    if num_devices:
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (CLIENTS_AXIS,))


def client_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis sharding over clients (pytree-prefix usable)."""
    return NamedSharding(mesh, P(CLIENTS_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def segment_client_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for segment-leading stacks: leaves are [segments, clients,
    ...] — replicate the segment axis, shard clients."""
    return NamedSharding(mesh, P(None, CLIENTS_AXIS))


def local_slice_bounds(sharding: NamedSharding, shape,
                       clients_axis: int) -> "tuple[int, int]":
    """[lo, hi) bounds of this process's addressable slice of the clients
    axis for an array of `shape` under `sharding`. The contiguous union of
    the per-device slices GSPMD assigns this host — also the partition the
    host-loss fault lane mirrors (fl/faults.py::host_of_lane). Handles
    shrunk worlds where the surviving device count no longer divides the
    padded client count (XLA leaves the trailing devices short slices or
    `None` stops)."""
    index_map = sharding.addressable_devices_indices_map(tuple(shape))
    bounds = [(sl[clients_axis].start or 0,
               sl[clients_axis].stop if sl[clients_axis].stop is not None
               else shape[clients_axis]) for sl in index_map.values()]
    return (min(b[0] for b in bounds), max(b[1] for b in bounds))


def _place(t, sharding: NamedSharding, clients_axis: int):
    """Single-controller: plain device_put. Multi-process (DCN): every host
    holds the full host-side plan (selection/plan RNGs are seeded
    identically on all hosts), and hands ONLY its addressable slice of the
    clients axis to `jax.make_array_from_process_local_data` — the per-host
    input-placement pattern for multi-host SPMD (device_put cannot target
    non-addressable devices). After an elastic shrink the relaunched world
    simply recomputes these bounds over the surviving devices — the lost
    host's cohort re-enters through this re-sharding, no special case."""
    if jax.process_count() == 1:
        return jax.device_put(t, sharding)
    t = np.asarray(t)
    lo, hi = local_slice_bounds(sharding, t.shape, clients_axis)
    local = t[(slice(None),) * clients_axis + (slice(lo, hi),)]
    return jax.make_array_from_process_local_data(sharding, local, t.shape)


def shard_round_inputs(mesh: Mesh, tasks_seq: Any, idx_seq, mask_seq,
                       num_samples):
    """Place one round's segment-stacked inputs ([I, C, ...] leaves) with
    clients-axis sharding; num_samples is [C]."""
    seg_cs = segment_client_sharding(mesh)
    put = lambda t: _place(t, seg_cs, clients_axis=1)
    return (jax.tree_util.tree_map(put, tasks_seq), put(idx_seq),
            put(mask_seq),
            _place(num_samples, client_sharding(mesh), clients_axis=0))


def replicate_for_mesh(mesh: Mesh, tree: Any) -> Any:
    """Replicate host-side state (global model, defense state) onto the
    mesh. Multi-process: every host contributes its identical full copy via
    make_array_from_process_local_data (device_put cannot span processes)."""
    rep = replicated_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(tree, rep)
    return jax.tree_util.tree_map(
        lambda l: jax.make_array_from_process_local_data(
            rep, np.asarray(l), np.asarray(l).shape), tree)


def pad_clients(n_clients: int, mesh: Optional[Mesh]) -> int:
    """Smallest padded client count that tiles the mesh. On an elastic
    shrink the relaunched (smaller) mesh re-pads from scratch — the
    padding is a property of the CURRENT world, never carried over from
    the world that lost a host."""
    if mesh is None:
        return n_clients
    d = mesh.devices.size
    return int(-(-n_clients // d) * d)
