"""Parallelism layer: the `clients` device mesh and multi-host init.

The reference's only "distribution" is a sequential Python loop over clients
on one GPU (SURVEY §2.2). Here *clients are a mesh axis*: stacked per-client
inputs are placed with a `clients` NamedSharding, the jitted round computation
is partitioned by XLA across the mesh (each device trains its clients), and
aggregation reductions lower to ICI collectives. Multi-host (DCN) scale uses
the same program via `jax.distributed`.
"""
from dba_mod_tpu.parallel.mesh import (client_sharding, make_mesh,
                                       replicated_sharding,
                                       shard_round_inputs)
from dba_mod_tpu.parallel.distributed import initialize_distributed
