"""Multi-host (DCN) initialization.

One FL round is a single SPMD program, so pod-scale runs need only
`jax.distributed` process bootstrap: every host runs the same driver, the
mesh spans all hosts' devices, per-host input shards are placed with
`jax.make_array_from_process_local_data`, and XLA routes the aggregation
collectives over ICI within a slice and DCN across slices. This is the
TPU-native replacement for the NCCL/MPI backend slot the reference leaves
empty (SURVEY §2.2 communication row).
"""
from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger("dba_mod_tpu")

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Initialize jax.distributed when running multi-host.

    Explicit args win; otherwise standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) or cloud
    auto-detection. Returns True when a multi-process runtime was set up.
    No-op (False) for the common single-host case.
    """
    global _initialized
    coordinator_address = (coordinator_address or
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address is None and num_processes is None:
        return False
    if _initialized:  # idempotent: every Experiment calls this
        return jax.process_count() > 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=(num_processes if num_processes is not None else
                       int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None),
        process_id=(process_id if process_id is not None else
                    int(os.environ.get("JAX_PROCESS_ID", "-1"))
                    if "JAX_PROCESS_ID" in os.environ else None))
    _initialized = True
    logger.info("jax.distributed initialized: process %d/%d, %d local / %d "
                "global devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())
    return jax.process_count() > 1
