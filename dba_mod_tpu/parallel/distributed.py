"""Multi-host (DCN) initialization + the elastic peer-health layer.

One FL round is a single SPMD program, so pod-scale runs need only
`jax.distributed` process bootstrap: every host runs the same driver, the
mesh spans all hosts' devices, per-host input shards are placed with
`jax.make_array_from_process_local_data`, and XLA routes the aggregation
collectives over ICI within a slice and DCN across slices. This is the
TPU-native replacement for the NCCL/MPI backend slot the reference leaves
empty (SURVEY §2.2 communication row).

Elasticity (:class:`PeerHealth`): a JAX collective cannot survive a peer
vanishing mid-program — a lost host leaves the survivors wedged inside the
next collective, indistinguishable from a slow peer. Elastic rounds
therefore mean **detect → classify → restart shrunk**, never in-flight
recovery:

- every process writes a per-host heartbeat file into a shared directory
  (``heartbeat_dir``; local disk for single-machine multi-process runs, the
  shared checkpoint filesystem for real pods) every
  ``heartbeat_interval_s``;
- at round boundaries the driver beats with the round epoch and runs a
  non-blocking staleness check (optionally a bounded-timeout barrier), so
  "peer is gone" is distinguished from "peer is slow" *outside* any
  collective;
- when a stall does happen inside a collective, the PR-4 watchdog consults
  :meth:`PeerHealth.lost_peers` at its hard deadline and exits with the
  distinct ``EXIT_PEER_LOST`` (77) verdict instead of the generic stall
  abort — the supervisor (scripts/elastic_smoke.sh is the reference
  recipe) relaunches the survivors with ``JAX_NUM_PROCESSES`` shrunk and
  ``--resume auto``, and the mesh/padding layers rebuild over the
  surviving devices.

Heartbeats carry a membership *generation* (default: the world size, so a
shrink-restart never confuses the old world's files with the new one's;
override with ``DBA_ELASTIC_GEN`` for equal-size replacement restarts, or
have the supervisor clean ``heartbeat_dir``). Files from a different
generation are ignored. Everything here is a strict no-op unless
``heartbeat_interval_s > 0`` in a multi-process run: no thread, no files.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

import jax

logger = logging.getLogger("dba_mod_tpu")

_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Initialize jax.distributed when running multi-host.

    Explicit args win; otherwise standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) or cloud
    auto-detection. Returns True when a multi-process runtime was set up.
    No-op (False) for the common single-host case.
    """
    global _initialized
    coordinator_address = (coordinator_address or
                           os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coordinator_address is None and num_processes is None:
        return False
    if _initialized:  # idempotent: every Experiment calls this
        return jax.process_count() > 1
    try:
        # CPU cross-process collectives need the gloo transport; the
        # default ("none") makes every multi-process CPU round fail with
        # "Multiprocess computations aren't implemented on the CPU
        # backend". Harmless on TPU (the option only affects the CPU
        # backend); tolerated absent on jax versions that predate it.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover — other jax
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=(num_processes if num_processes is not None else
                       int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None),
        process_id=(process_id if process_id is not None else
                    int(os.environ.get("JAX_PROCESS_ID", "-1"))
                    if "JAX_PROCESS_ID" in os.environ else None))
    _initialized = True
    logger.info("jax.distributed initialized: process %d/%d, %d local / %d "
                "global devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())
    return jax.process_count() > 1


class PeerLostError(RuntimeError):
    """A peer host is gone (heartbeat stale past the timeout), not slow.

    Raised at round boundaries (and synthesized from collective failures by
    Experiment.run's classification pass). The CLI maps it to
    ``run_guard.EXIT_PEER_LOST`` (77) so a supervisor can relaunch the
    survivors shrunk instead of reporting a crash."""

    def __init__(self, lost: List[int], detail: str = ""):
        self.lost = sorted(lost)
        msg = (f"peer host(s) {self.lost} lost — heartbeat stale past the "
               f"timeout")
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class PeerHealth:
    """File-based peer liveness for one multi-process run.

    One instance per process. :meth:`start` writes the first heartbeat and
    launches a daemon beat thread; :meth:`beat` (also called at round
    boundaries with the boundary epoch) rewrites this host's file
    atomically; :meth:`lost_peers` reads every peer's file and returns the
    ids whose heartbeat is stale past ``timeout_s`` — the classification
    primitive the round boundary, the failure classifier, and the watchdog
    verdict all share. :meth:`barrier` is the bounded-timeout
    round-boundary barrier: it waits (never past ``timeout``) for every
    peer to reach a boundary epoch, raising :class:`PeerLostError` the
    moment any peer's heartbeat goes stale — a slow peer times the barrier
    out (returns False, the caller proceeds into the collective and the
    watchdog takes over), a dead one is reported before the program can
    wedge."""

    def __init__(self, folder: str | Path, process_id: int, world_size: int,
                 interval_s: float, timeout_s: float = 0.0,
                 gen: Optional[int] = None):
        self.folder = Path(folder)
        self.process_id = int(process_id)
        self.world_size = int(world_size)
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s) if timeout_s > 0 else (
            6.0 * self.interval_s)
        # membership generation: the world size unless the supervisor says
        # otherwise — a 2→1 shrink restart must not read the dead world's
        # heartbeat files as current-generation peers
        env_gen = os.environ.get("DBA_ELASTIC_GEN")
        self.gen = int(gen if gen is not None else
                       env_gen if env_gen is not None else self.world_size)
        self.boundary_epoch = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_wall: Optional[float] = None
        self._known_lost: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        self.folder.mkdir(parents=True, exist_ok=True)
        self._started_wall = time.time()
        self._stop.clear()
        self.beat()
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="dba-heartbeat")
            self._thread.start()
        logger.info("peer health: process %d/%d gen=%d beating every %.2fs "
                    "into %s (timeout %.2fs)", self.process_id,
                    self.world_size, self.gen, self.interval_s, self.folder,
                    self.timeout_s)

    def stop(self) -> None:
        """Clean shutdown: final beat marked ``stopped`` so peers draining
        at a different instant don't read the quiescing file as a loss."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, self.interval_s))
        if self._started_wall is not None:
            try:
                self.beat(stopped=True)
            except OSError:  # pragma: no cover — fs went away at teardown
                pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except OSError as exc:  # pragma: no cover — transient fs error
                logger.warning("peer health: heartbeat write failed (%r)",
                               exc)

    # ------------------------------------------------------------------ beat
    def _path(self, pid: int) -> Path:
        return self.folder / f"host_{pid}.json"

    def beat(self, boundary_epoch: Optional[int] = None,
             stopped: bool = False) -> None:
        # the whole write-then-rename stays under the lock: the daemon
        # beat thread and the main thread's boundary beat share one tmp
        # path, and an unlocked interleaving could rename a torn tmp into
        # place — which a peer would read as "unparsable = missing" and,
        # past the grace window, spuriously classify as a lost host
        with self._lock:
            if boundary_epoch is not None:
                self.boundary_epoch = int(boundary_epoch)
            payload = {"pid": self.process_id, "gen": self.gen,
                       "time": time.time(),
                       "boundary_epoch": self.boundary_epoch,
                       "ospid": os.getpid(), "stopped": bool(stopped)}
            path = self._path(self.process_id)
            tmp = path.with_suffix(f".tmp{self.process_id}")
            tmp.write_text(json.dumps(payload))
            tmp.replace(path)  # atomic: peers never read a torn heartbeat

    def _read(self, pid: int) -> Optional[Dict]:
        try:
            d = json.loads(self._path(pid).read_text())
        except (OSError, ValueError):
            return None
        return d if d.get("gen") == self.gen else None

    # ------------------------------------------------------------ liveness
    @property
    def peer_ids(self) -> List[int]:
        return [p for p in range(self.world_size) if p != self.process_id]

    def lost_peers(self, now: Optional[float] = None) -> List[int]:
        """Peer ids whose heartbeat is stale past ``timeout_s``.

        A peer with no current-generation file yet is only lost once the
        startup grace window (3× timeout from :meth:`start`) has passed —
        jax.distributed.initialize barriers all processes at startup, so a
        live peer writes its first beat within milliseconds of ours. A
        peer whose final beat is marked ``stopped`` exited cleanly and is
        never reported."""
        if self._started_wall is None:
            return []
        now = time.time() if now is None else now
        in_grace = (now - self._started_wall) < 3.0 * self.timeout_s
        lost = []
        for pid in self.peer_ids:
            d = self._read(pid)
            if d is None:
                if not in_grace:
                    lost.append(pid)
                continue
            if d.get("stopped"):
                continue
            if now - float(d["time"]) > self.timeout_s:
                lost.append(pid)
        new = set(lost) - self._known_lost
        if new:
            self._known_lost |= new
            from dba_mod_tpu.utils import telemetry
            telemetry.count("peer/heartbeat_missed", len(new))
            logger.error(
                "peer health: heartbeat from peer(s) %s stale past %.2fs — "
                "peer lost (slow peers keep beating; a silent one is gone)",
                sorted(new), self.timeout_s)
        return lost

    def check(self, epoch: int) -> None:
        """Non-blocking round-boundary check: beat with the boundary epoch,
        then raise :class:`PeerLostError` if any peer's heartbeat is
        stale — the cheap per-round detection path (one file write + one
        directory read)."""
        self.beat(boundary_epoch=epoch)
        lost = self.lost_peers()
        if lost:
            raise PeerLostError(lost, detail=f"epoch {epoch} boundary")

    def barrier(self, epoch: int, timeout: float) -> bool:
        """Bounded-timeout boundary barrier: True when every peer reported
        a boundary epoch >= ``epoch`` within ``timeout`` seconds, False on
        timeout (peer slow — proceed, the watchdog owns in-collective
        stalls). Raises :class:`PeerLostError` if a peer dies while we
        wait."""
        self.beat(boundary_epoch=epoch)
        deadline = time.monotonic() + float(timeout)
        poll = max(min(self.interval_s / 2.0, 0.25), 0.02)
        while True:
            lost = self.lost_peers()
            if lost:
                raise PeerLostError(lost, detail=f"epoch {epoch} barrier")
            behind = []
            for pid in self.peer_ids:
                d = self._read(pid)
                if d is None or int(d.get("boundary_epoch", 0)) < epoch:
                    behind.append(pid)
            if not behind:
                return True
            if time.monotonic() >= deadline:
                logger.warning(
                    "peer health: barrier for epoch %d timed out after "
                    "%.2fs waiting on peer(s) %s — peers are slow, not "
                    "gone; proceeding", epoch, timeout, behind)
                return False
            time.sleep(poll)
