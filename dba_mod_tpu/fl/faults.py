"""Deterministic fault injection for the round path.

The reference simulator (and the seed port) assumes every selected client
returns a finite, well-formed delta. Real federated deployments — and the
robust-aggregation literature this framework exists to study — are defined by
partial participation and byzantine payloads. This module perturbs per-round
client *outcomes* (what the server receives), never the training computation
itself: faults model the uplink, not the local SGD.

Fault taxonomy (per client, per round; mutually exclusive, resolved in
priority order dropout > corrupt > blowup > stale):

  dropout — the client never reports. Its payload is zeroed and it is
            excluded from the survivor mask (the server always knows who
            reported, independent of any screening).
  corrupt — the payload arrives NaN/Inf-poisoned (bit flips, truncated
            uploads). Caught by the server's finite screen.
  blowup  — the payload is scaled by ``fault_blowup_factor`` (fp overflow,
            exploding local training). Caught by the norm screen when
            enabled; otherwise it may push the aggregated model non-finite,
            which the round-level retry path handles.
  stale   — the client replays the delta it *submitted* the previous round
            (straggler whose round-N upload arrives at round N+1). Finite
            and norm-plausible, hence deliberately NOT screenable. Applies
            to deltas only: FoolsGold aggregates gradient accumulators, so
            under FoolsGold a stale client is a no-op by construction.

The plan is a pure function of ``(fault_seed, epoch)`` via ``jax.random`` —
a fault schedule reproduces exactly across runs and resumes, and is
independent of every other RNG stream (selection, plans, training). One
resume caveat: the stale lane's replay source (last round's submitted
deltas) is not checkpointed, so the first post-resume stale replay falls
back to a zero delta; the plan itself is unaffected. All injection runs
inside the jitted round program; with ``fault_injection: false`` none of
it is traced, so the fault path costs nothing when disabled.
"""
from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from dba_mod_tpu import config as cfg
from dba_mod_tpu.ops.aggregation import _bc_mask as _bc


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Static (compile-time) fault-injection knobs."""
    enabled: bool
    dropout_prob: float
    corrupt_prob: float
    blowup_prob: float
    blowup_factor: float
    stale_prob: float
    seed: int

    @property
    def stale_enabled(self) -> bool:
        return self.enabled and self.stale_prob > 0.0

    @classmethod
    def from_params(cls, p: cfg.Params) -> "FaultConfig":
        probs = {k: float(p.get(f"fault_{k}_prob", 0.0))
                 for k in ("dropout", "corrupt", "blowup", "stale")}
        for k, v in probs.items():
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"fault_{k}_prob={v} not in [0, 1]")
        return cls(enabled=bool(p.get("fault_injection", False)),
                   dropout_prob=probs["dropout"],
                   corrupt_prob=probs["corrupt"],
                   blowup_prob=probs["blowup"],
                   blowup_factor=float(p.get("fault_blowup_factor", 1e8)),
                   stale_prob=probs["stale"],
                   seed=int(p.get("fault_seed", 0)))


class FaultPlan(NamedTuple):
    """Per-client fault assignment for one round (all [C] bool)."""
    dropped: jax.Array
    corrupt: jax.Array
    blowup: jax.Array
    stale: jax.Array


def make_fault_plan(fcfg: FaultConfig, rng: jax.Array,
                    counted: jax.Array) -> FaultPlan:
    """Draw one round's fault assignment. ``counted`` ([C] bool) marks real
    clients — inert mesh-padding lanes never fault (their zero deltas must
    stay zero or padding would perturb FedAvg's static divisor)."""
    kd, kc, kb, ks = jax.random.split(rng, 4)

    def draw(k, p, free):
        hit = (jax.random.uniform(k, counted.shape) < p) & free
        return hit, free & ~hit

    free = counted
    dropped, free = draw(kd, fcfg.dropout_prob, free)
    corrupt, free = draw(kc, fcfg.corrupt_prob, free)
    blowup, free = draw(kb, fcfg.blowup_prob, free)
    stale, _ = draw(ks, fcfg.stale_prob, free)
    return FaultPlan(dropped, corrupt, blowup, stale)


def perturb_tree(tree: Any, plan: FaultPlan, fcfg: FaultConfig,
                 stale_tree: Optional[Any] = None) -> Any:
    """Apply one round's faults to a client-stacked payload pytree.

    Non-float leaves pass through untouched (NaN has no integer encoding;
    the survivor mask, not the payload, is what excludes a dropped client's
    integer state). When ``stale_tree`` is None the stale lane is a no-op.
    """
    def f(leaf, stale_leaf):
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        x = jnp.where(_bc(plan.corrupt, leaf), jnp.nan, leaf)
        x = jnp.where(_bc(plan.blowup, leaf),
                      leaf * jnp.asarray(fcfg.blowup_factor, leaf.dtype), x)
        if stale_leaf is not None:
            x = jnp.where(_bc(plan.stale, leaf),
                          stale_leaf.astype(leaf.dtype), x)
        x = jnp.where(_bc(plan.dropped, leaf),
                      jnp.zeros((), leaf.dtype), x)
        return x

    if stale_tree is None:
        return jax.tree_util.tree_map(lambda l: f(l, None), tree)
    return jax.tree_util.tree_map(f, tree, stale_tree)
