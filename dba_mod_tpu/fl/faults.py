"""Deterministic fault injection for the round path.

The reference simulator (and the seed port) assumes every selected client
returns a finite, well-formed delta. Real federated deployments — and the
robust-aggregation literature this framework exists to study — are defined by
partial participation and byzantine payloads. This module perturbs per-round
client *outcomes* (what the server receives), never the training computation
itself: faults model the uplink, not the local SGD.

Fault taxonomy (per client, per round; mutually exclusive, resolved in
priority order host-loss > dropout > corrupt > blowup > stale):

  dropout — the client never reports. Its payload is zeroed and it is
            excluded from the survivor mask (the server always knows who
            reported, independent of any screening).
  corrupt — the payload arrives NaN/Inf-poisoned (bit flips, truncated
            uploads). Caught by the server's finite screen.
  blowup  — the payload is scaled by ``fault_blowup_factor`` (fp overflow,
            exploding local training). Caught by the norm screen when
            enabled; otherwise it may push the aggregated model non-finite,
            which the round-level retry path handles.
  stale   — the client replays the delta it *submitted* the previous round
            (straggler whose round-N upload arrives at round N+1). Finite
            and norm-plausible, hence deliberately NOT screenable. Applies
            to deltas only: FoolsGold aggregates gradient accumulators, so
            under FoolsGold a stale client is a no-op by construction.

Host-level lane (``fault_host_loss_prob``, PR 6): a whole *host* vanishes
at a round boundary — the deployment-layer failure the elastic layer
(parallel/distributed.py) exists to survive. The victim is a pure
function of the same per-round fault key (:func:`host_loss_victim`), so
both enactments agree on who dies and when:

  - multi-process runs: the experiment driver evaluates the victim
    host-side at the round boundary and the designated process SIGKILLs
    itself — the survivors then exercise the real detect → classify →
    restart-shrunk path (heartbeats, exit 77, shrunk relaunch) in CI
    rather than hoping it works;
  - single-process runs (``fault_num_hosts`` virtual hosts): the victim
    host's whole contiguous client slice is dropped through the survivor
    mask inside the round program — the masked-cohort semantics a real
    shrink converges to, without needing processes.

The plan is a pure function of ``(fault_seed, epoch)`` via ``jax.random`` —
a fault schedule reproduces exactly across runs and resumes, and is
independent of every other RNG stream (selection, plans, training). The
stale lane's replay source (last round's submitted deltas) is checkpointed
in the full-state aux sidecar (``save_model`` runs), so a resumed run's
first stale replay is faithful; only sidecar-less resumes (pretrain /
model-only checkpoints) fall back to a zero delta. All injection runs
inside the jitted round program; with ``fault_injection: false`` none of
it is traced, so the fault path costs nothing when disabled.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from dba_mod_tpu import config as cfg
from dba_mod_tpu.ops.aggregation import _bc_mask as _bc

logger = logging.getLogger("dba_mod_tpu")


@dataclasses.dataclass(frozen=True)
class FaultConfig:
    """Static (compile-time) fault-injection knobs."""
    enabled: bool
    dropout_prob: float
    corrupt_prob: float
    blowup_prob: float
    blowup_factor: float
    stale_prob: float
    seed: int
    # host-level lane: P(the round loses one whole host) and the host
    # count the client axis is partitioned into. `host_loss_in_program` is
    # the enactment switch — True (single-process) masks the victim's
    # client slice inside the round program; False (multi-process) leaves
    # the round program untouched and the experiment driver kills the
    # victim process at the boundary instead (the loss must not be
    # double-counted).
    host_loss_prob: float = 0.0
    num_hosts: int = 0
    host_loss_in_program: bool = True

    @property
    def stale_enabled(self) -> bool:
        return self.enabled and self.stale_prob > 0.0

    @property
    def host_loss_enabled(self) -> bool:
        return self.enabled and self.host_loss_prob > 0.0

    @classmethod
    def from_params(cls, p: cfg.Params) -> "FaultConfig":
        probs = {k: float(p.get(f"fault_{k}_prob", 0.0))
                 for k in ("dropout", "corrupt", "blowup", "stale",
                           "host_loss")}
        for k, v in probs.items():
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"fault_{k}_prob={v} not in [0, 1]")
        enabled = bool(p.get("fault_injection", False))
        pc = jax.process_count()
        if pc > 1:
            # real hosts: the experiment kills the victim process at the
            # round boundary; the program sees only the consequences
            num_hosts, in_program = pc, False
        else:
            num_hosts, in_program = int(p.get("fault_num_hosts", 0)), True
            if enabled and probs["host_loss"] > 0.0 and num_hosts < 2:
                # NOT an error: a 2-process run with the lane on loses its
                # victim by design, the survivor exits 77, and the
                # supervisor relaunches ONE process with the same YAML —
                # raising here would break the exact recovery path the
                # lane exists to exercise. Single-process simulation needs
                # an explicit fault_num_hosts >= 2; without one the lane
                # is off, loudly.
                logger.warning(
                    "fault_host_loss_prob=%s ignored: single-process run "
                    "with fault_num_hosts=%d — set fault_num_hosts >= 2 "
                    "to simulate host loss through the survivor mask "
                    "(a shrunk-to-1 elastic relaunch lands here by "
                    "design and must start)", probs["host_loss"],
                    num_hosts)
                probs["host_loss"] = 0.0
        return cls(enabled=enabled,
                   dropout_prob=probs["dropout"],
                   corrupt_prob=probs["corrupt"],
                   blowup_prob=probs["blowup"],
                   blowup_factor=float(p.get("fault_blowup_factor", 1e8)),
                   stale_prob=probs["stale"],
                   seed=int(p.get("fault_seed", 0)),
                   host_loss_prob=probs["host_loss"],
                   num_hosts=num_hosts,
                   host_loss_in_program=in_program)


class FaultPlan(NamedTuple):
    """Per-client fault assignment for one round (all [C] bool)."""
    dropped: jax.Array
    corrupt: jax.Array
    blowup: jax.Array
    stale: jax.Array


# fold_in tag isolating the host-loss stream from the per-client draws:
# enabling the host lane must not reshuffle the client-lane assignments an
# existing fault_seed already produces (and vice versa)
_HOST_LANE_TAG = 0x4057


def host_loss_victim(fcfg: FaultConfig, rng: jax.Array) -> jax.Array:
    """Scalar victim for the host-loss lane: the host index the round
    loses, or -1 for no loss. Pure function of the per-round fault key, so
    the experiment driver (multi-process boundary kill) and the round
    program (single-process survivor-mask simulation) derive the SAME
    victim independently."""
    kl, kv = jax.random.split(jax.random.fold_in(rng, _HOST_LANE_TAG))
    lost = jax.random.uniform(kl, ()) < fcfg.host_loss_prob
    v = jax.random.randint(kv, (), 0, max(fcfg.num_hosts, 1))
    return jnp.where(lost, v, -1)


def host_of_lane(num_lanes: int, num_hosts: int) -> jax.Array:
    """[C] host index per client lane: contiguous proportional slices,
    the same leading-axis partition `parallel/mesh.py::_place` hands each
    process of a real multi-host run."""
    return (jnp.arange(num_lanes) * num_hosts) // max(num_lanes, 1)


def make_fault_plan(fcfg: FaultConfig, rng: jax.Array,
                    counted: jax.Array) -> FaultPlan:
    """Draw one round's fault assignment. ``counted`` ([C] bool) marks real
    clients — inert mesh-padding lanes never fault (their zero deltas must
    stay zero or padding would perturb FedAvg's static divisor). The
    host-loss lane resolves first (the whole host vanished — its clients
    can't independently corrupt or straggle) and folds into ``dropped``:
    downstream, a host-dropped client is exactly a client that never
    reported."""
    kd, kc, kb, ks = jax.random.split(rng, 4)

    def draw(k, p, free):
        hit = (jax.random.uniform(k, counted.shape) < p) & free
        return hit, free & ~hit

    free = counted
    host_dropped = jnp.zeros_like(counted)
    if fcfg.host_loss_enabled and fcfg.host_loss_in_program:
        victim = host_loss_victim(fcfg, rng)
        hosts = host_of_lane(counted.shape[0], fcfg.num_hosts)
        host_dropped = (hosts == victim) & counted
        free = free & ~host_dropped
    dropped, free = draw(kd, fcfg.dropout_prob, free)
    corrupt, free = draw(kc, fcfg.corrupt_prob, free)
    blowup, free = draw(kb, fcfg.blowup_prob, free)
    stale, _ = draw(ks, fcfg.stale_prob, free)
    return FaultPlan(dropped | host_dropped, corrupt, blowup, stale)


def perturb_tree(tree: Any, plan: FaultPlan, fcfg: FaultConfig,
                 stale_tree: Optional[Any] = None) -> Any:
    """Apply one round's faults to a client-stacked payload pytree.

    Non-float leaves pass through untouched (NaN has no integer encoding;
    the survivor mask, not the payload, is what excludes a dropped client's
    integer state). When ``stale_tree`` is None the stale lane is a no-op.
    """
    def f(leaf, stale_leaf):
        if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            return leaf
        x = jnp.where(_bc(plan.corrupt, leaf), jnp.nan, leaf)
        x = jnp.where(_bc(plan.blowup, leaf),
                      leaf * jnp.asarray(fcfg.blowup_factor, leaf.dtype), x)
        if stale_leaf is not None:
            x = jnp.where(_bc(plan.stale, leaf),
                          stale_leaf.astype(leaf.dtype), x)
        x = jnp.where(_bc(plan.dropped, leaf),
                      jnp.zeros((), leaf.dtype), x)
        return x

    if stale_tree is None:
        return jax.tree_util.tree_map(lambda l: f(l, None), tree)
    return jax.tree_util.tree_map(f, tree, stale_tree)
