"""The round engine: one jitted XLA computation per FL round, plus the jitted
local/global evaluation batteries.

Replaces main.py:135-231's sequential orchestration: the round computation
vmaps the client step over the stacked clients axis, feeds the stacked deltas
straight into the configured aggregator, and returns the new global state —
server→client broadcast and client→server upload are XLA data flow, not
host dict-copies (contrast image_train.py:32, helper.py:223-227).
"""
from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu import config as cfg
from dba_mod_tpu.models import ModelDef, ModelVars
from dba_mod_tpu.fl.client import ClientMetrics, make_client_step
from dba_mod_tpu.fl.device_data import DeviceData
from dba_mod_tpu.fl.evaluation import EvalResult, make_eval_fn
from dba_mod_tpu.fl.state import ClientTask, RoundHyper
from dba_mod_tpu.ops import aggregation as agg


class RoundResult(NamedTuple):
    new_vars: ModelVars
    new_fg_state: agg.FoolsGoldState
    metrics: ClientMetrics        # stacked [C, E]
    deltas: ModelVars             # stacked [C, ...] (for local evals)
    delta_norms: jax.Array        # [C] ‖Δ_params‖ — scale_result.csv distance
    wv: jax.Array                 # [C] aggregation weights (RFA/FoolsGold)
    alpha: jax.Array              # [C] RFA distances / FoolsGold alphas
    num_oracle_calls: jax.Array   # RFA oracle counter (1 otherwise)


class LocalEvals(NamedTuple):
    """Per-client local-model eval rows (all [C]): reference CSV parity.
    clean/pre-scale rows evaluate the unscaled model (image_train.py:150-164
    runs Mytest/Mytest_poison BEFORE scaling); post rows the submitted one."""
    clean: EvalResult             # test_result rows (image_train.py:268-271)
    poison_pre: EvalResult        # posiontest_result pre-scale (:157-164)
    poison_post: EvalResult       # posiontest_result post-scale (:275-282)
    agent_trigger: EvalResult     # poisontriggertest_result (:291-295)


class GlobalEvals(NamedTuple):
    clean: EvalResult             # Mytest(global) (main.py:198-201)
    poison: EvalResult            # Mytest_poison(global) (main.py:207-215)
    per_trigger: EvalResult       # [T] rows (main.py:225-231)


@dataclasses.dataclass
class EvalPlans:
    """Device-resident eval index plans, built once per experiment."""
    clean_idx: jax.Array      # [S, B]
    clean_slots: jax.Array
    clean_mask: jax.Array
    poison_idx: jax.Array     # [S', B] — target-label samples dropped
    poison_slots: jax.Array
    poison_mask: jax.Array


class RoundEngine:
    """Holds the jitted round + eval computations for one experiment config.

    With a mesh, the stacked clients axis is sharded across devices (GSPMD via
    jit in_shardings): each device trains its clients locally and the
    aggregation reductions lower to ICI collectives (SURVEY §2.2)."""

    def __init__(self, params: cfg.Params, model_def: ModelDef,
                 data: DeviceData, plans: EvalPlans, mesh=None):
        self.params = params
        self.hyper = RoundHyper.from_params(params)
        self.model_def = model_def
        self.data = data
        self.plans = plans
        self.mesh = mesh
        hyper = self.hyper
        fg_enabled = hyper.aggregation == cfg.AGGR_FOOLSGOLD
        client_step = make_client_step(model_def, data, hyper, fg_enabled)
        eval_clean = make_eval_fn(model_def, data, poison=False)
        eval_poison = make_eval_fn(model_def, data, poison=True)
        is_poison_run = bool(params["is_poison"])

        def round_fn(global_vars: ModelVars, fg_state: agg.FoolsGoldState,
                     tasks: ClientTask, idx, mask, num_samples,
                     rng) -> RoundResult:
            C = idx.shape[0]
            rng, dp_rng = jax.random.split(rng)
            client_rngs = jax.random.split(rng, C)
            res = jax.vmap(client_step, in_axes=(None, 0, 0, 0, 0))(
                global_vars, tasks, idx, mask, client_rngs)

            wv = jnp.zeros((C,), jnp.float32)
            alpha = jnp.zeros((C,), jnp.float32)
            calls = jnp.int32(1)
            new_fg = fg_state
            if hyper.aggregation == cfg.AGGR_MEAN:
                new_vars = agg.fedavg_update(
                    global_vars, res.delta, hyper.eta, hyper.no_models,
                    hyper.sigma if hyper.diff_privacy else 0.0, dp_rng)
            elif hyper.aggregation == cfg.AGGR_GEO_MED:
                r = agg.geometric_median_update(
                    global_vars, res.delta, num_samples, hyper.eta,
                    maxiter=hyper.geom_median_maxiter,
                    max_update_norm=hyper.max_update_norm,
                    dp_sigma=hyper.sigma if hyper.diff_privacy else 0.0,
                    rng=dp_rng)
                new_vars, calls, wv, alpha = (r.new_state, r.num_oracle_calls,
                                              r.wv, r.distances)
            else:  # foolsgold
                r = agg.foolsgold_update(
                    global_vars.params, res.fg_grads, res.fg_feature,
                    tasks.participant_id, fg_state, hyper.eta, hyper.lr,
                    hyper.momentum, hyper.weight_decay,
                    use_memory=hyper.fg_use_memory)
                # BN stats are not aggregated by FoolsGold (the reference
                # steps an optimizer over named_parameters only,
                # helper.py:286-290)
                new_vars = ModelVars(r.new_params, global_vars.batch_stats)
                new_fg, wv, alpha = r.new_fg_state, r.wv, r.alpha
            from dba_mod_tpu.ops.losses import tree_global_norm
            delta_norms = jax.vmap(
                lambda d: tree_global_norm(d.params))(res.delta)
            return RoundResult(new_vars, new_fg, res.metrics, res.delta,
                               delta_norms, wv, alpha, calls)

        if mesh is not None:
            from dba_mod_tpu.parallel.mesh import (client_sharding,
                                                   replicated_sharding)
            rep = replicated_sharding(mesh)
            cs = client_sharding(mesh)
            # (global_vars, fg_state, tasks, idx, mask, num_samples, rng) —
            # pytree-prefix shardings; outputs left to the partitioner.
            self.round_fn = jax.jit(
                round_fn, in_shardings=(rep, rep, cs, cs, cs, cs, rep))
        else:
            self.round_fn = jax.jit(round_fn)

        def local_evals(global_vars: ModelVars, deltas: ModelVars,
                        tasks: ClientTask) -> LocalEvals:
            def per_client(delta: ModelVars, scale, adv_slot):
                unscaled = jax.tree_util.tree_map(
                    lambda g, d: g + d / scale, global_vars, delta)
                scaled = jax.tree_util.tree_map(
                    lambda g, d: g + d, global_vars, delta)
                clean = eval_clean(unscaled, plans.clean_idx,
                                   plans.clean_slots, plans.clean_mask,
                                   jnp.int32(-1))
                if is_poison_run:
                    pre = eval_poison(unscaled, plans.poison_idx,
                                      plans.poison_slots, plans.poison_mask,
                                      jnp.int32(-1))
                    post = eval_poison(scaled, plans.poison_idx,
                                       plans.poison_slots, plans.poison_mask,
                                       jnp.int32(-1))
                    agent = eval_poison(scaled, plans.poison_idx,
                                        plans.poison_slots, plans.poison_mask,
                                        adv_slot)
                else:
                    zero = EvalResult(*(jnp.float32(0),) * 4)
                    pre = post = agent = zero
                return LocalEvals(clean, pre, post, agent)

            return jax.vmap(per_client, in_axes=(0, 0, 0))(
                deltas, tasks.scale, tasks.adv_slot)

        if mesh is not None:
            from dba_mod_tpu.parallel.mesh import (client_sharding,
                                                   replicated_sharding)
            self.local_evals_fn = jax.jit(
                local_evals,
                in_shardings=(replicated_sharding(mesh),
                              client_sharding(mesh), client_sharding(mesh)))
        else:
            self.local_evals_fn = jax.jit(local_evals)

        # Global per-trigger battery (main.py:225-231): centralized mode tests
        # each sub-pattern by index — only when `centralized_test_trigger` is
        # set (main.py:226) — distributed mode tests each adversary's pattern
        # (= its slot).
        if params.is_centralized_attack:
            n_triggers = (int(params["trigger_num"])
                          if bool(params["centralized_test_trigger"]) else 0)
        else:
            n_triggers = params.num_adversaries
        self.num_global_triggers = n_triggers
        trigger_ids = jnp.arange(max(n_triggers, 1), dtype=jnp.int32)

        def global_evals(model_vars: ModelVars) -> GlobalEvals:
            clean = eval_clean(model_vars, plans.clean_idx, plans.clean_slots,
                               plans.clean_mask, jnp.int32(-1))
            if is_poison_run:
                poison = eval_poison(model_vars, plans.poison_idx,
                                     plans.poison_slots, plans.poison_mask,
                                     jnp.int32(-1))
                if n_triggers > 0:
                    per_trigger = jax.vmap(
                        lambda t: eval_poison(model_vars, plans.poison_idx,
                                              plans.poison_slots,
                                              plans.poison_mask,
                                              t))(trigger_ids)
                else:
                    zero = EvalResult(*(jnp.float32(0),) * 4)
                    per_trigger = jax.tree_util.tree_map(
                        lambda z: jnp.zeros((1,)), zero)
            else:
                zero = EvalResult(*(jnp.float32(0),) * 4)
                poison = zero
                per_trigger = jax.tree_util.tree_map(
                    lambda z: jnp.zeros((max(n_triggers, 1),)), zero)
            return GlobalEvals(clean, poison, per_trigger)

        self.global_evals_fn = jax.jit(global_evals)

        def backdoor_acc(model_vars: ModelVars) -> jax.Array:
            """Combined-trigger backdoor accuracy of the global model — feeds
            the LOAN adaptive poison LR (loan_train.py:67-75)."""
            r = eval_poison(model_vars, plans.poison_idx, plans.poison_slots,
                            plans.poison_mask, jnp.int32(-1))
            return r.acc

        self.backdoor_acc_fn = jax.jit(backdoor_acc)
