"""The round engine: jitted train + aggregate computations, plus the jitted
local/global evaluation batteries.

Replaces main.py:135-231's sequential orchestration. A round is:

  train_fn   — for each `aggr_epoch_interval` segment (global epoch), the
               vmapped client step runs all clients in parallel, chaining each
               client's state across segments (the reference's local model
               trains continuously within a round, re-anchoring its distance
               loss and scaling at each global epoch — image_train.py:50-54,
               :306); emits Δ = w_end - w_global plus FoolsGold gradient
               accumulators and per-segment metrics.
  aggregate_fn — the configured rule over the stacked deltas.

Splitting the two lets the sequential debug mode (SURVEY §7.2.4) run clients
one at a time through the identical per-client program and still share the
aggregation path. Server→client broadcast and client→server upload are XLA
data flow, not host dict-copies (contrast image_train.py:32,
helper.py:223-227).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu import config as cfg
from dba_mod_tpu.models import ModelDef, ModelVars
from dba_mod_tpu.fl import faults as flt
from dba_mod_tpu.fl.client import ClientMetrics, make_client_step
from dba_mod_tpu.fl.device_data import DeviceData
from dba_mod_tpu.fl.evaluation import EvalResult, make_eval_fn
from dba_mod_tpu.fl.state import ClientTask, RoundHyper
from dba_mod_tpu.ops import aggregation as agg
from dba_mod_tpu.ops.losses import tree_global_norm
from dba_mod_tpu.utils import telemetry


def count_bn_layers(batch_stats: Any) -> int:
    """Number of BatchNorm layers = number of `mean` running-stat leaves.

    Each BN layer in the reference's state_dict carries one
    `num_batches_tracked` scalar alongside running_mean/running_var; RFA's
    Weiszfeld distance sums squared differences over ALL state entries
    (helper.py:376-381), so the counter term enters the geometry once per BN
    layer."""
    paths = jax.tree_util.tree_flatten_with_path(batch_stats)[0]
    n = 0
    for path, _leaf in paths:
        last = path[-1]
        key = getattr(last, "key", getattr(last, "name", None))
        if key == "mean":
            n += 1
    return n


def nbt_client_deltas(mask_seq: jax.Array, scale_seq: jax.Array) -> jax.Array:
    """Per-client `num_batches_tracked` deltas for one round, [C] f32.

    torch BN increments the counter once per train-mode forward batch, so a
    client's counter delta is its number of REAL (non-padded) batch steps;
    the model-replacement epilogue scales the whole state_dict including the
    counter — `anchor + (v-anchor)·γ` copied into an int64 buffer truncates
    (image_train.py:166-171) — and with aggr_epoch_interval > 1 each segment
    re-anchors, so the round delta is Σ_seg trunc(steps_seg · γ_seg).

    mask_seq: [S, C, E, steps, B] validity mask; scale_seq: [S, C]."""
    steps = jnp.sum(jnp.any(mask_seq, axis=-1), axis=(2, 3))   # [S, C]
    return jnp.sum(jnp.trunc(steps.astype(jnp.float32) * scale_seq), axis=0)


class TrainResult(NamedTuple):
    deltas: ModelVars             # stacked [C, ...]: w_end - w_global
    fg_grads: Any                 # [C, ...] grads accumulated over the round
    fg_feature: jax.Array         # [C, L] similarity-layer grad, flattened
    metrics: ClientMetrics        # [I, C, E] per segment/client/epoch
    delta_norms: jax.Array        # [C] ‖Δ_params‖ — scale_result.csv distance
    batch_loss: jax.Array         # [I, C, E*S] per-batch loss ([I, C, 0]
                                  # when vis_train_batch_loss is off)
    batch_dist: jax.Array         # [I, C, E*S] per-batch post-step distance
                                  # ([I, C, 0] when batch_track_distance off)
    seg_deltas: Any               # list (len I-1) of full-state ModelVars
                                  # [C, ...] cumulative deltas at each
                                  # INTERMEDIATE segment end — feeds the
                                  # per-epoch local clean evals when
                                  # aggr_epoch_interval > 1
                                  # (image_train.py:268-271 runs inside the
                                  # global-epoch loop); empty list when I == 1


class RobustStats(NamedTuple):
    """Per-round fault-tolerance outcome, computed inside the jitted round
    program (None in the payload when the fault layer is off)."""
    n_dropped: jax.Array      # i32 — injected dropouts (never reported)
    n_quarantined: jax.Array  # i32 — reported but failed the screen
    n_surviving: jax.Array    # i32 — survivors among counted clients
    degraded: jax.Array       # bool — aggregation skipped (< min survivors)
    global_finite: jax.Array  # bool — post-aggregation model is all-finite
    survivor_mask: jax.Array  # [C] bool


class ForensicStats(NamedTuple):
    """Per-client defense-forensics diagnostics, computed inside the jitted
    round program when `forensics: true` (None in the payload otherwise).
    Rides the payload's single device_get at finalize — no host callbacks
    inside jit, no extra sync."""
    recv_norms: jax.Array     # [C] ‖Δ_params‖ as RECEIVED by the server
                              # (post fault injection; equals delta_norms
                              # when the fault layer is off — NaN/Inf for
                              # corrupted payloads, honestly)
    cosine_to_agg: jax.Array  # [C] cos(received Δ_c, applied global update)
    verdict: jax.Array        # [C] bool — client entered the aggregate
    reason: jax.Array         # [C] i32 quarantine reason (REASON_*)
    oracle_calls: jax.Array   # i32 — RFA Weiszfeld oracle count (1 else)


# quarantine-reason codes carried in ForensicStats.reason
REASON_OK = 0           # aggregated
REASON_DROPPED = 1      # never reported (injected dropout)
REASON_NONFINITE = 2    # failed the finite screen
REASON_NORM = 3         # exceeded the norm-screen threshold
REASON_NAMES = {REASON_OK: "ok", REASON_DROPPED: "dropped",
                REASON_NONFINITE: "nonfinite", REASON_NORM: "norm_exceeded"}


def forensic_stats(global_vars: ModelVars, new_vars: ModelVars,
                   recv_deltas: ModelVars, survivor_mask: jax.Array,
                   reason: jax.Array, oracle_calls) -> ForensicStats:
    """Assemble the per-client forensics pytree (jit-traced).

    `recv_deltas` are the deltas the SERVER received (post-fault); the
    cosine compares each against the update the server actually APPLIED
    (new - old params), which works uniformly across all three aggregation
    rules (and yields 0 for a degraded round, where the update is zero).
    A NaN-corrupted row produces a NaN norm/cosine for that client only —
    rows are independent, so nothing leaks across clients."""
    recv_norms = jax.vmap(
        lambda d: tree_global_norm(d.params))(recv_deltas)
    pts = agg.flatten_stacked(recv_deltas.params)              # [C, P]
    upd = agg.flatten_stacked(jax.tree_util.tree_map(
        lambda n, g: (n - g)[None], new_vars.params,
        global_vars.params))[0]                                # [P]
    unorm = jnp.sqrt(jnp.sum(upd * upd))
    denom = jnp.maximum(recv_norms * unorm, 1e-12)
    cos = (pts @ upd) / denom
    return ForensicStats(recv_norms, cos, survivor_mask,
                         reason.astype(jnp.int32),
                         jnp.asarray(oracle_calls, jnp.int32))


def _per_client_finite(tree: Any) -> jax.Array:
    """[C] bool — every leaf entry of each client's stacked row is finite."""
    flags = None
    for l in jax.tree_util.tree_leaves(tree):
        f = jnp.all(jnp.isfinite(l.astype(jnp.float32))
                    .reshape(l.shape[0], -1), axis=1)
        flags = f if flags is None else flags & f
    return flags


def screen_client_updates(deltas: ModelVars, reported: jax.Array,
                          counted: jax.Array, norm_mult: jax.Array,
                          extra_trees=()):
    """The server-side delta validation/quarantine pass (jit-traced).

    Two screens over the stacked client payloads:
      finite — every entry of the delta (and any `extra_trees`, e.g. the
               FoolsGold gradient accumulators) must be finite;
      norm   — ‖Δ_params‖ must not exceed `norm_mult` × the median norm of
               the reported-and-finite counted clients. `norm_mult` is a
               TRACED scalar so round-level retries can escalate it without
               recompiling; <= 0 disables the norm screen (threshold = ∞).

    Returns (survivor_mask [C] bool, norms [C]). A client that never
    reported (`reported` False) is excluded regardless of screens; inert
    padding lanes (`counted` False) never enter the median.
    """
    finite = _per_client_finite(deltas)
    for t in extra_trees:
        finite = finite & _per_client_finite(t)
    norms = jax.vmap(lambda d: tree_global_norm(d.params))(deltas)
    valid = reported & finite & counted
    med = jnp.nanmedian(jnp.where(valid, norms, jnp.nan))
    thresh = jnp.where(norm_mult > 0, norm_mult * med, jnp.inf)
    return reported & finite & (norms <= thresh), norms


def model_health_stats(old_vars: Any, new_vars: Any):
    """The jitted half of the post-merge model-health sentinel: (all leaves
    of the committed model finite, global L2 norm of the applied update).
    One reduction pass over the tree — cheap relative to a round; callers
    jit it once and pay one scalar host sync per checked merge."""
    new_leaves = jax.tree_util.tree_leaves(new_vars)
    finite = jnp.asarray(True)
    sq = jnp.float32(0.0)
    for o, n in zip(jax.tree_util.tree_leaves(old_vars), new_leaves):
        if not jnp.issubdtype(n.dtype, jnp.floating):
            continue
        finite = finite & jnp.all(jnp.isfinite(n))
        d = (n - o).astype(jnp.float32)
        sq = sq + jnp.sum(d * d)
    return finite, jnp.sqrt(sq)


class HealthSentinel:
    """Post-merge model-health gate shared by both engines
    (``model_health_check``): an unhealthy merge is one whose committed
    model has a non-finite leaf, or — once ``warmup`` healthy merges have
    seeded the trailing EMA — whose update norm exceeds ``band`` × that
    EMA (``health_norm_band``; 0 keeps only the finite check). Healthy
    commits feed the EMA and a last-good ring of up to ``ring_size``
    in-memory model versions; ``rollback_target`` hands back the newest
    ring entry (or the caller's pre-merge fallback when the ring is off or
    still empty). The ring is in-memory only — a resumed run restarts it
    from its first healthy merge, while (ema, merges) ride the async aux
    sidecar via state()/load_state() so the band re-arms deterministically."""

    def __init__(self, band: float, ema_alpha: float, warmup: int,
                 ring_size: int):
        self.band = float(band)
        self.alpha = float(ema_alpha)
        self.warmup = int(warmup)
        self.ring_size = int(ring_size)
        self.ema = 0.0
        self.merges = 0
        self.ring: List[Tuple[int, Any]] = []  # (version, model vars)
        self._fn = jax.jit(model_health_stats)

    def check(self, old_vars: Any, new_vars: Any) -> Tuple[bool, float]:
        """(healthy, update_norm) for one candidate merge — one host sync."""
        finite, norm = jax.device_get(self._fn(old_vars, new_vars))
        healthy = bool(finite)
        if (healthy and self.band > 0 and self.merges >= max(1, self.warmup)
                and self.ema > 0):
            healthy = float(norm) <= self.band * self.ema
        return healthy, float(norm)

    def commit(self, version: int, new_vars: Any, norm: float) -> None:
        """Record one healthy committed merge: advance the EMA and push the
        model onto the last-good ring."""
        self.merges += 1
        self.ema = (norm if self.merges == 1
                    else self.alpha * norm + (1.0 - self.alpha) * self.ema)
        if self.ring_size > 0:
            self.ring.append((int(version), new_vars))
            if len(self.ring) > self.ring_size:
                self.ring.pop(0)

    def rollback_target(self, fallback: Any) -> Any:
        return self.ring[-1][1] if self.ring else fallback

    def state(self) -> Dict[str, Any]:
        return {"ema": float(self.ema), "merges": int(self.merges)}

    def load_state(self, st: Optional[Dict[str, Any]]) -> None:
        if st:
            self.ema = float(st.get("ema", 0.0))
            self.merges = int(st.get("merges", 0))


class AggregateResult(NamedTuple):
    new_vars: ModelVars
    new_fg_state: agg.FoolsGoldState
    wv: jax.Array                 # [C] aggregation weights (RFA/FoolsGold)
    alpha: jax.Array              # [C] RFA distances / FoolsGold alphas
    num_oracle_calls: jax.Array   # RFA oracle counter (1 otherwise)
    is_updated: jax.Array         # bool — False iff RFA's max_update_norm
                                  # rejected the round (helper.py:360-369)


class LocalEvals(NamedTuple):
    """Per-client local-model eval rows (all [C]): reference CSV parity.
    clean/pre-scale rows evaluate the unscaled model (image_train.py:150-164
    runs Mytest/Mytest_poison BEFORE scaling); post rows the submitted one."""
    clean: EvalResult             # test_result rows (image_train.py:268-271)
    poison_pre: EvalResult        # posiontest_result pre-scale (:157-164)
    poison_post: EvalResult       # posiontest_result post-scale (:275-282)
    agent_trigger: EvalResult     # poisontriggertest_result (:291-295)


class GlobalEvals(NamedTuple):
    clean: EvalResult             # Mytest(global) (main.py:198-201)
    poison: EvalResult            # Mytest_poison(global) (main.py:207-215)
    per_trigger: EvalResult       # [T] rows (main.py:225-231)


@dataclasses.dataclass
class EvalPlans:
    """Device-resident eval index plans, built once per experiment."""
    clean_idx: jax.Array      # [S, B]
    clean_slots: jax.Array
    clean_mask: jax.Array
    poison_idx: jax.Array     # [S', B] — target-label samples dropped
    poison_slots: jax.Array
    poison_mask: jax.Array


class RoundEngine:
    """Holds the jitted round + eval computations for one experiment config.

    With a mesh, the stacked clients axis is sharded across devices (GSPMD via
    jit in_shardings): each device trains its clients locally and the
    aggregation reductions lower to ICI collectives (SURVEY §2.2)."""

    def __init__(self, params: cfg.Params, model_def: ModelDef,
                 data: DeviceData, plans: EvalPlans, mesh=None,
                 num_segments: int = 1):
        # one span around the whole host-side build (tracing the jit
        # wrappers is free — XLA compiles lazily on first call; those
        # compiles land in the xla/compiles counter via the monitoring
        # listener, not here)
        with telemetry.span("engine/build"):
            self._build(params, model_def, data, plans, mesh, num_segments)

    def _build(self, params: cfg.Params, model_def: ModelDef,
               data: DeviceData, plans: EvalPlans, mesh,
               num_segments: int):
        self.params = params
        self.hyper = RoundHyper.from_params(params)
        self.model_def = model_def
        self.data = data
        self.plans = plans
        self.mesh = mesh
        self.num_segments = num_segments
        hyper = self.hyper
        fg_enabled = hyper.aggregation == cfg.AGGR_FOOLSGOLD
        # fault layer (fl/faults.py + the screening/quarantine pass below):
        # every flag is static, so with fault_injection off and screening
        # off the robust path is simply not traced
        self.fault_cfg = fcfg = flt.FaultConfig.from_params(params)
        screen = params.get("screen_updates", "auto")
        self.screening = fcfg.enabled if screen == "auto" else bool(screen)
        self.robust = fcfg.enabled or self.screening
        self.min_surviving = max(1, int(params.get("min_surviving_clients",
                                                   1)))
        self.base_norm_mult = float(params.get("screen_norm_mult", 0.0))
        screening, min_surv = self.screening, self.min_surviving
        # defense forensics (utils/forensics.py): static flag — when off,
        # nothing below is traced and the payload keeps a None in the
        # forensic slot, so the round program is bit-identical to pre-PR
        self.forensics = forensics_on = bool(params.get("forensics", False))
        # fused per-step updates: pallas multi-tensor kernels; sound only
        # when the clients axis is unsharded (GSPMD cannot partition a
        # custom call), so the mesh path keeps the per-leaf jnp form
        fu = params.get("fused_updates", "auto")
        fused_pallas = bool(fu) if fu != "auto" else (
            mesh is None and jax.default_backend() == "tpu")
        client_step = make_client_step(
            model_def, data, hyper, fg_enabled, fused_pallas=fused_pallas,
            fused_interpret=bool(params.get("fused_interpret", False)))
        # grouped-layout client execution (models/grouped.py): holds the
        # grouped layout vmap's conv batching re-derives per conv. Measured
        # A/B on the bench chip (benchmarks/grouped_ab.py, TRAIN_FLOOR.md
        # round-5 section): train phase 0.539 → 0.528 s — within tunnel
        # noise, because the layout moves live inside XLA's grouped-conv
        # lowering, not in the vmap program. Kept flag-gated (default OFF:
        # no measured win, and a second lowering to keep numerically
        # audited); requires a BasicBlock ResNet and an unsharded clients
        # axis (GSPMD shards the stacked axis; grouped layout folds it into
        # features).
        from dba_mod_tpu.models.grouped import supports_grouped
        self.use_grouped = bool(params.get("grouped_clients", False))
        if self.use_grouped and not (supports_grouped(model_def)
                                     and mesh is None):
            raise ValueError(
                "grouped_clients=true requires a BasicBlock-ResNet "
                "model and an unsharded clients axis")
        if self.use_grouped:
            from dba_mod_tpu.fl.grouped_client import make_grouped_client_step
            grouped_step = make_grouped_client_step(model_def, data, hyper,
                                                    fg_enabled)
        eval_clean = make_eval_fn(model_def, data, poison=False)
        eval_poison = make_eval_fn(model_def, data, poison=True)
        is_poison_run = bool(params["is_poison"])

        def train_fn(global_vars: ModelVars, tasks_seq: ClientTask, idx_seq,
                     mask_seq, lane, rng) -> TrainResult:
            # tasks_seq leaves [I, C, ...]; idx/mask [I, C, E, S, B];
            # lane [C] — absolute lane index so per-client rng streams are
            # identical between the vmapped and sequential-debug paths
            n_seg, C = idx_seq.shape[0], idx_seq.shape[1]
            start = jax.tree_util.tree_map(
                lambda l: jnp.broadcast_to(l, (C,) + l.shape), global_vars)
            benign_mom = jax.tree_util.tree_map(
                lambda l: jnp.zeros((C,) + l.shape), global_vars.params)
            fg_total = jax.tree_util.tree_map(
                lambda l: jnp.zeros((C,) + l.shape), global_vars.params)
            seg_metrics = []
            seg_bloss, seg_bdist = [], []
            seg_deltas = []
            for s in range(n_seg):  # static unroll; n_seg is 1 in practice
                seg_rng = jax.random.fold_in(rng, s)
                rngs = jax.vmap(
                    lambda i: jax.random.fold_in(seg_rng, i))(lane)
                tasks_s = jax.tree_util.tree_map(lambda l: l[s], tasks_seq)
                if self.use_grouped:
                    res = grouped_step(start, benign_mom, tasks_s,
                                       idx_seq[s], mask_seq[s], rngs)
                else:
                    res = jax.vmap(client_step)(start, benign_mom, tasks_s,
                                                idx_seq[s], mask_seq[s],
                                                rngs)
                start = res.end_vars
                benign_mom = res.benign_mom
                if fg_enabled:
                    fg_total = jax.tree_util.tree_map(jnp.add, fg_total,
                                                      res.fg_grads)
                seg_metrics.append(res.metrics)
                seg_bloss.append(res.batch_loss)
                seg_bdist.append(res.batch_dist)
                if s < n_seg - 1:  # intermediate states feed per-epoch evals
                    seg_deltas.append(jax.tree_util.tree_map(
                        lambda e, g: e - g, start, global_vars))
            deltas = jax.tree_util.tree_map(lambda e, g: e - g, start,
                                            global_vars)
            fg_feature = jax.vmap(
                lambda t: model_def.similarity_param(t).reshape(-1))(fg_total)
            metrics = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), *seg_metrics)
            delta_norms = jax.vmap(
                lambda d: tree_global_norm(d.params))(deltas)
            return TrainResult(deltas, fg_total, fg_feature, metrics,
                               delta_norms, jnp.stack(seg_bloss),
                               jnp.stack(seg_bdist), seg_deltas)

        def aggregate_fn(global_vars: ModelVars,
                         fg_state: agg.FoolsGoldState, deltas: ModelVars,
                         fg_grads, fg_feature, participant_ids, num_samples,
                         rng, nbt_deltas=None, mask=None) -> AggregateResult:
            # mask ([C], optional): survivor mask from the quarantine pass —
            # routes to the mask-aware rule variants; None is the dense path
            C = fg_feature.shape[0]
            wv = jnp.zeros((C,), jnp.float32)
            alpha = jnp.zeros((C,), jnp.float32)
            calls = jnp.int32(1)
            is_updated = jnp.asarray(True)
            new_fg = fg_state
            if hyper.aggregation == cfg.AGGR_MEAN:
                if mask is None:
                    new_vars = agg.fedavg_update(
                        global_vars, deltas, hyper.eta, hyper.no_models,
                        hyper.sigma if hyper.diff_privacy else 0.0, rng)
                else:
                    new_vars = agg.fedavg_update_masked(
                        global_vars, deltas, hyper.eta, hyper.no_models,
                        mask, num_samples > 0,
                        hyper.sigma if hyper.diff_privacy else 0.0, rng)
            elif hyper.aggregation == cfg.AGGR_GEO_MED:
                r = agg.geometric_median_update(
                    global_vars, deltas, num_samples, hyper.eta,
                    maxiter=hyper.geom_median_maxiter,
                    max_update_norm=hyper.max_update_norm,
                    dp_sigma=hyper.sigma if hyper.diff_privacy else 0.0,
                    rng=rng, nbt_deltas=nbt_deltas,
                    n_bn=count_bn_layers(global_vars.batch_stats),
                    mask=mask)
                new_vars, calls, wv, alpha = (r.new_state, r.num_oracle_calls,
                                              r.wv, r.distances)
                is_updated = r.is_updated
            elif hyper.aggregation == cfg.AGGR_KRUM:
                r = agg.krum_update(
                    global_vars, deltas, hyper.eta, hyper.krum_m,
                    hyper.krum_f, mask=mask,
                    dp_sigma=hyper.sigma if hyper.diff_privacy else 0.0,
                    rng=rng)
                # wv = applied selection weights; alpha records the Krum
                # scores (clipped into a plottable range — excluded
                # sentinels are ~1e35)
                new_vars = r.new_state
                wv = r.wv
                alpha = jnp.minimum(r.scores, jnp.float32(1e30))
            elif hyper.aggregation in (cfg.AGGR_TRIMMED_MEAN,
                                       cfg.AGGR_MEDIAN):
                if hyper.aggregation == cfg.AGGR_TRIMMED_MEAN:
                    r = agg.trimmed_mean_update(
                        global_vars, deltas, hyper.eta, hyper.trim_beta,
                        mask=mask,
                        dp_sigma=hyper.sigma if hyper.diff_privacy else 0.0,
                        rng=rng)
                else:
                    r = agg.coordinate_median_update(
                        global_vars, deltas, hyper.eta, mask=mask,
                        dp_sigma=hyper.sigma if hyper.diff_privacy else 0.0,
                        rng=rng)
                new_vars = r.new_state
                wv = r.wv  # uniform survivor weights (coordinate-wise
                # rules have no per-client scalar weight; alpha stays 0)
            else:  # foolsgold
                r = agg.foolsgold_update(
                    global_vars.params, fg_grads, fg_feature,
                    participant_ids, fg_state, hyper.eta, hyper.lr,
                    hyper.momentum, hyper.weight_decay,
                    use_memory=hyper.fg_use_memory, mask=mask)
                # BN stats are not aggregated by FoolsGold (the reference
                # steps an optimizer over named_parameters only,
                # helper.py:286-290)
                new_vars = ModelVars(r.new_params, global_vars.batch_stats)
                new_fg, wv, alpha = r.new_fg_state, r.wv, r.alpha
            return AggregateResult(new_vars, new_fg, wv, alpha, calls,
                                   is_updated)

        if mesh is not None:
            from dba_mod_tpu.parallel.mesh import (CLIENTS_AXIS,
                                                   client_sharding,
                                                   replicated_sharding)
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = replicated_sharding(mesh)
            cs = client_sharding(mesh)
            seg_cs = NamedSharding(mesh, P(None, CLIENTS_AXIS))
            # out_shardings must be pinned: without them XLA may return
            # constant-foldable outputs (e.g. the all-zero fg_grads tree when
            # FoolsGold is off) replicated, and aggregate_fn's P('clients')
            # in_shardings then reject them at the call boundary.
            out_shard = TrainResult(deltas=cs, fg_grads=cs, fg_feature=cs,
                                    metrics=seg_cs, delta_norms=cs,
                                    batch_loss=seg_cs, batch_dist=seg_cs,
                                    seg_deltas=[cs] * (num_segments - 1))
            self.train_fn = jax.jit(
                train_fn, in_shardings=(rep, seg_cs, seg_cs, seg_cs, cs,
                                        rep),
                out_shardings=out_shard)
            self.aggregate_fn = jax.jit(
                aggregate_fn,
                in_shardings=(rep, rep, cs, cs, cs, cs, cs, rep, cs))
        else:
            self.train_fn = jax.jit(train_fn)
            self.aggregate_fn = jax.jit(aggregate_fn)

        # Stacked local battery: C client models share ONE eval plan, so the
        # batch fetch + combined-trigger stamp are hoisted out of the model
        # vmap — one gather per batch instead of C (the naive per-client
        # vmap gathered and stamped every test batch C times per battery).
        from dba_mod_tpu.fl.evaluation import make_stacked_eval_fn
        eval_clean_s = make_stacked_eval_fn(model_def, data, poison=False)
        eval_poison_s = make_stacked_eval_fn(model_def, data, poison=True)
        eval_agent_s = make_stacked_eval_fn(model_def, data, poison=True,
                                            per_client_trigger=True)

        def _bc(s, leaf):
            """[C] → [C, 1, ...] for per-client scalars against [C, ...]."""
            return s.reshape((s.shape[0],) + (1,) * (leaf.ndim - 1))

        def _stacked_battery(unscaled: ModelVars, scaled: ModelVars,
                             adv_slots) -> LocalEvals:
            """The per-client battery (all leaves [C]): clean on the
            pre-scaling model (image_train.py:150-155, :268-271), poison pre
            on it (:157-164), poison post + per-agent trigger on the
            submitted one (:275-282, :291-295)."""
            clean = eval_clean_s(unscaled, plans.clean_idx, plans.clean_slots,
                                 plans.clean_mask, jnp.int32(-1))
            if is_poison_run:
                pre = eval_poison_s(unscaled, plans.poison_idx,
                                    plans.poison_slots, plans.poison_mask,
                                    jnp.int32(-1))
                post = eval_poison_s(scaled, plans.poison_idx,
                                     plans.poison_slots, plans.poison_mask,
                                     jnp.int32(-1))
                agent = eval_agent_s(scaled, plans.poison_idx,
                                     plans.poison_slots, plans.poison_mask,
                                     adv_slots)
            else:
                C = adv_slots.shape[0]
                zero = EvalResult(*(jnp.zeros((C,), jnp.float32),) * 4)
                pre = post = agent = zero
            return LocalEvals(clean, pre, post, agent)

        def local_evals(global_vars: ModelVars, deltas: ModelVars,
                        tasks: ClientTask,
                        prev_deltas: ModelVars) -> LocalEvals:
            # `prev_deltas` anchors the final segment: the pre-scaling model
            # is (global + prev) + (Δ - prev)/scale — for interval=1 prev is
            # zero and this reduces to global + Δ/scale; for interval>1 it
            # divides only the FINAL segment's step by its scale (earlier
            # segments' contributions were already scaled when submitted)
            unscaled = jax.tree_util.tree_map(
                lambda g, p, d: g + p + (d - p) / _bc(tasks.scale, d),
                global_vars, prev_deltas, deltas)
            scaled = jax.tree_util.tree_map(lambda g, d: g + d, global_vars,
                                            deltas)
            return _stacked_battery(unscaled, scaled, tasks.adv_slot)

        if mesh is not None:
            from dba_mod_tpu.parallel.mesh import (client_sharding,
                                                   replicated_sharding)
            self.local_evals_fn = jax.jit(
                local_evals,
                in_shardings=(replicated_sharding(mesh),
                              client_sharding(mesh), client_sharding(mesh),
                              client_sharding(mesh)))
        else:
            self.local_evals_fn = jax.jit(local_evals)

        # Per-epoch local evals for aggr_epoch_interval > 1: the reference
        # runs the whole battery inside the per-global-epoch loop — clean +
        # pre-scaling poison in the poison branch (image_train.py:150-164),
        # clean for benign epochs (:268-271), post-scaling poison and the
        # per-agent trigger test (:273-295) — the final segment is covered by
        # local_evals above, intermediate segments here, with the same
        # LocalEvals battery per segment.
        def seg_local_evals(global_vars: ModelVars, seg_deltas, scales_seq,
                            adv_slots_seq):
            outs = []
            prev = None
            for s, cur in enumerate(seg_deltas):
                if prev is None:
                    prev = jax.tree_util.tree_map(jnp.zeros_like, cur)
                # live model of this segment: anchor (global + prev Δ) plus
                # this segment's step, unscaled for the pre rows
                unscaled = jax.tree_util.tree_map(
                    lambda g, p, c: g + p + (c - p) / _bc(scales_seq[s], c),
                    global_vars, prev, cur)
                scaled = jax.tree_util.tree_map(
                    lambda g, c: g + c, global_vars, cur)
                outs.append(_stacked_battery(unscaled, scaled,
                                             adv_slots_seq[s]))
                prev = cur
            return outs

        if num_segments > 1:
            if mesh is not None:
                from dba_mod_tpu.parallel.mesh import (
                    client_sharding, replicated_sharding,
                    segment_client_sharding)
                self.seg_local_evals_fn = jax.jit(
                    seg_local_evals,
                    in_shardings=(replicated_sharding(mesh),
                                  [client_sharding(mesh)]
                                  * (num_segments - 1),
                                  segment_client_sharding(mesh),
                                  segment_client_sharding(mesh)))
            else:
                self.seg_local_evals_fn = jax.jit(seg_local_evals)
        else:
            self.seg_local_evals_fn = None

        # Global per-trigger battery (main.py:225-231): centralized mode tests
        # each sub-pattern by index — only when `centralized_test_trigger` is
        # set (main.py:226) — distributed mode tests each adversary's pattern
        # (= its slot).
        if params.is_centralized_attack:
            n_triggers = (int(params["trigger_num"])
                          if bool(params["centralized_test_trigger"]) else 0)
        else:
            n_triggers = params.num_adversaries
        self.num_global_triggers = n_triggers
        trigger_ids = jnp.arange(max(n_triggers, 1), dtype=jnp.int32)

        def global_evals(model_vars: ModelVars) -> GlobalEvals:
            clean = eval_clean(model_vars, plans.clean_idx, plans.clean_slots,
                               plans.clean_mask, jnp.int32(-1))
            if is_poison_run:
                poison = eval_poison(model_vars, plans.poison_idx,
                                     plans.poison_slots, plans.poison_mask,
                                     jnp.int32(-1))
                if n_triggers > 0:
                    per_trigger = jax.vmap(
                        lambda t: eval_poison(model_vars, plans.poison_idx,
                                              plans.poison_slots,
                                              plans.poison_mask,
                                              t))(trigger_ids)
                else:
                    zero = EvalResult(*(jnp.float32(0),) * 4)
                    per_trigger = jax.tree_util.tree_map(
                        lambda z: jnp.zeros((1,)), zero)
            else:
                zero = EvalResult(*(jnp.float32(0),) * 4)
                poison = zero
                per_trigger = jax.tree_util.tree_map(
                    lambda z: jnp.zeros((max(n_triggers, 1),)), zero)
            return GlobalEvals(clean, poison, per_trigger)

        self.global_evals_fn = jax.jit(global_evals)

        def backdoor_acc(model_vars: ModelVars) -> jax.Array:
            """Combined-trigger backdoor accuracy of the global model — feeds
            the LOAN adaptive poison LR (loan_train.py:67-75)."""
            r = eval_poison(model_vars, plans.poison_idx, plans.poison_slots,
                            plans.poison_mask, jnp.int32(-1))
            return r.acc

        self.backdoor_acc_fn = jax.jit(backdoor_acc)

        # Standalone batteries get telemetry spans with honest device-sync
        # points (fl/evaluation.py:instrument_eval) — a passthrough while
        # telemetry is off, so the fused/pipelined paths keep their deferred
        # sync. `batches` counts eval-plan scan steps (= batch fetches; the
        # stacked batteries share one gather across the C client models).
        from dba_mod_tpu.fl.evaluation import instrument_eval
        clean_steps = int(plans.clean_idx.shape[0])
        poison_steps = int(plans.poison_idx.shape[0])
        local_batches = clean_steps + (3 * poison_steps if is_poison_run
                                       else 0)
        global_batches = clean_steps + ((1 + n_triggers) * poison_steps
                                        if is_poison_run else 0)
        self.local_evals_fn = instrument_eval(
            self.local_evals_fn, "eval/local", batches=local_batches)
        if self.seg_local_evals_fn is not None:
            self.seg_local_evals_fn = instrument_eval(
                self.seg_local_evals_fn, "eval/seg_local",
                batches=(num_segments - 1) * local_batches)
        self.global_evals_fn = instrument_eval(
            self.global_evals_fn, "eval/global", batches=global_batches)
        self.backdoor_acc_fn = instrument_eval(
            self.backdoor_acc_fn, "eval/backdoor_probe",
            batches=poison_steps)

        # The whole round as ONE program: train → [inject faults → screen] →
        # aggregate → local evals → global evals. One dispatch, no
        # cross-program buffer boundaries (the separate fns above stay for
        # sequential_debug and for bench phase diagnostics). Returns
        # (new_vars, new_fg_state, payload) — payload ordered exactly as
        # Experiment.finalize_round unpacks it, with a RobustStats (or None)
        # in slot 9 and a ForensicStats (or None) in the last slot — the
        # robust dispatch's degraded-path payload surgery slices around
        # slot 1, so new slots must only ever be APPENDED. The robust
        # variant additionally takes
        # (rng_f, prev_deltas, norm_mult) and returns the submitted deltas
        # as a 4th output so the next round can replay them for the stale
        # fault lane (an empty tuple when staleness is off).
        do_local_eval = bool(params.get("local_eval", True))

        def _round(global_vars: ModelVars, fg_state, tasks_seq, idx_seq,
                   mask_seq, lane, num_samples, rng_t, rng_a,
                   rng_f=None, prev_deltas=(), norm_mult=None,
                   with_evals=True):
            robust = norm_mult is not None  # trace-time switch
            train = train_fn(global_vars, tasks_seq, idx_seq, mask_seq,
                             lane, rng_t)
            deltas, fg_grads = train.deltas, train.fg_grads
            fg_feature = train.fg_feature
            tasks_last = jax.tree_util.tree_map(lambda l: l[-1], tasks_seq)
            tasks_first = jax.tree_util.tree_map(lambda l: l[0], tasks_seq)
            nbt = nbt_client_deltas(mask_seq, tasks_seq.scale)
            stats = None
            fstats = None
            deltas_out = ()
            if robust:
                counted = num_samples > 0
                reported = jnp.ones_like(counted)
                n_dropped = jnp.int32(0)
                if fcfg.enabled:
                    plan = flt.make_fault_plan(fcfg, rng_f, counted)
                    stale = prev_deltas if fcfg.stale_enabled else None
                    deltas = flt.perturb_tree(deltas, plan, fcfg, stale)
                    if fg_enabled:
                        # FoolsGold aggregates the gradient accumulators,
                        # not the deltas — corrupt that payload too (stale
                        # replay stays delta-only; see faults.py docstring)
                        fg_grads = flt.perturb_tree(fg_grads, plan, fcfg)
                        fg_feature = flt.perturb_tree(fg_feature, plan,
                                                      fcfg)
                    reported = ~plan.dropped
                    n_dropped = jnp.sum(
                        plan.dropped & counted).astype(jnp.int32)
                if fcfg.stale_enabled:
                    deltas_out = deltas  # what the server RECEIVED
                if screening:
                    extra = (fg_grads,) if fg_enabled else ()
                    smask, _norms = screen_client_updates(
                        deltas, reported, counted, norm_mult, extra)
                else:
                    # dropout is server-visible without any screening: a
                    # client that never reported cannot be aggregated
                    smask = reported
                n_quar = jnp.sum(reported & ~smask
                                 & counted).astype(jnp.int32)
                n_surv = jnp.sum(smask & counted).astype(jnp.int32)
                degraded = n_surv < min_surv
                res = aggregate_fn(global_vars, fg_state, deltas, fg_grads,
                                   fg_feature, tasks_first.participant_id,
                                   num_samples, rng_a, nbt,
                                   mask=smask.astype(jnp.float32))
                # graceful degradation: too few survivors → skip the
                # aggregate, carry the global model and defense state
                new_vars = jax.tree_util.tree_map(
                    lambda g, a: jnp.where(degraded, g, a),
                    global_vars, res.new_vars)
                new_fg = jax.tree_util.tree_map(
                    lambda o, n: jnp.where(degraded, o, n),
                    fg_state, res.new_fg_state)
                gfin = jnp.asarray(True)
                for l in jax.tree_util.tree_leaves(new_vars):
                    gfin = gfin & jnp.all(
                        jnp.isfinite(l.astype(jnp.float32)))
                stats = RobustStats(n_dropped, n_quar, n_surv, degraded,
                                    gfin, smask)
                res = res._replace(new_vars=new_vars, new_fg_state=new_fg)
                if forensics_on:
                    # quarantine reason, consistent with the mask actually
                    # applied: never-reported → dropped; reported but
                    # screened out → nonfinite or norm_exceeded (screening
                    # off means smask == reported, so the middle branch is
                    # unreachable and `finite` is never consulted)
                    if screening:
                        finite = _per_client_finite(deltas)
                        for t in ((fg_grads,) if fg_enabled else ()):
                            finite = finite & _per_client_finite(t)
                    else:
                        finite = jnp.ones_like(smask)
                    reason = jnp.where(
                        ~reported, jnp.int32(REASON_DROPPED),
                        jnp.where(reported & ~smask,
                                  jnp.where(finite, jnp.int32(REASON_NORM),
                                            jnp.int32(REASON_NONFINITE)),
                                  jnp.int32(REASON_OK)))
                    fstats = forensic_stats(global_vars, new_vars, deltas,
                                            smask, reason,
                                            res.num_oracle_calls)
            else:
                res = aggregate_fn(global_vars, fg_state, deltas, fg_grads,
                                   fg_feature, tasks_first.participant_id,
                                   num_samples, rng_a, nbt)
                if forensics_on:
                    C = fg_feature.shape[0]
                    fstats = forensic_stats(
                        global_vars, res.new_vars, deltas,
                        jnp.ones((C,), bool), jnp.zeros((C,), jnp.int32),
                        res.num_oracle_calls)
            prev = (train.seg_deltas[-1] if num_segments > 1 else
                    jax.tree_util.tree_map(jnp.zeros_like, train.deltas))
            if with_evals:
                # the local battery evaluates what each client TRAINED
                # (faults model the uplink, not local training) — pre-fault
                # deltas
                locals_ = (local_evals(global_vars, train.deltas, tasks_last,
                                       prev)
                           if do_local_eval else None)
                seg_l = (seg_local_evals(global_vars, train.seg_deltas,
                                         tasks_seq.scale, tasks_seq.adv_slot)
                         if do_local_eval and num_segments > 1 else None)
                globals_ = global_evals(res.new_vars)
            else:
                # overlap_eval's round CORE: the eval tail is stripped —
                # the dispatcher runs the SAME jitted batteries as separate
                # programs against the returned eval inputs, after the model
                # commit, so they overlap the next round's train dispatch
                locals_ = seg_l = globals_ = None
            track_pair = ((train.batch_loss, train.batch_dist)
                          if hyper.track_batches else None)
            payload = (locals_, globals_, train.metrics, train.delta_norms,
                       res.wv, res.alpha, track_pair, res.is_updated, seg_l,
                       stats, fstats)
            if not with_evals:
                # everything the stripped batteries need that only exists
                # inside the program: the PRE-fault deltas (the local
                # battery's input even on the robust path), the final
                # segment's anchor, and the per-segment deltas
                eval_in = (train.deltas, prev, tuple(train.seg_deltas))
                if robust:
                    return (res.new_vars, res.new_fg_state, payload,
                            deltas_out, eval_in)
                return res.new_vars, res.new_fg_state, payload, eval_in
            if robust:
                return res.new_vars, res.new_fg_state, payload, deltas_out
            return res.new_vars, res.new_fg_state, payload

        def round_fn(global_vars: ModelVars, fg_state, tasks_seq, idx_seq,
                     mask_seq, lane, num_samples, rng_t, rng_a):
            return _round(global_vars, fg_state, tasks_seq, idx_seq,
                          mask_seq, lane, num_samples, rng_t, rng_a)

        def round_fn_robust(global_vars: ModelVars, fg_state, tasks_seq,
                            idx_seq, mask_seq, lane, num_samples, rng_t,
                            rng_a, rng_f, prev_deltas, norm_mult):
            return _round(global_vars, fg_state, tasks_seq, idx_seq,
                          mask_seq, lane, num_samples, rng_t, rng_a,
                          rng_f, prev_deltas, norm_mult)

        # The round CORE for the overlap_eval scheduler: train → [faults →
        # screen] → aggregate, with the eval tail stripped and the eval
        # inputs returned instead. Snapshot contract: the core must NOT
        # donate (or otherwise alias) its input buffers — the overlapped
        # eval batteries read the RETAINED pre-round global_vars and the
        # returned delta snapshots after round N+1's core has already been
        # enqueued against the new model.
        def core_fn(global_vars: ModelVars, fg_state, tasks_seq, idx_seq,
                    mask_seq, lane, num_samples, rng_t, rng_a):
            return _round(global_vars, fg_state, tasks_seq, idx_seq,
                          mask_seq, lane, num_samples, rng_t, rng_a,
                          with_evals=False)

        def core_fn_robust(global_vars: ModelVars, fg_state, tasks_seq,
                           idx_seq, mask_seq, lane, num_samples, rng_t,
                           rng_a, rng_f, prev_deltas, norm_mult):
            return _round(global_vars, fg_state, tasks_seq, idx_seq,
                          mask_seq, lane, num_samples, rng_t, rng_a,
                          rng_f, prev_deltas, norm_mult, with_evals=False)

        if mesh is not None:
            from dba_mod_tpu.parallel.mesh import (client_sharding,
                                                   replicated_sharding,
                                                   segment_client_sharding)
            rep2 = replicated_sharding(mesh)
            cs2 = client_sharding(mesh)
            seg_cs2 = segment_client_sharding(mesh)
            # out_shardings: the new global/defense state stays replicated
            # (it feeds the next round's rep in_shardings), and the small
            # metrics payload is replicated so finalize_round's device_get
            # is host-local on EVERY process of a multi-host run
            base_in = (rep2, rep2, seg_cs2, seg_cs2, seg_cs2, cs2, cs2,
                       rep2, rep2)
            # the eval-input snapshot trio (deltas, prev anchor, seg deltas)
            # keeps the client sharding the eval batteries expect
            eval_out = (cs2, cs2, cs2)
            if self.robust:
                self.round_fn = jax.jit(
                    round_fn_robust,
                    in_shardings=base_in + (rep2, cs2, rep2),
                    out_shardings=(rep2, rep2, rep2, cs2))
                self.core_fn = jax.jit(
                    core_fn_robust,
                    in_shardings=base_in + (rep2, cs2, rep2),
                    out_shardings=(rep2, rep2, rep2, cs2, eval_out))
            else:
                self.round_fn = jax.jit(
                    round_fn, in_shardings=base_in,
                    out_shardings=(rep2, rep2, rep2))
                self.core_fn = jax.jit(
                    core_fn, in_shardings=base_in,
                    out_shardings=(rep2, rep2, rep2, eval_out))
        else:
            self.round_fn = jax.jit(round_fn_robust if self.robust
                                    else round_fn)
            self.core_fn = jax.jit(core_fn_robust if self.robust
                                   else core_fn)

        # Donation gate (snapshot/donation contract): the fused round is the
        # LAST reader of its (global_vars, fg_state) buffers on the
        # steady-state non-robust path, so on non-CPU backends a donated
        # twin lets XLA reuse those buffers in place — model-sized headroom
        # per round. Three exclusions, each load-bearing:
        #   * CPU: buffers are host RAM — aliasing saves nothing and XLA:CPU
        #     donation is the one backend where it has historically been
        #     fragile, so the gate stays off (tier-1 runs are CPU);
        #   * robust: the retry loop re-runs the program with the SAME
        #     captured inputs, which donation would have invalidated;
        #   * core_fn/overlap: the overlapped eval batteries read the
        #     retained pre-round buffers AFTER the next core is enqueued —
        #     the core never donates (see core_fn above).
        # Experiment-side contract: route through round_fn_donated only when
        # no health sentinel is armed (its check/rollback re-reads the
        # pre-round model), and warm calls must pass copies.
        self.round_fn_donated = None
        if not self.robust and jax.default_backend() != "cpu":
            if mesh is not None:
                self.round_fn_donated = jax.jit(
                    round_fn, in_shardings=base_in,
                    out_shardings=(rep2, rep2, rep2),
                    donate_argnums=(0, 1))
            else:
                self.round_fn_donated = jax.jit(round_fn,
                                                donate_argnums=(0, 1))

        # Split-path forensics (sequential_debug / telemetry's per-phase
        # dispatch — the robust path is never split): the same ForensicStats
        # as its own tiny jitted program, called by _finish_split_round with
        # an all-ones mask (no screening on the split path). None when
        # forensics is off so the split payload keeps its None slot.
        def forensic_fn(global_vars: ModelVars, new_vars: ModelVars,
                        deltas: ModelVars, oracle_calls) -> ForensicStats:
            C = jax.tree_util.tree_leaves(deltas)[0].shape[0]
            return forensic_stats(global_vars, new_vars, deltas,
                                  jnp.ones((C,), bool),
                                  jnp.zeros((C,), jnp.int32), oracle_calls)

        if not forensics_on:
            self.forensic_fn = None
        elif mesh is not None:
            from dba_mod_tpu.parallel.mesh import (client_sharding,
                                                   replicated_sharding)
            rep3 = replicated_sharding(mesh)
            self.forensic_fn = jax.jit(
                forensic_fn,
                in_shardings=(rep3, rep3, client_sharding(mesh), rep3))
        else:
            self.forensic_fn = jax.jit(forensic_fn)
