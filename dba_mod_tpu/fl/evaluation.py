"""The evaluation battery — jitted equivalents of reference test.py.

Four reference entry points map to two jitted kernels:
- `Mytest` (test.py:7-51)                     → evaluate(poison=False)
- `Mytest_poison` (test.py:54-115)            → evaluate(poison=True, adv=-1)
- `Mytest_poison_trigger` (test.py:118-177)   → evaluate(poison=True, adv=j)
- `Mytest_poison_agent_trigger` (:180-239)    → evaluate(poison=True, adv=slot)

Semantics preserved: loss is a reduction='sum' divided by the count
(test.py:21-22, :40); poisoned accuracy divides by `poison_data_count`
(test.py:105), which equals the valid-sample count since evaluation poisons
every sample; the poisoned image eval runs on the test set with target-label
images dropped (image_helper.py:148-172), expressed in the eval plan's index
set; the LOAN branches iterate every state shard (test.py:13-24) — here the
plan concatenates all shards with a per-row slot array.

Local (per-client) evals vmap the same kernel over stacked client models —
ten models' test passes in one XLA computation instead of the reference's
sequential loop.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from dba_mod_tpu.models import ModelDef, ModelVars
from dba_mod_tpu.fl.device_data import DeviceData
from dba_mod_tpu.ops.losses import cross_entropy_sum
from dba_mod_tpu.utils import telemetry


class EvalResult(NamedTuple):
    loss: jax.Array      # average loss (sum / count)
    acc: jax.Array       # percentage
    correct: jax.Array
    count: jax.Array     # dataset_size / poison_data_count


def instrument_eval(fn, name: str, batches: int = 0):
    """Telemetry wrapper for a compiled eval battery: each call runs under a
    span with an explicit device sync (``jax.block_until_ready`` on the
    results — under async dispatch the un-synced call time is just the
    enqueue), and counts `batches` scan steps into ``eval/batches``.

    A zero-overhead passthrough while telemetry is off, so the standalone
    batteries keep deferring their sync to ``finalize_round`` and round
    pipelining is unaffected. With telemetry on, evals that run outside the
    fused round program (the split-phase dispatch, sequential_debug, the
    degraded-round re-eval, the LOAN backdoor probe) report honest phase
    times at the cost of syncing where they are called."""
    return telemetry.instrument(fn, name, batches=batches)


def pick_eval_device(mesh, overlap: bool):
    """The device the overlap_eval batteries should run on, or None to
    share device 0. A SECOND local device (when present, and only without a
    clients mesh — sharded batteries stay on the mesh) gives true compute
    overlap: round N's eval executables compile against their own
    placement-cached copy of the test-set constants (JAX places
    closure-captured data per compiled executable), so they run while
    device 0 executes round N+1's train/aggregate. With one device the
    batteries still dispatch ahead but only the host-side fetch/record/
    checkpoint path is hidden."""
    if not overlap or mesh is not None:
        return None
    devs = jax.local_devices()
    return devs[1] if len(devs) > 1 else None


def place_eval_inputs(operands, device):
    """One-hop ``jax.device_put`` of the overlap path's eval operands onto
    the eval device (passthrough when placement is off). The operands are
    the superseded round's SNAPSHOTS (model, pre-fault deltas, task row) —
    transferring them here, at dispatch, is what lets the donated/overwritten
    device-0 buffers belong to round N+1 while N's batteries still read
    bit-identical inputs."""
    if device is None:
        return operands
    return jax.device_put(operands, device)


def make_eval_fn(model_def: ModelDef, data: DeviceData, poison: bool):
    """evaluate(model_vars, idx[S,B], slots[S,B], mask[S,B], adv_index)
    -> EvalResult. `poison` is static: True stamps every sample with trigger
    `adv_index` and swaps labels (test.py:95, evaluation=True)."""

    def evaluate(model_vars: ModelVars, idx, slots, mask,
                 adv_index) -> EvalResult:
        def body(carry, inp):
            loss_sum, correct, count = carry
            bidx, bslot, bmask = inp
            x, y = data.fetch_test(bslot, bidx)
            if poison:
                x, y, _ = data.stamp(x, y, adv_index, 0, poison_all=True)
            logits, _ = model_def.apply(model_vars, x, train=False)
            bmaskf = bmask.astype(jnp.float32)
            loss_sum += cross_entropy_sum(logits, y, bmask)
            preds = jnp.argmax(logits, axis=-1)
            correct += jnp.sum((preds == y) * bmaskf)
            count += jnp.sum(bmaskf)
            return (loss_sum, correct, count), None

        (loss_sum, correct, count), _ = jax.lax.scan(
            body, (jnp.float32(0), jnp.float32(0), jnp.float32(0)),
            (idx, slots, mask))
        safe = jnp.maximum(count, 1.0)
        return EvalResult(loss=loss_sum / safe, acc=100.0 * correct / safe,
                          correct=correct, count=count)

    return evaluate


def make_stacked_eval_fn(model_def: ModelDef, data: DeviceData, poison: bool,
                         per_client_trigger: bool = False):
    """evaluate_stacked(stacked_vars [C, ...], idx[S,B], slots[S,B],
    mask[S,B], adv) -> EvalResult with [C] leaves.

    The per-client local battery evaluates C client models over ONE shared
    eval plan — fetching and trigger-stamping each test batch inside a
    per-client vmap (the naive formulation) gathers and stamps every batch
    C times. Here the batch fetch (and, unless `per_client_trigger`, the
    stamp) is hoisted out of the model vmap: one gather per batch, shared
    by all C models; only the forward passes are batched over clients.
    Numerics are bit-identical to vmapping :func:`make_eval_fn` — same ops,
    same per-client accumulation order (tests/test_eval_stacked.py).

    `per_client_trigger=True` is the Mytest_poison_agent_trigger variant
    (test.py:180-239): `adv` is a [C] array and each client's model is
    evaluated against its own trigger pattern, so only the stamp stays
    under the vmap; the fetch is still shared."""

    def evaluate_stacked(stacked_vars: ModelVars, idx, slots, mask,
                         adv) -> EvalResult:
        def body(carry, inp):
            loss_sum, correct, count = carry         # [C] each
            bidx, bslot, bmask = inp
            x, y = data.fetch_test(bslot, bidx)      # ONE gather, shared
            if poison and not per_client_trigger:
                x, y, _ = data.stamp(x, y, adv, 0, poison_all=True)
            bmaskf = bmask.astype(jnp.float32)

            def per_model(mv: ModelVars, adv_c):
                if poison and per_client_trigger:
                    xx, yy, _ = data.stamp(x, y, adv_c, 0, poison_all=True)
                else:
                    xx, yy = x, y
                logits, _ = model_def.apply(mv, xx, train=False)
                loss = cross_entropy_sum(logits, yy, bmask)
                preds = jnp.argmax(logits, axis=-1)
                return (loss, jnp.sum((preds == yy) * bmaskf),
                        jnp.sum(bmaskf))

            adv_vec = (adv if per_client_trigger else
                       jnp.zeros((loss_sum.shape[0],), jnp.int32))
            dl, dc, dn = jax.vmap(per_model)(stacked_vars, adv_vec)
            return (loss_sum + dl, correct + dc, count + dn), None

        C = jax.tree_util.tree_leaves(stacked_vars)[0].shape[0]
        zeros = jnp.zeros((C,), jnp.float32)
        (loss_sum, correct, count), _ = jax.lax.scan(
            body, (zeros, zeros, zeros), (idx, slots, mask))
        safe = jnp.maximum(count, 1.0)
        return EvalResult(loss=loss_sum / safe, acc=100.0 * correct / safe,
                          correct=correct, count=count)

    return evaluate_stacked
