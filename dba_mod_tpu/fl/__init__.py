"""FL engine: the client-parallel round computation and its orchestration.

The reference trains clients one at a time in a Python loop sharing a single
model instance (image_train.py:21-32). Here a *round* is one jitted XLA
computation: client state is stacked on a leading `clients` axis, local
training is `vmap`ped (and mesh-sharded, see `dba_mod_tpu.parallel`) over that
axis, and aggregation consumes the stacked deltas directly — the host only
schedules, selects agents and records metrics.
"""
from dba_mod_tpu.fl.state import ClientTask, RoundHyper
from dba_mod_tpu.fl.faults import FaultConfig, FaultPlan
from dba_mod_tpu.fl.experiment import Experiment
