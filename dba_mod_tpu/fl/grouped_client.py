"""Grouped-layout client training step: all C clients in one program, no
outer vmap.

Drop-in replacement for `jax.vmap(make_client_step(...))` (fl/rounds.py) for
the ResNet workloads: same inputs/outputs (stacked [C, ...] trees), same
per-client math — the forward/backward runs through the persistent grouped
layout (models/grouped.py) instead of vmap's per-conv re-grouping, and the
SGD/momentum/FoolsGold state is carried in conv layout across the whole scan
so the grouped-kernel merge stays a free reshape every step. Layout
conversions happen once per segment, not once per conv per step.

Semantics mirror fl/client.py line for line (reference image_train.py:21-315);
tests/test_grouped_clients.py asserts equality against the vmapped path.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from dba_mod_tpu.models import ModelDef, ModelVars
from dba_mod_tpu.models.grouped import (client_axis_of, conv_layout_in,
                                        conv_layout_out, grouped_train_apply)
from dba_mod_tpu.fl.client import ClientMetrics, SegmentResult
from dba_mod_tpu.fl.device_data import DeviceData
from dba_mod_tpu.fl.state import ClientTask, RoundHyper


def _bc(v, leaf):
    """Broadcast a per-client [C] vector against a conv-layout leaf."""
    ca = client_axis_of(leaf)
    shape = [1] * leaf.ndim
    shape[ca] = v.shape[0]
    return v.reshape(shape)


def _tree_sq_per_client(tree) -> jax.Array:
    """Σ leaf² reduced to [C] (client axis per conv-layout leaf)."""
    def per_leaf(l):
        ca = client_axis_of(l)
        axes = tuple(a for a in range(l.ndim) if a != ca)
        return jnp.sum(jnp.square(l), axis=axes)
    return sum(jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(per_leaf, tree)))


def _dist_norm_per_client(params, anchor) -> jax.Array:
    """Per-client ‖w - w_anchor‖₂ with the zero-gradient-safe double-where
    (ops/losses.py::tree_dist_norm, elementwise per client)."""
    sq = _tree_sq_per_client(jax.tree_util.tree_map(
        lambda a, b: a - b, params, anchor))
    safe = jnp.where(sq > 0.0, sq, 1.0)
    return jnp.where(sq > 0.0, jnp.sqrt(safe), 0.0)


def make_grouped_client_step(model_def: ModelDef, data: DeviceData,
                             hyper: RoundHyper, fg_enabled: bool):
    """Returns grouped_step(start_vars, benign_mom, tasks, idx, mask, rngs)
    -> SegmentResult, with every argument/result stacked [C, ...] — the same
    contract as jax.vmap(client_step)."""
    wd, momentum = hyper.weight_decay, hyper.momentum

    def sgd_update(lr_c, keep_c, params, grads, mom):
        def upd(w, g, m):
            lr, keep = _bc(lr_c, w), _bc(keep_c, w)
            g2 = g + wd * w
            m2 = momentum * m + g2
            return (jnp.where(keep, w - lr * m2, w),
                    jnp.where(keep, m2, m))
        pairs = jax.tree_util.tree_map(upd, params, grads, mom)
        is_pair = lambda t: isinstance(t, tuple)
        w2 = jax.tree_util.tree_map(lambda t: t[0], pairs, is_leaf=is_pair)
        m2 = jax.tree_util.tree_map(lambda t: t[1], pairs, is_leaf=is_pair)
        return w2, m2

    def sel_c(keep_c, new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(_bc(keep_c, a), a, b), new, old)

    def grouped_step(start_vars: ModelVars, benign_mom: Any,
                     task: ClientTask, idx, mask, rngs) -> SegmentResult:
        C, E, S, B = idx.shape
        # conv layout in — once per segment (fl/client.py's vmap pays the
        # equivalent moves once per conv per step)
        params0 = conv_layout_in(start_vars.params)
        bn0 = start_vars.batch_stats
        is_poison_seg = task.poisoning_per_batch > 0          # [C]
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params0)
        mom0 = sel_c(is_poison_seg, zeros,
                     conv_layout_in(benign_mom))
        fg0 = zeros
        zeros_ce = jnp.zeros((C, E), jnp.float32)
        metrics0 = ClientMetrics(zeros_ce, zeros_ce, zeros_ce, zeros_ce)

        def step(carry, inp):
            params, bn, mom, fg, m = carry
            step_i, bidx, bmask = inp                          # [C,B] each
            e = step_i // S
            x, y = jax.vmap(data.fetch_train)(task.slot, bidx)
            x, y, sel = jax.vmap(data.stamp)(x, y, task.adv_index,
                                             task.poisoning_per_batch)

            def loss_fn(p):
                logits, new_bn = grouped_train_apply(model_def, p, bn, x)
                # per-client masked-mean CE (ops/losses.py::cross_entropy)
                logp = jax.nn.log_softmax(logits, axis=-1)
                nll = -jnp.take_along_axis(
                    logp, y[:, :, None].astype(jnp.int32), axis=-1)[..., 0]
                mk = bmask.astype(nll.dtype)
                denom = jnp.maximum(jnp.sum(mk, axis=1), 1.0)
                ce_c = jnp.sum(nll * mk, axis=1) / denom       # [C]
                if hyper.alpha_loss == 1.0:
                    loss_c = ce_c
                else:
                    dist_c = _dist_norm_per_client(p, params0)
                    loss_c = (task.alpha * ce_c
                              + (1.0 - task.alpha) * dist_c)
                # Σ over clients: per-client grads are independent, so the
                # grad of the sum IS each client's own grad
                return jnp.sum(loss_c), (loss_c, logits, new_bn)

            (_, (loss_c, logits, new_bn)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            lr_c = task.lr_row[:, e]                           # [C]
            valid = jnp.sum(bmask, axis=1) > 0                 # [C]
            params, mom = sgd_update(lr_c, valid, params, grads, mom)
            if fg_enabled:
                fg = sel_c(valid, jax.tree_util.tree_map(jnp.add, fg, grads),
                           fg)
            bn = sel_c(valid, new_bn, bn)

            preds = jnp.argmax(logits, axis=-1)                # [C,B]
            bmaskf = bmask.astype(jnp.float32)
            vf = valid.astype(jnp.float32)                     # [C]
            m = ClientMetrics(
                loss_sum=m.loss_sum.at[:, e].add(vf * loss_c),
                correct=m.correct.at[:, e].add(
                    vf * jnp.sum((preds == y) * bmaskf, axis=1)),
                count=m.count.at[:, e].add(vf * jnp.sum(bmaskf, axis=1)),
                poison_count=m.poison_count.at[:, e].add(
                    vf * jnp.sum(sel * bmaskf, axis=1)))
            if hyper.track_batches:
                ys = (vf * loss_c,
                      vf * _dist_norm_per_client(params, params0))
            else:
                ys = None
            return (params, bn, mom, fg, m), ys

        xs = (jnp.arange(E * S),
              jnp.moveaxis(idx.reshape(C, E * S, B), 1, 0),
              jnp.moveaxis(mask.reshape(C, E * S, B), 1, 0))
        carry, ys = jax.lax.scan(step, (params0, bn0, mom0, fg0, metrics0),
                                 xs)
        params, bn, mom, fg, metrics = carry
        if hyper.track_batches:
            batch_loss, batch_dist = (jnp.moveaxis(ys[0], 0, 1),
                                      jnp.moveaxis(ys[1], 0, 1))
        else:
            batch_loss = batch_dist = jnp.zeros((C, 0), jnp.float32)

        # conv layout out — once per segment; everything below matches
        # fl/client.py's epilogue on stacked [C, ...] trees
        params = conv_layout_out(params)
        mom = conv_layout_out(mom)
        fg = conv_layout_out(fg)
        start_p = start_vars.params
        benign_mom_out = _select_tree_c(is_poison_seg, benign_mom, mom)
        scale = task.scale
        end_vars = ModelVars(
            params=jax.tree_util.tree_map(
                lambda a, w: a + _bcl(scale, w) * (w - a), start_p, params),
            batch_stats=jax.tree_util.tree_map(
                lambda a, w: a + _bcl(scale, w) * (w - a), bn0, bn))
        return SegmentResult(end_vars, benign_mom_out, fg, metrics,
                             batch_loss, batch_dist)

    def _bcl(v, leaf):  # [C] against a client-leading stacked leaf
        return v.reshape((v.shape[0],) + (1,) * (leaf.ndim - 1))

    def _select_tree_c(pred_c, new, old):
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(_bcl(pred_c, a), a, b), new, old)

    return grouped_step
