"""Buffered-asynchronous federation: the FedBuff-style streaming engine.

The synchronous engine (fl/experiment.py) is a barrier per round: select C
clients, train them in one vmapped program, aggregate, evaluate. The ROADMAP
north star is a service absorbing updates as they arrive; this module is the
buffered-asynchronous middle point of Nguyen et al., *Federated Learning
with Buffered Asynchronous Aggregation* (AISTATS 2022): the server admits
client updates continuously, buffers them, and merges every K arrivals with
a staleness-weighted partial-participation rule.

Shape of the simulation (single-controller, deterministic per seed):

  - Client work is dispatched in *cohorts* ("waves") through the SAME
    jitted ``engine.train_fn`` program the lockstep rounds run — one wave
    per selection epoch, trained against the global model current at
    dispatch. A wave's lanes then become individual *arrivals*, each with a
    service delay drawn from the arrival process below; a new wave is
    dispatched whenever the arrival queue drains, so stragglers from
    earlier cohorts interleave with later cohorts and accumulate staleness.
  - The arrival process is a pure function of ``(random_seed, wave)``:
    Exp(1/arrival_rate) service times, optional lognormal jitter
    (``arrival_jitter``), and a straggler tail (``straggler_tail`` fraction
    delayed by ``straggler_factor``). Virtual time — merge ORDER is what
    matters; no wall-clock sleeps.
  - Every K arrivals (``buffer_k``; 0 ⇒ no_models) the buffer is merged by
    a jitted partial-participation rule reusing the survivor-mask contract
    of ops/aggregation.py: occupancy is a mask, the buffer is padded with
    inert zero-delta lanes to the static K, so occupancy < K (the final
    flush of a gracefully stopped run) compiles to the same program shape.
  - Staleness of a buffered update = merges applied since its wave was
    dispatched. ``staleness_weighting``: "none" (static no-op branch — the
    weight multiply is not even traced, keeping the sync reduction
    bit-exact), "polynomial" w(s) = (1+s)^-staleness_alpha (the FedBuff
    paper's choice), or "exponential" w(s) = staleness_alpha^s.
  - Faults (fl/faults.py) become arrival-process events: the same
    deterministic per-epoch plan f(fault_seed, wave_epoch) is drawn, but a
    *dropped* client never arrives, a *stale* client becomes a straggler
    (its arrival is delayed by ``straggler_factor`` — the streaming
    generalization of the lockstep lane's replay-last-round model), and
    *corrupt*/*blowup* perturb the payload in transit; when
    ``screen_updates`` is on, the merge screens the buffer and quarantines
    via the mask. Host-loss lanes are a lockstep/multi-process concept and
    are ignored here (the driver is single-controller).

Sync-reduction guarantee (the keystone parity artifact,
tests/test_async_rounds.py): with ``buffer_k == no_models`` a merge fires
exactly when a full wave has arrived and the next wave is dispatched only
after the merge — the cadence, RNG stream consumption, train program,
masked-FedAvg divisor, and eval batteries all reduce to the synchronous
round, and the recorded metrics.jsonl rows are bit-identical (modulo wall
times and the async-only keys). This holds for ANY arrival knobs: arrival
order within a wave cannot matter because the merge sorts its buffer by
(wave, lane).

Known deviations from the lockstep engine (documented, not silent):
  - DP noise draws use the newest merged wave's aggregation key — merges
    are not 1:1 with waves in general, so the sync noise stream cannot be
    reproduced for K != C (it IS reproduced at K == C).
  - The LOAN adaptive poison-LR probe never blocks the stream: it always
    uses the last *finalized* backdoor accuracy (the ``stale_poison_probe``
    behavior), one merge stale.
  - Per-batch visualization channels (vis_train_batch_loss /
    batch_track_distance) are not recorded in async mode.
  - Leftover buffered updates at the end of a run are discarded (counted
    in telemetry as ``async/unmerged_leftovers``); a graceful stop flushes
    the partial buffer as one final padded merge instead.

Checkpoint/resume: the full streaming state (version, wave counter, virtual
clock, arrival heap, buffer, and the delta payloads of every wave still
referenced) rides the PR-4 aux sidecar under the ``async_state`` key —
``kill -9`` between merges resumes bit-exactly from the last committed
merge (tests/test_async_rounds.py).

Self-healing layer (README "Self-healing federation"; every knob a strict
bit-identical no-op at its default):

  - ``merge_timeout_v`` + ``merge_min_k``: a merge fires on K arrivals OR
    when the oldest buffered update has waited past the virtual-time
    deadline with at least ``merge_min_k`` buffered — the padded partial
    merge is the same compiled program shape.
  - ``starvation_policy``: what 200 consecutive empty cohorts means —
    "abort" (the pre-existing RuntimeError), "carry" (record a degraded
    no-op step and keep going), "wait" (keep drawing cohorts; the
    watchdog is the backstop). Starved cohorts are counted either way.
  - ``max_outstanding_waves``: admission control — with the watermark hit
    and mergeable updates buffered, the driver flushes a partial merge
    instead of dispatching another cohort. ``arrival_ttl_v`` expires heap
    entries whose service delay exceeded the TTL; they never reach the
    buffer.
  - ``model_health_check``: the shared HealthSentinel (fl/rounds.py) gates
    every commit — an unhealthy merge re-merges the SAME buffer with
    escalated screening up to ``max_round_retries`` (the async analog of
    the sync retry loop; the escalation never recompiles because
    norm_mult is a traced scalar), then rolls back to the last-good ring
    (``rollback_ring``) and records the step degraded.
  - ``min_surviving_clients``: a merge whose screen leaves fewer
    survivors skips aggregation inside the jitted merge (the same
    jnp.where carry as the sync round) and records the step degraded.
"""
from __future__ import annotations

import dataclasses
import heapq
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu import config as cfg
from dba_mod_tpu.data import build_batch_plan
from dba_mod_tpu.fl import faults as flt
from dba_mod_tpu.fl.rounds import (count_bn_layers, nbt_client_deltas,
                                   screen_client_updates)
from dba_mod_tpu.fl.selection import select_agents
from dba_mod_tpu.fl.state import build_client_tasks
from dba_mod_tpu.ops import aggregation as agg

logger = logging.getLogger("async_rounds")

# consecutive empty cohorts before the stream counts as starved and
# starvation_policy decides (abort / wait / carry). Module-level so tests
# can starve cheaply; the production value is deliberately generous — a
# fault plan has to zero out 200 cohorts in a row before we give up
STARVATION_LIMIT = 200


def staleness_weights(staleness: np.ndarray, weighting: str,
                      alpha: float) -> np.ndarray:
    """w(s) per buffered update, f32. "none" ⇒ ones (the caller's static
    branch skips the multiply entirely; this exists for unit tests and the
    recorded histogram), "polynomial" ⇒ (1+s)^-alpha (FedBuff §5),
    "exponential" ⇒ alpha^s."""
    s = np.asarray(staleness, np.float32)
    if weighting == "none":
        return np.ones_like(s)
    if weighting == "polynomial":
        return (1.0 + s) ** np.float32(-alpha)
    if weighting == "exponential":
        return np.float32(alpha) ** s
    raise ValueError(f"unknown staleness_weighting {weighting!r}")


class ArrivalProcess:
    """Deterministic per-(seed, wave) service delays for a cohort's lanes.

    Draws are a pure function of ``SeedSequence((seed, wave))`` — a resumed
    run (or a re-run on another host) replays the identical arrival plan,
    which the determinism test pins."""

    def __init__(self, seed: int, rate: float, jitter: float,
                 straggler_tail: float, straggler_factor: float):
        if rate <= 0:
            raise ValueError(f"arrival_rate must be > 0, got {rate}")
        self.seed = int(seed)
        self.rate = float(rate)
        self.jitter = float(jitter)
        self.straggler_tail = float(straggler_tail)
        self.straggler_factor = float(straggler_factor)

    def delays(self, wave: int, n: int) -> np.ndarray:
        rng = np.random.default_rng(
            np.random.SeedSequence((self.seed, int(wave))))
        d = rng.exponential(1.0 / self.rate, size=n)
        if self.jitter > 0:
            d = d * rng.lognormal(0.0, self.jitter, size=n)
        if self.straggler_tail > 0:
            tail = rng.random(n) < self.straggler_tail
            d = np.where(tail, d * self.straggler_factor, d)
        return d.astype(np.float64)


@dataclasses.dataclass
class _Wave:
    """One dispatched cohort: device-resident payloads + host metadata kept
    until every lane is consumed (merged or dropped) and its per-client
    rows are recorded."""
    wave: int                    # 0-based cohort counter
    epoch: int                   # wave+1 — selection/poison-schedule epoch
    base_version: int            # merge count at dispatch (staleness base)
    names: List[Any]
    adv_names: List[Any]
    tasks: Any                   # host-side ClientTask (np leaves)
    deltas: Any                  # [C] stacked ModelVars tree (post-fault)
    nbt: jax.Array               # [C] num_batches_tracked deltas
    num_samples: np.ndarray      # [C] f32
    pids: np.ndarray             # [C] i32
    rng_agg: jax.Array           # this wave's aggregation key
    metrics_dev: Any             # TrainResult.metrics handles (or np, post-resume)
    locals_dev: Any              # LocalEvals handles or None
    delta_norms: Any             # [C] device/np
    outstanding: int             # lanes not yet consumed
    recorded: bool = False
    t_dispatch: float = 0.0      # virtual clock at dispatch (arrival_ttl_v)


@dataclasses.dataclass
class _MergeInFlight:
    """One dispatched-but-unfinalized merge (overlap_eval's async analog of
    experiment.RoundInFlight): device handles of the merge outputs + every
    host value finalize needs, captured at dispatch time — by finalize time
    the live driver state (version, clock, heap, RNG streams, global model)
    already belongs to the NEXT step's fill."""
    step: int
    t0: float                    # perf_counter at dispatch start
    globals_dev: Any
    wv: Any
    alpha: Any
    is_updated: Any
    n_quar: Any
    degr: Any
    names: List[Any]
    adversaries: List[Any]
    staleness: np.ndarray
    occupancy: int
    retries: int
    rolled_back: bool
    n_dropped: int
    dispatch_wall: float
    extras: Dict[str, Any]
    entries: List[Tuple[int, int]]
    rows: List[_Wave]            # cohorts resolved since the previous merge,
    # in resolution order — finalize replays them before the merge rows
    t_dispatch_end: float = 0.0
    # checkpoint capture (run() only): the streaming sidecar + model/RNG
    # state at dispatch — what save_model must persist for THIS step
    snapshot: Optional[Dict[str, Any]] = None
    vars_after: Any = None
    fg_after: Any = None
    rng_after: Optional[Dict[str, Any]] = None


class AsyncDriver:
    """The persistent buffered-async server loop over one Experiment."""

    def __init__(self, exp):
        p = exp.params
        if jax.process_count() > 1:
            raise ValueError("mode: async is single-controller only")
        if exp.mesh is not None:
            raise ValueError(
                "mode: async does not support a sharded clients mesh yet "
                "(set num_devices: 0); the wave train program is "
                "single-device in this version")
        if exp.sequential_debug:
            raise ValueError("mode: async is incompatible with "
                             "sequential_debug")
        self.exp = exp
        self.C = int(p["no_models"])
        self.K = int(p.get("buffer_k", 0) or 0) or self.C
        self.weighting = str(p.get("staleness_weighting", "none"))
        self.alpha = float(p.get("staleness_alpha", 0.5))
        self.arrivals = ArrivalProcess(
            seed=int(p.get("random_seed") or 0),
            rate=float(p.get("arrival_rate", 1.0)),
            jitter=float(p.get("arrival_jitter", 0.0)),
            straggler_tail=float(p.get("straggler_tail", 0.0)),
            straggler_factor=float(p.get("straggler_factor", 10.0)))
        if bool(p.get("vis_train_batch_loss")) or bool(
                p.get("batch_track_distance")):
            logger.warning("async mode does not record per-batch channels; "
                           "vis_train_batch_loss/batch_track_distance rows "
                           "will be absent")
        # self-healing knobs (README "Self-healing federation") — every
        # default is a strict bit-identical no-op
        self.merge_timeout_v = float(p.get("merge_timeout_v", 0.0))
        self.merge_min_k = int(p.get("merge_min_k", 1))
        self.starvation_policy = str(p.get("starvation_policy", "abort"))
        self.max_outstanding = int(p.get("max_outstanding_waves", 0))
        self.arrival_ttl_v = float(p.get("arrival_ttl_v", 0.0))
        self._sentinel = exp._sentinel  # shared HealthSentinel or None
        # streaming state
        self.version = 0          # merges applied
        self.wave = 0             # cohorts dispatched
        self.clock = 0.0          # virtual time of the last consumed arrival
        self._seq = 0             # heap tie-break
        self._heap: List[Tuple[float, int, int, int]] = []  # (t, seq, wid, lane)
        self._buffer: List[Tuple[int, int]] = []            # (wid, lane)
        self._arrival_t: Dict[Tuple[int, int], float] = {}  # buffered → t
        self._waves: Dict[int, _Wave] = {}
        self._pending_dropped = 0
        self._dispatch_wall = 0.0
        self._total_arrivals = 0
        # self-healing observability (stats() — bench.py's --async lane)
        self._starved_cohorts = 0
        self._expired_arrivals = 0
        self._deadline_merges = 0
        self._backpressure_hits = 0
        self._rollbacks = 0
        self._waves_highwater = 0
        self._merge_latencies: List[float] = []
        # cohorts fully resolved (merged/dropped/expired) whose per-client
        # rows have not been written yet — drained into the next merge's
        # handle and replayed, in resolution order, at its finalize
        self._pending_rows: List[_Wave] = []
        # overlap_eval: pipeline each merge's host finalize (device fetch +
        # row recording + checkpoint) behind the NEXT step's fill/merge
        # compute. Gated off under telemetry (per-step span/epoch
        # attribution stays honest) and for poisoned LOAN runs (the
        # adaptive-LR probe reads last_backdoor_acc at wave dispatch, which
        # pipelining would make one more merge stale than the documented
        # deviation). Off ⇒ this module is a strict bit-identical no-op of
        # the serial driver; on, the recorded stream is byte-identical by
        # construction — finalize replays the deferred rows in resolution
        # order before anything later records.
        self._pipeline = (bool(p.get("overlap_eval", False))
                          and not exp.telemetry.enabled
                          and not (p.type == cfg.TYPE_LOAN
                                   and exp.is_poison_run))
        self._overlap_merges = 0
        self._overlap_hidden_s = 0.0
        self._merge_fn = self._build_merge_fn()
        fcfg = exp.engine.fault_cfg
        self._perturb_fn = (jax.jit(
            lambda tree, plan: flt.perturb_tree(tree, plan, fcfg))
            if fcfg.enabled else None)
        self._restore(exp._resume_aux)

    # ------------------------------------------------------------ merge rule
    def _build_merge_fn(self):
        """The jitted staleness-weighted partial-participation merge over
        the padded [K] buffer. Mirrors engine.aggregate_fn's rule dispatch
        but with the BUFFER as the participation unit: the masked-FedAvg
        divisor counts occupied surviving lanes out of K (so a full,
        unscreened buffer at K == no_models is bitwise the dense sync
        FedAvg — ops/aggregation.py's scale-rewrite), and every rule gets
        the occupancy/survivor mask. The staleness multiply is a STATIC
        branch: "none" traces no weighting ops at all."""
        exp = self.exp
        hyper = exp.engine.hyper
        screening = exp.engine.screening
        min_surv = int(exp.params.get("min_surviving_clients", 1))
        weighting = self.weighting
        K = self.K
        if hyper.aggregation == cfg.AGGR_FOOLSGOLD:  # config.py rejects too
            raise ValueError("foolsgold is stateful per-round and has no "
                             "buffered-async form; pick another rule")

        def merge(global_vars, deltas, nbt, ns, occ, w, rng, norm_mult):
            # deltas: [K] stacked tree; occ [K] bool occupancy; w [K] f32;
            # norm_mult a TRACED scalar so health re-merges escalate the
            # screen without recompiling (the sync retry-loop contract)
            if weighting != "none":
                deltas = jax.tree_util.tree_map(
                    lambda l: (l * agg._bc_mask(w, l)
                               if jnp.issubdtype(l.dtype, jnp.floating)
                               else l), deltas)
            mask = occ
            n_quar = jnp.int32(0)
            if screening:
                surv, _ = screen_client_updates(deltas, occ, occ, norm_mult)
                mask = occ & surv
                n_quar = jnp.sum((occ & ~surv).astype(jnp.int32))
            sigma = hyper.sigma if hyper.diff_privacy else 0.0
            wv = jnp.zeros((K,), jnp.float32)
            alpha = jnp.zeros((K,), jnp.float32)
            calls = jnp.int32(1)
            is_updated = jnp.asarray(True)
            if hyper.aggregation == cfg.AGGR_MEAN:
                # counted=ones ⇒ divisor = #surviving occupied lanes: the
                # partial flush is a true mean over present updates, and a
                # full unscreened buffer keeps the dense eta/K scale bitwise
                new_vars = agg.fedavg_update_masked(
                    global_vars, deltas, hyper.eta, K, mask,
                    jnp.ones((K,), bool), sigma, rng)
            elif hyper.aggregation == cfg.AGGR_GEO_MED:
                r = agg.geometric_median_update(
                    global_vars, deltas, ns, hyper.eta,
                    maxiter=hyper.geom_median_maxiter,
                    max_update_norm=hyper.max_update_norm,
                    dp_sigma=sigma, rng=rng, nbt_deltas=nbt,
                    n_bn=count_bn_layers(global_vars.batch_stats),
                    mask=mask)
                new_vars, calls, wv, alpha = (r.new_state,
                                              r.num_oracle_calls, r.wv,
                                              r.distances)
                is_updated = r.is_updated
            elif hyper.aggregation == cfg.AGGR_KRUM:
                r = agg.krum_update(global_vars, deltas, hyper.eta,
                                    hyper.krum_m, hyper.krum_f, mask=mask,
                                    dp_sigma=sigma, rng=rng)
                new_vars, wv = r.new_state, r.wv
                alpha = jnp.minimum(r.scores, jnp.float32(1e30))
            elif hyper.aggregation == cfg.AGGR_TRIMMED_MEAN:
                r = agg.trimmed_mean_update(global_vars, deltas, hyper.eta,
                                            hyper.trim_beta, mask=mask,
                                            dp_sigma=sigma, rng=rng)
                new_vars, wv = r.new_state, r.wv
            else:  # cfg.AGGR_MEDIAN
                r = agg.coordinate_median_update(global_vars, deltas,
                                                 hyper.eta, mask=mask,
                                                 dp_sigma=sigma, rng=rng)
                new_vars, wv = r.new_state, r.wv
            # min_surviving_clients skip-and-carry, the sync round's
            # degradation ported to the buffered merge: too few surviving
            # occupied lanes ⇒ the global model is carried unchanged
            # (jnp.where with a False scalar is a bitwise passthrough, so
            # the default min_surv=1 path stays bit-identical)
            n_surv = jnp.sum(mask.astype(jnp.int32))
            degraded = n_surv < jnp.int32(min_surv)
            new_vars = jax.tree_util.tree_map(
                lambda g, a: jnp.where(degraded, g, a), global_vars,
                new_vars)
            return (new_vars, wv, alpha, calls, is_updated, n_quar, n_surv,
                    degraded)

        return jax.jit(merge)

    # --------------------------------------------------------------- running
    def run(self, epochs: Optional[int] = None) -> Dict[str, Any]:
        """The persistent server loop: fill the buffer from the arrival
        queue (dispatching cohorts on demand), merge, record, checkpoint —
        until the merge budget is spent or a graceful stop lands."""
        exp = self.exp
        p = exp.params
        eps = int(epochs if epochs is not None else p["epochs"])
        total = int(p.get("async_steps", 0) or 0)
        if total <= 0:
            # same client-update budget as `epochs` sync rounds — at
            # K == C this is exactly `epochs` merges
            total = max(1, eps * self.C // self.K)
        last: Dict[str, Any] = {}
        # overlap_eval: hold ONE dispatched-but-unfinalized merge, so step
        # S's device fetch + row recording + checkpoint drain behind step
        # S+1's fill (wave training) and merge compute — the async analog
        # of the sync engine's depth-1 pipelined loop
        pending: Optional[_MergeInFlight] = None

        def _drain(p: Optional[_MergeInFlight]) -> Optional[Dict[str, Any]]:
            if p is None:
                return None
            r = self._finalize_merge(p)
            self._save_pending(p)
            exp.telemetry.mark_warm()
            logger.info(
                "merge %d/%d done acc=%.2f staleness_mean=%.2f "
                "occupancy=%d/%d", p.step, total, r["global_acc"],
                r["staleness_mean"], r["buffer_occupancy"], self.K)
            return r

        while self.version < total:
            if exp.guard.stop_requested:
                last = _drain(pending) or last
                pending = None
                if self._buffer:
                    # graceful stop: flush the partial buffer as one final
                    # padded merge (occupancy < K — same compiled shape)
                    last = self._merge_and_record()
                    self._save()
                exp.interrupted = True
                logger.warning(
                    "graceful stop honored at the merge boundary after "
                    "step %d (resume with --resume auto)", self.version)
                break
            if self._fill_buffer():
                if self._pipeline:
                    nxt = self._dispatch_merge(capture_save=True)
                    last = _drain(pending) or last
                    pending = nxt
                    continue
                last = self._merge_and_record()
            else:
                last = _drain(pending) or last
                pending = None
                last = self._carry_starved_step()
            self._save()
            exp.telemetry.mark_warm()
            logger.info(
                "merge %d/%d done acc=%.2f staleness_mean=%.2f "
                "occupancy=%d/%d", self.version, total, last["global_acc"],
                last["staleness_mean"], last["buffer_occupancy"], self.K)
        last = _drain(pending) or last
        leftovers = len(self._buffer) + len(self._heap)
        if leftovers and not exp.interrupted:
            exp.telemetry.counter("async/unmerged_leftovers").inc(leftovers)
            logger.info("run end: %d buffered/in-flight updates discarded "
                        "(budget of %d merges spent)", leftovers, total)
        return last

    def run_steps(self, n: int) -> Dict[str, Any]:
        """Run exactly n merges (bench.py's --async lane), no checkpoints.
        Under overlap_eval the merges are pipelined depth-1 exactly like
        run(); the trailing merge is drained before returning, so n calls
        leave no in-flight state behind."""
        last: Dict[str, Any] = {}
        pending: Optional[_MergeInFlight] = None
        for _ in range(n):
            if self._fill_buffer():
                if self._pipeline:
                    nxt = self._dispatch_merge()
                    if pending is not None:
                        last = self._finalize_merge(pending)
                    pending = nxt
                    continue
                last = self._merge_and_record()
            else:
                if pending is not None:
                    last = self._finalize_merge(pending)
                    pending = None
                last = self._carry_starved_step()
        if pending is not None:
            last = self._finalize_merge(pending)
        return last

    def stats(self) -> Dict[str, Any]:
        """Self-healing observability for bench.py's --async lane: p95
        virtual merge latency (arrival → merge, virtual seconds) plus the
        backpressure/starvation counters and the outstanding-waves
        high-water mark."""
        lat = sorted(self._merge_latencies)
        p95 = float(lat[int(0.95 * (len(lat) - 1))]) if lat else 0.0
        return {"merge_latency_v_p95": p95,
                "outstanding_waves_highwater": self._waves_highwater,
                "starved_cohorts": self._starved_cohorts,
                "expired_arrivals": self._expired_arrivals,
                "deadline_merges": self._deadline_merges,
                "backpressure_hits": self._backpressure_hits,
                "health_rollbacks": self._rollbacks,
                # overlap_eval: merges finalized one step late + host
                # seconds that ran behind the next step's compute
                "pipelined_merges": self._overlap_merges,
                "hidden_finalize_s": round(self._overlap_hidden_s, 6)}

    def _save(self):
        self.exp.save_model(self.version,
                            extra_aux={"async_state": self._snapshot()})

    # ------------------------------------------------------ arrivals / waves
    def _deadline_due(self) -> bool:
        """True when a merge_timeout_v deadline merge should fire: the
        oldest buffered update has waited past the deadline (>= merge_min_k
        buffered) and the next known arrival — if any — lands after it.
        Firing advances the virtual clock to the deadline instant."""
        if self.merge_timeout_v <= 0 or len(self._buffer) < self.merge_min_k:
            return False
        oldest = self._arrival_t.get(tuple(self._buffer[0]), self.clock)
        deadline = oldest + self.merge_timeout_v
        if self._heap and self._heap[0][0] < deadline:
            return False
        self.clock = max(self.clock, deadline)
        return True

    def _expire_arrival(self, t: float, wid: int) -> bool:
        """arrival_ttl_v: an update whose service delay exceeded the TTL is
        expired at pop time — it never reaches the buffer, its lane is
        freed, and a fully-resolved cohort is recorded immediately."""
        w = self._waves[wid]
        if t - w.t_dispatch <= self.arrival_ttl_v:
            return False
        self._expired_arrivals += 1
        self.exp.telemetry.counter("async/expired_arrivals").inc()
        w.outstanding -= 1
        if w.outstanding == 0 and not w.recorded:
            self._resolve_wave(w)
            del self._waves[wid]
        return True

    def _fill_buffer(self) -> bool:
        """Pop arrivals into the buffer until it holds K — or until a
        merge_timeout_v deadline or max_outstanding_waves backpressure
        flush fires a partial merge. Dispatches a new cohort whenever the
        queue drains; virtual time advances to each consumed arrival.
        Returns True when the buffer should be merged, False when the
        stream is starved and starvation_policy says to carry a no-op
        step."""
        exp = self.exp
        empty_waves = 0
        while len(self._buffer) < self.K:
            if self._deadline_due():
                self._deadline_merges += 1
                exp.telemetry.counter("async/deadline_merges").inc()
                return True
            while not self._heap:
                if (self.max_outstanding > 0 and self._buffer
                        and len(self._waves) >= self.max_outstanding):
                    # admission control: the watermark is hit and we hold
                    # mergeable updates — flush instead of dispatching
                    self._backpressure_hits += 1
                    exp.telemetry.counter("async/backpressure_hits").inc()
                    return True
                before = len(self._heap)
                self._dispatch_wave()
                if len(self._heap) == before:
                    empty_waves += 1
                    self._starved_cohorts += 1
                    exp.telemetry.counter("async/starved_cohorts").inc()
                    if empty_waves > STARVATION_LIMIT:
                        if self.starvation_policy == "carry":
                            if self._buffer:
                                return True  # flush what we hold
                            return False     # carry a degraded no-op step
                        if self.starvation_policy == "wait":
                            # keep drawing cohorts indefinitely; the
                            # watchdog (watchdog_hard_s) is the backstop
                            empty_waves = 0
                            continue
                        raise RuntimeError(
                            "async arrival queue starved: "
                            f"{STARVATION_LIMIT} consecutive cohorts "
                            "produced no arrivals (fault dropout too "
                            "aggressive?)")
                else:
                    empty_waves = 0
            t, _seq, wid, lane = heapq.heappop(self._heap)
            if self.arrival_ttl_v > 0 and self._expire_arrival(t, wid):
                continue
            self.clock = max(self.clock, t)
            self._buffer.append((wid, lane))
            self._arrival_t[(wid, lane)] = self.clock
            self._total_arrivals += 1
            exp.telemetry.counter("async/arrivals").inc()
            exp.telemetry.gauge("async/buffer_occupancy").set(
                len(self._buffer))
        return True

    def _dispatch_wave(self):
        """Select + train one cohort through the lockstep train program and
        enqueue its lanes as future arrivals. Consumes the selection/plan/
        train RNG streams exactly like a sync round dispatch — the parity
        anchor."""
        exp = self.exp
        p = exp.params
        wid = self.wave
        self.wave += 1
        epoch = wid + 1
        t0 = time.perf_counter()
        with exp.telemetry.span("async/dispatch_wave"):
            agent_names, adv_names = select_agents(
                p, epoch, exp.participants, exp.benign_names, exp.select_rng)
            backdoor_acc = None
            if (p.type == cfg.TYPE_LOAN and exp.is_poison_run
                    and any(p.adversary_slot_of(n) >= 0 and
                            epoch in p.poison_epochs_for(
                                p.adversary_slot_of(n))
                            for n in agent_names)):
                # never block the stream on a probe: one merge stale
                backdoor_acc = exp.last_backdoor_acc
            slots = np.array([exp.client_slots[n] for n in agent_names],
                             np.int64)
            tasks = build_client_tasks(p, agent_names, epoch, slots,
                                       exp.epochs_max, backdoor_acc)
            if exp.dynamic_steps:
                b = int(p["batch_size"])
                round_max = max((len(exp.client_indices[n])
                                 for n in agent_names), default=1)
                min_steps = exp._bucket_steps(
                    max(1, int(np.ceil(round_max / b))))
            else:
                min_steps = exp.steps_per_epoch
            plan = build_batch_plan(
                [exp.client_indices[n] for n in agent_names],
                [int(e) for e in tasks.num_epochs], int(p["batch_size"]),
                exp.plan_rng, min_steps=min_steps,
                min_epochs=exp.epochs_max)
            tasks_seq = jax.tree_util.tree_map(
                lambda l: jnp.asarray(l[None]), tasks)
            idx_seq = jnp.asarray(plan.idx[None])
            mask_seq = jnp.asarray(plan.mask[None])
            exp.rng_key, round_key = jax.random.split(exp.rng_key)
            rng_train, rng_agg = jax.random.split(round_key)
            lane = jnp.arange(len(agent_names), dtype=jnp.int32)
            train = exp.engine.train_fn(exp.global_vars, tasks_seq, idx_seq,
                                        mask_seq, lane, rng_train)
            nbt = nbt_client_deltas(mask_seq, tasks_seq.scale)
            locals_dev = None
            if exp.local_eval:
                tasks_last = jax.tree_util.tree_map(lambda l: l[0],
                                                    tasks_seq)
                prev = jax.tree_util.tree_map(jnp.zeros_like, train.deltas)
                locals_dev = exp.engine.local_evals_fn(
                    exp.global_vars, train.deltas, tasks_last, prev)
            deltas = train.deltas
            dropped = np.zeros(len(agent_names), bool)
            delay_mult = np.ones(len(agent_names))
            fcfg = exp.engine.fault_cfg
            if fcfg.enabled:
                # faults as arrival events: same deterministic per-epoch
                # plan as the lockstep lanes — dropped never arrives, stale
                # straggles, corrupt/blowup perturb the payload in transit
                rng_f = jax.random.fold_in(exp._fault_key, epoch)
                fplan = flt.make_fault_plan(
                    fcfg, rng_f, jnp.ones((len(agent_names),), bool))
                fhost = jax.device_get(fplan)
                dropped = np.asarray(fhost.dropped)
                delay_mult = np.where(np.asarray(fhost.stale),
                                      self.arrivals.straggler_factor, 1.0)
                deltas = self._perturb_fn(deltas, fplan)
            self._pending_dropped += int(dropped.sum())
            delays = self.arrivals.delays(wid, len(agent_names)) * delay_mult
            for c in range(len(agent_names)):
                if dropped[c]:
                    continue
                heapq.heappush(self._heap,
                               (self.clock + float(delays[c]), self._seq,
                                wid, c))
                self._seq += 1
            self._waves[wid] = _Wave(
                wave=wid, epoch=epoch, base_version=self.version,
                names=list(agent_names), adv_names=list(adv_names),
                tasks=tasks, deltas=deltas, nbt=nbt,
                num_samples=plan.num_samples.astype(np.float32),
                pids=np.asarray(tasks.participant_id),
                rng_agg=rng_agg, metrics_dev=train.metrics,
                locals_dev=locals_dev, delta_norms=train.delta_norms,
                outstanding=int(len(agent_names) - dropped.sum()),
                t_dispatch=self.clock)
            if self._waves[wid].outstanding == 0:
                # fully dropped cohort: resolve its train rows and free it
                self._resolve_wave(self._waves[wid])
                del self._waves[wid]
        if len(self._waves) > self._waves_highwater:
            self._waves_highwater = len(self._waves)
            exp.telemetry.gauge("async/outstanding_waves_highwater").set(
                self._waves_highwater)
        exp.telemetry.counter("async/waves").inc()
        self._dispatch_wall += time.perf_counter() - t0

    # ----------------------------------------------------------------- merge
    def _merge_and_record(self) -> Dict[str, Any]:
        """Merge the buffer (padded to K), advance the version, run the
        global battery, and record one metrics.jsonl row keyed by the
        aggregation step. Serial composition of the two merge phases; the
        pipelined run() loop holds the dispatched handle across one fill
        instead."""
        return self._finalize_merge(self._dispatch_merge())

    def _dispatch_merge(self, capture_save: bool = False) -> _MergeInFlight:
        """Phase 1 of a merge: consume the buffer, run the jitted merge
        (with the sentinel retry loop), dispatch the global battery, and
        COMMIT the new model/version — returning without blocking on the
        eval transfer. Every host value the deferred finalize needs is
        captured in the handle, because by finalize time the live driver
        state may already belong to the next step's fill. With
        ``capture_save`` the checkpoint payload (streaming snapshot +
        model/RNG state) is captured too, at exactly the state a serial
        post-merge save would see."""
        exp = self.exp
        t0 = time.perf_counter()
        step = self.version + 1
        exp.telemetry.set_epoch(step)
        entries = sorted(self._buffer)     # (wave, lane) — deterministic
        self._buffer = []
        B = len(entries)
        # per-client rows for cohorts that fully resolved with this batch:
        # resolution is deferred into the handle and replayed at finalize —
        # the serial path finalizes immediately, so the recorded stream is
        # order-identical in both modes
        for wid, _lane in entries:
            self._waves[wid].outstanding -= 1
        for wid in sorted({w for w, _ in entries}):
            w = self._waves[wid]
            if w.outstanding == 0 and not w.recorded:
                self._resolve_wave(w)
        names = [self._waves[w].names[lane] for w, lane in entries]
        merged_by_wave: Dict[int, set] = {}
        for (wid, lane) in entries:
            merged_by_wave.setdefault(wid, set()).add(lane)
        adversaries: List[Any] = []
        for wid in sorted(merged_by_wave):
            w = self._waves[wid]
            present = {w.names[ln] for ln in merged_by_wave[wid]}
            adversaries.extend(n for n in w.adv_names if n in present)
        for e in entries:
            lat = max(0.0, self.clock - self._arrival_t.pop(e, self.clock))
            self._merge_latencies.append(lat)
            exp.telemetry.histogram("async/merge_latency_v").observe(lat)
        if len(self._merge_latencies) > 100_000:
            del self._merge_latencies[:-50_000]
        rolled_back = False
        with exp.telemetry.span("async/merge"):
            deltas, nbt, ns, pids = self._gather(entries)
            staleness = np.array(
                [self.version - self._waves[w].base_version
                 for w, _ in entries], np.float32)
            for s in staleness:
                exp.telemetry.histogram("staleness").observe(float(s))
            w_full = np.zeros((self.K,), np.float32)
            w_full[:B] = staleness_weights(staleness, self.weighting,
                                           self.alpha)
            occ = np.zeros((self.K,), bool)
            occ[:B] = True
            rng = self._waves[max(w for w, _ in entries)].rng_agg
            vars_before = exp.global_vars
            # health sentinel loop (async analog of the sync retry loop):
            # an unhealthy candidate re-merges the SAME buffer with an
            # escalated norm screen; norm_mult is traced, so no recompile
            norm_mult: Optional[float] = None
            retries = 0
            healthy, unorm = True, 0.0
            while True:
                nm = (exp.engine.base_norm_mult if norm_mult is None
                      else norm_mult)
                (new_vars, wv, alpha, calls, is_updated, n_quar, n_surv,
                 degr) = self._merge_fn(
                    vars_before, deltas, nbt, jnp.asarray(ns),
                    jnp.asarray(occ), jnp.asarray(w_full), rng,
                    jnp.float32(nm))
                if self._sentinel is None:
                    break
                healthy, unorm = self._sentinel.check(vars_before, new_vars)
                if (healthy or not exp.engine.screening
                        or retries >= exp.max_round_retries):
                    break
                retries += 1
                norm_mult = exp._escalate_norm_mult(nm)
                logger.warning(
                    "merge %d: unhealthy aggregate; re-merge %d/%d with "
                    "norm screen at %.2fx median", step, retries,
                    exp.max_round_retries, norm_mult)
            if self._sentinel is not None and not healthy:
                # retries exhausted (or unscreened): roll back to the
                # last-good ring and record the step degraded
                rolled_back = True
                self._rollbacks += 1
                exp.telemetry.counter("async/health_rollbacks").inc()
                new_vars = self._sentinel.rollback_target(vars_before)
                logger.warning(
                    "merge %d: unhealthy aggregate after %d re-merges "
                    "(update norm %.3g vs EMA %.3g); rolled back to "
                    "last-good model", step, retries, unorm,
                    self._sentinel.ema)
            globals_dev = exp.engine.global_evals_fn(new_vars)
        exp.global_vars = new_vars
        self.version = step
        # free fully-consumed cohorts (their payloads are merged + resolved)
        for wid in [w for w, v in self._waves.items()
                    if v.outstanding == 0 and v.recorded]:
            del self._waves[wid]
        if self._sentinel is not None and not rolled_back:
            # commit the ring at DISPATCH so the sentinel observes merge S
            # before merge S+1's candidate is checked against it — the same
            # observation order as the serial path. The degradation scalar
            # is already synced (sentinel.check device_gets the norms), so
            # this fetch does not stall the pipeline.
            degr_host = bool(jax.device_get(degr))
            if not degr_host:
                self._sentinel.commit(step, new_vars, unorm)
        extras = {"mode": "async", "buffer_occupancy": B,
                  "staleness_mean": float(staleness.mean()) if B else 0.0,
                  "staleness_max": float(staleness.max()) if B else 0.0,
                  "waves_dispatched": self.wave,
                  "arrivals_total": self._total_arrivals,
                  "virtual_time": self.clock}
        h = _MergeInFlight(
            step=step, t0=t0, globals_dev=globals_dev, wv=wv, alpha=alpha,
            is_updated=is_updated, n_quar=n_quar, degr=degr, names=names,
            adversaries=adversaries, staleness=staleness, occupancy=B,
            retries=retries, rolled_back=rolled_back,
            n_dropped=self._pending_dropped,
            dispatch_wall=self._dispatch_wall, extras=extras,
            entries=entries, rows=self._pending_rows)
        self._pending_rows = []
        self._pending_dropped = 0
        self._dispatch_wall = 0.0
        if capture_save:
            h.snapshot = self._snapshot()
            h.vars_after = new_vars
            h.fg_after = exp.fg_state
            h.rng_after = exp._snapshot_rng()
        h.t_dispatch_end = time.perf_counter()
        return h

    def _finalize_merge(self, h: _MergeInFlight) -> Dict[str, Any]:
        """Phase 2 of a merge: block on the eval transfer, replay the
        deferred per-client rows (in resolution order), and record the
        merge. Under overlap_eval this runs one step late — everything it
        touches rides the handle, so the recorded stream is byte-identical
        to the serial composition."""
        exp = self.exp
        with exp.telemetry.span("async/finalize"):
            t_fin = time.perf_counter()
            (globals_, wv_h, alpha_h, is_upd_h, n_quar_h,
             degr_h) = jax.device_get(
                (h.globals_dev, h.wv, h.alpha, h.is_updated, h.n_quar,
                 h.degr))
        finalize_time = time.perf_counter() - t_fin
        if self._pipeline:
            self._overlap_merges += 1
            self._overlap_hidden_s += max(0.0, t_fin - h.t_dispatch_end)
        for w in h.rows:
            self._record_wave_rows(w)
        degraded = bool(degr_h) or h.rolled_back
        exp.last_is_updated = bool(is_upd_h)
        exp.last_global_loss = float(globals_.clean.loss)
        if exp.is_poison_run:
            exp.last_backdoor_acc = float(globals_.poison.acc)
        times = {"round_time": time.perf_counter() - h.t0,
                 "dispatch_time": h.dispatch_wall,
                 "finalize_time": finalize_time}
        robust = {"n_quarantined": int(n_quar_h), "n_dropped": h.n_dropped,
                  "n_retries": h.retries, "degraded": degraded}
        self._record_merge(h.step, h.entries, h.names, h.adversaries,
                           globals_, wv_h, alpha_h, times, robust, h.extras)
        exp.telemetry.counter("async/merges").inc()
        exp.telemetry.counter("async/updates_merged").inc(h.occupancy)
        self._flush_merge_telemetry(h.step, robust, times)
        return {"epoch": h.step, "agents": h.names,
                "global_acc": float(globals_.clean.acc),
                "backdoor_acc": (float(globals_.poison.acc)
                                 if exp.is_poison_run else None),
                **times, **robust, **h.extras}

    def _save_pending(self, h: _MergeInFlight):
        """Checkpoint a finalized pipelined merge from its dispatch-time
        capture. Runs AFTER _finalize_merge(h): save_model reads
        last_global_loss (best-val) and last_backdoor_acc, which finalize
        just set from this merge's battery — the same values a serial save
        would see."""
        if h.snapshot is None:
            return
        from dba_mod_tpu.fl.experiment import RoundInFlight
        fl = RoundInFlight(
            epoch=h.step, t0=h.t0, seg_epochs=[], agent_names=[],
            adv_names=[], tasks_list=[], mask_list=[], payload=None,
            vars_after=h.vars_after, fg_after=h.fg_after,
            rng_after=h.rng_after)
        self.exp.save_model(h.step, fl=fl,
                            extra_aux={"async_state": h.snapshot})

    def _carry_starved_step(self) -> Dict[str, Any]:
        """starvation_policy "carry": the stream produced no arrivals for
        200 consecutive cohorts and the buffer is empty — consume one merge
        step as a recorded no-op (model unchanged, row degraded) so a
        starved run terminates inside its budget instead of aborting."""
        exp = self.exp
        t0 = time.perf_counter()
        step = self.version + 1
        exp.telemetry.set_epoch(step)
        self._flush_pending_rows()  # cohorts expired during the starved fill
        globals_dev = exp.engine.global_evals_fn(exp.global_vars)
        self.version = step
        globals_ = jax.device_get(globals_dev)
        exp.last_is_updated = False
        exp.last_global_loss = float(globals_.clean.loss)
        if exp.is_poison_run:
            exp.last_backdoor_acc = float(globals_.poison.acc)
        times = {"round_time": time.perf_counter() - t0,
                 "dispatch_time": self._dispatch_wall, "finalize_time": 0.0}
        self._dispatch_wall = 0.0
        robust = {"n_quarantined": 0, "n_dropped": self._pending_dropped,
                  "n_retries": 0, "degraded": True}
        self._pending_dropped = 0
        extras = {"mode": "async", "buffer_occupancy": 0,
                  "staleness_mean": 0.0, "staleness_max": 0.0,
                  "waves_dispatched": self.wave,
                  "arrivals_total": self._total_arrivals,
                  "virtual_time": self.clock}
        zeros = np.zeros((self.K,), np.float32)
        self._record_merge(step, [], [], [], globals_, zeros, zeros, times,
                           robust, extras)
        exp.telemetry.counter("async/starved_steps").inc()
        self._flush_merge_telemetry(step, robust, times)
        logger.warning("merge %d: starved stream carried as a degraded "
                       "no-op step (starvation_policy: carry)", step)
        return {"epoch": step, "agents": [],
                "global_acc": float(globals_.clean.acc),
                "backdoor_acc": (float(globals_.poison.acc)
                                 if exp.is_poison_run else None),
                **times, **robust, **extras}

    def _gather(self, entries):
        """Assemble the padded [K] merge batch from the per-wave stacked
        payloads, grouped per wave (one gather per cohort, not per lane).
        Inert padding lanes are zero-delta and masked out by occupancy —
        the same contract as the lockstep mesh padding."""
        groups: List[Tuple[_Wave, List[int]]] = []
        for wid, lane in entries:  # entries sorted ⇒ groups contiguous
            w = self._waves[wid]
            if groups and groups[-1][0] is w:
                groups[-1][1].append(lane)
            else:
                groups.append((w, [lane]))
        d_parts, n_parts, ns_parts, pid_parts = [], [], [], []
        for w, lanes in groups:
            if lanes == list(range(len(w.names))):
                d_parts.append(w.deltas)   # whole-cohort fast path — and
                n_parts.append(w.nbt)      # the K == C parity path: the
                # buffer IS the wave, untouched by any gather op
            else:
                idx = jnp.asarray(lanes, jnp.int32)
                d_parts.append(jax.tree_util.tree_map(
                    lambda l: jnp.take(l, idx, axis=0), w.deltas))
                n_parts.append(jnp.take(w.nbt, idx, axis=0))
            ns_parts.append(w.num_samples[lanes])
            pid_parts.append(w.pids[lanes])
        pad = self.K - len(entries)
        if pad:
            zero = jax.tree_util.tree_map(
                lambda l: jnp.zeros((pad,) + l.shape[1:], l.dtype),
                d_parts[0])
            d_parts.append(zero)
            n_parts.append(jnp.zeros((pad,), jnp.float32))
            ns_parts.append(np.zeros((pad,), np.float32))
            pid_parts.append(np.zeros((pad,), np.int32))
        if len(d_parts) == 1:
            deltas, nbt = d_parts[0], n_parts[0]
        else:
            deltas = jax.tree_util.tree_map(
                lambda *ls: jnp.concatenate(ls, axis=0), *d_parts)
            nbt = jnp.concatenate(n_parts, axis=0)
        return (deltas, nbt, np.concatenate(ns_parts).astype(np.float32),
                np.concatenate(pid_parts).astype(np.int32))

    # ------------------------------------------------------------- recording
    def _resolve_wave(self, w: _Wave):
        """Mark a fully-consumed cohort resolved and queue its per-client
        rows. Rows are ALWAYS deferred (both modes) and replayed in
        resolution order by the next finalize — identical in-memory stream
        to recording inline, but under overlap_eval the device_get of the
        cohort's train metrics rides the hidden finalize instead of
        stalling the dispatch path."""
        w.recorded = True
        self._pending_rows.append(w)

    def _flush_pending_rows(self):
        """Record any resolved-but-unrecorded cohorts now — the non-merge
        recording paths (starved carry steps) must flush before they write
        their own rows to keep the stream ordered."""
        rows, self._pending_rows = self._pending_rows, []
        for w in rows:
            self._record_wave_rows(w)

    def _record_wave_rows(self, w: _Wave):
        """Per-client rows for one fully-resolved cohort: train metrics and
        (when local_eval) the local battery — the same row semantics as the
        lockstep recorder block for an interval-1 round, keyed by the
        cohort's selection epoch."""
        exp = self.exp
        rec = exp.recorder
        params = exp.params
        w.recorded = True
        metrics, locals_, delta_norms = jax.device_get(
            (w.metrics_dev, w.locals_dev, w.delta_norms))
        w.metrics_dev, w.locals_dev = None, None
        baseline = bool(params["baseline"])
        ppb = np.asarray(w.tasks.poisoning_per_batch)
        adv_slot = np.asarray(w.tasks.adv_slot)
        for c, name in enumerate(w.names):
            n_e = int(w.tasks.num_epochs[c])
            for e in range(n_e):
                count = max(float(metrics.count[0, c, e]), 1.0)
                rec.add_train(name, (w.epoch - 1) * n_e + e + 1, w.epoch,
                              e + 1,
                              float(metrics.loss_sum[0, c, e]) / count,
                              100.0 * float(metrics.correct[0, c, e])
                              / count,
                              int(metrics.correct[0, c, e]), int(count))
            poisoning = bool(ppb[c] > 0)
            if locals_ is not None:
                lr = locals_
                if not (poisoning and baseline):
                    rec.add_test(name, w.epoch, float(lr.clean.loss[c]),
                                 float(lr.clean.acc[c]),
                                 int(lr.clean.correct[c]),
                                 int(lr.clean.count[c]))
                if poisoning and exp.is_poison_run:
                    if not baseline:
                        rec.add_poisontest(name, w.epoch,
                                           float(lr.poison_pre.loss[c]),
                                           float(lr.poison_pre.acc[c]),
                                           int(lr.poison_pre.correct[c]),
                                           int(lr.poison_pre.count[c]))
                    rec.add_poisontest(name, w.epoch,
                                       float(lr.poison_post.loss[c]),
                                       float(lr.poison_post.acc[c]),
                                       int(lr.poison_post.correct[c]),
                                       int(lr.poison_post.count[c]))
                if exp.is_poison_run and int(adv_slot[c]) >= 0:
                    rec.add_triggertest(
                        name, f"{name}_trigger", "", w.epoch,
                        float(lr.agent_trigger.loss[c]),
                        float(lr.agent_trigger.acc[c]),
                        int(lr.agent_trigger.correct[c]),
                        int(lr.agent_trigger.count[c]))
            if poisoning and not baseline:
                rec.scale_temp_one_row.extend(
                    [w.epoch, round(float(delta_norms[c]), 4)])

    def _record_merge(self, step, entries, names, adversaries, globals_,
                      wv, alpha, times, robust, extras):
        """Global battery rows + the metrics.jsonl row for one merge —
        keyed by the aggregation step, same semantic keys as a sync round
        plus the async extras."""
        exp = self.exp
        rec = exp.recorder
        params = exp.params
        rec.add_test("global", step, float(globals_.clean.loss),
                     float(globals_.clean.acc), int(globals_.clean.correct),
                     int(globals_.clean.count))
        if exp.is_poison_run:
            g = globals_
            rec.add_poisontest("global", step, float(g.poison.loss),
                               float(g.poison.acc), int(g.poison.correct),
                               int(g.poison.count))
            rec.add_triggertest("global", "combine", "", step,
                                float(g.poison.loss), float(g.poison.acc),
                                int(g.poison.correct), int(g.poison.count))
            if params.is_centralized_attack:
                tnames = [f"global_in_index_{j}_trigger"
                          for j in range(exp.engine.num_global_triggers)]
            else:
                tnames = [f"global_in_{a}_trigger"
                          for a in params.adversary_list]
            for j, tname in enumerate(tnames):
                rec.add_triggertest(
                    "global", tname, "", step,
                    float(g.per_trigger.loss[j]),
                    float(g.per_trigger.acc[j]),
                    int(g.per_trigger.correct[j]),
                    int(g.per_trigger.count[j]))
        if rec.scale_temp_one_row:
            rec.scale_temp_one_row.append(
                round(float(globals_.clean.acc), 4))
        if params.aggregation != cfg.AGGR_MEAN:
            rec.add_weight_result([str(n) for n in names],
                                  np.asarray(wv)[:len(names)].tolist(),
                                  np.asarray(alpha)[:len(names)].tolist(),
                                  epoch=step)
        rec.add_round_json(
            epoch=step, agents=[str(n) for n in names],
            adversaries=[str(a) for a in adversaries],
            is_updated=exp.last_is_updated,
            global_acc=float(globals_.clean.acc),
            global_loss=float(globals_.clean.loss),
            backdoor_acc=(float(globals_.poison.acc)
                          if exp.is_poison_run else None),
            **times, **robust, **extras)
        rec.save(exp.is_poison_run)

    def _flush_merge_telemetry(self, step, robust, times):
        t = self.exp.telemetry
        if not t.enabled:
            return
        t.counter("rounds").inc()
        if robust.get("n_quarantined"):
            t.counter("clients_quarantined").inc(robust["n_quarantined"])
        if robust.get("n_dropped"):
            t.counter("clients_dropped").inc(robust["n_dropped"])
        if robust.get("n_retries"):
            t.counter("round_retries").inc(robust["n_retries"])
        if robust.get("degraded"):
            t.counter("degraded_rounds").inc()
        t.histogram("round_seconds").observe(times["round_time"])
        t.flush_round(step)

    # ------------------------------------------------------ checkpoint state
    def _snapshot(self) -> Dict[str, Any]:
        """Host-picklable streaming state for the aux sidecar: everything
        needed to resume the arrival queue and buffer bit-exactly. Wave
        payloads are np trees; device handles for unrecorded rows are
        fetched here (they must survive the process dying)."""
        waves = {}
        live = ({e[2] for e in self._heap} | {w for w, _ in self._buffer})
        for wid in live:
            w = self._waves[wid]
            metrics, locals_, norms = jax.device_get(
                (w.metrics_dev, w.locals_dev, w.delta_norms))
            waves[wid] = {
                "wave": w.wave, "epoch": w.epoch,
                "base_version": w.base_version, "names": w.names,
                "adv_names": w.adv_names,
                "tasks": jax.tree_util.tree_map(np.asarray, w.tasks),
                "deltas": jax.tree_util.tree_map(np.asarray, w.deltas),
                "nbt": np.asarray(w.nbt),
                "num_samples": w.num_samples, "pids": w.pids,
                "rng_agg": np.asarray(jax.random.key_data(w.rng_agg)),
                "metrics": metrics, "locals": locals_,
                "delta_norms": np.asarray(norms),
                "outstanding": w.outstanding, "recorded": w.recorded,
                "t_dispatch": w.t_dispatch}
        return {"version": self.version, "wave": self.wave,
                "clock": self.clock, "seq": self._seq,
                "heap": list(self._heap), "buffer": list(self._buffer),
                "arrival_t": [[wid, lane, t] for (wid, lane), t
                              in self._arrival_t.items()],
                "health": (self._sentinel.state()
                           if self._sentinel is not None else None),
                "pending_dropped": self._pending_dropped,
                "total_arrivals": self._total_arrivals, "waves": waves}

    def _restore(self, aux: Optional[Dict[str, Any]]):
        st = (aux or {}).get("async_state")
        if st is None:
            if self.exp.start_epoch > 1:
                # model-only resume (no/discarded sidecar): restart the
                # stream at the committed version with an empty buffer —
                # the arrival queue is rebuilt from fresh cohorts
                self.version = self.exp.start_epoch - 1
                self.wave = self.version * self.K // max(self.C, 1)
                logger.warning(
                    "async resume without a streaming sidecar: restarting "
                    "the arrival queue at merge %d (buffer state lost)",
                    self.version)
            return
        self.version = int(st["version"])
        self.wave = int(st["wave"])
        self.clock = float(st["clock"])
        self._seq = int(st["seq"])
        self._heap = [tuple(e) for e in st["heap"]]
        heapq.heapify(self._heap)
        self._buffer = [tuple(e) for e in st["buffer"]]
        # pre-PR sidecars carry no arrival times: buffered entries then get
        # no deadline credit (t defaults to the restored clock)
        self._arrival_t = {(int(a), int(b)): float(t)
                           for a, b, t in st.get("arrival_t", [])}
        if self._sentinel is not None:
            self._sentinel.load_state(st.get("health"))
        self._pending_dropped = int(st["pending_dropped"])
        self._total_arrivals = int(st["total_arrivals"])
        for wid, d in st["waves"].items():
            self._waves[int(wid)] = _Wave(
                wave=int(d["wave"]), epoch=int(d["epoch"]),
                base_version=int(d["base_version"]), names=d["names"],
                adv_names=d["adv_names"], tasks=d["tasks"],
                deltas=jax.tree_util.tree_map(jnp.asarray, d["deltas"]),
                nbt=jnp.asarray(d["nbt"]),
                num_samples=d["num_samples"], pids=d["pids"],
                rng_agg=jax.random.wrap_key_data(jnp.asarray(d["rng_agg"])),
                metrics_dev=d["metrics"], locals_dev=d["locals"],
                delta_norms=d["delta_norms"],
                outstanding=int(d["outstanding"]),
                recorded=bool(d["recorded"]),
                t_dispatch=float(d.get("t_dispatch", 0.0)))
        logger.info("async resume: merge %d, %d cohorts live, %d buffered, "
                    "%d in flight", self.version, len(self._waves),
                    len(self._buffer), len(self._heap))
