"""The single-client local-training step — one `lax.scan`, vmapped over the
clients axis by the round engine.

Capability parity with the reference client loop (image_train.py:21-315,
loan_train.py:17-261), re-expressed as data-dependent selects so benign and
poison clients share one compiled program:

- fresh torch-SGD per global epoch (momentum buffers start at zero — the
  reference constructs a new optimizer per client per round,
  image_train.py:33, :63);
- per-internal-epoch LR row (benign constant lr; poison MultiStepLR —
  image_train.py:66-68, 118-119);
- loss = α·CE + (1-α)·‖w - w_anchor‖ (image_train.py:85-90);
- batch poisoning of the first `poisoning_per_batch` samples
  (image_helper.py:298-326);
- FoolsGold per-parameter gradient accumulation across every batch
  (image_train.py:94-100);
- model-replacement scaling epilogue w ← w_a + γ·(w - w_a) over the FULL
  state including BN stats (image_train.py:166-171 scales the state_dict).

One call covers ONE global epoch (one `aggr_epoch_interval` segment). The
anchor for the distance loss and the scaling epilogue is the client's state at
the segment start — the reference re-snapshots `last_local_model` at the top
of every global epoch (image_train.py:26-27, :52-54, :306), which equals the
global model only for the first segment of a round. The engine chains
segments and derives Δ = w_end - w_global at the end.

Per-epoch train metrics (loss sum, correct, count, poisoned count) are
accumulated with scatter-adds for CSV-schema parity (csv_record.train_result).
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from dba_mod_tpu.models import ModelDef, ModelVars
from dba_mod_tpu.fl.device_data import DeviceData
from dba_mod_tpu.fl.state import ClientTask, RoundHyper
from dba_mod_tpu.ops.fused_update import make_fused_step_update
from dba_mod_tpu.ops.losses import cross_entropy, tree_dist_norm
from dba_mod_tpu.ops.sgd import sgd_init


class ClientMetrics(NamedTuple):
    loss_sum: jax.Array      # [E] Σ batch-mean losses (reference total_loss)
    correct: jax.Array       # [E] correct predictions
    count: jax.Array         # [E] samples seen (reference dataset_size)
    poison_count: jax.Array  # [E] poisoned samples seen


class SegmentResult(NamedTuple):
    end_vars: ModelVars      # post-scaling client state (next segment's start)
    benign_mom: Any          # benign-optimizer momentum after this segment
    fg_grads: Any            # grads accumulated THIS segment (params tree)
    metrics: ClientMetrics
    batch_loss: jax.Array    # [E*S] per-batch loss (vis_train_batch_loss,
                             # image_train.py:225-235); [0] when tracking off
    batch_dist: jax.Array    # [E*S] post-step ‖w-w_anchor‖ (batch_track_
                             # distance, image_train.py:236-245); [0] off


def _select_tree(pred, new, old):
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), new, old)


def make_client_step(model_def: ModelDef, data: DeviceData,
                     hyper: RoundHyper, fg_enabled: bool,
                     fused_pallas: bool = False,
                     fused_interpret: bool = False):
    """Returns client_step(start_vars, task_row, idx[E,S,B], mask[E,S,B],
    rng) -> SegmentResult, suitable for vmap over (start_vars, task_row, idx,
    mask, rng). `fused_pallas` routes the per-step state update through the
    fused multi-tensor kernel (ops/fused_update.py) when the engine runs
    unsharded on TPU; the math is identical either way."""
    fused_update = make_fused_step_update(
        hyper.momentum, hyper.weight_decay, fg_enabled,
        use_pallas=fused_pallas, interpret=fused_interpret)

    def client_step(start_vars: ModelVars, benign_mom: Any, task: ClientTask,
                    idx, mask, rng) -> SegmentResult:
        E, S, B = idx.shape
        params0, bn0 = start_vars.params, start_vars.batch_stats
        # The benign optimizer lives for the whole round (image_train.py:33 is
        # outside the global-epoch loop), so its momentum chains across
        # segments; the poison optimizer is fresh per poison epoch
        # (image_train.py:63 inside the loop) → zero buffers.
        is_poison_seg = task.poisoning_per_batch > 0
        zeros = sgd_init(params0)
        mom0 = _select_tree(is_poison_seg, zeros, benign_mom)
        fg0 = jax.tree_util.tree_map(jnp.zeros_like, params0)
        zeros_e = jnp.zeros((E,), jnp.float32)
        metrics0 = ClientMetrics(zeros_e, zeros_e, zeros_e, zeros_e)

        def step(carry, inp):
            params, bn, mom, fg, m = carry
            step_i, bidx, bmask = inp
            e = step_i // S
            x, y = data.fetch_train(task.slot, bidx)
            x, y, sel = data.stamp(x, y, task.adv_index,
                                   task.poisoning_per_batch)
            # derive from (epoch, step-within-epoch), NOT the flat index:
            # the flat index depends on the plan width S, and dynamic_steps
            # (experiment.py) shrinks S per round — dropout streams must not
            # change with the padding
            step_rng = jax.random.fold_in(
                jax.random.fold_in(rng, e), step_i - e * S)

            def loss_fn(p):
                logits, new_bn = model_def.apply(
                    ModelVars(p, bn), x, train=True, dropout_rng=step_rng)
                ce = cross_entropy(logits, y, bmask)
                if hyper.alpha_loss == 1.0:
                    # every reference config sets alpha_loss=1 — the
                    # anomaly-evading distance term is identically zero, so
                    # skip its fwd+bwd (a full extra pass over the params)
                    # at trace time
                    loss = ce
                else:
                    dist = tree_dist_norm(p, params0)
                    loss = task.alpha * ce + (1.0 - task.alpha) * dist
                return loss, (logits, new_bn)

            (loss, (logits, new_bn)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            lr = task.lr_row[e]
            # Padded steps (mask all-false: epochs beyond this client's count,
            # or steps beyond its batches) must be no-ops; the fused op does
            # torch-SGD + the validity selects (+ FoolsGold accumulation)
            # over the whole state in one logical op.
            valid = jnp.sum(bmask) > 0
            params, mom, fg, bn = fused_update(lr, valid, params, grads,
                                               mom, fg, new_bn, bn)

            preds = jnp.argmax(logits, axis=-1)
            bmaskf = bmask.astype(jnp.float32)
            vf = valid.astype(jnp.float32)
            m = ClientMetrics(
                loss_sum=m.loss_sum.at[e].add(vf * loss),
                correct=m.correct.at[e].add(
                    vf * jnp.sum((preds == y) * bmaskf)),
                count=m.count.at[e].add(vf * jnp.sum(bmaskf)),
                poison_count=m.poison_count.at[e].add(
                    vf * jnp.sum(sel * bmaskf)))
            if hyper.track_batches:
                # the reference measures the distance AFTER the step
                # (image_train.py:238: optimizer.step() precedes it)
                ys = (vf * loss, vf * tree_dist_norm(params, params0))
            else:
                ys = None  # nothing stacked, nothing transferred
            return (params, bn, mom, fg, m), ys

        xs = (jnp.arange(E * S), idx.reshape(E * S, B),
              mask.reshape(E * S, B))
        carry, ys = jax.lax.scan(step, (params0, bn0, mom0, fg0, metrics0),
                                 xs)
        (params, bn, mom, fg, metrics) = carry
        if hyper.track_batches:
            batch_loss, batch_dist = ys
        else:  # zero-width channels: shape-compatible, cost-free
            batch_loss = batch_dist = jnp.zeros((0,), jnp.float32)
        # a poison segment leaves the benign buffers untouched
        benign_mom_out = _select_tree(is_poison_seg, benign_mom, mom)

        # Model-replacement scaling over the FULL state (image_train.py:166-171
        # iterates state_dict — BN stats included) against the segment anchor.
        end_vars = ModelVars(
            params=jax.tree_util.tree_map(
                lambda a, w: a + task.scale * (w - a), params0, params),
            batch_stats=jax.tree_util.tree_map(
                lambda a, w: a + task.scale * (w - a), bn0, bn))
        return SegmentResult(end_vars, benign_mom_out, fg, metrics,
                             batch_loss, batch_dist)

    return client_step
