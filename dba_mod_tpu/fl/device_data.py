"""Device-resident datasets and the fetch/stamp closures used by the client
step.

Datasets live on device once (images as uint8 to halve HBM traffic; scaled to
[0,1] at gather time, matching the reference's ToTensor()-only pipeline,
image_helper.py:178-201). A batch fetch is a single XLA gather — the host
never touches sample data during training (contrast image_helper.py:289-296,
which moves every batch host→GPU).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu import config as cfg
from dba_mod_tpu.data.batching import stack_ragged
from dba_mod_tpu.data.datasets import ImageData, LoanData
from dba_mod_tpu.ops import triggers

# fetch(slot, idx[B]) -> (x[B, ...], y[B]); stamp(x, y, adv_index, k,
# poison_all) -> (x, y, poisoned_mask)
FetchFn = Callable[[jax.Array, jax.Array], Tuple[jax.Array, jax.Array]]
StampFn = Callable[..., Tuple[jax.Array, jax.Array, jax.Array]]


@dataclasses.dataclass
class DeviceData:
    fetch_train: FetchFn
    fetch_test: FetchFn
    stamp: StampFn
    num_train: int
    num_test: int
    compute_dtype: jnp.dtype


def make_image_device_data(data: ImageData, params: cfg.Params,
                           compute_dtype=jnp.float32) -> DeviceData:
    train_x = jnp.asarray(data.train_images)          # [N,H,W,C] uint8
    train_y = jnp.asarray(data.train_labels.astype(np.int32))
    test_x = jnp.asarray(data.test_images)
    test_y = jnp.asarray(data.test_labels.astype(np.int32))
    h, w = data.train_images.shape[1:3]
    bank = jnp.asarray(triggers.build_pixel_pattern_bank(params, h, w),
                       compute_dtype)
    swap = int(params["poison_label_swap"])

    def fetch_train(slot, idx):
        x = train_x[idx].astype(compute_dtype) / 255.0
        return x, train_y[idx]

    def fetch_test(slot, idx):
        x = test_x[idx].astype(compute_dtype) / 255.0
        return x, test_y[idx]

    def stamp(x, y, adv_index, k, poison_all=False):
        return triggers.poison_batch(x, y, bank, adv_index, swap, k,
                                     poison_all)

    return DeviceData(fetch_train, fetch_test, stamp,
                      num_train=len(data.train_labels),
                      num_test=len(data.test_labels),
                      compute_dtype=compute_dtype)


def make_loan_device_data(data: LoanData, params: cfg.Params,
                          compute_dtype=jnp.float32) -> DeviceData:
    """LOAN shards are ragged per state → stacked [S, max_n, F] with per-state
    row counts carried by the batch plans' masks. `slot` selects the state."""
    train_x = jnp.asarray(stack_ragged(data.train_x), compute_dtype)
    train_y = jnp.asarray(stack_ragged(data.train_y).astype(np.int32))
    test_x = jnp.asarray(stack_ragged(data.test_x), compute_dtype)
    test_y = jnp.asarray(stack_ragged(data.test_y).astype(np.int32))
    values, masks = triggers.build_feature_trigger_bank(
        params, data.feature_dict, train_x.shape[-1])
    values = jnp.asarray(values, compute_dtype)
    masks = jnp.asarray(masks, compute_dtype)
    swap = int(params["poison_label_swap"])

    def fetch_train(slot, idx):
        return train_x[slot, idx], train_y[slot, idx]

    def fetch_test(slot, idx):
        return test_x[slot, idx], test_y[slot, idx]

    def stamp(x, y, adv_index, k, poison_all=False):
        return triggers.poison_batch_features(x, y, values, masks, adv_index,
                                              swap, k, poison_all)

    return DeviceData(fetch_train, fetch_test, stamp,
                      num_train=sum(len(y) for y in data.train_y),
                      num_test=sum(len(y) for y in data.test_y),
                      compute_dtype=compute_dtype)
