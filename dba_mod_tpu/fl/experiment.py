"""The end-to-end FL experiment driver — the TPU-native main.py.

Replaces the reference's __main__ round loop (main.py:84-244) with a class:
data loading + partitioning once at startup, then per round: host-side agent
selection and plan building, one jitted round computation (train all clients →
aggregate), jitted local/global evaluation batteries, and recording. No import
cycles, no global mutable state (SURVEY §1 layer-crossing notes, §7.3).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu import config as cfg
from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.data import (build_batch_plan, build_eval_plan,
                              load_image_dataset, load_loan_dataset)
from dba_mod_tpu.data.partition import (equal_split_indices,
                                        poison_test_indices,
                                        sample_dirichlet_indices)
from dba_mod_tpu.fl import evaluation
from dba_mod_tpu.fl.device_data import (make_image_device_data,
                                        make_loan_device_data)
from dba_mod_tpu.fl.rounds import EvalPlans, RoundEngine
from dba_mod_tpu.fl.selection import select_agents
from dba_mod_tpu.fl.state import build_client_tasks
from dba_mod_tpu.models import ModelVars, build_model, compute_dtype_of
from dba_mod_tpu.ops.aggregation import foolsgold_init
from dba_mod_tpu.utils import run_guard, telemetry
from dba_mod_tpu.utils.recorder import Recorder

logger = logging.getLogger("dba_mod_tpu")


def _pad_tasks(tasks, pad: int, aggregation: str):
    """Append `pad` inert clients (fully-masked plans → zero deltas) so the
    stacked axis tiles the mesh. Sound only for FedAvg, whose divisor is the
    static no_models — a zero delta shifts RFA's geometric median and
    FoolsGold's similarity geometry. Enforced here, not by caller
    convention."""
    if aggregation != cfg.AGGR_MEAN:
        raise ValueError(
            f"inert-client padding is only sound for FedAvg (aggregation="
            f"{cfg.AGGR_MEAN!r}); got {aggregation!r} — pick a no_models "
            "that tiles the mesh instead")
    from dba_mod_tpu.fl.state import ClientTask
    return ClientTask(
        slot=np.pad(tasks.slot, (0, pad)),
        participant_id=np.pad(tasks.participant_id, (0, pad)),
        adv_index=np.pad(tasks.adv_index, (0, pad), constant_values=-1),
        adv_slot=np.pad(tasks.adv_slot, (0, pad), constant_values=-1),
        poisoning_per_batch=np.pad(tasks.poisoning_per_batch, (0, pad)),
        alpha=np.pad(tasks.alpha, (0, pad), constant_values=1.0),
        scale=np.pad(tasks.scale, (0, pad), constant_values=1.0),
        lr_row=np.pad(tasks.lr_row, ((0, pad), (0, 0))),
        num_epochs=np.pad(tasks.num_epochs, (0, pad)))


@dataclasses.dataclass
class RoundInFlight:
    """Device handles + host context of a dispatched round, awaiting its one
    blocking transfer. Produced by `dispatch_round`, consumed by
    `finalize_round`; holding two of these pipelines round N+1's compute
    behind round N's host fetch (the tunnel round-trip is ~100 ms — hiding it
    is worth ~10% of a bench round)."""
    epoch: int
    t0: float                    # perf_counter at dispatch start
    seg_epochs: List[int]
    agent_names: List[Any]
    adv_names: List[Any]
    tasks_list: List[Any]
    mask_list: List[Any]
    payload: Any                 # device trees handed to jax.device_get
    # host planning + enqueue seconds (perf_counter), set by dispatch_round;
    # finalize_round records it next to its own fetch time so
    # round_result.csv splits round_time into honest phases
    dispatch_time: float = 0.0
    # fault-tolerance outcome of the dispatch (fl/faults.py + the screening
    # pass in fl/rounds.py): retries consumed re-running the round after a
    # non-finite aggregate, and whether the host forced a degraded round
    # (restored the pre-round state) because retries ran out
    n_retries: int = 0
    forced_degraded: bool = False
    # Post-round state handles + host RNG snapshots, captured at dispatch
    # time: under pipelining, by the time round N finalizes the experiment's
    # live attributes already belong to round N+1, so checkpoints must save
    # these captured values, not the live ones.
    vars_after: Any = None       # global ModelVars after this round
    fg_after: Any = None         # FoolsGoldState after this round
    rng_after: Optional[Dict[str, Any]] = None
    # the deltas the server RECEIVED this round — the stale fault lane's
    # replay source for the NEXT round, captured per-round for the resume
    # sidecar (under pipelining the live _prev_deltas may already belong
    # to round N+1 when round N checkpoints). None unless the stale lane
    # is on.
    deltas_after: Any = None
    # overlap_eval bookkeeping: the round ran the split core + overlapped
    # eval batteries, and eval_dispatch_t is the perf_counter when the last
    # battery was enqueued — finalize_round turns (fetch wall time vs time
    # since enqueue) into the hidden-eval clock
    overlapped: bool = False
    eval_dispatch_t: float = 0.0


class Experiment:
    def __init__(self, params: cfg.Params, save_results: bool = True):
        from dba_mod_tpu.parallel.distributed import initialize_distributed
        initialize_distributed()  # env-triggered; no-op single-host
        self.params = params
        # crash/preemption guard (utils/run_guard.py): stop flag checked at
        # round boundaries + watchdog around host sync points. Construction
        # is side-effect free; run() installs/uninstalls the handlers.
        # Strict no-op (no threads, no handlers) with the default knobs.
        self.guard = run_guard.RunGuard.from_params(params)
        self.interrupted = False
        self._ckpt_mgr: Optional[ckpt.CheckpointManager] = None
        # resumed_model: auto — discover the newest VERIFIED checkpoint
        # across run_dir's run folders BEFORE creating a new folder: the
        # resumed run re-enters the killed run's folder and continues its
        # recorder stream, instead of scattering each retry into a fresh
        # timestamped dir
        self._auto_resume_path: Optional[Path] = None
        resumed_folder: Optional[Path] = None
        # one results writer per multi-process run: every process shares
        # the run folder path (orbax checkpoint saves are collective — all
        # processes must call with the same path), but only process 0
        # writes run metadata, logs, and the recorder streams
        is_writer = jax.process_index() == 0
        if (save_results and jax.process_count() > 1
                and not params.run_name):
            raise ValueError(
                "multi-process runs that save results require run_name: "
                "every process — and every elastic relaunch of the "
                "survivors — must agree on ONE run folder, which "
                "per-process timestamped folders cannot guarantee")
        if params.resume_mode == "auto":
            hit = ckpt.find_auto_resume(Path(str(params["run_dir"])),
                                        params.type, params.run_name)
            if hit is not None:
                resumed_folder, self._auto_resume_path = hit
        if not save_results:
            self.folder: Optional[Path] = None
        elif resumed_folder is not None:
            self.folder = resumed_folder
            if is_writer:  # exclusive-owner mutations: one process only
                ckpt.sweep_stale(self.folder)  # debris: *.tmp, orbax tmp
                params.write_yaml(self.folder)
        elif is_writer:
            self.folder = params.make_run_folder()
        else:
            self.folder = Path(str(params["run_dir"])) / params.run_name
            self.folder.mkdir(parents=True, exist_ok=True)
        # idempotent logger setup (telemetry.py): one stream handler, one
        # run-folder file handler that FOLLOWS the active experiment —
        # replaces the old basicConfig + per-instance FileHandler stacking
        # (two experiments in one process each logged every line twice)
        telemetry.setup_logging(self.folder if is_writer else None)
        if self.folder and is_writer:
            from dba_mod_tpu.utils.html import dict_html
            (self.folder / "params.html").write_text(
                dict_html(params.raw, params.current_time))
        self.recorder = Recorder(self.folder if is_writer else None,
                                 tensorboard=bool(params.get("tensorboard")))
        # telemetry (utils/telemetry.py): spans + metrics + XLA compile and
        # memory instrumentation. Files land in telemetry_dir (default: the
        # run folder; in-memory when neither exists); one writer per
        # multi-process run. The instance is process-wide current, so spans
        # in shared code paths (checkpoint.py, rounds.py) resolve to it.
        tdir = str(params.get("telemetry_dir", "") or "")
        tfolder: Optional[Path] = Path(tdir) if tdir else self.folder
        if tfolder is not None and jax.process_index() != 0:
            tfolder = None
        self.telemetry = telemetry.configure(
            enabled=bool(params.get("telemetry", False)), folder=tfolder,
            tb_sink=(self.recorder._scalar
                     if self.recorder._tb is not None else None))
        # defense forensics (utils/forensics.py): per-client aggregation
        # introspection streamed from the jitted round's ForensicStats
        # payload slot. Opt-in and strictly inert when off: no writer, no
        # files, no extra device work anywhere in the round path.
        self.forensics_writer = None
        if bool(params.get("forensics", False)):
            from dba_mod_tpu.utils.forensics import ForensicsWriter
            self.forensics_writer = ForensicsWriter(
                self.folder if is_writer else None,
                tb_sink=(self.recorder._scalar
                         if self.recorder._tb is not None else None))
        self.model_def = build_model(params)
        seed = int(params.get("random_seed", 1))
        self.select_rng = random.Random(seed)
        self.plan_rng = np.random.RandomState(seed)
        self.rng_key = jax.random.key(seed)

        self._load_data_and_partition(seed)

        # Fixed plan shape across rounds → the jitted round compiles once.
        max_client = max((len(v) for v in self.client_indices.values()),
                         default=1)
        b = int(params["batch_size"])
        self.steps_per_epoch = max(1, int(np.ceil(max_client / b)))
        self.is_poison_run = bool(params["is_poison"])
        self.epochs_max = (max(int(params["internal_epochs"]),
                               int(params["internal_poison_epochs"]))
                           if self.is_poison_run
                           else int(params["internal_epochs"]))

        # Global model: fresh init or resume (image_helper.py:56-67)
        init_rng = jax.random.key(seed)
        self.global_vars = self.model_def.init_vars(init_rng)
        self.start_epoch = 1
        self._resume_aux: Optional[Dict[str, Any]] = None
        resume_path: Optional[Path] = None
        if params.resume_mode == "auto":
            resume_path = self._auto_resume_path
            if resume_path is None:
                logger.warning(
                    "resume auto: no verified checkpoint under %s — "
                    "starting a fresh run", params["run_dir"])
        elif params.resume_mode == "named":
            path = (Path(str(params.get("checkpoint_dir", "saved_models")))
                    / str(params["resumed_model_name"]))
            # integrity gate: verified → load; manifest-less (pretrain/
            # legacy) → load unverified, the reference behavior; corrupt →
            # fall back to the newest verified SAME-NAME sibling. No sweep
            # and no quarantine here: checkpoint_dir is a shared library
            # (another process may be mid-commit into it), unlike the
            # exclusively owned run folder swept in __init__
            resume_path = ckpt.resolve_verified(path)
        if resume_path is not None:
            self.global_vars, saved_epoch, saved_lr = ckpt.load_checkpoint(
                resume_path, self.global_vars)
            if params.resume_mode == "auto":
                # the checkpoint records the completed round's BASE epoch;
                # with aggr_epoch_interval > 1 that round also trained the
                # interval-1 following epochs, and the killed run's round
                # grid steps by the interval — continuing the exact
                # trajectory means the next base, not base+1 (which would
                # re-train epoch base+1 and shift the whole grid)
                self.start_epoch = (saved_epoch
                                    + int(params["aggr_epoch_interval"]))
            else:
                # named resume keeps the reference's +1 semantics
                self.start_epoch = saved_epoch + 1
            self.params.raw["lr"] = saved_lr
            # full-state sidecar, when the checkpoint has one (save_model
            # runs write it; pretrain checkpoints don't — model-only resume
            # is the reference behavior, image_helper.py:56-67). A corrupt
            # sidecar also degrades to model-only (checkpoint.py).
            self._resume_aux = ckpt.load_aux_state(resume_path)
            if (self._resume_aux is not None
                    and int(self._resume_aux["epoch"]) != saved_epoch):
                # a crash between the (synchronous) sidecar write and the
                # async orbax commit can leave the sidecar one round ahead
                # of the model — restoring it would replay round N with
                # round N+1's RNG/memory. Fall back to model-only resume.
                logger.warning(
                    "resume sidecar is for epoch %d but the model "
                    "checkpoint is epoch %d — discarding the sidecar "
                    "(model-only resume; FoolsGold memory and RNG streams "
                    "restart)", int(self._resume_aux["epoch"]), saved_epoch)
                self._resume_aux = None
            logger.info("resumed %s: lr=%s start_epoch=%d aux=%s",
                        resume_path, saved_lr, self.start_epoch,
                        self._resume_aux is not None)
            if params.resume_mode == "auto" and self.folder is not None:
                # continue the killed run's recorder stream: reload rows
                # through the resume round's FINAL global epoch and drop
                # the rest — a kill can land after round N recorded but
                # before its checkpoint verified, and the replayed round N
                # must not appear twice in metrics.jsonl/round_result.csv
                cut = saved_epoch + int(params["aggr_epoch_interval"]) - 1
                kept = self.recorder.load_from_folder(cut)
                logger.info(
                    "resume auto: continuing recorder stream in %s "
                    "(%d metrics rows kept through epoch %d)",
                    self.folder, kept, cut)
                if self.forensics_writer is not None:
                    # same truncate-and-continue contract for the forensic
                    # streams — a replayed round must not appear twice
                    self.forensics_writer.load_from_folder(cut)

        # clients mesh: 0 → single-device; -1 → all visible devices; n → n
        nd = int(params.get("num_devices", 0))
        self.mesh = None
        if nd == -1 or nd > 1:
            from dba_mod_tpu.parallel.mesh import make_mesh
            self.mesh = make_mesh(0 if nd == -1 else nd)

        # elastic peer-health layer (parallel/distributed.py::PeerHealth):
        # per-host heartbeats, round-boundary staleness checks, and the
        # peer-lost watchdog verdict (exit 77). Active only in
        # multi-process runs with heartbeat_interval_s > 0 — single-host
        # the knobs are strict no-ops: no thread, no files, no per-round
        # work (run() never touches a None peers).
        self.peers = None
        self.heartbeat_barrier_s = float(
            params.get("heartbeat_barrier_s", 0.0))
        hb = float(params.get("heartbeat_interval_s", 0.0))
        if hb > 0 and jax.process_count() > 1:
            from dba_mod_tpu.parallel.distributed import PeerHealth
            # default under THIS run's folder: concurrent runs sharing a
            # run_dir must not read each other's heartbeats (a same-gen
            # twin world would mask a real loss); folder-less runs
            # (save_results=False) fall back to run_dir/_peers
            hb_dir = (str(params.get("heartbeat_dir", "") or "")
                      or str((self.folder if self.folder is not None
                              else Path(str(params["run_dir"])))
                             / "_peers"))
            self.peers = PeerHealth(
                hb_dir, jax.process_index(), jax.process_count(),
                interval_s=hb,
                timeout_s=float(params.get("heartbeat_timeout_s", 0.0)))
        self.telemetry.gauge("mesh/world_size").set(jax.process_count())

        self.interval = int(params["aggr_epoch_interval"])
        self.sequential_debug = bool(params.get("sequential_debug", False))
        if self.sequential_debug and self.mesh is not None:
            # width-1 client slices cannot tile a sharded clients axis
            logger.warning("sequential_debug forces single-device execution; "
                           "ignoring num_devices")
            self.mesh = None
        self.engine = RoundEngine(params, self.model_def, self.device_data,
                                  self.eval_plans, mesh=self.mesh,
                                  num_segments=self.interval)
        # fault-tolerance layer (fl/faults.py; README "Fault model"): the
        # robust round program screens payloads into a survivor mask and the
        # host retries/degrades rounds below. Sequential-debug runs the
        # split train/aggregate path which bypasses the fault layer — refuse
        # the combination rather than silently not injecting.
        if self.engine.robust and self.sequential_debug:
            raise ValueError("fault_injection/screen_updates are not "
                             "supported with sequential_debug")
        if (self.engine.fault_cfg.stale_enabled
                and jax.process_count() > 1):
            raise ValueError("fault_stale_prob > 0 is single-controller "
                             "only (the replayed-delta carry cannot be "
                             "placed across processes)")
        self.max_round_retries = int(params.get("max_round_retries", 2))
        self.retry_backoff_s = float(params.get("retry_backoff_s", 0.0))
        # post-merge model-health sentinel (README "Self-healing
        # federation"): None when off — no program traced, no host sync,
        # strict no-op. Shared with the async driver so both engines gate
        # commits through the same EMA band + last-good ring.
        self._sentinel = None
        if bool(params.get("model_health_check", False)):
            from dba_mod_tpu.fl.rounds import HealthSentinel
            self._sentinel = HealthSentinel(
                band=float(params.get("health_norm_band", 0.0)),
                ema_alpha=float(params.get("health_ema_alpha", 0.1)),
                warmup=int(params.get("health_warmup_merges", 3)),
                ring_size=int(params.get("rollback_ring", 0)))
        # overlap_eval (README "Round pipelining"): dispatch round N's eval
        # batteries + host record/checkpoint concurrently with round N+1's
        # train/aggregate. The scheduler lives in _dispatch_overlap; here we
        # only pick the eval placement: with >1 local device and no clients
        # mesh the batteries run on a SECOND device (true compute overlap —
        # the eval executables get their own placement-cached data
        # constants), otherwise they share device 0 and the overlap hides
        # the host-side fetch/record/checkpoint path only. sequential_debug
        # takes precedence (see _dispatch); with telemetry on the split
        # program still runs but the loop stays SEQUENTIAL (_run_rounds) so
        # span attribution is honest. Off is a strict no-op — no core
        # program is ever compiled.
        self._overlap = bool(params.get("overlap_eval", False))
        self._eval_device = evaluation.pick_eval_device(self.mesh,
                                                        self._overlap)
        self._overlap_rounds = 0
        self._overlap_hidden_s = 0.0  # cumulative eval+fetch seconds hidden
        self._overlap_wait_s = 0.0    # cumulative finalize blocking seconds
        self._fault_key = jax.random.key(self.engine.fault_cfg.seed)
        # last round's submitted deltas (the stale lane's replay source).
        # Checkpointed in the aux sidecar when the lane is on (save_model
        # captures each round's deltas_after), so a resumed run's first
        # stale replay is faithful; only sidecar-less resumes (pretrain /
        # model-only checkpoints) fall back to the zero delta here.
        self._prev_deltas = None
        grad_len = int(np.prod(
            self.model_def.similarity_param(self.global_vars.params).shape))
        self.fg_state = foolsgold_init(self.num_participants, grad_len)
        if self.mesh is not None:
            # replicate host-initialized state onto the mesh explicitly —
            # required on multi-host (device_put cannot span processes), a
            # no-op-cost placement single-host
            from dba_mod_tpu.parallel.mesh import replicate_for_mesh
            self.global_vars = replicate_for_mesh(self.mesh,
                                                  self.global_vars)
            self.fg_state = replicate_for_mesh(self.mesh, self.fg_state)
        self.local_eval = bool(params.get("local_eval", True))
        self.last_is_updated = True  # set per-round in finalize_round
        self.last_global_loss = float("inf")  # feeds the best-val checkpoint
        self.best_loss = float("inf")         # helper.py:433, main.py:120
        # stale_poison_probe (flag-gated deviation): the LOAN adaptive
        # poison-LR probe reads the CURRENT global model's backdoor accuracy
        # (loan_train.py:67-75), which forces a host sync that serializes
        # round pipelining on every poison round. With this flag the probe
        # uses the most recently FINALIZED round's backdoor accuracy
        # instead — one round stale in sequential runs, two rounds stale
        # under pipeline_rounds (dispatch of round N precedes finalize of
        # N-1) — for a quantity the reference itself recomputes mid-loop.
        self.stale_poison_probe = bool(params.get("stale_poison_probe",
                                                  False))
        self.last_backdoor_acc: Optional[float] = None
        # Per-round step-count bucketing: the static plan pads every client to
        # the GLOBAL max client size; a round of 10 sampled clients usually
        # needs far fewer steps, and masked padding steps cost full compute.
        # dynamic_steps sizes the plan to the round's own max, quantized to
        # multiples of _STEP_BUCKET so the jitted round compiles a handful of
        # shapes instead of one-per-round. Identical numerics: dropped steps
        # were fully-masked no-ops (tests/test_fl_integration.py).
        self.dynamic_steps = bool(params.get("dynamic_steps", False))
        self._warmed_buckets: set = set()
        self._apply_resume_aux()

    def _apply_resume_aux(self):
        """Restore the full-state sidecar loaded during resume: FoolsGold
        memory, best-val loss, and every RNG stream — so a killed-and-resumed
        run continues the uninterrupted trajectory exactly (the reference
        cannot: helper.py:545-549 is RAM-only)."""
        aux = self._resume_aux
        if not aux:
            return
        self.select_rng.setstate(aux["select_rng"])
        self.plan_rng.set_state(aux["plan_rng"])
        self.rng_key = jax.random.wrap_key_data(jnp.asarray(aux["rng_key"]))
        self.best_loss = float(aux["best_loss"])
        self.last_backdoor_acc = aux.get("last_backdoor_acc")
        mem = jnp.asarray(aux["fg_memory"])
        if mem.shape != self.fg_state.memory.shape:
            raise ValueError(
                f"resume sidecar FoolsGold memory shape {mem.shape} does not "
                f"match this run's {self.fg_state.memory.shape} — the "
                "checkpoint belongs to a different participant set or model")
        self.fg_state = self.fg_state._replace(memory=mem)
        if self.mesh is not None:
            from dba_mod_tpu.parallel.mesh import replicate_for_mesh
            self.fg_state = replicate_for_mesh(self.mesh, self.fg_state)
        pd = aux.get("prev_deltas")
        if pd is not None and self.engine.fault_cfg.stale_enabled:
            # faithful first post-resume stale replay (the lane is
            # single-process-only, so plain placement suffices)
            tree = jax.tree_util.tree_map(jnp.asarray, pd)
            if self.mesh is not None:
                from dba_mod_tpu.parallel.mesh import client_sharding
                tree = jax.device_put(tree, client_sharding(self.mesh))
            self._prev_deltas = tree

    # ------------------------------------------------------------------ data
    def _load_data_and_partition(self, seed: int):
        params = self.params
        cdtype = compute_dtype_of(params)
        # eval batch size only shapes the eval scans; the recorded sums are
        # batch-size invariant (test.py:21-22's reduction='sum')
        eb = int(params.get("eval_batch_size", 0) or
                 params["test_batch_size"])
        if params.is_image:
            data = self.image_data = load_image_dataset(params)
            self.device_data = make_image_device_data(data, params,
                                                      compute_dtype=cdtype)
            if params["sampling_dirichlet"]:
                indices = sample_dirichlet_indices(
                    data.train_labels,
                    int(params["number_of_total_participants"]),
                    float(params["dirichlet_alpha"]),
                    py_rng=random.Random(seed),
                    np_rng=np.random.RandomState(seed))
            else:
                indices = equal_split_indices(
                    len(data.train_labels),
                    int(params["number_of_total_participants"]),
                    py_rng=random.Random(seed))
            self.client_indices = indices
            self.client_slots = {name: 0 for name in indices}
            if params["is_random_namelist"]:
                self.participants = list(
                    range(int(params["number_of_total_participants"])))
            else:
                self.participants = list(params["participants_namelist"])
            self.benign_names = sorted(
                set(self.participants) - set(params.adversary_list))
            self.num_participants = int(
                params["number_of_total_participants"])

            clean = build_eval_plan(np.arange(len(data.test_labels)), eb)
            poison = build_eval_plan(
                poison_test_indices(data.test_labels,
                                    int(params["poison_label_swap"])), eb)
            self.eval_plans = EvalPlans(
                clean_idx=jnp.asarray(clean.idx),
                clean_slots=jnp.zeros_like(jnp.asarray(clean.idx)),
                clean_mask=jnp.asarray(clean.mask),
                poison_idx=jnp.asarray(poison.idx),
                poison_slots=jnp.zeros_like(jnp.asarray(poison.idx)),
                poison_mask=jnp.asarray(poison.mask))
        else:
            data = self.loan_data = load_loan_dataset(params)
            self.device_data = make_loan_device_data(data, params,
                                                     compute_dtype=cdtype)
            state_of = {n: i for i, n in enumerate(data.state_names)}
            # benign list: first `number_of_total_participants` shards that
            # are not adversaries (loan_helper.py:134-141)
            benign = []
            for j, name in enumerate(data.state_names):
                if j >= int(params["number_of_total_participants"]):
                    break
                if name not in params.adversary_list:
                    benign.append(name)
            self.benign_names = benign
            if params["is_random_namelist"]:
                self.participants = benign + params.adversary_list
            else:
                self.participants = list(params["participants_namelist"])
            self.client_indices = {
                name: list(range(len(data.train_y[state_of[name]])))
                for name in data.state_names}
            self.client_slots = state_of
            self.num_participants = len(data.state_names)

            # eval plans concatenate every state shard (test.py:13-24)
            b = eb
            pairs = [(s, i) for s, ys in enumerate(data.test_y)
                     for i in range(len(ys))]
            slots = np.array([p[0] for p in pairs], np.int64)
            rows = np.array([p[1] for p in pairs], np.int64)
            plan = build_eval_plan(np.arange(len(pairs)), b)
            # map flat eval positions back to (slot, row)
            idx = rows[plan.idx.reshape(-1)].reshape(plan.idx.shape)
            slt = slots[plan.idx.reshape(-1)].reshape(plan.idx.shape)
            self.eval_plans = EvalPlans(
                clean_idx=jnp.asarray(idx.astype(np.int32)),
                clean_slots=jnp.asarray(slt.astype(np.int32)),
                clean_mask=jnp.asarray(plan.mask),
                poison_idx=jnp.asarray(idx.astype(np.int32)),
                poison_slots=jnp.asarray(slt.astype(np.int32)),
                poison_mask=jnp.asarray(plan.mask))

    # ----------------------------------------------------------------- round
    _STEP_BUCKET = 2       # quantum of the per-round step-count buckets
    _STEP_BUCKET_MIN = 8   # floor: tiny rounds share one shape

    def _bucket_steps(self, s: int) -> int:
        b = self._STEP_BUCKET
        s = max(((s + b - 1) // b) * b, self._STEP_BUCKET_MIN)
        return min(s, max(self.steps_per_epoch, 1))

    def warm_step_buckets(self) -> List[int]:
        """Pre-compile the round program for every step bucket (all-masked
        zero plans → the compile is shape-driven only). Keeps dynamic_steps
        rounds from hitting a fresh XLA compile mid-run."""
        if not self.dynamic_steps:
            return []
        failures: list = []
        buckets = sorted({self._bucket_steps(s) for s in
                          range(1, self.steps_per_epoch + 1)})
        names = self.participants[:int(self.params["no_models"])]
        slots = np.array([self.client_slots[n] for n in names], np.int64)
        tasks = build_client_tasks(self.params, names, 1, slots,
                                   self.epochs_max, None)
        C, E, B = len(names), self.epochs_max, int(self.params["batch_size"])
        if self.mesh is not None:
            # match dispatch_round's inert-client padding, or the warm
            # shapes won't be the shapes real rounds compile
            from dba_mod_tpu.parallel.mesh import pad_clients
            c_pad = pad_clients(C, self.mesh)
            if c_pad != C:
                tasks = _pad_tasks(tasks, c_pad - C, self.params.aggregation)
                C = c_pad
        I = self.interval  # real rounds stack one segment per interval epoch
        tasks_stacked = jax.tree_util.tree_map(
            lambda l: jnp.asarray(np.stack([l] * I)), tasks)
        lane = jnp.arange(C, dtype=jnp.int32)
        rng_t, rng_a = jax.random.split(jax.random.key(0))
        robust_args = self._robust_round_args(1, C)
        for s in buckets:
            idx = jnp.zeros((I, C, E, s, B), jnp.int32)
            mask = jnp.zeros((I, C, E, s, B), bool)
            ns = jnp.zeros((C,), jnp.float32)
            tasks_seq = tasks_stacked
            if self.mesh is not None:
                # identical placement to dispatch_round — the warm shapes
                # AND shardings must be the ones real rounds compile
                from dba_mod_tpu.parallel.mesh import shard_round_inputs
                tasks_seq, idx, mask, ns = shard_round_inputs(
                    self.mesh, tasks_seq, idx, mask, ns)
            for attempt in (1, 2):
                try:
                    # warm the program real rounds run: the fused round —
                    # or, under telemetry's split-phase dispatch, the train
                    # program (the only split program whose shape varies
                    # with the step bucket; aggregate/eval are bucket-free),
                    # or the overlap scheduler's round core. The donated
                    # twin is warmed on COPIES: donation consumes the input
                    # buffers, and these are the live model/defense state.
                    if self._overlap and not self.sequential_debug:
                        self.engine.core_fn(self.global_vars, self.fg_state,
                                            tasks_seq, idx, mask, lane, ns,
                                            rng_t, rng_a, *robust_args)
                    elif self._telemetry_split and not self.sequential_debug:
                        self.engine.train_fn(self.global_vars, tasks_seq,
                                             idx, mask, lane, rng_t)
                    elif self._use_donated_round:
                        gv = jax.tree_util.tree_map(lambda x: x.copy(),
                                                    self.global_vars)
                        fg = jax.tree_util.tree_map(lambda x: x.copy(),
                                                    self.fg_state)
                        self.engine.round_fn_donated(
                            gv, fg, tasks_seq, idx, mask, lane, ns,
                            rng_t, rng_a)
                    else:
                        self.engine.round_fn(self.global_vars, self.fg_state,
                                             tasks_seq, idx, mask, lane, ns,
                                             rng_t, rng_a, *robust_args)
                    self._warmed_buckets.add(s)
                    break
                except Exception as exc:  # noqa: BLE001 — the TPU
                    # remote-compile RPC path throws transient 500s; retry
                    # once, then record the failure with its cause
                    if attempt == 2:
                        failures.append((s, exc))
                        logger.warning(
                            "warm_step_buckets: compile for S=%d failed "
                            "twice (%r); will compile on first use", s, exc)
        if len(buckets) > 1 and len(failures) == len(buckets):
            # SEVERAL independent shapes all failing is not a transient RPC
            # hiccup — the warm shapes (or the round program itself) are
            # broken, and hiding that would resurface as a crash mid-run,
            # far from here. (A single-bucket failure stays a warning: two
            # transient remote-compile 500s in a row must not abort a run
            # that compile-on-first-use would recover.)
            raise RuntimeError(
                "warm_step_buckets: every step bucket failed to compile; "
                f"first error: {failures[0][1]!r}") from failures[0][1]
        return buckets

    def build_static_round_inputs(self, epoch: int):
        """Device-ready train_fn inputs at the STATIC plan shape — for
        diagnostics that call the engine directly (bench.py's phase probe).
        Consumes the experiment's selection/plan RNG streams. Returns
        (tasks_seq, idx_seq, mask_seq, num_samples, lane)."""
        params = self.params
        agent_names, _ = select_agents(params, epoch, self.participants,
                                       self.benign_names, self.select_rng)
        slots = np.array([self.client_slots[n] for n in agent_names],
                         np.int64)
        tasks = build_client_tasks(params, agent_names, epoch, slots,
                                   self.epochs_max, None)
        plan = build_batch_plan(
            [self.client_indices[n] for n in agent_names],
            [int(e) for e in tasks.num_epochs], int(params["batch_size"]),
            self.plan_rng, min_steps=self.steps_per_epoch,
            min_epochs=self.epochs_max)
        tasks_seq = jax.tree_util.tree_map(lambda l: jnp.asarray(l[None]),
                                           tasks)
        return (tasks_seq, jnp.asarray(plan.idx[None]),
                jnp.asarray(plan.mask[None]),
                jnp.asarray(plan.num_samples.astype(np.float32)),
                jnp.arange(len(agent_names), dtype=jnp.int32))

    def run_round(self, epoch: int) -> Dict[str, Any]:
        return self.finalize_round(self.dispatch_round(epoch))

    @property
    def _telemetry_split(self) -> bool:
        """Split-phase dispatch only while THIS experiment's telemetry is
        the process-wide current instance: the shared eval/checkpoint
        wrappers resolve ``telemetry.current()`` at call time, so after
        another Experiment takes over, the split path would pay its
        per-phase device syncs with no spans recorded — fall back to the
        fused program (whose dispatch/finalize spans, recorded on this
        instance, stay honest: host planning + enqueue / blocking fetch)."""
        return (self.telemetry.enabled and not self.engine.robust
                and telemetry.current() is self.telemetry)

    @property
    def _use_donated_round(self) -> bool:
        """Route through the fused round's donated twin (non-CPU, non-robust
        — see the gate in rounds.py) only when nothing re-reads the consumed
        buffers after dispatch: the health sentinel's check/rollback path
        does (it compares against the pre-round model), and the overlap
        scheduler never runs the fused program at all."""
        return (self.engine.round_fn_donated is not None
                and self._sentinel is None and not self._overlap)

    def dispatch_round(self, epoch: int) -> RoundInFlight:
        """Telemetry/timing shell around :meth:`_dispatch`: the whole host
        planning + enqueue runs under the ``round/dispatch`` span, and its
        perf_counter duration lands in ``round_result.csv`` as
        ``dispatch_time`` (the old single `round_time` measured with
        ``time.time()`` attributed pipelined fetches to whatever wall
        segment they landed in)."""
        t0 = time.perf_counter()
        self.telemetry.set_epoch(epoch)
        with self.telemetry.span("round/dispatch"):
            fl = self._dispatch(epoch, t0)
        fl.dispatch_time = time.perf_counter() - t0
        return fl

    def _dispatch(self, epoch: int, t0: float) -> RoundInFlight:
        """Host-side planning + every device dispatch for one round; no host
        sync — EXCEPT the LOAN adaptive-poison probe below, which must read
        the current global model's backdoor accuracy (loan_train.py:67-75)
        and therefore blocks on all previously dispatched work (pipelining
        degrades to sequential for those rounds, by necessity), and the
        explicit per-phase sync points of telemetry's split-phase path. The
        returned handle feeds `finalize_round`, which performs the round's
        single blocking transfer and the CSV/JSONL recording."""
        params = self.params
        agent_names, adv_names = select_agents(
            params, epoch, self.participants, self.benign_names,
            self.select_rng)
        logger.info("Server Epoch:%d choose agents: %s", epoch, agent_names)

        backdoor_acc = None
        if (params.type == cfg.TYPE_LOAN and self.is_poison_run
                and any(params.adversary_slot_of(n) >= 0 and
                        epoch in params.poison_epochs_for(
                            params.adversary_slot_of(n))
                        for n in agent_names)):
            if self.stale_poison_probe and self.last_backdoor_acc is not None:
                backdoor_acc = self.last_backdoor_acc  # round N-1's battery
            else:
                with self.guard.watch("round/poison_probe"):
                    backdoor_acc = float(self.engine.backdoor_acc_fn(
                        self.global_vars))

        slots = np.array([self.client_slots[n] for n in agent_names],
                         np.int64)
        # one segment per global epoch in the aggregation interval
        # (image_train.py:50: the local model trains continuously across the
        # interval; the server applies the summed update once)
        seg_epochs = list(range(epoch, epoch + self.interval))
        if self.dynamic_steps:
            b = int(params["batch_size"])
            round_max = max((len(self.client_indices[n])
                             for n in agent_names), default=1)
            min_steps = self._bucket_steps(
                max(1, int(np.ceil(round_max / b))))
            if self._warmed_buckets and min_steps not in self._warmed_buckets:
                # warm shapes drifting from real round shapes is exactly the
                # failure warm_step_buckets exists to prevent — be loud
                logger.warning(
                    "dispatch_round: step bucket S=%d was not pre-warmed "
                    "(warmed: %s); this round pays a fresh XLA compile",
                    min_steps, sorted(self._warmed_buckets))
        else:
            min_steps = self.steps_per_epoch
        tasks_list, idx_list, mask_list = [], [], []
        num_samples_np = None
        for ep in seg_epochs:
            tasks_s = build_client_tasks(params, agent_names, ep, slots,
                                         self.epochs_max, backdoor_acc)
            plan = build_batch_plan(
                [self.client_indices[n] for n in agent_names],
                [int(e) for e in tasks_s.num_epochs],
                int(params["batch_size"]), self.plan_rng,
                min_steps=min_steps, min_epochs=self.epochs_max)
            if num_samples_np is None:
                num_samples_np = plan.num_samples.astype(np.float32)
            tasks_list.append(tasks_s)
            idx_list.append(plan.idx)
            mask_list.append(plan.mask)

        if self.mesh is not None:
            from dba_mod_tpu.parallel.mesh import pad_clients
            c_pad = pad_clients(len(agent_names), self.mesh)
            if c_pad != len(agent_names):
                if params.aggregation != cfg.AGGR_MEAN:
                    raise ValueError(
                        f"no_models={len(agent_names)} does not tile the "
                        f"{self.mesh.devices.size}-device mesh; pick a "
                        "multiple (inert-client padding is only sound for "
                        "FedAvg, whose divisor is the static no_models)")
                pad = c_pad - len(agent_names)
                tasks_list = [_pad_tasks(t, pad, params.aggregation)
                              for t in tasks_list]
                idx_list = [np.pad(i, ((0, pad),) + ((0, 0),) * 3)
                            for i in idx_list]
                mask_list = [np.pad(m, ((0, pad),) + ((0, 0),) * 3)
                             for m in mask_list]
                num_samples_np = np.pad(num_samples_np, (0, pad))

        tasks_seq = jax.tree_util.tree_map(
            lambda *ls: jnp.asarray(np.stack(ls)), *tasks_list)
        idx_seq = jnp.asarray(np.stack(idx_list))
        mask_seq = jnp.asarray(np.stack(mask_list))
        ns_dev = jnp.asarray(num_samples_np)
        if self.mesh is not None:
            from dba_mod_tpu.parallel.mesh import shard_round_inputs
            tasks_seq, idx_seq, mask_seq, ns_dev = shard_round_inputs(
                self.mesh, tasks_seq, idx_seq, mask_seq, ns_dev)

        self.rng_key, round_key = jax.random.split(self.rng_key)
        rng_train, rng_agg = jax.random.split(round_key)
        lane = jnp.arange(idx_seq.shape[1], dtype=jnp.int32)
        # Three dispatch shapes: the fused round (one program, one dispatch —
        # the perf path), the robust fused round (adds the screening sync +
        # host retry loop), and the SPLIT path — clients-one-by-one for
        # sequential_debug, or vmapped-per-phase when telemetry is on: the
        # fused round is a single XLA program, so honest per-phase times
        # require running train/aggregate/evals as separate programs with an
        # explicit sync each (the same programs sequential_debug and
        # bench.py's phase probe already exercise).
        # overlap_eval outranks the telemetry split: its batteries are
        # instrument_eval-wrapped (each call synced under telemetry) and the
        # round loop is forced sequential (_run_rounds), so the split core +
        # standalone batteries give the same honest per-phase attribution
        # the telemetry split path exists for.
        use_split = (self.sequential_debug
                     or (self._telemetry_split and not self._overlap))
        if not use_split:
            if self._overlap:
                return self._dispatch_overlap(
                    epoch, t0, seg_epochs, agent_names, adv_names,
                    tasks_list, mask_list, tasks_seq, idx_seq, mask_seq,
                    lane, ns_dev, rng_train, rng_agg)
            if self.engine.robust:
                return self._dispatch_robust(
                    epoch, t0, seg_epochs, agent_names, adv_names,
                    tasks_list, mask_list, tasks_seq, idx_seq, mask_seq,
                    lane, ns_dev, rng_train, rng_agg)
            # one program, one dispatch: train → aggregate → evals (the
            # donated twin when the gate allows — same program, XLA may
            # reuse the consumed state buffers in place)
            rf = (self.engine.round_fn_donated if self._use_donated_round
                  else self.engine.round_fn)
            new_vars, new_fg, payload = rf(
                self.global_vars, self.fg_state, tasks_seq, idx_seq,
                mask_seq, lane, ns_dev, rng_train, rng_agg)
            rolled = False
            if self._sentinel is not None:
                new_vars, payload, rolled = self._health_gate(
                    epoch, self.global_vars, new_vars, payload)
                if rolled:
                    new_fg = self.fg_state
            self.global_vars = new_vars
            self.fg_state = new_fg
            return RoundInFlight(
                epoch=epoch, t0=t0, seg_epochs=seg_epochs,
                agent_names=agent_names, adv_names=adv_names,
                tasks_list=tasks_list, mask_list=mask_list, payload=payload,
                forced_degraded=rolled,
                vars_after=new_vars, fg_after=new_fg,
                rng_after=self._snapshot_rng())

        if self.sequential_debug:
            train = self._train_sequential(tasks_seq, idx_seq, mask_seq,
                                           rng_train)
        else:
            with self.guard.watch("round/train"), \
                    self.telemetry.span("round/train"):
                train = self.engine.train_fn(self.global_vars, tasks_seq,
                                             idx_seq, mask_seq, lane,
                                             rng_train)
                self.telemetry.sync(train.deltas)
        return self._finish_split_round(epoch, t0, seg_epochs, agent_names,
                                        adv_names, tasks_list, mask_list,
                                        tasks_seq, mask_seq, ns_dev,
                                        rng_agg, train)

    def _finish_split_round(self, epoch, t0, seg_epochs, agent_names,
                            adv_names, tasks_list, mask_list, tasks_seq,
                            mask_seq, ns_dev, rng_agg,
                            train) -> RoundInFlight:
        """Aggregate + eval batteries + payload assembly for the split
        dispatch paths (sequential_debug and telemetry's per-phase mode) —
        the same tail the fused round program runs on device."""
        params = self.params
        tasks_last = jax.tree_util.tree_map(lambda l: l[-1], tasks_seq)
        tasks_first = jax.tree_util.tree_map(lambda l: l[0], tasks_seq)
        from dba_mod_tpu.fl.rounds import nbt_client_deltas
        with self.guard.watch("round/aggregate"), \
                self.telemetry.span("round/aggregate"):
            result = self.engine.aggregate_fn(
                self.global_vars, self.fg_state, train.deltas,
                train.fg_grads, train.fg_feature,
                tasks_first.participant_id, ns_dev, rng_agg,
                nbt_client_deltas(mask_seq, tasks_seq.scale))
            self.telemetry.sync(result.new_vars)

        # dispatch every eval before any host sync — one blocking transfer,
        # deferred to finalize_round so a caller can overlap the next round.
        # (With telemetry on, the instrumented batteries sync here instead:
        # honest eval/local + eval/global span times in exchange for the
        # pipeline overlap.)
        prev_deltas = (train.seg_deltas[-1] if train.seg_deltas else
                       jax.tree_util.tree_map(jnp.zeros_like, train.deltas))
        locals_dev = (self.engine.local_evals_fn(
            self.global_vars, train.deltas, tasks_last, prev_deltas)
            if self.local_eval else None)
        seg_locals_dev = None
        if self.local_eval and self.engine.seg_local_evals_fn is not None:
            seg_locals_dev = self.engine.seg_local_evals_fn(
                self.global_vars, train.seg_deltas, tasks_seq.scale,
                tasks_seq.adv_slot)
        globals_dev = self.engine.global_evals_fn(result.new_vars)
        fstats_dev = None
        if self.engine.forensic_fn is not None:
            # must see the PRE-aggregation globals (the cosine baseline is
            # "applied update" = new - old), so compute before reassignment
            fstats_dev = self.engine.forensic_fn(
                self.global_vars, result.new_vars, train.deltas,
                result.num_oracle_calls)
        track = (bool(params.get("vis_train_batch_loss"))
                 or bool(params.get("batch_track_distance")))
        batch_dev = (train.batch_loss, train.batch_dist) if track else None
        payload = (locals_dev, globals_dev, train.metrics, train.delta_norms,
                   result.wv, result.alpha, batch_dev, result.is_updated,
                   seg_locals_dev, None, fstats_dev)
        new_vars, new_fg = result.new_vars, result.new_fg_state
        rolled = False
        if self._sentinel is not None:
            new_vars, payload, rolled = self._health_gate(
                epoch, self.global_vars, new_vars, payload)
            if rolled:
                new_fg = self.fg_state
        self.global_vars = new_vars
        self.fg_state = new_fg
        return RoundInFlight(epoch=epoch, t0=t0, seg_epochs=seg_epochs,
                             agent_names=agent_names, adv_names=adv_names,
                             tasks_list=tasks_list, mask_list=mask_list,
                             payload=payload, forced_degraded=rolled,
                             vars_after=self.global_vars,
                             fg_after=self.fg_state,
                             rng_after=self._snapshot_rng())

    def _zero_deltas(self, n_clients: int):
        """A [C]-stacked all-zero delta tree — the stale lane's replay
        source before any round has been submitted."""
        tree = jax.tree_util.tree_map(
            lambda l: jnp.zeros((n_clients,) + l.shape, l.dtype),
            self.global_vars)
        if self.mesh is not None:
            from dba_mod_tpu.parallel.mesh import client_sharding
            tree = jax.device_put(tree, client_sharding(self.mesh))
        return tree

    def _robust_round_args(self, epoch: int, n_clients: int,
                           norm_mult: Optional[float] = None,
                           use_carry: bool = False):
        """The extra (rng_f, prev_deltas, norm_mult) inputs of the robust
        round program; () when the fault layer is off. The fault key is a
        pure function of (fault_seed, epoch) — independent of every other
        RNG stream, so fault schedules reproduce across runs and retries."""
        if not self.engine.robust:
            return ()
        rng_f = jax.random.fold_in(self._fault_key, epoch)
        if self.engine.fault_cfg.stale_enabled:
            prev = (self._prev_deltas
                    if use_carry and self._prev_deltas is not None
                    else self._zero_deltas(n_clients))
        else:
            prev = ()
        nm = self.engine.base_norm_mult if norm_mult is None else norm_mult
        return (rng_f, prev, jnp.float32(nm))

    def _health_check(self, epoch, vars_before, new_vars):
        """The sentinel decision alone — check the merged model BEFORE
        anything of round N+1 commits (the overlap scheduler calls this
        between the core program and the eval dispatch; the serial paths
        via _health_gate below). Returns (vars_to_commit, rolled_back);
        on a healthy merge the sentinel's EMA/ring commit happens here."""
        healthy, unorm = self._sentinel.check(vars_before, new_vars)
        if healthy:
            self._sentinel.commit(epoch, new_vars, unorm)
            return new_vars, False
        self.telemetry.counter("health_rollbacks").inc()
        target = self._sentinel.rollback_target(vars_before)
        logger.warning(
            "epoch %d: unhealthy aggregate (update norm %.3g vs EMA %.3g, "
            "band %.1fx); rolled back to last-good model", epoch, unorm,
            self._sentinel.ema, self._sentinel.band)
        return target, True

    def _health_gate(self, epoch, vars_before, new_vars, payload):
        """Post-merge sentinel for the non-retrying SERIAL dispatch paths:
        _health_check, plus — because those paths already ran the global
        battery on the pre-rollback model — a re-run on the restored model
        spliced into the payload so the recorded round stays finite.
        Returns (vars, payload, rolled_back)."""
        target, rolled = self._health_check(epoch, vars_before, new_vars)
        if not rolled:
            return target, payload, False
        globals_dev = self.engine.global_evals_fn(target)
        return target, payload[:1] + (globals_dev,) + payload[2:], True

    @staticmethod
    def _escalate_norm_mult(cur: float) -> float:
        """Retry-k screening escalation: switch the norm screen on if it was
        off (10× the survivor median catches any blowup that slipped a
        finite-only screen), then halve it each further retry, floored at
        1× the median — tighter than that would quarantine the majority."""
        return 10.0 if cur <= 0 else max(cur / 2.0, 1.0)

    def _dispatch_robust(self, epoch, t0, seg_epochs, agent_names,
                         adv_names, tasks_list, mask_list, tasks_seq,
                         idx_seq, mask_seq, lane, ns_dev, rng_train,
                         rng_agg) -> RoundInFlight:
        """The robust round dispatch: run the fused round program, then —
        only when screening is on — check the post-aggregation model is
        finite (ONE host sync; this is what pipeline depth costs under the
        fault layer) and re-run the round from the captured pre-round state
        with escalated screening up to max_round_retries. If retries run
        out, force a degraded round: restore the pre-round state, re-run
        the global battery on it, and record the degradation."""
        vars_before, fg_before = self.global_vars, self.fg_state
        C = int(idx_seq.shape[1])
        norm_mult: Optional[float] = None
        retries = 0
        healthy, unorm = True, 0.0
        while True:
            extra = self._robust_round_args(epoch, C, norm_mult=norm_mult,
                                            use_carry=True)
            # the robust round stays ONE fused program (the screening sync
            # below is the pipeline cost it already pays) — telemetry times
            # it as a single round/compute span per attempt
            with self.telemetry.span("round/compute"):
                new_vars, new_fg, payload, deltas_out = self.engine.round_fn(
                    vars_before, fg_before, tasks_seq, idx_seq, mask_seq,
                    lane, ns_dev, rng_train, rng_agg, *extra)
            if not self.engine.screening:
                finite = True  # unscreened injection: faults flow through
                if self._sentinel is not None:
                    # no norm screen to escalate — unhealthy goes straight
                    # to the rollback path below
                    healthy, unorm = self._sentinel.check(vars_before,
                                                          new_vars)
                break
            with self.guard.watch("round/screen_sync"), \
                    self.telemetry.span("round/screen_sync"):
                finite = bool(payload[9].global_finite)  # the one host sync
            healthy, unorm = True, 0.0
            if finite and self._sentinel is not None:
                healthy, unorm = self._sentinel.check(vars_before, new_vars)
            if (finite and healthy) or retries >= self.max_round_retries:
                break
            retries += 1
            cur = (self.engine.base_norm_mult if norm_mult is None
                   else norm_mult)
            norm_mult = self._escalate_norm_mult(cur)
            if self.retry_backoff_s > 0:
                time.sleep(min(self.retry_backoff_s * 2 ** (retries - 1),
                               30.0))
            logger.warning(
                "epoch %d: aggregated model %s; retry %d/%d with "
                "norm screen at %.2f× median", epoch,
                "non-finite" if not finite else "outside the health band",
                retries, self.max_round_retries, norm_mult)
        forced = (self.engine.screening and not finite) or not healthy
        if forced:
            # retries exhausted and the aggregate is still non-finite (or
            # outside the health band): degrade — restore the last-good
            # model (the pre-round state when no ring is armed) and re-run
            # the global battery on it so the record stays finite
            logger.warning(
                "epoch %d: aggregated model %s after %d retries; degraded "
                "round (last-good model carried forward)", epoch,
                "non-finite" if not finite else "outside the health band",
                retries)
            new_vars = (self._sentinel.rollback_target(vars_before)
                        if self._sentinel is not None else vars_before)
            new_fg = fg_before
            if self._sentinel is not None and not healthy:
                self.telemetry.counter("health_rollbacks").inc()
            globals_dev = self.engine.global_evals_fn(new_vars)
            payload = payload[:1] + (globals_dev,) + payload[2:]
        elif self._sentinel is not None:
            self._sentinel.commit(epoch, new_vars, unorm)
        self.global_vars = new_vars
        self.fg_state = new_fg
        stale_on = self.engine.fault_cfg.stale_enabled
        if stale_on:
            self._prev_deltas = deltas_out
        return RoundInFlight(
            epoch=epoch, t0=t0, seg_epochs=seg_epochs,
            agent_names=agent_names, adv_names=adv_names,
            tasks_list=tasks_list, mask_list=mask_list, payload=payload,
            n_retries=retries, forced_degraded=forced,
            vars_after=new_vars, fg_after=new_fg,
            rng_after=self._snapshot_rng(),
            deltas_after=deltas_out if stale_on else None)

    def _dispatch_overlap(self, epoch, t0, seg_epochs, agent_names,
                          adv_names, tasks_list, mask_list, tasks_seq,
                          idx_seq, mask_seq, lane, ns_dev, rng_train,
                          rng_agg) -> RoundInFlight:
        """The overlap scheduler (overlap_eval): run the round CORE — the
        fused program minus its eval tail (train → [faults → screen] →
        aggregate) — commit the model update, THEN dispatch round N's eval
        batteries as separate programs against the retained pre-round
        buffers. The pipelined loop in _run_rounds dispatches round N+1's
        core immediately after this returns, so the batteries (pure
        functions of the superseded model) and the host fetch/record/
        checkpoint path run concurrently with N+1's train. Contracts:

        * bit-identity — the batteries are the same jitted programs the
          fused round inlines, on the same inputs (pre-fault deltas,
          pre-round globals, post-commit model); fused ≡ core+batteries is
          A/B-verified by tests/test_overlap.py;
        * sentinel-before-commit — _health_check gates the merged model
          between the core and the eval dispatch, so the sentinel observes
          round N before anything of N+1 is enqueued, exactly as on the
          serial path;
        * retry cancellation — a rejected robust attempt never had evals in
          flight (the core returns only train/aggregate state); the
          batteries dispatch once, for the accepted (or force-degraded)
          attempt, whose train deltas are identical across attempts
          (rng_train and the fault key are fixed per epoch)."""
        engine = self.engine
        vars_before, fg_before = self.global_vars, self.fg_state
        retries = 0
        forced = False
        deltas_out = ()
        if not engine.robust:
            new_vars, new_fg, payload, eval_in = engine.core_fn(
                vars_before, fg_before, tasks_seq, idx_seq, mask_seq, lane,
                ns_dev, rng_train, rng_agg)
            if self._sentinel is not None:
                new_vars, forced = self._health_check(epoch, vars_before,
                                                      new_vars)
                if forced:
                    new_fg = fg_before
        else:
            C = int(idx_seq.shape[1])
            norm_mult: Optional[float] = None
            healthy, unorm = True, 0.0
            while True:
                extra = self._robust_round_args(epoch, C,
                                                norm_mult=norm_mult,
                                                use_carry=True)
                with self.telemetry.span("round/compute"):
                    (new_vars, new_fg, payload, deltas_out,
                     eval_in) = engine.core_fn(
                        vars_before, fg_before, tasks_seq, idx_seq,
                        mask_seq, lane, ns_dev, rng_train, rng_agg, *extra)
                if not engine.screening:
                    finite = True
                    if self._sentinel is not None:
                        healthy, unorm = self._sentinel.check(vars_before,
                                                              new_vars)
                    break
                with self.guard.watch("round/screen_sync"), \
                        self.telemetry.span("round/screen_sync"):
                    finite = bool(payload[9].global_finite)
                healthy, unorm = True, 0.0
                if finite and self._sentinel is not None:
                    healthy, unorm = self._sentinel.check(vars_before,
                                                          new_vars)
                if (finite and healthy) or retries >= self.max_round_retries:
                    break
                retries += 1
                cur = (engine.base_norm_mult if norm_mult is None
                       else norm_mult)
                norm_mult = self._escalate_norm_mult(cur)
                if self.retry_backoff_s > 0:
                    time.sleep(min(
                        self.retry_backoff_s * 2 ** (retries - 1), 30.0))
                logger.warning(
                    "epoch %d: aggregated model %s; retry %d/%d with "
                    "norm screen at %.2f× median", epoch,
                    "non-finite" if not finite
                    else "outside the health band",
                    retries, self.max_round_retries, norm_mult)
            forced = (engine.screening and not finite) or not healthy
            if forced:
                logger.warning(
                    "epoch %d: aggregated model %s after %d retries; "
                    "degraded round (last-good model carried forward)",
                    epoch, "non-finite" if not finite
                    else "outside the health band", retries)
                new_vars = (self._sentinel.rollback_target(vars_before)
                            if self._sentinel is not None else vars_before)
                new_fg = fg_before
                if self._sentinel is not None and not healthy:
                    self.telemetry.counter("health_rollbacks").inc()
            elif self._sentinel is not None:
                self._sentinel.commit(epoch, new_vars, unorm)
        # the model update is decided — commit, so the caller can enqueue
        # round N+1's core before the batteries below have drained
        self.global_vars = new_vars
        self.fg_state = new_fg
        stale_on = engine.fault_cfg.stale_enabled
        if stale_on:
            self._prev_deltas = deltas_out
        # eval dispatch against snapshots of the superseded buffers. With a
        # second local device the inputs are copied there and the same
        # jitted batteries compile a per-device executable (their
        # closure-captured eval data is placed per executable and cached),
        # so N's eval compute itself overlaps N+1's train — otherwise the
        # batteries share device 0 behind N+1's enqueue and the overlap
        # hides the host-side fetch/record/checkpoint path.
        deltas_pre, prev_dev, seg_deltas = eval_in
        tasks_last = jax.tree_util.tree_map(lambda l: l[-1], tasks_seq)
        scales, adv_slots = tasks_seq.scale, tasks_seq.adv_slot
        vars_old, vars_new = vars_before, new_vars
        (vars_old, vars_new, deltas_pre, prev_dev, seg_deltas, tasks_last,
         scales, adv_slots) = evaluation.place_eval_inputs(
            (vars_old, vars_new, deltas_pre, prev_dev, seg_deltas,
             tasks_last, scales, adv_slots), self._eval_device)
        locals_dev = (engine.local_evals_fn(vars_old, deltas_pre,
                                            tasks_last, prev_dev)
                      if self.local_eval else None)
        seg_locals_dev = None
        if self.local_eval and engine.seg_local_evals_fn is not None:
            seg_locals_dev = engine.seg_local_evals_fn(
                vars_old, list(seg_deltas), scales, adv_slots)
        globals_dev = engine.global_evals_fn(vars_new)
        payload = ((locals_dev, globals_dev) + payload[2:8]
                   + (seg_locals_dev,) + payload[9:])
        fl = RoundInFlight(
            epoch=epoch, t0=t0, seg_epochs=seg_epochs,
            agent_names=agent_names, adv_names=adv_names,
            tasks_list=tasks_list, mask_list=mask_list, payload=payload,
            n_retries=retries, forced_degraded=forced,
            vars_after=new_vars, fg_after=new_fg,
            rng_after=self._snapshot_rng(),
            deltas_after=deltas_out if stale_on else None,
            overlapped=True)
        fl.eval_dispatch_t = time.perf_counter()
        return fl

    def _snapshot_rng(self) -> Dict[str, Any]:
        """Host snapshot of every RNG stream a round consumes, taken right
        after dispatch consumed them — the state a resumed run needs to
        replay round N+1 onward exactly (tests/test_full_state_resume.py)."""
        return {"select_rng": self.select_rng.getstate(),
                "plan_rng": self.plan_rng.get_state(),
                "rng_key": np.asarray(jax.random.key_data(self.rng_key))}

    def finalize_round(self, fl: RoundInFlight) -> Dict[str, Any]:
        t_fin = time.perf_counter()
        self.telemetry.set_epoch(fl.epoch)
        # the round's one blocking transfer — the sync point where a wedged
        # runtime stalls, hence the watchdog zone (run_guard.py)
        with self.guard.watch("round/finalize"), \
                self.telemetry.span("round/finalize"):
            (locals_, globals_, metrics, delta_norms, wv, alpha,
             batches, is_updated, seg_locals, rstats,
             fstats) = jax.device_get(fl.payload)
        finalize_time = time.perf_counter() - t_fin
        # perf_counter durations (the old time.time() delta could jump under
        # clock adjustments); under pipeline_rounds round_time spans the
        # overlap with the next round's dispatch — dispatch_time and
        # finalize_time are the honest per-phase components
        times = {"round_time": time.perf_counter() - fl.t0,
                 "dispatch_time": fl.dispatch_time,
                 "finalize_time": finalize_time}
        if fl.overlapped:
            # honest attribution of the overlapped eval+sync work: of the
            # wall time since the batteries were enqueued, finalize only
            # BLOCKED for finalize_time — the rest drained behind whatever
            # the caller dispatched in between (round N+1's core under the
            # pipelined loop). Mirrored to the overlap/ telemetry family
            # when telemetry is wired (bench reads the experiment counters
            # directly — the pipelined loop runs with telemetry off).
            since_enqueue = time.perf_counter() - fl.eval_dispatch_t
            hidden = max(0.0, since_enqueue - finalize_time)
            self._overlap_rounds += 1
            self._overlap_hidden_s += hidden
            self._overlap_wait_s += finalize_time
            t = self.telemetry
            if t.enabled:
                t.counter("overlap/rounds").inc()
                t.gauge("overlap/hidden_eval_s").set(self._overlap_hidden_s)
                t.gauge("overlap/dispatch_ahead_depth").set(1.0)
                t.histogram("overlap/eval_wait_s").observe(finalize_time)
        self.last_is_updated = bool(is_updated)
        self.last_global_loss = float(globals_.clean.loss)
        if self.is_poison_run:
            self.last_backdoor_acc = float(globals_.poison.acc)
        # robust counters: from the jitted screen plus the host retry path
        # (a forced degradation restored the pre-round state host-side)
        robust = {"n_quarantined": 0, "n_dropped": 0,
                  "n_retries": int(fl.n_retries),
                  "degraded": bool(fl.forced_degraded)}
        if rstats is not None:
            robust["n_quarantined"] = int(rstats.n_quarantined)
            robust["n_dropped"] = int(rstats.n_dropped)
            robust["degraded"] = (bool(rstats.degraded)
                                  or bool(fl.forced_degraded))
        self._record(fl.epoch, fl.seg_epochs, fl.agent_names, fl.adv_names,
                     fl.tasks_list, metrics, locals_, globals_, delta_norms,
                     wv, alpha, times, batches, fl.mask_list, seg_locals,
                     robust)
        if self.forensics_writer is not None and fstats is not None:
            self._record_forensics(fl, locals_, delta_norms, wv, alpha,
                                   fstats, robust)
        self._flush_round_telemetry(fl, robust, delta_norms, times)
        return {"epoch": fl.epoch, "agents": fl.agent_names,
                "global_acc": float(globals_.clean.acc),
                "backdoor_acc": (float(globals_.poison.acc)
                                 if self.is_poison_run else None),
                **times, **robust}

    def _flush_round_telemetry(self, fl: RoundInFlight, robust: Dict[str,
                               Any], delta_norms, times) -> None:
        """Per-round metrics-registry update + flush: one telemetry.jsonl
        line carrying the round's counters/gauges and the span-duration and
        delta-norm histogram windows (mirrored to TB when wired)."""
        t = self.telemetry
        if not t.enabled:
            return
        t.counter("rounds").inc()
        if fl.n_retries:
            t.counter("round_retries").inc(fl.n_retries)
        if robust.get("n_quarantined"):
            t.counter("clients_quarantined").inc(robust["n_quarantined"])
        if robust.get("n_dropped"):
            t.counter("clients_dropped").inc(robust["n_dropped"])
        if robust.get("degraded"):
            t.counter("degraded_rounds").inc()
        for n in np.asarray(delta_norms).reshape(-1):
            t.histogram("delta_norm").observe(float(n))
        t.histogram("round_seconds").observe(times["round_time"])
        t.flush_round(fl.epoch)

    def _record_forensics(self, fl: RoundInFlight, locals_, delta_norms,
                          wv, alpha, fstats, robust) -> None:
        """One forensic record per round: host-side assembly of the jitted
        ForensicStats slot plus the identity/defense context only the
        experiment knows (names, adversary membership, defense weights,
        poison battery). Arrays are sliced to the real client count —
        trailing mesh-padding lanes carry no client."""
        from dba_mod_tpu.fl.rounds import REASON_NAMES
        params = self.params
        names = list(fl.agent_names)
        C = len(names)
        adv = set(params.adversary_list)
        pids = np.asarray(fl.tasks_list[0].participant_id)[:C]
        poison_acc = None
        if self.is_poison_run and locals_ is not None:
            poison_acc = np.asarray(locals_.poison_post.acc)[:C]
        robust_agg = params.aggregation != cfg.AGGR_MEAN
        self.forensics_writer.add_round(
            epoch=fl.epoch, aggregation=params.aggregation, names=names,
            participant_ids=pids,
            adversary_flags=[int(n in adv) for n in names],
            delta_norms=np.asarray(delta_norms)[:C],
            recv_norms=np.asarray(fstats.recv_norms)[:C],
            cosine=np.asarray(fstats.cosine_to_agg)[:C],
            verdict=np.asarray(fstats.verdict)[:C],
            reason_codes=np.asarray(fstats.reason)[:C],
            reason_names=REASON_NAMES,
            weights=np.asarray(wv)[:C] if robust_agg else None,
            alpha=np.asarray(alpha)[:C] if robust_agg else None,
            poison_acc=poison_acc,
            oracle_calls=int(fstats.oracle_calls),
            n_retries=int(robust.get("n_retries", 0)),
            degraded=bool(robust.get("degraded", False)))
        self.forensics_writer.save()

    def _train_sequential(self, tasks_seq, idx_seq, mask_seq, rng):
        """Sequential debug mode (SURVEY §7.2.4): run clients one at a time
        through the SAME per-client program (width-1 train_fn calls with the
        true lane index, so rng streams match the vmapped path), then stitch
        the stacked results back together for the shared aggregation path."""
        from dba_mod_tpu.fl.rounds import TrainResult
        C = idx_seq.shape[1]
        outs = []
        for c in range(C):
            t = jax.tree_util.tree_map(lambda l: l[:, c:c + 1], tasks_seq)
            outs.append(self.engine.train_fn(
                self.global_vars, t, idx_seq[:, c:c + 1],
                mask_seq[:, c:c + 1], jnp.asarray([c], jnp.int32), rng))
        cat0 = lambda *ls: jnp.concatenate(ls, axis=0)
        cat1 = lambda *ls: jnp.concatenate(ls, axis=1)
        n_seg_deltas = len(outs[0].seg_deltas)
        return TrainResult(
            deltas=jax.tree_util.tree_map(cat0, *[o.deltas for o in outs]),
            fg_grads=jax.tree_util.tree_map(cat0,
                                            *[o.fg_grads for o in outs]),
            fg_feature=jnp.concatenate([o.fg_feature for o in outs], 0),
            metrics=jax.tree_util.tree_map(cat1,
                                           *[o.metrics for o in outs]),
            delta_norms=jnp.concatenate([o.delta_norms for o in outs], 0),
            batch_loss=jnp.concatenate([o.batch_loss for o in outs], 1),
            batch_dist=jnp.concatenate([o.batch_dist for o in outs], 1),
            seg_deltas=[jax.tree_util.tree_map(
                cat0, *[o.seg_deltas[s] for o in outs])
                for s in range(n_seg_deltas)])

    # ------------------------------------------------------------- recording
    def _record(self, epoch, seg_epochs, agent_names, adv_names, tasks_list,
                metrics, locals_, globals_, delta_norms, wv, alpha, times,
                batches=None, mask_list=None, seg_locals=None, robust=None):
        # metrics leaves are [I, C, E]; tasks_list one ClientTask per segment.
        # Local clean evals: final segment from locals_, intermediate
        # segments (interval > 1) from seg_locals — matching the reference's
        # per-global-epoch cadence (image_train.py:268-271, :150-155). The
        # poison battery stays round-final: the reference runs it in the
        # poison branch against the round's submitted update.
        params = self.params
        rec = self.recorder
        tasks = tasks_list[-1]
        # round-final rows carry the round's LAST global epoch, like the
        # reference's temp_global_epoch = epoch + interval - 1 (main.py:196)
        final_ep = seg_epochs[-1]
        # per-client flags hold if ANY segment of the round poisoned
        # (a client may poison at epoch 3 of a (3,4) interval round)
        poisoning_any = np.zeros(len(agent_names), bool)
        adv_slot_any = np.full(len(agent_names), -1, np.int64)
        for t in tasks_list:
            poisoning_any |= np.asarray(t.poisoning_per_batch)[
                :len(agent_names)] > 0
            adv_slot_any = np.maximum(adv_slot_any,
                                      np.asarray(t.adv_slot)
                                      [:len(agent_names)])
        for c, name in enumerate(agent_names):
            for s, ep in enumerate(seg_epochs):
                n_e = int(tasks_list[s].num_epochs[c])
                for e in range(n_e):
                    count = max(float(metrics.count[s, c, e]), 1.0)
                    rec.add_train(name, (ep - 1) * n_e + e + 1, ep, e + 1,
                                  float(metrics.loss_sum[s, c, e]) / count,
                                  100.0 * float(metrics.correct[s, c, e])
                                  / count,
                                  int(metrics.correct[s, c, e]), int(count))
                if batches is not None:
                    # [I, C, E*S] per-batch channels; only steps whose batch
                    # mask is non-empty ran (padded epochs/steps are no-ops).
                    # The loss channel is benign-only: the reference calls
                    # train_batch_vis in the benign branch alone
                    # (image_train.py:225-228), while distance is tracked in
                    # both branches (:107-112, :235-240).
                    bloss, bdist = batches
                    S = mask_list[s].shape[2]
                    valid = mask_list[s][c].any(axis=-1).reshape(-1)  # [E*S]
                    seg_poisons = (np.asarray(
                        tasks_list[s].poisoning_per_batch)[c] > 0)
                    want_loss = (bool(params.get("vis_train_batch_loss"))
                                 and not seg_poisons)
                    want_dist = bool(params.get("batch_track_distance"))
                    for st in np.nonzero(valid)[0]:
                        e_i, b_i = int(st) // S, int(st) % S
                        tle = (ep - 1) * n_e + e_i + 1
                        if want_loss:
                            rec.add_batch_loss(name, tle, ep, e_i + 1, b_i, S,
                                               float(bloss[s, c, st]))
                        if want_dist:
                            rec.add_batch_distance(
                                name, tle, ep, e_i + 1, b_i, S,
                                float(bdist[s, c, st]))
            poisoning = bool(poisoning_any[c])
            # the FINAL segment's clean row gates on that segment's own
            # poisoning flag (a client may poison epoch 3 of a (3,4) round
            # and still get its benign epoch-4 row, image_train.py:267-271)
            final_seg_poisons = bool(
                np.asarray(tasks_list[-1].poisoning_per_batch)[c] > 0)
            baseline = bool(params["baseline"])
            if seg_locals is not None:
                # intermediate-segment rows (interval > 1): the reference
                # runs the whole battery inside the per-global-epoch loop —
                # same gating as the final segment below
                for s, seg_ev in enumerate(seg_locals):
                    ep_s = seg_epochs[s]
                    seg_poisons = (np.asarray(
                        tasks_list[s].poisoning_per_batch)[c] > 0)
                    if not (seg_poisons and baseline):
                        # image_train.py:148-155 gating
                        rec.add_test(name, ep_s,
                                     float(seg_ev.clean.loss[c]),
                                     float(seg_ev.clean.acc[c]),
                                     int(seg_ev.clean.correct[c]),
                                     int(seg_ev.clean.count[c]))
                    if seg_poisons and self.is_poison_run:
                        if not baseline:  # pre-scale row (:157-164)
                            rec.add_poisontest(
                                name, ep_s,
                                float(seg_ev.poison_pre.loss[c]),
                                float(seg_ev.poison_pre.acc[c]),
                                int(seg_ev.poison_pre.correct[c]),
                                int(seg_ev.poison_pre.count[c]))
                        # post-scale row (:275-282)
                        rec.add_poisontest(
                            name, ep_s,
                            float(seg_ev.poison_post.loss[c]),
                            float(seg_ev.poison_post.acc[c]),
                            int(seg_ev.poison_post.correct[c]),
                            int(seg_ev.poison_post.count[c]))
                    if (self.is_poison_run and int(np.asarray(
                            tasks_list[s].adv_slot)[c]) >= 0):
                        # per-agent trigger row runs for every adversary
                        # every global epoch (:285-295)
                        rec.add_triggertest(
                            name, f"{name}_trigger", "", ep_s,
                            float(seg_ev.agent_trigger.loss[c]),
                            float(seg_ev.agent_trigger.acc[c]),
                            int(seg_ev.agent_trigger.correct[c]),
                            int(seg_ev.agent_trigger.count[c]))
            if locals_ is not None:
                lr = locals_
                # the local clean eval for a poisoning client happens inside
                # `if not baseline` in the reference (image_train.py:148-155);
                # benign clients always get one (:267-271)
                if not (final_seg_poisons and baseline):
                    rec.add_test(name, final_ep, float(lr.clean.loss[c]),
                                 float(lr.clean.acc[c]),
                                 int(lr.clean.correct[c]),
                                 int(lr.clean.count[c]))
                if poisoning and self.is_poison_run:
                    if not baseline:
                        rec.add_poisontest(name, final_ep,
                                           float(lr.poison_pre.loss[c]),
                                           float(lr.poison_pre.acc[c]),
                                           int(lr.poison_pre.correct[c]),
                                           int(lr.poison_pre.count[c]))
                    rec.add_poisontest(name, final_ep,
                                       float(lr.poison_post.loss[c]),
                                       float(lr.poison_post.acc[c]),
                                       int(lr.poison_post.correct[c]),
                                       int(lr.poison_post.count[c]))
                if (self.is_poison_run and
                        int(adv_slot_any[c]) >= 0):
                    rec.add_triggertest(
                        name, f"{name}_trigger", "", final_ep,
                        float(lr.agent_trigger.loss[c]),
                        float(lr.agent_trigger.acc[c]),
                        int(lr.agent_trigger.correct[c]),
                        int(lr.agent_trigger.count[c]))
            if poisoning and not baseline:
                rec.scale_temp_one_row.extend(
                    [epoch, round(float(delta_norms[c]), 4)])

        rec.add_test("global", final_ep, float(globals_.clean.loss),
                     float(globals_.clean.acc), int(globals_.clean.correct),
                     int(globals_.clean.count))
        if self.is_poison_run:
            g = globals_
            rec.add_poisontest("global", final_ep, float(g.poison.loss),
                               float(g.poison.acc), int(g.poison.correct),
                               int(g.poison.count))
            rec.add_triggertest("global", "combine", "", final_ep,
                                float(g.poison.loss), float(g.poison.acc),
                                int(g.poison.correct), int(g.poison.count))
            if params.is_centralized_attack:
                # gated on centralized_test_trigger (main.py:226)
                names = [f"global_in_index_{j}_trigger"
                         for j in range(self.engine.num_global_triggers)]
            else:
                names = [f"global_in_{a}_trigger"
                         for a in params.adversary_list]
            for j, tname in enumerate(names):
                rec.add_triggertest(
                    "global", tname, "", final_ep,
                    float(g.per_trigger.loss[j]), float(g.per_trigger.acc[j]),
                    int(g.per_trigger.correct[j]),
                    int(g.per_trigger.count[j]))
        if rec.scale_temp_one_row:
            rec.scale_temp_one_row.append(round(float(globals_.clean.acc), 4))
        if self.params.aggregation != cfg.AGGR_MEAN:
            rec.add_weight_result(list(agent_names), wv.tolist(),
                                  alpha.tolist(), epoch=epoch)
        rec.add_round_json(
            epoch=epoch, agents=[str(a) for a in agent_names],
            adversaries=[str(a) for a in adv_names],
            is_updated=self.last_is_updated,
            global_acc=float(globals_.clean.acc),
            global_loss=float(globals_.clean.loss),
            backdoor_acc=(float(globals_.poison.acc)
                          if self.is_poison_run else None),
            **times, **(robust or {}))
        rec.save(self.is_poison_run)

    # ------------------------------------------------------------------- run
    @property
    def checkpoint_manager(self) -> ckpt.CheckpointManager:
        """Manifest/retention policy bound to the CURRENT run folder —
        rebuilt when the folder changes (tests reassign ``exp.folder``
        after construction). Pending async-manifest state is module-level
        in checkpoint.py, so a rebuild loses nothing."""
        if self._ckpt_mgr is None or self._ckpt_mgr.folder != self.folder:
            self._ckpt_mgr = ckpt.CheckpointManager(
                self.folder,
                keep_last_n=int(self.params.get("keep_last_n", 0)),
                manifests=bool(self.params.get("checkpoint_manifests",
                                               True)))
        return self._ckpt_mgr

    def save_model(self, epoch: int, fl: Optional[RoundInFlight] = None,
                   async_save: bool = False,
                   extra_aux: Optional[Dict[str, Any]] = None):
        """Checkpoint the round's post-aggregation state. With `fl`, saves
        the state captured at that round's dispatch (required under
        pipelining — the live attributes already belong to the next round);
        `async_save` routes through orbax's AsyncCheckpointer so the commit
        overlaps the next round's compute (run() waits before returning).
        Every committed snapshot gets an integrity manifest (immediately
        for sync saves; once the commit provably landed for async ones),
        then retention GC runs (checkpoint.py::CheckpointManager).
        `extra_aux` merges additional keys into the full-state sidecar —
        the buffered-async driver rides its streaming state (arrival heap,
        buffer, live cohorts) here under ``async_state``."""
        params = self.params
        if not params["save_model"] or self.folder is None:
            return
        mgr = self.checkpoint_manager
        with self.telemetry.span("round/checkpoint"):
            model_vars = fl.vars_after if fl is not None else self.global_vars
            fg_state = fl.fg_after if fl is not None else self.fg_state
            rng = fl.rng_after if fl is not None else self._snapshot_rng()
            path = self.folder / "model_last.pt.tar"
            lr = float(params["lr"])
            written = [path]
            if epoch in list(params["save_on_epochs"]):
                written.append(Path(str(path) + f".epoch_{epoch}"))
            # best-val snapshot whenever the global eval loss improves
            # (helper.py:433-435, called with epoch_loss from main.py:233)
            if self.last_global_loss < self.best_loss:
                written.append(Path(str(path) + ".best"))
                self.best_loss = self.last_global_loss
            # before force=True replaces committed snapshots: land owed
            # async manifests, drop queued ones for the doomed dirs, and
            # clone each verified snapshot to <name>.prev so a kill at any
            # instant of this save leaves a verified resume point
            mgr.prepare_overwrite(written, async_save,
                                  writer=jax.process_index() == 0)
            for p in written:
                ckpt.save_checkpoint(p, model_vars, epoch, lr,
                                     async_save=async_save)
            # full-state sidecar (deviation, documented in checkpoint.py):
            # the reference loses FoolsGold memory / best loss / RNG position
            # on restart; we persist them so resume replays the exact
            # trajectory. Every snapshot gets one — resuming from
            # .epoch_N/.best must not silently reset the defense. One writer
            # on multi-process.
            mem = fg_state.memory
            if jax.process_index() == 0 and (jax.process_count() == 1
                                             or mem.is_fully_addressable):
                aux = {"epoch": int(epoch),
                       "fg_memory": np.asarray(mem),
                       "best_loss": float(self.best_loss),
                       "last_backdoor_acc": self.last_backdoor_acc,
                       **rng}
                if extra_aux:
                    aux.update(extra_aux)
                if self.engine.fault_cfg.stale_enabled:
                    # the stale lane's replay source: what the server
                    # received THIS round (deltas_after under pipelining —
                    # the live _prev_deltas may already be next round's).
                    # Model-sized × C, but the lane is single-process-only
                    # and opt-in; without it the first post-resume stale
                    # replay would silently replay a zero delta.
                    src = (fl.deltas_after if fl is not None
                           else self._prev_deltas)
                    if src is not None:
                        aux["prev_deltas"] = jax.tree_util.tree_map(
                            np.asarray, src)
                for p in written:
                    ckpt.save_aux_state(p, aux)
            if jax.process_index() == 0:  # one manifest/GC writer
                # manifests cover the step dir + the sidecar when one was
                # written (sharded-fg multi-host runs skip the sidecar but
                # must still get verifiable — hence resumable — snapshots);
                # sync saves get them now, async ones once committed
                mgr.note_saved(written, epoch, async_save=async_save)
                mgr.gc()

    def run(self, epochs: Optional[int] = None) -> Dict[str, Any]:
        self.interrupted = False
        from dba_mod_tpu.parallel.distributed import PeerLostError
        # the guard context installs the SIGTERM/SIGINT handlers around the
        # run loop (and restores the previous ones after) — a no-op unless
        # graceful_shutdown is on
        with self.guard:
            if self.peers is not None:
                # heartbeats + the peer-lost watchdog verdict live exactly
                # as long as the round loop
                self.peers.start()
                self.guard.attach_peer_health(self.peers)
            try:
                return self._run_rounds(epochs)
            except PeerLostError:
                telemetry.count("run/peer_lost")
                raise
            except Exception as exc:
                # classify: a collective that failed because its peer
                # vanished must surface as PeerLost (exit 77, relaunch
                # shrunk), not as a generic crash — poll the heartbeats
                # long enough for a real loss to become stale
                lost = self._classify_peer_failure()
                if lost:
                    telemetry.count("run/peer_lost")
                    raise PeerLostError(
                        lost, detail=f"collective failure: "
                        f"{type(exc).__name__}") from exc
                raise
            finally:
                try:
                    # EVERY exit path — normal return, graceful stop, or a
                    # mid-run exception — must land the in-flight async
                    # commit (force=True already deleted the previous
                    # model_last) and write the manifests it was owed
                    with self.guard.watch("checkpoint/wait_async"):
                        ckpt.wait_for_async_saves()
                finally:
                    if self.peers is not None:
                        self.guard.attach_peer_health(None)
                        self.peers.stop()
                    # end-of-run telemetry: final trace.json flush + the
                    # printed phase-summary table (p50/p95 per span,
                    # recompile count, peak device memory) — also on a
                    # mid-run exception, so a crashed run still leaves a
                    # loadable trace
                    self._finish_telemetry()

    def _classify_peer_failure(self) -> List[int]:
        """An exception escaped the round loop: slow peer or gone peer?
        Poll the heartbeats for up to one timeout window — a dead host's
        file goes stale within it, a live-but-erroring world's does not.
        Empty list = not a peer loss (re-raise the original)."""
        if self.peers is None:
            return []
        deadline = (time.monotonic() + self.peers.timeout_s
                    + self.peers.interval_s)
        while True:
            lost = self.peers.lost_peers()
            if lost or time.monotonic() >= deadline:
                return lost
            time.sleep(min(max(self.peers.interval_s, 0.05), 0.25))

    def _finish_telemetry(self) -> None:
        t = self.telemetry
        if not t.enabled:
            return
        t.record_memory()
        t.close()
        print(t.summary_table())

    def _run_rounds(self, epochs: Optional[int] = None) -> Dict[str, Any]:
        if str(self.params.get("mode", "sync")) == "async":
            # the buffered-async engine owns the whole loop: cohort
            # dispatch, arrival simulation, K-arrival merges, recording,
            # and checkpointing (fl/async_rounds.py). run()'s guard /
            # wait_for_async_saves / telemetry teardown still wrap it.
            from dba_mod_tpu.fl.async_rounds import AsyncDriver
            return AsyncDriver(self).run(epochs)
        last: Dict[str, Any] = {}
        end = epochs if epochs is not None else int(self.params["epochs"])
        profile_dir = str(self.params.get("profile_dir", "") or "")
        if self.telemetry.enabled and not self.sequential_debug:
            # compile every dynamic-steps bucket up front: mark_warm() fires
            # after the first full round, and a later round landing in a
            # fresh bucket would otherwise count its legitimate first
            # compile as a retrace regression
            with self.telemetry.span("engine/warm_buckets"):
                self.warm_step_buckets()
        # pipeline_rounds: overlap round N's host fetch/record with round
        # N+1's device compute (depth 1). Checkpoints ride orbax async saves
        # — save_model(fl=...) uses the state captured at dispatch, and
        # AsyncCheckpointer serializes commits, so per-epoch checkpoints
        # land in program order (tests/test_async_checkpoint.py). Profiling
        # forces sequential rounds (a trace needs one round's dispatch+fetch
        # alone on the timeline), and so does telemetry: finalize(N) flushes
        # round N's histogram window, which dispatch(N+1) — fully synced on
        # the split path — would otherwise pollute with round N+1's spans.
        # overlap_eval rides the same depth-1 loop: its dispatch returns
        # with round N's eval batteries still in flight, so dispatching
        # N+1's core before finalizing N is what actually hides them
        if ((bool(self.params.get("pipeline_rounds", False))
                or self._overlap)
                and not profile_dir and not self.telemetry.enabled):
            def finalize_and_log(fl):
                r = self.finalize_round(fl)
                self.save_model(fl.epoch, fl=fl, async_save=True)
                # one full round has finished end-to-end: every program a
                # steady-state round needs has compiled — later compiles
                # are retrace regressions (telemetry counts + warns)
                self.telemetry.mark_warm()
                logger.info("epoch %d done in %.2fs acc=%.2f backdoor=%s",
                            r["epoch"], r["round_time"], r["global_acc"],
                            r["backdoor_acc"])
                return r

            # (run()'s finally holds the wait_for_async_saves that used to
            # live here — it now covers every exit path, not just this one)
            pending: Optional[RoundInFlight] = None
            for epoch in range(self.start_epoch, end + 1, self.interval):
                if self.guard.stop_requested:
                    self._note_interrupted(epoch)
                    break
                self._round_boundary(epoch)
                fl = self.dispatch_round(epoch)
                if pending is not None:
                    last = finalize_and_log(pending)
                pending = fl
            if pending is not None:
                last = finalize_and_log(pending)
            return last
        for epoch in range(self.start_epoch, end + 1, self.interval):
            if self.guard.stop_requested:
                # round-boundary stop: the previous round's save_model
                # already committed a verified checkpoint and the recorder
                # saved — nothing mid-flight to lose
                self._note_interrupted(epoch)
                break
            self._round_boundary(epoch)
            if profile_dir and epoch == self.start_epoch + self.interval:
                # trace the first post-compile round (SURVEY §5 tracing row)
                with jax.profiler.trace(profile_dir):
                    last = self.run_round(epoch)
            else:
                last = self.run_round(epoch)
            self.save_model(epoch)
            self.telemetry.mark_warm()  # first full round ends warmup
            logger.info("epoch %d done in %.2fs acc=%.2f backdoor=%s",
                        epoch, last["round_time"], last["global_acc"],
                        last["backdoor_acc"])
        return last

    def _round_boundary(self, epoch: int) -> None:
        """Elastic round-boundary work, in order: (1) the host-loss fault
        lane may SIGKILL this process (multi-process runs — the designated
        victim dies HERE, at a boundary, so committed rounds stay
        committed); (2) beat + peer staleness check, optionally the
        bounded barrier — a dead peer surfaces as PeerLostError now,
        outside any collective, instead of a wedged program. No-op when
        the elastic layer and the host-loss lane are off."""
        self._maybe_kill_self(epoch)
        if self.peers is None:
            return
        if self.heartbeat_barrier_s > 0:
            self.peers.barrier(epoch, self.heartbeat_barrier_s)
        else:
            self.peers.check(epoch)

    def _maybe_kill_self(self, epoch: int) -> None:
        """Multi-process enactment of the host-loss fault lane
        (fl/faults.py::host_loss_victim): every process derives the same
        per-epoch victim from (fault_seed, epoch); the victim SIGKILLs
        itself — no handlers, no cleanup, exactly the preemption shape the
        elastic layer must survive. Single-process runs enact the lane
        inside the round program instead (host_loss_in_program)."""
        from dba_mod_tpu.fl import faults as flt
        fcfg = self.engine.fault_cfg
        if (not fcfg.host_loss_enabled or fcfg.host_loss_in_program
                or jax.process_count() == 1):
            return
        rng_f = jax.random.fold_in(self._fault_key, epoch)
        victim = int(flt.host_loss_victim(fcfg, rng_f))
        if victim != jax.process_index():
            return
        logger.critical(
            "fault injection: host-loss lane kills process %d at the "
            "epoch-%d boundary (SIGKILL — survivors must detect, exit %d, "
            "and relaunch shrunk)", victim, epoch,
            run_guard.EXIT_PEER_LOST)
        logging.shutdown()
        os.kill(os.getpid(), signal.SIGKILL)

    def _note_interrupted(self, next_epoch: int) -> None:
        """A graceful-stop request was honored at a round boundary: record
        it so the CLI can exit with run_guard.EXIT_INTERRUPTED and a
        wrapper can relaunch with ``--resume auto``."""
        self.interrupted = True
        telemetry.count("run/interrupted")
        logger.warning(
            "graceful stop honored at the round boundary before epoch %d — "
            "writing final state and exiting (resume with --resume auto)",
            next_epoch)
