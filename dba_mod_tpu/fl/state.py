"""State trees for the FL round computation.

SURVEY §7.2.1: the reference mutates torch state_dicts in place everywhere;
the functional equivalent is an explicit carry. The per-round carry is
`(ModelVars global, FoolsGoldState, rng)`; everything per-client is a
`ClientTask` row stacked on the clients axis.
"""
from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax
import numpy as np

from dba_mod_tpu import config as cfg


class ClientTask(NamedTuple):
    """Per-client round inputs; every field stacked to [C] (lr_row to [C, E]).

    Encodes the reference's per-client branching (benign vs poison path,
    image_train.py:56-191) as data so one jitted computation serves all
    clients:
      - benign lane: poisoning_per_batch=0, alpha=1, scale=1, lr_row=lr
      - poison lane: poisoning_per_batch=k, alpha=alpha_loss, scale=
        scale_weights_poison (1 when `baseline`), lr_row=poison MultiStepLR
    """
    slot: jax.Array              # i32 — data shard slot (LOAN state index)
    participant_id: jax.Array    # i32 — global participant id (FoolsGold memory)
    adv_index: jax.Array         # i32 — trigger bank row; -1 = combined/global
    adv_slot: jax.Array          # i32 — position in adversary_list, -1 benign
                                 #       (keys the local-trigger eval even in
                                 #       centralized mode, test.py:218-223)
    poisoning_per_batch: jax.Array  # i32 — 0 disables poisoning
    alpha: jax.Array             # f32 — blended-loss α (image_train.py:89)
    scale: jax.Array             # f32 — model-replacement γ (image_train.py:166-171)
    lr_row: jax.Array            # f32[E] — per-internal-epoch LR
    num_epochs: jax.Array        # i32 — valid internal epochs (≤ E)


@dataclasses.dataclass(frozen=True)
class RoundHyper:
    """Static (compile-time) round hyperparameters."""
    momentum: float
    weight_decay: float
    poison_label_swap: int
    lr: float                  # global lr — FoolsGold's apply step uses it
    eta: float
    no_models: int
    aggregation: str           # cfg.AGGR_*
    fg_use_memory: bool
    diff_privacy: bool
    sigma: float
    geom_median_maxiter: int
    max_update_norm: float | None = None
    track_batches: bool = False
    alpha_loss: float = 1.0    # static: 1.0 ⇒ the blended-loss distance
                               # term is identically zero and its (fwd+bwd)
                               # compute is skipped at trace time
    krum_m: int = 1            # multi-Krum selection count (krum only)
    krum_f: int = 0            # assumed Byzantine count in the Krum score
    trim_beta: float = 0.1     # trimmed-mean per-coordinate trim fraction

    @classmethod
    def from_params(cls, p: cfg.Params) -> "RoundHyper":
        mun = p.get("max_update_norm")
        return cls(momentum=float(p["momentum"]),
                   weight_decay=float(p["decay"]),
                   poison_label_swap=int(p["poison_label_swap"]),
                   lr=float(p["lr"]),
                   eta=float(p["eta"]), no_models=int(p["no_models"]),
                   aggregation=p.aggregation,
                   fg_use_memory=bool(p["fg_use_memory"]),
                   diff_privacy=bool(p["diff_privacy"]),
                   sigma=float(p["sigma"]),
                   geom_median_maxiter=int(p["geom_median_maxiter"]),
                   max_update_norm=(None if mun is None else float(mun)),
                   track_batches=bool(p.get("vis_train_batch_loss")
                                      or p.get("batch_track_distance")),
                   alpha_loss=float(p["alpha_loss"]),
                   krum_m=int(p.get("krum_m", 1)),
                   krum_f=int(p.get("krum_byzantine_f", 0)),
                   trim_beta=float(p.get("trimmed_mean_beta", 0.1)))


def build_client_tasks(params: cfg.Params, agent_names: list, epoch: int,
                       slots: np.ndarray, num_epochs_max: int,
                       backdoor_acc: float | None = None) -> ClientTask:
    """Host-side construction of the stacked ClientTask for one round.

    Mirrors the reference's per-client setup: adversarial index resolution
    (image_train.py:37-48), poison-epoch scheduling (:56), poison LR schedule
    (:59-68), LOAN adaptive poison LR from the current global backdoor
    accuracy (loan_train.py:67-75), scaling/baseline flags (:148,166).
    """
    from dba_mod_tpu.ops.sgd import poison_multistep_lr_array

    C = len(agent_names)
    is_loan = params.type == cfg.TYPE_LOAN
    is_poison_run = bool(params["is_poison"])
    baseline = bool(params["baseline"])
    lr = float(params["lr"])
    poison_lr = float(params["poison_lr"])
    if is_loan and backdoor_acc is not None:
        # loan_train.py:71-75
        from dba_mod_tpu.ops.sgd import loan_adaptive_poison_lr
        poison_lr = float(loan_adaptive_poison_lr(
            poison_lr, np.float32(backdoor_acc), baseline))

    E = num_epochs_max
    internal_epochs = int(params["internal_epochs"])
    internal_poison = int(params["internal_poison_epochs"])
    step_lr_mult = (poison_multistep_lr_array(internal_poison,
                                              step_before=is_loan)
                    if bool(params["poison_step_lr"])
                    else np.ones((internal_poison,), np.float32))

    adv_idx = np.full((C,), -1, np.int32)
    adv_slot = np.full((C,), -1, np.int32)
    ppb = np.zeros((C,), np.int32)
    alpha = np.ones((C,), np.float32)
    scale = np.ones((C,), np.float32)
    lr_rows = np.full((C, E), lr, np.float32)
    n_epochs = np.full((C,), internal_epochs, np.int32)
    pids = np.zeros((C,), np.int32)

    for c, name in enumerate(agent_names):
        if is_loan:
            pids[c] = int(slots[c])
        else:
            pids[c] = int(name)
        slot_of = params.adversary_slot_of(name)
        adv_slot[c] = slot_of
        poisoning_now = (is_poison_run and slot_of >= 0 and
                         epoch in params.poison_epochs_for(slot_of))
        if poisoning_now:
            adv_idx[c] = params.adversarial_index_of(name)
            ppb[c] = int(params["poisoning_per_batch"])
            alpha[c] = float(params["alpha_loss"])
            scale[c] = 1.0 if baseline else float(params["scale_weights_poison"])
            n_epochs[c] = internal_poison
            row = poison_lr * step_lr_mult
            lr_rows[c, :] = 0.0
            lr_rows[c, :min(E, internal_poison)] = row[:E]
    return ClientTask(slot=slots.astype(np.int32), participant_id=pids,
                      adv_index=adv_idx, adv_slot=adv_slot,
                      poisoning_per_batch=ppb, alpha=alpha,
                      scale=scale, lr_row=lr_rows, num_epochs=n_epochs)
