"""Per-round agent selection — host-side, reference main.py:139-164 parity.

Three modes:
1. random namelist + random adversary: uniform sample of no_models (may pick
   no adversaries at all);
2. random namelist + fixed adversary (the paper's mode): adversaries whose
   poison schedule covers this round are forced in, the rest of the round is
   filled with a uniform sample over benign agents + off-schedule adversaries;
3. fixed namelist: participants_namelist verbatim.

Uses an explicit `random.Random` instead of the reference's module-global
seeded RNG (main.py:36-38) so selection is reproducible independent of other
host-side consumers.
"""
from __future__ import annotations

import random
from typing import Any, List, Tuple

from dba_mod_tpu import config as cfg


def select_agents(params: cfg.Params, epoch: int, participants: List[Any],
                  benign_names: List[Any], rng: random.Random
                  ) -> Tuple[List[Any], List[Any]]:
    """Returns (agent_name_keys, adversarial_name_keys) for one round."""
    agent_name_keys = list(participants)
    adversarial_name_keys: List[Any] = []
    if params["is_random_namelist"]:
        if params["is_random_adversary"]:
            agent_name_keys = rng.sample(participants, params["no_models"])
            adversarial_name_keys = [n for n in agent_name_keys
                                     if n in params.adversary_list]
        else:
            ongoing = list(range(epoch, epoch + params["aggr_epoch_interval"]))
            for idx, adv in enumerate(params.adversary_list):
                sched = params.poison_epochs_for(idx)
                if any(e in sched for e in ongoing):
                    if adv not in adversarial_name_keys:
                        adversarial_name_keys.append(adv)
            nonattacker = [adv for adv in params.adversary_list
                           if adv not in adversarial_name_keys]
            benign_num = params["no_models"] - len(adversarial_name_keys)
            fill = rng.sample(benign_names + nonattacker, benign_num)
            agent_name_keys = adversarial_name_keys + fill
    else:
        if not params["is_random_adversary"]:
            adversarial_name_keys = list(params.adversary_list)
    return agent_name_keys, adversarial_name_keys
