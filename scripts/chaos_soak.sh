#!/usr/bin/env bash
# make chaos-soak / make chaos-smoke: the self-healing soak harness.
# Drives the sync and async engines (one lane each, derived from
# configs/chaos_soak_params.yaml) under a seeded compound schedule —
# every client fault lane at once plus the host-loss lane in the config,
# while this script SIGTERMs or SIGKILLs the process at seeded instants
# and flips a byte in a committed checkpoint between resumes. After the
# final `--resume auto` leg completes, the lane must satisfy the
# self-healing invariants: ONE run folder, aggregation steps 1..N exactly
# once across every resume (monotonic versions, no duplicate recorder
# steps), finite global metrics on every row, and a verified final
# checkpoint. Any exit not caused by our own signal must be one of
# {0, 75, 76, 77} (run_guard.py's exit contract).
#
# Env knobs: CHAOS_SEED (schedule seed, default 0), CHAOS_KILLS
# (kill/resume cycles per lane, default 3), CHAOS_LANES (default
# "async sync"). `make chaos-smoke` runs the single-kill async lane.
# See README "Self-healing federation".
set -euo pipefail
cd "$(dirname "$0")/.."

SEED=${CHAOS_SEED:-0}
KILLS=${CHAOS_KILLS:-3}
LANES=${CHAOS_LANES:-"async sync"}
CFG=configs/chaos_soak_params.yaml
BASE_DIR=$(python -c "import yaml; print(yaml.safe_load(open('$CFG'))['run_dir'])")
rm -rf "$BASE_DIR"; mkdir -p "$BASE_DIR"

# seeded compound schedule: per cycle "rows_to_wait:signal:flip" — let the
# run commit 1-3 more merges, hit it with SIGTERM or SIGKILL, and maybe
# corrupt a checkpoint before the resume leg
SCHEDULE=$(python - "$SEED" "$KILLS" <<'EOF'
import random, sys
r = random.Random(int(sys.argv[1]))
print(" ".join(
    f"{r.randint(1, 3)}:{r.choice(['TERM', 'KILL'])}:{int(r.random() < 0.5)}"
    for _ in range(int(sys.argv[2]))))
EOF
)
echo "chaos-soak: seed=$SEED kills=$KILLS lanes=[$LANES] schedule: $SCHEDULE"

PID=""
trap '[ -n "$PID" ] && kill -9 "$PID" 2>/dev/null || true' EXIT

for LANE in $LANES; do
  LANE_CFG="$BASE_DIR/${LANE}_params.yaml"
  RUN_DIR="$BASE_DIR/$LANE"
  python - "$CFG" "$LANE" "$RUN_DIR" "$LANE_CFG" <<'EOF'
import sys, yaml
cfg = yaml.safe_load(open(sys.argv[1]))
cfg["mode"] = sys.argv[2]
cfg["run_dir"] = sys.argv[3]
yaml.safe_dump(cfg, open(sys.argv[4], "w"))
EOF

  rc=1
  RESUME=""
  for CYCLE in $SCHEDULE; do
    WAIT_ROWS=${CYCLE%%:*}; REST=${CYCLE#*:}
    SIG=${REST%%:*}; FLIP=${REST##*:}
    BASE_ROWS=$({ cat "$RUN_DIR"/mnist_*/metrics.jsonl 2>/dev/null || true; } | wc -l)
    TARGET=$((BASE_ROWS + WAIT_ROWS))

    # shellcheck disable=SC2086
    env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train \
      --params "$LANE_CFG" $RESUME &
    PID=$!
    for _ in $(seq 1 600); do
      n=$({ cat "$RUN_DIR"/mnist_*/metrics.jsonl 2>/dev/null || true; } | wc -l)
      [ "${n:-0}" -ge "$TARGET" ] && break
      kill -0 "$PID" 2>/dev/null || break   # finished before the signal
      sleep 0.5
    done
    SIGNALLED=0
    if kill -0 "$PID" 2>/dev/null; then
      SIGNALLED=1
      echo "chaos-soak[$LANE]: rows=$n -> SIG$SIG"
      kill "-$SIG" "$PID" 2>/dev/null || true
    fi
    set +e; wait "$PID"; rc=$?; set -e
    PID=""
    echo "chaos-soak[$LANE]: run exited rc=$rc (signalled=$SIGNALLED sig=$SIG)"
    if [ "$SIGNALLED" -eq 1 ] && [ "$SIG" = "KILL" ]; then
      # 137 = killed by our own SIGKILL; anything else means the run beat
      # the signal to a contract exit
      case "$rc" in 137|0|75|76|77) ;; *)
        echo "chaos-soak[$LANE]: unexpected exit code $rc after SIGKILL" >&2
        exit 1 ;;
      esac
    else
      case "$rc" in 0|75|76|77) ;; *)
        echo "chaos-soak[$LANE]: exit code $rc outside the {0,75,76,77} contract" >&2
        exit 1 ;;
      esac
    fi
    [ "$rc" -eq 0 ] && break   # lane outran the schedule — soak done early

    if [ "$FLIP" -eq 1 ]; then
      # flip one byte in the newest verified snapshot — but only when an
      # older verified snapshot exists for resume to fall back to
      python - "$RUN_DIR" "$SEED" <<'EOF'
import glob, random, sys
from pathlib import Path
from dba_mod_tpu import checkpoint as ckpt
folders = sorted(glob.glob(sys.argv[1] + "/mnist_*"))
if folders:
    cands = [p for *_, p in ckpt._discovery_candidates(Path(folders[0]))]
    verified = [p for p in cands if ckpt.verify_checkpoint(p)[0]]
    print(f"chaos-soak: verified snapshots: {[p.name for p in verified]}")
    if len(verified) >= 2:
        r = random.Random(int(sys.argv[2]))
        files = sorted(p for p in verified[0].rglob("*") if p.is_file())
        f = files[r.randrange(len(files))]
        data = bytearray(f.read_bytes())
        if data:
            i = r.randrange(len(data))
            data[i] ^= 0xFF
            # replace through a fresh inode: .prev clones hardlink their
            # source (checkpoint.py::_clone_file), and an in-place write
            # would corrupt BOTH snapshots through the shared data blocks
            tmp = f.with_name(f.name + ".flip")
            tmp.write_bytes(bytes(data))
            tmp.replace(f)
            print(f"chaos-soak: flipped byte {i} of {f}")
    else:
        print("chaos-soak: skipped byte-flip (needs 2 verified snapshots)")
EOF
    fi
    RESUME="--resume auto"
  done

  if [ "$rc" -ne 0 ]; then
    # final leg: resume to completion, no chaos
    env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train \
      --params "$LANE_CFG" --resume auto
  fi

  python - "$LANE_CFG" "$LANE" <<'EOF'
import glob, json, math, sys, yaml
cfg = yaml.safe_load(open(sys.argv[1]))
lane = sys.argv[2]
folders = sorted(glob.glob(cfg["run_dir"] + "/mnist_*"))
assert len(folders) == 1, \
    f"[{lane}] auto-resume must reuse the run folder, found {folders}"
rows = [json.loads(l) for l in open(folders[0] + "/metrics.jsonl")]
total = cfg["async_steps"] if lane == "async" else cfg["epochs"]
eps = [r["epoch"] for r in rows]
assert eps == list(range(1, total + 1)), \
    f"[{lane}] expected steps 1..{total} exactly once across resumes, got {eps}"
for r in rows:
    assert math.isfinite(r["global_acc"]) and math.isfinite(r["global_loss"]), \
        f"[{lane}] non-finite global metrics: {r}"
    if lane == "async":
        assert r["mode"] == "async", r
from dba_mod_tpu import checkpoint as ckpt
ok, reason = ckpt.verify_checkpoint(folders[0] + "/model_last.pt.tar")
assert ok, f"[{lane}] final checkpoint failed verification: {reason}"
degraded = sum(bool(r.get("degraded")) for r in rows)
retried = sum(int(r.get("n_retries", 0)) for r in rows)
quar = sum(int(r.get("n_quarantined", 0)) for r in rows)
print(f"chaos-soak {lane} OK: {total} steps in {folders[0]} "
      f"({degraded} degraded, {retried} retries, {quar} quarantined), "
      "final checkpoint verified")
EOF
done
echo "chaos-soak OK: lanes [$LANES] survived the schedule"
