#!/usr/bin/env bash
# make crash-smoke: launch the tiny crash-smoke run, SIGTERM it once two
# rounds have committed, assert the graceful-stop exit code (75) and a
# verified checkpoint, relaunch with --resume auto, and assert the resumed
# run completes the SAME run folder with no duplicate rounds.
# See README "Crash & preemption tolerance".
set -euo pipefail
cd "$(dirname "$0")/.."

CFG=configs/crash_smoke_params.yaml
RUN_DIR=$(python -c "import yaml; print(yaml.safe_load(open('$CFG'))['run_dir'])")
rm -rf "$RUN_DIR"

env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train --params "$CFG" &
PID=$!
trap 'kill "$PID" 2>/dev/null || true' EXIT

# wait for >= 2 committed rounds (round_result.csv data rows), then SIGTERM
for _ in $(seq 1 600); do
  # `|| true`: the CSV does not exist until the first round lands, and a
  # failing `cat` inside $() would trip set -e/pipefail
  n=$({ cat "$RUN_DIR"/mnist_*/round_result.csv 2>/dev/null || true; } \
      | tail -n +2 | wc -l)
  [ "${n:-0}" -ge 2 ] && break
  kill -0 "$PID" 2>/dev/null || break   # finished before we could signal
  sleep 0.5
done
if [ "${n:-0}" -lt 2 ] && kill -0 "$PID" 2>/dev/null; then
  # fail fast with the real cause: on a box this slow the resume leg
  # would find no verified checkpoint and the folder-count assertion
  # below would misreport a crash-tolerance regression
  echo "crash-smoke: no 2 committed rounds within the wait budget" >&2
  kill -9 "$PID" 2>/dev/null || true
  exit 1
fi
kill -TERM "$PID" 2>/dev/null || true
set +e; wait "$PID"; rc=$?; set -e
echo "crash-smoke: first run exited rc=$rc"
# 75 = EXIT_INTERRUPTED (graceful stop); 0 = the box outran the signal
if [ "$rc" -ne 75 ] && [ "$rc" -ne 0 ]; then
  echo "crash-smoke: unexpected exit code $rc" >&2
  exit 1
fi

env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train --params "$CFG" \
  --resume auto

python - "$CFG" <<'EOF'
import glob, json, sys, yaml
cfg = yaml.safe_load(open(sys.argv[1]))
folders = sorted(glob.glob(cfg["run_dir"] + "/mnist_*"))
assert len(folders) == 1, \
    f"auto-resume must reuse the run folder, found {folders}"
rows = [json.loads(l) for l in open(folders[0] + "/metrics.jsonl")]
eps = [r["epoch"] for r in rows]
assert eps == list(range(1, cfg["epochs"] + 1)), \
    f"expected rounds 1..{cfg['epochs']} exactly once, got {eps}"
from dba_mod_tpu import checkpoint as ckpt
ok, reason = ckpt.verify_checkpoint(folders[0] + "/model_last.pt.tar")
assert ok, f"final checkpoint failed verification: {reason}"
print(f"crash-smoke OK: {len(eps)} rounds in {folders[0]}, "
      "final checkpoint verified")
EOF
