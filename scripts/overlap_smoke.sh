#!/usr/bin/env bash
# make overlap-smoke: prove the round-pipelining bit-identity contract
# through the real CLI. Derives four lanes from
# configs/overlap_smoke_params.yaml — the lockstep engine and the
# buffered-async engine, each with overlap_eval off and on — runs each
# end-to-end, and asserts the canonical run outputs (metrics.jsonl +
# every recorder CSV, wall-clock columns stripped) are BYTE-IDENTICAL
# off vs on for both engines. See README "Round pipelining".
set -euo pipefail
cd "$(dirname "$0")/.."

BASE=configs/overlap_smoke_params.yaml
OUT=runs/overlap_smoke
rm -rf "$OUT"
mkdir -p "$OUT"

python - "$BASE" "$OUT" <<'EOF'
import sys
import yaml

base = yaml.safe_load(open(sys.argv[1]))
out = sys.argv[2]
ASYNC = dict(mode="async", buffer_k=3, staleness_weighting="polynomial",
             staleness_alpha=0.5, arrival_rate=3.0, arrival_jitter=0.7,
             straggler_tail=0.25, straggler_factor=6.0, async_steps=4)
lanes = {
    "sync_off": dict(overlap_eval=False),
    "sync_on": dict(overlap_eval=True),
    "async_off": dict(ASYNC, overlap_eval=False),
    "async_on": dict(ASYNC, overlap_eval=True),
}
for name, over in lanes.items():
    cfg = dict(base, **over, run_dir=f"{out}/{name}")
    with open(f"{out}/{name}_params.yaml", "w") as f:
        yaml.safe_dump(cfg, f)
EOF

for lane in sync_off sync_on async_off async_on; do
  echo "overlap-smoke: running lane $lane"
  env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train \
    --params "$OUT/${lane}_params.yaml"
done

python - "$OUT" <<'EOF'
import glob
import sys

from dba_mod_tpu.utils.recorder import canonical_run_outputs

out = sys.argv[1]


def folder(lane):
    fs = sorted(glob.glob(f"{out}/{lane}/mnist_*"))
    assert len(fs) == 1, f"expected one run folder for {lane}, got {fs}"
    return fs[0]


for eng in ("sync", "async"):
    off = canonical_run_outputs(folder(f"{eng}_off"))
    on = canonical_run_outputs(folder(f"{eng}_on"))
    assert off, f"{eng}: no recorded outputs found"
    assert off.keys() == on.keys(), \
        f"{eng}: artifact sets differ: {sorted(off)} vs {sorted(on)}"
    for k in sorted(off):
        assert off[k] == on[k], \
            f"{eng}: {k} differs between overlap_eval off and on"
    print(f"overlap-smoke {eng} OK: {len(off)} canonical artifacts "
          "byte-identical (overlap_eval on vs off)")
EOF
