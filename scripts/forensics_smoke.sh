#!/usr/bin/env bash
# make forensics-smoke: run the tiny FoolsGold sybil config with
# `forensics: true`, assert the two forensic artifacts stream into the run
# folder with the pinned schema, render the HTML round-audit via the
# `report` subcommand, and assert the report is a self-contained document
# with the suspicion table and SVG timelines. See README "Defense
# forensics".
set -euo pipefail
cd "$(dirname "$0")/.."

CFG=configs/forensics_smoke_params.yaml
RUN_DIR=$(python -c "import yaml; print(yaml.safe_load(open('$CFG'))['run_dir'])")
rm -rf "$RUN_DIR"

env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train --params "$CFG"

FOLDER=$(ls -d "$RUN_DIR"/mnist_* | head -n 1)
test -s "$FOLDER/forensics.jsonl"
test -s "$FOLDER/client_forensics.csv"

env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main report --run "$FOLDER"

python - "$FOLDER" <<'EOF'
import csv, json, sys
from pathlib import Path
from dba_mod_tpu.utils.forensics import FORENSICS_HEADER

folder = Path(sys.argv[1])
rows = list(csv.reader(open(folder / "client_forensics.csv")))
assert rows[0] == FORENSICS_HEADER, f"schema drift: {rows[0]}"
assert len(rows) > 1, "no per-client forensic rows"
rounds = [json.loads(l)
          for l in (folder / "forensics.jsonl").read_text().splitlines()]
assert rounds and all(r["aggregation"] == "foolsgold" for r in rounds)
recs = [dict(zip(rows[0], r)) for r in rows[1:]]
att = [float(r["agg_weight"]) for r in recs if r["adversary"] == "1"]
ben = [float(r["agg_weight"]) for r in recs if r["adversary"] == "0"]
att_m, ben_m = sum(att) / len(att), sum(ben) / len(ben)
assert att_m < ben_m - 0.3, \
    f"sybils not punished: attacker weight {att_m:.3f} vs benign {ben_m:.3f}"
html = (folder / "forensics_report.html").read_text()
assert "<!DOCTYPE html>" in html and "<svg" in html and "suspicion" in html
print(f"forensics-smoke OK: {len(rows) - 1} client rows over "
      f"{len(rounds)} rounds in {folder}; attacker weight {att_m:.3f} "
      f"< benign {ben_m:.3f}; report rendered ({len(html)} bytes)")
EOF
