#!/usr/bin/env bash
# make elastic-smoke: the elastic multi-host drill (README "Elastic
# multi-host"). Launch a 2-process jax.distributed run (2 x 4 virtual CPU
# devices = one 8-device clients mesh), SIGKILL worker 1 once two rounds
# have committed, assert the SURVIVOR exits 77 (EXIT_PEER_LOST — peer
# classified gone, not slow) with a verified checkpoint on disk, then
# relaunch the survivors SHRUNK (JAX_NUM_PROCESSES=1) with --resume auto
# and assert the experiment completes in the same run folder with every
# round recorded exactly once. This script is also the reference
# supervisor recipe for production wrappers.
set -euo pipefail
cd "$(dirname "$0")/.."

CFG=configs/elastic_smoke_params.yaml
RUN_DIR=$(python -c "import yaml; print(yaml.safe_load(open('$CFG'))['run_dir'])")
EPOCHS=$(python -c "import yaml; print(yaml.safe_load(open('$CFG'))['epochs'])")
rm -rf "$RUN_DIR"
PORT=$(python -c "import socket; s=socket.socket(); s.bind(('127.0.0.1',0)); print(s.getsockname()[1]); s.close()")

LOG0=$(mktemp /tmp/elastic_smoke_p0.XXXXXX.log)
LOG1=$(mktemp /tmp/elastic_smoke_p1.XXXXXX.log)

launch_worker() {  # $1 = process id. exec: $! must be the python PID
  # itself (killing a wrapper subshell would orphan the worker alive)
  exec env JAX_PLATFORMS=cpu \
      XLA_FLAGS="--xla_force_host_platform_device_count=4" \
      JAX_COORDINATOR_ADDRESS="127.0.0.1:$PORT" \
      JAX_NUM_PROCESSES=2 JAX_PROCESS_ID="$1" \
      python -m dba_mod_tpu.main train --params "$CFG"
}

launch_worker 0 >"$LOG0" 2>&1 &
PID0=$!
launch_worker 1 >"$LOG1" 2>&1 &
PID1=$!
trap 'kill -9 "$PID0" "$PID1" 2>/dev/null || true' EXIT

# wait for >= 2 committed rounds, then SIGKILL worker 1 (no handlers, no
# cleanup — the real preemption shape)
n=0
for _ in $(seq 1 900); do
  n=$({ cat "$RUN_DIR"/elastic/round_result.csv 2>/dev/null || true; } \
      | tail -n +2 | wc -l)
  [ "${n:-0}" -ge 2 ] && break
  if ! kill -0 "$PID0" 2>/dev/null || ! kill -0 "$PID1" 2>/dev/null; then
    echo "elastic-smoke: a worker died before the kill landed" >&2
    tail -n 40 "$LOG0" "$LOG1" >&2
    exit 1
  fi
  sleep 0.5
done
if [ "${n:-0}" -lt 2 ]; then
  echo "elastic-smoke: no 2 committed rounds within the wait budget" >&2
  tail -n 40 "$LOG0" "$LOG1" >&2
  exit 1
fi
echo "elastic-smoke: $n rounds committed — SIGKILL worker 1"
kill -9 "$PID1" 2>/dev/null || true

# the survivor must exit 77 (EXIT_PEER_LOST) on its own — bounded by the
# watchdog hard limit, never a hang
set +e; wait "$PID0"; rc0=$?; set -e
wait "$PID1" 2>/dev/null || true
echo "elastic-smoke: survivor exited rc=$rc0"
if [ "$rc0" -ne 77 ]; then
  echo "elastic-smoke: expected the peer-lost exit code 77, got $rc0" >&2
  tail -n 60 "$LOG0" >&2
  exit 1
fi

# a verified checkpoint must be on disk — the shrunk relaunch's resume
# point. The peer can die MID-SAVE (force=True already deleted the
# previous model_last); the .prev protection layer guarantees a verified
# fallback survives that exact race, so assert via the same discovery the
# resume uses, not one hardcoded path.
python - "$RUN_DIR" <<'EOF'
import sys
from dba_mod_tpu import checkpoint as ckpt
hit = ckpt.latest_verified_checkpoint(sys.argv[1] + "/elastic",
                                      quarantine=False)
assert hit is not None, "no verified checkpoint survived the peer loss"
print(f"elastic-smoke: verified resume point {hit.name} "
      f"(epoch {ckpt.manifest_epoch(hit)})")
EOF

# relaunch the survivors SHRUNK: one process, 4 devices, same config, same
# run folder — --resume auto continues the recorder stream
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    python -m dba_mod_tpu.main train --params "$CFG" --resume auto

python - "$RUN_DIR" "$EPOCHS" <<'EOF'
import glob, json, sys
run_dir, epochs = sys.argv[1], int(sys.argv[2])
folders = sorted(glob.glob(run_dir + "/*"))
folders = [f for f in folders if not f.endswith("_peers")]
assert folders == [run_dir + "/elastic"], \
    f"shrunk relaunch must reuse the run folder, found {folders}"
rows = [json.loads(l) for l in open(folders[0] + "/metrics.jsonl")]
eps = [r["epoch"] for r in rows]
assert eps == list(range(1, epochs + 1)), \
    f"expected rounds 1..{epochs} exactly once, got {eps}"
from dba_mod_tpu import checkpoint as ckpt
ok, reason = ckpt.verify_checkpoint(folders[0] + "/model_last.pt.tar")
assert ok, f"final checkpoint failed verification: {reason}"
print(f"elastic-smoke OK: {len(eps)} rounds in {folders[0]}, survivor "
      "exit 77, shrunk relaunch completed, final checkpoint verified")
EOF
