#!/usr/bin/env bash
# make async-smoke: run the tiny buffered-async config (mode: async,
# 4-client cohorts, merge every 2 arrivals, stragglers + staleness
# weighting), SIGTERM it once three merges have committed (graceful stop
# flushes the partial buffer and checkpoints the streaming state), relaunch
# with --resume auto, and assert the SAME run folder ends with merges 1..8
# exactly once, every row carrying the async extras, and a verified final
# checkpoint. See README "Asynchronous federation".
set -euo pipefail
cd "$(dirname "$0")/.."

CFG=configs/async_smoke_params.yaml
RUN_DIR=$(python -c "import yaml; print(yaml.safe_load(open('$CFG'))['run_dir'])")
rm -rf "$RUN_DIR"

env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train --params "$CFG" &
PID=$!
trap 'kill "$PID" 2>/dev/null || true' EXIT

# wait for >= 3 committed merges (metrics.jsonl rows), then SIGTERM
for _ in $(seq 1 600); do
  n=$({ cat "$RUN_DIR"/mnist_*/metrics.jsonl 2>/dev/null || true; } | wc -l)
  [ "${n:-0}" -ge 3 ] && break
  kill -0 "$PID" 2>/dev/null || break   # finished before we could signal
  sleep 0.5
done
if [ "${n:-0}" -lt 3 ] && kill -0 "$PID" 2>/dev/null; then
  echo "async-smoke: no 3 committed merges within the wait budget" >&2
  kill -9 "$PID" 2>/dev/null || true
  exit 1
fi
kill -TERM "$PID" 2>/dev/null || true
set +e; wait "$PID"; rc=$?; set -e
echo "async-smoke: first run exited rc=$rc"
# 75 = EXIT_INTERRUPTED (graceful stop); 0 = the box outran the signal
if [ "$rc" -ne 75 ] && [ "$rc" -ne 0 ]; then
  echo "async-smoke: unexpected exit code $rc" >&2
  exit 1
fi

env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main train --params "$CFG" \
  --resume auto

python - "$CFG" <<'EOF'
import glob, json, sys, yaml
cfg = yaml.safe_load(open(sys.argv[1]))
folders = sorted(glob.glob(cfg["run_dir"] + "/mnist_*"))
assert len(folders) == 1, \
    f"auto-resume must reuse the run folder, found {folders}"
rows = [json.loads(l) for l in open(folders[0] + "/metrics.jsonl")]
steps = [r["epoch"] for r in rows]
total = cfg["async_steps"]
assert steps == list(range(1, total + 1)), \
    f"expected aggregation steps 1..{total} exactly once, got {steps}"
K = cfg["buffer_k"]
for r in rows:
    assert r["mode"] == "async", r
    assert 1 <= r["buffer_occupancy"] <= K, r
    assert r["staleness_max"] >= r["staleness_mean"] >= 0, r
assert rows[-1]["waves_dispatched"] >= total * K // cfg["no_models"]
from dba_mod_tpu import checkpoint as ckpt
ok, reason = ckpt.verify_checkpoint(folders[0] + "/model_last.pt.tar")
assert ok, f"final checkpoint failed verification: {reason}"
aux = ckpt.load_aux_state(folders[0] + "/model_last.pt.tar")
assert aux is not None and "async_state" in aux, \
    "streaming state missing from the aux sidecar"
stale = [r["staleness_max"] for r in rows]
print(f"async-smoke OK: {len(steps)} merges in {folders[0]} "
      f"(buffer_k={K}, max staleness {max(stale):.0f}, "
      f"{rows[-1]['waves_dispatched']} waves, "
      f"{rows[-1]['arrivals_total']} arrivals), final checkpoint verified "
      "with streaming sidecar")
EOF
