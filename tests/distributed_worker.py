"""Worker process for the 2-process jax.distributed test (run by
tests/test_multihost.py, one invocation per process). Bootstraps a
2-process × 4-virtual-CPU-device runtime — 8 global devices — and runs one
sharded FL round through the standard Experiment driver; the multi-host
path is exactly the single-host one plus `initialize_distributed()` (called
by Experiment.__init__ from env vars) and per-process input placement
(parallel/mesh.py::_place)."""
import os
import sys


def main():
    process_id = int(sys.argv[1])
    coordinator = sys.argv[2]
    # optional aggregation rule (default FedAvg); "geom_median" exercises
    # RFA's per-iteration Weiszfeld distance collectives across the
    # process boundary (DCN path)
    method = sys.argv[3] if len(sys.argv) > 3 else "mean"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_COORDINATOR_ADDRESS"] = coordinator
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(process_id)
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dba_mod_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()

    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment

    params = Params.from_dict(dict(
        type="mnist", lr=0.1, batch_size=8, epochs=2, no_models=8,
        number_of_total_participants=8, eta=0.8,
        aggregation_methods=method, internal_epochs=1,
        internal_poison_epochs=2, is_poison=True, synthetic_data=True,
        synthetic_train_size=128, synthetic_test_size=64, momentum=0.9,
        decay=0.0005, sampling_dirichlet=False, local_eval=True,
        poison_label_swap=2, poisoning_per_batch=4, poison_lr=0.05,
        scale_weights_poison=2.0, adversary_list=[0], trigger_num=1,
        alpha_loss=1.0, random_seed=1, num_devices=-1,
        **{"0_poison_pattern": [[0, 0], [0, 1]],
           "0_poison_epochs": [1, 2]}))
    exp = Experiment(params, save_results=False)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8 and jax.local_device_count() == 4
    assert exp.mesh is not None and exp.mesh.devices.size == 8
    r = exp.run_round(1)
    # both processes print identical results (replicated payload)
    print(f"RESULT {process_id} acc={r['global_acc']:.6f} "
          f"backdoor={r['backdoor_acc']:.6f}", flush=True)


if __name__ == "__main__":
    main()
