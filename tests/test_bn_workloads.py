"""FL-round coverage for the BatchNorm workloads (CIFAR / Tiny ResNets) —
SURVEY §7.2.2's #2-ranked hard part: `batch_stats` must thread through the
client scan (fl/client.py), scale in the model-replacement epilogue
(image_train.py:166-171 scales the state_dict, BN buffers included), aggregate
under FedAvg (helper.py:240-257 iterates the full state), and stay untouched
by FoolsGold (helper.py:286-290 steps named_parameters only).

Synthetic CIFAR-shaped data keeps this runnable in the zero-egress image; the
first run pays ResNet compiles (cached via conftest's persistent cache)."""
import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

CIFAR = dict(
    type="cifar", lr=0.1, batch_size=8, epochs=7, no_models=3,
    number_of_total_participants=6, eta=0.8, aggregation_methods="mean",
    internal_epochs=2, internal_poison_epochs=4, is_poison=True,
    synthetic_data=True, synthetic_train_size=288, synthetic_test_size=64,
    momentum=0.9, decay=0.0005, sampling_dirichlet=False, local_eval=True,
    # scale = no_models/eta = exact model replacement (global ← adversary)
    poison_label_swap=2, poisoning_per_batch=6, poison_lr=0.05,
    scale_weights_poison=3.75, adversary_list=[0], trigger_num=1,
    alpha_loss=1.0, random_seed=1,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3],
                            [0, 4], [0, 5]],
       "0_poison_epochs": [4, 5, 6, 7]})


def _bn_flat(e):
    return np.concatenate([np.asarray(l, np.float64).ravel() for l in
                           jax.tree_util.tree_leaves(
                               e.global_vars.batch_stats)])


def test_cifar_fedavg_round_aggregates_batch_stats():
    """A clean round must move the global BN running stats (clients saw real
    batches → nonzero means) and keep training finite."""
    e = Experiment(Params.from_dict(dict(CIFAR, is_poison=False,
                                         local_eval=False)),
                   save_results=False)
    bn0 = _bn_flat(e)
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])
    bn1 = _bn_flat(e)
    assert np.abs(bn1 - bn0).max() > 1e-4, "BN stats did not aggregate"
    assert np.isfinite(bn1).all()
    # second round chains on the aggregated stats
    r2 = e.run_round(2)
    assert np.isfinite(r2["global_acc"])


def test_cifar_backdoor_plants_with_bn_scaling():
    """Distributed backdoor on the BN model: model replacement (scale=4,
    full-state epilogue incl. BN — fl/client.py:148-152) must plant the
    trigger within the poison window."""
    e = Experiment(Params.from_dict(CIFAR), save_results=False)
    out = {}
    for i in range(1, 8):
        out[i] = e.run_round(i)
        assert np.isfinite(out[i]["global_acc"])
    # clean phase learns real class structure through the BN model
    assert out[3]["global_acc"] > 20.0, out
    # the adversary's PRE-SCALE local model plants the trigger every poison
    # round (posiontest rows [name, epoch, loss, acc, correct, count];
    # pre-scale row precedes the post-scale row — image_train.py:157-164)
    pre_rows = {}
    for r in e.recorder.posiontest_result:
        if r[0] == 0 and r[1] not in pre_rows:
            pre_rows[r[1]] = r[3]
    assert set(pre_rows) == {4, 5, 6, 7}
    # trajectories on this tiny synthetic config are compiler-sensitive
    # (f32 reassociation); the mechanism bound is: trigger planted locally
    # every poison round, near-perfectly in at least one
    assert all(acc > 70.0 for acc in pre_rows.values()), pre_rows
    assert max(pre_rows.values()) > 95.0, pre_rows
    # and model replacement carries it into the global model within the
    # window (exact replacement on 3-client rounds whipsaws tiny synthetic
    # models round-to-round, so assert the window, not one fixed round)
    assert max(out[i]["backdoor_acc"] for i in (4, 5, 6, 7)) > 70.0, out
    # BN state stayed finite through poison training + scaling + FedAvg
    assert np.isfinite(_bn_flat(e)).all()


def test_bn_scaling_epilogue_scales_linearly():
    """w ← w_a + γ(w − w_a) over the FULL state: with identical RNG, the
    global BN delta under scale γ=4 is 4× the γ=1 delta (FedAvg is linear in
    the client delta — helper.py:240-257, image_train.py:166-171)."""
    deltas = {}
    for scale in (1.0, 4.0):
        e = Experiment(Params.from_dict(
            dict(CIFAR, scale_weights_poison=scale, local_eval=False,
                 # every selected client poisons epoch 2 → whole round scaled
                 adversary_list=[0], no_models=1,
                 number_of_total_participants=3)),
            save_results=False)
        bn0 = _bn_flat(e)
        e.run_round(4)  # poison epoch for adversary 0
        deltas[scale] = _bn_flat(e) - bn0
    ratio = (np.linalg.norm(deltas[4.0]) /
             max(np.linalg.norm(deltas[1.0]), 1e-12))
    assert ratio == pytest.approx(4.0, rel=1e-3), ratio


def test_foolsgold_leaves_bn_untouched():
    """FoolsGold aggregates trainable params only (helper.py:286-290): the
    global batch_stats must be BIT-identical after the round while params
    move (fl/rounds.py:184-187)."""
    e = Experiment(Params.from_dict(dict(CIFAR,
                                         aggregation_methods="foolsgold",
                                         local_eval=False)),
                   save_results=False)
    bn0 = _bn_flat(e)
    p0 = np.asarray(jax.tree_util.tree_leaves(e.global_vars.params)[0]).copy()
    e.run_round(4)
    np.testing.assert_array_equal(bn0, _bn_flat(e))
    p1 = np.asarray(jax.tree_util.tree_leaves(e.global_vars.params)[0])
    assert np.abs(p1 - p0).max() > 0


def test_tiny_imagenet_round_smoke():
    """Tiny ResNet-18 (imagenet stem, 200 classes) through one FL round."""
    cfg = dict(type="tiny-imagenet-200", lr=0.05, batch_size=4, epochs=1,
               no_models=2, number_of_total_participants=4, eta=0.8,
               aggregation_methods="mean", internal_epochs=1,
               is_poison=False, synthetic_data=True,
               synthetic_train_size=32, synthetic_test_size=16,
               momentum=0.9, decay=0.0005, sampling_dirichlet=False,
               local_eval=False, random_seed=1)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])
    assert np.isfinite(_bn_flat(e)).all()
