"""Grouped-layout client path (models/grouped.py, fl/grouped_client.py)
equals the vmapped path.

Both paths lower the stacked per-client convs to the same grouped
convolutions; the grouped path removes vmap's per-conv layout moves
(TRAIN_FLOOR.md). Per-client math is identical, so agreement bars:

- one forward pass: tight (≤5e-5 — last-ulp conv summation only);
- a full round's deltas: chaos envelope (ReLU gate flips amplify last-ulp
  conv differences across ~80 SGD steps — the same measured behavior as the
  cross-framework A/B, PARITY_AB.md), with accuracies equal exactly.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment
from dba_mod_tpu.models import build_model
from dba_mod_tpu.models.grouped import (conv_layout_in, grouped_train_apply,
                                        supports_grouped)

CIFAR_CFG = dict(
    type="cifar", lr=0.1, batch_size=8, epochs=2, no_models=4,
    number_of_total_participants=8, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, internal_poison_epochs=2, is_poison=True,
    synthetic_data=True, synthetic_train_size=128, synthetic_test_size=64,
    momentum=0.9, decay=0.0005, sampling_dirichlet=False, local_eval=True,
    poison_label_swap=2, poisoning_per_batch=4, poison_lr=0.05,
    scale_weights_poison=2.0, adversary_list=[0], trigger_num=1,
    alpha_loss=1.0, random_seed=1,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2]],
       "0_poison_epochs": [1, 2]})


def _max_leaf_diff(a, b):
    return max(float(np.abs(np.asarray(x) - np.asarray(y)).max())
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


@pytest.mark.parametrize("mtype", ["cifar", "tiny-imagenet-200"])
def test_grouped_forward_matches_vmapped(mtype):
    """grouped_train_apply == vmap(model.apply) on one train-mode batch —
    logits and new BN stats (both stems, incl. the 7×7/maxpool one)."""
    cfg = dict(CIFAR_CFG, type=mtype)
    if mtype != "cifar":
        cfg.update(synthetic_train_size=64, synthetic_test_size=32)
    p = Params.from_dict(cfg)
    md = build_model(p)
    assert supports_grouped(md)
    C, B = 3, 4
    keys = jax.random.split(jax.random.key(0), C)
    mvs = [md.init_vars(k) for k in keys]
    stack = lambda *ls: jnp.stack(ls)
    params = jax.tree_util.tree_map(stack, *[m.params for m in mvs])
    bn = jax.tree_util.tree_map(stack, *[m.batch_stats for m in mvs])
    hw = md.input_shape[0]
    x = jax.random.uniform(jax.random.key(1), (C, B, hw, hw, 3))

    from dba_mod_tpu.models import ModelVars
    logits_v, bn_v = jax.vmap(
        lambda pp, bb, xx: md.apply(ModelVars(pp, bb), xx, train=True))(
            params, bn, x)
    logits_g, bn_g = jax.jit(
        lambda pp, bb, xx: grouped_train_apply(md, conv_layout_in(pp), bb,
                                               xx))(params, bn, x)
    # last-ulp conv-summation differences only; the wider tiny net doubles
    # the envelope (same ×2 scaling as the torch A/B, PARITY_AB.md)
    assert _max_leaf_diff(logits_v, logits_g) <= 5e-5
    assert _max_leaf_diff(bn_v, bn_g) <= 5e-5


def _round_pair(cfg):
    ev = Experiment(Params.from_dict(dict(cfg, grouped_clients=False)),
                    save_results=False)
    eg = Experiment(Params.from_dict(dict(cfg, grouped_clients=True)),
                    save_results=False)
    assert eg.engine.use_grouped and not ev.engine.use_grouped
    return ev, eg


def test_grouped_round_matches_vmapped_cifar():
    ev, eg = _round_pair(CIFAR_CFG)
    rv, rg = ev.run_round(1), eg.run_round(1)
    # accuracies are discrete — chaos-envelope differences must not move them
    assert rv["global_acc"] == rg["global_acc"]
    assert rv["backdoor_acc"] == rg["backdoor_acc"]
    assert _max_leaf_diff(ev.global_vars.params, eg.global_vars.params) < 5e-4
    assert _max_leaf_diff(ev.global_vars.batch_stats,
                          eg.global_vars.batch_stats) < 1e-4


def test_grouped_round_foolsgold_blended_loss():
    """FoolsGold grads accumulation + the α<1 distance-loss branch through
    the grouped path: wv rows and the similarity feature agree."""
    cfg = dict(CIFAR_CFG, aggregation_methods="foolsgold", alpha_loss=0.9)
    ev, eg = _round_pair(cfg)
    rv, rg = ev.run_round(1), eg.run_round(1)
    assert rv["global_acc"] == rg["global_acc"]
    wv_v = ev.recorder.weight_result[1]
    wv_g = eg.recorder.weight_result[1]
    # FoolsGold's logit reweighting amplifies the round's chaos envelope
    # (cosine similarities of grads accumulated over ~32 chaotic SGD steps);
    # observed ~3e-3 — a real mapping bug shows as O(1) disagreement
    np.testing.assert_allclose(wv_v, wv_g, atol=2e-2)
    assert _max_leaf_diff(ev.fg_state.memory, eg.fg_state.memory) < 2e-2


def test_grouped_gating():
    """Default OFF (measured perf-neutral — TRAIN_FLOOR.md round-5 section);
    explicit grouped_clients=true on an unsupported config is loud."""
    e = Experiment(Params.from_dict(dict(CIFAR_CFG)), save_results=False)
    assert not e.engine.use_grouped
    with pytest.raises(ValueError, match="grouped_clients"):
        Experiment(Params.from_dict(dict(
            CIFAR_CFG, type="mnist", synthetic_train_size=64,
            grouped_clients=True)), save_results=False)
    with pytest.raises(ValueError, match="grouped_clients"):
        Experiment(Params.from_dict(dict(CIFAR_CFG, no_models=8,
                                         num_devices=8,
                                         grouped_clients=True)),
                   save_results=False)
