"""Round pipelining (overlap_eval — README "Round pipelining"): the
bit-identity contract of the split-phase sync round and the pipelined async
merge. Overlap ON must record byte-identical outputs (modulo the wall-clock
VOLATILE_KEYS) to the serial path on every lane — plain, robust retry,
health sentinel, and across a kill/--resume auto boundary — and overlap OFF
(the default) must be a strict no-op. The multi-lane and resume rehearsals
are slow-marked; tier 1 keeps one fast A/B per engine plus the contract
guards."""
import json
from pathlib import Path

import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment
from dba_mod_tpu.utils.recorder import VOLATILE_KEYS, canonical_run_outputs

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=3, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=True, random_seed=1)

RECORDER_LISTS = ("train_result", "test_result", "posiontest_result",
                  "poisontriggertest_result", "weight_result",
                  "scale_temp_one_row", "scale_result")


def _run(cfg, **over):
    e = Experiment(Params.from_dict(dict(cfg, **over)), save_results=False)
    e.run()
    return e


def _rows(e):
    return [{k: v for k, v in r.items() if k not in VOLATILE_KEYS}
            for r in e.recorder._jsonl_rows]


def _bitwise_equal(a, b):
    la, lb = (jax.tree_util.tree_leaves(t) for t in (a, b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
        for x, y in zip(la, lb))


def _assert_ab(off, on):
    assert _rows(off) == _rows(on)
    for name in RECORDER_LISTS:
        assert getattr(off.recorder, name) == getattr(on.recorder, name), \
            f"recorder.{name} differs under overlap_eval"
    assert _bitwise_equal(off.global_vars, on.global_vars)


# ------------------------------------------------------------ sync engine
def test_sync_overlap_bit_identical():
    """The tentpole contract: the split core + overlapped batteries record
    the same stream as the fused serial round, bit for bit."""
    off = _run(BASE, epochs=2)
    on = _run(BASE, epochs=2, overlap_eval=True)
    _assert_ab(off, on)
    assert on._overlap and on._overlap_rounds == 2


@pytest.mark.slow
def test_sync_overlap_robust_retry_lane():
    """Fault-injected + screened rounds retry inside the core program; the
    re-dispatched train deltas are identical per epoch, so the single eval
    dispatch after acceptance stays bit-identical — and a retry 'cancels'
    cleanly (no battery is ever in flight for a rejected attempt)."""
    cfg = dict(BASE, fault_injection=True, fault_corrupt_prob=0.4,
               screen_updates=True, fault_seed=7)
    _assert_ab(_run(cfg), _run(cfg, overlap_eval=True))


@pytest.mark.slow
def test_sync_overlap_sentinel_rollback_lane():
    """The health sentinel observes round N's merged model BEFORE round
    N+1's commit: a tight band forces rollbacks, and the rolled-back global
    battery (evaluated on the rollback target) must match the serial path
    exactly, degraded column included."""
    cfg = dict(BASE, epochs=4, model_health_check=True, health_norm_band=1e-9,
               rollback_ring=2, health_warmup_merges=1)
    off, on = _run(cfg), _run(cfg, overlap_eval=True)
    _assert_ab(off, on)
    degraded = [r["degraded"] for r in off.recorder._jsonl_rows]
    assert any(degraded)            # the lane actually exercised a rollback


@pytest.mark.slow
def test_sync_overlap_poison_lane():
    """Backdoor run: seg-epoch local batteries, poison/trigger rows, and
    the scale stream all ride the overlapped path bit-identically."""
    cfg = dict(BASE, epochs=2, internal_poison_epochs=2, is_poison=True,
               poison_label_swap=2, poisoning_per_batch=8, poison_lr=0.05,
               scale_weights_poison=4.0, adversary_list=[0, 1],
               trigger_num=2, alpha_loss=1.0,
               **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
                  "1_poison_pattern": [[3, 0], [3, 1], [3, 2], [3, 3]],
                  "0_poison_epochs": [1, 2], "1_poison_epochs": [2]})
    _assert_ab(_run(cfg), _run(cfg, overlap_eval=True))


@pytest.mark.slow
def test_sync_overlap_resume_mid_overlap(tmp_path):
    """kill -9 between rounds of an overlapped run, --resume auto: the
    checkpoint written from dispatch-time capture resumes into a stream
    byte-identical to an uninterrupted SERIAL run (canonical view — wall
    clocks stripped)."""
    cfg = dict(BASE, epochs=5, save_model=True)
    ref = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ref"))), save_results=True)
    ref.run()
    a = Experiment(Params.from_dict(dict(
        cfg, overlap_eval=True, epochs=3,
        run_dir=str(tmp_path / "ab"))), save_results=True)
    a.run()
    folder = a.folder
    del a
    b = Experiment(Params.from_dict(dict(
        cfg, overlap_eval=True, resumed_model="auto",
        run_dir=str(tmp_path / "ab"))), save_results=True)
    assert str(b.folder) == str(folder)
    b.run()
    assert canonical_run_outputs(folder) == canonical_run_outputs(ref.folder)


def test_sync_overlap_telemetry_forces_sequential():
    """Per-phase span attribution is only honest when phases do not
    overlap: with telemetry on the loop runs the split program
    SEQUENTIALLY — still bit-identical, and the overlap/ metric family is
    emitted from the serial clocks."""
    cfg = dict(BASE, epochs=2)
    off = _run(cfg)
    on = _run(cfg, overlap_eval=True, telemetry=True)
    _assert_ab(off, on)
    t = on.telemetry
    assert t.enabled and t.counter("overlap/rounds").value == 2


def test_donated_round_gate_off_on_cpu_and_under_overlap():
    """round_fn donation is only sound when nobody re-reads the donated
    buffers: never on CPU (jit aliasing is unsupported → warning spam),
    never with the sentinel armed (rollback re-reads vars_before), never
    under overlap (the core path owns the buffers)."""
    e = Experiment(Params.from_dict(dict(BASE, epochs=1)),
                   save_results=False)
    assert jax.default_backend() == "cpu"
    assert e.engine.round_fn_donated is None
    assert e._use_donated_round is False


# ----------------------------------------------------------- async engine
def test_async_overlap_bit_identical():
    """Merge pipelining: host finalize of merge S hidden behind step S+1's
    fill/merge — recorded stream and final model bit-identical."""
    cfg = dict(BASE, mode="async", buffer_k=3,
               staleness_weighting="polynomial", staleness_alpha=0.5,
               arrival_rate=3.0, arrival_jitter=0.7, straggler_tail=0.25,
               straggler_factor=6.0, async_steps=4)
    off, on = _run(cfg), _run(cfg, overlap_eval=True)
    _assert_ab(off, on)


@pytest.mark.slow
def test_async_overlap_selfhealing_lane():
    """Deadline merges, TTL expiry, backpressure flushes, and fault retry
    all pipeline bit-identically (deferred wave rows replay in resolution
    order; the sentinel ring commits at dispatch)."""
    cfg = dict(BASE, mode="async", buffer_k=3, async_steps=5,
               arrival_jitter=0.5, fault_injection=True,
               fault_drop_prob=0.2, fault_corrupt_prob=0.3,
               screen_updates=True, fault_seed=7, arrival_ttl_v=2.0,
               merge_timeout_v=1.5, merge_min_k=1, max_outstanding_waves=3,
               starvation_policy="carry")
    _assert_ab(_run(cfg), _run(cfg, overlap_eval=True))
    cfg = dict(BASE, mode="async", buffer_k=3, async_steps=5,
               model_health_check=True, health_norm_band=1.5,
               rollback_ring=2, health_warmup_merges=1)
    _assert_ab(_run(cfg), _run(cfg, overlap_eval=True))


@pytest.mark.slow
def test_async_overlap_resume_mid_overlap(tmp_path):
    """Kill between pipelined merges, --resume auto: the dispatch-time
    snapshot restores heap/buffer/cohorts into a stream byte-identical to
    the uninterrupted serial run."""
    cfg = dict(BASE, epochs=6, save_model=True, mode="async", buffer_k=2,
               arrival_rate=2.0, arrival_jitter=0.6, straggler_tail=0.25,
               straggler_factor=4.0, staleness_weighting="polynomial",
               async_steps=8, random_seed=3)
    ref = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ref"))), save_results=True)
    ref.run()
    a = Experiment(Params.from_dict(dict(
        cfg, overlap_eval=True, async_steps=4,
        run_dir=str(tmp_path / "ab"))), save_results=True)
    a.run()
    folder = a.folder
    del a
    b = Experiment(Params.from_dict(dict(
        cfg, overlap_eval=True, resumed_model="auto",
        run_dir=str(tmp_path / "ab"))), save_results=True)
    assert str(b.folder) == str(folder)
    assert (b._resume_aux or {}).get("async_state") is not None
    b.run()
    assert canonical_run_outputs(folder) == canonical_run_outputs(ref.folder)


def test_async_pipeline_gates():
    """The async pipeline stands down where its contracts cannot hold:
    telemetry's split-phase mode, and the poisoned LOAN probe (whose
    last-finalized-backdoor-acc read would go one merge more stale)."""
    from dba_mod_tpu.fl.async_rounds import AsyncDriver
    e = Experiment(Params.from_dict(dict(
        BASE, mode="async", buffer_k=3, async_steps=2, overlap_eval=True,
        telemetry=True)), save_results=False)
    assert AsyncDriver(e)._pipeline is False
    e2 = Experiment(Params.from_dict(dict(
        BASE, mode="async", buffer_k=3, async_steps=2, overlap_eval=True)),
        save_results=False)
    d = AsyncDriver(e2)
    assert d._pipeline is True
    d.run_steps(2)                  # drains its own in-flight handle
    assert d.stats()["pipelined_merges"] == 2
