"""Defense-forensics layer (utils/forensics.py + the ForensicStats payload
slot in fl/rounds.py).

Coverage:
  1. schema golden — client_forensics.csv column names and per-column dtypes
     are pinned (downstream notebooks parse by name);
  2. strict no-op when off — `forensics: false` writes no forensic files and
     the recorded metrics trajectory is byte-identical to a forensics-on run
     (the flag must not perturb the round math);
  3. screening forensics — injected-fault runs mark quarantined clients with
     verdict 0 and the right reason code, consistent with the round's
     robust counters;
  4. e2e FoolsGold sybil — two adversaries submitting the same trigger get
     measurably lower aggregation weights than benign clients in the
     emitted CSV (the ISSUE acceptance gate);
  5. the `report` renderer produces a self-contained HTML round-audit;
  6. split-dispatch parity — telemetry's per-phase path fills the same
     forensic record via the standalone forensic_fn.

Experiment builds dominate the wall clock here, so the benign-FedAvg and
sybil-FoolsGold runs are module-scoped fixtures shared by every test that
only READS their artifacts.
"""
import csv
import json
import math

import numpy as np
import pytest

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment
from dba_mod_tpu.fl.rounds import REASON_NAMES
from dba_mod_tpu.utils.forensics import FORENSICS_HEADER

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=6, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=1)

# the forensics-smoke geometry: two sybils sharing one trigger pattern and
# schedule with full-poison batches — FoolsGold's detection target.
# internal_poison_epochs kept at 2 (epochs_max sizes the compiled round
# program; 4 triples this module's wall clock for no extra signal).
SYBIL = dict(
    BASE, epochs=3, aggregation_methods="foolsgold", is_poison=True,
    local_eval=True, internal_poison_epochs=2, poisoning_per_batch=16,
    poison_label_swap=2, poison_lr=0.05, scale_weights_poison=1.0,
    adversary_list=[0, 1], trigger_num=2, alpha_loss=1.0,
    is_random_adversary=False, sampling_dirichlet=True, dirichlet_alpha=0.5,
    synthetic_train_size=400, synthetic_test_size=128,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
       "1_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
       "0_poison_epochs": [1, 2, 3], "1_poison_epochs": [1, 2, 3]})


def _run_to_folder(tmp_path, cfg, rounds, sub="run"):
    p = Params.from_dict(dict(cfg, run_dir=str(tmp_path / sub)))
    e = Experiment(p)
    results = [e.run_round(i) for i in range(1, rounds + 1)]
    return e, results


def _read_csv(folder):
    with open(folder / "client_forensics.csv", newline="") as f:
        rows = list(csv.reader(f))
    return rows[0], rows[1:]


@pytest.fixture(scope="module")
def mean_run(tmp_path_factory):
    """Benign FedAvg, forensics on, 2 rounds — shared read-only."""
    tmp = tmp_path_factory.mktemp("forensics_mean")
    return _run_to_folder(tmp, dict(BASE, forensics=True), 2)


@pytest.fixture(scope="module")
def sybil_run(tmp_path_factory):
    """FoolsGold sybil attack, forensics on, 3 rounds — shared read-only."""
    tmp = tmp_path_factory.mktemp("forensics_sybil")
    return _run_to_folder(tmp, dict(SYBIL, forensics=True), 3)


# ------------------------------------------------------------ schema golden
def test_schema_golden(mean_run):
    """Column names and dtypes of client_forensics.csv are STABLE."""
    e, _ = mean_run
    header, rows = _read_csv(e.folder)
    assert header == FORENSICS_HEADER
    assert len(rows) == 2 * 4  # rounds x clients, one row each
    int_cols = ["epoch", "client", "participant_id", "adversary", "verdict"]
    float_cols = ["delta_norm", "recv_norm", "cosine_to_agg", "agg_weight",
                  "fg_max_sim", "rfa_distance", "poison_acc"]
    for row in rows:
        rec = dict(zip(header, row))
        for c in int_cols:
            assert rec[c] == str(int(rec[c])), (c, rec[c])
        for c in float_cols:  # float-typed: blank (n/a) or parseable
            if rec[c] != "":
                float(rec[c])
        assert rec["reason"] in REASON_NAMES.values()
        assert rec["name"] != ""
    # benign FedAvg: every client aggregated, no defense weights, no battery
    for row in rows:
        rec = dict(zip(header, row))
        assert rec["verdict"] == "1" and rec["reason"] == "ok"
        assert rec["agg_weight"] == "" and rec["poison_acc"] == ""


def test_jsonl_round_records(mean_run):
    e, _ = mean_run
    recs = [json.loads(l) for l in
            (e.folder / "forensics.jsonl").read_text().splitlines()]
    assert [r["epoch"] for r in recs] == [1, 2]
    for r in recs:
        assert r["aggregation"] == "mean"
        assert len(r["clients"]) == 4 == len(r["delta_norm"])
        assert r["n_quarantined"] == 0 and not r["degraded"]
        assert r["oracle_calls"] == 1  # no Weiszfeld under FedAvg
        # jsonl must be valid JSON end-to-end: no bare NaN tokens
        assert all(v is None or math.isfinite(v) for v in r["delta_norm"])


# -------------------------------------------------- forensics off: no-op
def test_off_is_strict_noop_and_bit_identical(tmp_path, mean_run):
    """`forensics: false` (the default) writes no forensic files, and the
    flag itself must not perturb the trajectory: recorded metrics from an
    off run and an on run are byte-identical (timing columns excluded)."""
    e_on, r_on = mean_run
    e_off, r_off = _run_to_folder(tmp_path, dict(BASE), 2, "off")
    assert e_off.forensics_writer is None
    assert not (e_off.folder / "forensics.jsonl").exists()
    assert not (e_off.folder / "client_forensics.csv").exists()
    for name in ("train_result.csv", "test_result.csv"):
        assert ((e_off.folder / name).read_bytes()
                == (e_on.folder / name).read_bytes()), name
    assert ([r["global_acc"] for r in r_off]
            == [r["global_acc"] for r in r_on])


# ------------------------------------------------- screening verdict rows
def test_quarantined_clients_marked(tmp_path):
    """Injected NaN payloads: the forensic rows carry verdict 0 with reason
    'nonfinite', consistent with the round's robust counters."""
    e, results = _run_to_folder(
        tmp_path, dict(BASE, forensics=True, fault_injection=True,
                       fault_corrupt_prob=0.4, fault_seed=3), 3)
    header, rows = _read_csv(e.folder)
    recs = [dict(zip(header, r)) for r in rows]
    quarantined = [r for r in recs if r["verdict"] == "0"]
    assert quarantined, "corrupt_prob=0.4 over 3x4 lanes must quarantine"
    assert all(r["reason"] == "nonfinite" for r in quarantined)
    assert (len(quarantined)
            == sum(r["n_quarantined"] for r in results))
    per_epoch = {int(r["epoch"]): 0 for r in recs}
    for r in quarantined:
        per_epoch[int(r["epoch"])] += 1
    for res in results:
        assert per_epoch[res["epoch"]] == res["n_quarantined"]


def test_dropped_clients_marked(tmp_path):
    """Total dropout: every row is verdict 0 / reason 'dropped' and the
    round-level record carries the degradation."""
    e, results = _run_to_folder(
        tmp_path, dict(BASE, forensics=True, fault_injection=True,
                       fault_dropout_prob=1.0), 1)
    header, rows = _read_csv(e.folder)
    recs = [dict(zip(header, r)) for r in rows]
    assert all(r["verdict"] == "0" and r["reason"] == "dropped"
               for r in recs)
    jl = [json.loads(l) for l in
          (e.folder / "forensics.jsonl").read_text().splitlines()]
    assert jl[0]["degraded"] and jl[0]["n_quarantined"] == 4


# ----------------------------------------------------- e2e FoolsGold sybil
def test_foolsgold_sybil_attackers_get_lower_weights(sybil_run):
    """ISSUE acceptance gate: attacker rows in the emitted CSV show
    measurably lower FoolsGold weights than benign rows."""
    e, _ = sybil_run
    header, rows = _read_csv(e.folder)
    recs = [dict(zip(header, r)) for r in rows]
    att = [float(r["agg_weight"]) for r in recs if r["adversary"] == "1"]
    ben = [float(r["agg_weight"]) for r in recs if r["adversary"] == "0"]
    assert att and ben
    assert np.mean(att) < np.mean(ben) - 0.3, (np.mean(att), np.mean(ben))
    # the similarity evidence behind the verdict is recorded too
    sims = [float(r["fg_max_sim"]) for r in recs
            if r["adversary"] == "1" and r["fg_max_sim"] != ""
            and math.isfinite(float(r["fg_max_sim"]))]
    assert max(sims) > 0.9  # sybils are near-identical in feature space
    # poison battery columns populated for the poisoning clients
    assert any(r["poison_acc"] != "" for r in recs)


def test_report_html(sybil_run):
    e, _ = sybil_run
    from dba_mod_tpu.utils.forensics import write_report
    out = write_report(e.folder)
    html = out.read_text()
    assert html.startswith("<!DOCTYPE html>")
    assert "<svg" in html and "suspicion" in html
    assert "foolsgold" in html
    # self-contained: no external fetches (the SVG xmlns URI is a
    # namespace identifier, not a fetch)
    stripped = html.replace("http://www.w3.org/2000/svg", "")
    assert "http://" not in stripped and "https://" not in stripped


# --------------------------------------------- split-dispatch (telemetry)
def test_split_dispatch_fills_forensics(tmp_path):
    """Telemetry's per-phase dispatch path assembles the same forensic
    record via the standalone forensic_fn."""
    e, _ = _run_to_folder(
        tmp_path, dict(BASE, forensics=True, telemetry=True), 2)
    header, rows = _read_csv(e.folder)
    assert len(rows) == 2 * 4
    recs = [dict(zip(header, r)) for r in rows]
    assert all(r["verdict"] == "1" and r["reason"] == "ok" for r in recs)
    assert all(float(r["recv_norm"]) > 0 for r in recs)


def test_in_memory_writer_without_folder():
    """save_results=False (the bench path): rows accumulate in memory, no
    files are written, save() is a no-op."""
    e = Experiment(Params.from_dict(dict(BASE, forensics=True)),
                   save_results=False)
    e.run_round(1)
    w = e.forensics_writer
    assert w is not None and w.folder is None
    assert len(w.rows) == 4 and len(w.round_rows) == 1
