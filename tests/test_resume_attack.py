"""Pretrain → checkpoint → resume → attack, end to end through the CLI —
the reference's canonical flow (image_helper.py:56-67 restores the clean
model, overwrites lr from the checkpoint and continues at saved epoch + 1;
utils/cifar_params.yaml:68-69 points attack configs at the pretrained file)."""
from pathlib import Path

import numpy as np
import pytest
import yaml

import jax

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment
from dba_mod_tpu.main import main

CLEAN = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=2, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=1)


def test_pretrain_resume_attack_e2e(tmp_path, capsys):
    ckdir = tmp_path / "ckpts"
    cfg_clean = dict(CLEAN, checkpoint_dir=str(ckdir))
    clean_yaml = tmp_path / "clean.yaml"
    clean_yaml.write_text(yaml.safe_dump(cfg_clean))

    # 1. CLI pretrain writes the clean checkpoint under checkpoint_dir
    assert main(["pretrain", "--params", str(clean_yaml),
                 "--out", "clean/model.pt.tar"]) == 0
    saved = ckdir / "clean" / "model.pt.tar"
    assert saved.exists()

    # 2. attack config resumes it: lr overwritten from the checkpoint,
    #    start_epoch = saved + 1, weights = the pretrained weights
    cfg_attack = dict(
        CLEAN, checkpoint_dir=str(ckdir), epochs=5, lr=0.9,  # 0.9 must lose
        resumed_model=True, resumed_model_name="clean/model.pt.tar",
        is_poison=True, local_eval=True, internal_poison_epochs=4,
        poison_label_swap=2, poisoning_per_batch=8, poison_lr=0.05,
        scale_weights_poison=4.0, adversary_list=[0], trigger_num=1,
        alpha_loss=1.0,
        **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
           "0_poison_epochs": [3, 4, 5]})
    e = Experiment(Params.from_dict(cfg_attack), save_results=False)
    assert e.start_epoch == 3                       # saved epoch 2 + 1
    assert e.params["lr"] == pytest.approx(0.1)     # checkpoint lr wins

    like = e.model_def.init_vars(jax.random.key(9))
    restored, saved_epoch, saved_lr = ckpt.load_checkpoint(saved, like)
    assert saved_epoch == 2 and saved_lr == pytest.approx(0.1)
    a = jax.tree_util.tree_leaves(e.global_vars.params)[0]
    b = jax.tree_util.tree_leaves(restored.params)[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a fresh init would differ — the resume genuinely loaded weights
    fresh = jax.tree_util.tree_leaves(
        e.model_def.init_vars(jax.random.key(
            int(cfg_attack["random_seed"]))).params)[0]
    assert np.abs(np.asarray(a) - np.asarray(fresh)).max() > 0

    # 3. the attack trains from the pretrained model and plants the backdoor
    out = {}
    for i in range(e.start_epoch, 6):
        out[i] = e.run_round(i)
    assert out[5]["backdoor_acc"] > 80.0
    assert np.isfinite(out[5]["global_acc"])

    # 4. the full CLI train path accepts the same resumed config
    attack_yaml = tmp_path / "attack.yaml"
    attack_yaml.write_text(yaml.safe_dump(cfg_attack))
    assert main(["train", "--params", str(attack_yaml), "--no-save"]) == 0
    assert "final: epoch=5" in capsys.readouterr().out


def test_resume_past_final_epoch_runs_nothing(tmp_path, capsys):
    """Checkpoint at/after `epochs` → no rounds (start_epoch > end), the CLI
    reports it instead of crashing."""
    ckdir = tmp_path / "ckpts"
    cfg_clean = dict(CLEAN, checkpoint_dir=str(ckdir))
    clean_yaml = tmp_path / "clean.yaml"
    clean_yaml.write_text(yaml.safe_dump(cfg_clean))
    assert main(["pretrain", "--params", str(clean_yaml),
                 "--out", "clean/model.pt.tar"]) == 0
    cfg_resume = dict(cfg_clean, epochs=2, resumed_model=True,
                      resumed_model_name="clean/model.pt.tar")
    resume_yaml = tmp_path / "resume.yaml"
    resume_yaml.write_text(yaml.safe_dump(cfg_resume))
    assert main(["train", "--params", str(resume_yaml), "--no-save"]) == 0
    assert "no rounds to run" in capsys.readouterr().out


def test_best_val_checkpoint_tracks_lowest_global_loss(tmp_path):
    """helper.py:433-435 via main.py:233: `model_last.pt.tar.best` is
    (re)written whenever the round's global eval loss improves on the best
    seen, alongside the unconditional model_last."""
    cfg = dict(CLEAN, save_model=True, epochs=3)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    e.folder = tmp_path  # unit-level: inject the run folder
    losses = {}
    for i in (1, 2, 3):
        e.run_round(i)
        e.save_model(i)
        losses[i] = e.last_global_loss
    best = tmp_path / "model_last.pt.tar.best"
    assert best.exists() and (tmp_path / "model_last.pt.tar").exists()
    like = e.model_def.init_vars(jax.random.key(0))
    _, best_epoch, _ = ckpt.load_checkpoint(best, like)
    assert best_epoch == min(losses, key=losses.get)
    # a non-improving round must NOT overwrite the best snapshot
    e.last_global_loss = e.best_loss + 1.0
    e.save_model(9)
    _, still_epoch, _ = ckpt.load_checkpoint(best, like)
    assert still_epoch == best_epoch
