"""Unit tests: agent selection modes, recorder CSV output, checkpoint
roundtrip, ETL, DP noise, CLI parser."""
import csv
import random
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dba_mod_tpu import config as cfg
from dba_mod_tpu.fl.selection import select_agents
from dba_mod_tpu.utils.recorder import Recorder


def _params(**extra):
    d = dict(type="mnist", lr=0.1, batch_size=64, epochs=10, no_models=5,
             number_of_total_participants=20, eta=0.1,
             aggregation_methods="mean", adversary_list=[3, 7],
             is_poison=True, trigger_num=2,
             **{"0_poison_epochs": [4, 5], "1_poison_epochs": [5]})
    d.update(extra)
    return cfg.Params.from_dict(d)


PARTICIPANTS = list(range(20))
BENIGN = [p for p in PARTICIPANTS if p not in (3, 7)]


class TestSelection:
    def test_forced_adversaries_in_poison_epoch(self):
        # main.py:147-161: scheduled adversaries forced in, benign fill
        p = _params()
        rng = random.Random(0)
        agents, advs = select_agents(p, 5, PARTICIPANTS, BENIGN, rng)
        assert agents[:2] == [3, 7] and advs == [3, 7]
        assert len(agents) == 5 and len(set(agents)) == 5

    def test_offschedule_adversaries_can_fill_benign_slots(self):
        p = _params()
        rng = random.Random(0)
        agents, advs = select_agents(p, 1, PARTICIPANTS, BENIGN, rng)
        assert advs == []
        assert len(agents) == 5

    def test_random_adversary_mode(self):
        # main.py:142-146: pure uniform sample; adversaries only by chance
        p = _params(is_random_adversary=True)
        rng = random.Random(1)
        agents, advs = select_agents(p, 4, PARTICIPANTS, BENIGN, rng)
        assert len(agents) == 5
        assert set(advs) == set(agents) & {3, 7}

    def test_fixed_namelist_mode(self):
        p = _params(is_random_namelist=False,
                    participants_namelist=[1, 2, 3])
        agents, advs = select_agents(p, 4, [1, 2, 3], BENIGN,
                                     random.Random(0))
        assert agents == [1, 2, 3]
        assert advs == [3, 7]


class TestRecorder:
    def test_csv_files_and_schemas(self, tmp_path):
        rec = Recorder(tmp_path)
        rec.add_train(0, 1, 1, 1, 0.5, 90.0, 450, 500)
        rec.add_test("global", 1, 0.4, 91.0, 9100, 10000)
        rec.add_poisontest("global", 1, 1.2, 55.0, 4950, 9000)
        rec.add_triggertest("global", "combine", "", 1, 1.2, 55.0, 4950, 9000)
        rec.add_weight_result([0, 1], [0.5, 0.5], [0.1, 0.2])
        rec.scale_temp_one_row.extend([1, 6.4])
        rec.add_round_json(epoch=1, global_acc=91.0)
        rec.save(is_poison=True)
        names = {p.name for p in tmp_path.iterdir()}
        assert {"train_result.csv", "test_result.csv",
                "posiontest_result.csv", "poisontriggertest_result.csv",
                "weight_result.csv", "scale_result.csv",
                "metrics.jsonl"} <= names
        with open(tmp_path / "train_result.csv") as f:
            rows = list(csv.reader(f))
        assert rows[0] == ["local_model", "round", "epoch", "internal_epoch",
                           "average_loss", "accuracy", "correct_data",
                           "total_data"]
        assert rows[1][0] == "0"
        # rewrite-every-round: saving again must not duplicate
        rec.save(is_poison=True)
        with open(tmp_path / "train_result.csv") as f:
            assert len(list(csv.reader(f))) == 2

    def test_scale_row_closes_without_folder(self):
        rec = Recorder(None)
        rec.scale_temp_one_row.extend([3, 1.5])
        rec.save(is_poison=True)
        assert rec.scale_result == [[3, 1.5]]
        assert rec.scale_temp_one_row == []


def test_checkpoint_roundtrip(tmp_path):
    from dba_mod_tpu import checkpoint as ckpt
    from dba_mod_tpu.models import build_model
    p = _params()
    md = build_model(p)
    mv = md.init_vars(jax.random.key(0))
    ckpt.save_checkpoint(tmp_path / "m", mv, epoch=7, lr=0.05)
    like = md.init_vars(jax.random.key(1))
    restored, epoch, lr = ckpt.load_checkpoint(tmp_path / "m", like)
    assert epoch == 7 and lr == 0.05
    a = jax.tree_util.tree_leaves(mv.params)[0]
    b = jax.tree_util.tree_leaves(restored.params)[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_loan_etl(tmp_path):
    import pandas as pd
    from dba_mod_tpu.data.etl import preprocess_loan
    rng = np.random.RandomState(0)
    n = 60
    df = pd.DataFrame({
        "id": np.arange(n),                      # dropped
        "loan_status": rng.randint(0, 9, n),
        "grade": rng.choice(["A", "B", "C"], n),  # object → ordinal
        "big_num": rng.uniform(5000, 20000, n),   # mean>1000 → /10000
        "mid_num": rng.uniform(15, 40, n),        # mean in (10,100] → /10
        "addr_state": rng.choice(["CA", "NY", "TX"], n),
    })
    src = tmp_path / "loan.csv"
    df.to_csv(src, index=False)
    count = preprocess_loan(src, tmp_path / "loan")
    assert count == 3
    out = pd.read_csv(tmp_path / "loan" / "loan_CA.csv")
    assert "id" not in out.columns and "addr_state" not in out.columns
    assert out["big_num"].mean() < 10  # magnitude-bucketed
    assert set(out["grade"].unique()) <= {0, 1, 2}


def test_tiny_etl(tmp_path):
    from dba_mod_tpu.data.etl import reformat_tiny_imagenet_val
    val = tmp_path / "val"
    (val / "images").mkdir(parents=True)
    for i, wnid in enumerate(["n01", "n01", "n02"]):
        (val / "images" / f"val_{i}.JPEG").write_bytes(b"x")
    with open(val / "val_annotations.txt", "w") as f:
        f.write("val_0.JPEG\tn01\t0\t0\t10\t10\n"
                "val_1.JPEG\tn01\t0\t0\t10\t10\n"
                "val_2.JPEG\tn02\t0\t0\t10\t10\n")
    moved = reformat_tiny_imagenet_val(tmp_path)
    assert moved == 3
    assert (val / "n01" / "val_0.JPEG").exists()
    assert (val / "n02" / "val_2.JPEG").exists()
    assert not (val / "val_annotations.txt").exists()


def test_dp_noise_applied_in_fedavg():
    from dba_mod_tpu.ops import aggregation as agg
    g = {"w": jnp.zeros((50, 50))}
    deltas = {"w": jnp.zeros((4, 50, 50))}
    out_plain = agg.fedavg_update(g, deltas, 0.1, 4)
    out_noised = agg.fedavg_update(g, deltas, 0.1, 4, dp_sigma=0.01,
                                   rng=jax.random.key(0))
    assert float(jnp.abs(out_plain["w"]).sum()) == 0.0
    noise = np.asarray(out_noised["w"])
    assert noise.std() == pytest.approx(0.01, rel=0.2)


def test_cli_parser_reference_style():
    from dba_mod_tpu.main import build_parser, main
    # reference style gets rewritten to the train subcommand
    args = build_parser().parse_args(
        ["train", "--params", "configs/smoke_params.yaml"])
    assert args.cmd == "train" and args.params.endswith("smoke_params.yaml")
