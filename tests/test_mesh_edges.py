"""parallel/mesh.py + parallel/distributed.py edge cases (PR 6 satellite):
pad_clients on shrunk meshes, local_slice_bounds when the surviving world
no longer divides the client count, and initialize_distributed
idempotency / env-var precedence."""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from dba_mod_tpu.parallel import distributed
from dba_mod_tpu.parallel.mesh import (CLIENTS_AXIS, client_sharding,
                                       local_slice_bounds, make_mesh,
                                       pad_clients,
                                       segment_client_sharding)


# ------------------------------------------------------------ pad_clients
def test_pad_clients_tiles_every_shrunk_mesh_size():
    """An elastic shrink rebuilds the mesh over fewer devices; padding is
    a property of the CURRENT world for every size it can shrink to."""
    assert jax.device_count() >= 8, "conftest must provide 8 CPU devices"
    for d in (1, 2, 3, 4, 5, 6, 7, 8):
        mesh = make_mesh(d)
        for c in (1, 5, 8, 10, 100):
            padded = pad_clients(c, mesh)
            assert padded >= c
            assert padded % d == 0
            assert padded - c < d          # smallest such padding
    assert pad_clients(10, None) == 10     # no mesh: no padding


# ------------------------------------------------------ local_slice_bounds
@pytest.mark.parametrize("ndev,c", [(8, 16), (4, 16), (8, 8), (2, 6),
                                    (4, 12)])
def test_local_slice_bounds_cover_whole_axis_single_process(ndev, c):
    """Single-process worlds address every device: bounds must span the
    full clients axis, for stacked ([I, C, ...]) and flat ([C]) layouts."""
    mesh = make_mesh(ndev)
    assert local_slice_bounds(client_sharding(mesh), (c, 3), 0) == (0, c)
    assert local_slice_bounds(segment_client_sharding(mesh),
                              (2, c, 5), 1) == (0, c)


def test_local_slice_bounds_per_device_partition_non_dividing():
    """The per-device slices under a world that does not divide the padded
    client count evenly must still tile [0, C) without gaps or overlaps —
    the property the shrunk relaunch's re-sharding relies on."""
    mesh = make_mesh(8)
    c = pad_clients(10, mesh)   # 16 over 8 devices
    sharding = client_sharding(mesh)
    index_map = sharding.addressable_devices_indices_map((c, 4))
    slices = sorted((sl[0].start or 0,
                     sl[0].stop if sl[0].stop is not None else c)
                    for sl in index_map.values())
    assert slices[0][0] == 0 and slices[-1][1] == c
    for (a_lo, a_hi), (b_lo, b_hi) in zip(slices, slices[1:]):
        assert a_hi == b_lo                # contiguous, no overlap
    # shrunk mesh (3 devices) with a count the world doesn't divide
    mesh3 = make_mesh(3)
    c3 = pad_clients(10, mesh3)            # 12 over 3 devices
    assert local_slice_bounds(client_sharding(mesh3), (c3,), 0) == (0, c3)


def test_local_slice_bounds_handles_none_stops():
    """GSPMD emits slice(None) stops for trailing full slices; the bounds
    math must fall back to the axis length, not crash or shrink."""
    mesh = make_mesh(1)
    sharding = NamedSharding(mesh, P(CLIENTS_AXIS))
    lo, hi = local_slice_bounds(sharding, (7, 2), 0)
    assert (lo, hi) == (0, 7)


# ------------------------------------------- initialize_distributed
@pytest.fixture
def _clean_distributed(monkeypatch):
    """Isolate the module's init guard and env from the suite."""
    monkeypatch.setattr(distributed, "_initialized", False)
    for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    calls = []

    class FakeDistributed:
        @staticmethod
        def initialize(coordinator_address=None, num_processes=None,
                       process_id=None):
            calls.append(dict(coordinator_address=coordinator_address,
                              num_processes=num_processes,
                              process_id=process_id))

    monkeypatch.setattr(distributed.jax, "distributed", FakeDistributed)
    return calls


def test_initialize_distributed_noop_without_env(_clean_distributed):
    assert distributed.initialize_distributed() is False
    assert _clean_distributed == []
    assert distributed._initialized is False


def test_initialize_distributed_idempotent(_clean_distributed,
                                           monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
    monkeypatch.setenv("JAX_PROCESS_ID", "0")
    monkeypatch.setattr(distributed.jax, "process_count", lambda: 2)
    distributed.initialize_distributed()
    distributed.initialize_distributed()   # second call: no re-init
    distributed.initialize_distributed()
    assert len(_clean_distributed) == 1
    call = _clean_distributed[0]
    assert call["coordinator_address"] == "127.0.0.1:1234"
    assert call["num_processes"] == 2 and call["process_id"] == 0


def test_initialize_distributed_explicit_args_beat_env(_clean_distributed,
                                                       monkeypatch):
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1111")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "4")
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    monkeypatch.setattr(distributed.jax, "process_count", lambda: 2)
    distributed.initialize_distributed("10.0.0.1:2222", 2, 1)
    call = _clean_distributed[0]
    assert call["coordinator_address"] == "10.0.0.1:2222"
    assert call["num_processes"] == 2 and call["process_id"] == 1


def test_initialize_distributed_env_only_partial(_clean_distributed,
                                                 monkeypatch):
    """Coordinator set but no process vars: cloud auto-detection path —
    None num_processes/process_id forwarded for jax to resolve."""
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:9999")
    monkeypatch.setattr(distributed.jax, "process_count", lambda: 2)
    distributed.initialize_distributed()
    call = _clean_distributed[0]
    assert call["coordinator_address"] == "127.0.0.1:9999"
    assert call["num_processes"] is None and call["process_id"] is None
