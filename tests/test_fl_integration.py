"""End-to-end FL integration: a few rounds on synthetic data for all four
aggregation/attack pathways. The TPU-world 'fake backend' is the virtual
8-device CPU platform set up in conftest.py (SURVEY §4)."""
import numpy as np
import pytest

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=8, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9, decay=0.0005,
    sampling_dirichlet=False, local_eval=False, random_seed=1)

POISON = dict(
    BASE, internal_epochs=1, internal_poison_epochs=4, is_poison=True,
    local_eval=True, poison_label_swap=2, poisoning_per_batch=8,
    poison_lr=0.05, scale_weights_poison=4.0, adversary_list=[0, 1],
    trigger_num=2, alpha_loss=1.0,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
       "1_poison_pattern": [[3, 0], [3, 1], [3, 2], [3, 3]],
       "0_poison_epochs": [3, 4, 5, 6], "1_poison_epochs": [4, 5, 6]})


def test_clean_fedavg_learns():
    e = Experiment(Params.from_dict(dict(BASE, internal_epochs=2)),
                   save_results=False)
    accs = [e.run_round(i)["global_acc"] for i in range(1, 9)]
    assert np.isfinite(accs).all()
    assert accs[-1] > 25.0, accs  # synthetic task is near-linear — must learn
    # train rows recorded with the reference schema granularity
    assert len(e.recorder.train_result) == 8 * 4 * 2
    row = e.recorder.train_result[0]
    assert len(row) == 8 and row[2] == 1  # epoch column


def test_distributed_backdoor_attack():
    e = Experiment(Params.from_dict(POISON), save_results=False)
    out = {}
    for i in range(1, 7):
        out[i] = e.run_round(i)
    # before any poison epoch the backdoor is ineffective; model replacement
    # with scale 4 and 2 adversaries must plant it
    assert out[2]["backdoor_acc"] < 50.0
    assert out[6]["backdoor_acc"] > 80.0
    # scale rows: one (epoch, distance) pair per poisoning client + global acc
    assert len(e.recorder.scale_result) >= 3
    # forced selection: scheduled adversaries are in the round
    assert 0 in out[3]["agents"] and 0 in out[6]["agents"]
    assert 1 in out[4]["agents"]
    # local-trigger eval rows exist for adversaries
    trig_models = {r[0] for r in e.recorder.poisontriggertest_result}
    assert 0 in trig_models and "global" in trig_models
    # posiontest has pre-scale and post-scale rows for poisoning clients
    poison_rows = [r for r in e.recorder.posiontest_result if r[0] == 0]
    assert len(poison_rows) >= 2


@pytest.mark.parametrize("method", ["geom_median", "foolsgold"])
def test_defense_aggregators_run(method):
    cfg_d = dict(POISON, aggregation_methods=method, local_eval=False,
                 epochs=4)
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    for i in range(1, 5):
        r = e.run_round(i)
        assert np.isfinite(r["global_acc"])
    # weight rows recorded (names, wv, alpha) per round
    assert len(e.recorder.weight_result) == 3 * 4
    wv = e.recorder.weight_result[1]
    assert len(wv) == 4 and np.isfinite(wv).all()


def test_foolsgold_memory_persists():
    cfg_d = dict(POISON, aggregation_methods="foolsgold", local_eval=False)
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    e.run_round(1)
    m1 = np.abs(np.asarray(e.fg_state.memory)).sum()
    e.run_round(2)
    m2 = np.abs(np.asarray(e.fg_state.memory)).sum()
    assert m1 > 0 and m2 > m1


LOAN = dict(
    type="loan", lr=0.01, poison_lr=0.005, batch_size=32, epochs=4,
    no_models=4, number_of_total_participants=8, eta=0.8,
    aggregation_methods="mean", internal_epochs=1, internal_poison_epochs=3,
    is_poison=True, synthetic_data=True, momentum=0.9, decay=0.0005,
    sampling_dirichlet=False, local_eval=True, poison_label_swap=7,
    poisoning_per_batch=10, scale_weights_poison=3.0, trigger_num=2,
    alpha_loss=1.0, random_seed=1,
    adversary_list=["AK", "AL"],
    **{"0_poison_trigger_names": ["num_tl_120dpd_2m", "num_tl_90g_dpd_24m"],
       "0_poison_trigger_values": [10, 80],
       "1_poison_trigger_names": ["pub_rec_bankruptcies", "pub_rec"],
       "1_poison_trigger_values": [20, 100],
       "0_poison_epochs": [2, 3], "1_poison_epochs": [3]})


def test_loan_workload_end_to_end():
    e = Experiment(Params.from_dict(LOAN), save_results=False)
    out = {}
    for i in range(1, 5):
        out[i] = e.run_round(i)
        assert np.isfinite(out[i]["global_acc"])
    assert "AK" in out[2]["agents"]  # forced adversary
    assert out[4]["backdoor_acc"] is not None
    # natural non-IID: clients are state shards
    assert e.num_participants >= 8


def test_bf16_compute_path():
    """bfloat16 fwd/bwd (MXU path) with float32 params/aggregation must still
    learn and plant the backdoor."""
    e = Experiment(Params.from_dict(dict(POISON, compute_dtype="bfloat16")),
                   save_results=False)
    for i in range(1, 7):
        r = e.run_round(i)
        assert np.isfinite(r["global_acc"])
    assert r["backdoor_acc"] > 80.0
    import jax.numpy as jnp
    import jax
    # params stayed f32
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(e.global_vars.params))


def test_centralized_attack_mode():
    """Single adversary = centralized mode: it trains on the COMBINED pattern
    (adversarial_index -1, image_train.py:47-48) and the global battery tests
    each sub-pattern by index, gated on centralized_test_trigger
    (main.py:225-228)."""
    cfg_d = dict(POISON, adversary_list=[0],
                 **{"0_poison_epochs": [2, 3, 4]})
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    for i in range(1, 5):
        r = e.run_round(i)
    assert r["backdoor_acc"] > 80.0
    names = {row[1] for row in e.recorder.poisontriggertest_result
             if row[0] == "global"}
    assert "global_in_index_0_trigger" in names
    assert "global_in_index_1_trigger" in names

    # gate off: per-index rows disappear, combined row stays
    cfg_d2 = dict(cfg_d, centralized_test_trigger=False)
    e2 = Experiment(Params.from_dict(cfg_d2), save_results=False)
    e2.run_round(2)
    names2 = {row[1] for row in e2.recorder.poisontriggertest_result
              if row[0] == "global"}
    assert "combine" in names2
    assert not any("global_in_index" in n for n in names2)


def test_aggr_epoch_interval_two():
    """interval=2: clients train two consecutive global epochs without
    re-sync; poison scheduling applies per epoch; the server applies the
    summed update once per round (main.py:135, helper.py:218-222)."""
    cfg_d = dict(POISON, aggr_epoch_interval=2, epochs=6, local_eval=False)
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    out = {}
    for i in (1, 3, 5):
        out[i] = e.run_round(i)
        assert np.isfinite(out[i]["global_acc"])
    # adversary 0 poisons at epochs 3-6 → rounds starting at 3 and 5
    assert out[5]["backdoor_acc"] > 80.0
    # train rows carry per-segment epochs: both 5 and 6 appear
    epochs_seen = {r[2] for r in e.recorder.train_result}
    assert {1, 2, 3, 4, 5, 6} <= epochs_seen


def test_aggr_interval_per_epoch_local_evals():
    """interval=2 with local_eval: every global epoch of the round gets the
    FULL local battery per client — clean rows (image_train.py:268-271 in
    the epoch loop; :150-155 pre-scaling in the poison branch), poisontest
    pre+post rows for poisoning epochs (:157-164, :275-282), and per-agent
    trigger rows for adversaries (:285-295) — not just the round-final
    state."""
    cfg_d = dict(POISON, aggr_epoch_interval=2, epochs=4, local_eval=True)
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    e.run_round(3)  # segments: epochs 3 and 4; adversaries 0,1 poison
    rows = [r for r in e.recorder.test_result if r[0] != "global"]
    by_epoch = {ep: {r[0] for r in rows if r[1] == ep} for ep in (3, 4)}
    # intermediate epoch 3 rows exist for every selected client, and the
    # final epoch 4 rows for every client (baseline=False → no gating)
    assert len(by_epoch[3]) == 4 and len(by_epoch[4]) == 4
    # intermediate rows are real evals: finite loss, count = test set size
    for r in rows:
        assert np.isfinite(r[2]) and r[5] == 256
    # adversary 0 poisons BOTH epochs 3 and 4 → posiontest pre+post rows at
    # each epoch (intermediate battery, not just round-final)
    p_rows = [r for r in e.recorder.posiontest_result if r[0] == 0]
    assert len([r for r in p_rows if r[1] == 3]) == 2
    assert len([r for r in p_rows if r[1] == 4]) == 2
    # per-agent trigger rows exist for both epochs of the round
    trig_eps = {r[3] for r in e.recorder.poisontriggertest_result
                if r[0] == 0}
    assert {3, 4} <= trig_eps
    for r in e.recorder.posiontest_result:
        assert np.isfinite(r[2])


def test_batch_tracking_channels():
    """vis_train_batch_loss / batch_track_distance (image_train.py:225-245)
    record per-batch loss and post-step distance-to-anchor rows instead of
    being silently ignored."""
    cfg_d = dict(POISON, epochs=3, local_eval=False,
                 vis_train_batch_loss=True, batch_track_distance=True)
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    e.run_round(3)  # epoch 3: adversary 0 poisons
    rec = e.recorder
    assert rec.batch_loss_result and rec.batch_distance_result
    # distance rows cover every training client (both branches,
    # image_train.py:107-112, :235-240); the loss channel is benign-only
    # (:225-228), so poisoning client 0 appears in distance but not loss
    train_names = {r[0] for r in rec.train_result}
    assert {r[0] for r in rec.batch_distance_result} == train_names
    loss_names = {r[0] for r in rec.batch_loss_result}
    assert loss_names == train_names - {0}
    assert len(rec.batch_distance_result) > len(rec.batch_loss_result)
    # post-step distance to the anchor is strictly positive after any step
    dists = [r[5] for r in rec.batch_distance_result]
    assert all(d > 0 for d in dists)
    losses = [r[5] for r in rec.batch_loss_result]
    assert np.isfinite(losses).all()
    # per-epoch sums over the batch channel agree with the train rows' loss
    # accounting (same scan, same masking) — pick a benign client's row,
    # since the loss channel is benign-only
    row0 = next(r for r in rec.train_result if r[0] != 0)
    client, ep, ie = row0[0], row0[2], row0[3]
    chan = [r[5] for r in rec.batch_loss_result
            if r[0] == client and r[2] == ep and r[3] == ie]
    assert len(chan) >= 1
    # channels off → nothing recorded (and nothing transferred)
    e2 = Experiment(Params.from_dict(dict(POISON, epochs=3,
                                          local_eval=False)),
                    save_results=False)
    e2.run_round(3)
    assert not e2.recorder.batch_loss_result
    assert not e2.recorder.batch_distance_result


def test_rfa_max_update_norm_rejection():
    """max_update_norm (helper.py:360-369) config key reaches the RFA branch:
    an absurdly small threshold rejects every round (global model frozen),
    a large one admits them."""
    import jax
    cfg_d = dict(POISON, aggregation_methods="geom_median", epochs=2,
                 local_eval=False, max_update_norm=1e-12)
    e = Experiment(Params.from_dict(cfg_d), save_results=False)
    before = jax.tree_util.tree_leaves(e.global_vars.params)[0].copy()
    e.run_round(1)
    assert e.last_is_updated is False
    after = jax.tree_util.tree_leaves(e.global_vars.params)[0]
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
    # round JSONL carries the rejection flag
    assert e.recorder._jsonl_rows[-1]["is_updated"] is False

    e2 = Experiment(Params.from_dict(dict(cfg_d, max_update_norm=1e9)),
                    save_results=False)
    b2 = jax.tree_util.tree_leaves(e2.global_vars.params)[0].copy()
    e2.run_round(1)
    assert e2.last_is_updated is True
    a2 = jax.tree_util.tree_leaves(e2.global_vars.params)[0]
    assert np.abs(np.asarray(a2) - np.asarray(b2)).max() > 0


def test_sequential_debug_matches_vmapped():
    """The strictly-sequential debug path (SURVEY §7.2.4) reproduces the
    vmapped round: same per-lane rng streams, same deltas, same aggregate."""
    import jax
    cfg_v = dict(POISON, epochs=2, local_eval=False)
    e_v = Experiment(Params.from_dict(cfg_v), save_results=False)
    e_s = Experiment(Params.from_dict(dict(cfg_v, sequential_debug=True)),
                     save_results=False)
    for i in (1, 2, 3):
        rv = e_v.run_round(i)
        rs = e_s.run_round(i)
    assert abs(rv["global_acc"] - rs["global_acc"]) < 0.5
    lv = jax.tree_util.tree_leaves(e_v.global_vars.params)[0]
    ls = jax.tree_util.tree_leaves(e_s.global_vars.params)[0]
    np.testing.assert_allclose(np.asarray(lv), np.asarray(ls), atol=2e-3)


def test_loan_stale_poison_probe_skips_blocking_eval():
    """stale_poison_probe (flag-gated deviation, README): poison rounds
    reuse the previous round's recorded backdoor accuracy instead of the
    blocking mid-round probe of the current model (loan_train.py:67-75);
    with the flag off the blocking probe runs."""
    def counting(e):
        calls = []
        orig = e.engine.backdoor_acc_fn
        e.engine.backdoor_acc_fn = (
            lambda v: calls.append(1) or orig(v))
        return calls

    e = Experiment(Params.from_dict(dict(LOAN, stale_poison_probe=True)),
                   save_results=False)
    calls = counting(e)
    out = {}
    for i in range(1, 4):
        out[i] = e.run_round(i)  # round 1 records the backdoor accuracy
        assert np.isfinite(out[i]["global_acc"])
    # poison rounds 2 and 3 had history → the blocking probe never ran
    assert calls == []

    e2 = Experiment(Params.from_dict(LOAN), save_results=False)
    calls2 = counting(e2)
    e2.run_round(1)
    e2.run_round(2)  # AK poisons epoch 2 → blocking probe
    assert len(calls2) == 1
