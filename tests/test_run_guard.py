"""utils/run_guard.py: graceful shutdown + watchdog units, the
round-boundary stop in Experiment.run, and the config validation for the
new knobs. The end-to-end signal/kill behavior (real SIGTERM/SIGKILL
against a subprocess) lives in tests/test_crash_harness.py."""
import logging
import os
import signal
import threading
import time

import jax
import pytest

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.config import Params
from dba_mod_tpu.utils import run_guard
from dba_mod_tpu.utils.run_guard import (EXIT_INTERRUPTED, EXIT_WATCHDOG,
                                         GracefulShutdown, RunGuard,
                                         Watchdog)

CFG = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=6, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=3)


# ---------------------------------------------------------------- watchdog
def test_watchdog_disabled_is_strict_noop():
    wd = Watchdog(soft_s=0.0, hard_s=0.0)
    assert not wd.enabled and wd._thread is None
    with wd.zone("anything"):
        pass
    assert wd._thread is None  # no thread ever started


def test_watchdog_soft_then_hard_fire(caplog):
    fired = []
    wd = Watchdog(soft_s=0.05, hard_s=0.15, on_hard=lambda: fired.append(1))
    # an earlier experiment test may have run telemetry's logger setup,
    # which sets propagate=False on "dba_mod_tpu" — caplog hangs off the
    # root logger, so force propagation for the capture window
    lg = logging.getLogger("dba_mod_tpu")
    prev_propagate = lg.propagate
    lg.propagate = True
    try:
        with caplog.at_level("ERROR", logger="dba_mod_tpu"):
            with wd.zone("round/finalize"):
                deadline = time.monotonic() + 5.0
                while not fired and time.monotonic() < deadline:
                    time.sleep(0.01)
    finally:
        lg.propagate = prev_propagate
    assert wd.soft_stalls == 1 and wd.hard_aborts == 1 and fired
    stall = [r for r in caplog.records if "stalled" in r.getMessage()]
    assert stall and "round/finalize" in stall[0].getMessage()


def test_watchdog_fast_zone_fires_nothing():
    fired = []
    wd = Watchdog(soft_s=0.5, hard_s=1.0, on_hard=lambda: fired.append(1))
    for _ in range(5):
        with wd.zone("quick"):
            time.sleep(0.01)
    time.sleep(0.1)  # give the thread a chance to mis-fire
    assert wd.soft_stalls == 0 and wd.hard_aborts == 0 and not fired


def test_watchdog_soft_only_never_aborts():
    fired = []
    wd = Watchdog(soft_s=0.05, hard_s=0.0, on_hard=lambda: fired.append(1))
    with wd.zone("slow"):
        time.sleep(0.2)
    assert wd.soft_stalls == 1 and wd.hard_aborts == 0 and not fired


# -------------------------------------------------------- graceful shutdown
def test_shutdown_disabled_installs_no_handlers():
    before = {s: signal.getsignal(s) for s in GracefulShutdown.SIGNALS}
    g = GracefulShutdown(enabled=False)
    g.install()
    assert not g._prev
    for s, h in before.items():
        assert signal.getsignal(s) is h
    g.uninstall()


def test_shutdown_signal_sets_flag_then_second_forces_exit():
    g = GracefulShutdown(enabled=True)
    codes = []
    g._force_exit = codes.append
    g.install()
    try:
        assert not g.stop_requested
        os.kill(os.getpid(), signal.SIGTERM)
        # delivery is synchronous in the main thread on return from kill
        assert g.stop_requested
        assert not codes
        os.kill(os.getpid(), signal.SIGTERM)
        assert codes == [128 + signal.SIGTERM]
    finally:
        g.uninstall()
    # handlers restored
    assert signal.getsignal(signal.SIGTERM) is not g._handler


def test_shutdown_state_resets_on_reinstall():
    """A second run() on the same Experiment reinstalls the handlers; the
    previous run's stop flag and signal count must not leak in — a stale
    count would make the NEXT first signal take the force-exit branch."""
    g = GracefulShutdown(enabled=True)
    g._force_exit = lambda code: None
    g.install()
    try:
        g._handler(signal.SIGTERM, None)
        assert g.stop_requested and g._signal_count == 1
    finally:
        g.uninstall()
    g.install()
    try:
        assert not g.stop_requested and g._signal_count == 0
    finally:
        g.uninstall()


def test_runguard_context_installs_and_restores():
    prev = signal.getsignal(signal.SIGTERM)
    guard = RunGuard(graceful_shutdown=True)
    with guard:
        assert signal.getsignal(signal.SIGTERM) == guard.shutdown._handler
    assert signal.getsignal(signal.SIGTERM) is prev


def test_runguard_disabled_watch_is_nullcontext():
    guard = RunGuard()  # everything off
    assert not guard.watchdog.enabled
    assert not guard.shutdown.enabled
    with guard.watch("x"):
        pass
    assert guard.watchdog._thread is None
    # exit codes are distinct from each other and from success
    assert len({0, EXIT_INTERRUPTED, EXIT_WATCHDOG}) == 3


# -------------------------------------------- round-boundary graceful stop
def test_run_stops_at_round_boundary_with_verified_checkpoint(tmp_path,
                                                              monkeypatch):
    """A stop request lands mid-run: the run finishes the current round,
    checkpoints it (manifest-verified), flushes the recorder, and reports
    interrupted — epochs after the boundary never run."""
    from dba_mod_tpu.fl.experiment import Experiment
    cfg = dict(CFG, save_model=True, graceful_shutdown=True,
               run_dir=str(tmp_path / "runs"))
    e = Experiment(Params.from_dict(cfg), save_results=True)
    orig = Experiment.save_model

    def save_and_stop(self, epoch, fl=None, async_save=False):
        orig(self, epoch, fl=fl, async_save=async_save)
        if epoch >= 2:
            self.guard.shutdown.request_stop()

    monkeypatch.setattr(Experiment, "save_model", save_and_stop)
    last = e.run(6)
    assert e.interrupted
    assert last["epoch"] == 2  # the boundary honored the stop before 3
    path = e.folder / "model_last.pt.tar"
    ok, reason = ckpt.verify_checkpoint(path)
    assert ok, reason
    _, saved_epoch, _ = ckpt.load_checkpoint(
        path, e.model_def.init_vars(jax.random.key(0)))
    assert saved_epoch == 2
    # recorder flushed through the boundary
    rows = (e.folder / "round_result.csv").read_text().strip().splitlines()
    assert len(rows) - 1 == 2  # header + 2 rounds


def test_run_without_guard_has_no_handlers_or_threads(tmp_path):
    """The acceptance contract: with the knobs at their defaults a run
    installs no signal handlers and starts no watchdog thread."""
    from dba_mod_tpu.fl.experiment import Experiment
    before = {s: signal.getsignal(s) for s in GracefulShutdown.SIGNALS}
    threads_before = {t.name for t in threading.enumerate()}
    e = Experiment(Params.from_dict(dict(CFG, epochs=1)), save_results=False)
    e.run(1)
    assert not e.interrupted
    for s, h in before.items():
        assert signal.getsignal(s) is h
    assert "dba-watchdog" not in {t.name for t in threading.enumerate()
                                  } - threads_before


# ------------------------------------------------------- config validation
def test_config_validates_guard_knobs():
    ok = dict(CFG)
    Params.from_dict(dict(ok, watchdog_soft_s=5, watchdog_hard_s=30))
    Params.from_dict(dict(ok, watchdog_soft_s=5, watchdog_hard_s=0))
    Params.from_dict(dict(ok, resumed_model="auto"))
    with pytest.raises(ValueError, match="watchdog_hard_s"):
        Params.from_dict(dict(ok, watchdog_soft_s=30, watchdog_hard_s=5))
    with pytest.raises(ValueError, match="watchdog"):
        Params.from_dict(dict(ok, watchdog_soft_s=-1))
    with pytest.raises(ValueError, match="resumed_model"):
        Params.from_dict(dict(ok, resumed_model="maybe"))
    with pytest.raises(ValueError, match="keep_last_n"):
        Params.from_dict(dict(ok, keep_last_n=-2))
    # auto-resume only restores manifest-verified snapshots: the
    # combination that can never resume is a config error, not a
    # silent fresh start on every relaunch
    with pytest.raises(ValueError, match="checkpoint_manifests"):
        Params.from_dict(dict(ok, resumed_model="auto",
                              checkpoint_manifests=False))
    assert Params.from_dict(dict(ok, resumed_model="auto")).resume_mode \
        == "auto"
    assert Params.from_dict(dict(ok, resumed_model=True)).resume_mode \
        == "named"
    assert Params.from_dict(ok).resume_mode == "off"
