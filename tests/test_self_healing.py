"""Self-healing server loop (fl/async_rounds.py, fl/experiment.py):
merge deadlines + graceful starvation, wave backpressure + arrival TTL,
the model-health sentinel with last-good-ring rollback in both engines,
and the strict all-knobs-off bitwise no-op contract.

Every e2e rehearsal here runs multi-round Experiment pairs (the expensive
XLA-compile + A/B-run pattern); they are slow-marked so tier 1 keeps only
the config-guard test, and the full battery rides tier 2 / the nightly
lane."""
import json
from pathlib import Path

import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.async_rounds import AsyncDriver
from dba_mod_tpu.fl.experiment import Experiment

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=3, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=1)

VOLATILE = {"time", "round_time", "dispatch_time", "finalize_time"}


def _rows(exp, drop=()):
    return [{k: v for k, v in r.items() if k not in VOLATILE | set(drop)}
            for r in exp.recorder._jsonl_rows]


def _bitwise_equal(a, b):
    la, lb = (jax.tree_util.tree_leaves(t) for t in (a, b))
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ------------------------------------------------- strict no-op contract
@pytest.mark.slow
def test_inert_knob_values_are_bitwise_noop():
    """Every self-healing knob set to a value that cannot fire (huge
    deadline/TTL, generous watermark, health check with no band, a
    non-default starvation policy on a stream that never starves) must
    leave the async run bit-identical to the all-defaults run."""
    cfg = dict(BASE, mode="async", buffer_k=2, async_steps=4,
               arrival_rate=2.0, arrival_jitter=0.5, straggler_tail=0.2,
               straggler_factor=5.0)
    ref = Experiment(Params.from_dict(cfg), save_results=False)
    ref.run()
    loud = Experiment(Params.from_dict(dict(
        cfg, merge_timeout_v=1e9, merge_min_k=2, starvation_policy="wait",
        max_outstanding_waves=1000, arrival_ttl_v=1e9,
        model_health_check=True, health_norm_band=0.0, rollback_ring=3)),
        save_results=False)
    loud.run()
    assert _rows(ref) == _rows(loud)
    assert _bitwise_equal(ref.global_vars, loud.global_vars)


@pytest.mark.slow
def test_sync_mode_ignores_self_healing_knobs():
    """mode: sync with the async-side knobs set stays bit-identical —
    the lockstep engine never reads them."""
    ref = Experiment(Params.from_dict(dict(BASE, epochs=2)),
                     save_results=False)
    ref.run()
    loud = Experiment(Params.from_dict(dict(
        BASE, epochs=2, merge_timeout_v=3.0, merge_min_k=2,
        starvation_policy="carry", max_outstanding_waves=2,
        arrival_ttl_v=5.0)), save_results=False)
    loud.run()
    assert _rows(ref) == _rows(loud)
    assert _bitwise_equal(ref.global_vars, loud.global_vars)


# ------------------------------------------------------- merge deadlines
@pytest.mark.slow
def test_deadline_partial_merge_fires_and_is_deterministic():
    """With a tight merge_timeout_v the merge fires before K arrivals —
    partial occupancy rows — and two identical runs stay bit-identical."""

    def run():
        e = Experiment(Params.from_dict(dict(
            BASE, mode="async", buffer_k=4, async_steps=6,
            arrival_rate=0.5, arrival_jitter=0.8, straggler_tail=0.3,
            straggler_factor=20.0, merge_timeout_v=0.05, merge_min_k=1)),
            save_results=False)
        d = AsyncDriver(e)
        d.run_steps(6)
        return e, d

    ea, da = run()
    eb, db = run()
    occ = [r["buffer_occupancy"] for r in ea.recorder._jsonl_rows]
    assert da.stats()["deadline_merges"] > 0
    assert any(o < 4 for o in occ)          # partial merges actually fired
    assert all(r["epoch"] == i + 1
               for i, r in enumerate(ea.recorder._jsonl_rows))
    assert _rows(ea) == _rows(eb)
    assert da.stats() == db.stats()
    assert _bitwise_equal(ea.global_vars, eb.global_vars)


@pytest.mark.slow
def test_deadline_merge_resume_bit_identical(tmp_path):
    """Deadline-triggered partial merges survive a kill + --resume auto
    bit-exactly: the buffered arrival times ride the async sidecar, so a
    pending deadline re-arms with the same credit."""
    cfg = dict(BASE, epochs=6, save_model=True, mode="async", buffer_k=4,
               arrival_rate=0.5, arrival_jitter=0.8, straggler_tail=0.3,
               straggler_factor=20.0, merge_timeout_v=0.05, merge_min_k=1,
               staleness_weighting="polynomial", async_steps=8,
               random_seed=3)

    def rows(folder):
        drop = VOLATILE | {"virtual_time"}
        with open(Path(folder) / "metrics.jsonl") as f:
            return [{k: v for k, v in json.loads(l).items()
                     if k not in drop} for l in f if l.strip()]

    ref = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ref"))), save_results=True)
    ref.run()
    ref_rows = rows(ref.folder)
    assert any(r["buffer_occupancy"] < 4 for r in ref_rows)
    a = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ab"), async_steps=4)),
        save_results=True)
    a.run()
    folder = a.folder
    del a
    b = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ab"), resumed_model="auto")),
        save_results=True)
    assert (b._resume_aux or {}).get("async_state") is not None
    b.run()
    got = rows(folder)
    assert [r["epoch"] for r in got] == list(range(1, 9))
    assert got == ref_rows


# ------------------------------------------------------------ backpressure
@pytest.mark.slow
def test_backpressure_caps_outstanding_waves():
    """K larger than the per-cohort yield (heavy dropout) piles up
    resident waves; max_outstanding_waves flushes partial merges at the
    watermark instead."""
    cfg = dict(BASE, mode="async", buffer_k=8, async_steps=4,
               fault_injection=True, fault_dropout_prob=0.7, fault_seed=5)
    e0 = Experiment(Params.from_dict(cfg), save_results=False)
    d0 = AsyncDriver(e0)
    d0.run_steps(4)
    hw0 = d0.stats()["outstanding_waves_highwater"]
    assert hw0 > 3                          # the pathological pile-up

    e1 = Experiment(Params.from_dict(dict(cfg, max_outstanding_waves=3)),
                    save_results=False)
    d1 = AsyncDriver(e1)
    d1.run_steps(4)
    s1 = d1.stats()
    assert s1["outstanding_waves_highwater"] <= 3
    assert s1["backpressure_hits"] > 0
    rows = e1.recorder._jsonl_rows
    assert np.isfinite([r["global_acc"] for r in rows]).all()


@pytest.mark.slow
def test_arrival_ttl_expires_stragglers():
    """arrival_ttl_v drops updates whose service delay exceeded the TTL —
    they never reach the buffer, and the run still completes finite."""
    cfg = dict(BASE, mode="async", buffer_k=2, async_steps=4,
               straggler_tail=0.5, straggler_factor=1000.0,
               arrival_ttl_v=20.0)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    d = AsyncDriver(e)
    d.run_steps(4)
    assert d.stats()["expired_arrivals"] > 0
    rows = e.recorder._jsonl_rows
    assert [r["epoch"] for r in rows] == [1, 2, 3, 4]
    assert np.isfinite([r["global_acc"] for r in rows]).all()


# ------------------------------------------------------- graceful starvation
@pytest.mark.slow
def test_starvation_carry_records_degraded_noop_steps(monkeypatch):
    """fault_dropout_prob=1.0 starves the arrival queue completely:
    policy "carry" consumes the budget as recorded degraded no-op steps
    (model untouched) instead of the pre-existing hard RuntimeError."""
    from dba_mod_tpu.fl import async_rounds
    monkeypatch.setattr(async_rounds, "STARVATION_LIMIT", 5)
    cfg = dict(BASE, mode="async", buffer_k=2, async_steps=1,
               fault_injection=True, fault_dropout_prob=1.0, fault_seed=7)
    with pytest.raises(RuntimeError, match="starved"):
        Experiment(Params.from_dict(cfg), save_results=False).run()

    e = Experiment(Params.from_dict(dict(cfg, starvation_policy="carry")),
                   save_results=False)
    before = jax.device_get(e.global_vars)
    e.run()
    rows = e.recorder._jsonl_rows
    assert [r["epoch"] for r in rows] == [1]
    assert rows[0]["degraded"] and rows[0]["buffer_occupancy"] == 0
    assert np.isfinite(rows[0]["global_acc"])
    assert _bitwise_equal(before, jax.device_get(e.global_vars))


# ------------------------------------------------- health sentinel + rollback
@pytest.mark.slow
def test_async_rollback_restores_premerge_model_bit_exactly():
    """A merge outside the health band rolls back to the last-good ring:
    the committed model after the unhealthy merge is bit-identical to the
    pre-merge model, the step is recorded degraded, and the stream keeps
    going."""
    cfg = dict(BASE, mode="async", buffer_k=4, async_steps=3,
               model_health_check=True, health_norm_band=1e-9,
               health_warmup_merges=1, rollback_ring=2)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    d = AsyncDriver(e)
    d.run_steps(1)                          # merge 1 seeds the EMA
    good = jax.device_get(e.global_vars)
    d.run_steps(2)                          # merges 2..3: outside the band
    assert d.stats()["health_rollbacks"] == 2
    assert _bitwise_equal(good, jax.device_get(e.global_vars))
    rows = e.recorder._jsonl_rows
    assert [r["degraded"] for r in rows] == [False, True, True]
    assert np.isfinite([r["global_acc"] for r in rows]).all()


@pytest.mark.slow
def test_async_min_surviving_clients_skips_and_carries():
    """The sync min_surviving_clients degradation, ported to the buffered
    merge: a screen that leaves too few survivors skips aggregation and
    carries the model."""
    cfg = dict(BASE, mode="async", buffer_k=4, async_steps=2,
               fault_injection=True, fault_corrupt_prob=1.0, fault_seed=3,
               min_surviving_clients=1)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    before = jax.device_get(e.global_vars)
    e.run()
    rows = e.recorder._jsonl_rows
    # every payload NaN-corrupted → screened out → zero survivors → carry
    assert all(r["degraded"] for r in rows)
    assert all(r["n_quarantined"] == 4 for r in rows)
    assert _bitwise_equal(before, jax.device_get(e.global_vars))
    assert np.isfinite([r["global_acc"] for r in rows]).all()


@pytest.mark.slow
def test_sync_health_rollback_degrades_round():
    """The sentinel in the lockstep engine: after the EMA seeds, a normal
    round's update norm sits far outside a microscopic band — every later
    round degrades and the model stays pinned at the last-good version."""
    e = Experiment(Params.from_dict(dict(
        BASE, epochs=3, model_health_check=True, health_norm_band=1e-9,
        health_warmup_merges=1, rollback_ring=2)), save_results=False)
    e.run()
    rows = e.recorder._jsonl_rows
    assert [r["degraded"] for r in rows] == [False, True, True]
    assert np.isfinite([r["global_acc"] for r in rows]).all()
    assert _bitwise_equal(e._sentinel.ring[-1][1], e.global_vars)


@pytest.mark.slow
def test_sync_health_check_with_no_band_is_value_identical():
    """model_health_check with band 0 (finite-only) must not change any
    recorded value of a healthy sync run."""
    ref = Experiment(Params.from_dict(dict(BASE, epochs=2)),
                     save_results=False)
    ref.run()
    chk = Experiment(Params.from_dict(dict(
        BASE, epochs=2, model_health_check=True)), save_results=False)
    chk.run()
    assert _rows(ref) == _rows(chk)
    assert _bitwise_equal(ref.global_vars, chk.global_vars)


# ------------------------------------------------------------ config guards
def test_self_healing_config_rejections():
    with pytest.raises(ValueError, match="starvation_policy"):
        Params.from_dict(dict(BASE, starvation_policy="panic"))
    with pytest.raises(ValueError, match="merge_timeout_v"):
        Params.from_dict(dict(BASE, merge_timeout_v=-1.0))
    with pytest.raises(ValueError, match="merge_min_k"):
        Params.from_dict(dict(BASE, merge_min_k=0))
    with pytest.raises(ValueError, match="max_outstanding_waves"):
        Params.from_dict(dict(BASE, max_outstanding_waves=-1))
    with pytest.raises(ValueError, match="arrival_ttl_v"):
        Params.from_dict(dict(BASE, arrival_ttl_v=-0.5))
    with pytest.raises(ValueError, match="health_ema_alpha"):
        Params.from_dict(dict(BASE, health_ema_alpha=0.0))
    with pytest.raises(ValueError, match="rollback_ring"):
        Params.from_dict(dict(BASE, rollback_ring=-1))
