"""Multi-host (DCN slot) execution test (VERDICT r3 ask 4): a REAL
2-process jax.distributed runtime — 2 × 4 virtual CPU devices = one
8-device clients mesh spanning processes — runs one full sharded FL round
through the standard Experiment driver. Verifies:

- `initialize_distributed()` bootstraps from env vars inside
  Experiment.__init__ (parallel/distributed.py);
- per-process input placement: each host device_puts only its addressable
  clients slice via jax.make_array_from_process_local_data
  (parallel/mesh.py::_place);
- replicated round outputs: every process can device_get the metrics
  payload host-locally and reports identical accuracies.

Single-controller fallback: without JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES in the env this path is never taken — the driver runs
exactly as single-host (plain device_put), which every other test covers.
"""
import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).parent / "distributed_worker.py"


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.parametrize("method", ["mean", "geom_median"])
def test_two_process_round(method):
    """FedAvg proves the bootstrap + placement path; geom_median (RFA)
    additionally runs the per-iteration Weiszfeld distance collectives
    across the process boundary."""
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_PROCESSES",
                        "JAX_PROCESS_ID", "JAX_COORDINATOR_ADDRESS")}
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    env["PYTHONPATH"] = str(WORKER.parent.parent)  # repo root import
    procs = [subprocess.Popen(
        [sys.executable, str(WORKER), str(pid), coord, method],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(WORKER.parent.parent))
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=1200)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = {}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        m = re.search(r"RESULT (\d) acc=([\d.]+) backdoor=([\d.]+)", out)
        assert m, f"proc {pid} printed no RESULT:\n{out[-4000:]}"
        results[int(m.group(1))] = (float(m.group(2)), float(m.group(3)))
    assert set(results) == {0, 1}
    # replicated payload → both processes observed the same round
    assert results[0] == results[1], results
