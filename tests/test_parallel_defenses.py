"""Mesh coverage for the flagship paths (VERDICT r3 ask 3): CIFAR-BN rounds,
FoolsGold, and RFA on the 8-device clients mesh must reproduce single-device
numerics — batch_stats trees through GSPMD, the FoolsGold [C, L] feature
all-gather + participant-id memory scatter, and RFA's per-iteration distance
collectives all run sharded here.

Tolerance rationale (VERDICT r3 ask 8): after ONE round the only difference
between the mesh and single-device programs is collective reduction order
(per-client training is device-local and bit-identical), so round-1
comparisons are tight. Over multiple rounds those last-ulp differences are
amplified chaotically through ReLU boundaries — the same measured behavior
as the cross-framework A/B (PARITY_AB.md) — so multi-round comparisons use
a drift envelope plus the accuracy bound."""
import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

MNIST8 = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=4, no_models=8,
    number_of_total_participants=16, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, internal_poison_epochs=2, is_poison=True,
    synthetic_data=True, synthetic_train_size=640, synthetic_test_size=256,
    momentum=0.9, decay=0.0005, sampling_dirichlet=False, local_eval=False,
    poison_label_swap=2, poisoning_per_batch=8, poison_lr=0.05,
    scale_weights_poison=3.0, adversary_list=[0], trigger_num=1,
    alpha_loss=1.0, random_seed=1,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
       "0_poison_epochs": [1, 2, 3]})

CIFAR8 = dict(
    type="cifar", lr=0.1, batch_size=8, epochs=2, no_models=8,
    number_of_total_participants=8, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, internal_poison_epochs=1, is_poison=True,
    synthetic_data=True, synthetic_train_size=128, synthetic_test_size=128,
    momentum=0.9, decay=0.0005, sampling_dirichlet=False, local_eval=True,
    poison_label_swap=2, poisoning_per_batch=4, poison_lr=0.05,
    scale_weights_poison=2.0, adversary_list=[0], trigger_num=1,
    alpha_loss=1.0, random_seed=1,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2]],
       "0_poison_epochs": [1, 2]})


LOAN8 = dict(
    type="loan", lr=0.05, poison_lr=0.05, batch_size=64, epochs=2,
    no_models=8, number_of_total_participants=12, eta=0.8,
    aggregation_methods="mean", internal_epochs=1, internal_poison_epochs=2,
    is_poison=True, synthetic_data=True, momentum=0.9, decay=0.0005,
    sampling_dirichlet=False, local_eval=True, poison_label_swap=7,
    poisoning_per_batch=16, poison_step_lr=True, scale_weights_poison=2.0,
    trigger_num=2, alpha_loss=1.0, random_seed=1,
    adversary_list=["AK", "AL"],
    **{"0_poison_trigger_names": ["num_tl_120dpd_2m", "num_tl_90g_dpd_24m"],
       "0_poison_trigger_values": [10, 80],
       "1_poison_trigger_names": ["pub_rec_bankruptcies", "pub_rec"],
       "1_poison_trigger_values": [20, 100],
       "0_poison_epochs": [1, 2], "1_poison_epochs": [2]})


def _pair(cfg):
    e1 = Experiment(Params.from_dict(cfg), save_results=False)
    e8 = Experiment(Params.from_dict(dict(cfg, num_devices=8)),
                    save_results=False)
    assert e8.mesh is not None and e8.mesh.devices.size == 8
    return e1, e8


def _flat(tree):
    return np.concatenate([np.asarray(l, np.float64).ravel()
                           for l in jax.tree_util.tree_leaves(tree)])


def test_cifar_bn_round_on_mesh_matches_single_device():
    """The flagship model (BN ResNet) with the full local battery, sharded:
    batch_stats trees flow through the GSPMD round; one round is tight."""
    e1, e8 = _pair(CIFAR8)
    r1 = e1.run_round(1)
    r8 = e8.run_round(1)
    assert np.isfinite(r8["global_acc"])
    # Unlike the MNIST case, the BN ResNet cannot be near-bit here: sharding
    # changes the per-device client-batch (8 clients on one device vs 1 per
    # device), so XLA compiles different conv kernels whose f32 summation
    # orders differ at ~1e-6 — and any activation inside that band of zero
    # flips its ReLU gate (the same measured chaos as the cross-framework
    # A/B, tests/test_parity_ab.py::test_cifar_bn_ab_parity). Envelope on
    # state, tight-ish bar on accuracy (128-sample eval ⇒ 0.8% per sample).
    np.testing.assert_allclose(_flat(e1.global_vars.params),
                               _flat(e8.global_vars.params), atol=5e-3)
    np.testing.assert_allclose(_flat(e1.global_vars.batch_stats),
                               _flat(e8.global_vars.batch_stats), atol=5e-3)
    assert abs(r1["global_acc"] - r8["global_acc"]) < 3.0
    assert abs(r1["backdoor_acc"] - r8["backdoor_acc"]) < 3.0
    # the sharded local battery produced rows for every client
    assert len({row[0] for row in e8.recorder.test_result
                if row[0] != "global"}) == 8


TINY8 = dict(
    type="tiny-imagenet-200", lr=0.1, batch_size=4, epochs=1,
    no_models=8, number_of_total_participants=8, eta=0.8,
    aggregation_methods="mean", internal_epochs=1, internal_poison_epochs=1,
    is_poison=True, synthetic_data=True, synthetic_train_size=64,
    synthetic_test_size=64, momentum=0.9, decay=0.0005,
    sampling_dirichlet=False, local_eval=False, poison_label_swap=3,
    poisoning_per_batch=2, poison_lr=0.05, scale_weights_poison=2.0,
    adversary_list=[0], trigger_num=1, alpha_loss=1.0, random_seed=1,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2]],
       "0_poison_epochs": [1]})


def test_tiny_round_on_mesh_matches_single_device():
    """Tiny-ImageNet on the sharded clients axis — completes the
    workload×mesh matrix (MNIST/CIFAR-BN/LOAN covered above): the imagenet
    stem + max pool + global-average-pool graph with batch_stats trees
    through GSPMD. One round, same chaos rationale as the CIFAR-BN test."""
    e1, e8 = _pair(TINY8)
    r1 = e1.run_round(1)
    r8 = e8.run_round(1)
    assert np.isfinite(r8["global_acc"])
    # measured: max 6.4e-3 with 4 ppm of elements above 5e-3 (batch-4 BN
    # statistics amplify the reduction-order chaos harder than CIFAR's
    # batch-8); 2e-2 is the gross-divergence tripwire
    np.testing.assert_allclose(_flat(e1.global_vars.params),
                               _flat(e8.global_vars.params), atol=2e-2)
    np.testing.assert_allclose(_flat(e1.global_vars.batch_stats),
                               _flat(e8.global_vars.batch_stats), atol=5e-3)
    # 64-sample eval ⇒ 1.6% per sample
    assert abs(r1["global_acc"] - r8["global_acc"]) < 4.0
    assert abs(r1["backdoor_acc"] - r8["backdoor_acc"]) < 4.0


def test_loan_round_on_mesh_matches_single_device():
    """LOAN on the sharded clients axis — the one workload whose mesh path
    had no coverage: ragged per-state shards fetched by (slot, idx) gathers,
    feature-trigger stamping, lane-keyed dropout streams, and the blocking
    adaptive poison-LR probe (round 2 probes the round-1 planted backdoor,
    loan_train.py:67-75) must reproduce single-device numerics."""
    e1, e8 = _pair(LOAN8)
    for ep in (1, 2):
        r1 = e1.run_round(ep)
        r8 = e8.run_round(ep)
        assert np.isfinite(r8["global_acc"])
        assert abs(r1["global_acc"] - r8["global_acc"]) < 1.0
        assert abs(r1["backdoor_acc"] - r8["backdoor_acc"]) < 1.0
    # MLP matmul reductions reorder between the one-device [8·B] batch and
    # the per-device [B] kernels; two rounds of drift stay tiny
    np.testing.assert_allclose(_flat(e1.global_vars.params),
                               _flat(e8.global_vars.params), atol=1e-4)
    # every one of round 2's 8 sharded clients produced its local row
    assert len({row[0] for row in e8.recorder.test_result
                if row[0] != "global" and row[1] == 2}) == 8


@pytest.mark.parametrize("method", ["foolsgold", "geom_median"])
def test_defenses_on_mesh_match_single_device(method):
    """FoolsGold (feature all-gather + id-keyed memory scatter) and RFA
    (Weiszfeld distance collectives) over the sharded clients axis."""
    cfg = dict(MNIST8, aggregation_methods=method)
    e1, e8 = _pair(cfg)
    r1 = e1.run_round(1)
    r8 = e8.run_round(1)
    assert np.isfinite(r8["global_acc"])
    np.testing.assert_allclose(_flat(e1.global_vars.params),
                               _flat(e8.global_vars.params), atol=1e-4)
    # defense weight/alpha rows agree per client
    w1 = e1.recorder.weight_result
    w8 = e8.recorder.weight_result
    assert w1[0] == w8[0]                      # same client names
    np.testing.assert_allclose(w1[1], w8[1], atol=1e-4)  # wv
    np.testing.assert_allclose(w1[2], w8[2], atol=1e-3)  # alphas/distances
    if method == "foolsgold":
        # cross-round memory accumulated identically (id-keyed scatter)
        np.testing.assert_allclose(np.asarray(e1.fg_state.memory),
                                   np.asarray(e8.fg_state.memory),
                                   atol=1e-5)
        r1b = e1.run_round(2)
        r8b = e8.run_round(2)
        assert abs(r1b["global_acc"] - r8b["global_acc"]) < 1.0
