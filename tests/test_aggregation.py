"""Golden tests for the three aggregation rules against independent numpy
oracles implementing the reference semantics (helper.py:240-418, :527-607)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dba_mod_tpu.ops import aggregation as agg


def _rand_tree(rng, batch=None):
    shape = lambda *s: (batch,) + s if batch else s
    return {"dense": {"kernel": rng.randn(*shape(4, 3)).astype(np.float32),
                      "bias": rng.randn(*shape(3)).astype(np.float32)},
            "bn": {"mean": rng.randn(*shape(3)).astype(np.float32)}}


def _flat(tree_leaf_list):
    return np.concatenate([l.reshape(-1) for l in tree_leaf_list])


# ------------------------------------------------------------------- FedAvg
def test_fedavg_matches_manual():
    rng = np.random.RandomState(0)
    g = _rand_tree(rng)
    deltas = _rand_tree(rng, batch=5)
    eta, no_models = 0.1, 5
    new = agg.fedavg_update(g, jax.tree_util.tree_map(jnp.asarray, deltas),
                            eta, no_models)
    for path in [("dense", "kernel"), ("dense", "bias"), ("bn", "mean")]:
        got = np.asarray(new[path[0]][path[1]])
        exp = g[path[0]][path[1]] + eta / no_models * deltas[path[0]][path[1]].sum(0)
        np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)


# ------------------------------------------------------------------- RFA
def _numpy_weiszfeld(points, num_samples, maxiter=10, eps=1e-5, ftol=1e-6):
    """Independent oracle for helper.py:295-353: weighted-average start, then
    weights α_i / max(eps, ‖median − p_i‖), normalized; break on ftol."""
    alphas = np.asarray(num_samples, np.float64)
    alphas = alphas / alphas.sum()
    median = (alphas[:, None] * points).sum(0)
    obj = (alphas * np.linalg.norm(points - median, axis=1)).sum()
    calls, wv = 1, alphas.copy()
    for _ in range(maxiter):
        dist = np.linalg.norm(points - median, axis=1)
        w = alphas / np.maximum(eps, dist)
        w = w / w.sum()
        new_median = (w[:, None] * points).sum(0)
        new_obj = (alphas * np.linalg.norm(points - new_median, axis=1)).sum()
        calls += 1
        median, prev_obj, obj = new_median, obj, new_obj
        wv = w
        if abs(prev_obj - obj) < ftol * obj:
            break
    return median, wv, calls


def test_rfa_matches_numpy_oracle():
    rng = np.random.RandomState(1)
    g = _rand_tree(rng)
    deltas = _rand_tree(rng, batch=6)
    num_samples = np.array([100, 50, 80, 120, 60, 90], np.float32)

    res = agg.geometric_median_update(
        g, jax.tree_util.tree_map(jnp.asarray, deltas),
        jnp.asarray(num_samples), eta=0.1, maxiter=10)

    # leaf order: jax flattens dict keys alphabetically (bn < dense), and
    # within dense: bias < kernel
    points = np.stack([_flat([deltas["bn"]["mean"][i],
                              deltas["dense"]["bias"][i],
                              deltas["dense"]["kernel"][i]])
                       for i in range(6)])
    exp_median, exp_wv, exp_calls = _numpy_weiszfeld(points, num_samples)

    np.testing.assert_allclose(np.asarray(res.wv), exp_wv, rtol=1e-4)
    assert int(res.num_oracle_calls) == exp_calls
    assert bool(res.is_updated)
    got_state = _flat([np.asarray(res.new_state["bn"]["mean"]),
                       np.asarray(res.new_state["dense"]["bias"]),
                       np.asarray(res.new_state["dense"]["kernel"])])
    exp_state = _flat([g["bn"]["mean"], g["dense"]["bias"],
                       g["dense"]["kernel"]]) + 0.1 * exp_median
    np.testing.assert_allclose(got_state, exp_state, rtol=1e-4, atol=1e-5)


def test_rfa_identical_points_converges_immediately_no_crash():
    """Reference crashes at helper.py:371 when Weiszfeld converges at iter 0
    (wv=None); our fix reports the latest weights instead."""
    rng = np.random.RandomState(2)
    one = _rand_tree(rng)
    deltas = jax.tree_util.tree_map(
        lambda l: jnp.broadcast_to(jnp.asarray(l), (4,) + l.shape), one)
    res = agg.geometric_median_update(
        one, deltas, jnp.asarray(np.full(4, 10.0, np.float32)), eta=1.0)
    assert np.all(np.isfinite(np.asarray(res.wv)))
    assert int(res.num_oracle_calls) >= 1


# ------------------------------------------------------------------- FoolsGold
def _numpy_foolsgold(grads):
    """Independent oracle for FoolsGold.foolsgold (helper.py:574-607)."""
    import sklearn.metrics.pairwise as smp
    n = grads.shape[0]
    cs = smp.cosine_similarity(grads) - np.eye(n)
    maxcs = np.max(cs, axis=1)
    for i in range(n):
        for j in range(n):
            if i != j and maxcs[i] < maxcs[j]:
                cs[i][j] = cs[i][j] * maxcs[i] / maxcs[j]
    wv = 1 - (np.max(cs, axis=1))
    wv[wv > 1] = 1
    wv[wv < 0] = 0
    alpha = np.max(cs, axis=1)
    wv = wv / np.max(wv)
    wv[(wv == 1)] = .99
    wv = (np.log(wv / (1 - wv)) + 0.5)
    wv[(np.isinf(wv) + wv > 1)] = 1
    wv[(wv < 0)] = 0
    return wv, alpha


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_foolsgold_weights_match_numpy(seed):
    rng = np.random.RandomState(seed)
    grads = rng.randn(8, 30).astype(np.float32)
    # two sybils with near-identical gradient directions
    grads[6] = grads[7] + 0.01 * rng.randn(30).astype(np.float32)
    exp_wv, exp_alpha = _numpy_foolsgold(grads.astype(np.float64))
    got_wv, got_alpha = agg.foolsgold_weights(jnp.asarray(grads))
    np.testing.assert_allclose(np.asarray(got_wv), exp_wv, rtol=1e-3, atol=1e-3)
    # alpha is visualization-only in the reference; f32-vs-f64 cosine matrices
    # amplified through the pardoning ratios justify a looser tolerance.
    np.testing.assert_allclose(np.asarray(got_alpha), exp_alpha, rtol=1e-2,
                               atol=5e-3)


def test_foolsgold_sybils_downweighted():
    rng = np.random.RandomState(3)
    grads = rng.randn(6, 50).astype(np.float32)
    grads[4] = grads[5]  # perfect sybils
    wv, _ = agg.foolsgold_weights(jnp.asarray(grads))
    wv = np.asarray(wv)
    assert wv[4] < 0.01 and wv[5] < 0.01
    assert wv[:4].min() > 0.5


def test_foolsgold_update_applies_sgd_and_memory():
    rng = np.random.RandomState(4)
    params = {"w": jnp.asarray(rng.randn(5, 4).astype(np.float32)),
              "head": jnp.asarray(rng.randn(4, 3).astype(np.float32))}
    C, L = 4, 12
    grads = {"w": jnp.asarray(rng.randn(C, 5, 4).astype(np.float32)),
             "head": jnp.asarray(rng.randn(C, 4, 3).astype(np.float32))}
    feature = jnp.reshape(grads["head"], (C, L))
    ids = jnp.asarray([0, 3, 7, 9])
    st = agg.foolsgold_init(10, L)

    res = agg.foolsgold_update(params, grads, feature, ids, st, eta=0.1,
                               lr=0.1, momentum=0.9, weight_decay=0.0005)
    # memory accumulated at participant rows
    mem = np.asarray(res.new_fg_state.memory)
    np.testing.assert_allclose(mem[3], np.asarray(feature)[1], rtol=1e-6)
    assert (mem[1] == 0).all()

    # aggregation + torch-SGD apply: p' = p - lr*(eta*sum(wv*g)/C + wd*p)
    wv = np.asarray(res.wv)
    agg_w = (wv[:, None, None] * np.asarray(grads["w"])).sum(0) / C
    exp_w = np.asarray(params["w"]) - 0.1 * (
        0.1 * agg_w + 0.0005 * np.asarray(params["w"]))
    np.testing.assert_allclose(np.asarray(res.new_params["w"]), exp_w,
                               rtol=1e-4, atol=1e-6)


def test_foolsgold_memory_across_rounds():
    """use_memory=True computes similarity on the historical sum
    (helper.py:545-553): sybils that alternate directions each round are still
    caught by the memory."""
    rng = np.random.RandomState(5)
    L = 20
    st = agg.foolsgold_init(4, L)
    base = rng.randn(L).astype(np.float32)
    ids = jnp.arange(4)
    for sign in (1.0, 1.0):
        feature = np.stack([rng.randn(L), rng.randn(L),
                            sign * base, sign * base]).astype(np.float32)
        params = {"w": jnp.zeros((2, 2))}
        grads = {"w": jnp.zeros((4, 2, 2))}
        res = agg.foolsgold_update(params, grads, jnp.asarray(feature), ids,
                                   st, eta=0.1, lr=0.1, momentum=0.0,
                                   weight_decay=0.0)
        st = res.new_fg_state
    wv = np.asarray(res.wv)
    assert wv[2] < 0.01 and wv[3] < 0.01
    assert wv[0] > 0.5 and wv[1] > 0.5


# --------------------------------------- Krum / trimmed mean / coord median
def _numpy_krum(points, m, f):
    """Independent oracle for Blanchard et al.'s (multi-)Krum over a dense
    [n, P] point set: score = sum of the n-f-2 smallest squared distances
    (clipped to [1, n-1] neighbors), select the m lowest scores, average."""
    n = points.shape[0]
    d2 = ((points[:, None, :] - points[None, :, :]) ** 2).sum(-1)
    nb = int(np.clip(n - f - 2, 1, n - 1))
    scores = np.array([np.sort(np.delete(d2[i], i))[:nb].sum()
                       for i in range(n)])
    sel = np.argsort(scores, kind="stable")[:m]
    return scores, sel, points[sel].mean(0)


@pytest.mark.parametrize("m,f", [(1, 0), (2, 1), (3, 2)])
def test_krum_matches_numpy_oracle(m, f):
    rng = np.random.RandomState(7)
    g = _rand_tree(rng)
    deltas = _rand_tree(rng, batch=7)
    res = agg.krum_update(g, jax.tree_util.tree_map(jnp.asarray, deltas),
                          eta=0.5, num_selected=m, byz_f=f)
    points = np.stack([_flat([deltas["bn"]["mean"][i],
                              deltas["dense"]["bias"][i],
                              deltas["dense"]["kernel"][i]])
                       for i in range(7)]).astype(np.float64)
    exp_scores, exp_sel, exp_mean = _numpy_krum(points, m, f)
    np.testing.assert_allclose(np.asarray(res.scores), exp_scores,
                               rtol=1e-4, atol=1e-5)
    got_sel = np.flatnonzero(np.asarray(res.wv) > 0)
    assert sorted(got_sel) == sorted(exp_sel)
    np.testing.assert_allclose(np.asarray(res.wv)[got_sel], 1.0 / m)
    got = _flat([np.asarray(res.new_state["bn"]["mean"]),
                 np.asarray(res.new_state["dense"]["bias"]),
                 np.asarray(res.new_state["dense"]["kernel"])])
    exp = _flat([g["bn"]["mean"], g["dense"]["bias"],
                 g["dense"]["kernel"]]) + 0.5 * exp_mean
    np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


def test_krum_outlier_rejected():
    rng = np.random.RandomState(8)
    deltas = _rand_tree(rng, batch=6)
    g = jax.tree_util.tree_map(lambda l: np.zeros_like(l[0]), deltas)
    # one blown-up client far from the benign cluster
    deltas["dense"]["kernel"][5] *= 1e4
    res = agg.krum_update(g, jax.tree_util.tree_map(jnp.asarray, deltas),
                          eta=1.0, num_selected=2, byz_f=1)
    assert np.asarray(res.wv)[5] == 0.0


@pytest.mark.parametrize("beta", [0.0, 0.2, 0.4])
def test_trimmed_mean_matches_numpy_oracle(beta):
    rng = np.random.RandomState(9)
    g = _rand_tree(rng)
    deltas = _rand_tree(rng, batch=6)
    res = agg.trimmed_mean_update(
        g, jax.tree_util.tree_map(jnp.asarray, deltas), eta=0.3, beta=beta)
    n = 6
    k = min(int(np.floor(beta * n)), (n - 1) // 2)
    for p0, p1 in [("dense", "kernel"), ("dense", "bias"), ("bn", "mean")]:
        vals = np.sort(deltas[p0][p1].astype(np.float64), axis=0)
        tm = vals[k:n - k].mean(0)
        np.testing.assert_allclose(np.asarray(res.new_state[p0][p1]),
                                   g[p0][p1] + 0.3 * tm, rtol=1e-4,
                                   atol=1e-6)
    np.testing.assert_allclose(np.asarray(res.wv), np.full(6, 1.0 / 6),
                               rtol=1e-6)


@pytest.mark.parametrize("n", [5, 6])
def test_coordinate_median_matches_numpy(n):
    rng = np.random.RandomState(10)
    g = _rand_tree(rng)
    deltas = _rand_tree(rng, batch=n)
    res = agg.coordinate_median_update(
        g, jax.tree_util.tree_map(jnp.asarray, deltas), eta=1.0)
    for p0, p1 in [("dense", "kernel"), ("dense", "bias"), ("bn", "mean")]:
        med = np.median(deltas[p0][p1].astype(np.float64), axis=0)
        np.testing.assert_allclose(np.asarray(res.new_state[p0][p1]),
                                   g[p0][p1] + med, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize("rule", ["krum", "trim", "median"])
def test_masked_rule_equals_dense_on_survivor_subset(rule):
    """The survivor-mask contract: running the masked rule over C clients
    with a mask selecting a subset must equal the dense rule over just that
    subset — excluded rows (even NaN/Inf-poisoned ones) cannot leak into
    the geometry, scores, or the applied update."""
    rng = np.random.RandomState(11)
    g = _rand_tree(rng)
    deltas = _rand_tree(rng, batch=7)
    mask_np = np.array([1, 0, 1, 1, 0, 1, 1], bool)
    # quarantined payloads may be non-finite — exclusion must select
    deltas["dense"]["kernel"][1] = np.nan
    deltas["bn"]["mean"][4] = np.inf
    sub = jax.tree_util.tree_map(lambda l: jnp.asarray(l[mask_np]), deltas)
    full = jax.tree_util.tree_map(jnp.asarray, deltas)
    mask = jnp.asarray(mask_np)
    if rule == "krum":
        rm = agg.krum_update(g, full, 0.5, 2, 1, mask=mask)
        rd = agg.krum_update(g, sub, 0.5, 2, 1)
        np.testing.assert_allclose(
            np.asarray(rm.scores)[mask_np], np.asarray(rd.scores),
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(rm.wv)[mask_np], np.asarray(rd.wv), rtol=1e-6)
        assert (np.asarray(rm.wv)[~mask_np] == 0).all()
    elif rule == "trim":
        rm = agg.trimmed_mean_update(g, full, 0.5, 0.2, mask=mask)
        rd = agg.trimmed_mean_update(g, sub, 0.5, 0.2)
    else:
        rm = agg.coordinate_median_update(g, full, 0.5, mask=mask)
        rd = agg.coordinate_median_update(g, sub, 0.5)
    for p0, p1 in [("dense", "kernel"), ("dense", "bias"), ("bn", "mean")]:
        got = np.asarray(rm.new_state[p0][p1])
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got, np.asarray(rd.new_state[p0][p1]),
                                   rtol=1e-5, atol=1e-6)
