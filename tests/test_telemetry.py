"""Telemetry subsystem (utils/telemetry.py) + the recorder's crash-safe
saves.

Covers: span nesting and timing monotonicity, Chrome-trace JSON schema,
counter/gauge/histogram flush semantics (cumulative counters, windowed
histograms), the XLA recompile listener (fires on a forced retrace, silent
on a cache hit), no-op mode adding no files, idempotent logging setup, the
recorder's atomic save (a failure mid-write leaves the previous file
intact), and the end-to-end Experiment wiring — telemetry files with the
required per-round spans, and none at all when the knob is off.
"""
import csv
import json
import logging
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment
from dba_mod_tpu.utils import telemetry as tel
from dba_mod_tpu.utils.recorder import ROUND_HEADER, Recorder

SMOKE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=2, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=True, random_seed=1)


@pytest.fixture
def enabled_tel(tmp_path):
    t = tel.configure(enabled=True, folder=tmp_path)
    yield t
    tel.configure(enabled=False)


# ------------------------------------------------------------------- spans
def test_span_nesting_and_timing_monotonicity(enabled_tel):
    with tel.span("outer"):
        time.sleep(0.01)
        with tel.span("inner"):
            time.sleep(0.01)
    events = {e["name"]: e for e in enabled_tel._trace_events}
    outer, inner = events["outer"], events["inner"]
    assert inner["dur"] > 0 and outer["dur"] >= inner["dur"]
    # containment: the inner span starts no earlier and ends no later
    assert inner["ts"] >= outer["ts"]
    assert (inner["ts"] + inner["dur"]
            <= outer["ts"] + outer["dur"] + 1.0)  # 1 µs slack
    # spans feed duration histograms
    assert enabled_tel.histogram("span/outer").total_count == 1
    assert enabled_tel.histogram("span/inner").total_count == 1


def test_span_stack_feeds_phase_context(enabled_tel):
    assert enabled_tel.phase() == "-"
    with tel.span("round/dispatch"):
        assert enabled_tel.phase() == "round/dispatch"
        with tel.span("eval/global"):
            assert enabled_tel.phase() == "eval/global"
        assert enabled_tel.phase() == "round/dispatch"
    assert enabled_tel.phase() == "-"


def test_chrome_trace_schema(enabled_tel, tmp_path):
    with tel.span("a"):
        with tel.span("b"):
            pass
    enabled_tel.write_trace()
    doc = json.loads((tmp_path / "trace.json").read_text())
    assert isinstance(doc["traceEvents"], list)
    complete = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert {e["name"] for e in complete} == {"a", "b"}
    for e in complete:
        for key in ("name", "ph", "ts", "dur", "pid", "tid"):
            assert key in e
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # metadata record present (process naming for Perfetto)
    assert any(e.get("ph") == "M" for e in doc["traceEvents"])


def test_sync_returns_payload(enabled_tel):
    x = jnp.ones((3,)) * 2
    assert tel.sync(x) is x
    np.testing.assert_array_equal(np.asarray(x), 2.0)


# ---------------------------------------------------------------- registry
def test_counter_histogram_flush_and_window_reset(enabled_tel, tmp_path):
    enabled_tel.counter("rounds").inc()
    enabled_tel.counter("rounds").inc(2)
    enabled_tel.histogram("delta_norm").observe(1.0)
    enabled_tel.histogram("delta_norm").observe(3.0)
    enabled_tel.gauge("g").set(7.0)
    enabled_tel.flush_round(1)
    enabled_tel.flush_round(2)
    lines = [json.loads(line) for line in
             (tmp_path / "telemetry.jsonl").read_text().splitlines()]
    assert [ln["epoch"] for ln in lines] == [1, 2]
    assert lines[0]["counters"]["rounds"] == 3
    h = lines[0]["histograms"]["delta_norm"]
    assert h["count"] == 2 and h["min"] == 1.0 and h["max"] == 3.0
    assert h["p95"] == 3.0 and h["sum"] == 4.0
    assert lines[0]["gauges"]["g"] == 7.0
    # histograms are windowed per flush; counters are cumulative
    assert "delta_norm" not in lines[1]["histograms"]
    assert lines[1]["counters"]["rounds"] == 3


# ----------------------------------------------------------- XLA listeners
def test_recompile_listener_fires_on_retrace_not_cache_hit(enabled_tel):
    salt = np.float32(time.time() % 97)  # defeat any persistent jit reuse

    @jax.jit
    def f(x):
        return x * 2.0 + salt

    f(jnp.ones((4,)))  # warmup compile
    assert enabled_tel.counter("xla/compiles").value >= 1
    enabled_tel.mark_warm()
    f(jnp.ones((4,)))  # jit cache hit: must stay silent
    assert enabled_tel.counter("xla/recompiles_after_warmup").value == 0
    f(jnp.ones((5,)))  # new shape: forced retrace, counted loudly
    assert enabled_tel.counter("xla/recompiles_after_warmup").value >= 1


def test_mark_warm_is_idempotent(enabled_tel):
    enabled_tel.mark_warm()
    enabled_tel.mark_warm()
    assert enabled_tel._warm
    assert enabled_tel.counter("xla/recompiles_after_warmup").value == 0


def test_record_memory_never_raises(enabled_tel):
    enabled_tel.record_memory()  # CPU backend reports None → no-op


# ------------------------------------------------------------- no-op mode
def test_noop_mode_adds_no_files_and_no_state(tmp_path):
    t = tel.configure(enabled=False, folder=tmp_path)
    assert t is tel.NULL and not t.enabled
    with tel.span("x"):
        pass
    tel.count("c")
    tel.observe("h", 1.0)
    tel.set_gauge("g", 2.0)
    tel.sync(jnp.ones((2,)))
    t.flush_round(1)
    t.write_trace()
    t.close()
    assert list(tmp_path.iterdir()) == []


def test_instrument_is_passthrough_when_disabled(enabled_tel):
    calls = []

    def f(x):
        calls.append(x)
        return x + 1

    tel.configure(enabled=False)
    g = tel.instrument(f, "probe", batches=5)
    assert g(1) == 2
    t2 = tel.configure(enabled=True)
    assert g(2) == 3
    assert calls == [1, 2]
    assert t2.counter("eval/batches").value == 5
    assert t2.histogram("span/probe").total_count == 1
    tel.configure(enabled=False)


# ------------------------------------------------------------ logging setup
def test_logging_setup_is_idempotent_and_replaces_run_file(tmp_path):
    lg = tel.setup_logging(tmp_path)
    n = len(lg.handlers)
    assert tel.setup_logging(tmp_path) is lg
    assert len(lg.handlers) == n  # same folder: nothing added
    other = tmp_path / "other"
    other.mkdir()
    tel.setup_logging(other)
    run_files = [h for h in lg.handlers
                 if getattr(h, "_dba_run_file", False)]
    assert len(run_files) == 1  # replaced, not stacked
    assert run_files[0].baseFilename.endswith(str(other / "log.txt"))
    assert lg.propagate is False


# --------------------------------------------------- recorder atomic saves
def test_recorder_atomic_save_keeps_previous_csv_on_failure(tmp_path):
    rec = Recorder(tmp_path)
    rec.add_test("global", 1, 0.5, 90.0, 9, 10)
    rec.add_round_json(epoch=1, global_acc=90.0, round_time=0.1,
                       dispatch_time=0.08, finalize_time=0.02)
    rec.save(is_poison=False)
    before_csv = (tmp_path / "round_result.csv").read_text()
    before_jsonl = (tmp_path / "metrics.jsonl").read_text()

    class Poison:
        def __str__(self):
            raise RuntimeError("boom mid-write")

    rec.round_result.append([Poison()])
    with pytest.raises(RuntimeError):
        rec.save(is_poison=False)
    # the interrupted rewrite left the previous files byte-identical
    assert (tmp_path / "round_result.csv").read_text() == before_csv
    assert (tmp_path / "metrics.jsonl").read_text() == before_jsonl
    assert not list(tmp_path.glob("*.tmp"))


def test_recorder_atomic_save_keeps_previous_jsonl_on_failure(tmp_path):
    rec = Recorder(tmp_path)
    rec.add_round_json(epoch=1, global_acc=1.0)
    rec.save(is_poison=False)
    before = (tmp_path / "metrics.jsonl").read_text()
    rec._jsonl_rows.append({"bad": object()})  # not JSON-serializable
    with pytest.raises(TypeError):
        rec.save(is_poison=False)
    assert (tmp_path / "metrics.jsonl").read_text() == before
    assert not list(tmp_path.glob("*.tmp"))


def test_round_header_carries_split_times():
    assert ROUND_HEADER[-3:] == ["round_time", "dispatch_time",
                                 "finalize_time"]


# ------------------------------------------------------------- end-to-end
def test_experiment_telemetry_end_to_end(tmp_path):
    e = Experiment(Params.from_dict(dict(
        SMOKE, telemetry=True, run_dir=str(tmp_path))))
    try:
        e.run()
        folder = e.folder
        assert (folder / "telemetry.jsonl").exists()
        assert (folder / "trace.json").exists()
        doc = json.loads((folder / "trace.json").read_text())
        names = {ev["name"] for ev in doc["traceEvents"]
                 if ev.get("ph") == "X"}
        assert {"round/dispatch", "round/finalize", "round/train",
                "round/aggregate", "eval/local", "eval/global"} <= names
        lines = [json.loads(line) for line in
                 (folder / "telemetry.jsonl").read_text().splitlines()]
        assert [ln["epoch"] for ln in lines] == [1, 2]
        last = lines[-1]
        # per-round span durations for dispatch/finalize/eval
        for span in ("span/round/dispatch", "span/round/finalize",
                     "span/eval/global"):
            assert last["histograms"][span]["count"] >= 1
        assert last["counters"]["rounds"] == 2
        assert last["counters"]["eval/batches"] > 0
        # no retraces once the first full round has compiled everything
        assert last["counters"]["xla/recompiles_after_warmup"] == 0
        # the recorder carries the honest split times
        with open(folder / "round_result.csv") as f:
            rows = list(csv.reader(f))
        assert rows[0] == ROUND_HEADER
        times = dict(zip(rows[0], rows[1]))
        assert float(times["dispatch_time"]) > 0
        assert float(times["finalize_time"]) > 0
        summary = e.telemetry.summary_table()
        assert "round/dispatch" in summary and "xla compiles" in summary
    finally:
        tel.configure(enabled=False)


def test_experiment_telemetry_off_writes_no_files(tmp_path):
    e = Experiment(Params.from_dict(dict(SMOKE, run_dir=str(tmp_path))))
    e.run_round(1)
    assert not (e.folder / "telemetry.jsonl").exists()
    assert not (e.folder / "trace.json").exists()
    assert e.telemetry is tel.NULL


def test_split_path_falls_back_after_takeover(tmp_path):
    """A later configure() (another Experiment taking over the process-wide
    instance) must not leave the first experiment paying the split path's
    per-phase syncs with no spans recorded — it falls back to the fused
    program while still flushing per-round metrics on its own instance."""
    e = Experiment(Params.from_dict(dict(
        SMOKE, telemetry=True, telemetry_dir=str(tmp_path / "t"))),
        save_results=False)
    try:
        assert e._telemetry_split
        tel.configure(enabled=False)  # a second experiment takes over
        assert not e._telemetry_split  # → fused dispatch from here on
        r = e.run_round(1)
        assert r["dispatch_time"] > 0
        lines = [json.loads(line) for line in
                 (tmp_path / "t" / "telemetry.jsonl").read_text()
                 .splitlines()]
        assert lines and lines[-1]["counters"]["rounds"] == 1
    finally:
        tel.configure(enabled=False)


def test_telemetry_split_path_matches_fused_metrics(tmp_path):
    """telemetry=true routes rounds through the split-phase programs (the
    same computations the fused round runs, as separate jits); the recorded
    round metrics must agree with the fused path's."""
    r_fused = Experiment(Params.from_dict(dict(SMOKE)),
                         save_results=False).run_round(1)
    e = Experiment(Params.from_dict(dict(
        SMOKE, telemetry=True, telemetry_dir=str(tmp_path / "t"))),
        save_results=False)
    try:
        r_split = e.run_round(1)
        assert r_split["agents"] == r_fused["agents"]
        np.testing.assert_allclose(r_split["global_acc"],
                                   r_fused["global_acc"], rtol=1e-5)
    finally:
        tel.configure(enabled=False)
