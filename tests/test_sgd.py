"""Golden tests: torch-semantics SGD and LR schedules vs real torch (CPU).

The client step's optimizer must match torch.optim.SGD(lr, momentum,
weight_decay) and torch MultiStepLR including its float-milestone quirk
(reference image_train.py:33-35, :66-68) — torch itself is the oracle here.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dba_mod_tpu.ops import sgd as sgd_ops


def _torch_sgd_trajectory(params0, grads_seq, lr, momentum, wd):
    import torch
    ps = [torch.nn.Parameter(torch.tensor(p)) for p in params0]
    opt = torch.optim.SGD(ps, lr=lr, momentum=momentum, weight_decay=wd)
    for grads in grads_seq:
        opt.zero_grad()
        for p, g in zip(ps, grads):
            p.grad = torch.tensor(g)
        opt.step()
    return [p.detach().numpy() for p in ps]


def test_sgd_matches_torch_multi_step():
    rng = np.random.RandomState(0)
    params0 = [rng.randn(4, 3).astype(np.float32),
               rng.randn(5).astype(np.float32)]
    grads_seq = [[rng.randn(4, 3).astype(np.float32),
                  rng.randn(5).astype(np.float32)] for _ in range(5)]

    expected = _torch_sgd_trajectory(params0, grads_seq, lr=0.1, momentum=0.9,
                                     wd=0.0005)

    params = [jnp.asarray(p) for p in params0]
    buf = sgd_ops.sgd_init(params)
    for grads in grads_seq:
        params, buf = sgd_ops.sgd_step(params, [jnp.asarray(g) for g in grads],
                                       buf, 0.1, 0.9, 0.0005)
    for got, exp in zip(params, expected):
        np.testing.assert_allclose(np.asarray(got), exp, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("E,step_before", [(10, False), (6, False), (5, False),
                                           (10, True), (6, True)])
def test_multistep_lr_matches_torch(E, step_before):
    import torch
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=1.0)
    sched = torch.optim.lr_scheduler.MultiStepLR(
        opt, milestones=[0.2 * E, 0.8 * E], gamma=0.1)
    torch_lrs = []
    for _ in range(1, E + 1):
        if step_before:
            sched.step()
        torch_lrs.append(opt.param_groups[0]["lr"])
        if not step_before:
            opt.step()
            sched.step()
    ours = sgd_ops.poison_multistep_lr_array(E, 0.1, step_before=step_before)
    np.testing.assert_allclose(ours, np.array(torch_lrs, np.float32), rtol=1e-6)


def test_float_milestones_never_fire_for_E6():
    # 0.2*6 = 1.2000000000000002 — torch never decays; we must not either.
    ours = sgd_ops.poison_multistep_lr_array(6, 0.1, step_before=False)
    np.testing.assert_array_equal(ours, np.ones(6, np.float32))


def test_loan_adaptive_poison_lr():
    lr = sgd_ops.loan_adaptive_poison_lr(0.0005, jnp.float32(10.0), False)
    assert np.isclose(float(lr), 0.0005)
    lr = sgd_ops.loan_adaptive_poison_lr(0.0005, jnp.float32(30.0), False)
    assert np.isclose(float(lr), 0.0001)
    lr = sgd_ops.loan_adaptive_poison_lr(0.0005, jnp.float32(70.0), False)
    assert np.isclose(float(lr), 1e-5)
    # baseline flag disables adaptation (loan_train.py:71)
    lr = sgd_ops.loan_adaptive_poison_lr(0.0005, jnp.float32(70.0), True)
    assert np.isclose(float(lr), 0.0005)


def test_cross_entropy_matches_torch():
    import torch
    import torch.nn.functional as F
    from dba_mod_tpu.ops import losses

    rng = np.random.RandomState(1)
    logits = rng.randn(8, 10).astype(np.float32)
    labels = rng.randint(0, 10, size=(8,))
    exp_mean = float(F.cross_entropy(torch.tensor(logits),
                                     torch.tensor(labels)))
    exp_sum = float(F.cross_entropy(torch.tensor(logits),
                                    torch.tensor(labels), reduction="sum"))
    got_mean = float(losses.cross_entropy(jnp.asarray(logits),
                                          jnp.asarray(labels)))
    got_sum = float(losses.cross_entropy_sum(jnp.asarray(logits),
                                             jnp.asarray(labels)))
    assert np.isclose(got_mean, exp_mean, rtol=1e-5)
    assert np.isclose(got_sum, exp_sum, rtol=1e-5)

    # masked mean == torch mean over the valid prefix
    mask = np.array([1, 1, 1, 1, 1, 0, 0, 0], np.float32)
    exp_masked = float(F.cross_entropy(torch.tensor(logits[:5]),
                                       torch.tensor(labels[:5])))
    got_masked = float(losses.cross_entropy(jnp.asarray(logits),
                                            jnp.asarray(labels),
                                            jnp.asarray(mask)))
    assert np.isclose(got_masked, exp_masked, rtol=1e-5)


def test_dist_norm_matches_reference_semantics():
    from dba_mod_tpu.ops import losses
    a = {"w": jnp.ones((3, 3)), "b": jnp.full((3,), 2.0)}
    b = {"w": jnp.zeros((3, 3)), "b": jnp.zeros((3,))}
    # sqrt(9*1 + 3*4) = sqrt(21)
    assert np.isclose(float(losses.tree_dist_norm(a, b)), np.sqrt(21.0))
    assert np.isclose(float(losses.tree_global_norm(a)), np.sqrt(21.0))
