"""End-to-end elastic multi-host harness (the PR-6 acceptance test, in the
style of tests/test_crash_harness.py): real processes, real SIGKILL, real
jax.distributed worlds.

- Launch a REAL 2-process jax.distributed run (2 x 4 virtual CPU devices =
  one 8-device clients mesh) through the standard `main.py train` CLI,
  SIGKILL worker 1 mid-run, and assert the survivor exits with the
  distinct EXIT_PEER_LOST code (77) — bounded by watchdog_hard_s, never a
  hang — leaving a manifest-verified checkpoint.
- Relaunch the survivors SHRUNK (one process, half the devices) with
  ``--resume auto`` and assert the experiment completes in the same run
  folder, every round recorded exactly once.
- Assert the recorded metrics for every round committed BEFORE the loss
  are bit-identical to an uninterrupted 2-process run with the same seed
  (the post-loss rounds run on a different — shrunk — mesh, whose FedAvg
  reduction order may differ in the last ulp; the committed prefix must
  not).

Subprocesses share the suite's persistent XLA compile cache, so each
launch pays import time but not a fresh compile."""
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest
import yaml

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.utils.run_guard import EXIT_PEER_LOST

REPO = Path(__file__).resolve().parent.parent

BASE_CFG = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=5, no_models=8,
    number_of_total_participants=8, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=256, synthetic_test_size=128, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False,
    random_seed=5, num_devices=-1, run_name="elastic", save_model=True,
    graceful_shutdown=True, heartbeat_interval_s=0.5,
    heartbeat_timeout_s=4.0, watchdog_soft_s=60, watchdog_hard_s=120)

VOLATILE = {"time", "round_time", "dispatch_time", "finalize_time"}


def _env(world=None):
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID", "JAX_COORDINATOR_ADDRESS"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_dba_tests")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    if world is not None:
        coord, n, pid = world
        env["JAX_COORDINATOR_ADDRESS"] = coord
        env["JAX_NUM_PROCESSES"] = str(n)
        env["JAX_PROCESS_ID"] = str(pid)
    return env


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_cfg(tmp_path, name, **overrides):
    cfg = dict(BASE_CFG, run_dir=str(tmp_path / name), **overrides)
    path = tmp_path / f"{name}.yaml"
    path.write_text(yaml.dump(cfg))
    return path, cfg


def _launch_world(cfg_path, n_procs, *extra):
    coord = f"127.0.0.1:{_free_port()}"
    return [subprocess.Popen(
        [sys.executable, "-m", "dba_mod_tpu.main", "train",
         "--params", str(cfg_path), *extra],
        cwd=REPO, env=_env((coord, n_procs, pid)),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in range(n_procs)]


def _rounds_recorded(run_dir: Path) -> int:
    f = run_dir / "elastic" / "round_result.csv"
    if not f.exists():
        return 0
    return max(0, len(f.read_text().strip().splitlines()) - 1)


def _metrics_rows(run_dir: Path):
    with open(run_dir / "elastic" / "metrics.jsonl") as f:
        return [json.loads(line) for line in f if line.strip()]


def _strip(row):
    return {k: v for k, v in row.items() if k not in VOLATILE}


def test_peer_loss_exit77_then_shrunk_resume_bit_identical(tmp_path):
    # ---- uninterrupted 2-process reference (same seed, separate run_dir)
    ref_path, ref_cfg = _write_cfg(tmp_path, "ref")
    procs = _launch_world(ref_path, 2)
    outs = [p.communicate(timeout=900)[0] for p in procs]
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"ref proc {pid} rc={p.returncode}\n" \
                                  f"{out[-4000:]}"
    ref_rows = _metrics_rows(Path(ref_cfg["run_dir"]))
    assert [r["epoch"] for r in ref_rows] == list(range(1, 6))

    # ---- crash world: SIGKILL worker 1 once >= 2 rounds committed
    crash_path, crash_cfg = _write_cfg(tmp_path, "crash")
    run_dir = Path(crash_cfg["run_dir"])
    procs = _launch_world(crash_path, 2)
    try:
        # wait for >= 2 rounds recorded AND a verified checkpoint at >= 2:
        # the kill must land after round 2's snapshot committed, so the
        # bit-identity window below provably covers two rounds
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            ep = ckpt.manifest_epoch(
                run_dir / "elastic" / "model_last.pt.tar")
            if _rounds_recorded(run_dir) >= 2 and (ep or 0) >= 2:
                break
            if any(p.poll() is not None for p in procs):
                outs = [p.communicate(timeout=10)[0] for p in procs]
                pytest.fail("a worker died before the kill landed:\n"
                            + "\n".join(o[-2000:] for o in outs))
            time.sleep(0.25)
        committed = _rounds_recorded(run_dir)
        assert committed >= 2, "no 2 committed rounds within the budget"
        procs[1].kill()  # SIGKILL: no handlers, no cleanup — a lost host
        procs[1].wait(timeout=60)
        assert procs[1].returncode == -signal.SIGKILL

        # the survivor must classify the loss and exit 77 on its own,
        # bounded by watchdog_hard_s + classification slack — never hang
        out0, _ = procs[0].communicate(
            timeout=BASE_CFG["watchdog_hard_s"] + 120)
        assert procs[0].returncode == EXIT_PEER_LOST, \
            f"survivor rc={procs[0].returncode}\n{out0[-4000:]}"
        assert "peer lost" in out0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    # a manifest-verified checkpoint is on disk — the shrunk relaunch's
    # resume point. The peer can die MID-SAVE (force=True already deleted
    # the previous model_last); the .prev protection guarantees a verified
    # fallback survives that race, so discover like the resume does.
    resume_pt = ckpt.latest_verified_checkpoint(run_dir / "elastic",
                                                quarantine=False)
    assert resume_pt is not None, \
        "no verified checkpoint survived the peer loss"
    resume_epoch = ckpt.manifest_epoch(resume_pt)
    assert resume_epoch and resume_epoch >= 2

    # ---- relaunch the survivors SHRUNK: 1 process, 4 devices
    proc = subprocess.Popen(
        [sys.executable, "-m", "dba_mod_tpu.main", "train",
         "--params", str(crash_path), "--resume", "auto"],
        cwd=REPO, env=_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out, _ = proc.communicate(timeout=900)
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out[-4000:]}"
    assert "final: epoch=5" in out

    # same folder, every round exactly once, final checkpoint verified
    rows = _metrics_rows(run_dir)
    assert [r["epoch"] for r in rows] == list(range(1, 6))
    ok, reason = ckpt.verify_checkpoint(
        run_dir / "elastic" / "model_last.pt.tar")
    assert ok, reason

    # ---- bit-identity of every round committed BEFORE the loss: rows up
    # to the verified resume point are the ORIGINAL 2-process world's rows
    # (the recorder stream truncates past the resume epoch and continues),
    # so they must match the uninterrupted reference byte-for-byte. Rounds
    # after the resume point re-ran on the shrunk mesh, whose FedAvg
    # reduction order may differ in the last ulp — excluded by design.
    assert resume_epoch >= 2
    for ref, got in zip(ref_rows[:resume_epoch], rows[:resume_epoch]):
        assert _strip(ref) == _strip(got), \
            f"epoch {ref['epoch']} diverged before the loss round"
