"""`fetch` preflight subcommand units (PR 6 satellite, VERDICT Missing
#3): status classification, sha256 verification, check-only exit codes,
and the explicit synthetic-fallback printout — all with zero network."""
import gzip
import hashlib

import pytest

from dba_mod_tpu.data import fetch as F


def _sha(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def test_every_dataset_has_pinned_or_documented_sources():
    assert set(F.DATASETS) == {"mnist", "cifar", "tiny-imagenet-200",
                               "loan"}
    for spec in F.DATASETS.values():
        assert spec.files, spec.name
        for rf in spec.files:
            # every artifact either has an URL or documents the manual path
            assert rf.url or spec.post_steps
    # stable upstreams are sha256-pinned (64 hex chars)
    for rf in F.DATASETS["mnist"].files + F.DATASETS["cifar"].files:
        assert rf.sha256 and len(rf.sha256) == 64
        int(rf.sha256, 16)


def test_check_missing_reports_urls(tmp_path):
    status, details = F.check_dataset("cifar", tmp_path)
    assert status == F.MISSING
    assert any("cs.toronto.edu" in d for d in details)


def test_check_ready_short_circuits(tmp_path):
    (tmp_path / "cifar-10-batches-py").mkdir()
    (tmp_path / "cifar-10-batches-py" / "data_batch_1").write_bytes(b"x")
    status, _ = F.check_dataset("cifar", tmp_path)
    assert status == F.READY


def test_check_archive_verifies_pinned_sha(tmp_path, monkeypatch):
    payload = b"definitely-a-cifar-tarball"
    (tmp_path / "cifar-10-python.tar.gz").write_bytes(payload)
    # wrong bytes vs the real pin -> corrupt
    status, details = F.check_dataset("cifar", tmp_path)
    assert status == F.CORRUPT
    assert any("MISMATCH" in d for d in details)
    # re-pin to the actual payload hash -> verified archive
    spec = F.DATASETS["cifar"]
    monkeypatch.setitem(
        F.DATASETS, "cifar",
        F.DatasetSpec(spec.name,
                      [F.RemoteFile(spec.files[0].relpath,
                                    spec.files[0].url, _sha(payload))],
                      spec.ready_probe, spec.post_steps))
    status, details = F.check_dataset("cifar", tmp_path)
    assert status == F.ARCHIVE
    assert any("verified" in d for d in details)


def test_check_unpinned_artifact_reports_computed_sha(tmp_path):
    payload = b"tiny-zip-bytes"
    (tmp_path / "tiny-imagenet-200.zip").write_bytes(payload)
    status, details = F.check_dataset("tiny-imagenet-200", tmp_path)
    assert status == F.ARCHIVE
    assert any(_sha(payload) in d for d in details)  # pinnable digest shown


def test_mnist_gz_files_are_loader_ready(tmp_path):
    raw = tmp_path / "MNIST" / "raw"
    raw.mkdir(parents=True)
    for rf in F.DATASETS["mnist"].files:
        name = rf.relpath.split("/")[-1]
        with gzip.open(raw / name, "wb") as f:
            f.write(b"idx")
    status, _ = F.check_dataset("mnist", tmp_path)
    assert status == F.READY


def test_loan_is_manual_then_ready(tmp_path):
    status, details = F.check_dataset("loan", tmp_path)
    assert status == F.MANUAL
    assert any("loan-etl" in d for d in details)
    (tmp_path / "loan").mkdir()
    (tmp_path / "loan" / "loan_CA.csv").write_text("loan_status\n1\n")
    status, _ = F.check_dataset("loan", tmp_path)
    assert status == F.READY


def test_run_preflight_check_only_exit_codes(tmp_path, capsys):
    rc = F.run_preflight(["cifar"], str(tmp_path), check_only=True)
    out = capsys.readouterr().out
    assert rc == 1
    assert "DETERMINISTIC SYNTHETIC" in out   # the explicit fallback note
    (tmp_path / "cifar-10-batches-py").mkdir()
    (tmp_path / "cifar-10-batches-py" / "data_batch_1").write_bytes(b"x")
    rc = F.run_preflight(["cifar"], str(tmp_path), check_only=True)
    out = capsys.readouterr().out
    assert rc == 0
    assert "DETERMINISTIC SYNTHETIC" not in out


def test_run_preflight_fetch_downloads_and_extracts(tmp_path, monkeypatch):
    """Network path with urlopen stubbed: download → verify → extract →
    READY, no real sockets."""
    import io
    import tarfile

    # build a tiny tar.gz that extracts to the loader layout
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as t:
        data = b"batch-bytes"
        info = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
        info.size = len(data)
        t.addfile(info, io.BytesIO(data))
    payload = buf.getvalue()

    spec = F.DATASETS["cifar"]
    monkeypatch.setitem(
        F.DATASETS, "cifar",
        F.DatasetSpec(spec.name,
                      [F.RemoteFile(spec.files[0].relpath,
                                    spec.files[0].url, _sha(payload))],
                      spec.ready_probe, spec.post_steps))

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(
        "urllib.request.urlopen",
        lambda url, timeout=60: FakeResponse(payload))
    rc = F.run_preflight(["cifar"], str(tmp_path), check_only=False)
    assert rc == 0
    assert (tmp_path / "cifar-10-batches-py" / "data_batch_1").exists()


def test_run_preflight_fetch_failure_degrades_to_fallback_note(
        tmp_path, monkeypatch, capsys):
    def boom(url, timeout=60):
        raise OSError("no route to host (zero-egress image)")
    monkeypatch.setattr("urllib.request.urlopen", boom)
    rc = F.run_preflight(["cifar"], str(tmp_path), check_only=False)
    out = capsys.readouterr().out
    assert rc == 1
    assert "DETERMINISTIC SYNTHETIC" in out
