"""Config loader tests: reference-schema ingestion and per-adversary accessors."""
import pytest

from dba_mod_tpu import config as cfg


BASE = {
    "type": "cifar", "lr": 0.1, "batch_size": 64, "epochs": 10,
    "no_models": 10, "number_of_total_participants": 100, "eta": 0.1,
    "aggregation_methods": "mean",
    "adversary_list": [17, 33, 77, 11],
    "trigger_num": 4,
    "0_poison_pattern": [[0, 0], [0, 1]],
    "1_poison_pattern": [[0, 9], [0, 10]],
    "2_poison_pattern": [[4, 0], [4, 1]],
    "3_poison_pattern": [[4, 9], [4, 10]],
    "0_poison_epochs": [3],
    "1_poison_epochs": [5],
    "2_poison_epochs": [7],
    "3_poison_epochs": [9],
    "poison_epochs": [1],
}


def test_required_key_validation():
    with pytest.raises(ValueError, match="missing required"):
        cfg.Params.from_dict({"type": "cifar"})


def test_unknown_aggregation_rejected():
    bad = dict(BASE, aggregation_methods="krum")
    with pytest.raises(ValueError, match="aggregation"):
        cfg.Params.from_dict(bad)


def test_adversarial_index_distributed():
    p = cfg.Params.from_dict(BASE)
    assert p.adversarial_index_of(33) == 1
    assert p.adversarial_index_of(5) == -1
    assert not p.is_centralized_attack


def test_adversarial_index_centralized_forces_global_pattern():
    # single adversary => pattern index -1 => combined pattern
    # (image_train.py:47-48), but the SCHEDULE still keys on slot 0
    # (resolved before the -1 is forced, image_train.py:38-48)
    p = cfg.Params.from_dict(dict(BASE, adversary_list=[45]))
    assert p.is_centralized_attack
    assert p.adversarial_index_of(45) == -1
    assert p.is_adversary(45) and not p.is_adversary(999)
    assert p.adversary_slot_of(45) == 0
    assert p.poison_epochs_for(p.adversary_slot_of(45)) == [3]


def test_defaults_not_shared_across_instances():
    p1 = cfg.Params.from_dict(dict(BASE))
    p1.raw["save_on_epochs"].append(42)
    p2 = cfg.Params.from_dict(dict(BASE))
    assert 42 not in p2.raw["save_on_epochs"]


def test_pattern_union():
    p = cfg.Params.from_dict(BASE)
    assert p.poison_pattern_for(2) == [[4, 0], [4, 1]]
    combined = p.poison_pattern_for(-1)
    assert len(combined) == 8 and [0, 9] in combined and [4, 10] in combined


def test_poison_epochs_missing_slot_key_raises():
    # Reference parity: image_train.py:43 / main.py:151 look the per-slot key
    # up unconditionally — a missing key must fail loudly, not silently
    # schedule the global default.
    raw = dict(BASE)
    del raw["2_poison_epochs"]
    p = cfg.Params.from_dict(raw)
    with pytest.raises(KeyError):
        p.poison_epochs_for(2)
    assert p.poison_epochs_for(0) == [3]
    assert p.poison_epochs_for(-1) == [1]  # benign default


def test_scheduled_adversaries():
    p = cfg.Params.from_dict(BASE)
    assert p.scheduled_adversaries([5]) == [33]
    assert p.scheduled_adversaries([3, 4, 5]) == [17, 33]
    assert p.scheduled_adversaries([100]) == []


def test_defaults_fill_in():
    p = cfg.Params.from_dict(BASE)
    assert p["momentum"] == 0.9
    assert p["fg_use_memory"] is True
    assert p["is_poison"] is False


def test_loads_reference_yamls_verbatim():
    """Schema compatibility: the reference's own config files must load and
    resolve through the typed accessors."""
    import os
    ref = "/root/reference/utils"
    if not os.path.isdir(ref):
        pytest.skip("reference not mounted")
    for name, typ in [("mnist_params.yaml", "mnist"),
                      ("cifar_params.yaml", "cifar"),
                      ("tiny_params.yaml", "tiny-imagenet-200"),
                      ("loan_params.yaml", "loan")]:
        p = cfg.Params.from_yaml(os.path.join(ref, name))
        assert p.type == typ
        assert p.num_adversaries >= 1
        for slot in range(p.num_adversaries):
            assert len(p.poison_epochs_for(slot)) >= 1
        if p.is_image:
            assert len(p.poison_pattern_for(-1)) > 0
        else:
            names, values = p.poison_trigger_features_for(-1)
            assert len(names) == len(values) > 0
