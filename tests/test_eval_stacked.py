"""The local-battery fetch/stamp hoist (make_stacked_eval_fn) must be
bit-identical to vmapping the per-client eval kernel — same ops, same
accumulation order, one shared gather instead of C."""
import jax
import jax.numpy as jnp
import numpy as np

from dba_mod_tpu.config import Params
from dba_mod_tpu.data import build_eval_plan, load_image_dataset
from dba_mod_tpu.fl.device_data import make_image_device_data
from dba_mod_tpu.fl.evaluation import make_eval_fn, make_stacked_eval_fn
from dba_mod_tpu.models import ModelVars, build_model

C = 3


def _setup():
    params = Params.from_dict(dict(
        type="mnist", lr=0.1, batch_size=16, epochs=1, no_models=C,
        number_of_total_participants=4, eta=0.1, aggregation_methods="mean",
        synthetic_data=True, synthetic_train_size=64,
        synthetic_test_size=100, is_poison=True, poison_label_swap=2,
        adversary_list=[0, 1], trigger_num=2,
        **{"0_poison_pattern": [[0, 0], [0, 1]],
           "1_poison_pattern": [[3, 0], [3, 1]]}))
    data = load_image_dataset(params)
    dd = make_image_device_data(data, params)
    mdef = build_model(params)
    stacked = jax.vmap(lambda k: mdef.init_vars(k))(
        jax.random.split(jax.random.key(0), C))
    # ragged plan: 100 samples / batch 16 → final batch masked to 4
    plan = build_eval_plan(np.arange(100), 16)
    idx = jnp.asarray(plan.idx)
    slots = jnp.zeros_like(idx)
    mask = jnp.asarray(plan.mask)
    return mdef, dd, stacked, idx, slots, mask


def _eq(a, b):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_stacked_clean_and_combined_poison_bit_exact():
    mdef, dd, stacked, idx, slots, mask = _setup()
    for poison in (False, True):
        per = make_eval_fn(mdef, dd, poison=poison)
        ref = jax.vmap(per, in_axes=(0, None, None, None, None))(
            stacked, idx, slots, mask, jnp.int32(-1))
        got = make_stacked_eval_fn(mdef, dd, poison=poison)(
            stacked, idx, slots, mask, jnp.int32(-1))
        _eq(got, ref)


def test_stacked_per_client_trigger_bit_exact():
    mdef, dd, stacked, idx, slots, mask = _setup()
    advs = jnp.asarray([0, 1, -1], jnp.int32)  # each client its own trigger
    per = make_eval_fn(mdef, dd, poison=True)
    ref = jax.vmap(per, in_axes=(0, None, None, None, 0))(
        stacked, idx, slots, mask, advs)
    got = make_stacked_eval_fn(mdef, dd, poison=True,
                               per_client_trigger=True)(
        stacked, idx, slots, mask, advs)
    _eq(got, ref)
