"""Buffered-asynchronous federation (fl/async_rounds.py): the sync-reduction
parity keystone, staleness-weight units, arrival-plan determinism, the
partial-buffer padded merge, and buffer checkpoint/resume continuity. The
reference-scale streaming rehearsal is slow-marked."""
import json
from pathlib import Path

import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.async_rounds import ArrivalProcess, staleness_weights
from dba_mod_tpu.fl.experiment import Experiment

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=3, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=1)

# wall-clock keys never compared, plus the async-only extras a sync row
# does not carry — everything else must match bit-for-bit at K == C
VOLATILE = {"time", "round_time", "dispatch_time", "finalize_time"}
ASYNC_ONLY = {"mode", "buffer_occupancy", "staleness_mean", "staleness_max",
              "waves_dispatched", "arrivals_total", "virtual_time"}


def _rows(exp, drop=()):
    return [{k: v for k, v in r.items() if k not in VOLATILE | set(drop)}
            for r in exp.recorder._jsonl_rows]


def _bitwise_equal(a, b):
    la, lb = (jax.tree_util.tree_leaves(t) for t in (a, b))
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ----------------------------------------------------------- unit: weights
def test_staleness_weight_units():
    s = np.array([0, 1, 2, 5], np.float32)
    np.testing.assert_array_equal(
        staleness_weights(s, "none", 0.5), np.ones(4, np.float32))
    np.testing.assert_allclose(
        staleness_weights(s, "polynomial", 0.5),
        (1.0 + s) ** -0.5, rtol=1e-6)
    np.testing.assert_allclose(
        staleness_weights(s, "exponential", 0.7), 0.7 ** s, rtol=1e-6)
    # fresh updates always carry full weight
    for w in ("none", "polynomial", "exponential"):
        assert staleness_weights(np.zeros(1), w, 0.5)[0] == 1.0
    with pytest.raises(ValueError):
        staleness_weights(s, "inverse", 0.5)


# ----------------------------------------------------- unit: arrival plans
def test_arrival_plans_deterministic_per_seed():
    a = ArrivalProcess(seed=7, rate=2.0, jitter=0.5, straggler_tail=0.3,
                      straggler_factor=10.0)
    b = ArrivalProcess(seed=7, rate=2.0, jitter=0.5, straggler_tail=0.3,
                      straggler_factor=10.0)
    for wave in (0, 1, 5):
        np.testing.assert_array_equal(a.delays(wave, 16), b.delays(wave, 16))
    # distinct waves and distinct seeds give distinct plans
    assert not np.array_equal(a.delays(0, 16), a.delays(1, 16))
    c = ArrivalProcess(seed=8, rate=2.0, jitter=0.5, straggler_tail=0.3,
                      straggler_factor=10.0)
    assert not np.array_equal(a.delays(0, 16), c.delays(0, 16))


def test_arrival_straggler_tail_stretches_delays():
    fast = ArrivalProcess(seed=1, rate=1.0, jitter=0.0, straggler_tail=0.0,
                          straggler_factor=10.0)
    slow = ArrivalProcess(seed=1, rate=1.0, jitter=0.0, straggler_tail=1.0,
                          straggler_factor=10.0)
    df, ds = fast.delays(0, 64), slow.delays(0, 64)
    # tail draw consumes RNG after the exponentials, so the base delays
    # match and every straggler is exactly factor× slower
    np.testing.assert_allclose(ds, df * 10.0, rtol=1e-12)
    assert ArrivalProcess(seed=1, rate=4.0, jitter=0.0, straggler_tail=0.0,
                          straggler_factor=1.0).delays(0, 512).mean() < \
        fast.delays(0, 512).mean()
    with pytest.raises(ValueError):
        ArrivalProcess(seed=0, rate=0.0, jitter=0.0, straggler_tail=0.0,
                       straggler_factor=1.0)


# ------------------------------------------------- keystone: sync reduction
def test_async_k_equals_c_reduces_bit_exactly_to_sync():
    """buffer_k == no_models, staleness 0: the streaming engine must
    reproduce the synchronous run bit-for-bit — metrics.jsonl rows
    (modulo wall clocks and async-only keys), every recorder CSV stream,
    and the final global model. Arrival knobs deliberately non-trivial:
    within-wave arrival ORDER cannot matter because the merge sorts its
    buffer by (wave, lane)."""
    es = Experiment(Params.from_dict(BASE), save_results=False)
    es.run()
    ea = Experiment(Params.from_dict(dict(
        BASE, mode="async", arrival_rate=3.0, arrival_jitter=0.7,
        straggler_tail=0.25, straggler_factor=6.0)), save_results=False)
    ra = ea.run()
    assert ra["staleness_max"] == 0.0       # full-cohort merges: no overlap
    assert _rows(es) == _rows(ea, drop=ASYNC_ONLY)
    assert es.recorder.train_result == ea.recorder.train_result
    assert es.recorder.test_result == ea.recorder.test_result
    assert _bitwise_equal(es.global_vars, ea.global_vars)


# ----------------------------------------------- partial-buffer padded merge
def test_partial_buffer_merges_padded_to_k():
    """Occupancy < K (the graceful-stop flush path) runs through the same
    compiled merge: inert zero-padding lanes, occupancy mask, divisor = the
    present updates."""
    e = Experiment(Params.from_dict(dict(
        BASE, mode="async", buffer_k=4, async_steps=2)), save_results=False)
    from dba_mod_tpu.fl.async_rounds import AsyncDriver
    d = AsyncDriver(e)
    d._fill_buffer()
    d._buffer = d._buffer[:1]               # strand 3 arrivals in flight
    r1 = d._merge_and_record()
    assert r1["buffer_occupancy"] == 1
    d._fill_buffer()
    r2 = d._merge_and_record()
    assert r2["buffer_occupancy"] == 4
    rows = e.recorder._jsonl_rows
    assert [r["epoch"] for r in rows] == [1, 2]
    assert np.isfinite([r["global_acc"] for r in rows]).all()


# --------------------------------------------------- checkpoint / resume
def test_buffer_checkpoint_resume_is_bit_identical(tmp_path):
    """Kill between merges (simulated by dropping the Experiment after a
    capped run), `--resume auto`: the aux-sidecar async_state restores the
    arrival heap, buffer, and live cohorts, and the continued metrics
    stream is bit-identical to the uninterrupted run — stragglers carried
    across the kill included."""
    cfg = dict(BASE, epochs=6, save_model=True, mode="async", buffer_k=2,
               arrival_rate=2.0, arrival_jitter=0.6, straggler_tail=0.25,
               straggler_factor=4.0, staleness_weighting="polynomial",
               async_steps=8, random_seed=3)

    def rows(folder):
        drop = VOLATILE | {"virtual_time"}
        with open(Path(folder) / "metrics.jsonl") as f:
            return [{k: v for k, v in json.loads(l).items() if k not in drop}
                    for l in f if l.strip()]

    ref = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ref"))), save_results=True)
    ref.run()
    a = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ab"), async_steps=4)),
        save_results=True)
    a.run()
    folder = a.folder
    del a
    b = Experiment(Params.from_dict(dict(
        cfg, run_dir=str(tmp_path / "ab"), resumed_model="auto")),
        save_results=True)
    assert str(b.folder) == str(folder)     # same run folder, not a new one
    assert (b._resume_aux or {}).get("async_state") is not None
    b.run()
    got, want = rows(folder), rows(ref.folder)
    assert [r["epoch"] for r in got] == list(range(1, 9))
    assert got == want


def test_model_only_resume_restarts_stream_with_warning(tmp_path, caplog):
    """A checkpoint without the async_state sidecar (e.g. one written by a
    pretrain run) must still resume: model-only, empty buffer, loud
    warning — never a crash."""
    cfg = dict(BASE, save_model=True, mode="async", buffer_k=2,
               async_steps=4, run_dir=str(tmp_path / "runs"))
    a = Experiment(Params.from_dict(dict(cfg, async_steps=2)),
                   save_results=True)
    a.run()
    folder = a.folder
    del a
    # strip the streaming state out of every snapshot's sidecar (re-writing
    # the manifest so the slimmer sidecar still verifies — what a
    # pretrain-written checkpoint looks like)
    from dba_mod_tpu import checkpoint as ckpt
    for snap in (folder / "model_last.pt.tar",
                 folder / "model_last.pt.tar.best"):
        aux = ckpt.load_aux_state(snap)
        if aux is not None:
            aux.pop("async_state", None)
            ckpt.save_aux_state(snap, aux)
            ckpt.write_manifest(snap, int(aux["epoch"]))
    import logging
    lg = logging.getLogger("async_rounds")
    lg.addHandler(caplog.handler)
    try:
        with caplog.at_level("WARNING", logger="async_rounds"):
            b = Experiment(Params.from_dict(dict(cfg, resumed_model="auto")),
                           save_results=True)
            b.run()
    finally:
        lg.removeHandler(caplog.handler)
    assert any("buffer state lost" in r.getMessage()
               for r in caplog.records)
    with open(Path(folder) / "metrics.jsonl") as f:
        epochs = [json.loads(l)["epoch"] for l in f if l.strip()]
    assert epochs == [1, 2, 3, 4]           # stream restarted, no dupes


# ------------------------------------------------------------ config guards
def test_sync_mode_ignores_async_knobs():
    """mode: sync is a strict no-op for every async knob — same dispatch
    path, bit-identical rows whether or not the knobs are set."""
    ea = Experiment(Params.from_dict(dict(
        BASE, epochs=2, buffer_k=3, staleness_weighting="polynomial",
        arrival_rate=9.0, straggler_tail=0.9)), save_results=False)
    ea.run()
    eb = Experiment(Params.from_dict(dict(BASE, epochs=2)),
                    save_results=False)
    eb.run()
    assert _rows(ea) == _rows(eb)
    assert _bitwise_equal(ea.global_vars, eb.global_vars)


def test_async_config_rejections():
    with pytest.raises(ValueError, match="foolsgold"):
        Params.from_dict(dict(BASE, mode="async",
                              aggregation_methods="foolsgold"))
    with pytest.raises(ValueError, match="aggr_epoch_interval"):
        Params.from_dict(dict(BASE, mode="async", aggr_epoch_interval=2))
    with pytest.raises(ValueError, match="mode"):
        Params.from_dict(dict(BASE, mode="streaming"))
    with pytest.raises(ValueError, match="staleness_weighting"):
        Params.from_dict(dict(BASE, staleness_weighting="inverse"))


# ------------------------------------------------------- slow: rehearsal
@pytest.mark.slow
def test_async_streaming_rehearsal_100_participants():
    """Reference-scale streaming soak: 100 participants, 10-client cohorts,
    5-update buffer, faults as arrival events, staleness weighting on.
    Accuracy must stay finite, every merge at full occupancy, staleness
    actually exercised, and per-client rows recorded for resolved waves."""
    cfg = dict(
        BASE, epochs=10, no_models=10, number_of_total_participants=100,
        synthetic_train_size=4000, mode="async", buffer_k=5,
        staleness_weighting="polynomial", staleness_alpha=0.5,
        arrival_rate=2.0, arrival_jitter=0.8, straggler_tail=0.2,
        straggler_factor=8.0, async_steps=20, fault_injection=True,
        fault_dropout_prob=0.05, fault_stale_prob=0.1, fault_seed=11)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    r = e.run()
    rows = e.recorder._jsonl_rows
    assert len(rows) == 20
    assert all(row["buffer_occupancy"] == 5 for row in rows)
    assert np.isfinite([row["global_acc"] for row in rows]).all()
    assert max(row["staleness_max"] for row in rows) > 0
    assert sum(row["n_dropped"] for row in rows) > 0
    assert np.isfinite(r["global_acc"])
    assert len(e.recorder.train_result) > 0
