"""Async checkpointing composes with round pipelining (VERDICT r4 #4): the
pipelined run() path no longer degrades to sequential when save_model is on —
orbax AsyncCheckpointer commits in the background while the next round
computes, and commits are serialized, so per-epoch checkpoints land in
program order with the state captured at each round's dispatch."""
import jax
import numpy as np

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

CFG = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=4, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=3,
    save_model=True, save_on_epochs=[1, 2, 3, 4], pipeline_rounds=True)


def test_pipelined_checkpoints_land_in_program_order(tmp_path):
    e = Experiment(Params.from_dict(CFG), save_results=False)
    e.folder = tmp_path
    last = e.run(4)
    assert last["epoch"] == 4

    like = e.model_def.init_vars(jax.random.key(0))
    # every per-epoch snapshot exists and stores its own epoch
    snaps = {}
    for ep in (1, 2, 3, 4):
        mv, saved_ep, _ = ckpt.load_checkpoint(
            tmp_path / f"model_last.pt.tar.epoch_{ep}", like)
        assert saved_ep == ep
        snaps[ep] = mv
    # model_last holds the FINAL round (commits serialized in program order —
    # an out-of-order commit would leave an earlier round here)
    mv_last, saved_ep, _ = ckpt.load_checkpoint(
        tmp_path / "model_last.pt.tar", like)
    assert saved_ep == 4
    for a, b in zip(jax.tree_util.tree_leaves(mv_last.params),
                    jax.tree_util.tree_leaves(snaps[4].params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # each epoch's snapshot is the state AFTER that round, not a stale copy:
    # under pipelining the live attrs belong to round N+1 at save time, so
    # equality with the sequential run proves the captured-handle plumbing
    seq = Experiment(Params.from_dict(dict(CFG, pipeline_rounds=False)),
                     save_results=False)
    for ep in (1, 2, 3, 4):
        seq.run_round(ep)
        for a, b in zip(jax.tree_util.tree_leaves(snaps[ep].params),
                        jax.tree_util.tree_leaves(seq.global_vars.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the full-state sidecar landed too — for model_last AND every snapshot
    # (resuming from .epoch_N must not silently reset the defense state)
    aux = ckpt.load_aux_state(tmp_path / "model_last.pt.tar")
    assert aux is not None and aux["epoch"] == 4
    for ep in (1, 2, 3, 4):
        aux_n = ckpt.load_aux_state(tmp_path / f"model_last.pt.tar.epoch_{ep}")
        assert aux_n is not None and aux_n["epoch"] == ep


def test_best_val_checkpoint_works_pipelined(tmp_path):
    e = Experiment(Params.from_dict(CFG), save_results=False)
    e.folder = tmp_path
    e.run(4)
    assert (tmp_path / "model_last.pt.tar.best").exists()
    assert np.isfinite(e.best_loss)
