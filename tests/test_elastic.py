"""Elastic multi-host layer units (PR 6): PeerHealth liveness/barrier
semantics, the watchdog's peer-lost verdict (exit 77 vs 76), the
host-level fault lane's determinism and survivor-mask composition, and
the strict no-op contract of every new knob."""
import json
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl import faults as flt
from dba_mod_tpu.parallel.distributed import PeerHealth, PeerLostError
from dba_mod_tpu.utils.run_guard import (EXIT_PEER_LOST, EXIT_WATCHDOG,
                                         RunGuard, Watchdog)


# ------------------------------------------------------------- PeerHealth
def _pair(tmp_path, interval=0.05, timeout=0.2):
    a = PeerHealth(tmp_path, 0, 2, interval_s=interval, timeout_s=timeout)
    b = PeerHealth(tmp_path, 1, 2, interval_s=interval, timeout_s=timeout)
    return a, b


def test_peer_health_beats_and_sees_live_peer(tmp_path):
    a, b = _pair(tmp_path)
    a.start(), b.start()
    try:
        assert a.lost_peers() == []
        assert b.lost_peers() == []
        assert (tmp_path / "host_0.json").exists()
        assert (tmp_path / "host_1.json").exists()
    finally:
        a.stop(), b.stop()


def test_peer_health_detects_stale_peer_past_grace(tmp_path):
    a, b = _pair(tmp_path, interval=0.05, timeout=0.15)
    a.start(), b.start()
    try:
        b._stop.set()            # b's beat thread dies (the "kill")
        b._thread.join(1.0)
        # advance past staleness AND the 3x-timeout startup grace via a
        # synthetic clock: no real sleeping
        future = time.time() + 10.0
        assert a.lost_peers(now=future) == [1]
        # the boundary check raises on a stale peer
        a._started_wall -= 10.0  # move past grace in real time too
        time.sleep(0.3)          # real staleness (interval 0.05/to 0.15)
        with pytest.raises(PeerLostError, match=r"\[1\]"):
            a.check(3)
    finally:
        a._stop.set()
        b._started_wall = None   # suppress the stopped-beat write check
        a.stop(), b.stop()


def test_peer_health_stopped_beat_is_not_a_loss(tmp_path):
    a, b = _pair(tmp_path, timeout=0.15)
    a.start(), b.start()
    b.stop()                     # clean exit: final beat marked stopped
    try:
        assert a.lost_peers(now=time.time() + 10.0) == []
    finally:
        a.stop()


def test_peer_health_ignores_other_generation_files(tmp_path):
    # debris from the pre-shrink world (gen=2) must be invisible to the
    # relaunched world (world_size=3 → gen=3): within grace it is simply
    # a peer that has not beaten yet
    stale = {"pid": 1, "gen": 2, "time": time.time(),
             "boundary_epoch": 5, "ospid": 1, "stopped": False}
    (tmp_path / "host_1.json").write_text(json.dumps(stale))
    a = PeerHealth(tmp_path, 0, 3, interval_s=0.05, timeout_s=0.2)
    a.start()
    try:
        assert a._read(1) is None          # wrong generation
        assert a.lost_peers() == []        # inside startup grace
        assert 1 in a.lost_peers(now=time.time() + 10.0)  # past grace
    finally:
        a.stop()


def test_peer_health_barrier_reaches_and_times_out(tmp_path):
    a, b = _pair(tmp_path, interval=0.05, timeout=5.0)
    a.start(), b.start()
    try:
        b.beat(boundary_epoch=4)
        assert a.barrier(4, timeout=2.0) is True     # peer already there
        # peer stuck one epoch behind: bounded timeout, slow != gone
        t0 = time.monotonic()
        assert a.barrier(5, timeout=0.2) is False
        assert time.monotonic() - t0 < 2.0
    finally:
        a.stop(), b.stop()


def test_peer_health_barrier_raises_on_dead_peer(tmp_path):
    a, b = _pair(tmp_path, interval=0.05, timeout=0.15)
    a.start(), b.start()
    b._stop.set()
    b._thread.join(1.0)
    try:
        time.sleep(0.3)          # real staleness, still inside grace...
        a._started_wall -= 10.0  # ...so force past the startup grace
        with pytest.raises(PeerLostError):
            a.barrier(5, timeout=3.0)
    finally:
        b._started_wall = None
        a.stop(), b.stop()


# ------------------------------------------------- watchdog peer verdict
def test_watchdog_verdict_peer_lost_vs_generic():
    wd = Watchdog(soft_s=0.1, hard_s=0.2)
    assert wd.abort_verdict() == (EXIT_WATCHDOG, [])
    wd.peer_probe = lambda: [1]
    assert wd.abort_verdict() == (EXIT_PEER_LOST, [1])
    wd.peer_probe = lambda: []
    assert wd.abort_verdict() == (EXIT_WATCHDOG, [])
    # a probe failure must never mask the abort itself
    def boom():
        raise RuntimeError("probe broke")
    wd.peer_probe = boom
    assert wd.abort_verdict() == (EXIT_WATCHDOG, [])


def test_runguard_attach_detach_peer_health(tmp_path):
    guard = RunGuard(watchdog_soft_s=1.0, watchdog_hard_s=2.0)
    ph = PeerHealth(tmp_path, 0, 2, interval_s=0.05, timeout_s=0.2)
    guard.attach_peer_health(ph)
    assert guard.watchdog.peer_probe == ph.lost_peers
    guard.attach_peer_health(None)
    assert guard.watchdog.peer_probe is None


# ------------------------------------------------------ host-loss lane
def _fcfg(**kw):
    base = dict(enabled=True, dropout_prob=0.0, corrupt_prob=0.0,
                blowup_prob=0.0, blowup_factor=1e8, stale_prob=0.0,
                seed=7, host_loss_prob=1.0, num_hosts=4,
                host_loss_in_program=True)
    base.update(kw)
    return flt.FaultConfig(**base)


def test_host_loss_victim_is_deterministic_per_epoch():
    fcfg = _fcfg(host_loss_prob=0.5)
    key = jax.random.key(fcfg.seed)
    victims = [int(flt.host_loss_victim(fcfg, jax.random.fold_in(key, e)))
               for e in range(1, 30)]
    again = [int(flt.host_loss_victim(fcfg, jax.random.fold_in(key, e)))
             for e in range(1, 30)]
    assert victims == again                      # pure f(fault_seed, epoch)
    assert any(v == -1 for v in victims)         # some rounds lose no host
    assert any(v >= 0 for v in victims)
    assert all(-1 <= v < 4 for v in victims)


def test_host_loss_drops_exactly_the_victims_slice():
    fcfg = _fcfg(num_hosts=2, host_loss_prob=1.0)
    rng = jax.random.fold_in(jax.random.key(fcfg.seed), 3)
    counted = jnp.ones(8, bool)
    plan = flt.make_fault_plan(fcfg, rng, counted)
    victim = int(flt.host_loss_victim(fcfg, rng))
    hosts = np.asarray(flt.host_of_lane(8, 2))
    np.testing.assert_array_equal(np.asarray(plan.dropped),
                                  hosts == victim)
    assert int(plan.dropped.sum()) == 4
    # the other lanes never double-book a host-dropped client
    assert not bool((plan.corrupt & plan.dropped).any())


def test_host_loss_respects_counted_padding():
    fcfg = _fcfg(num_hosts=2, host_loss_prob=1.0)
    rng = jax.random.fold_in(jax.random.key(fcfg.seed), 3)
    counted = jnp.asarray([True] * 6 + [False] * 2)   # 2 inert pad lanes
    plan = flt.make_fault_plan(fcfg, rng, counted)
    assert not bool((plan.dropped & ~counted).any())


def test_host_loss_off_leaves_existing_plans_unchanged():
    """Strict no-op: enabling the host lane knobs at prob 0 must not
    reshuffle the client-lane draws an existing fault_seed produces."""
    rng = jax.random.fold_in(jax.random.key(11), 2)
    counted = jnp.ones(16, bool)
    legacy = flt.FaultConfig(enabled=True, dropout_prob=0.3,
                             corrupt_prob=0.2, blowup_prob=0.1,
                             blowup_factor=1e8, stale_prob=0.2, seed=11)
    with_lane = flt.FaultConfig(enabled=True, dropout_prob=0.3,
                                corrupt_prob=0.2, blowup_prob=0.1,
                                blowup_factor=1e8, stale_prob=0.2, seed=11,
                                host_loss_prob=0.0, num_hosts=4,
                                host_loss_in_program=True)
    p1 = flt.make_fault_plan(legacy, rng, counted)
    p2 = flt.make_fault_plan(with_lane, rng, counted)
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_host_of_lane_partitions_all_lanes():
    hosts = np.asarray(flt.host_of_lane(10, 4))
    assert hosts.min() == 0 and hosts.max() == 3
    assert (np.diff(hosts) >= 0).all()           # contiguous slices
    assert len(hosts) == 10


# ------------------------------------------------------ config contracts
_BASE = dict(type="mnist", lr=0.1, batch_size=16, epochs=2, no_models=4,
             number_of_total_participants=8, eta=0.8,
             aggregation_methods="mean", synthetic_data=True)


def test_config_rejects_bad_heartbeat_knobs():
    with pytest.raises(ValueError, match="heartbeat"):
        Params.from_dict(dict(_BASE, heartbeat_interval_s=-1))
    with pytest.raises(ValueError, match="must exceed"):
        Params.from_dict(dict(_BASE, heartbeat_interval_s=2.0,
                              heartbeat_timeout_s=1.0))
    # 0 timeout = derived default: fine
    Params.from_dict(dict(_BASE, heartbeat_interval_s=2.0))


def test_config_rejects_bad_host_loss_knobs():
    # prob range is enforced where every fault prob is: FaultConfig
    with pytest.raises(ValueError, match="fault_host_loss_prob"):
        flt.FaultConfig.from_params(
            Params.from_dict(dict(_BASE, fault_host_loss_prob=1.5)))
    with pytest.raises(ValueError, match="fault_num_hosts"):
        Params.from_dict(dict(_BASE, fault_num_hosts=-1))


def test_single_process_host_loss_without_num_hosts_disables_lane(caplog):
    """A shrunk-to-1 elastic relaunch keeps the dead world's YAML (lane on,
    no fault_num_hosts) and MUST start — the lane disables with a warning
    instead of raising, or the recovery path the lane exercises would
    crash at its final step."""
    p = Params.from_dict(dict(_BASE, fault_injection=True,
                              fault_host_loss_prob=0.5))
    with caplog.at_level("WARNING", logger="dba_mod_tpu"):
        fcfg = flt.FaultConfig.from_params(p)
    assert not fcfg.host_loss_enabled
    assert any("fault_num_hosts" in r.message for r in caplog.records)
    ok = Params.from_dict(dict(_BASE, fault_injection=True,
                               fault_host_loss_prob=0.5,
                               fault_num_hosts=2))
    fcfg = flt.FaultConfig.from_params(ok)
    assert fcfg.host_loss_enabled and fcfg.host_loss_in_program


def test_elastic_knobs_are_noop_single_host(tmp_path):
    """Acceptance contract: heartbeat/fault knobs (off) change nothing
    single-host — no peers object, no files, identical round results."""
    from dba_mod_tpu.fl.experiment import Experiment
    cfg = dict(_BASE, synthetic_train_size=256, synthetic_test_size=128,
               sampling_dirichlet=False, local_eval=False, random_seed=1,
               run_dir=str(tmp_path / "runs"))
    base = Experiment(Params.from_dict(cfg), save_results=False)
    r_base = base.run_round(1)
    knobbed = Experiment(
        Params.from_dict(dict(cfg, heartbeat_interval_s=1.0,
                              heartbeat_timeout_s=30.0,
                              heartbeat_barrier_s=2.0,
                              fault_num_hosts=4)),
        save_results=False)
    assert knobbed.peers is None          # single-host: layer never built
    r_knob = knobbed.run_round(1)
    assert r_base["global_acc"] == r_knob["global_acc"]
    assert not (tmp_path / "runs").exists()   # no files written


def test_host_loss_e2e_single_process_survivor_mask():
    """fault_host_loss_prob=1, 2 virtual hosts → every round drops exactly
    half the cohort through the survivor mask and still aggregates."""
    from dba_mod_tpu.fl.experiment import Experiment
    cfg = dict(_BASE, no_models=8, synthetic_train_size=256,
               synthetic_test_size=128, sampling_dirichlet=False,
               local_eval=False, random_seed=1, fault_injection=True,
               fault_host_loss_prob=1.0, fault_num_hosts=2)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    for ep in (1, 2):
        r = e.run_round(ep)
        assert r["n_dropped"] == 4, r
        assert np.isfinite(r["global_acc"])
        assert not r["degraded"]
