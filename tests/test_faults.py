"""Fault-injection harness + survivor-masked aggregation + retry/degradation.

Three layers of coverage:
  1. unit — the masked aggregation rules reduce EXACTLY to the dense rules
     under an all-ones mask, and exclude quarantined (NaN/blown-up) clients
     without propagating non-finite values;
  2. the jitted screening pass (finite + norm screens) and the fault plan's
     determinism/exclusivity;
  3. end-to-end — injected-NaN rounds recover (finite global model, quarantine
     counters recorded), injected-dropout rounds degrade gracefully (model
     carried forward), and the round-level retry restores the captured
     pre-round state and re-runs with escalated screening.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl import faults as flt
from dba_mod_tpu.fl.experiment import Experiment, _pad_tasks
from dba_mod_tpu.fl.rounds import RobustStats, screen_client_updates
from dba_mod_tpu.fl.state import build_client_tasks
from dba_mod_tpu.models import ModelVars
from dba_mod_tpu.ops import aggregation as agg

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=6, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=1)


def _rand_tree(rng, batch=None):
    shape = lambda *s: (batch,) + s if batch else s
    return {"dense": {"kernel": rng.randn(*shape(4, 3)).astype(np.float32),
                      "bias": rng.randn(*shape(3)).astype(np.float32)},
            "bn": {"mean": rng.randn(*shape(3)).astype(np.float32)}}


def _dev(tree):
    return jax.tree_util.tree_map(jnp.asarray, tree)


# ---------------------------------------------------- all-ones mask ≡ dense
def test_masked_fedavg_all_ones_is_dense_bitwise():
    rng = np.random.RandomState(0)
    g, deltas = _rand_tree(rng), _dev(_rand_tree(rng, batch=5))
    ones = jnp.ones((5,), jnp.float32)
    dense = agg.fedavg_update(g, deltas, 0.8, 5)
    masked = agg.fedavg_update_masked(g, deltas, 0.8, 5, ones, ones > 0)
    for d, m in zip(jax.tree_util.tree_leaves(dense),
                    jax.tree_util.tree_leaves(masked)):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(m))


def test_masked_rfa_all_ones_is_dense():
    rng = np.random.RandomState(1)
    g, deltas = _rand_tree(rng), _dev(_rand_tree(rng, batch=6))
    ns = jnp.asarray(np.array([100, 50, 80, 120, 60, 90], np.float32))
    dense = agg.geometric_median_update(g, deltas, ns, eta=0.1, maxiter=10)
    masked = agg.geometric_median_update(g, deltas, ns, eta=0.1, maxiter=10,
                                         mask=jnp.ones((6,), jnp.float32))
    np.testing.assert_array_equal(np.asarray(dense.wv),
                                  np.asarray(masked.wv))
    for d, m in zip(jax.tree_util.tree_leaves(dense.new_state),
                    jax.tree_util.tree_leaves(masked.new_state)):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(m))


def test_masked_foolsgold_all_ones_is_dense():
    rng = np.random.RandomState(2)
    params = {"w": jnp.asarray(rng.randn(5, 4).astype(np.float32))}
    C, L = 4, 12
    grads = {"w": jnp.asarray(rng.randn(C, 5, 4).astype(np.float32))}
    feature = jnp.asarray(rng.randn(C, L).astype(np.float32))
    ids = jnp.asarray([0, 3, 7, 9])
    st = agg.foolsgold_init(10, L)
    dense = agg.foolsgold_update(params, grads, feature, ids, st, eta=0.1,
                                 lr=0.1, momentum=0.9, weight_decay=5e-4)
    masked = agg.foolsgold_update(params, grads, feature, ids, st, eta=0.1,
                                  lr=0.1, momentum=0.9, weight_decay=5e-4,
                                  mask=jnp.ones((C,), jnp.float32))
    np.testing.assert_array_equal(np.asarray(dense.wv), np.asarray(masked.wv))
    np.testing.assert_array_equal(np.asarray(dense.new_params["w"]),
                                  np.asarray(masked.new_params["w"]))
    np.testing.assert_array_equal(np.asarray(dense.new_fg_state.memory),
                                  np.asarray(masked.new_fg_state.memory))


# -------------------------------------------------- masked exclusion works
def test_masked_fedavg_excludes_nan_client_and_renormalizes():
    rng = np.random.RandomState(3)
    g = _rand_tree(rng)
    deltas_np = _rand_tree(rng, batch=4)
    for leaf in jax.tree_util.tree_leaves(deltas_np):
        leaf[1] = np.nan  # client 1's payload is corrupt
    mask = jnp.asarray([1.0, 0.0, 1.0, 1.0])
    new = agg.fedavg_update_masked(g, _dev(deltas_np), 0.8, 4, mask,
                                   jnp.ones((4,), bool))
    # renormalized over 3 survivors, NaN row fully excluded
    for path in [("dense", "kernel"), ("dense", "bias"), ("bn", "mean")]:
        got = np.asarray(new[path[0]][path[1]])
        surv = np.delete(deltas_np[path[0]][path[1]], 1, axis=0)
        exp = g[path[0]][path[1]] + 0.8 / 3 * surv.sum(0)
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)


def test_masked_rfa_excludes_nan_client():
    rng = np.random.RandomState(4)
    g = _rand_tree(rng)
    deltas_np = _rand_tree(rng, batch=5)
    for leaf in jax.tree_util.tree_leaves(deltas_np):
        leaf[0] = np.inf
    mask = jnp.asarray([0.0, 1.0, 1.0, 1.0, 1.0])
    res = agg.geometric_median_update(
        g, _dev(deltas_np), jnp.full((5,), 10.0), eta=1.0, mask=mask)
    for leaf in jax.tree_util.tree_leaves(res.new_state):
        assert np.isfinite(np.asarray(leaf)).all()
    wv = np.asarray(res.wv)
    assert wv[0] == 0.0 and np.isfinite(wv).all()
    # excluded client gets zero Weiszfeld weight; survivors share the mass
    np.testing.assert_allclose(wv.sum(), 1.0, rtol=1e-5)


def test_masked_foolsgold_excludes_nan_client_and_protects_memory():
    rng = np.random.RandomState(5)
    params = {"w": jnp.asarray(rng.randn(5, 4).astype(np.float32))}
    C, L = 4, 12
    grads_np = rng.randn(C, 5, 4).astype(np.float32)
    feature_np = rng.randn(C, L).astype(np.float32)
    grads_np[2], feature_np[2] = np.nan, np.nan
    ids = jnp.asarray([0, 1, 2, 3])
    st = agg.foolsgold_init(10, L)
    mask = jnp.asarray([1.0, 1.0, 0.0, 1.0])
    res = agg.foolsgold_update(params, {"w": jnp.asarray(grads_np)},
                               jnp.asarray(feature_np), ids, st, eta=0.1,
                               lr=0.1, momentum=0.9, weight_decay=5e-4,
                               mask=mask)
    assert np.isfinite(np.asarray(res.new_params["w"])).all()
    wv = np.asarray(res.wv)
    assert wv[2] == 0.0 and np.isfinite(wv).all()
    # the quarantined client's NaN feature must NOT poison the memory
    mem = np.asarray(res.new_fg_state.memory)
    assert np.isfinite(mem).all() and (mem[2] == 0).all()


# ------------------------------------------------------------ screening pass
def _stack_vars(rng, C):
    t = _rand_tree(rng, batch=C)
    return ModelVars(params=_dev({"dense": t["dense"]}),
                     batch_stats=_dev({"bn": t["bn"]}))


def test_screen_catches_nonfinite_and_norm_blowup():
    rng = np.random.RandomState(6)
    deltas = _stack_vars(rng, 6)
    bad = jax.tree_util.tree_map(lambda l: l.at[1].set(jnp.nan), deltas)
    bad = jax.tree_util.tree_map(lambda l: l.at[2].multiply(1e6), bad)
    ones = jnp.ones((6,), bool)
    # norm screen off: only the NaN client is quarantined
    mask, norms = screen_client_updates(bad, ones, ones, jnp.float32(0.0))
    assert list(np.asarray(mask)) == [True, False, True, True, True, True]
    # norm screen at 10x median: the blowup client goes too
    mask, _ = screen_client_updates(bad, ones, ones, jnp.float32(10.0))
    assert list(np.asarray(mask)) == [True, False, False, True, True, True]
    # a client that never reported is excluded regardless of screens
    reported = ones.at[4].set(False)
    mask, _ = screen_client_updates(bad, reported, ones, jnp.float32(0.0))
    assert not bool(mask[4])


def test_fault_plan_deterministic_and_exclusive():
    fcfg = flt.FaultConfig(enabled=True, dropout_prob=0.3, corrupt_prob=0.3,
                           blowup_prob=0.3, blowup_factor=1e8,
                           stale_prob=0.3, seed=0)
    counted = jnp.ones((64,), bool).at[60:].set(False)
    key = jax.random.key(7)
    p1 = flt.make_fault_plan(fcfg, key, counted)
    p2 = flt.make_fault_plan(fcfg, key, counted)
    for a, b in zip(p1, p2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    lanes = np.stack([np.asarray(x) for x in p1])
    assert (lanes.sum(0) <= 1).all()          # mutually exclusive
    assert not lanes[:, 60:].any()            # padding lanes never fault
    assert lanes.any()                        # p=0.3 x4 over 60 lanes: some hit


def test_perturb_tree_lanes():
    fcfg = flt.FaultConfig(enabled=True, dropout_prob=0, corrupt_prob=0,
                           blowup_prob=0, blowup_factor=100.0, stale_prob=0,
                           seed=0)
    plan = flt.FaultPlan(dropped=jnp.asarray([True, False, False, False]),
                         corrupt=jnp.asarray([False, True, False, False]),
                         blowup=jnp.asarray([False, False, True, False]),
                         stale=jnp.asarray([False, False, False, True]))
    x = jnp.ones((4, 3))
    stale = jnp.full((4, 3), 7.0)
    out = np.asarray(flt.perturb_tree(x, plan, fcfg, stale))
    assert (out[0] == 0).all()
    assert np.isnan(out[1]).all()
    assert (out[2] == 100.0).all()
    assert (out[3] == 7.0).all()
    # int leaves pass through untouched
    ints = jnp.ones((4, 3), jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(flt.perturb_tree(ints, plan, fcfg)), np.asarray(ints))


# ------------------------------------------------------------------ config
def test_config_validation():
    with pytest.raises(ValueError, match="screen_updates"):
        Params.from_dict(dict(BASE, screen_updates="yes"))
    with pytest.raises(ValueError, match="min_surviving"):
        Params.from_dict(dict(BASE, min_surviving_clients=0))
    with pytest.raises(ValueError, match="fault_corrupt_prob"):
        e = Experiment(Params.from_dict(dict(
            BASE, fault_injection=True, fault_corrupt_prob=1.5)),
            save_results=False)


def test_pad_tasks_rejects_non_fedavg():
    p = Params.from_dict(BASE)
    tasks = build_client_tasks(p, [0, 1], 1, np.zeros(2, np.int64), 1, None)
    padded = _pad_tasks(tasks, 2, "mean")
    assert padded.slot.shape == (4,)
    with pytest.raises(ValueError, match="only sound for FedAvg"):
        _pad_tasks(tasks, 2, "geom_median")


# -------------------------------------------------------------- end-to-end
def _run(params_dict, rounds):
    e = Experiment(Params.from_dict(params_dict), save_results=False)
    return e, [e.run_round(i) for i in range(1, rounds + 1)]


def _params_finite(e):
    return all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(e.global_vars))


def test_no_faults_screening_matches_dense_run():
    """Regression: the robust round program with nothing to quarantine must
    produce the same trajectory as the dense program (all-ones mask)."""
    e_dense, r_dense = _run(dict(BASE), 3)
    e_robust, r_robust = _run(dict(BASE, screen_updates=True), 3)
    for a, b in zip(jax.tree_util.tree_leaves(e_dense.global_vars),
                    jax.tree_util.tree_leaves(e_robust.global_vars)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    assert [r["global_acc"] for r in r_dense] == pytest.approx(
        [r["global_acc"] for r in r_robust], abs=1e-3)
    assert all(r["n_quarantined"] == 0 and not r["degraded"]
               for r in r_robust)


@pytest.mark.parametrize("aggregation", ["mean", "geom_median", "foolsgold"])
def test_injected_nan_never_reaches_global_model(aggregation):
    """Acceptance: an injected NaN-delta round never propagates non-finite
    values into the global model, under every aggregation rule."""
    e, results = _run(dict(BASE, aggregation_methods=aggregation,
                           fault_injection=True, fault_corrupt_prob=0.4,
                           fault_seed=3), 3)
    assert _params_finite(e)
    assert sum(r["n_quarantined"] for r in results) > 0
    assert all(np.isfinite(r["global_acc"]) for r in results)


def test_injected_dropout_degrades_gracefully():
    """All clients dropping out leaves too few survivors: aggregation is
    skipped, the global model is carried forward, the round is degraded."""
    e = Experiment(Params.from_dict(dict(
        BASE, fault_injection=True, fault_dropout_prob=1.0)),
        save_results=False)
    before = jax.device_get(e.global_vars)
    r = e.run_round(1)
    assert r["degraded"] and r["n_dropped"] == 4
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(jax.device_get(e.global_vars))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # recorder: degraded round lands in the round CSV columns
    row = dict(zip(
        ["epoch", "global_acc", "global_loss", "backdoor_acc",
         "n_quarantined", "n_dropped", "n_retries", "degraded",
         "round_time"], e.recorder.round_result[-1]))
    assert row["degraded"] == 1 and row["n_dropped"] == 4


def test_partial_dropout_renormalizes_and_learns():
    e, results = _run(dict(BASE, fault_injection=True,
                           fault_dropout_prob=0.3, fault_seed=5,
                           internal_epochs=2), 8)
    assert _params_finite(e)
    assert sum(r["n_dropped"] for r in results) > 0
    assert results[-1]["global_acc"] > 25.0  # still learns under dropout


def test_stale_replay_of_zero_history_is_identity():
    """stale_prob=1: every client replays the previous round's submitted
    delta; before any round that history is zero, so the global model is
    carried unchanged — deterministic check of the replay lane."""
    e = Experiment(Params.from_dict(dict(
        BASE, fault_injection=True, fault_stale_prob=1.0)),
        save_results=False)
    before = jax.device_get(e.global_vars)
    e.run_round(1)
    e.run_round(2)  # round 2 replays round 1's (zero) submitted deltas
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(jax.device_get(e.global_vars))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_stale_replay_source_survives_resume(tmp_path):
    """PR-6 satellite: the stale lane's replay source (last submitted
    deltas) rides the aux sidecar, so a killed-and-resumed run's first
    stale replay is faithful — previously it silently replayed zeros."""
    # stale_prob 0.5, not 1.0: with EVERY client replaying, the history
    # is zeros forever (round 1 replays the empty history) and the test
    # could not tell a faithful restore from the old zero fallback
    cfg = dict(BASE, epochs=4, fault_injection=True, fault_stale_prob=0.5,
               save_model=True, run_dir=str(tmp_path / "runs"),
               resumed_model="auto")
    e1 = Experiment(Params.from_dict(cfg))
    e1.run(epochs=2)  # rounds 1-2; checkpoint at 2 carries round-2 deltas
    want = jax.device_get(e1._prev_deltas)
    assert want is not None

    # fresh process stand-in: auto-resume from the same run_dir
    e2 = Experiment(Params.from_dict(cfg))
    assert e2.start_epoch == 3
    got = e2._prev_deltas
    assert got is not None, "replay source was not restored from the aux"
    for a, b in zip(jax.tree_util.tree_leaves(want),
                    jax.tree_util.tree_leaves(jax.device_get(got))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # some round-2 client actually submitted something non-zero, so the
    # faithful replay is distinguishable from the old zero fallback
    assert any(np.abs(np.asarray(l)).sum() > 0
               for l in jax.tree_util.tree_leaves(want))
    # and the resumed run keeps training on the restored history
    r = e2.run_round(3)
    assert np.isfinite(r["global_acc"])


def _corrupting_round_fn(real_fn, fail_times):
    """Wrap the engine's round program: the first `fail_times` invocations
    return a NaN global model with global_finite=False — a deterministic
    stand-in for aggregation overflow that screening could not prevent."""
    calls = {"n": 0}

    def wrapped(*args):
        new_vars, new_fg, payload, deltas_out = real_fn(*args)
        calls["n"] += 1
        if calls["n"] <= fail_times:
            new_vars = jax.tree_util.tree_map(
                lambda l: l * jnp.nan, new_vars)
            stats = payload[9]._replace(global_finite=jnp.asarray(False))
            payload = payload[:9] + (stats,) + payload[10:]
        return new_vars, new_fg, payload, deltas_out

    return wrapped, calls


def test_round_retry_recovers_from_nonfinite_aggregate():
    e = Experiment(Params.from_dict(dict(
        BASE, screen_updates=True, max_round_retries=2)),
        save_results=False)
    e.engine.round_fn, calls = _corrupting_round_fn(e.engine.round_fn, 1)
    r = e.run_round(1)
    assert calls["n"] == 2          # original attempt + one retry
    assert r["n_retries"] == 1 and not r["degraded"]
    assert _params_finite(e)


def test_round_retry_exhaustion_forces_degraded_round():
    e = Experiment(Params.from_dict(dict(
        BASE, screen_updates=True, max_round_retries=1)),
        save_results=False)
    before = jax.device_get(e.global_vars)
    e.engine.round_fn, calls = _corrupting_round_fn(e.engine.round_fn, 99)
    r = e.run_round(1)
    assert calls["n"] == 2          # original attempt + one retry
    assert r["n_retries"] == 1 and r["degraded"]
    assert _params_finite(e)
    assert np.isfinite(r["global_acc"])  # battery re-ran on restored model
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(jax.device_get(e.global_vars))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_norm_blowup_quarantined_by_norm_screen():
    e, results = _run(dict(BASE, fault_injection=True,
                           fault_blowup_prob=0.4, fault_blowup_factor=1e6,
                           screen_norm_mult=10.0, fault_seed=11), 3)
    assert _params_finite(e)
    assert sum(r["n_quarantined"] for r in results) > 0


@pytest.mark.slow
def test_backdoor_attack_under_faults_mesh():
    """Reference-scale rehearsal: the poison pathway with dropout + NaN
    faults on the 8-device mesh — survivor-masked FedAvg on a sharded
    clients axis, plus the robust counters flowing into the recorder."""
    poison = dict(
        BASE, no_models=8, internal_epochs=1, internal_poison_epochs=2,
        is_poison=True, local_eval=True, poison_label_swap=2,
        poisoning_per_batch=8, poison_lr=0.05, scale_weights_poison=4.0,
        adversary_list=[0, 1], trigger_num=2, alpha_loss=1.0,
        num_devices=-1, fault_injection=True, fault_dropout_prob=0.15,
        fault_corrupt_prob=0.15, fault_seed=2,
        **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
           "1_poison_pattern": [[3, 0], [3, 1], [3, 2], [3, 3]],
           "0_poison_epochs": [2, 3, 4], "1_poison_epochs": [3, 4]})
    e, results = _run(poison, 5)
    assert _params_finite(e)
    assert all(np.isfinite(r["global_acc"]) for r in results)
    faulted = sum(r["n_dropped"] + r["n_quarantined"] for r in results)
    assert faulted > 0
