"""Multi-device tests on the virtual 8-device CPU mesh (conftest.py) — the
clients axis sharded over devices must reproduce single-device numerics."""
import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

BASE = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=4, no_models=8,
    number_of_total_participants=16, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, internal_poison_epochs=2, is_poison=True,
    synthetic_data=True, synthetic_train_size=640, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False,
    poison_label_swap=2, poisoning_per_batch=8, poison_lr=0.05,
    scale_weights_poison=3.0, adversary_list=[0], trigger_num=1,
    alpha_loss=1.0, random_seed=1,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
       "0_poison_epochs": [2, 3]})


def test_mesh_matches_single_device():
    assert jax.device_count() >= 8, "conftest must provide 8 CPU devices"
    e1 = Experiment(Params.from_dict(BASE), save_results=False)
    e8 = Experiment(Params.from_dict(dict(BASE, num_devices=8)),
                    save_results=False)
    assert e8.mesh is not None and e8.mesh.devices.size == 8
    r1 = e1.run_round(1)
    r8 = e8.run_round(1)
    # ROUND 1 is tight: per-client training is device-local and
    # bit-identical; the two programs differ only in the FedAvg reduction
    # order (psum tree vs flat sum) — last-ulp noise through one round.
    l1 = jax.tree_util.tree_leaves(e1.global_vars.params)[0]
    l8 = jax.tree_util.tree_leaves(e8.global_vars.params)[0]
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l8), atol=1e-5)
    assert abs(r1["global_acc"] - r8["global_acc"]) < 0.5
    for i in range(2, 4):
        r1 = e1.run_round(i)
        r8 = e8.run_round(i)
    # Later rounds amplify that ulp-level seed chaotically through ReLU
    # boundaries (the same measured behavior as the cross-framework A/B,
    # PARITY_AB.md) → drift envelope + the accuracy bound, not bit equality.
    assert abs(r1["global_acc"] - r8["global_acc"]) < 1.0
    assert abs(r1["backdoor_acc"] - r8["backdoor_acc"]) < 2.0
    l1 = jax.tree_util.tree_leaves(e1.global_vars.params)[0]
    l8 = jax.tree_util.tree_leaves(e8.global_vars.params)[0]
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l8), atol=5e-3)


def test_mesh_pads_nondividing_client_count():
    cfg = dict(BASE, no_models=6, num_devices=8)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])
    # only the 6 real clients are recorded
    assert len({row[0] for row in e.recorder.train_result}) == 6


def test_mesh_padding_rejected_for_defenses():
    cfg = dict(BASE, no_models=6, num_devices=8,
               aggregation_methods="geom_median")
    e = Experiment(Params.from_dict(cfg), save_results=False)
    with pytest.raises(ValueError, match="tile"):
        e.run_round(1)
