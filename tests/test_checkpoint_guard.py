"""Checkpoint integrity manifests, quarantine/fallback, retention GC,
startup sweep, and `resumed_model: auto` (checkpoint.py + the Experiment
wiring). The subprocess kill/-9 end-to-end lives in
tests/test_crash_harness.py; everything here is in-process and cheap."""
import json
from pathlib import Path

import pytest

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

CFG = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=6, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=3,
    save_model=True)

VOLATILE = {"time", "round_time", "dispatch_time", "finalize_time"}


@pytest.fixture
def dba_log(caplog):
    """caplog wired to the 'dba_mod_tpu' logger directly: setup_logging
    (telemetry.py) sets propagate=False once a result-saving Experiment
    exists in the process, so root-level capture sees nothing."""
    import logging
    lg = logging.getLogger("dba_mod_tpu")
    lg.addHandler(caplog.handler)
    with caplog.at_level("WARNING", logger="dba_mod_tpu"):
        yield caplog
    lg.removeHandler(caplog.handler)


def _strip(row):
    return {k: v for k, v in row.items() if k not in VOLATILE}


def _metrics_rows(folder):
    with open(Path(folder) / "metrics.jsonl") as f:
        return [json.loads(line) for line in f if line.strip()]


def _flip_byte(path: Path, offset_frac=0.5):
    data = bytearray(path.read_bytes())
    data[int(len(data) * offset_frac) % len(data)] ^= 0xFF
    path.write_bytes(bytes(data))


def _largest_data_file(step_dir: Path) -> Path:
    return max((p for p in step_dir.rglob("*") if p.is_file()),
               key=lambda p: p.stat().st_size)


def _run(cfg, epochs, save_results=True):
    e = Experiment(Params.from_dict(cfg), save_results=save_results)
    e.run(epochs)
    return e


# ---------------------------------------------------------------- manifests
def test_manifest_verify_roundtrip(tmp_path):
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs")), 2)
    path = e.folder / "model_last.pt.tar"
    ok, reason = ckpt.verify_checkpoint(path)
    assert ok and reason == ckpt.VERIFY_OK
    assert ckpt.manifest_epoch(path) == 2
    doc = json.loads(ckpt.manifest_path(path).read_text())
    assert "aux" in doc["files"]  # the sidecar is covered too


def test_no_manifest_is_distinguished_from_corrupt(tmp_path):
    like = Experiment(Params.from_dict(dict(CFG, save_model=False)),
                      save_results=False)
    p = tmp_path / "m.pt.tar"
    ckpt.save_checkpoint(p, like.global_vars, 1, 0.1)
    ok, reason = ckpt.verify_checkpoint(p)
    assert not ok and reason == ckpt.VERIFY_NO_MANIFEST
    # resolve_verified accepts legacy (pretrain-style) snapshots as-is
    assert ckpt.resolve_verified(p) == p.absolute()
    with pytest.raises(FileNotFoundError):
        ckpt.resolve_verified(tmp_path / "never_saved.pt.tar")


def test_flipped_model_byte_detected_quarantined_and_fallback(tmp_path):
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs"),
                  save_on_epochs=[1, 2, 3]), 3)
    folder = e.folder
    # corrupt the two newest snapshots (model_last and .epoch_3 both hold
    # epoch 3; .best may too — kill it as well so the fallback is epoch 2)
    for name in ("model_last.pt.tar", "model_last.pt.tar.epoch_3",
                 "model_last.pt.tar.best"):
        _flip_byte(_largest_data_file(folder / name))
    best = ckpt.latest_verified_checkpoint(folder)
    assert best is not None and best.name == "model_last.pt.tar.epoch_2"
    quarantined = sorted(p.name for p in folder.iterdir()
                         if ckpt.CORRUPT_SUFFIX in p.name)
    assert quarantined == ["model_last.pt.tar.best.corrupt",
                           "model_last.pt.tar.corrupt",
                           "model_last.pt.tar.epoch_3.corrupt"]
    # the quarantine dir holds the moved pieces for post-mortem
    q = folder / "model_last.pt.tar.corrupt"
    assert (q / "model_last.pt.tar").is_dir()
    assert (q / "model_last.pt.tar.manifest.json").exists()


def test_flipped_sidecar_byte_detected_quarantined_and_fallback(tmp_path):
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs"),
                  save_on_epochs=[1, 2, 3]), 3)
    folder = e.folder
    for name in ("model_last.pt.tar", "model_last.pt.tar.epoch_3",
                 "model_last.pt.tar.best"):
        _flip_byte(folder / (name + ckpt.AUX_SUFFIX))
    best = ckpt.latest_verified_checkpoint(folder)
    assert best is not None and best.name == "model_last.pt.tar.epoch_2"
    ok, reason = ckpt.verify_checkpoint(best)
    assert ok, reason


def test_corrupt_sidecar_without_manifest_degrades_to_model_only(tmp_path,
                                                                 dba_log):
    like = Experiment(Params.from_dict(dict(CFG, save_model=False)),
                      save_results=False)
    p = tmp_path / "m.pt.tar"
    ckpt.save_checkpoint(p, like.global_vars, 1, 0.1)
    (tmp_path / ("m.pt.tar" + ckpt.AUX_SUFFIX)).write_bytes(
        b"\x80\x04 truncated garbage")
    assert ckpt.load_aux_state(p) is None
    assert any("model-only resume" in r.getMessage()
               for r in dba_log.records)
    # and a resume over it still works (reference model-only semantics)
    cfg = dict(CFG, save_model=False, checkpoint_dir=str(tmp_path),
               resumed_model=True, resumed_model_name="m.pt.tar")
    r = Experiment(Params.from_dict(cfg), save_results=False)
    assert r.start_epoch == 2 and r._resume_aux is None


# -------------------------------------------------------------- sweep + gc
def test_startup_sweep_removes_stale_tmp_artifacts(tmp_path, dba_log):
    folder = tmp_path / "f"
    folder.mkdir()
    (folder / ("model_last.pt.tar" + ckpt.AUX_SUFFIX + ".tmp")).write_bytes(
        b"half a pickle")
    (folder / "metrics.jsonl.tmp").write_text("{}")
    orphan = folder / "model_last.pt.tar.orbax-checkpoint-tmp-1234"
    orphan.mkdir()
    (orphan / "d").mkdir()
    removed = ckpt.sweep_stale(folder)
    assert sorted(removed) == [
        "metrics.jsonl.tmp",
        "model_last.pt.tar.aux.pkl.tmp",
        "model_last.pt.tar.orbax-checkpoint-tmp-1234/"]
    assert not orphan.exists()
    assert any("startup sweep" in r.getMessage() for r in dba_log.records)
    assert ckpt.sweep_stale(folder) == []  # idempotent


def test_retention_gc_keeps_last_n_best_and_model_last(tmp_path):
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs"), keep_last_n=2,
                  save_on_epochs=[1, 2, 3, 4, 5]), 5)
    folder = e.folder
    dirs = sorted(p.name for p in folder.iterdir() if p.is_dir())
    assert dirs == ["model_last.pt.tar", "model_last.pt.tar.best",
                    "model_last.pt.tar.epoch_4",
                    "model_last.pt.tar.epoch_5"]
    # sidecars + manifests of the GC'd snapshots are gone too
    for ep in (1, 2, 3):
        base = folder / f"model_last.pt.tar.epoch_{ep}"
        assert not Path(str(base) + ckpt.AUX_SUFFIX).exists()
        assert not ckpt.manifest_path(base).exists()
    # survivors are verified
    for name in dirs:
        ok, reason = ckpt.verify_checkpoint(folder / name)
        assert ok, (name, reason)


def test_verify_never_raises_on_mangled_manifest(tmp_path):
    """verify_checkpoint's never-crash contract: valid-JSON-wrong-shape
    manifests (the plausible products of partial writes and bit rot) must
    come back as (False, reason), never raise into the resume path."""
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs")), 1)
    path = e.folder / "model_last.pt.tar"
    m = ckpt.manifest_path(path)
    for doc in ('{"version": 1, "epoch": 1, "files": null}',
                '{"version": 1, "epoch": 1, "files": {"aux": 3}}',
                '{"version": 1, "epoch": 1, '
                '"files": {"aux": {"size": "y", "sha256": 1}}}',
                '[]',
                '{"epoch": 1}'):
        m.write_text(doc)
        ok, reason = ckpt.verify_checkpoint(path)
        assert not ok and reason, doc


def test_async_model_last_has_manifest_between_rounds(tmp_path):
    """A kill -9 *between* pipelined rounds must still find a verified
    model_last (with save_on_epochs: [] it is the only snapshot): the
    manifest owed to async save K is flushed at save K+1's
    prepare_overwrite — after waiting out commit K, which the K+1 enqueue
    would have blocked on anyway — not only at run end."""
    cfg = dict(CFG, run_dir=str(tmp_path / "runs"))
    e = Experiment(Params.from_dict(cfg), save_results=True)
    path = e.folder / "model_last.pt.tar"
    e.run_round(1)
    e.save_model(1, async_save=True)
    e.run_round(2)
    e.save_model(2, async_save=True)
    # no wait_for_async_saves: mid-run, epoch 1's manifest is on disk
    assert ckpt.manifest_epoch(path) == 1
    ckpt.wait_for_async_saves()
    assert ckpt.manifest_epoch(path) == 2
    ok, reason = ckpt.verify_checkpoint(path)
    assert ok, reason


def test_prev_clone_protects_mid_save_kill(tmp_path):
    """The observed kill-mid-save_model state (real kill -9 trace): the
    in-place model_last re-save landed but its manifest didn't (stale →
    quarantined on discovery), and the .best force-save died after
    deleting the old dir. The <name>.prev clone made by prepare_overwrite
    must be the surviving verified candidate, so auto-resume falls back
    one round instead of restarting from scratch."""
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs")), 2)
    folder = e.folder
    path = folder / "model_last.pt.tar"
    prev = ckpt.protect_last(path)
    assert prev is not None and ckpt.verify_checkpoint(prev)[0]
    import shutil
    # the round-3 re-save landed (orbax replaces the dir with NEW files —
    # the .prev hardlinks keep the old inodes) but its manifest didn't,
    # so model_last's manifest is stale...
    shutil.rmtree(path)
    ckpt.save_checkpoint(path, e.global_vars, 3, 0.05)
    # ...and the .best force-save died after deleting the old dir
    shutil.rmtree(folder / "model_last.pt.tar.best", ignore_errors=True)
    best = ckpt.latest_verified_checkpoint(folder)
    assert best is not None and best.name == "model_last.pt.tar.prev"
    # unprotect (manifest first) removes the clone entirely
    ckpt.unprotect_prev(path)
    assert not prev.exists()
    assert not ckpt.manifest_path(prev).exists()


def test_pipelined_async_saves_all_get_manifests(tmp_path):
    e = _run(dict(CFG, run_dir=str(tmp_path / "runs"), pipeline_rounds=True,
                  save_on_epochs=[1, 2, 3, 4]), 4)
    for name in ("model_last.pt.tar", "model_last.pt.tar.epoch_1",
                 "model_last.pt.tar.epoch_2", "model_last.pt.tar.epoch_3",
                 "model_last.pt.tar.epoch_4"):
        ok, reason = ckpt.verify_checkpoint(e.folder / name)
        assert ok, (name, reason)
        assert ckpt.manifest_epoch(e.folder / name) is not None


# ------------------------------------------------------------- auto-resume
def test_auto_resume_continues_same_folder_identical_trajectory(tmp_path):
    """The in-process half of the e2e acceptance: kill after 3 rounds
    (simulated by dropping the Experiment), `resumed_model: auto` reuses
    the run folder, continues the recorder stream with no duplicate
    rounds, and the full metrics trajectory is bit-identical (modulo
    wall-clock fields) to an uninterrupted run."""
    cfg = dict(CFG, run_dir=str(tmp_path / "runs"))
    ref = _run(dict(cfg, run_dir=str(tmp_path / "runs_ref")), 6)
    ref_rows = _metrics_rows(ref.folder)

    a = _run(cfg, 3)
    folder = a.folder
    del a
    b = Experiment(Params.from_dict(dict(cfg, resumed_model="auto")),
                   save_results=True)
    assert b.folder == folder          # reused, not a fresh timestamped dir
    assert b.start_epoch == 4
    assert b._resume_aux is not None   # full-state sidecar restored
    b.run(6)

    rows = _metrics_rows(folder)
    assert [r["epoch"] for r in rows] == [1, 2, 3, 4, 5, 6]  # no dupes
    assert len(ref_rows) == len(rows)
    for x, y in zip(ref_rows, rows):
        assert _strip(x) == _strip(y)
    # round_result.csv continued too
    lines = (folder / "round_result.csv").read_text().strip().splitlines()
    assert [line.split(",")[0] for line in lines[1:]] == [
        "1", "2", "3", "4", "5", "6"]


def test_auto_resume_interval_two_stays_on_grid(tmp_path):
    """aggr_epoch_interval=2: the checkpoint records the completed round's
    BASE epoch, and that round also trained the following seg epoch — the
    resumed run must continue at base+interval (the killed run's round
    grid), not base+1, and the recorder must keep the completed round's
    rows exactly once."""
    cfg = dict(CFG, run_dir=str(tmp_path / "runs"), aggr_epoch_interval=2)
    ref = _run(dict(cfg, run_dir=str(tmp_path / "runs_ref")), 6)
    ref_rows = _metrics_rows(ref.folder)

    a = _run(cfg, 4)       # rounds at base epochs 1, 3 (seg epochs 1..4)
    folder = a.folder
    del a
    b = Experiment(Params.from_dict(dict(cfg, resumed_model="auto")),
                   save_results=True)
    assert b.folder == folder
    assert b.start_epoch == 5          # next base on the 1,3,5 grid
    b.run(6)

    rows = _metrics_rows(folder)
    assert [r["epoch"] for r in rows] == [r["epoch"] for r in ref_rows]
    for x, y in zip(ref_rows, rows):
        assert _strip(x) == _strip(y)


def test_auto_resume_falls_back_past_corrupt_newest(tmp_path, dba_log):
    cfg = dict(CFG, run_dir=str(tmp_path / "runs"), save_on_epochs=[1, 2, 3])
    a = _run(cfg, 3)
    folder = a.folder
    del a
    for name in ("model_last.pt.tar", "model_last.pt.tar.epoch_3",
                 "model_last.pt.tar.best"):
        _flip_byte(_largest_data_file(folder / name))
    b = Experiment(Params.from_dict(dict(cfg, resumed_model="auto")),
                   save_results=True)
    assert b.folder == folder
    assert b.start_epoch == 3  # fell back to the verified epoch-2 snapshot
    assert any("failed verification" in r.getMessage()
               for r in dba_log.records)
    # recorder truncated past the fallback epoch: round 3 will be replayed
    assert [r["epoch"] for r in b.recorder._jsonl_rows] == [1, 2]
    b.run(3)
    assert [r["epoch"] for r in _metrics_rows(folder)] == [1, 2, 3]


def test_auto_resume_with_nothing_to_find_starts_fresh(tmp_path, dba_log):
    cfg = dict(CFG, run_dir=str(tmp_path / "empty_runs"),
               resumed_model="auto")
    e = Experiment(Params.from_dict(cfg), save_results=True)
    assert e.start_epoch == 1
    assert any("no verified checkpoint" in r.getMessage()
               for r in dba_log.records)


def test_named_resume_of_corrupt_checkpoint_falls_back(tmp_path):
    cfg = dict(CFG, run_dir=str(tmp_path / "runs"), save_on_epochs=[1, 2])
    a = _run(cfg, 2)
    folder = a.folder
    del a
    _flip_byte(_largest_data_file(folder / "model_last.pt.tar"))
    # epoch_2/.best hold epoch 2 verified — the named resume restores a
    # same-name-family fallback instead of crashing, and (the dir may be
    # a shared checkpoint library other processes write into) it must NOT
    # mutate anything: no quarantine, no sweep
    resume = dict(CFG, checkpoint_dir=str(folder), resumed_model=True,
                  resumed_model_name="model_last.pt.tar")
    r = Experiment(Params.from_dict(resume), save_results=False)
    assert r.start_epoch == 3
    assert not any(ckpt.CORRUPT_SUFFIX in p.name for p in folder.iterdir())
