"""Performance-path semantics: the bench-mode knobs (dynamic step buckets,
round pipelining, clients-per-device stacking) must not change numerics.

These are the TPU-native throughput levers (no reference counterpart — the
reference's sequential loop has no plan shapes to bucket and nothing to
pipeline); the contract tested here is exact-parity with the plain path."""
import numpy as np
import pytest

import jax

from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

# Dirichlet sampling → unequal client sizes → per-round max steps varies,
# so dynamic_steps actually changes the plan shapes it must prove inert.
BASE = dict(
    type="mnist", lr=0.1, batch_size=8, epochs=4, no_models=4,
    number_of_total_participants=12, eta=0.8, aggregation_methods="mean",
    internal_epochs=2, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=128, momentum=0.9,
    decay=0.0005, sampling_dirichlet=True, dirichlet_alpha=0.5,
    local_eval=False, random_seed=3)


def _params_of(e):
    return np.concatenate([np.asarray(l).ravel() for l in
                           jax.tree_util.tree_leaves(e.global_vars.params)])


def test_dynamic_steps_bitexact():
    """Bucketed per-round plans = the static plan minus fully-masked no-op
    steps → bit-identical training (models without dropout)."""
    e_s = Experiment(Params.from_dict(BASE), save_results=False)
    e_d = Experiment(Params.from_dict(dict(BASE, dynamic_steps=True)),
                     save_results=False)
    buckets = e_d.warm_step_buckets()
    assert buckets, "dynamic mode must expose its compile shapes"
    shrunk = False
    for i in range(1, 5):
        r_s = e_s.run_round(i)
        r_d = e_d.run_round(i)
        assert r_s["global_acc"] == r_d["global_acc"]
        # at least one round must actually use a smaller plan
        smax = max(len(e_d.client_indices[n]) for n in r_d["agents"])
        b = int(e_d.params["batch_size"])
        if e_d._bucket_steps(int(np.ceil(smax / b))) < e_d.steps_per_epoch:
            shrunk = True
    assert shrunk, "test must exercise a genuinely smaller bucket"
    np.testing.assert_array_equal(_params_of(e_s), _params_of(e_d))
    # identical recorded training rows (same losses, same counts)
    assert e_s.recorder.train_result == e_d.recorder.train_result


def test_pipelined_rounds_bitexact():
    """Depth-1 round pipelining (fetch N while computing N+1) reorders only
    host transfers, never device math."""
    e_p = Experiment(Params.from_dict(dict(BASE, pipeline_rounds=True,
                                           local_eval=True)),
                     save_results=False)
    e_n = Experiment(Params.from_dict(dict(BASE, local_eval=True)),
                     save_results=False)
    last_p = e_p.run()
    last_n = e_n.run()
    assert last_p["epoch"] == last_n["epoch"]
    assert last_p["global_acc"] == last_n["global_acc"]
    np.testing.assert_array_equal(_params_of(e_p), _params_of(e_n))
    assert e_p.recorder.train_result == e_n.recorder.train_result
    assert len(e_p.recorder.test_result) == len(e_n.recorder.test_result)


def test_full_width_round_stacks_clients_per_device():
    """100 selected clients on the 8-device mesh → 13 stacked clients per
    device (SURVEY §7.1 step 10): the clients axis is a capacity axis, not
    capped at the device count."""
    assert jax.device_count() >= 8
    cfg = dict(BASE, no_models=100, number_of_total_participants=120,
               synthetic_train_size=1500, internal_epochs=1, num_devices=8,
               epochs=1)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])
    # all 100 real clients trained and were recorded; the 4 inert pads not
    assert len({row[0] for row in e.recorder.train_result}) == 100
    # and the full-width round matches the same round without a mesh
    e1 = Experiment(Params.from_dict(dict(cfg, num_devices=0)),
                    save_results=False)
    r1 = e1.run_round(1)
    assert abs(r1["global_acc"] - r["global_acc"]) < 0.5
    np.testing.assert_allclose(_params_of(e), _params_of(e1), atol=1e-5)
