"""Data layer: partition numerics vs an inline reference oracle, batch plans,
synthetic datasets."""
import random
from collections import defaultdict

import numpy as np

from dba_mod_tpu.data import batching, datasets, partition


def _reference_dirichlet(labels, no_participants, alpha, seed):
    """Oracle transcribing the documented semantics of
    image_helper.py:82-110 (shuffle pool; dirichlet; int(round) prefix)."""
    py = random.Random(seed)
    nprng = np.random.RandomState(seed)
    classes = defaultdict(list)
    for ind, l in enumerate(labels):
        classes[int(l)].append(ind)
    class_size = len(classes[0])
    per = defaultdict(list)
    for n in range(len(classes)):
        py.shuffle(classes[n])
        probs = class_size * nprng.dirichlet(np.array([alpha] * no_participants))
        for user in range(no_participants):
            k = min(len(classes[n]), int(round(probs[user])))
            per[user].extend(classes[n][:k])
            classes[n] = classes[n][k:]
    return per


def test_dirichlet_partition_matches_oracle():
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 10, size=2000)
    exp = _reference_dirichlet(labels, 20, 0.5, seed=7)
    got = partition.sample_dirichlet_indices(
        labels, 20, 0.5, py_rng=random.Random(7),
        np_rng=np.random.RandomState(7))
    for u in range(20):
        assert got[u] == exp[u]


def test_dirichlet_partition_nonuniform_and_disjoint():
    labels = np.random.RandomState(1).randint(0, 10, size=5000)
    got = partition.sample_dirichlet_indices(
        labels, 10, 0.5, py_rng=random.Random(1),
        np_rng=np.random.RandomState(1))
    sizes = [len(v) for v in got.values()]
    assert max(sizes) > min(sizes)  # non-IID → unequal
    all_idx = sum(got.values(), [])
    assert len(all_idx) == len(set(all_idx))  # disjoint


def test_equal_split():
    got = partition.equal_split_indices(1000, 10, py_rng=random.Random(0))
    assert all(len(v) == 100 for v in got.values())
    all_idx = sum(got.values(), [])
    assert len(set(all_idx)) == 1000


def test_poison_test_indices_drop_target_class():
    labels = np.array([0, 2, 1, 2, 3, 2])
    idx = partition.poison_test_indices(labels, 2)
    np.testing.assert_array_equal(idx, [0, 2, 4])


def test_batch_plan_shapes_and_masks():
    clients = [list(range(10)), list(range(10, 150)), []]
    plan = batching.build_batch_plan(clients, [2, 1, 1], batch_size=64,
                                    rng=np.random.RandomState(0))
    C, E, S, B = plan.idx.shape
    assert (C, E, B) == (3, 2, 64)
    assert S == 3  # ceil(140/64)
    np.testing.assert_array_equal(plan.num_samples, [10, 140, 0])
    # client 0 epoch 0: 10 valid, each epoch a different shuffle of its subset
    assert plan.mask[0, 0].sum() == 10
    assert sorted(plan.idx[0, 0][plan.mask[0, 0]].tolist()) == list(range(10))
    assert plan.mask[0, 1].sum() == 10  # epoch 1 exists for client 0 (2 epochs)
    # client 1 has only 1 epoch -> epoch row 1 fully masked
    assert plan.mask[1, 1].sum() == 0
    assert plan.mask[1, 0].sum() == 140
    # empty client fully masked
    assert plan.mask[2].sum() == 0


def test_eval_plan_padding():
    plan = batching.build_eval_plan(np.arange(130), 64)
    assert plan.idx.shape == (3, 64)
    assert plan.mask.sum() == 130
    assert plan.mask[2, :2].all() and not plan.mask[2, 2:].any()


def test_synthetic_image_dataset_learnable_and_deterministic():
    a = datasets.synthetic_image_dataset("mnist", train_size=256, test_size=64,
                                         seed=3)
    b = datasets.synthetic_image_dataset("mnist", train_size=256, test_size=64,
                                         seed=3)
    np.testing.assert_array_equal(a.train_images, b.train_images)
    assert a.train_images.shape == (256, 28, 28, 1)
    assert a.train_images.dtype == np.uint8
    assert set(np.unique(a.train_labels)) <= set(range(10))
    # classes are separable by nearest-template → a linear probe can learn:
    # check within-class variance < between-class distance on pixel means
    m0 = a.train_images[a.train_labels == 0].mean(0)
    m1 = a.train_images[a.train_labels == 1].mean(0)
    assert np.abs(m0 - m1).mean() > 10


def test_synthetic_loan_dataset_schema():
    d = datasets.synthetic_loan_dataset(num_states=51, seed=1)
    assert len(d.state_names) == 51
    assert d.train_x[0].shape[1] == 91
    fd = d.feature_dict
    for name in ["num_tl_120dpd_2m", "pub_rec", "tax_liens"]:
        assert name in fd
    assert len(set(d.state_names)) == 51


def test_stack_ragged():
    arrs = [np.ones((3, 2)), np.ones((5, 2)) * 2]
    out = batching.stack_ragged(arrs)
    assert out.shape == (2, 5, 2)
    assert out[0, 3:].sum() == 0


def test_poisoning_clients_report_clean_partition_size():
    """num_samples quirk decision (README quirk table): the reference's
    poison branch iterates the SAME per-client loader as the benign branch
    (image_train.py:72 reuses helper.train_data[agent]; LOAN's
    get_poison_trainloader returns the full state shard,
    loan_helper.py:56-61), so the `dataset_size` it reports into
    num_samples_dict (image_train.py:137) EQUALS the clean partition size.
    build_batch_plan.num_samples — which feeds RFA's Weiszfeld alphas
    (helper.py:303,316) — must therefore be the clean partition size for
    poisoning and benign clients alike."""
    rng = np.random.RandomState(0)
    indices = [list(range(37)), list(range(100, 153)), list(range(200, 212))]
    # poisoning client 0 trains more epochs than the benign ones — the
    # reported size must not depend on the epoch count or poison status
    plan = batching.build_batch_plan(indices, [6, 2, 2], batch_size=8,
                                     rng=rng, min_steps=7, min_epochs=6)
    np.testing.assert_array_equal(plan.num_samples, [37, 53, 12])
