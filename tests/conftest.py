"""Test configuration: force an 8-device virtual CPU platform.

Multi-device tests exercise the `clients` mesh axis without TPU hardware — the
TPU-world equivalent of a fake backend (SURVEY.md §4). Must run before jax
initializes a backend, hence module-level in conftest.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
