"""Test configuration: force an 8-device virtual CPU platform.

Multi-device tests exercise the `clients` mesh axis without TPU hardware — the
TPU-world equivalent of a fake backend (SURVEY.md §4).

Note: this image's sitecustomize registers an `axon` TPU PJRT plugin at
interpreter startup and pins the platform, so setting JAX_PLATFORMS in the
environment is not enough — we must override the jax config after import and
set the host-device-count flag before the CPU backend initializes.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
# Persistent compile cache: the suite's cost is XLA compiles of model-sized
# programs; cache them across runs (safe to delete anytime).
from dba_mod_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()
assert jax.device_count() == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}")
