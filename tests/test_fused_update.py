"""ops/fused_update.py coverage (ADVICE r3 medium): the custom_vmap batch
rule, VMEM chunking, and rank/size fallback paths run in Pallas INTERPRET
mode on CPU and must be bit-identical to the plain per-leaf jnp reference —
exercised the way the client step uses them: vmapped over clients inside a
lax.scan, with invalid (masked) lanes and FoolsGold on/off."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dba_mod_tpu.ops.fused_update import _VMEM_BUDGET, make_fused_step_update

C = 3
MOMENTUM, DECAY = 0.9, 5e-4


def _stacked_state(rng):
    """Per-client leaves of rank 0-4 (stacked rank 1-5): the rank-1 stacked
    leaves are the Pallas lane; everything else exercises the rank fallback;
    `big` exceeds _VMEM_BUDGET in tiled layout → size fallback."""
    def a(*shape):
        return jnp.asarray(rng.randn(C, *shape).astype(np.float32))

    big_d = _VMEM_BUDGET // (5 * 4 * 8) + 128  # padded bytes > budget
    mid_d = big_d // 2                         # two fit only in separate
    params = {"r0": a(), "r1a": a(33), "r1b": a(257), "r2": a(9, 130),
              "r3": a(3, 5, 7), "r4": a(2, 3, 4, 5), "big": a(big_d),
              "mid1": a(mid_d), "mid2": a(mid_d)}  # exercise chunk flush
    assert params["big"].ndim == 2
    return params


def _run(fused, fg_enabled, rng):
    params = _stacked_state(rng)
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    fg = jax.tree_util.tree_map(jnp.zeros_like, params) if fg_enabled else {}
    bn_old = {"mean": jnp.asarray(rng.randn(C, 18, 140).astype(np.float32)),
              "var": jnp.asarray(rng.rand(C, 31).astype(np.float32))}
    lr = jnp.asarray([0.1, 0.02, 0.5], jnp.float32)
    valid_seq = jnp.asarray([[True, False, True],
                             [True, True, False],
                             [False, False, True]])
    gseed = jax.tree_util.tree_map(lambda l: l * 0.1, params)

    def body(carry, inp):
        params, mom, fg = carry
        step, valid = inp
        # iteration-dependent grads and BN updates
        grads = jax.tree_util.tree_map(
            lambda l: l * (1.0 + 0.3 * step), gseed)
        bn_new = jax.tree_util.tree_map(
            lambda l: l + 0.01 * step, bn_old)
        p2, m2, f2, b2 = jax.vmap(fused)(lr, valid, params, grads, mom, fg,
                                         bn_new, bn_old)
        return (p2, m2, f2), b2

    (p, m, f), bns = jax.lax.scan(
        body, (params, mom, fg),
        (jnp.arange(3, dtype=jnp.float32), valid_seq))
    return p, m, f, bns


@pytest.mark.parametrize("fg_enabled", [False, True])
def test_interpret_mode_matches_jnp_reference_bit_exact(fg_enabled):
    fused = make_fused_step_update(MOMENTUM, DECAY, fg_enabled,
                                   use_pallas=True, interpret=True)
    ref = make_fused_step_update(MOMENTUM, DECAY, fg_enabled,
                                 use_pallas=False)
    out_f = _run(fused, fg_enabled, np.random.RandomState(0))
    out_r = _run(ref, fg_enabled, np.random.RandomState(0))
    for leaf_f, leaf_r in zip(jax.tree_util.tree_leaves(out_f),
                              jax.tree_util.tree_leaves(out_r)):
        np.testing.assert_array_equal(np.asarray(leaf_f), np.asarray(leaf_r))


def test_invalid_lanes_are_exact_no_ops():
    """A fully-masked lane's state must be bit-untouched through the fused
    path (inert-client padding and step-mask semantics depend on it)."""
    fused = make_fused_step_update(MOMENTUM, DECAY, True, use_pallas=True,
                                   interpret=True)
    rng = np.random.RandomState(1)
    params = _stacked_state(rng)
    mom = jax.tree_util.tree_map(lambda l: l * 0.5, params)
    fg = jax.tree_util.tree_map(lambda l: l * 0.25, params)
    bn_old = {"v": jnp.asarray(rng.randn(C, 12).astype(np.float32))}
    bn_new = jax.tree_util.tree_map(lambda l: l + 1.0, bn_old)
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    lr = jnp.full((C,), 0.1, jnp.float32)
    valid = jnp.asarray([False, True, False])
    p2, m2, f2, b2 = jax.vmap(fused)(lr, valid, params, grads, mom, fg,
                                     bn_new, bn_old)
    for new, old in ((p2, params), (m2, mom), (f2, fg), (b2, bn_old)):
        for ln, lo in zip(jax.tree_util.tree_leaves(new),
                          jax.tree_util.tree_leaves(old)):
            np.testing.assert_array_equal(np.asarray(ln)[0],
                                          np.asarray(lo)[0])
            np.testing.assert_array_equal(np.asarray(ln)[2],
                                          np.asarray(lo)[2])
    # ... while the valid lane moved
    assert np.abs(np.asarray(p2["r1a"])[1]
                  - np.asarray(params["r1a"])[1]).max() > 0
