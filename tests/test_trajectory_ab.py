"""Compressed converged-regime trajectory A/B (VERDICT r4 ask #1) — the
slow-marked envelope assertion; the full curves artifact is
`python -m benchmarks.trajectory_ab` (PARITY_AB.md trajectory section).

Both frameworks resume from the SAME pretrained state and replay the
reference's single-shot DBA schedule structure (staggered poison rounds,
then clean rounds of backdoor decay) with shared batch plans. The ±1%
north-star envelope (BASELINE.json) is asserted on the curve level: mean
per-round gap and final-state gaps.
"""
import numpy as np
import pytest

from benchmarks.trajectory_ab import (multi_shot_epochs, pretrain,
                                      run_trajectory, single_shot_epochs,
                                      splice_trajectory_section,
                                      extract_trajectory_section, summarize,
                                      CIFAR_TRAJ, MNIST_TRAJ)

# compressed CIFAR lane: same hyper-structure as the full harness
# (model-replacement strength eta*scale/no_models = 1 preserved via
# scale=no_models/eta), smaller population/data so the test compiles+runs
# in minutes instead of hours
CIFAR_SMALL = dict(
    CIFAR_TRAJ, number_of_total_participants=16, no_models=6,
    scale_weights_poison=60,  # 6 clients / eta 0.1 → full replacement
    synthetic_train_size=1200, synthetic_test_size=400, batch_size=32,
    internal_poison_epochs=3, adversary_list=[5, 3, 7, 11])

MNIST_SMALL = dict(
    MNIST_TRAJ, number_of_total_participants=16, no_models=6,
    synthetic_train_size=1200, synthetic_test_size=400,
    internal_poison_epochs=4, poisoning_per_batch=10,
    adversary_list=[5, 3, 7, 11])


@pytest.mark.slow
def test_cifar_single_shot_converged_envelope():
    E0 = 12
    init_vars, accs = pretrain(CIFAR_SMALL, E0)
    # "converged": stable non-trivial accuracy on the learnable fabricated
    # data — far from the 10% chance level of the r4 near-init cells
    assert accs[-1] > 40.0, f"pretrain did not converge: {accs}"

    cfg = dict(CIFAR_SMALL, **single_shot_epochs(E0))
    traj = run_trajectory(cfg, init_vars, E0 + 1, E0 + 21,
                          label="test: cifar single-shot + fedavg")
    s = summarize(traj)
    # the attack landed on both sides (model replacement from a converged
    # state — the reference's headline phenomenon)
    assert s["jax_peak_backdoor"] > 50.0 and s["torch_peak_backdoor"] > 50.0
    # ±1% envelope at the curve level (both frameworks integrate their own
    # f32 rounding; per-round decay transients can wobble, the running
    # claim is mean + final agreement)
    assert s["mean_clean_gap"] <= 1.0, s
    assert s["mean_backdoor_gap"] <= 1.5, s
    assert s["final_clean_gap"] <= 1.0, s
    assert s["final_backdoor_gap"] <= 1.0, s


@pytest.mark.slow
def test_mnist_multi_shot_ramp_envelope():
    M0 = 6
    init_vars, accs = pretrain(MNIST_SMALL, M0)
    cfg = dict(MNIST_SMALL, **multi_shot_epochs(M0 + 1, M0 + 8))
    traj = run_trajectory(cfg, init_vars, M0 + 1, M0 + 11,
                          label="test: mnist multi-shot ramp")
    s = summarize(traj)
    assert s["jax_peak_backdoor"] > 50.0 and s["torch_peak_backdoor"] > 50.0
    assert s["mean_clean_gap"] <= 1.0, s
    assert s["mean_backdoor_gap"] <= 1.5, s
    assert s["final_clean_gap"] <= 1.0, s
    assert s["final_backdoor_gap"] <= 1.0, s


def test_trajectory_section_splice(tmp_path):
    """Marker-section splice/extract round-trips and preserves surrounding
    content (parity_ab.main regeneration path)."""
    md = tmp_path / "P.md"
    md.write_text("# head\nbody\n")
    splice_trajectory_section(str(md), "SECTION ONE\n")
    assert extract_trajectory_section(md.read_text()) == "\nSECTION ONE\n"
    splice_trajectory_section(str(md), "SECTION TWO\n")
    text = md.read_text()
    assert extract_trajectory_section(text) == "\nSECTION TWO\n"
    assert text.startswith("# head\nbody\n") and "SECTION ONE" not in text
