"""Compressed converged-regime trajectory A/B (VERDICT r4 ask #1) — the
slow-marked envelope assertions; the full curves artifact (CIFAR-BN under
all three defenses + the MNIST ramp, flax side on the TPU) is
`python -m benchmarks.trajectory_ab` → PARITY_AB.md trajectory section.

Both frameworks resume from the SAME pretrained state and replay the
reference's attack schedules with shared batch plans. The lanes here are
MNIST (CPU-tractable on this box) in the two regimes where per-round curve
agreement is a meaningful claim:

- single-shot + model replacement with the STEPPED poison LR
  (internal_poison_epochs=10 → torch MultiStepLR milestones 2.0/8.0 fire,
  unlike CIFAR's never-firing 1.2/4.8 — ops/sgd.py::_milestone_hits);
- the multi-shot ramp (baseline=true, eta=1 — mnist_params.yaml:30-31).

The CIFAR scale-100 replacement transient is deliberately NOT asserted
per-round: its flat-LR 6-epoch poison training is a measured knife edge
where any two runs (including two reference runs) separate chaotically —
see the phase-wise gap analysis in the PARITY_AB.md trajectory section.
"""
import numpy as np
import pytest

from benchmarks.trajectory_ab import (MNIST_TRAJ, multi_shot_epochs,
                                      pretrain, run_trajectory,
                                      single_shot_epochs,
                                      splice_trajectory_section,
                                      extract_trajectory_section, summarize)

MNIST_BASE = dict(
    MNIST_TRAJ, number_of_total_participants=16, no_models=6,
    synthetic_train_size=1600, synthetic_test_size=400,
    adversary_list=[5, 3, 7, 11])

# single-shot: reference mnist_params.yaml single-shot switches
# (baseline=false, eta=0.1; scale preserves eta·scale/no_models = 1)
MNIST_SINGLE = dict(MNIST_BASE, baseline=False, eta=0.1,
                    scale_weights_poison=60)


@pytest.mark.slow
def test_mnist_single_shot_converged_envelope():
    E0 = 10
    # the BN-free MnistNet needs more local work per clean round than the
    # attack config's internal_epochs=1 provides (trajectory_ab.pretrain)
    init_vars, accs = pretrain(MNIST_SINGLE, E0, internal_epochs=4, eta=1.0)
    # converged: stable non-trivial accuracy on the learnable fabricated
    # data — far from the 10% chance level of the r4 near-init cells
    assert accs[-1] > 60.0, f"pretrain did not converge: {accs}"

    cfg = dict(MNIST_SINGLE,
               **{f"{i}_poison_epochs": [E0 + o]
                  for i, o in enumerate((2, 3, 4, 5))})
    traj = run_trajectory(cfg, init_vars, E0 + 1, E0 + 17,
                          label="test: mnist single-shot + fedavg")
    s = summarize(traj)
    # the attack lands on both sides (model replacement from converged)
    assert s["jax_peak_backdoor"] > 50.0 and s["torch_peak_backdoor"] > 50.0
    # ±1% envelope where it is a meaningful claim: the converged pre-attack
    # rounds and the post-decay tail; the whole-run mean stays small too
    assert s["pre_max_clean_gap"] <= 1.0, s
    assert s["tail_mean_clean_gap"] <= 1.0, s
    assert s["tail_mean_backdoor_gap"] <= 1.5, s
    assert s["final_clean_gap"] <= 1.0, s


@pytest.mark.slow
def test_mnist_multi_shot_ramp_envelope():
    M0 = 6
    init_vars, accs = pretrain(MNIST_BASE, M0, internal_epochs=4, eta=1.0)
    cfg = dict(MNIST_BASE, **multi_shot_epochs(M0 + 1, M0 + 8))
    traj = run_trajectory(cfg, init_vars, M0 + 1, M0 + 11,
                          label="test: mnist multi-shot ramp")
    s = summarize(traj)
    assert s["jax_peak_backdoor"] > 50.0 and s["torch_peak_backdoor"] > 50.0
    assert s["mean_clean_gap"] <= 1.0, s
    assert s["mean_backdoor_gap"] <= 1.5, s
    assert s["final_clean_gap"] <= 1.0, s
    assert s["final_backdoor_gap"] <= 1.5, s


def test_trajectory_section_splice(tmp_path):
    """Marker-section splice/extract round-trips and preserves surrounding
    content (parity_ab.main regeneration path)."""
    md = tmp_path / "P.md"
    md.write_text("# head\nbody\n")
    splice_trajectory_section(str(md), "SECTION ONE\n")
    assert extract_trajectory_section(md.read_text()) == "\nSECTION ONE\n"
    splice_trajectory_section(str(md), "SECTION TWO\n")
    text = md.read_text()
    assert extract_trajectory_section(text) == "\nSECTION TWO\n"
    assert text.startswith("# head\nbody\n") and "SECTION ONE" not in text
