"""Full-state checkpointing (VERDICT r4 #3): the checkpoint sidecar carries
FoolsGold memory, best-val loss and every RNG stream, so a killed-and-resumed
run replays the uninterrupted trajectory exactly.

The reference cannot do this: helper.py:420-435 checkpoints weights only and
FoolsGold's cross-round memory_dict is RAM-only (helper.py:545-549) — a
mid-attack restart silently resets the defense. Documented deviation
(checkpoint.py module docstring).
"""
import jax
import numpy as np
import pytest

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.config import Params
from dba_mod_tpu.fl.experiment import Experiment

FG_CFG = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=6, no_models=4,
    number_of_total_participants=10, eta=0.8,
    aggregation_methods="foolsgold", internal_epochs=1, is_poison=False,
    synthetic_data=True, synthetic_train_size=600, synthetic_test_size=256,
    momentum=0.9, decay=0.0005, sampling_dirichlet=False, local_eval=False,
    random_seed=7, save_model=True)


def _run_rounds(exp, epochs):
    """run_round + save_model per epoch; returns the wv rows (one list per
    round — recorder appends [names, wv, alpha] triplets)."""
    for ep in epochs:
        exp.run_round(ep)
        exp.save_model(ep)
    rows = exp.recorder.weight_result
    return {i // 3: rows[i + 1] for i in range(0, len(rows), 3)}


def test_foolsgold_kill_resume_identical_wv_trajectory(tmp_path):
    # A: uninterrupted 6-round run
    a = Experiment(Params.from_dict(FG_CFG), save_results=False)
    a.folder = tmp_path / "a"
    wv_a = _run_rounds(a, range(1, 7))
    assert len(wv_a) == 6

    # B: run 3 rounds, "kill", resume from the checkpoint, run 4..6
    b = Experiment(Params.from_dict(FG_CFG), save_results=False)
    b.folder = tmp_path / "b"
    wv_b_pre = _run_rounds(b, range(1, 4))
    del b  # the kill

    cfg_resume = dict(FG_CFG, checkpoint_dir=str(tmp_path / "b"),
                      resumed_model=True,
                      resumed_model_name="model_last.pt.tar")
    c = Experiment(Params.from_dict(cfg_resume), save_results=False)
    c.folder = tmp_path / "c"
    assert c.start_epoch == 4
    assert c._resume_aux is not None          # the sidecar was found
    # FoolsGold memory survived the restart (a fresh init would be zeros)
    assert float(np.abs(np.asarray(c.fg_state.memory)).max()) > 0
    wv_c = _run_rounds(c, range(4, 7))

    # the resumed rounds 4-6 must equal the uninterrupted run's — same
    # selected agents (select_rng), same batch plans (plan_rng), same
    # dropout/noise keys (rng_key), same FoolsGold memory
    for local_i, ep_i in zip(range(3), range(3, 6)):
        np.testing.assert_allclose(wv_c[local_i], wv_a[ep_i], rtol=0,
                                   atol=0, err_msg=f"round {ep_i + 1}")
    # and the pre-kill rounds matched too (same seed, same code path)
    for i in range(3):
        np.testing.assert_allclose(wv_b_pre[i], wv_a[i], rtol=0, atol=0)

    # final global params identical to the uninterrupted run's
    for la, lc in zip(jax.tree_util.tree_leaves(a.global_vars.params),
                      jax.tree_util.tree_leaves(c.global_vars.params)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lc))


def test_model_only_resume_still_works(tmp_path):
    """A checkpoint without a sidecar (e.g. pretrain output) resumes in the
    reference's model-only mode — no crash, RNGs restart from the seed."""
    cfg = dict(FG_CFG, save_model=False)
    e = Experiment(Params.from_dict(cfg), save_results=False)
    e.run_round(1)
    path = tmp_path / "model.pt.tar"
    ckpt.save_checkpoint(path, e.global_vars, 1, float(e.params["lr"]))
    assert ckpt.load_aux_state(path) is None

    cfg_resume = dict(cfg, checkpoint_dir=str(tmp_path), resumed_model=True,
                      resumed_model_name="model.pt.tar")
    r = Experiment(Params.from_dict(cfg_resume), save_results=False)
    assert r.start_epoch == 2 and r._resume_aux is None
    assert float(np.abs(np.asarray(r.fg_state.memory)).max()) == 0
    r.run_round(2)  # runs fine


def test_sidecar_shape_mismatch_is_loud(tmp_path):
    """Resuming a sidecar from a different participant set must raise, not
    silently mis-seed the defense."""
    e = Experiment(Params.from_dict(FG_CFG), save_results=False)
    e.folder = tmp_path
    e.run_round(1)
    e.save_model(1)
    bad = dict(FG_CFG, number_of_total_participants=6,
               checkpoint_dir=str(tmp_path), resumed_model=True,
               resumed_model_name="model_last.pt.tar")
    with pytest.raises(ValueError, match="FoolsGold memory shape"):
        Experiment(Params.from_dict(bad), save_results=False)
