"""Trigger stamping parity with reference image_helper.py:298-350 and
loan_train.py:99-107 / test.py:75-81 semantics."""
import numpy as np

import jax.numpy as jnp

from dba_mod_tpu import config as cfg
from dba_mod_tpu.ops import triggers

CIFAR_PATTERNS = {
    "0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3], [0, 4], [0, 5]],
    "1_poison_pattern": [[0, 9], [0, 10], [0, 11], [0, 12], [0, 13], [0, 14]],
    "2_poison_pattern": [[4, 0], [4, 1], [4, 2], [4, 3], [4, 4], [4, 5]],
    "3_poison_pattern": [[4, 9], [4, 10], [4, 11], [4, 12], [4, 13], [4, 14]],
}


def _params(**extra):
    d = dict(type="cifar", lr=0.1, batch_size=64, epochs=10, no_models=10,
             number_of_total_participants=100, eta=0.1,
             aggregation_methods="mean", trigger_num=4, poison_label_swap=2,
             poisoning_per_batch=5, **CIFAR_PATTERNS)
    d.update(extra)
    return cfg.Params.from_dict(d)


def test_pattern_bank_rows_and_union():
    bank = triggers.build_pixel_pattern_bank(_params(), 32, 32)
    assert bank.shape == (5, 32, 32)
    for i in range(4):
        assert bank[i].sum() == 6
        for (r, c) in CIFAR_PATTERNS[f"{i}_poison_pattern"]:
            assert bank[i, r, c] == 1.0
    # last row = union of all sub-patterns (adversarial_index == -1)
    assert bank[4].sum() == 24
    np.testing.assert_array_equal(bank[4], np.clip(bank[:4].sum(0), 0, 1))


def test_stamp_sets_all_channels_to_one():
    bank = jnp.asarray(triggers.build_pixel_pattern_bank(_params(), 32, 32))
    img = jnp.full((2, 32, 32, 3), 0.25)
    out = np.asarray(triggers.stamp_pixel_pattern(img, bank, jnp.int32(2)))
    for (r, c) in CIFAR_PATTERNS["2_poison_pattern"]:
        np.testing.assert_array_equal(out[:, r, c, :], 1.0)
    # untouched elsewhere
    assert np.isclose(out[0, 10, 10, 0], 0.25)
    # adv_index -1 = combined pattern
    out = np.asarray(triggers.stamp_pixel_pattern(img, bank, jnp.int32(-1)))
    for i in range(4):
        for (r, c) in CIFAR_PATTERNS[f"{i}_poison_pattern"]:
            np.testing.assert_array_equal(out[:, r, c, :], 1.0)


def test_poison_batch_first_k_training_all_eval():
    p = _params()
    bank = jnp.asarray(triggers.build_pixel_pattern_bank(p, 32, 32))
    imgs = jnp.zeros((8, 32, 32, 3))
    labels = jnp.arange(8)
    out_i, out_l, sel = triggers.poison_batch(
        imgs, labels, bank, jnp.int32(0), 2, jnp.int32(5), poison_all=False)
    assert np.asarray(sel).sum() == 5
    np.testing.assert_array_equal(np.asarray(out_l)[:5], 2)
    np.testing.assert_array_equal(np.asarray(out_l)[5:], [5, 6, 7])
    assert np.asarray(out_i)[0, 0, 0, 0] == 1.0   # stamped
    assert np.asarray(out_i)[7, 0, 0, 0] == 0.0   # clean

    _, out_l, sel = triggers.poison_batch(
        imgs, labels, bank, jnp.int32(0), 2, jnp.int32(5), poison_all=True)
    assert np.asarray(sel).all()
    np.testing.assert_array_equal(np.asarray(out_l), 2)

    # benign lane: poisoning_per_batch=0 leaves the batch untouched
    out_i, out_l, sel = triggers.poison_batch(
        imgs, labels, bank, jnp.int32(0), 2, jnp.int32(0), poison_all=False)
    assert not np.asarray(sel).any()
    np.testing.assert_array_equal(np.asarray(out_l), np.arange(8))
    assert np.asarray(out_i).sum() == 0.0


def test_loan_feature_triggers():
    p = cfg.Params.from_dict(dict(
        type="loan", lr=0.001, batch_size=64, epochs=10, no_models=10,
        number_of_total_participants=50, eta=0.1, aggregation_methods="mean",
        trigger_num=2, poison_label_swap=7,
        **{"0_poison_trigger_names": ["f_a", "f_b"],
           "0_poison_trigger_values": [10, 80],
           "1_poison_trigger_names": ["f_c"],
           "1_poison_trigger_values": [20]}))
    feature_dict = {"f_a": 0, "f_b": 3, "f_c": 5}
    values, masks = triggers.build_feature_trigger_bank(p, feature_dict, 8)
    assert values.shape == (3, 8)
    assert values[0, 0] == 10 and values[0, 3] == 80 and masks[0, 5] == 0
    assert values[1, 5] == 20 and masks[1, 0] == 0
    # combined row
    assert values[2, 0] == 10 and values[2, 3] == 80 and values[2, 5] == 20

    rows = jnp.full((4, 8), -1.0)
    labels = jnp.zeros((4,), jnp.int32)
    out_r, out_l, sel = triggers.poison_batch_features(
        rows, labels, jnp.asarray(values), jnp.asarray(masks), jnp.int32(-1),
        7, jnp.int32(2), poison_all=False)
    out_r = np.asarray(out_r)
    assert out_r[0, 0] == 10 and out_r[0, 3] == 80 and out_r[0, 5] == 20
    assert out_r[0, 1] == -1.0            # non-trigger features untouched
    assert (out_r[2] == -1.0).all()       # beyond poisoning_per_batch
    np.testing.assert_array_equal(np.asarray(out_l), [7, 7, 0, 0])
