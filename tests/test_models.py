"""Model parity tests: parameter counts and output shapes match the reference
architectures (rebuilt independently in torch from their documented structure)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as tnn

from dba_mod_tpu import config as cfg
from dba_mod_tpu.models import build_model


def _params(type_name):
    return cfg.Params.from_dict({
        "type": type_name, "lr": 0.1, "batch_size": 64, "epochs": 1,
        "no_models": 2, "number_of_total_participants": 4, "eta": 0.1,
        "aggregation_methods": "mean",
    })


def n_params(tree):
    return sum(int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(tree))


# ---- torch twins (architecture per reference docs, built independently) ----

def torch_mnist():
    return tnn.Sequential(
        tnn.Conv2d(1, 20, 5, 1), tnn.ReLU(), tnn.MaxPool2d(2, 2),
        tnn.Conv2d(20, 50, 5, 1), tnn.ReLU(), tnn.MaxPool2d(2, 2),
        tnn.Flatten(), tnn.Linear(4 * 4 * 50, 500), tnn.ReLU(),
        tnn.Linear(500, 10), tnn.LogSoftmax(dim=1))


def torch_loan():
    return tnn.Sequential(
        tnn.Linear(91, 46), tnn.Dropout(0.5), tnn.ReLU(),
        tnn.Linear(46, 23), tnn.Dropout(0.5), tnn.ReLU(),
        tnn.Linear(23, 9))


class _TorchBasicBlock(tnn.Module):
    def __init__(self, in_planes, planes, stride):
        super().__init__()
        self.conv1 = tnn.Conv2d(in_planes, planes, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(planes)
        self.conv2 = tnn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(planes)
        self.short = tnn.Sequential()
        if stride != 1 or in_planes != planes:
            self.short = tnn.Sequential(
                tnn.Conv2d(in_planes, planes, 1, stride, bias=False),
                tnn.BatchNorm2d(planes))

    def forward(self, x):
        y = torch.relu(self.bn1(self.conv1(x)))
        y = self.bn2(self.conv2(y))
        return torch.relu(y + self.short(x))


def torch_cifar_resnet18():
    layers = [tnn.Conv2d(3, 32, 3, 1, 1, bias=False), tnn.BatchNorm2d(32)]
    in_planes = 32
    for stage, planes in enumerate([32, 64, 128, 256]):
        for i in range(2):
            stride = (2 if stage > 0 else 1) if i == 0 else 1
            layers.append(_TorchBasicBlock(in_planes, planes, stride))
            in_planes = planes
    layers += [tnn.AvgPool2d(4), tnn.Flatten(), tnn.Linear(256, 10)]
    return tnn.Sequential(*layers)


def torch_tiny_resnet18():
    layers = [tnn.Conv2d(3, 64, 7, 2, 3, bias=False), tnn.BatchNorm2d(64),
              tnn.MaxPool2d(3, 2, 1)]
    in_planes = 64
    for stage, planes in enumerate([64, 128, 256, 512]):
        for i in range(2):
            stride = (2 if stage > 0 else 1) if i == 0 else 1
            layers.append(_TorchBasicBlock(in_planes, planes, stride))
            in_planes = planes
    layers += [tnn.AdaptiveAvgPool2d(1), tnn.Flatten(), tnn.Linear(512, 200)]
    return tnn.Sequential(*layers)


CASES = [
    ("mnist", torch_mnist, (28, 28, 1), 10),
    ("cifar", torch_cifar_resnet18, (32, 32, 3), 10),
    ("tiny-imagenet-200", torch_tiny_resnet18, (64, 64, 3), 200),
    ("loan", torch_loan, (91,), 9),
]


@pytest.mark.parametrize("type_name,twin,in_shape,n_classes", CASES)
def test_param_count_matches_torch_twin(type_name, twin, in_shape, n_classes):
    mdef = build_model(_params(type_name))
    mv = mdef.init_vars(jax.random.key(0))
    tm = twin()
    torch_n = sum(p.numel() for p in tm.parameters())
    assert n_params(mv.params) == torch_n
    # BN running stats must exist iff the torch twin has buffers (minus
    # num_batches_tracked, which flax BN does not carry — documented deviation).
    torch_buf = sum(b.numel() for name, b in tm.named_buffers()
                    if "num_batches_tracked" not in name)
    assert n_params(mv.batch_stats) == torch_buf


@pytest.mark.parametrize("type_name,twin,in_shape,n_classes", CASES)
def test_forward_shapes_and_finiteness(type_name, twin, in_shape, n_classes):
    mdef = build_model(_params(type_name))
    mv = mdef.init_vars(jax.random.key(0))
    x = jnp.ones((4,) + in_shape, jnp.float32) * 0.5
    logits, _ = mdef.apply(mv, x, train=False)
    assert logits.shape == (4, n_classes)
    assert bool(jnp.all(jnp.isfinite(logits)))
    # train mode must run too (mutates BN stats / needs dropout rng)
    logits2, new_stats = mdef.apply(mv, x, train=True,
                                    dropout_rng=jax.random.key(1))
    assert logits2.shape == (4, n_classes)


@pytest.mark.parametrize("type_name,twin,in_shape,n_classes", CASES)
def test_similarity_param_is_final_dense_kernel(type_name, twin, in_shape, n_classes):
    """FoolsGold keys on the reference's params[-2] == final linear weight
    (helper.py:537); our similarity_path must land on a kernel with
    num_classes columns."""
    mdef = build_model(_params(type_name))
    mv = mdef.init_vars(jax.random.key(0))
    p = mdef.similarity_param(mv.params)
    assert p.ndim == 2 and p.shape[1] == n_classes


def test_mnist_output_is_log_softmax():
    mdef = build_model(_params("mnist"))
    mv = mdef.init_vars(jax.random.key(0))
    x = jnp.ones((2, 28, 28, 1))
    logits, _ = mdef.apply(mv, x, train=False)
    np.testing.assert_allclose(np.exp(np.asarray(logits)).sum(-1), 1.0, rtol=1e-5)
