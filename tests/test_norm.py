"""TorchBatchNorm semantics: train-mode output, UNBIASED running_var update
(the rule flax's stock BatchNorm gets wrong vs torch), eval-mode stats."""
import jax.numpy as jnp
import numpy as np
import torch

from dba_mod_tpu.models.norm import TorchBatchNorm


def _mk(rng):
    tbn = torch.nn.BatchNorm2d(6, eps=1e-5, momentum=0.1)
    with torch.no_grad():
        tbn.weight.copy_(torch.tensor(rng.randn(6).astype(np.float32)))
        tbn.bias.copy_(torch.tensor(rng.randn(6).astype(np.float32)))
        tbn.running_mean.copy_(torch.tensor(rng.randn(6).astype(np.float32)))
        tbn.running_var.copy_(
            torch.tensor((rng.rand(6) + 0.5).astype(np.float32)))
    variables = {
        "params": {"scale": jnp.asarray(tbn.weight.detach().numpy()),
                   "bias": jnp.asarray(tbn.bias.detach().numpy())},
        "batch_stats": {"mean": jnp.asarray(tbn.running_mean.numpy().copy()),
                        "var": jnp.asarray(tbn.running_var.numpy().copy())}}
    return tbn, variables


def test_train_output_and_unbiased_running_update_match_torch():
    rng = np.random.RandomState(0)
    x = (rng.randn(4, 5, 5, 6) * 2 + 0.5).astype(np.float32)
    tbn, variables = _mk(rng)
    y, upd = TorchBatchNorm(use_running_average=False).apply(
        variables, jnp.asarray(x), mutable=["batch_stats"])
    tbn.train()
    ty = tbn(torch.tensor(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(upd["batch_stats"]["mean"]),
                               tbn.running_mean.numpy(), atol=1e-6)
    # torch updates running_var with the n/(n-1) UNBIASED batch variance
    np.testing.assert_allclose(np.asarray(upd["batch_stats"]["var"]),
                               tbn.running_var.numpy(), rtol=1e-5)


def test_eval_mode_uses_running_stats():
    rng = np.random.RandomState(1)
    x = rng.randn(3, 2, 2, 6).astype(np.float32)
    tbn, variables = _mk(rng)
    y = TorchBatchNorm(use_running_average=True).apply(variables,
                                                      jnp.asarray(x))
    tbn.eval()
    ty = tbn(torch.tensor(x).permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(y), ty.detach().numpy(), atol=1e-5)
