"""Cross-framework A/B parity (VERDICT r3 ask 1): the same FL rounds through
a fresh torch implementation of the reference's client-loop semantics and
through dba_mod_tpu, from identical initial weights and identical batch
plans. Two kinds of claim:

1. SEMANTIC parity — from bit-identical state, one full round (benign lanes,
   poison lane with MultiStepLR + stamping + model-replacement scaling,
   FedAvg) agrees to float-roundoff (measured ≤9e-8 abs on O(0.4) updates).
2. STATISTICAL parity — over multiple rounds each framework integrates its
   own f32 rounding (reordered reductions cross ReLU boundaries and the
   trajectories separate chaotically), but main/backdoor accuracy stays
   within the ±1% north star (BASELINE.json; measured 0.0).

Measured gaps are committed in PARITY_AB.md (python -m benchmarks.parity_ab).
"""
import numpy as np

from benchmarks.parity_ab import CIFAR_AB, MNIST_AB, MNIST_AB_R1, run_ab


def _check_accuracy(rep):
    for r in rep["rounds"]:
        assert r["clean_acc_gap"] <= 1.0, r
        assert r["backdoor_acc_gap"] <= 1.0, r
        assert np.isfinite(r["jax_clean_acc"])


def test_mnist_identical_state_round_is_bit_tight():
    """Round 1 from identical weights: 2 poison clients (20 masked SGD steps,
    milestones firing at internal epochs 1 and 4, ×3 scaling) + 2 benign
    clients. Everything agrees to float roundoff — the composed client loop
    is semantically identical, not just per-op."""
    rep = run_ab(dict(MNIST_AB_R1), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc
    assert r["global_max_abs_diff"] <= 1e-6, r
    _check_accuracy(rep)


def test_mnist_ab_parity_four_rounds():
    """4 rounds covering benign-only, mixed, and both-adversaries rounds
    (poison epochs 2-4). Deltas stay inside a 2% drift envelope (pure f32
    accumulation chaos — see the identical-state test for the semantic
    claim); accuracies inside the ±1% north star."""
    rep = run_ab(dict(MNIST_AB), 4)
    for r in rep["rounds"]:
        for pc in r["per_client"]:
            # inherited drift compounds against the GLOBAL weight scale
            # round over round (measured ≤1.5e-2 by round 4, PARITY_AB.md);
            # this bound is a gross-divergence tripwire — the semantic
            # claim lives in the identical-state test, the statistical one
            # in the accuracy bar
            assert pc["max_abs_diff"] <= 0.08, (r["epoch"], pc)
        assert r["global_max_abs_diff"] <= 0.05, r
    _check_accuracy(rep)


def test_cifar_bn_ab_parity():
    """CIFAR ResNet-18 with BatchNorm: one poisoned + one mixed round;
    batch_stats (running mean + UNBIASED running var, models/norm.py) travel
    through delta/scaling/FedAvg exactly like torch.

    Unlike MNIST, deep conv nets cannot be bit-tight ACROSS frameworks:
    XLA and torch conv kernels differ at ~1e-6 (summation order), and any
    activation within that band of zero flips its ReLU gate, changing one
    unit's backward contribution outright. Measured: single fwd pass agrees
    to 2e-6, loss to 2e-7, BN stats to 6e-8, but per-step worst-leaf grads
    drift up to ~1e-2 relative with the drifting LAYER moving randomly
    across seeds — the signature of chaotic gate flips, not of a systematic
    semantic error (a real bug would pin to a fixed layer; disabling
    torch's oneDNN changes nothing). Hence: drift envelope on deltas, exact
    bar on accuracies."""
    rep = run_ab(dict(CIFAR_AB), 2)
    for r in rep["rounds"]:
        for pc in r["per_client"]:
            # measured ≤2.3e-2 (PARITY_AB.md); gross-divergence tripwire
            assert pc["max_abs_diff"] <= 0.1, (r["epoch"], pc)
        assert r["global_max_abs_diff"] <= 0.05, r
    _check_accuracy(rep)


def test_mnist_rfa_identical_state_round():
    """RFA geometric median cross-framework: the torch side implements the
    reference Weiszfeld flow (helper.py:295-373) independently; from
    identical state the aggregated global models must agree to float
    roundoff (distances computed in different precisions leave ~1e-6)."""
    from benchmarks.parity_ab import MNIST_AB_RFA
    rep = run_ab(dict(MNIST_AB_RFA), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc  # train is agg-independent
    assert r["global_max_abs_diff"] <= 2e-5, r
    _check_accuracy(rep)


def test_mnist_foolsgold_identical_state_rounds():
    """FoolsGold cross-framework: cosine-similarity reweighting over the
    [-2] parameter's accumulated gradient (sybil adversaries 0/1 share a
    trigger objective), id-keyed memory, pardoning + logit quirks, and the
    server SGD step — torch side independent (helper.py:259-293, :527-607).
    Round 1 from identical state is tight; round 2 chains the memory."""
    from benchmarks.parity_ab import MNIST_AB_FG
    rep = run_ab(dict(MNIST_AB_FG), 2)
    r1 = rep["rounds"][0]
    for pc in r1["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc  # train is agg-independent
    assert r1["global_max_abs_diff"] <= 1e-5, r1
    # round 2 exercises the id-keyed memory chaining: still tight (measured
    # 2.8e-6) — a memory-path regression would blow this long before the
    # coarse accuracy bar noticed
    assert rep["rounds"][1]["global_max_abs_diff"] <= 1e-4, rep["rounds"][1]
    _check_accuracy(rep)
