"""Cross-framework A/B parity (VERDICT r3 ask 1): the same FL rounds through
a fresh torch implementation of the reference's client-loop semantics and
through dba_mod_tpu, from identical initial weights and identical batch
plans. Two kinds of claim:

1. SEMANTIC parity — from bit-identical state, one full round (benign lanes,
   poison lane with MultiStepLR + stamping + model-replacement scaling,
   FedAvg) agrees to float-roundoff (measured ≤9e-8 abs on O(0.4) updates).
2. STATISTICAL parity — over multiple rounds each framework integrates its
   own f32 rounding (reordered reductions cross ReLU boundaries and the
   trajectories separate chaotically), but main/backdoor accuracy stays
   within the ±1% north star (BASELINE.json; measured 0.0).

Measured gaps are committed in PARITY_AB.md (python -m benchmarks.parity_ab).
"""
import numpy as np

from benchmarks.parity_ab import CIFAR_AB, MNIST_AB, MNIST_AB_R1, run_ab


def _check_accuracy(rep):
    for r in rep["rounds"]:
        assert r["clean_acc_gap"] <= 1.0, r
        assert r["backdoor_acc_gap"] <= 1.0, r
        assert np.isfinite(r["jax_clean_acc"])


def test_mnist_identical_state_round_is_bit_tight():
    """Round 1 from identical weights: 2 poison clients (20 masked SGD steps,
    milestones firing at internal epochs 1 and 4, ×3 scaling) + 2 benign
    clients. Everything agrees to float roundoff — the composed client loop
    is semantically identical, not just per-op."""
    rep = run_ab(dict(MNIST_AB_R1), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc
    assert r["global_max_abs_diff"] <= 1e-6, r
    _check_accuracy(rep)


def test_mnist_ab_parity_four_rounds():
    """4 rounds covering benign-only, mixed, and both-adversaries rounds
    (poison epochs 2-4). Deltas stay inside a 2% drift envelope (pure f32
    accumulation chaos — see the identical-state test for the semantic
    claim); accuracies inside the ±1% north star."""
    rep = run_ab(dict(MNIST_AB), 4)
    for r in rep["rounds"]:
        for pc in r["per_client"]:
            # inherited drift compounds against the GLOBAL weight scale
            # round over round (measured ≤1.5e-2 by round 4, PARITY_AB.md);
            # this bound is a gross-divergence tripwire — the semantic
            # claim lives in the identical-state test, the statistical one
            # in the accuracy bar
            assert pc["max_abs_diff"] <= 0.08, (r["epoch"], pc)
        assert r["global_max_abs_diff"] <= 0.05, r
    _check_accuracy(rep)


def test_cifar_bn_ab_parity():
    """CIFAR ResNet-18 with BatchNorm: one poisoned + one mixed round;
    batch_stats (running mean + UNBIASED running var, models/norm.py) travel
    through delta/scaling/FedAvg exactly like torch.

    Unlike MNIST, deep conv nets cannot be bit-tight ACROSS frameworks:
    XLA and torch conv kernels differ at ~1e-6 (summation order), and any
    activation within that band of zero flips its ReLU gate, changing one
    unit's backward contribution outright. Measured: single fwd pass agrees
    to 2e-6, loss to 2e-7, BN stats to 6e-8, but per-step worst-leaf grads
    drift up to ~1e-2 relative with the drifting LAYER moving randomly
    across seeds — the signature of chaotic gate flips, not of a systematic
    semantic error (a real bug would pin to a fixed layer; disabling
    torch's oneDNN changes nothing). Hence: drift envelope on deltas, exact
    bar on accuracies."""
    rep = run_ab(dict(CIFAR_AB), 2)
    for r in rep["rounds"]:
        for pc in r["per_client"]:
            # measured ≤2.3e-2 (PARITY_AB.md); gross-divergence tripwire
            assert pc["max_abs_diff"] <= 0.1, (r["epoch"], pc)
        assert r["global_max_abs_diff"] <= 0.05, r
    _check_accuracy(rep)


def test_mnist_rfa_identical_state_round():
    """RFA geometric median cross-framework: the torch side implements the
    reference Weiszfeld flow (helper.py:295-373) independently; from
    identical state the aggregated global models must agree to float
    roundoff (distances computed in different precisions leave ~1e-6)."""
    from benchmarks.parity_ab import MNIST_AB_RFA
    rep = run_ab(dict(MNIST_AB_RFA), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc  # train is agg-independent
    assert r["global_max_abs_diff"] <= 2e-5, r
    _check_accuracy(rep)


def test_mnist_dp_noise_identical_state_round():
    """FedAvg + differential-privacy noise cross-framework: the Gaussian
    noise tree is recomputed from the engine's own rng and added on the
    torch side too (a shared input, like the LOAN dropout masks), so what
    the round tests is the reference's DP composition — σ-scaled noise per
    state entry added ONCE after the eta/no_models sum, not eta-scaled
    (helper.py:186-191, :253-254). Bit-tight (measured 1.5e-8 global)."""
    from benchmarks.parity_ab import MNIST_AB_DP
    rep = run_ab(dict(MNIST_AB_DP), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc
    assert r["global_max_abs_diff"] <= 1e-6, r
    _check_accuracy(rep)


def test_mnist_blended_loss_and_baseline_variants():
    """Two attack-machinery branches no reference config exercises but the
    framework must carry: (a) alpha_loss=0.9 activates the anomaly-evading
    α·CE + (1-α)·‖w-w_anchor‖ loss (image_train.py:85-90) in the POISON
    branch only — its gradient (a unit vector scaled by the weight, with the
    torch.norm zero-subgradient on the first batch where w == w_anchor) must
    match torch; (b) baseline=True disables model-replacement scaling
    (image_train.py:148). Both identical-state rounds stay at float
    roundoff (measured 2.4e-6 / 3e-8)."""
    from benchmarks.parity_ab import MNIST_AB_ALPHA, MNIST_AB_BASELINE
    for cfg, tol in ((MNIST_AB_ALPHA, 2e-5), (MNIST_AB_BASELINE, 1e-6)):
        rep = run_ab(dict(cfg), 1)
        r = rep["rounds"][0]
        for pc in r["per_client"]:
            assert pc["max_abs_diff"] <= tol, (cfg["alpha_loss"], pc)
        assert r["global_max_abs_diff"] <= tol, r
        _check_accuracy(rep)


def test_mnist_interval2_identical_state_round():
    """aggr_epoch_interval=2 cross-framework: one round = two chained
    training segments (epochs 1 and 2) with the reference's per-segment
    machinery — the distance/scaling anchor re-snapshots to the client state
    at each segment start (image_train.py:52-54, :166-171), the poison
    optimizer + MultiStepLR are rebuilt per poison segment, and the benign
    optimizer (with its momentum) persists across segments. Adversary 0
    poisons segment 1 then trains BENIGN in segment 2; adversary 1 poisons
    both. From identical state the whole-round submitted deltas agree to
    float roundoff (measured ≤3.5e-6 over 2 chained segments)."""
    from benchmarks.parity_ab import MNIST_AB_I2
    rep = run_ab(dict(MNIST_AB_I2), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 5e-5, pc
    assert r["global_max_abs_diff"] <= 5e-5, r
    _check_accuracy(rep)


def test_tiny_imagenet_ab_parity():
    """Tiny-ImageNet ResNet-18 (imagenet stem + global pool, 200 classes,
    centralized combined trigger): identical-state round. Forward parity is
    tight (measured: eval fwd ≤1.1e-6, train fwd ≤5.5e-6, BN stats ≤7e-7 —
    a state-mapping bug would show here), but the deeper/wider net amplifies
    the same conv-summation ReLU-gate chaos as CIFAR through 2 epochs of SGD
    + ×2 scaling (measured delta envelope ~1.4e-1 on O(2.7) updates), so the
    delta bound is a gross-divergence tripwire and the semantic claim lives
    in the accuracy bar."""
    from benchmarks.parity_ab import TINY_AB
    rep = run_ab(dict(TINY_AB), 1)
    r = rep["rounds"][0]
    for pc in r["per_client"]:
        assert pc["max_abs_diff"] <= 0.4, pc
    assert r["global_max_abs_diff"] <= 0.15, r
    _check_accuracy(rep)


def test_loan_ab_parity_with_shared_dropout_masks():
    """LOAN cross-framework: the dropout masks the flax engine draws are
    extracted from its per-step RNG keys (probe forward + captured Dropout
    intermediates) and fed to the torch twin's mask-consuming Dropout, making
    the one framework-specific RNG stream a SHARED input like the batch
    plans. Covers feature-value triggers, the top-of-epoch MultiStepLR step
    (loan_train.py:90-92), model-replacement scaling, and the adaptive
    poison-LR decay (loan_train.py:71-75) — round 1 is identical-state, and
    rounds 2-3 must run with the decayed LR (backdoor acc 100 → lr/50) on
    BOTH sides to stay tight. The 91→46→23→9 MLP has a stable summation
    order, so unlike the conv models every round stays at float roundoff
    (measured ≤1.8e-7)."""
    from benchmarks.parity_ab import LOAN_AB, run_ab_loan
    rep = run_ab_loan(dict(LOAN_AB), 3)
    for r in rep["rounds"]:
        for pc in r["per_client"]:
            assert pc["max_abs_diff"] <= 5e-6, (r["epoch"], pc)
        assert r["global_max_abs_diff"] <= 5e-6, r
    _check_accuracy(rep)
    # the adaptive-LR rule must actually fire: round 1 plants the backdoor
    # (scaled ×3 update), so rounds 2+ probe at acc > 60 → lr/50
    lrs = [r["torch_poison_lr"] for r in rep["rounds"]]
    assert lrs[0] == LOAN_AB["poison_lr"], lrs
    assert any(lr is not None and lr < LOAN_AB["poison_lr"] / 10
               for lr in lrs[1:]), lrs


def test_cifar_foolsgold_bn_rounds():
    """FoolsGold on the BN ResNet — the defenses×BN cell: the server step
    aggregates NAMED PARAMETERS only, so BN running stats keep the global's
    values on both sides (helper.py:286-290 / fl/rounds.py:203-206), the
    [-2]-parameter similarity feature is the fc weight in both frameworks,
    and round 2 chains the id-keyed memory. Same conv-chaos envelope as the
    FedAvg CIFAR round; accuracies exact."""
    from benchmarks.parity_ab import CIFAR_AB_FG
    rep = run_ab(dict(CIFAR_AB_FG), 2)
    for r in rep["rounds"]:
        for pc in r["per_client"]:
            # measured ≤2.5e-2 (PARITY_AB.md); gross-divergence tripwire
            assert pc["max_abs_diff"] <= 0.1, (r["epoch"], pc)
        assert r["global_max_abs_diff"] <= 0.05, r
    _check_accuracy(rep)


def test_mnist_foolsgold_identical_state_rounds():
    """FoolsGold cross-framework: cosine-similarity reweighting over the
    [-2] parameter's accumulated gradient (sybil adversaries 0/1 share a
    trigger objective), id-keyed memory, pardoning + logit quirks, and the
    server SGD step — torch side independent (helper.py:259-293, :527-607).
    Round 1 from identical state is tight; round 2 chains the memory."""
    from benchmarks.parity_ab import MNIST_AB_FG
    rep = run_ab(dict(MNIST_AB_FG), 2)
    r1 = rep["rounds"][0]
    for pc in r1["per_client"]:
        assert pc["max_abs_diff"] <= 1e-6, pc  # train is agg-independent
    assert r1["global_max_abs_diff"] <= 1e-5, r1
    # round 2 exercises the id-keyed memory chaining: still tight (measured
    # 2.8e-6) — a memory-path regression would blow this long before the
    # coarse accuracy bar noticed
    assert rep["rounds"][1]["global_max_abs_diff"] <= 1e-4, rep["rounds"][1]
    _check_accuracy(rep)
