"""Real-file ingestion coverage: fabricated on-disk fixtures in the exact
formats the reference consumes (MNIST idx, CIFAR-10 pickle batches,
Tiny-ImageNet class folders, LOAN per-state CSVs — image_helper.py:173-250,
loan_helper.py:111-132) run through loader → partition → device data → one
FL round. Zero-egress: the files are fabricated, the formats are real."""
import gzip
import pickle
import struct

import numpy as np
import pytest

from dba_mod_tpu.config import Params
from dba_mod_tpu.data import datasets as ds
from dba_mod_tpu.fl.experiment import Experiment


def _round_cfg(**kw):
    base = dict(lr=0.1, eta=0.8, aggregation_methods="mean",
                internal_epochs=1, is_poison=False, momentum=0.9,
                decay=0.0005, sampling_dirichlet=False, local_eval=False,
                random_seed=1, synthetic_data=False, epochs=1)
    base.update(kw)
    return Params.from_dict(base)


# ---------------------------------------------------------------------- MNIST
def _write_idx_images(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", *arr.shape))
        f.write(arr.tobytes())


def _write_idx_labels(path, arr):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", arr.shape[0]))
        f.write(arr.tobytes())


def _fake_mnist(root, n_train=600, n_test=256):
    rng = np.random.RandomState(0)
    tr_x = rng.randint(0, 256, (n_train, 28, 28), dtype=np.uint8)
    tr_y = rng.randint(0, 10, n_train).astype(np.uint8)
    te_x = rng.randint(0, 256, (n_test, 28, 28), dtype=np.uint8)
    te_y = rng.randint(0, 10, n_test).astype(np.uint8)
    d = root / "MNIST" / "raw"
    d.mkdir(parents=True)
    _write_idx_images(d / "train-images-idx3-ubyte", tr_x)
    _write_idx_labels(d / "train-labels-idx1-ubyte", tr_y)
    # gzip variant exercises the .gz opener branch
    raw = (struct.pack(">I", 0x00000803) + struct.pack(">III", *te_x.shape)
           + te_x.tobytes())
    with gzip.open(d / "t10k-images-idx3-ubyte.gz", "wb") as f:
        f.write(raw)
    _write_idx_labels(d / "t10k-labels-idx1-ubyte", te_y)
    return tr_x, tr_y, te_x, te_y


def test_mnist_idx_loader_and_round(tmp_path):
    tr_x, tr_y, te_x, te_y = _fake_mnist(tmp_path)
    data = ds.load_mnist(str(tmp_path))
    assert data is not None and not data.synthetic
    np.testing.assert_array_equal(data.train_images[..., 0], tr_x)
    np.testing.assert_array_equal(data.train_labels, tr_y)
    np.testing.assert_array_equal(data.test_images[..., 0], te_x)  # .gz path
    assert data.num_classes == 10

    e = Experiment(_round_cfg(type="mnist", batch_size=16, no_models=4,
                              number_of_total_participants=10,
                              data_dir=str(tmp_path)), save_results=False)
    assert not e.image_data.synthetic
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])


# --------------------------------------------------------------------- CIFAR10
def _fake_cifar(root, n_train=144, n_test=64):
    rng = np.random.RandomState(1)
    d = root / "cifar-10-batches-py"
    d.mkdir(parents=True)
    per = n_train // 5
    all_imgs, all_labels = [], []
    for i in range(1, 6):
        n = per if i < 5 else n_train - 4 * per
        imgs = rng.randint(0, 256, (n, 3, 32, 32), dtype=np.uint8)
        labels = rng.randint(0, 10, n).astype(int).tolist()
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": imgs.reshape(n, -1), b"labels": labels}, f)
        all_imgs.append(imgs), all_labels.extend(labels)
    te = rng.randint(0, 256, (n_test, 3, 32, 32), dtype=np.uint8)
    te_l = rng.randint(0, 10, n_test).astype(int).tolist()
    with open(d / "test_batch", "wb") as f:
        pickle.dump({b"data": te.reshape(n_test, -1), b"labels": te_l}, f)
    return np.concatenate(all_imgs), np.array(all_labels), te, np.array(te_l)


def test_cifar_pickle_loader_and_round(tmp_path):
    tr, tr_y, te, te_y = _fake_cifar(tmp_path)
    data = ds.load_cifar10(str(tmp_path))
    assert data is not None
    # channel order: pickle rows are CHW planes → loader must emit NHWC
    np.testing.assert_array_equal(data.train_images,
                                  tr.transpose(0, 2, 3, 1))
    np.testing.assert_array_equal(data.train_labels, tr_y)
    np.testing.assert_array_equal(data.test_images,
                                  te.transpose(0, 2, 3, 1))

    e = Experiment(_round_cfg(type="cifar", batch_size=8, no_models=3,
                              number_of_total_participants=6,
                              data_dir=str(tmp_path)), save_results=False)
    assert not e.image_data.synthetic
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])


# -------------------------------------------------------------- Tiny-ImageNet
def test_tiny_folder_loader_and_round(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.RandomState(2)
    root = tmp_path / "tiny-imagenet-200"
    wnids = ["n01443537", "n01629819"]
    for split, per in (("train", 16), ("val", 8)):
        for w in wnids:
            d = root / split / w / ("images" if split == "train" else "")
            d.mkdir(parents=True, exist_ok=True)
            for j in range(per):
                img = rng.randint(0, 256, (64, 64, 3), dtype=np.uint8)
                PIL.fromarray(img).save(d / f"{w}_{j}.JPEG", quality=95)
    data = ds.load_tiny_imagenet(str(tmp_path))
    assert data is not None
    assert data.train_images.shape == (32, 64, 64, 3)
    assert data.test_images.shape == (16, 64, 64, 3)
    assert set(data.train_labels) == {0, 1} and data.num_classes == 200

    e = Experiment(_round_cfg(type="tiny-imagenet-200", batch_size=4,
                              no_models=2, number_of_total_participants=4,
                              lr=0.05, data_dir=str(tmp_path)),
                   save_results=False)
    assert not e.image_data.synthetic
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])


# ------------------------------------------------------------------------ LOAN
def test_loan_csv_loader_and_round(tmp_path):
    pd = pytest.importorskip("pandas")
    pytest.importorskip("sklearn")
    rng = np.random.RandomState(3)
    d = tmp_path / "loan"
    d.mkdir()
    # LoanNet's input layer is the reference's 91-feature schema
    feats = ds._LOAN_TRIGGER_FEATURES + [
        f"feat_{i}" for i in range(91 - len(ds._LOAN_TRIGGER_FEATURES))]
    rows = {}
    for state, n in (("AK", 40), ("AL", 52), ("AR", 36), ("AZ", 44)):
        df = pd.DataFrame(rng.randn(n, len(feats)).astype(np.float32),
                          columns=feats)
        df["loan_status"] = rng.randint(0, 9, n)
        df.to_csv(d / f"loan_{state}.csv", index=False)
        rows[state] = n
    data = ds.load_loan_csvs(str(tmp_path))
    assert data is not None
    assert data.state_names == ["AK", "AL", "AR", "AZ"]
    assert data.feature_names == feats
    for i, s in enumerate(data.state_names):
        # sklearn random_state=42 80/20 split parity (loan_helper.py:172)
        assert len(data.train_y[i]) == rows[s] - int(np.ceil(0.2 * rows[s]))
        assert len(data.test_y[i]) == int(np.ceil(0.2 * rows[s]))

    e = Experiment(_round_cfg(type="loan", batch_size=8, no_models=3,
                              number_of_total_participants=4, lr=0.01,
                              data_dir=str(tmp_path)), save_results=False)
    assert not e.loan_data.synthetic
    r = e.run_round(1)
    assert np.isfinite(r["global_acc"])
