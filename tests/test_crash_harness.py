"""End-to-end crash/preemption harness (the PR-4 acceptance tests): real
processes, real signals.

- `kill -9` a run mid-flight, relaunch with ``--resume auto``, and assert
  the completed metrics.jsonl trajectory is bit-identical (modulo
  wall-clock fields) to an uninterrupted run with the same seed — the
  integrity manifests guarantee the resume point is a *verified*
  checkpoint, and the full-state sidecar guarantees the replayed rounds
  land on the same trajectory.
- SIGTERM a run with ``graceful_shutdown: true`` and assert it exits
  within one round boundary with the distinct EXIT_INTERRUPTED code and a
  verified checkpoint on disk.

Subprocesses share the suite's persistent XLA compile cache via env vars,
so each launch pays import time but not a fresh compile."""
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest
import yaml

from dba_mod_tpu import checkpoint as ckpt
from dba_mod_tpu.utils.run_guard import EXIT_INTERRUPTED

REPO = Path(__file__).resolve().parent.parent

BASE_CFG = dict(
    type="mnist", lr=0.1, batch_size=16, epochs=8, no_models=4,
    number_of_total_participants=10, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=600, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False, random_seed=5,
    save_model=True, graceful_shutdown=True)

VOLATILE = {"time", "round_time", "dispatch_time", "finalize_time"}


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # share the suite's persistent compile cache (tests/conftest.py /
    # utils/compile_cache.py) so subprocess launches skip recompiles
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache_dba_tests")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    return env


def _write_cfg(tmp_path, name, **overrides):
    cfg = dict(BASE_CFG, run_dir=str(tmp_path / name), **overrides)
    path = tmp_path / f"{name}.yaml"
    path.write_text(yaml.dump(cfg))
    return path, cfg


def _launch(cfg_path, *extra):
    return subprocess.Popen(
        [sys.executable, "-m", "dba_mod_tpu.main", "train",
         "--params", str(cfg_path), *extra],
        cwd=REPO, env=_env(), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def _run_to_completion(cfg_path, *extra, timeout=600):
    proc = _launch(cfg_path, *extra)
    out, _ = proc.communicate(timeout=timeout)
    assert proc.returncode == 0, f"rc={proc.returncode}\n{out}"
    return out


def _rounds_recorded(run_dir: Path) -> int:
    rows = 0
    for f in run_dir.glob("mnist_*/round_result.csv"):
        rows = max(rows, len(f.read_text().strip().splitlines()) - 1)
    return rows


def _wait_for_rounds(proc, run_dir: Path, n: int, timeout=300) -> int:
    """Poll until >= n data rows are committed (or the process exits)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        done = _rounds_recorded(run_dir)
        if done >= n or proc.poll() is not None:
            return done
        time.sleep(0.2)
    return _rounds_recorded(run_dir)


def _metrics_rows(run_dir: Path):
    folders = sorted(run_dir.glob("mnist_*"))
    assert len(folders) == 1, f"expected one run folder, got {folders}"
    with open(folders[0] / "metrics.jsonl") as f:
        return [json.loads(line) for line in f if line.strip()]


def _strip(row):
    return {k: v for k, v in row.items() if k not in VOLATILE}


def test_kill9_then_auto_resume_bit_identical_trajectory(tmp_path):
    base_path, base_cfg = _write_cfg(tmp_path, "base")
    crash_path, crash_cfg = _write_cfg(tmp_path, "crash")

    # uninterrupted reference run (same seed, separate run_dir)
    _run_to_completion(base_path)
    ref_rows = _metrics_rows(Path(base_cfg["run_dir"]))
    assert [r["epoch"] for r in ref_rows] == list(range(1, 9))

    # crash run: SIGKILL once >= 2 rounds have committed
    proc = _launch(crash_path)
    run_dir = Path(crash_cfg["run_dir"])
    done = _wait_for_rounds(proc, run_dir, 2)
    if proc.poll() is not None:  # pragma: no cover — box far too fast
        pytest.skip("run finished before the kill landed")
    proc.kill()  # SIGKILL: no handlers, no cleanup, no atexit
    proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    assert done >= 2

    # auto-resume: same config + --resume auto must finish the job
    out = _run_to_completion(crash_path, "--resume", "auto")
    assert "final: epoch=8" in out

    rows = _metrics_rows(run_dir)  # one folder: the killed run's, reused
    assert [r["epoch"] for r in rows] == list(range(1, 9))  # no dup rounds
    for ref, got in zip(ref_rows, rows):
        assert _strip(ref) == _strip(got), f"epoch {ref['epoch']} diverged"

    # and the finished run's newest checkpoint is verified
    folder = next(iter(run_dir.glob("mnist_*")))
    ok, reason = ckpt.verify_checkpoint(folder / "model_last.pt.tar")
    assert ok, reason


def test_sigterm_graceful_stop_exits_75_with_verified_checkpoint(tmp_path):
    cfg_path, cfg = _write_cfg(tmp_path, "term", epochs=30)
    proc = _launch(cfg_path)
    run_dir = Path(cfg["run_dir"])
    done = _wait_for_rounds(proc, run_dir, 1)
    if proc.poll() is not None:  # pragma: no cover
        pytest.skip("run finished before the signal landed")
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert proc.returncode == EXIT_INTERRUPTED, f"rc\n{out}"
    assert "interrupted: graceful stop" in out
    # stopped within one round boundary of the signal: at most one more
    # round was recorded after the one that triggered the send
    rounds = _rounds_recorded(run_dir)
    assert done <= rounds <= done + 2
    assert rounds < 30  # it genuinely stopped early
    folder = next(iter(run_dir.glob("mnist_*")))
    ok, reason = ckpt.verify_checkpoint(folder / "model_last.pt.tar")
    assert ok, reason
    # recorder stream is intact and consistent with the checkpoint
    rows = _metrics_rows(run_dir)
    assert [r["epoch"] for r in rows] == list(range(1, rounds + 1))
    # the interrupted run is resumable to completion
    out = _run_to_completion(cfg_path, "--resume", "auto", "--epochs",
                             str(rounds + 2))
    rows = _metrics_rows(run_dir)
    assert [r["epoch"] for r in rows] == list(range(1, rounds + 3))
