# Developer/CI entry points. `make tier1` is the ROADMAP.md tier-1 verify
# command: the fast CPU suite (slow-marked rehearsals deselected) on the
# 8-virtual-device platform tests/conftest.py sets up.
SHELL := /bin/bash
.PHONY: tier1 test-slow trace crash-smoke elastic-smoke forensics-smoke \
  async-smoke chaos-soak chaos-smoke overlap-smoke

tier1:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

test-slow:
	env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m slow \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# One short telemetry-instrumented run (telemetry + profile_dir on): writes
# telemetry.jsonl + Chrome-trace trace.json into the run folder, the XLA
# profiler dump into runs/trace_profile, and prints the phase summary.
trace:
	env JAX_PLATFORMS=cpu python -m dba_mod_tpu.main \
	  --params configs/trace_params.yaml
	@echo "telemetry files:"; ls -1 runs/mnist_*/telemetry.jsonl \
	  runs/mnist_*/trace.json 2>/dev/null | tail -2

# Preemption drill (README "Crash & preemption tolerance"): tiny run,
# SIGTERM it mid-flight (expects the graceful-stop exit code 75 + a
# verified checkpoint), `--resume auto`, assert the run completes in the
# same folder with no duplicate rounds.
crash-smoke:
	bash scripts/crash_smoke.sh

# Elastic multi-host drill (README "Elastic multi-host"): 2-process
# jax.distributed run on virtual CPU devices, SIGKILL one worker mid-run
# (expects the survivor to exit 77 = EXIT_PEER_LOST with a verified
# checkpoint, bounded by watchdog_hard_s), relaunch the survivors SHRUNK
# (1 process) with --resume auto, assert the run completes in the same
# folder with no duplicate rounds.
elastic-smoke:
	bash scripts/elastic_smoke.sh

# Buffered-async drill (README "Asynchronous federation"): tiny `mode:
# async` run (merge every 2 arrivals, straggler tail, staleness weighting),
# SIGTERM it mid-stream (expects the graceful-stop exit code 75 + the
# streaming buffer checkpointed in the aux sidecar), `--resume auto`,
# assert aggregation steps 1..N land exactly once in the same folder.
async-smoke:
	bash scripts/async_smoke.sh

# Self-healing soak (README "Self-healing federation"): sync + async lanes
# under the full compound fault schedule (dropout / corruption / blowup /
# stale replay / host loss) while the harness SIGTERMs/SIGKILLs the
# process at seeded instants and flips bytes in committed checkpoints.
# Asserts: one run folder per lane, steps 1..N exactly once across every
# resume, finite metrics, verified final checkpoint, exit codes inside the
# {0, 75, 76, 77} contract. CHAOS_SEED / CHAOS_KILLS / CHAOS_LANES
# override the schedule.
chaos-soak:
	bash scripts/chaos_soak.sh

# CI-sized slice of the soak: the async lane only, one seeded kill cycle.
chaos-smoke:
	CHAOS_KILLS=1 CHAOS_LANES=async bash scripts/chaos_soak.sh

# Round-pipelining drill (README "Round pipelining"): four tiny CLI runs —
# {sync, async} x {overlap_eval off, on} — then assert the canonical run
# outputs (metrics.jsonl + every recorder CSV, wall-clock columns
# stripped) are byte-identical off vs on for both engines.
overlap-smoke:
	bash scripts/overlap_smoke.sh

# Defense-forensics drill (README "Defense forensics"): tiny FoolsGold
# sybil run with `forensics: true`, assert forensics.jsonl +
# client_forensics.csv stream into the run folder with the pinned schema,
# and render + sanity-check the standalone HTML round-audit via the
# `report` subcommand.
forensics-smoke:
	bash scripts/forensics_smoke.sh
