"""Benchmark: CIFAR-10 FL rounds/sec (100 clients, 10/round, narrow
ResNet-18) on the available accelerator — the north-star workload
(BASELINE.json: CIFAR-10 DBA on v5e; its steady-state rounds are clean, since
single-shot poisoning touches 4 of 300 rounds).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

vs_baseline is measured against a reference-style sequential torch loop doing
identical work on this host's CPU (benchmarks/torch_reference.py) — the only
runnable form of the reference in this zero-egress, GPU-less image; the
reference repo publishes no numbers of its own (BASELINE.md). The baseline
measurement is cached in BENCH_BASELINE_LOCAL.json after the first run.

Usage: python bench.py [--rounds N] [--skip-baseline]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).parent
CACHE = REPO / "BENCH_BASELINE_LOCAL.json"

BENCH_CONFIG = dict(
    type="cifar", lr=0.1, batch_size=64, epochs=10, no_models=10,
    number_of_total_participants=100, eta=0.1, aggregation_methods="mean",
    internal_epochs=2, momentum=0.9, decay=0.0005, is_poison=False,
    synthetic_data=True,  # zero-egress image: CIFAR-shaped synthetic data
    sampling_dirichlet=True, dirichlet_alpha=0.5, local_eval=True,
    random_seed=1,
    # TPU-native settings: bf16 MXU compute (f32 params/aggregation —
    # backdoor efficacy validated in tests/test_fl_integration.py), fat eval
    # batches (eval sums are batch-size invariant)
    compute_dtype="bfloat16", eval_batch_size=512)


def measure_ours(timed_rounds: int) -> float:
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment

    exp = Experiment(Params.from_dict(BENCH_CONFIG), save_results=False)
    exp.run_round(1)  # warmup: compiles round + eval programs
    t0 = time.time()
    for i in range(2, 2 + timed_rounds):
        exp.run_round(i)
    return (time.time() - t0) / timed_rounds


def baseline_seconds_per_round(skip: bool) -> float | None:
    if CACHE.exists():
        return json.loads(CACHE.read_text())["seconds_per_round"]
    if skip:
        return None
    from benchmarks.torch_reference import measure_torch_reference_round
    secs = measure_torch_reference_round(
        num_clients=BENCH_CONFIG["no_models"], samples_per_client=500,
        batch_size=BENCH_CONFIG["batch_size"],
        internal_epochs=BENCH_CONFIG["internal_epochs"])
    CACHE.write_text(json.dumps({
        "seconds_per_round": secs,
        "what": "reference-style sequential torch loop, same work, this "
                "host's CPU (see benchmarks/torch_reference.py)"}, indent=1))
    return secs


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--skip-baseline", action="store_true")
    args = ap.parse_args()

    ours = measure_ours(args.rounds)
    base = baseline_seconds_per_round(args.skip_baseline)
    rounds_per_sec = 1.0 / ours
    vs = (base / ours) if base else 1.0
    print(json.dumps({"metric": "cifar10_fl_rounds_per_sec",
                      "value": round(rounds_per_sec, 4),
                      "unit": "rounds/sec",
                      "vs_baseline": round(vs, 2)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
