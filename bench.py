"""Benchmark: CIFAR-10 FL rounds/sec (100 clients, 10/round, narrow
ResNet-18) on the available accelerator — the north-star workload
(BASELINE.json: CIFAR-10 DBA on v5e; its steady-state rounds are clean, since
single-shot poisoning touches 4 of 300 rounds).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "phases",
"mfu", ...}. `value` is end-to-end rounds/sec (host prep + device compute +
the round's blocking transfer, recording on).

vs_baseline is measured against a reference-style sequential torch loop doing
identical work on this host's CPU (benchmarks/torch_reference.py) — the only
runnable form of the reference in this zero-egress, GPU-less image; the
reference repo publishes no numbers of its own (BASELINE.md). The baseline
measurement is cached in BENCH_BASELINE_LOCAL.json after the first run.

`phases` reports per-phase device seconds measured by cumulative dispatch +
scalar-sync (block_until_ready does not block through the axon tunnel; the
scalar fetch is the only honest sync — its ~0.1 s latency is subtracted).
`mfu` divides useful-work FLOPs (XLA cost analysis of this model on the CPU
backend, cached in BENCH_FLOPS.json; padding-step compute excluded) by the
phase time × the chip's bf16 peak.

Usage: python bench.py [--rounds N] [--skip-baseline] [--no-phases]
Opt-in lanes (each appends a sub-object to the JSON, never breaks the
headline): --multihost, --poison-cost, --width, --forensics-cost, --async.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).parent
CACHE = REPO / "BENCH_BASELINE_LOCAL.json"
FLOPS_CACHE = REPO / "BENCH_FLOPS.json"

PEAK_BF16 = 197e12  # TPU v5e peak bf16 FLOP/s (the bench chip)

BENCH_CONFIG = dict(
    type="cifar", lr=0.1, batch_size=64, epochs=10, no_models=10,
    number_of_total_participants=100, eta=0.1, aggregation_methods="mean",
    internal_epochs=2, momentum=0.9, decay=0.0005, is_poison=False,
    synthetic_data=True,  # zero-egress image: CIFAR-shaped synthetic data
    sampling_dirichlet=True, dirichlet_alpha=0.5, local_eval=True,
    random_seed=1,
    # TPU-native settings (all semantics-preserving; see config.py):
    # bf16 MXU compute (f32 params/aggregation — backdoor efficacy validated
    # in tests/test_fl_integration.py); fat eval batches (eval sums are
    # batch-size invariant); per-round step buckets (padding steps are
    # fully-masked no-ops); round pipelining (recording lags one round);
    # overlap_eval splits the fused round so round N's eval batteries +
    # host sync run behind round N+1's train/aggregate dispatch — recorded
    # metrics stay bit-identical (tests/test_overlap.py), only the
    # schedule changes. The headline measures the knob ON; the JSON's
    # "overlap" sub-object carries the off/on A/B on the same workload.
    compute_dtype="bfloat16", eval_batch_size=2048,
    dynamic_steps=True, pipeline_rounds=True, overlap_eval=True)


# --poison-cost lane (VERDICT Weak #5): the SAME headline workload with the
# distributed backdoor on — 4 scheduled adversaries (the cifar_params.yaml
# stripe geometry), poisoning every timed round, scale_weights 1 so the
# model trajectory stays numerically tame — vs the benign headline. The
# delta isolates what the attack path costs end-to-end: the poison-batch
# injection inside the train step plus the 4-part local eval battery
# (clean / poison-pre / poison-post / per-agent trigger) vs benign's
# clean-only battery.
POISON_COST_CONFIG = dict(
    BENCH_CONFIG, is_poison=True,
    internal_poison_epochs=BENCH_CONFIG["internal_epochs"],
    poisoning_per_batch=5, poison_label_swap=2, poison_lr=0.05,
    scale_weights_poison=1.0, alpha_loss=1.0, trigger_num=4,
    is_random_adversary=False, adversary_list=[0, 1, 2, 3],
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3], [0, 4], [0, 5]],
       "1_poison_pattern": [[0, 9], [0, 10], [0, 11], [0, 12], [0, 13],
                            [0, 14]],
       "2_poison_pattern": [[4, 0], [4, 1], [4, 2], [4, 3], [4, 4], [4, 5]],
       "3_poison_pattern": [[4, 9], [4, 10], [4, 11], [4, 12], [4, 13],
                            [4, 14]]},
    **{f"{i}_poison_epochs": list(range(1, 400)) for i in range(4)})


# Second lane (VERDICT r4 ask 7): the Tiny-ImageNet workload — imagenet stem
# (7×7/s2 + maxpool), standard 64-base widths, global pool, 200 classes
# (reference models/resnet_tinyimagenet.py:40-238) — different conv/layout
# behavior than the narrow-CIFAR headline. Synthetic tiny, 10 clients.
# 10k images: the axon tunnel's remote-compile RPC rejects payloads whose
# embedded device-data constants exceed ~200 MB (HTTP 413); 10k 64×64
# images (123 MB) fits, 20k does not. Workload note in the JSON.
TINY_CONFIG = dict(
    BENCH_CONFIG, type="tiny-imagenet-200",
    synthetic_train_size=10000, synthetic_test_size=2000)


# --async lane (README "Asynchronous federation"): the headline workload
# through the buffered-async engine (fl/async_rounds.py) — 10-client
# cohorts, merge every 5 arrivals, polynomial staleness weighting, a
# jittered arrival process with a straggler tail. The FedBuff-native
# throughput unit is sustained client updates absorbed per second
# (merges/sec × buffer_k); pipeline_rounds is a lockstep-loop knob and is
# ignored by the streaming driver.
ASYNC_CONFIG = dict(
    BENCH_CONFIG, mode="async", buffer_k=5,
    staleness_weighting="polynomial", staleness_alpha=0.5,
    arrival_rate=2.0, arrival_jitter=0.5, straggler_tail=0.1,
    straggler_factor=5.0)


# --multihost lane (ROADMAP item 5): the 2-process DCN configuration the
# multi-host tests prove (tests/test_multihost.py) — 2 × 4 virtual CPU
# devices = one 8-device clients mesh spanning a process boundary — timed
# end-to-end so the scale-out path has a perf trajectory in the BENCH_*
# JSON, not just a correctness bit. sync_latency is the host-visible
# scalar-fetch round trip through the cross-process runtime, the quantity
# BENCH_r05 tracks single-host.
MULTIHOST_CONFIG = dict(
    type="mnist", lr=0.1, batch_size=32, epochs=12, no_models=8,
    number_of_total_participants=8, eta=0.8, aggregation_methods="mean",
    internal_epochs=1, is_poison=False, synthetic_data=True,
    synthetic_train_size=512, synthetic_test_size=256, momentum=0.9,
    decay=0.0005, sampling_dirichlet=False, local_eval=False,
    random_seed=1, num_devices=-1)


def _multihost_worker(process_id: int, coordinator: str,
                      timed_rounds: int) -> int:
    """One process of the 2-process bench world. Env must be set before
    jax imports, hence the subprocess re-entry."""
    import os
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["JAX_COORDINATOR_ADDRESS"] = coordinator
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(process_id)
    import jax
    from dba_mod_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment
    import jax.numpy as jnp

    exp = Experiment(Params.from_dict(MULTIHOST_CONFIG),
                     save_results=False)
    assert jax.process_count() == 2
    exp.run_round(1)  # compile
    lat = min(timeit(lambda: jax.device_get(jnp.float32(1.0) + 1))
              for _ in range(3))
    t0 = time.perf_counter()
    pending = None
    for i in range(2, 2 + timed_rounds):
        fl = exp.dispatch_round(i)
        if pending is not None:
            exp.finalize_round(pending)
        pending = fl
    exp.finalize_round(pending)
    spr = (time.perf_counter() - t0) / timed_rounds
    if process_id == 0:
        print(json.dumps({
            "metric": "multihost_2proc_rounds_per_sec",
            "value": round(1.0 / spr, 4), "unit": "rounds/sec",
            "sync_latency_s": round(lat, 4),
            "world": {"processes": 2, "devices": int(jax.device_count())},
            "workload": "synthetic mnist, 8 clients/round, 2-process DCN "
                        "over 2x4 virtual CPU devices "
                        "(tests/test_multihost.py configuration)"}),
            flush=True)
    return 0


def measure_multihost(timed_rounds: int) -> dict:
    """Spawn the 2-process world and collect process 0's JSON line."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    import os
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID",
                        "JAX_COORDINATOR_ADDRESS")}
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["TF_CPP_MIN_LOG_LEVEL"] = "3"
    procs = [subprocess.Popen(
        [sys.executable, str(REPO / "bench.py"), "--multihost-worker",
         str(pid), coord, str(timed_rounds)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=str(REPO)) for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=1800)[0])
    except subprocess.TimeoutExpired:
        # one wedged worker (startup race, gloo hang) must not take the
        # whole bench down or orphan its sibling — same contract as the
        # tiny lane: the headline number always prints
        for p in procs:
            if p.poll() is None:
                p.kill()
        return {"error": "multihost worker timed out after 1800s; "
                         "workers killed"}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            return {"error": f"multihost worker {pid} rc={p.returncode}: "
                             f"{out[-2000:]}"}
    for line in reversed(outs[0].strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return {"error": f"no JSON line from worker 0: {outs[0][-2000:]}"}


def _make_experiment(config=None):
    import jax
    # persistent compile cache: the 5 step-bucket shapes + eval programs
    # compile once per machine, not once per bench run
    from dba_mod_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache("/tmp/jax_cache_dba_bench")
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment
    exp = Experiment(Params.from_dict(config or BENCH_CONFIG),
                     save_results=False)
    exp.warm_step_buckets()   # compile every dynamic-steps shape up front
    exp.run_round(1)          # compile eval/aggregate programs
    exp.telemetry.mark_warm()  # further XLA compiles are regressions
    return exp


def _make_async_experiment(config=None):
    """The --async lane's experiment: same toolchain setup as
    _make_experiment, but warmed by the streaming driver itself (the
    lockstep run_round warm would consume the RNG streams the first wave
    dispatch expects)."""
    from dba_mod_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache("/tmp/jax_cache_dba_bench")
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment
    return Experiment(Params.from_dict(config or ASYNC_CONFIG),
                      save_results=False)


def measure_ours(exp, timed_rounds: int) -> float:
    """End-to-end seconds/round, pipelined: round N+1 dispatches before round
    N's blocking fetch, hiding the ~0.1 s tunnel round-trip."""
    t0 = time.time()
    pending = None
    for i in range(2, 2 + timed_rounds):
        fl = exp.dispatch_round(i)
        if pending is not None:
            exp.finalize_round(pending)
        pending = fl
    exp.finalize_round(pending)
    return (time.time() - t0) / timed_rounds


def model_flops() -> dict:
    """Per-sample FLOPs of the bench model (fwd eval; fwd+bwd+update train
    step), from XLA cost analysis on the CPU backend — the TPU-tunnel backend
    reports wrong totals. Cached: the numbers only change with the model."""
    if FLOPS_CACHE.exists():
        return json.loads(FLOPS_CACHE.read_text())
    code = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %r)
from bench import BENCH_CONFIG
from dba_mod_tpu.config import Params
from dba_mod_tpu.models import build_model
p = Params.from_dict(BENCH_CONFIG)
md = build_model(p)
v = md.init_vars(jax.random.key(0))
B = int(p["batch_size"])
x = jnp.zeros((B, 32, 32, 3), jnp.bfloat16)
y = jnp.zeros((B,), jnp.int32)
def fwd(v, x):
    logits, _ = md.apply(v, x, train=False)
    return logits
def train_step(v, x, y):
    def loss(vv):
        logits, bn = md.apply(vv, x, train=True,
                              dropout_rng=jax.random.key(0))
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], -1)), bn
    (l, bn), g = jax.value_and_grad(loss, has_aux=True)(v)
    newp = jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, v, g)
    return newp
def flops_of(f, *args):
    ca = jax.jit(f).lower(*args).compile().cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca["flops"])
print(json.dumps({
    "fwd_per_sample": flops_of(fwd, v, x) / B,
    "train_step_per_sample": flops_of(train_step, v, x, y) / B}))
""" % str(REPO)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=600)
    data = json.loads(out.stdout.strip().splitlines()[-1])
    FLOPS_CACHE.write_text(json.dumps(data, indent=1))
    return data


def measure_phases(exp) -> dict:
    """Per-phase device seconds via cumulative dispatch + scalar sync."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    tasks_seq, idx_seq, mask_seq, ns, lane = exp.build_static_round_inputs(
        999)
    rng_t, rng_a = jax.random.split(jax.random.key(3))
    tasks_last = jax.tree_util.tree_map(lambda l: l[-1], tasks_seq)
    real_samples = int(np.asarray(ns).sum()) * exp.epochs_max

    def upto(k):
        train = exp.engine.train_fn(exp.global_vars, tasks_seq, idx_seq,
                                    mask_seq, lane, rng_t)
        if k == 0:
            return train.delta_norms[0]
        from dba_mod_tpu.fl.rounds import nbt_client_deltas
        res = exp.engine.aggregate_fn(
            exp.global_vars, exp.fg_state, train.deltas, train.fg_grads,
            train.fg_feature, tasks_last.participant_id, ns, rng_a,
            nbt_client_deltas(mask_seq, tasks_seq.scale))
        if k == 1:
            return res.wv[0]
        prev = jax.tree_util.tree_map(jnp.zeros_like, train.deltas)
        lev = exp.engine.local_evals_fn(exp.global_vars, train.deltas,
                                        tasks_last, prev)
        if k == 2:
            return lev.clean.acc[0]
        gev = exp.engine.global_evals_fn(res.new_vars)
        return gev.clean.acc

    lat = min(timeit(lambda: jax.device_get(jnp.float32(1.0) + 1))
              for _ in range(3))
    cums = []
    for k in range(4):
        jax.device_get(upto(k))  # warm any fresh compile
        cums.append(min(timeit(lambda: jax.device_get(upto(k)))
                        for _ in range(2)) - lat)
    names = ["train", "aggregate", "local_eval", "global_eval"]
    phases = {"sync_latency_s": round(lat, 4)}
    prev = 0.0
    for k, n in enumerate(names):
        phases[n + "_s"] = round(max(cums[k] - prev, 0.0), 4)
        prev = cums[k]
    phases["_real_train_samples"] = real_samples
    return phases


def timeit(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def host_peak_rss_bytes():
    """Process peak resident-set high-water (bytes) — the memory ceiling
    that matters on CPU backends, where device_peak_bytes is None. Like the
    allocator stat it is monotone over the process lifetime: in the width
    lane each point's value subsumes every smaller config measured before
    it, so the last (widest) point is the series' ceiling."""
    import resource
    return int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss) * 1024


def device_peak_bytes():
    """Device-memory high-water (bytes) from the runtime's allocator stats.
    None where the backend publishes none (CPU). NOTE: peak_bytes_in_use is
    monotone over the PROCESS lifetime — in the width lane below, each
    point's peak subsumes the smaller configs measured before it, so read
    the series as a running high-water, exact only at the widest point."""
    import jax
    stats = jax.local_devices()[0].memory_stats()
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak else None


def baseline_seconds_per_round(skip: bool) -> float | None:
    if CACHE.exists():
        return json.loads(CACHE.read_text())["seconds_per_round"]
    if skip:
        return None
    from benchmarks.torch_reference import measure_torch_reference_round
    secs = measure_torch_reference_round(
        num_clients=BENCH_CONFIG["no_models"], samples_per_client=500,
        batch_size=BENCH_CONFIG["batch_size"],
        internal_epochs=BENCH_CONFIG["internal_epochs"])
    CACHE.write_text(json.dumps({
        "seconds_per_round": secs,
        "what": "reference-style sequential torch loop, same work, this "
                "host's CPU (see benchmarks/torch_reference.py)"}, indent=1))
    return secs


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--multihost-worker":
        # subprocess re-entry: env vars must precede jax import
        return _multihost_worker(int(sys.argv[2]), sys.argv[3],
                                 int(sys.argv[4]))
    ap = argparse.ArgumentParser()
    # 12 timed rounds: the tunnel's ~0.07-0.16 s sync-latency jitter puts
    # ±3% run-to-run noise on a 5-round measurement; 12 cuts it ~35%
    # (1/√n scaling)
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--skip-baseline", action="store_true")
    ap.add_argument("--no-phases", action="store_true")
    ap.add_argument("--no-tiny", action="store_true",
                    help="skip the Tiny-ImageNet second lane")
    ap.add_argument("--tiny-rounds", type=int, default=4)
    ap.add_argument("--multihost", action="store_true",
                    help="add the 2-process DCN lane (2x4 virtual CPU "
                         "devices, tests/test_multihost.py configuration): "
                         "rounds/sec + sync_latency into the JSON under "
                         "'multihost_lane'")
    ap.add_argument("--multihost-rounds", type=int, default=8)
    ap.add_argument("--poison-cost", action="store_true",
                    help="add the poison-cost lane: the headline workload "
                         "with the 4-adversary DBA + full 4-part local eval "
                         "battery on, and the rounds/sec delta vs the "
                         "benign headline (VERDICT Weak #5)")
    ap.add_argument("--poison-rounds", type=int, default=8)
    ap.add_argument("--width", action="store_true",
                    help="add the width lane: clients*rounds/sec at "
                         "C = 10/50/100 clients/round with the device "
                         "memory high-water per point (ROADMAP item 1's "
                         "measurement half)")
    ap.add_argument("--width-rounds", type=int, default=4)
    ap.add_argument("--async", dest="async_lane", action="store_true",
                    help="add the buffered-async lane: the headline "
                         "workload through the streaming engine "
                         "(fl/async_rounds.py) — sustained updates/sec "
                         "and merges/sec under the arrival process, under "
                         "'async_lane'")
    ap.add_argument("--async-rounds", type=int, default=12,
                    help="timed aggregation steps for the --async lane")
    ap.add_argument("--forensics-cost", action="store_true",
                    help="add the forensics-cost lane: the headline "
                         "workload with `forensics: true` and the overhead "
                         "%% vs the forensics-off headline (the <=5%% "
                         "acceptance gate)")
    ap.add_argument("--forensics-rounds", type=int, default=8)
    ap.add_argument("--telemetry", metavar="DIR", default="",
                    help="enable the telemetry layer (utils/telemetry.py): "
                         "writes telemetry.jsonl + Chrome-trace trace.json "
                         "to DIR and prints the phase summary to stderr. "
                         "NOTE: telemetry adds per-phase device syncs, so "
                         "the headline rounds/sec is NOT comparable to an "
                         "uninstrumented run")
    args = ap.parse_args()

    config = dict(BENCH_CONFIG)
    if args.telemetry:
        config.update(telemetry=True, telemetry_dir=args.telemetry)
    exp = _make_experiment(config)
    # the warmup round ran through the overlap path too — zero the hidden-
    # time clocks so the overlap sub-object reports the timed window only
    exp._overlap_rounds = 0
    exp._overlap_hidden_s = exp._overlap_wait_s = 0.0
    ours = measure_ours(exp, args.rounds)
    # snapshot now: the phases probe below intentionally compiles the
    # static-plan-shape programs post-warmup, which would pollute the
    # steady-state regression count reported in out["telemetry"]
    steady_recompiles = exp.telemetry.counter(
        "xla/recompiles_after_warmup").value
    base = baseline_seconds_per_round(args.skip_baseline)
    rounds_per_sec = 1.0 / ours
    vs = (base / ours) if base else 1.0

    out = {"metric": "cifar10_fl_rounds_per_sec",
           "value": round(rounds_per_sec, 4),
           "unit": "rounds/sec",
           "vs_baseline": round(vs, 2),
           "baseline_note": (
               "vs reference-style sequential torch loop on this host's "
               "single CPU core (benchmarks/torch_reference.py) — the only "
               "runnable reference form in this zero-egress GPU-less image; "
               "NOT the north-star PyTorch-GPU denominator" if base else
               "baseline skipped (--skip-baseline, no cache); vs_baseline "
               "is a 1.0 placeholder, not a measurement")}

    # overlap A/B (README "Round pipelining"): the identical workload with
    # overlap_eval OFF — the knob's contract is bit-identical recorded
    # metrics, so the whole delta is schedule. hidden_eval_s is the
    # cumulative eval+fetch wall time that ran behind the next round's
    # dispatch; eval_wait_s is what finalize still had to block on.
    try:
        oexp = _make_experiment(dict(config, overlap_eval=False))
        off_spr = measure_ours(oexp, args.rounds)
        del oexp
        hidden = float(exp._overlap_hidden_s)
        wait = float(exp._overlap_wait_s)
        out["overlap"] = {
            "rounds_per_sec_off": round(1.0 / off_spr, 4),
            "rounds_per_sec_on": round(rounds_per_sec, 4),
            "speedup": round(off_spr / ours, 3),
            "hidden_eval_s": round(hidden, 4),
            "eval_wait_s": round(wait, 4),
            "hidden_fraction": (round(hidden / (hidden + wait), 4)
                                if hidden + wait > 0 else None),
            "dispatch_ahead_depth": 1,
            "recompiles_after_warmup": steady_recompiles,
            "note": "off/on the same process+cache; hidden_fraction = "
                    "hidden / (hidden + still-blocking finalize) over the "
                    "timed window"}
    except Exception as e:  # noqa: BLE001 — lanes never break
        out["overlap_error"] = str(e)  # the headline number

    if not args.no_phases:
        try:
            fl = model_flops()
            ph = measure_phases(exp)
            real = ph.pop("_real_train_samples")
            n_test = exp.device_data.num_test
            C = int(exp.params["no_models"])
            train_fl = real * fl["train_step_per_sample"]
            eval_fl = (C * n_test + n_test) * fl["fwd_per_sample"]
            out["phases"] = ph
            denom = max(ph["train_s"], 1e-9)
            out["mfu"] = {
                "train": round(train_fl / denom / PEAK_BF16, 4),
                "eval": round(eval_fl / max(
                    ph["local_eval_s"] + ph["global_eval_s"], 1e-9)
                    / PEAK_BF16, 4),
                "peak_bf16_flops": PEAK_BF16,
                "note": "useful-work FLOPs (padding excluded) / phase "
                        "device-time; phase times at the STATIC plan shape "
                        "(worst case), headline rounds/sec uses dynamic "
                        "buckets"}
        except Exception as e:  # noqa: BLE001 — diagnostics must not
            out["phases_error"] = str(e)  # break the headline number

    if args.telemetry:
        # final trace/summary flush for the headline lane (the tiny lane
        # below builds its own un-instrumented Experiment); summary goes to
        # stderr — stdout stays the single JSON line
        exp.telemetry.record_memory()
        exp.telemetry.close()
        print(exp.telemetry.summary_table(), file=sys.stderr)
        out["telemetry"] = {
            "dir": args.telemetry,
            "recompiles_after_warmup": steady_recompiles,
            "note": "per-phase device syncs active; value above is NOT "
                    "comparable to an uninstrumented run"}

    if not args.no_tiny:
        # lane 2: heavier per-round, fewer timed rounds amortize fine
        try:
            texp = _make_experiment(TINY_CONFIG)
            tiny_spr = measure_ours(texp, args.tiny_rounds)
            out["tiny_lane"] = {
                "metric": "tiny_imagenet_fl_rounds_per_sec",
                "value": round(1.0 / tiny_spr, 4), "unit": "rounds/sec",
                "workload": "synthetic tiny-imagenet (10k imgs, Dirichlet "
                            "a=0.5), 10 clients/round, torchvision-style "
                            "ResNet-18 (200 classes)"}
        except Exception as e:  # noqa: BLE001 — the second lane must not
            out["tiny_lane_error"] = str(e)  # break the headline number

    if args.poison_cost:
        # poison-cost lane: benign denominator = the headline measurement
        # above (identical config apart from the attack keys)
        try:
            pexp = _make_experiment(POISON_COST_CONFIG)
            pspr = measure_ours(pexp, args.poison_rounds)
            out["poison_cost_lane"] = {
                "metric": "cifar10_poison_round_cost",
                "benign_rounds_per_sec": round(rounds_per_sec, 4),
                "poison_rounds_per_sec": round(1.0 / pspr, 4),
                "poison_overhead_pct": round(
                    100.0 * (pspr - ours) / ours, 2),
                "workload": "headline config + 4 scheduled DBA adversaries "
                            "poisoning every timed round; overhead = poison "
                            "injection in-train + the 4-part local eval "
                            "battery vs benign's clean-only battery"}
        except Exception as e:  # noqa: BLE001 — lanes never break
            out["poison_cost_lane_error"] = str(e)  # the headline number

    if args.width:
        # width lane: throughput in clients*rounds/sec vs clients-per-round
        # (C is the vmapped client axis of the fused round program). The
        # C=1000 point is the ROADMAP scale target: the participant pool
        # grows to match, fewer timed rounds amortize the heavier program,
        # and the memory high-water ceiling across the whole sweep is
        # reported alongside the per-point series.
        try:
            pts = []
            for C in (10, 50, 100, 1000):
                wexp = _make_experiment(dict(
                    BENCH_CONFIG, no_models=C,
                    number_of_total_participants=max(
                        int(BENCH_CONFIG["number_of_total_participants"]),
                        C)))
                spr = measure_ours(
                    wexp, args.width_rounds if C <= 100 else
                    max(1, args.width_rounds // 2))
                pts.append({
                    "clients_per_round": C,
                    "rounds_per_sec": round(1.0 / spr, 4),
                    "clients_rounds_per_sec": round(C / spr, 4),
                    "device_peak_bytes": device_peak_bytes(),
                    "host_peak_rss_bytes": host_peak_rss_bytes()})
                del wexp
            out["width_lane"] = {
                "metric": "clients_rounds_per_sec_vs_width",
                "points": pts,
                "memory_ceiling_bytes": {
                    "device": pts[-1]["device_peak_bytes"],
                    "host_rss": pts[-1]["host_peak_rss_bytes"]},
                "note": "device_peak_bytes/host_peak_rss_bytes are process-"
                        "lifetime high-waters (monotone across points; "
                        "device is null on backends without memory_stats) — "
                        "memory_ceiling_bytes is the widest point's "
                        "high-water, the sweep's ceiling"}
        except Exception as e:  # noqa: BLE001
            out["width_lane_error"] = str(e)

    if args.async_lane:
        # async lane: the buffered streaming engine's sustained throughput —
        # merges/sec and client updates absorbed/sec (merges x buffer_k).
        # Fresh experiment + driver; two untimed merges warm the wave-train
        # + merge + eval programs before the clock starts.
        try:
            aexp = _make_async_experiment()
            from dba_mod_tpu.fl.async_rounds import AsyncDriver
            drv = AsyncDriver(aexp)
            drv.run_steps(2)
            t0 = time.time()
            drv.run_steps(args.async_rounds)
            wall = time.time() - t0
            K = drv.K
            # merge-pipelining A/B: same workload, overlap_eval off — the
            # serial dispatch+finalize composition per merge
            aoff = _make_async_experiment(dict(ASYNC_CONFIG,
                                               overlap_eval=False))
            drv_off = AsyncDriver(aoff)
            drv_off.run_steps(2)
            t0 = time.time()
            drv_off.run_steps(args.async_rounds)
            wall_off = time.time() - t0
            del drv_off, aoff
            out["async_lane"] = {
                "metric": "async_buffered_updates_per_sec",
                "merges_per_sec": round(args.async_rounds / wall, 4),
                "updates_per_sec": round(args.async_rounds * K / wall, 4),
                "overlap": {
                    "merges_per_sec_off": round(
                        args.async_rounds / wall_off, 4),
                    "updates_per_sec_off": round(
                        args.async_rounds * K / wall_off, 4),
                    "speedup": round(wall_off / wall, 3),
                    "hidden_finalize_s": drv.stats()["hidden_finalize_s"],
                    "note": "merge S's host finalize (device fetch + row "
                            "recording) pipelined behind step S+1's "
                            "fill/merge compute"},
                "buffer_k": K,
                "cohort_clients": int(aexp.params["no_models"]),
                "staleness_weighting": str(
                    aexp.params["staleness_weighting"]),
                # self-healing observability (driver counters over the
                # timed window + warmup): virtual-time merge latency p95,
                # admission-control high-water, and the starvation/TTL
                # drop counts — all zero with the knobs at defaults
                "health": drv.stats(),
                "workload": "headline config through the buffered-async "
                            "engine: 10-client cohorts, merge every 5 "
                            "arrivals, polynomial staleness, jittered "
                            "arrivals with a straggler tail "
                            "(fl/async_rounds.py)"}
        except Exception as e:  # noqa: BLE001 — lanes never break
            out["async_lane_error"] = str(e)  # the headline number

    if args.forensics_cost:
        # forensics-cost lane: identical workload, forensics on. The writer
        # stays in-memory (save_results=False), so the measured delta is
        # the device-side ForensicStats computation + the bigger fetch +
        # host row assembly — the acceptance gate is <= 5%.
        try:
            fexp = _make_experiment(dict(BENCH_CONFIG, forensics=True))
            fspr = measure_ours(fexp, args.forensics_rounds)
            out["forensics_cost_lane"] = {
                "metric": "cifar10_forensics_overhead",
                "off_rounds_per_sec": round(rounds_per_sec, 4),
                "on_rounds_per_sec": round(1.0 / fspr, 4),
                "overhead_pct": round(100.0 * (fspr - ours) / ours, 2),
                "note": "forensics rows assembled in-memory (bench runs "
                        "with save_results off); file I/O is atomic full "
                        "rewrites on real runs"}
        except Exception as e:  # noqa: BLE001
            out["forensics_cost_lane_error"] = str(e)

    if args.multihost:
        # scale-out lane: spawns its own 2-process world (a process that
        # already initialized jax cannot join one), so it must not touch
        # this process's experiment — and, like the tiny lane, must never
        # break the headline number
        try:
            out["multihost_lane"] = measure_multihost(args.multihost_rounds)
        except Exception as e:  # noqa: BLE001
            out["multihost_lane"] = {"error": str(e)}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
