"""A torch implementation of one reference-style FL round, used ONLY as the
bench baseline denominator.

This is a fresh implementation of the reference's *workload semantics*
(sequential per-client SGD on one shared model + FedAvg + per-client and
global eval — image_train.py:21-271, helper.py:240-257, main.py:198-201), not
a copy of its code. It exists because the reference itself cannot run here
(zero egress: no dataset downloads, no visdom; no GPU), so the recorded
baseline is this loop on the same host's CPU via stock torch — the only
reference-framework measurement available in this environment. BASELINE.md
records that the reference publishes no numbers of its own.
"""
from __future__ import annotations

import time
from typing import List

import numpy as np


def _narrow_resnet18(num_classes: int = 10):
    """torch equivalent of the narrow (32/64/128/256) CIFAR ResNet-18 the
    reference trains (models/resnet_cifar.py:70-116 widths)."""
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    class Block(nn.Module):
        def __init__(self, in_p, p, stride):
            super().__init__()
            self.c1 = nn.Conv2d(in_p, p, 3, stride, 1, bias=False)
            self.b1 = nn.BatchNorm2d(p)
            self.c2 = nn.Conv2d(p, p, 3, 1, 1, bias=False)
            self.b2 = nn.BatchNorm2d(p)
            self.short = None
            if stride != 1 or in_p != p:
                self.short = nn.Sequential(
                    nn.Conv2d(in_p, p, 1, stride, bias=False),
                    nn.BatchNorm2d(p))

        def forward(self, x):
            y = F.relu(self.b1(self.c1(x)))
            y = self.b2(self.c2(y))
            s = x if self.short is None else self.short(x)
            return F.relu(y + s)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            widths = [32, 64, 128, 256]
            self.stem = nn.Sequential(nn.Conv2d(3, 32, 3, 1, 1, bias=False),
                                      nn.BatchNorm2d(32), nn.ReLU())
            layers: List[nn.Module] = []
            in_p = 32
            for stage, p in enumerate(widths):
                for i in range(2):
                    stride = 2 if (stage > 0 and i == 0) else 1
                    layers.append(Block(in_p, p, stride))
                    in_p = p
            self.body = nn.Sequential(*layers)
            self.head = nn.Linear(256, num_classes)

        def forward(self, x):
            x = self.body(self.stem(x))
            x = F.avg_pool2d(x, 4).flatten(1)
            return self.head(x)

    return Net()


def measure_torch_reference_round(num_clients: int = 10,
                                  samples_per_client: int = 500,
                                  batch_size: int = 64,
                                  internal_epochs: int = 2,
                                  test_size: int = 10000,
                                  lr: float = 0.1, eta: float = 0.1,
                                  threads: int | None = None,
                                  sample_clients: int | None = None) -> float:
    """Wall-clock seconds for ONE reference-style clean FL round: sequential
    clients (shared local model re-seeded from the global state_dict each
    time), per-client SGD epochs, per-client full-test-set eval, FedAvg,
    global eval — the same work our round does in one XLA computation.

    `sample_clients`: measure only that many clients and extrapolate linearly
    to `num_clients` (the loop is embarrassingly sequential and per-client
    work is identical, so the extrapolation is exact up to noise) — a full
    CPU round takes >10 minutes on this host."""
    import torch
    import torch.nn.functional as F

    if threads:
        torch.set_num_threads(threads)
    torch.manual_seed(0)
    global_model = _narrow_resnet18()
    local_model = _narrow_resnet18()
    rng = np.random.RandomState(0)
    client_data = [
        (torch.tensor(rng.rand(samples_per_client, 3, 32, 32),
                      dtype=torch.float32),
         torch.tensor(rng.randint(0, 10, samples_per_client)))
        for _ in range(num_clients)]
    test_x = torch.tensor(rng.rand(test_size, 3, 32, 32),
                          dtype=torch.float32)
    test_y = torch.tensor(rng.randint(0, 10, test_size))

    def evaluate(model):
        model.eval()
        correct = 0
        with torch.no_grad():
            for i in range(0, test_size, batch_size):
                out = model(test_x[i:i + batch_size])
                correct += (out.argmax(1) == test_y[i:i + batch_size]).sum()
        model.train()
        return correct

    measured = sample_clients or num_clients
    t0 = time.time()
    accum = {k: torch.zeros_like(v)
             for k, v in global_model.state_dict().items()}
    for (cx, cy) in client_data[:measured]:
        local_model.load_state_dict(global_model.state_dict())
        opt = torch.optim.SGD(local_model.parameters(), lr=lr, momentum=0.9,
                              weight_decay=5e-4)
        local_model.train()
        for _ in range(internal_epochs):
            perm = torch.randperm(len(cx))
            for i in range(0, len(cx), batch_size):
                idx = perm[i:i + batch_size]
                opt.zero_grad()
                loss = F.cross_entropy(local_model(cx[idx]), cy[idx])
                loss.backward()
                opt.step()
        evaluate(local_model)  # per-client local eval (image_train.py:268)
        for k, v in local_model.state_dict().items():
            accum[k] += v - global_model.state_dict()[k]
    per_client = (time.time() - t0) / measured
    t1 = time.time()
    sd = global_model.state_dict()
    for k in sd:
        sd[k] = sd[k] + (eta / num_clients) * accum[k]
    global_model.load_state_dict(sd)
    evaluate(global_model)     # global eval (main.py:198)
    tail = time.time() - t1
    return per_client * num_clients + tail
