"""Controlled A/B of the grouped-layout client path (models/grouped.py)
against the vmapped path on the bench workload — same inputs, same global
state, both engines' train_fn compared for (a) wall-clock train-phase time
and (b) numerical agreement of the round outputs.

Usage: python -m benchmarks.grouped_ab   (runs on the default backend — the
real TPU under axon; CPU works but measures nothing interesting).
Prints one JSON line; evidence recorded in TRAIN_FLOOR.md.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main() -> int:
    from dba_mod_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache("/tmp/jax_cache_dba_bench")
    from bench import BENCH_CONFIG
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment

    base = dict(BENCH_CONFIG, dynamic_steps=False, pipeline_rounds=False)
    exps = {k: Experiment(Params.from_dict(dict(base, grouped_clients=k)),
                          save_results=False)
            for k in (False, True)}
    ev, eg = exps[False], exps[True]
    assert eg.engine.use_grouped and not ev.engine.use_grouped

    # identical inputs for both engines (consume ONE experiment's RNG)
    tasks_seq, idx_seq, mask_seq, ns, lane = ev.build_static_round_inputs(2)
    rng_t = jax.random.key(7)
    gv = ev.global_vars  # same seed → same init as eg's

    def train(eng):
        return eng.engine.train_fn(gv, tasks_seq, idx_seq, mask_seq, lane,
                                   rng_t)

    # numerics: same inputs through both paths
    tv = jax.device_get(train(ev))
    tg = jax.device_get(train(eg))
    d_param = max(float(np.abs(a - b).max()) for a, b in zip(
        jax.tree_util.tree_leaves(tv.deltas.params),
        jax.tree_util.tree_leaves(tg.deltas.params)))
    d_bn = max(float(np.abs(a - b).max()) for a, b in zip(
        jax.tree_util.tree_leaves(tv.deltas.batch_stats),
        jax.tree_util.tree_leaves(tg.deltas.batch_stats)))
    d_scale = max(float(np.abs(a).max()) for a in
                  jax.tree_util.tree_leaves(tv.deltas.params))
    bitwise = d_param == 0.0 and d_bn == 0.0

    # timing: dispatch + scalar sync (bench.py::measure_phases methodology)
    lat = min(timeit(lambda: jax.device_get(jnp.float32(1.0) + 1))
              for _ in range(3))

    def phase_time(eng):
        sync = lambda: jax.device_get(train(eng).delta_norms[0])
        sync()  # warm
        return min(timeit(sync) for _ in range(3)) - lat

    t_v = phase_time(ev)
    t_g = phase_time(eg)
    out = {"metric": "grouped_ab_train_phase_s",
           "vmapped_s": round(t_v, 4), "grouped_s": round(t_g, 4),
           "speedup": round(t_v / t_g, 3) if t_g > 0 else None,
           "max_delta_param_diff": d_param, "max_delta_bn_diff": d_bn,
           "delta_scale": d_scale, "bitwise_identical": bitwise,
           "backend": jax.default_backend()}
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
