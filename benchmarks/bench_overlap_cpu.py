"""BENCH_r06 evidence driver for an accelerator-less container: the
bench.py overlap/async lanes on a reduced workload (same code paths,
smaller shapes) — the full CIFAR BENCH_CONFIG does not complete on one
CPU core (fused-round XLA compile alone exceeds 35 min). BENCH_TYPE
selects the model family (default cifar; BENCH_r06.json used mnist).
On one core the overlapped eval still executes on the only core, so
rounds/sec stays flat by construction — the honest quantities here are
hidden_fraction / hidden_eval_s (how much eval+host time ran behind the
next dispatch) and recompiles_after_warmup."""
import json
import os
import sys
import time

sys.path.insert(0, str(__import__("pathlib").Path(__file__).resolve().parents[1]))
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import bench  # noqa: E402
from bench import _make_experiment, _make_async_experiment, measure_ours  # noqa: E402

RED = dict(bench.BENCH_CONFIG, type=os.environ.get("BENCH_TYPE", "cifar"),
           batch_size=16, no_models=4,
           number_of_total_participants=20, internal_epochs=1,
           eval_batch_size=256, synthetic_train_size=2000,
           synthetic_test_size=512, epochs=40)
ROUNDS = 6
out = {"workload": f"REDUCED {RED['type']} lane on CPU (batch 16, "
                   "4 clients/round, 20 participants, 2000 synthetic "
                   "samples) — same code paths as BENCH_CONFIG, shrunk "
                   "to fit one CPU core"}

t_all = time.time()
exp = _make_experiment(dict(RED, overlap_eval=True))
exp._overlap_rounds = 0
exp._overlap_hidden_s = exp._overlap_wait_s = 0.0
on_spr = measure_ours(exp, ROUNDS)
steady = exp.telemetry.counter("xla/recompiles_after_warmup").value
hidden = float(exp._overlap_hidden_s)
wait = float(exp._overlap_wait_s)
n_overlapped = int(exp._overlap_rounds)
del exp

off = _make_experiment(dict(RED, overlap_eval=False))
off_spr = measure_ours(off, ROUNDS)
del off

out["overlap"] = {
    "rounds_per_sec_off": round(1.0 / off_spr, 4),
    "rounds_per_sec_on": round(1.0 / on_spr, 4),
    "speedup": round(off_spr / on_spr, 3),
    "overlapped_rounds": n_overlapped,
    "hidden_eval_s": round(hidden, 4),
    "eval_wait_s": round(wait, 4),
    "hidden_fraction": (round(hidden / (hidden + wait), 4)
                        if hidden + wait > 0 else None),
    "dispatch_ahead_depth": 1,
    "recompiles_after_warmup": steady,
}

ARED = dict(RED, mode="async", buffer_k=5,
            staleness_weighting="polynomial", staleness_alpha=0.5,
            arrival_rate=2.0, arrival_jitter=0.5, straggler_tail=0.1,
            straggler_factor=5.0)
ASTEPS = 6
from dba_mod_tpu.fl.async_rounds import AsyncDriver  # noqa: E402

aexp = _make_async_experiment(dict(ARED, overlap_eval=True))
drv = AsyncDriver(aexp)
drv.run_steps(2)
t0 = time.time()
drv.run_steps(ASTEPS)
wall = time.time() - t0
K = drv.K
stats_on = drv.stats()
del drv, aexp

aoff = _make_async_experiment(dict(ARED, overlap_eval=False))
drv_off = AsyncDriver(aoff)
drv_off.run_steps(2)
t0 = time.time()
drv_off.run_steps(ASTEPS)
wall_off = time.time() - t0
del drv_off, aoff

out["async_lane"] = {
    "merges_per_sec_off": round(ASTEPS / wall_off, 4),
    "merges_per_sec_on": round(ASTEPS / wall, 4),
    "updates_per_sec_off": round(ASTEPS * K / wall_off, 4),
    "updates_per_sec_on": round(ASTEPS * K / wall, 4),
    "speedup": round(wall_off / wall, 3),
    "hidden_finalize_s": stats_on["hidden_finalize_s"],
    "pipelined_merges": stats_on["pipelined_merges"],
    "buffer_k": K,
}
out["wall_s_total"] = round(time.time() - t_all, 1)
print(json.dumps(out, indent=1))
