"""Converged-regime attack-trajectory A/B (VERDICT r4 ask #1).

The reference's de-facto validation is its paper curves: resume a PRETRAINED
model and watch backdoor injection + persistence/decay over tens of rounds
(/root/reference/main.py:135-231; single-shot schedule
utils/cifar_params.yaml:48-52 resumes epoch 200 and poisons at rounds
203/205/207/209; multi-shot utils/mnist_params.yaml:48-60 poisons every
round with baseline=true, eta=1). The r4 parity matrix proved semantic
agreement 1-4 rounds from near-init — chance-level models. This harness
exercises the ±1% north star where it is hard: a CONVERGED model, the
reference's own attack schedules, and ≥30 subsequent clean rounds of
backdoor decay under each defense.

Method: pretrain the flax engine to stable accuracy on the fabricated
(learnable) dataset; seed BOTH frameworks with the identical converged state
via the exact state converters; drive both with shared batch plans
(benchmarks/parity_ab.py machinery) through the attack schedule; record
per-round clean/backdoor accuracy curves and their gaps. Default platforms:
flax side on the REAL TPU at jax_default_matmul_precision=highest
(f32-accurate convs — the production engine under test), torch twin on CPU
f32; `--platform cpu` forces the all-CPU form that isolates semantics from
backend precision entirely (the identical-state PARITY_AB.md sections
already pin that on CPU; it costs ~3-4× more wall-clock on this box).

Scaled-down analog of the reference configs (same hyper-parameters, smaller
population): 30 participants over 4,000 fabricated CIFAR images (Dirichlet
α=0.5), 10/round, eta=0.1, scale_weights_poison=100 — the same full
model-replacement strength as the reference (eta·scale/no_models = 1) —
with adversaries on nearest-mean shards (pick_adversaries).

Usage: python -m benchmarks.trajectory_ab   (~1.5 h: torch-twin CPU rounds
dominate; writes the `## Trajectory` section of PARITY_AB.md between
markers, incrementally per lane, plus TRAJECTORY_AB.json).
tests/test_trajectory_ab.py runs compressed MNIST lanes.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from benchmarks.parity_ab import (CONVERTERS, TorchFL, build_round_plans,
                                  _fedavg_apply)  # noqa: F401 (re-export)

BEGIN_MARK = "<!-- TRAJECTORY:BEGIN -->"
END_MARK = "<!-- TRAJECTORY:END -->"

# Reference cifar_params.yaml hyper block, population scaled 100→30 and
# batch 64→32 / 50k→4k images (the torch twin runs f32 on this box's ~1
# CPU core — the full-size analog costs many hours; the scaled one
# preserves the schedule structure, the Dirichlet non-IID partition, and
# the exact model-replacement strength eta·scale/no_models = 1).
# Adversaries are chosen as the 4 nearest-mean shards (pick_adversaries)
# — the reference's own adversaries hold near-mean shards too
# (cifar_params.yaml:33 notes "training img num : 526 - 527 - 496 - 546");
# a tail-of-the-Dirichlet adversary with a handful of samples makes the
# poison client's 6-epoch local training degenerate (measured: a
# 14-sample adversary collapses to a constant predictor on both
# frameworks, in different basins — no science to compare).
# Single-shot schedule offsets from the resume epoch: +3/+5/+7/+9
# (cifar_params.yaml:48-52 with resume at 200).
CIFAR_TRAJ = dict(
    type="cifar", test_batch_size=64, lr=0.1, poison_lr=0.05, momentum=0.9,
    decay=0.0005, batch_size=32, internal_epochs=2, internal_poison_epochs=6,
    poisoning_per_batch=5, aggr_epoch_interval=1,
    aggregation_methods="mean", geom_median_maxiter=10, fg_use_memory=True,
    no_models=10, number_of_total_participants=30, is_random_namelist=True,
    is_random_adversary=False, is_poison=True, baseline=False,
    scale_weights_poison=100, eta=0.1, sampling_dirichlet=True,
    dirichlet_alpha=0.5, poison_label_swap=2,
    adversary_list=[17, 3, 7, 11],  # replaced by pick_adversaries in main
    centralized_test_trigger=True,
    trigger_num=4, alpha_loss=1.0, epochs=300,
    synthetic_data=True, synthetic_train_size=4000, synthetic_test_size=800,
    synthetic_noise_std=90.0,  # plateau below saturation (real-data regime)
    random_seed=11, local_eval=False,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3], [0, 4], [0, 5]],
       "1_poison_pattern": [[0, 9], [0, 10], [0, 11], [0, 12], [0, 13],
                            [0, 14]],
       "2_poison_pattern": [[4, 0], [4, 1], [4, 2], [4, 3], [4, 4], [4, 5]],
       "3_poison_pattern": [[4, 9], [4, 10], [4, 11], [4, 12], [4, 13],
                            [4, 14]]})

# Reference mnist_params.yaml multi-shot block: baseline=true, eta=1,
# every adversary poisons every round of the ramp (mnist_params.yaml:30-31
# comments pin exactly this switch)
MNIST_TRAJ = dict(
    type="mnist", test_batch_size=64, lr=0.1, poison_lr=0.05,
    poison_step_lr=True, momentum=0.9, decay=0.0005, batch_size=64,
    internal_epochs=1, internal_poison_epochs=10, poisoning_per_batch=20,
    aggr_epoch_interval=1, aggregation_methods="mean",
    geom_median_maxiter=10, fg_use_memory=True, no_models=10,
    number_of_total_participants=30, is_random_namelist=True,
    is_random_adversary=False, is_poison=True, baseline=True,
    scale_weights_poison=100, eta=1.0, sampling_dirichlet=True,
    dirichlet_alpha=0.5, poison_label_swap=2,
    adversary_list=[7, 3, 1, 4], centralized_test_trigger=True,
    trigger_num=4, alpha_loss=1.0, epochs=300,
    synthetic_data=True, synthetic_train_size=1500, synthetic_test_size=600,
    synthetic_noise_std=80.0,  # plateau below saturation (real-data regime)
    random_seed=13, local_eval=False,
    **{"0_poison_pattern": [[0, 0], [0, 1], [0, 2], [0, 3]],
       "1_poison_pattern": [[0, 6], [0, 7], [0, 8], [0, 9]],
       "2_poison_pattern": [[3, 0], [3, 1], [3, 2], [3, 3]],
       "3_poison_pattern": [[3, 6], [3, 7], [3, 8], [3, 9]]})


def pick_adversaries(overrides: dict, k: int = 4) -> List[int]:
    """The k clients whose Dirichlet shard sizes are nearest the mean —
    the reference's own adversary regime (its cifar adversaries hold
    526/527/496/546 of a 500-sample mean, cifar_params.yaml:33). Uses the
    exact partition the experiment will build (same seed/RNG recipe)."""
    import random as pyrandom

    from dba_mod_tpu.config import Params
    from dba_mod_tpu.data.partition import sample_dirichlet_indices
    from dba_mod_tpu.data.datasets import synthetic_image_dataset

    p = Params.from_dict(overrides)
    seed = int(p.get("random_seed", 1))
    data = synthetic_image_dataset(
        p.type, int(p.get("synthetic_train_size", 0)),
        int(p.get("synthetic_test_size", 0)), seed=seed,
        noise_std=float(p.get("synthetic_noise_std", 25.0)))
    idx = sample_dirichlet_indices(
        data.train_labels, int(p["number_of_total_participants"]),
        float(p["dirichlet_alpha"]), py_rng=pyrandom.Random(seed),
        np_rng=np.random.RandomState(seed))
    mean = np.mean([len(v) for v in idx.values()])
    return sorted(sorted(idx, key=lambda n: abs(len(idx[n]) - mean))[:k])


def single_shot_epochs(resume_epoch: int) -> Dict[str, List[int]]:
    """The cifar_params.yaml:48-52 schedule relative to the resume epoch."""
    return {f"{i}_poison_epochs": [resume_epoch + o]
            for i, o in enumerate((3, 5, 7, 9))}


def multi_shot_epochs(start: int, end: int) -> Dict[str, List[int]]:
    """The mnist_params.yaml:53-60 ramp: every adversary, every round."""
    return {f"{i}_poison_epochs": list(range(start, end + 1))
            for i in range(4)}


def pretrain(overrides: dict, rounds: int, **pretrain_overrides):
    """Clean FedAvg pretraining on the flax engine — the `pretrain`
    subcommand's analog (replaces the reference's Google-Drive artifacts).
    Returns (converged ModelVars, per-round clean accuracy).
    `pretrain_overrides` tune the clean phase only (e.g. the BN-free
    MnistNet needs more local work per round: internal_epochs=4, eta=1)."""
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment

    cfg = dict(overrides, is_poison=False, aggregation_methods="mean",
               adversary_list=[])
    cfg.update(dict(eta=0.8), **pretrain_overrides)
    exp = Experiment(Params.from_dict(cfg), save_results=False)
    accs = []
    for ep in range(1, rounds + 1):
        accs.append(exp.run_round(ep)["global_acc"])
    return exp.global_vars, accs


def run_trajectory(overrides: dict, init_vars, start_epoch: int,
                   end_epoch: int, label: str = "") -> dict:
    """Drive both frameworks from the shared `init_vars` state through
    epochs [start_epoch, end_epoch]; returns per-round curves + gaps."""
    import jax
    import jax.numpy as jnp

    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.experiment import Experiment
    from dba_mod_tpu.fl.rounds import nbt_client_deltas
    from dba_mod_tpu.fl.selection import select_agents
    from dba_mod_tpu.models import ModelVars
    from dba_mod_tpu.ops.triggers import build_pixel_pattern_bank

    params = Params.from_dict(overrides)
    exp = Experiment(params, save_results=False)
    exp.global_vars = ModelVars(
        params=jax.tree_util.tree_map(jnp.asarray, init_vars.params),
        batch_stats=jax.tree_util.tree_map(jnp.asarray,
                                           init_vars.batch_stats))
    ctor, to_torch = CONVERTERS[params.type]
    data = exp.image_data
    h, w = data.train_images.shape[1:3]
    bank = build_pixel_pattern_bank(params, h, w)
    tfl = TorchFL(params.raw, ctor, to_torch(exp.global_vars),
                  data.train_images, data.train_labels, data.test_images,
                  data.test_labels, bank)

    rounds = []
    for epoch in range(start_epoch, end_epoch + 1):
        agent_names, adv_names = select_agents(
            params, epoch, exp.participants, exp.benign_names,
            exp.select_rng)
        tasks_list, idx_np, mask_np, num_samples = build_round_plans(
            exp, params, agent_names, [epoch])
        C = len(agent_names)
        tasks_seq = jax.tree_util.tree_map(
            lambda *ls: jnp.asarray(np.stack(ls)), *tasks_list)
        lane = jnp.arange(C, dtype=jnp.int32)
        exp.rng_key, round_key = jax.random.split(exp.rng_key)
        rng_t, rng_a = jax.random.split(round_key)
        train = exp.engine.train_fn(exp.global_vars, tasks_seq,
                                    jnp.asarray(idx_np),
                                    jnp.asarray(mask_np), lane, rng_t)
        agg = exp.engine.aggregate_fn(
            exp.global_vars, exp.fg_state, train.deltas, train.fg_grads,
            train.fg_feature, jnp.asarray(tasks_list[0].participant_id),
            jnp.asarray(num_samples), rng_a,
            nbt_client_deltas(jnp.asarray(mask_np),
                              jnp.asarray(np.stack(
                                  [t.scale for t in tasks_list]))))
        exp.global_vars = agg.new_vars
        exp.fg_state = agg.new_fg_state
        g = jax.device_get(exp.engine.global_evals_fn(agg.new_vars))

        tfl.run_round([epoch], agent_names, idx_np, mask_np,
                      num_samples=[int(n) for n in num_samples])
        t_clean, t_bd = tfl.clean_acc(), tfl.backdoor_acc()
        row = {"epoch": epoch,
               "poisoning": [str(a) for a in adv_names],
               "jax_clean": float(g.clean.acc), "torch_clean": t_clean,
               "jax_backdoor": float(g.poison.acc), "torch_backdoor": t_bd,
               "clean_gap": abs(float(g.clean.acc) - t_clean),
               "backdoor_gap": abs(float(g.poison.acc) - t_bd)}
        rounds.append(row)
        print(f"[{label}] epoch {epoch}: clean {row['jax_clean']:.2f}/"
              f"{row['torch_clean']:.2f} backdoor {row['jax_backdoor']:.2f}/"
              f"{row['torch_backdoor']:.2f}"
              + (f" POISON {row['poisoning']}" if adv_names else ""),
              flush=True)
    return {"label": label, "rounds": rounds}


def summarize(traj: dict) -> dict:
    """Whole-run + phase-wise gap statistics. Phases: `pre` = rounds before
    the first poisoning round (the converged steady state), `tail` = the
    last 10 rounds (post-decay steady state). The transient between them —
    scale-100 model replacement and the recovery from it — is a knife-edge
    regime where ANY two runs separate chaotically (the reference's own
    poison LR schedule is flat there: its float milestones 0.2·6/0.8·6
    never fire, ops/sgd.py::_milestone_hits), so per-round gaps inside the
    transient measure the attack's violence, not framework disagreement."""
    rs = traj["rounds"]
    poison_rounds = [i for i, r in enumerate(rs) if r["poisoning"]]
    pre = rs[:poison_rounds[0]] if poison_rounds else rs
    # tail = post-attack rounds only (up to the last 10 AFTER the final
    # poison round) — never mid-attack rounds mislabeled as steady state
    after = rs[poison_rounds[-1] + 1:] if poison_rounds else rs
    tail = after[-10:]

    def gaps(sub, key):
        vals = [r[key] for r in sub]
        if not vals:
            return (float("nan"), float("nan"))  # no such phase in this run
        return float(np.mean(vals)), float(np.max(vals))
    pre_c = gaps(pre, "clean_gap")
    pre_b = gaps(pre, "backdoor_gap")
    tail_c = gaps(tail, "clean_gap")
    tail_b = gaps(tail, "backdoor_gap")
    return {
        "label": traj["label"],
        "n_rounds": len(rs),
        "mean_clean_gap": float(np.mean([r["clean_gap"] for r in rs])),
        "max_clean_gap": float(np.max([r["clean_gap"] for r in rs])),
        "mean_backdoor_gap": float(np.mean([r["backdoor_gap"] for r in rs])),
        "max_backdoor_gap": float(np.max([r["backdoor_gap"] for r in rs])),
        "pre_rounds": len(pre), "tail_rounds": len(tail),
        "pre_mean_clean_gap": pre_c[0], "pre_max_clean_gap": pre_c[1],
        "pre_mean_backdoor_gap": pre_b[0], "pre_max_backdoor_gap": pre_b[1],
        "tail_mean_clean_gap": tail_c[0], "tail_max_clean_gap": tail_c[1],
        "tail_mean_backdoor_gap": tail_b[0],
        "tail_max_backdoor_gap": tail_b[1],
        "final_clean_gap": rs[-1]["clean_gap"],
        "final_backdoor_gap": rs[-1]["backdoor_gap"],
        "jax_peak_backdoor": float(np.max([r["jax_backdoor"] for r in rs])),
        "torch_peak_backdoor": float(
            np.max([r["torch_backdoor"] for r in rs])),
        "jax_final_backdoor": rs[-1]["jax_backdoor"],
        "torch_final_backdoor": rs[-1]["torch_backdoor"],
        "jax_final_clean": rs[-1]["jax_clean"],
        "torch_final_clean": rs[-1]["torch_clean"],
    }


def _fmt_traj(traj: dict, summary: dict) -> str:
    lines = [f"### {traj['label']}", "",
             "| epoch | poisoning | clean acc (jax / torch) | gap | "
             "backdoor acc (jax / torch) | gap |", "|---|---|---|---|---|---|"]
    for r in traj["rounds"]:
        lines.append(
            f"| {r['epoch']} | {','.join(r['poisoning']) or '—'} | "
            f"{r['jax_clean']:.2f} / {r['torch_clean']:.2f} | "
            f"{r['clean_gap']:.2f} | "
            f"{r['jax_backdoor']:.2f} / {r['torch_backdoor']:.2f} | "
            f"{r['backdoor_gap']:.2f} |")
    pre_txt = ("no pre-attack rounds in this run"
               if summary["pre_rounds"] == 0 else
               f"pre-attack ({summary['pre_rounds']} rounds) mean/max clean "
               f"{summary['pre_mean_clean_gap']:.3f}/"
               f"{summary['pre_max_clean_gap']:.3f}")
    tail_txt = ("no post-attack rounds in this run"
                if summary["tail_rounds"] == 0 else
                f"post-attack tail ({summary['tail_rounds']} rounds) "
                f"mean/max clean {summary['tail_mean_clean_gap']:.3f}/"
                f"{summary['tail_max_clean_gap']:.3f}, backdoor "
                f"{summary['tail_mean_backdoor_gap']:.3f}/"
                f"{summary['tail_max_backdoor_gap']:.3f}")
    lines += ["",
              f"Gaps (pct-points): {pre_txt}; {tail_txt}; whole-run mean "
              f"clean {summary['mean_clean_gap']:.3f} / backdoor "
              f"{summary['mean_backdoor_gap']:.3f} (max "
              f"{summary['max_clean_gap']:.3f}/"
              f"{summary['max_backdoor_gap']:.3f}). Peak backdoor "
              f"{summary['jax_peak_backdoor']:.2f} (jax) / "
              f"{summary['torch_peak_backdoor']:.2f} (torch); final "
              f"{summary['jax_final_backdoor']:.2f} / "
              f"{summary['torch_final_backdoor']:.2f}; final clean "
              f"{summary['jax_final_clean']:.2f} / "
              f"{summary['torch_final_clean']:.2f}.", ""]
    return "\n".join(lines)


def extract_trajectory_section(text: str) -> Optional[str]:
    """The marker-delimited section body, or None when absent/malformed.
    Single owner of the marker format — parity_ab.main() uses this too."""
    if BEGIN_MARK in text and END_MARK in text.split(BEGIN_MARK, 1)[1]:
        return text.split(BEGIN_MARK, 1)[1].split(END_MARK, 1)[0]
    return None


def splice_trajectory_section(md_path: str, section_body: str) -> None:
    """Insert/replace the marker-delimited trajectory section of
    PARITY_AB.md (parity_ab.main preserves it when regenerating)."""
    try:
        text = open(md_path).read()
    except FileNotFoundError:
        text = ""
    if extract_trajectory_section(text) is not None:
        head = text.split(BEGIN_MARK, 1)[0]
        tail = text.split(END_MARK, 1)[1]
    else:
        head, tail = (text if text.endswith("\n") or not text
                      else text + "\n"), ""
    with open(md_path, "w") as f:
        f.write(head + BEGIN_MARK + "\n" + section_body + END_MARK + tail)


def main(argv=None) -> int:
    import argparse
    import os
    ap = argparse.ArgumentParser()
    # The flax side runs on the real TPU by default — the production
    # engine, at jax_default_matmul_precision=highest so its f32 convs
    # match CPU-f32 accuracy (the torch twin is CPU f32 either way; the
    # identical-state sections above already isolate pure semantics on
    # CPU-vs-CPU). --platform cpu forces the all-CPU form: ~3-4× more
    # wall-clock per section on this box's ~1-core quota.
    ap.add_argument("--platform", choices=["tpu", "cpu"], default="tpu")
    args = ap.parse_args(argv)
    import jax
    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        from dba_mod_tpu.utils.compile_cache import enable_compile_cache
        enable_compile_cache()
    else:
        jax.config.update("jax_default_matmul_precision", "highest")
        from dba_mod_tpu.utils.compile_cache import enable_compile_cache
        enable_compile_cache("/tmp/jax_cache_dba_bench")

    sections, summaries = [], []
    pre_note = {}

    def flush_artifacts():
        """Incremental splice — a killed run still leaves every completed
        lane in the artifact."""
        body = (
            "\n## Trajectory (converged-regime attack efficacy)\n\n"
            "Generated by `python -m benchmarks.trajectory_ab` (flax side "
            f"on backend `{jax.default_backend()}`, matmul precision "
            "HIGHEST — f32-accurate convs; torch twin on CPU f32). Both "
            "frameworks resume from the SAME converged pretrained state "
            "(flax engine pretrain on the fabricated dataset at "
            "synthetic_noise_std=90/80; measured pretrain clean acc "
            f"{pre_note.get('cifar', float('nan')):.1f}% CIFAR / "
            f"{pre_note.get('mnist', float('nan')):.1f}% MNIST) and "
            "replay the reference's own attack schedules with shared "
            "batch plans: the cifar_params.yaml:48-52 single-shot DBA "
            "under all three defenses, and the mnist_params.yaml "
            "multi-shot ramp. Gaps are |jax − torch| in accuracy "
            "percentage points — read each lane's own phase line; no "
            "blanket claim is made here. Interpretation key: each "
            "framework integrates its own f32 rounding, so agreement is "
            "expected (and measured) in steady regimes, while the "
            "scale-100 replacement transient — 6 FLAT-LR poison epochs "
            "on a converged model (the reference's own float-milestone "
            "quirk: MultiStepLR milestones 0.2·6/0.8·6 never fire, "
            "ops/sgd.py::_milestone_hits) followed by ×100 amplification "
            "— is a measured knife-edge: single-bit differences flip "
            "which basin the poison client lands in, so backdoor "
            "persistence TIMING can diverge qualitatively there, exactly "
            "as two runs of the reference itself would. The "
            "identical-state sections above pin the per-round semantics "
            "tightly; these curves pin the phenomena (attack lands / "
            "decays / is blocked) and the steady-phase gaps.\n\n"
            + "\n".join(sections))
        splice_trajectory_section("PARITY_AB.md", body)
        with open("TRAJECTORY_AB.json", "w") as f:
            json.dump({"summaries": summaries}, f, indent=1)

    # --- CIFAR single-shot, all three defenses from one pretrain ---
    E0 = 25
    advs = pick_adversaries(CIFAR_TRAJ)
    base_cfg = dict(CIFAR_TRAJ, adversary_list=advs)
    print(f"adversaries (nearest-mean shards): {advs}", flush=True)
    init_vars, pre_accs = pretrain(base_cfg, E0)
    pre_note["cifar"] = pre_accs[-1]
    print(f"pretrain: {E0} rounds, clean acc {pre_accs[-1]:.2f} "
          f"(trajectory: {[round(a, 1) for a in pre_accs[::5]]})", flush=True)
    for defense in ("mean", "geom_median", "foolsgold"):
        cfg = dict(base_cfg, aggregation_methods=defense,
                   **single_shot_epochs(E0))
        traj = run_trajectory(
            cfg, init_vars, E0 + 1, E0 + 40,
            label=f"cifar single-shot DBA + {defense} (resume@{E0}, poison "
                  f"@{E0+3}/{E0+5}/{E0+7}/{E0+9}, 31 clean rounds after)")
        s = summarize(traj)
        summaries.append(s)
        sections.append(_fmt_traj(traj, s))
        flush_artifacts()

    # --- MNIST multi-shot ramp (baseline=true, eta=1) ---
    M0 = 10
    madvs = pick_adversaries(MNIST_TRAJ)
    mnist_cfg = dict(MNIST_TRAJ, adversary_list=madvs)
    init_m, pre_m = pretrain(mnist_cfg, M0)
    pre_note["mnist"] = pre_m[-1]
    print(f"mnist pretrain: {M0} rounds, clean acc {pre_m[-1]:.2f} "
          f"advs {madvs}", flush=True)
    cfg = dict(mnist_cfg, **multi_shot_epochs(M0 + 1, M0 + 15))
    traj = run_trajectory(
        cfg, init_m, M0 + 1, M0 + 20,
        label=f"mnist multi-shot ramp (baseline, eta=1; poison rounds "
              f"{M0+1}-{M0+15}, then 5 clean)")
    s = summarize(traj)
    summaries.append(s)
    sections.append(_fmt_traj(traj, s))
    flush_artifacts()
    print(json.dumps({"summaries": summaries}, indent=1))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
