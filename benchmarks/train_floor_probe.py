"""TPU train-phase floor probe (VERDICT r3 ask 2).

Measures, on the real bench workload (CIFAR narrow ResNet-18, 10 clients,
bf16):
1. controlled A/B of the local-eval battery: per-client-vmapped fetch+stamp
   (the r3 formulation) vs the shared-fetch stacked battery (fl/evaluation.py
   ::make_stacked_eval_fn);
2. a kernel-level trace of one train_fn execution (jax.profiler) — kernel
   count, total device time, duration histogram — quantifying how much of
   the train phase is per-kernel launch floor vs compute;
3. the per-kernel dispatch floor of this stack, measured directly with a
   chain of dependent tiny kernels.

Writes JSON to stdout; TRAIN_FLOOR.md summarizes the findings and projects
real-TPU MFU.  Timing rule for this image (see tests/axon notes): the only
honest sync is jax.device_get of a scalar — block_until_ready does not block
through the axon tunnel.
"""
from __future__ import annotations

import glob
import gzip
import json
import time


def timeit(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dba_mod_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache("/tmp/jax_cache_dba_bench")

    from bench import BENCH_CONFIG
    from dba_mod_tpu.config import Params
    from dba_mod_tpu.fl.evaluation import make_eval_fn
    from dba_mod_tpu.fl.experiment import Experiment

    out = {}
    exp = Experiment(Params.from_dict(BENCH_CONFIG), save_results=False)
    engine = exp.engine
    plans = exp.eval_plans
    tasks_seq, idx_seq, mask_seq, ns, lane = exp.build_static_round_inputs(1)
    rng_t, rng_a = jax.random.split(jax.random.key(0))
    tasks_last = jax.tree_util.tree_map(lambda l: l[-1], tasks_seq)

    train = engine.train_fn(exp.global_vars, tasks_seq, idx_seq, mask_seq,
                            lane, rng_t)
    prev = jax.tree_util.tree_map(jnp.zeros_like, train.deltas)
    lat = min(timeit(lambda: jax.device_get(jnp.float32(1.0) + 1))
              for _ in range(3))
    out["sync_latency_s"] = lat

    # --- 1. eval battery A/B: r3 per-client formulation vs stacked ---
    eval_clean = make_eval_fn(engine.model_def, engine.data, poison=False)

    def old_local_clean(global_vars, deltas, tasks):
        def per_client(delta, scale):
            unscaled = jax.tree_util.tree_map(
                lambda g, d: g + d / scale, global_vars, delta)
            return eval_clean(unscaled, plans.clean_idx, plans.clean_slots,
                              plans.clean_mask, jnp.int32(-1))
        return jax.vmap(per_client)(deltas, tasks.scale)

    old_fn = jax.jit(old_local_clean)
    jax.device_get(old_fn(exp.global_vars, train.deltas,
                          tasks_last).acc[0])  # compile+warm

    def run_old():
        jax.device_get(old_fn(exp.global_vars, train.deltas,
                              tasks_last).acc[0])

    def run_new():
        jax.device_get(engine.local_evals_fn(
            exp.global_vars, train.deltas, tasks_last, prev).clean.acc[0])

    run_new()
    out["local_eval_old_clean_only_s"] = round(
        min(timeit(run_old) for _ in range(3)) - lat, 4)
    out["local_eval_new_full_battery_s"] = round(
        min(timeit(run_new) for _ in range(3)) - lat, 4)
    # clean-only via the stacked kernel, for apples-to-apples
    from dba_mod_tpu.fl.evaluation import make_stacked_eval_fn
    stacked_clean = make_stacked_eval_fn(engine.model_def, engine.data,
                                         poison=False)

    def new_clean_only(global_vars, deltas, tasks):
        unscaled = jax.tree_util.tree_map(
            lambda g, d: g + d / tasks.scale.reshape(
                (-1,) + (1,) * (d.ndim - 1)), global_vars, deltas)
        return stacked_clean(unscaled, plans.clean_idx, plans.clean_slots,
                             plans.clean_mask, jnp.int32(-1))

    new_clean_fn = jax.jit(new_clean_only)
    jax.device_get(new_clean_fn(exp.global_vars, train.deltas,
                                tasks_last).acc[0])

    def run_new_clean():
        jax.device_get(new_clean_fn(exp.global_vars, train.deltas,
                                    tasks_last).acc[0])

    out["local_eval_new_clean_only_s"] = round(
        min(timeit(run_new_clean) for _ in range(3)) - lat, 4)

    # --- 2. train phase: timing + kernel trace ---
    def run_train():
        jax.device_get(engine.train_fn(exp.global_vars, tasks_seq, idx_seq,
                                       mask_seq, lane,
                                       rng_t).delta_norms[0])

    run_train()
    out["train_s"] = round(min(timeit(run_train) for _ in range(3)) - lat, 4)

    trace_dir = "/tmp/train_trace"
    with jax.profiler.trace(trace_dir):
        run_train()
    files = sorted(glob.glob(trace_dir + "/**/*.trace.json.gz",
                             recursive=True))
    out["trace_file"] = files[-1] if files else None
    if files:
        with gzip.open(files[-1], "rt") as f:
            trace = json.load(f)
        # device pid: the TPU device track
        pids = {p["pid"]: p.get("args", {}).get("name", "")
                for p in trace["traceEvents"] if p.get("ph") == "M"
                and p.get("name") == "process_name"}
        dev_pids = [pid for pid, name in pids.items() if "TPU" in name]
        evs = [e for e in trace["traceEvents"]
               if e.get("ph") == "X" and e.get("pid") in dev_pids
               and not e.get("name", "").startswith(("jit_", "while"))]
        durs = np.array([e["dur"] for e in evs], np.float64)  # microseconds
        if len(durs):
            out["trace_kernels"] = int(len(durs))
            out["trace_device_total_s"] = round(float(durs.sum()) / 1e6, 4)
            out["trace_dur_us_percentiles"] = {
                str(p): round(float(np.percentile(durs, p)), 1)
                for p in (10, 50, 90, 99)}
            out["trace_kernels_under_100us"] = int((durs < 100).sum())
            out["trace_time_in_under_100us_s"] = round(
                float(durs[durs < 100].sum()) / 1e6, 4)
            names = {}
            for e in evs:
                n = e.get("name", "?")[:40]
                names[n] = names.get(n, [0, 0.0])
                names[n][0] += 1
                names[n][1] += e["dur"] / 1e6
            top = sorted(names.items(), key=lambda kv: -kv[1][1])[:12]
            out["trace_top_ops"] = [
                {"name": n, "count": c, "total_s": round(s, 4)}
                for n, (c, s) in top]

    # --- 3. per-kernel dispatch floor: dependent chain of tiny kernels ---
    def chain(x, n):
        for i in range(n):
            x = x * 1.000001 + jnp.float32(i)  # dependent, unfusable-ish
            x = jnp.sin(x)
        return x

    for n in (64, 512):
        f = jax.jit(lambda x, n=n: chain(x, n))
        jax.device_get(f(jnp.float32(1.0)))
        t = min(timeit(lambda: jax.device_get(f(jnp.float32(1.0))))
                for _ in range(3)) - lat
        out[f"chain_{n}_s"] = round(t, 4)
    # floor = marginal cost per fused pair of tiny ops
    out["per_kernel_floor_us"] = round(
        (out["chain_512_s"] - out["chain_64_s"]) / (512 - 64) / 2 * 1e6, 2)

    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
